"""Multi-tenant serving + AOT program bundles (ISSUE 13).

The load-bearing assertions:

  * shape-generic scorer programs: N same-shape tenants share ONE
    compiled ladder — warming 8 tenants costs <= 1.1x the program
    builds of warming 1 (here: exactly 1x);
  * isolation: a tenant's scores are BITWISE equal to a dedicated
    single-tenant engine's, across full / SLO-shed fixed-only / int8 /
    two-tier cold-miss paths, and a neighbor's breaker trip, budget
    flood, or SLO shed never perturbs them;
  * canary/A-B: the traffic split is deterministic per (tenant, uid),
    sums to 100%, and responses carry typed (tenant, arm) attribution;
  * AOT program bundles: export -> clear -> load -> warmup performs
    zero traces and zero compiles, scores bitwise-equal; a corrupted
    bundle is refused typed (crc gate) and falls back to tracing —
    a re-trace, never a wrong score.
"""

from __future__ import annotations

import math
import os

import numpy as np
import pytest

from photon_tpu.game.dataset import EntityVocabulary
from photon_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    GeneralizedLinearModel,
    RandomEffectModel,
)
from photon_tpu.io.index_map import IndexMap, feature_key
from photon_tpu.io.model_io import (
    ServingFixedEffect,
    ServingGameModel,
    ServingRandomEffect,
    save_game_model,
)
from photon_tpu.obs.metrics import registry as metrics_registry
from photon_tpu.resilience import chaos
from photon_tpu.serving import (
    BreakerConfig,
    CoeffStoreConfig,
    DeviceResidentModel,
    FallbackReason,
    MultiTenantEngine,
    ScoreRequest,
    ServingConfig,
    ServingEngine,
    SLOConfig,
    SwapConfig,
    export_program_bundle,
    load_program_bundle,
)
from photon_tpu.serving.programs import bundle_dir_for
from photon_tpu.serving.tenants import _ladder_buckets
from photon_tpu.types import TaskType
from photon_tpu.utils import compile_cache, jitcache

D, E, K = 5, 3, 2


def _reasons(resp):
    return {f.reason for f in resp.fallbacks}


def _synth_model(seed=7, nan_fixed=False):
    """One-shard, one-RE ServingGameModel. Every seed produces the SAME
    shapes (the multi-tenant premise) with different values."""
    rng = np.random.default_rng(seed)
    imap = IndexMap.from_keys([feature_key(f"f{j}", "") for j in range(D)])
    theta = rng.normal(size=D).astype(np.float32)
    if nan_fixed:
        theta[0] = np.nan
    proj = np.stack([np.sort(rng.choice(D, size=K, replace=False))
                     for _ in range(E)]).astype(np.int32)
    coef = rng.normal(size=(E, K)).astype(np.float32)
    return ServingGameModel(
        TaskType.LOGISTIC_REGRESSION,
        [ServingFixedEffect("global", "s", theta)],
        [ServingRandomEffect("per-u", "uid", "s", coef, proj,
                             {f"u{e}": e for e in range(E)})],
        {"s": imap}, {})


def _req(uid, user="u0", tenant=None, seed=None):
    if seed is None:
        vals = [1.0] * D
    else:
        vals = np.random.default_rng(seed).normal(size=D).round(3).tolist()
    return ScoreRequest(uid, {"s": [(f"f{j}", "", float(v))
                                    for j, v in enumerate(vals)]},
                        {"uid": user}, tenant=tenant)


def _traffic(n, tenant=None, seed0=100):
    return [_req(f"q{i}", user=f"u{i % E}", tenant=tenant, seed=seed0 + i)
            for i in range(n)]


_CFG = dict(max_batch=4, max_wait_s=0.0)


def _misses():
    return metrics_registry.snapshot()["counters"].get("jitcache.misses", 0)


# -- shape-generic shared programs -------------------------------------------


def test_shape_signature_seed_independent():
    """Same shapes, different values -> same signature; a different
    feature width -> a different signature (its own program ladder)."""
    a = DeviceResidentModel(_synth_model(0))
    b = DeviceResidentModel(_synth_model(1))
    assert a.shape_signature() == b.shape_signature()
    wide = _synth_model(0)
    c = DeviceResidentModel(wide, feature_pad=16)
    assert a.shape_signature() != c.shape_signature()


def test_eight_tenants_share_one_compiled_ladder():
    """The acceptance bound: warming 8 same-shape tenants builds at most
    1.1x the programs of warming 1 (tenants 2..8 are pure cache hits)."""
    jitcache.clear()
    m0 = _misses()
    solo = MultiTenantEngine(config=ServingConfig(**_CFG))
    solo.add_tenant("t0", DeviceResidentModel(_synth_model(0)))
    one = _misses() - m0
    assert one > 0

    jitcache.clear()
    m1 = _misses()
    mte = MultiTenantEngine(config=ServingConfig(**_CFG))
    for i in range(8):
        mte.add_tenant(f"t{i}", DeviceResidentModel(_synth_model(i)))
    eight = _misses() - m1
    assert eight <= math.ceil(1.1 * one), (one, eight)

    # and the shared programs still score each tenant's OWN parameters
    got = mte.serve([_req("a", tenant="t0", seed=5),
                     _req("b", tenant="t5", seed=5)])
    assert got[0].score != got[1].score   # same features, different models
    assert (got[0].tenant, got[1].tenant) == ("t0", "t5")


def test_tenant_ladder_mismatch_rejected():
    mte = MultiTenantEngine(config=ServingConfig(**_CFG))
    with pytest.raises(ValueError, match="bucket ladder"):
        mte.add_tenant("bad", DeviceResidentModel(_synth_model(0)),
                       config=ServingConfig(max_batch=8, max_wait_s=0.0))


# -- per-tenant isolation: bitwise parity with a dedicated engine ------------


def _parity(config, n=10, seed_a=0, seed_b=1):
    """Serve identical traffic through tenant 'beta' of a 2-tenant MTE
    and through a dedicated engine over the same model; return both
    response lists (order preserved)."""
    mte = MultiTenantEngine(config=config)
    mte.add_tenant("alpha", DeviceResidentModel(_synth_model(seed_a)))
    mte.add_tenant("beta", DeviceResidentModel(_synth_model(seed_b)))
    dedicated = ServingEngine(DeviceResidentModel(_synth_model(seed_b)),
                              config=config)
    dedicated.warmup()
    got = mte.serve(_traffic(n, tenant="beta"))
    want = dedicated.serve(_traffic(n))
    return got, want


def test_tenant_full_path_bitwise_equal_dedicated():
    got, want = _parity(ServingConfig(**_CFG))
    for g, w in zip(got, want):
        assert g.score == w.score          # bitwise: same compiled program
        assert not g.degraded
        assert (g.tenant, g.arm) == ("beta", "live")


def test_tenant_int8_path_bitwise_equal_dedicated():
    got, want = _parity(ServingConfig(int8_serving=True, **_CFG))
    for g, w in zip(got, want):
        assert g.score == w.score


def test_tenant_slo_shed_bitwise_equal_dedicated():
    """Queue past the shed depth without pumping: the overflow scores
    fixed-effect-only, typed — identically in both hostings."""
    cfg = ServingConfig(max_batch=4, max_wait_s=60.0,
                        slo=SLOConfig(shed_queue_depth=2))
    mte = MultiTenantEngine(config=cfg)
    mte.add_tenant("beta", DeviceResidentModel(_synth_model(1)))
    dedicated = ServingEngine(DeviceResidentModel(_synth_model(1)),
                              config=cfg)
    dedicated.warmup()
    for r in _traffic(6, tenant="beta"):
        assert mte.submit(r) is None
    for r in _traffic(6):
        assert dedicated.submit(r) is None
    got, want = [], []
    while any(st.depth() for st in mte.tenants.values()):
        got.extend(mte.pump(flush=True))
    while dedicated.batcher.depth():
        want.extend(dedicated.pump(flush=True))
    assert len(got) == len(want) == 6
    by_uid_w = {w.uid: w for w in want}
    shed = 0
    for g in got:
        w = by_uid_w[g.uid]
        assert g.score == w.score and _reasons(g) == _reasons(w)
        shed += FallbackReason.SLO_SHED_RANDOM_EFFECTS in _reasons(g)
    assert shed > 0


def _model_dir(tmp_path, name="m"):
    """Reference-layout model dir (cold stores + sidecars) for the
    two-tier arm."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    im_g = IndexMap.from_keys([feature_key("g", str(j)) for j in range(D)])
    im_u = IndexMap.from_keys([feature_key("u", str(j)) for j in range(D)])
    proj = np.stack([np.sort(rng.choice(D, size=K, replace=False))
                     for _ in range(E)]).astype(np.int32)
    users = [f"user{e}" for e in range(E)]
    vocab = EntityVocabulary()
    vocab.build("userId", users)
    model = GameModel({
        "fixed": FixedEffectModel(
            GeneralizedLinearModel(
                Coefficients(jnp.asarray(rng.normal(size=D))),
                TaskType.LOGISTIC_REGRESSION), "g"),
        "per_user": RandomEffectModel(
            jnp.asarray(rng.normal(size=(E, K))), "userId", "u",
            TaskType.LOGISTIC_REGRESSION),
    })
    d = str(tmp_path / name)
    save_game_model(d, model, {"g": im_g, "u": im_u}, vocab=vocab,
                    projections={"per_user": proj}, sparsity_threshold=0.0)
    return d, users


def test_tenant_two_tier_cold_miss_bitwise_equal_dedicated(tmp_path):
    """The cold-miss path under a tenant: first touch degrades typed
    COLD_MISS with the identical fixed-only score a dedicated two-tier
    engine produces; after the transfer drains, both score clean and
    equal."""
    d, users = _model_dir(tmp_path)
    cfg = ServingConfig(max_batch=4, max_wait_s=0.0,
                        coeff_store=CoeffStoreConfig(
                            hot_capacity=4, transfer_batch=2,
                            prefetch=False))
    mte = MultiTenantEngine(config=cfg)
    mte.add_tenant_from_dir("tt", d)
    dedicated = ServingEngine.from_model_dir(d, config=cfg)
    dedicated.warmup()
    req = ScoreRequest("c0", {"g": [("g", str(j), 0.5) for j in range(D)],
                              "u": [("u", str(j), 0.5) for j in range(D)]},
                       {"userId": users[0]})
    try:
        g1 = mte.serve([ScoreRequest("c0", req.features, req.entity_ids,
                                     tenant="tt")])[0]
        w1 = dedicated.serve([req])[0]
        assert g1.degraded and FallbackReason.COLD_MISS in _reasons(g1)
        assert g1.score == w1.score and _reasons(g1) == _reasons(w1)
        assert mte.tenants["tt"].engine.model.drain_prefetch()
        assert dedicated.model.drain_prefetch()
        g2 = mte.serve([ScoreRequest("c1", req.features, req.entity_ids,
                                     tenant="tt")])[0]
        w2 = dedicated.serve([ScoreRequest("c1", req.features,
                                           req.entity_ids)])[0]
        assert not g2.degraded and g2.score == w2.score
    finally:
        mte.shutdown(drain_budget_s=0.0)
        dedicated.shutdown(drain_budget_s=0.0)


# -- fault isolation ---------------------------------------------------------


def test_breaker_trip_isolated_to_one_tenant():
    """Tenant A's NaN model trips A's breaker; B's responses stay clean
    and bitwise-equal to a dedicated engine's."""
    cfg = ServingConfig(max_batch=1, max_wait_s=0.0,
                        breaker=BreakerConfig(window=8, min_samples=2,
                                              failure_rate=0.4),
                        swap=SwapConfig(probation_s=0.0))
    mte = MultiTenantEngine(config=cfg)
    mte.add_tenant("A", DeviceResidentModel(_synth_model(0, nan_fixed=True)))
    mte.add_tenant("B", DeviceResidentModel(_synth_model(1)))
    dedicated = ServingEngine(DeviceResidentModel(_synth_model(1)),
                              config=cfg)
    dedicated.warmup()
    got_b = []
    for i in range(4):
        mte.submit(_req(f"a{i}", tenant="A", seed=i))
        mte.submit(_req(f"b{i}", tenant="B", seed=i))
        got_b.extend(r for r in mte.pump(flush=True) if r.tenant == "B")
    want_b = dedicated.serve([_req(f"b{i}", seed=i) for i in range(4)])
    assert mte.tenants["A"].engine.breaker.state() in ("shed", "open")
    assert mte.tenants["B"].engine.breaker.state() == "closed"
    for g, w in zip(got_b, want_b):
        assert g.score == w.score and not g.degraded


def test_admission_budget_typed_refusal_neighbor_clean():
    """Tenant A floods past its admission budget -> typed
    TENANT_BUDGET_EXCEEDED for A only; B keeps scoring undegraded."""
    cfg = ServingConfig(max_batch=4, max_wait_s=60.0)
    mte = MultiTenantEngine(config=cfg)
    mte.add_tenant("A", DeviceResidentModel(_synth_model(0)),
                   admission_budget=3)
    mte.add_tenant("B", DeviceResidentModel(_synth_model(1)))
    refused = []
    for i in range(8):
        r = mte.submit(_req(f"a{i}", tenant="A", seed=i))
        if r is not None:
            refused.append(r)
    assert len(refused) == 5
    assert all(_reasons(r) == {FallbackReason.TENANT_BUDGET_EXCEEDED}
               for r in refused)
    assert all(r.tenant == "A" for r in refused)
    got = mte.serve(_traffic(4, tenant="B"))
    assert all(not r.degraded and r.tenant == "B" for r in got)


def test_chaos_tenant_hot_loop_bounded_by_budget():
    """The noisy-neighbor injector: floods enter through tenant A's OWN
    budget gate, so B never sheds and never changes a score, while the
    flood itself is visibly injected+dropped (counters)."""
    cfg = ServingConfig(max_batch=2, max_wait_s=0.0)
    mte = MultiTenantEngine(config=cfg)
    mte.add_tenant("A", DeviceResidentModel(_synth_model(0)),
                   admission_budget=2)
    mte.add_tenant("B", DeviceResidentModel(_synth_model(1)))
    dedicated = ServingEngine(DeviceResidentModel(_synth_model(1)),
                              config=cfg)
    dedicated.warmup()
    with chaos.active(chaos.ChaosConfig(tenant_hot_loop="A",
                                        tenant_hot_loop_burst=4,
                                        tenant_hot_loop_total=40)):
        got_b, got_a = [], []
        for i in range(10):
            ra = mte.submit(_req(f"a{i}", tenant="A", seed=i))
            if ra is not None:
                got_a.append(ra)
            rb = mte.submit(_req(f"b{i}", tenant="B", seed=i))
            assert rb is None             # B admission never touched
            for r in mte.pump(flush=True):
                (got_a if r.tenant == "A" else got_b).append(r)
    want_b = dedicated.serve([_req(f"b{i}", seed=i) for i in range(10)])
    by_uid = {r.uid: r for r in got_b}
    for w in want_b:
        g = by_uid[w.uid]
        assert g.score == w.score and not g.degraded
    snap = metrics_registry.snapshot()["counters"]
    assert snap.get('serving.tenant_flood_injected{tenant="A"}', 0) > 0
    # no flood uid ever reaches a caller
    assert not any(r.uid.startswith("__chaos_flood__")
                   for r in got_a + got_b)


def test_unknown_tenant_typed_refusal():
    mte = MultiTenantEngine(config=ServingConfig(**_CFG))
    mte.add_tenant("only", DeviceResidentModel(_synth_model(0)))
    r = mte.submit(_req("x", tenant="nope"))
    assert r is not None and r.score is None
    assert _reasons(r) == {FallbackReason.UNKNOWN_TENANT}
    # tenant-less requests route to the default tenant
    assert mte.submit(_req("y")) is None


# -- canary / A-B ------------------------------------------------------------


def test_canary_split_deterministic_and_sums_to_100():
    mte = MultiTenantEngine(config=ServingConfig(**_CFG))
    mte.add_tenant("t", DeviceResidentModel(_synth_model(0)))
    res = mte.start_canary("t", _synth_model(9), "v2", fraction=0.3)
    assert res.accepted, res.reason
    n = 120
    got = mte.serve(_traffic(n, tenant="t"))
    arms = {r.uid: r.arm for r in got}
    # typed per-arm attribution matches the published hash split exactly
    for r in got:
        want = ("canary" if MultiTenantEngine.canary_pick("t", r.uid, 0.3)
                else "live")
        assert r.arm == want
    splits = dict(mte.tenants["t"].split_counts)      # first-pass snapshot
    assert splits["live"] + splits["canary"] == n     # sums to 100%
    assert 0 < splits["canary"] < n
    # deterministic: a second pass splits identically per uid
    got2 = mte.serve(_traffic(n, tenant="t"))
    assert {r.uid: r.arm for r in got2} == arms
    info = mte.promote_canary("t")
    assert mte.tenants["t"].engine.model_version == 2
    assert info["splits"]["canary"] == splits["canary"] * 2


def test_canary_gate_failure_opens_no_arm():
    mte = MultiTenantEngine(config=ServingConfig(**_CFG))
    mte.add_tenant("t", DeviceResidentModel(_synth_model(0)))
    res = mte.start_canary("t", _synth_model(9, nan_fixed=True), "bad",
                           fraction=0.5)
    assert not res.accepted
    assert mte.tenants["t"].canary_engine is None
    got = mte.serve(_traffic(4, tenant="t"))
    assert all(r.arm == "live" for r in got)


# -- AOT program bundles: instant cold start ---------------------------------


def test_program_bundle_roundtrip_zero_trace_bitwise_equal(tmp_path):
    cfg = ServingConfig(**_CFG)
    model = DeviceResidentModel(_synth_model(0))
    engine = ServingEngine(model, config=cfg)
    engine.warmup()
    want = engine.serve(_traffic(6))
    buckets = _ladder_buckets(cfg)
    bdir = bundle_dir_for(str(tmp_path), model)
    out = export_program_bundle(model, buckets, bdir)
    assert out["exported"] == len(buckets) * 2 and not out["skipped"]

    # simulated restart: empty jitcache, load, warm — zero traces
    jitcache.clear()
    model2 = DeviceResidentModel(_synth_model(0))
    got_load = load_program_bundle(model2, buckets, bdir)
    assert got_load["refused"] is None
    assert got_load["loaded"] == out["exported"]
    m0, c0 = _misses(), dict(compile_cache.compile_counts())
    engine2 = ServingEngine(model2, config=cfg)
    engine2.warmup()
    assert _misses() == m0                      # zero jit traces
    c1 = compile_cache.compile_counts()
    assert c1["warmup"] == c0["warmup"]         # zero XLA compiles
    assert c1["steady_state"] == c0["steady_state"]
    got = engine2.serve(_traffic(6))
    for g, w in zip(got, want):
        assert g.score == w.score


def test_program_bundle_corrupt_refused_falls_back(tmp_path):
    """chaos.program_cache_corrupt flips one byte -> the crc gate
    refuses the WHOLE bundle (typed), warmup traces instead, and scores
    are unchanged: a corrupt bundle costs a re-trace, never a wrong
    score."""
    cfg = ServingConfig(**_CFG)
    model = DeviceResidentModel(_synth_model(0))
    ServingEngine(model, config=cfg).warmup()
    want = ServingEngine(model, config=cfg).serve(_traffic(4))
    buckets = _ladder_buckets(cfg)
    bdir = bundle_dir_for(str(tmp_path), model)
    export_program_bundle(model, buckets, bdir)
    victim = chaos.program_cache_corrupt(bdir, seed=1)
    assert os.path.exists(victim)

    jitcache.clear()
    model2 = DeviceResidentModel(_synth_model(0))
    got_load = load_program_bundle(model2, buckets, bdir)
    assert got_load["loaded"] == 0 and got_load["refused"] == "crc_mismatch"
    engine2 = ServingEngine(model2, config=cfg)
    engine2.warmup()                            # tracing fallback
    got = engine2.serve(_traffic(4))
    for g, w in zip(got, want):
        assert g.score == w.score


def test_program_bundle_signature_mismatch_refused(tmp_path):
    cfg = ServingConfig(**_CFG)
    model = DeviceResidentModel(_synth_model(0))
    ServingEngine(model, config=cfg).warmup()
    buckets = _ladder_buckets(cfg)
    bdir = str(tmp_path / "b")
    export_program_bundle(model, buckets, bdir)
    other = DeviceResidentModel(_synth_model(0), feature_pad=16)
    got = load_program_bundle(other, buckets, bdir)
    assert got["refused"] == "signature_mismatch"


def test_multi_tenant_bundle_restart_zero_compile(tmp_path):
    """The full cold-start story: a 3-tenant host exports ONE shared
    bundle; a 'restarted' host loads it and warms all tenants with zero
    traces and zero compiles."""
    cfg = ServingConfig(**_CFG)
    mte = MultiTenantEngine(config=cfg)
    for i in range(3):
        mte.add_tenant(f"t{i}", DeviceResidentModel(_synth_model(i)))
    exported = mte.export_program_bundles(str(tmp_path))
    assert len(exported) == 1                   # one shape -> one bundle

    jitcache.clear()
    mte2 = MultiTenantEngine(config=cfg)
    for i in range(3):
        mte2.add_tenant(f"t{i}", DeviceResidentModel(_synth_model(i)),
                        warm=False)
    loads = mte2.load_program_bundles(str(tmp_path))
    assert all(v["loaded"] > 0 or "shared_with" in v for v in loads.values())
    m0, c0 = _misses(), dict(compile_cache.compile_counts())
    info = mte2.warmup()
    assert info["programs"] == 3 * len(_ladder_buckets(cfg)) * 2
    assert _misses() == m0
    c1 = compile_cache.compile_counts()
    assert (c1["warmup"], c1["steady_state"]) == \
        (c0["warmup"], c0["steady_state"])


# -- labeled warmup gauges through merge_snapshots (satellite a) -------------


def test_warmup_gauges_labeled_per_tenant_survive_merge():
    from photon_tpu.obs.metrics import MetricsRegistry, merge_snapshots

    mte = MultiTenantEngine(config=ServingConfig(**_CFG))
    mte.add_tenant("alpha", DeviceResidentModel(_synth_model(0)))
    mte.add_tenant("beta", DeviceResidentModel(_synth_model(1)))
    snap = metrics_registry.snapshot()["gauges"]
    for t in ("alpha", "beta"):
        assert f'serving.warmup_seconds{{tenant="{t}"}}' in snap
        assert snap[f'serving.warmup_programs{{tenant="{t}"}}'] > 0

    # regression: distinct labels stay distinct keys across a fleet merge
    snaps = []
    for pid, t in ((0, "alpha"), (1, "beta")):
        reg = MetricsRegistry()
        reg.gauge("serving.warmup_seconds", tenant=t).set(1.0 + pid)
        snaps.append(reg.snapshot())
    merged = merge_snapshots(snaps)
    assert merged["gauges"]['serving.warmup_seconds{tenant="alpha"}'] == 1.0
    assert merged["gauges"]['serving.warmup_seconds{tenant="beta"}'] == 2.0
