"""Diagnostics tests vs scipy/analytic oracles.

Mirrors photon-diagnostics test coverage: BootstrapTrainingTest,
HosmerLemeshowDiagnosticTest, KendallTauAnalysisTest,
FeatureImportanceDiagnosticTest, FittingDiagnostic + report renderers.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from photon_tpu.diagnostics import (
    BulletedList,
    Chapter,
    CoefficientSummary,
    Document,
    Section,
    SimpleText,
    Table,
    bootstrap_training,
    bootstrap_weights,
    expected_magnitude_importance,
    fitting_diagnostic,
    hosmer_lemeshow,
    kendall_tau,
    render_html,
    render_text,
    variance_importance,
)
from photon_tpu.data.dataset import DataBatch
from photon_tpu.types import TaskType


# -- bootstrap ---------------------------------------------------------------


def test_coefficient_summary_stats():
    s = CoefficientSummary(np.asarray([1.0, 2.0, 3.0, 4.0]))
    assert s.mean == pytest.approx(2.5)
    assert s.min == 1.0 and s.max == 4.0
    assert s.median in (3.0, 2.0)  # index-based quantile like the reference
    assert s.count == 4
    assert "Mean" in str(s)


def test_bootstrap_weights_shape_and_mass():
    w = bootstrap_weights(jnp.asarray(np.asarray([0, 1], np.uint32)), 5, 100,
                          portion=0.8)
    assert w.shape == (5, 100)
    np.testing.assert_allclose(np.asarray(w).sum(axis=1), 80)


def test_bootstrap_training_recovers_coefficients():
    rng = np.random.default_rng(0)
    n, d = 400, 4
    w_true = np.asarray([2.0, -1.0, 0.5, 0.0])
    X = rng.normal(size=(n, d))
    y = X @ w_true + 0.1 * rng.normal(size=n)
    batch = DataBatch(jnp.asarray(X), jnp.asarray(y))
    out = bootstrap_training(TaskType.LINEAR_REGRESSION, batch, d,
                             num_bootstrap_samples=12, seed=1)
    assert out["models"].shape == (12, d)
    summaries = out["coefficients"]
    for j in range(d):
        # true coefficient within the bootstrap spread
        spread = 5 * max(summaries[j].std_dev, 0.02)
        assert abs(summaries[j].mean - w_true[j]) < spread
    # replicas differ (resampling actually happened)
    assert np.std(out["models"][:, 0]) > 1e-4


def test_bootstrap_metric_aggregation():
    rng = np.random.default_rng(1)
    n, d = 200, 3
    X = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)
    batch = DataBatch(jnp.asarray(X), jnp.asarray(y))

    def ev(coef):
        return {"norm": float(jnp.linalg.norm(coef))}

    out = bootstrap_training(TaskType.LOGISTIC_REGRESSION, batch, d,
                             num_bootstrap_samples=5, l2_weight=1.0,
                             evaluate_fn=ev, seed=2)
    assert "norm" in out["metrics"]
    assert out["metrics"]["norm"].count == 5


# -- Hosmer-Lemeshow ---------------------------------------------------------


def test_hl_well_calibrated_model_passes():
    rng = np.random.default_rng(3)
    n = 5000
    p = rng.uniform(0.05, 0.95, size=n)
    y = (rng.random(n) < p).astype(float)
    rep = hosmer_lemeshow(y, p, num_bins=10)
    assert rep.degrees_of_freedom == 8
    # calibrated: chi2 below the 99% cutoff almost surely
    assert rep.chi_square < rep.cutoffs[0.99]
    assert 0.0 <= rep.p_value <= 1.0
    assert len(rep.bins) == 10
    assert "chi2" in rep.summary()


def test_hl_miscalibrated_model_fails():
    rng = np.random.default_rng(4)
    n = 5000
    p = rng.uniform(0.05, 0.95, size=n)
    y = (rng.random(n) < p ** 2).astype(float)  # systematically over-predicted
    rep = hosmer_lemeshow(y, p, num_bins=10)
    assert rep.chi_square > rep.cutoffs[0.99999999]
    assert rep.p_value < 1e-6


def test_hl_counts_conserve_mass():
    rng = np.random.default_rng(5)
    p = rng.uniform(size=1000)
    y = (rng.random(1000) < 0.3).astype(float)
    rep = hosmer_lemeshow(y, p)
    assert sum(b.count for b in rep.bins) == pytest.approx(1000)
    assert sum(b.observed_pos for b in rep.bins) == pytest.approx(y.sum())


# -- Kendall tau -------------------------------------------------------------


def test_kendall_tau_matches_scipy():
    from scipy.stats import kendalltau

    rng = np.random.default_rng(6)
    a = rng.normal(size=300)
    b = 0.6 * a + 0.4 * rng.normal(size=300)
    rep = kendall_tau(a, b)
    ref_tau, _ = kendalltau(a, b)
    assert rep.tau_beta == pytest.approx(ref_tau, abs=1e-10)
    assert rep.num_items == 300
    assert rep.z_alpha > 3  # clearly dependent


def test_kendall_tau_independent():
    rng = np.random.default_rng(7)
    rep = kendall_tau(rng.normal(size=400), rng.normal(size=400))
    assert abs(rep.tau_alpha) < 0.1
    assert rep.p_value < 0.99  # inside-mass not extreme


def test_kendall_tau_tie_reporting():
    a = np.asarray([1.0, 1.0, 2.0, 3.0])
    b = np.asarray([1.0, 2.0, 2.0, 3.0])
    rep = kendall_tau(a, b)
    assert rep.num_ties_a == 1 and rep.num_ties_b == 1
    assert "ties" in rep.message


# -- feature importance ------------------------------------------------------


def test_feature_importance_ordering():
    from photon_tpu.data.stats import compute_feature_stats

    rng = np.random.default_rng(8)
    X = rng.normal(size=(500, 3)) * np.asarray([1.0, 10.0, 0.1])
    stats = compute_feature_stats(jnp.asarray(X), 3)
    coef = np.asarray([1.0, 1.0, 1.0])
    rep = variance_importance(coef, stats, feature_names=["a", "b", "c"])
    assert rep.ranked[0][0] == "b"   # largest sd dominates
    assert rep.ranked[-1][0] == "c"
    rep2 = expected_magnitude_importance(coef, None)
    assert all(v == 1.0 for _, _, v in rep2.ranked)
    assert 0.0 in rep.rank_to_importance and 1.0 in rep.rank_to_importance


# -- fitting diagnostic ------------------------------------------------------


def test_fitting_diagnostic_learning_curve():
    from photon_tpu.optim.problem import GlmOptimizationProblem

    rng = np.random.default_rng(9)
    n, d = 600, 5
    w_true = rng.normal(size=d)
    X = rng.normal(size=(n, d))
    y = X @ w_true + 0.5 * rng.normal(size=n)
    Xt = rng.normal(size=(200, d))
    yt = Xt @ w_true + 0.5 * rng.normal(size=200)
    batch = DataBatch(jnp.asarray(X), jnp.asarray(y))
    prob = GlmOptimizationProblem(TaskType.LINEAR_REGRESSION)

    def train(masked):
        model, _ = prob.run(masked, dim=d, dtype=masked.labels.dtype)
        return model

    def evaluate(model, split):
        Xe, ye = (X, y) if split == "train" else (Xt, yt)
        pred = np.asarray(model.compute_score(jnp.asarray(Xe)))
        return {"rmse": float(np.sqrt(np.mean((pred - ye) ** 2)))}

    rep = fitting_diagnostic(batch, train, evaluate,
                             fractions=(0.1, 0.5, 1.0), seed=0)
    assert rep.fractions == [0.1, 0.5, 1.0]
    # test error improves (weakly) with more data
    assert rep.test_metrics["rmse"][-1] <= rep.test_metrics["rmse"][0] + 0.05
    assert "rmse" in rep.summary()


# -- reporting ---------------------------------------------------------------


def test_report_renderers():
    doc = Document("Model report").add(
        Chapter("Diagnostics").add(
            Section("Calibration")
            .add(SimpleText("chi2 = 3.2"))
            .add(BulletedList(["bin 1 ok", "bin 2 ok"]))
            .add(Table(["name", "value"], [["AUC", 0.91], ["RMSE", 0.3]],
                       caption="metrics"))))
    text = render_text(doc)
    assert "Model report" in text and "chi2 = 3.2" in text
    assert "* bin 1 ok" in text and "AUC" in text
    html = render_html(doc)
    assert html.startswith("<html>") and "<table" in html
    assert "<li>bin 2 ok</li>" in html
    # escaping
    doc2 = Document("<script>").add(Chapter("c").add(
        Section("s").add(SimpleText("a < b"))))
    html2 = render_html(doc2)
    assert "<script>" not in html2.replace("&lt;script&gt;", "")
    assert "a &lt; b" in html2
