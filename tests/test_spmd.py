"""SPMD execution tests on the 8-virtual-device mesh: sharded solves must
match single-device results and the compiled programs must actually
communicate (all-reduce in HLO) — the proof that the treeAggregate
replacement (SURVEY §5.8) executes, not just exists.

Reference behaviors being replaced: ValueAndGradientAggregator.scala:240-255
(treeAggregate), DistributedObjectiveFunction.scala:34 (coefficient
broadcast), RandomEffectCoordinate.scala:104-129 (co-partitioned per-entity
solves)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_tpu.data.dataset import DataBatch
from photon_tpu.function.objective import GLMObjective, Hyper
from photon_tpu.ops import features as F
from photon_tpu.ops.losses import LogisticLoss
from photon_tpu.parallel import mesh as M
from photon_tpu.optim.problem import GlmOptimizationProblem, GLMOptimizationConfiguration, OptimizerConfig
from photon_tpu.types import TaskType

from tests.test_game import glmix, glmix_estimator, make_glmix_frame  # noqa: F401


def make_logistic(rng, n=1024, d=16):
    X = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-X @ w))).astype(np.float64)
    return DataBatch(jnp.asarray(X), jnp.asarray(y)), X, y


def test_sharded_gradient_matches_and_allreduces(rng, devices8):
    """Data-sharded value+gradient == replicated result, and the compiled
    HLO contains an all-reduce (the treeAggregate equivalent on ICI)."""
    batch, _, _ = make_logistic(rng)
    mesh = M.create_mesh()
    obj = GLMObjective(LogisticLoss)
    hyper = Hyper.of(0.3, dtype=jnp.float64)
    coef = jnp.asarray(rng.normal(size=16))

    f_ref, g_ref = obj.value_and_gradient(coef, batch, hyper)

    sharded = M.shard_batch(batch, mesh)
    coef_r = M.replicate(coef, mesh)
    fn = jax.jit(lambda c, b: obj.value_and_gradient(c, b, hyper))
    f_sh, g_sh = fn(coef_r, sharded)

    np.testing.assert_allclose(float(f_sh), float(f_ref), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(g_sh), np.asarray(g_ref), rtol=1e-10)

    hlo = fn.lower(coef_r, sharded).compile().as_text()
    assert "all-reduce" in hlo, "sharded gradient must communicate over the mesh"


def test_sharded_solve_matches_single_device(rng, devices8):
    """A whole L-BFGS solve over the sharded batch equals the unsharded
    solve (the reference's Distributed vs SingleNode parity)."""
    batch, _, _ = make_logistic(rng, n=1000)  # 1000 % 8 != 0: exercises padding
    mesh = M.create_mesh()
    problem = GlmOptimizationProblem(
        TaskType.LOGISTIC_REGRESSION,
        GLMOptimizationConfiguration(
            optimizer=OptimizerConfig(max_iterations=200, tolerance=1e-12)),
    )
    m_single, r_single = problem.run(batch, dim=16, dtype=jnp.float64,
                                     regularization_weight=1.0)
    problem2 = GlmOptimizationProblem(
        TaskType.LOGISTIC_REGRESSION,
        GLMOptimizationConfiguration(
            optimizer=OptimizerConfig(max_iterations=200, tolerance=1e-12)),
    )
    m_mesh, r_mesh = problem2.run(batch, dim=16, dtype=jnp.float64,
                                  regularization_weight=1.0, mesh=mesh)
    np.testing.assert_allclose(np.asarray(m_mesh.coefficients.means),
                               np.asarray(m_single.coefficients.means),
                               rtol=1e-8, atol=1e-10)


def test_zero_weight_padding_is_exact(rng, devices8):
    """Padding to the device multiple must not change value or gradient."""
    batch, _, _ = make_logistic(rng, n=997)  # prime: heavy padding
    obj = GLMObjective(LogisticLoss)
    hyper = Hyper.of(0.0, dtype=jnp.float64)
    coef = jnp.asarray(rng.normal(size=16))
    f0, g0 = obj.value_and_gradient(coef, batch, hyper)
    padded = M.pad_batch(batch, 8)
    assert padded.num_samples == 1000
    f1, g1 = obj.value_and_gradient(coef, padded, hyper)
    np.testing.assert_allclose(float(f1), float(f0), rtol=1e-14)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-14)


def test_game_estimator_mesh_parity(glmix, devices8):  # noqa: F811
    """GLMix fit on the 8-device mesh == single-device fit (sharded fixed
    batch + entity-sharded random effects), and validation AUC matches."""
    train, val, _ = glmix
    mesh = M.create_mesh()

    est_single = glmix_estimator(num_iterations=1)
    res_single = est_single.fit(train, validation_df=val)[-1]

    est_mesh = glmix_estimator(num_iterations=1)
    est_mesh.mesh = mesh
    res_mesh = est_mesh.fit(train, validation_df=val)[-1]

    fixed_s = res_single.model["fixed"].model.coefficients.means
    fixed_m = res_mesh.model["fixed"].model.coefficients.means
    np.testing.assert_allclose(np.asarray(fixed_m), np.asarray(fixed_s),
                               rtol=1e-6, atol=1e-8)

    re_s = np.asarray(res_single.model["per-user"].coefficients)
    re_m = np.asarray(res_mesh.model["per-user"].coefficients)
    # published models carry the vocabulary's true entity count either way
    assert re_m.shape == re_s.shape
    np.testing.assert_allclose(re_m, re_s, rtol=1e-6, atol=1e-8)

    assert abs(res_mesh.evaluation["AUC"] - res_single.evaluation["AUC"]) < 1e-9


def test_entity_sharded_blocks_cover_all_devices(glmix, devices8):  # noqa: F811
    """Entity blocks must actually land sharded across the mesh."""
    train, _, _ = glmix
    mesh = M.create_mesh()
    est = glmix_estimator(num_iterations=1)
    est.mesh = mesh
    est.fit(train)
    from photon_tpu.game.coordinate import RandomEffectCoordinate
    # rebuild a coordinate directly to inspect placement
    ds = est._re_datasets["per-user"]
    coord = RandomEffectCoordinate(ds, train.num_samples, "userId",
                                   "user_feats", TaskType.LOGISTIC_REGRESSION,
                                   mesh=mesh)
    assert coord.dataset.blocks, "expected at least one entity block"
    for blk in coord.dataset.blocks:
        sharding = blk.labels.sharding
        assert len(sharding.device_set) == 8, "entity block not spread over mesh"


def test_model_parallel_margins_allreduce(rng, devices8):
    """Feature-dimension sharding of theta (SURVEY §5.7): dense X sharded
    (data, model), theta sharded (model,) -> psum-ed partial dots."""
    n, d = 256, 64
    X = rng.normal(size=(n, d))
    coef = rng.normal(size=d)
    mesh = M.create_mesh(axis_names=(M.DATA_AXIS, M.MODEL_AXIS), shape=(4, 2))
    batch = M.shard_features_model_parallel(
        DataBatch(jnp.asarray(X), jnp.zeros(n)), mesh)
    theta = M.shard_coef_model_parallel(jnp.asarray(coef), mesh)

    fn = jax.jit(lambda x, t: F.matvec(x, t))
    margins = fn(batch.features, theta)
    np.testing.assert_allclose(np.asarray(margins), X @ coef, rtol=1e-10)
    hlo = fn.lower(batch.features, theta).compile().as_text()
    assert "all-reduce" in hlo, "model-parallel matvec must psum partial dots"


def test_estimator_model_axis_sharding_parity():
    """Fixed-effect training with theta sharded over the model axis through
    the PUBLIC estimator API: a (data=4, model=2) mesh must produce the
    same model as the (8, 1) data-parallel mesh, with all-reduce in the
    solve HLO (SURVEY §5.7; VERDICT r2 item 5 done-criterion)."""
    import numpy as np

    from photon_tpu.estimators.game_estimator import (
        CoordinateConfiguration,
        FixedEffectDataConfiguration,
        GameEstimator,
    )
    from photon_tpu.function.objective import L2Regularization
    from photon_tpu.game.dataset import FeatureShard, GameDataFrame
    from photon_tpu.game.random_effect import RandomEffectDataConfiguration
    from photon_tpu.optim.problem import (
        GLMOptimizationConfiguration,
        OptimizerConfig,
    )
    from photon_tpu.types import TaskType

    rng = np.random.default_rng(5)
    n, d, users, d_u = 512, 24, 10, 3   # d=24 pads to 24 (div by 2)
    Xg = rng.normal(size=(n, d))
    Xu = rng.normal(size=(n, d_u))
    uid = rng.integers(0, users, size=n)
    y = (rng.random(n) < 1 / (1 + np.exp(-(Xg @ rng.normal(size=d))))
         ).astype(np.float64)
    iu = np.arange(d_u, dtype=np.int32)
    df = GameDataFrame(
        num_samples=n, response=y,
        feature_shards={"global": FeatureShard(Xg, d),   # DENSE -> tp path
                        "u": FeatureShard([(iu, Xu[i]) for i in range(n)], d_u)},
        id_tags={"userId": [str(v) for v in uid]})

    opt = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=60, tolerance=1e-10),
        regularization=L2Regularization, regularization_weight=1.0)

    def fit(mesh):
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            {"fixed": CoordinateConfiguration(
                FixedEffectDataConfiguration("global"), opt),
             "per_user": CoordinateConfiguration(
                 RandomEffectDataConfiguration("userId", "u"), opt)},
            update_sequence=["fixed", "per_user"], num_iterations=2,
            dtype=jnp.float64, mesh=mesh)
        res = est.fit(df)
        return est, res[-1].model

    mesh_dp = M.create_mesh(8, (M.DATA_AXIS, M.MODEL_AXIS), (8, 1))
    mesh_tp = M.create_mesh(8, (M.DATA_AXIS, M.MODEL_AXIS), (4, 2))

    est_dp, m_dp = fit(mesh_dp)
    est_tp, m_tp = fit(mesh_tp)
    assert est_tp._coordinates["fixed"]._model_sharded
    assert not est_dp._coordinates["fixed"]._model_sharded

    np.testing.assert_allclose(
        np.asarray(m_tp["fixed"].model.coefficients.means),
        np.asarray(m_dp["fixed"].model.coefficients.means),
        rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(
        np.asarray(m_tp["per_user"].coefficients),
        np.asarray(m_dp["per_user"].coefficients),
        rtol=1e-8, atol=1e-10)

    # the tp solve must communicate over the mesh
    coord = est_tp._coordinates["fixed"]
    l2 = jnp.asarray(1.0, jnp.float64)
    theta0 = M.shard_coef_model_parallel(
        jnp.zeros((d,), jnp.float64), mesh_tp)
    hlo = coord.problem._solve_fn.lower(
        theta0, coord.batch, l2, jnp.asarray(0.0, jnp.float64)
    ).compile().as_text()
    assert "all-reduce" in hlo

    # memory property (SURVEY §5.7): dense-path theta is genuinely
    # partitioned — each device holds d/2 entries on the (4, 2) mesh,
    # vs a full replica per device data-parallel; this is what lets a
    # dense theta exceed one chip's replicable size at width d/P_model
    per_dev = {s.data.nbytes for s in theta0.addressable_shards}
    assert per_dev == {theta0.nbytes // 2}
    rep = M.replicate(jnp.zeros((d,), jnp.float64), mesh_dp)
    assert {s.data.nbytes for s in rep.addressable_shards} == {rep.nbytes}


# -- sparse feature-sharded fixed effect (SURVEY §5.7, VERDICT r3 item 3) ----

def _ell(rng, n, d, k):
    """Random ELL rows: k distinct feature ids per sample out of d."""
    idx = np.stack([rng.choice(d, size=k, replace=False) for _ in range(n)])
    val = rng.normal(size=(n, k))
    return F.SparseFeatures(jnp.asarray(idx, jnp.int32), jnp.asarray(val))


def test_sparse_model_parallel_kernel_parity(rng, devices8):
    """matvec/rmatvec/sq_rmatvec on feature-range-partitioned ELL blocks
    must match the plain data-parallel ELL kernels, and the margins program
    must all-reduce over the model axis (the psum of partial gather-dots)."""
    n, d, k = 64, 37, 5                      # d deliberately not % 2
    sf = _ell(rng, n, d, k)
    theta = rng.normal(size=d)
    w = rng.normal(size=n)

    mesh = M.create_mesh(8, (M.DATA_AXIS, M.MODEL_AXIS), (4, 2))
    batch = M.shard_sparse_features_model_parallel(
        DataBatch(sf, jnp.zeros(n)), mesh, dim=d)
    ms = batch.features
    assert isinstance(ms, F.ModelShardedSparse)
    d_pad = ms.padded_dim
    th = M.shard_coef_model_parallel(jnp.asarray(theta), mesh,
                                     padded_dim=d_pad)

    mv = jax.jit(lambda x, t: F.matvec(x, t))
    margins = mv(ms, th)
    np.testing.assert_allclose(np.asarray(margins),
                               np.asarray(F.matvec(sf, jnp.asarray(theta))),
                               rtol=1e-12)
    hlo = mv.lower(ms, th).compile().as_text()
    assert "all-reduce" in hlo, "partial gather-dots must psum over model axis"

    wj = jax.device_put(jnp.asarray(w), NamedSharding(mesh, P(M.DATA_AXIS)))
    g = jax.jit(lambda x, v: F.rmatvec(x, v, d_pad))(ms, wj)
    np.testing.assert_allclose(np.asarray(g)[:d],
                               np.asarray(F.rmatvec(sf, jnp.asarray(w), d)),
                               rtol=1e-12, atol=1e-12)
    assert np.allclose(np.asarray(g)[d:], 0.0)
    g2 = jax.jit(lambda x, v: F.sq_rmatvec(x, v, d_pad))(ms, wj)
    np.testing.assert_allclose(np.asarray(g2)[:d],
                               np.asarray(F.sq_rmatvec(sf, jnp.asarray(w), d)),
                               rtol=1e-12, atol=1e-12)


def test_partition_by_feature_range_layout():
    """Host-side partitioner invariants: local ids in range, per-range
    widths cover the worst row, values preserved."""
    idx = np.array([[0, 5, 9, 0], [3, 4, 8, 2]], np.int32)
    val = np.array([[1., 2., 3., 0.], [4., 5., 6., 7.]])
    sf = F.SparseFeatures(jnp.asarray(idx), jnp.asarray(val))
    out_idx, out_val, shard_size = F.partition_by_feature_range(sf, 10, 2)
    assert shard_size == 5
    assert out_idx.shape[0] == 2 and out_idx.max() < 5
    # row 1: shard0 gets {3:4, 4:5, 2:7}, shard1 gets {8:6} (local 3)
    got0 = {(i, v) for i, v in zip(out_idx[0, 1], out_val[0, 1]) if v != 0}
    assert got0 == {(3, 4.0), (4, 5.0), (2, 7.0)}
    got1 = {(i, v) for i, v in zip(out_idx[1, 1], out_val[1, 1]) if v != 0}
    assert got1 == {(3, 6.0)}


def test_sparse_feature_sharded_fixed_effect_parity(rng, devices8):
    """A sparse fixed effect trains with theta sharded over the model axis:
    (4, 2) mesh == (8, 1) data-parallel coefficients, all-reduce in the
    solve HLO, and theta is genuinely partitioned (per-device bytes sum to
    ONE copy, vs 8 replicas on the data-parallel mesh) — the memory
    property that lets theta exceed a single chip's replicable size."""
    from photon_tpu.game.coordinate import FixedEffectCoordinate

    n, d, k = 512, 1000, 8
    sf = _ell(rng, n, d, k)
    w = rng.normal(size=d) * 0.5
    margins = np.asarray(F.matvec(sf, jnp.asarray(w)))
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-margins))).astype(np.float64)
    batch = DataBatch(sf, jnp.asarray(y))

    from photon_tpu.function.objective import L2Regularization

    cfg = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=50, tolerance=1e-10),
        regularization=L2Regularization, regularization_weight=1.0)

    def fit(shape):
        mesh = M.create_mesh(8, (M.DATA_AXIS, M.MODEL_AXIS), shape)
        coord = FixedEffectCoordinate(batch, d, "g",
                                      TaskType.LOGISTIC_REGRESSION,
                                      cfg, mesh=mesh)
        model = coord.update_model(None, None)
        return coord, model

    coord_dp, m_dp = fit((8, 1))
    coord_tp, m_tp = fit((4, 2))
    assert coord_tp._model_sharded and not coord_dp._model_sharded
    assert isinstance(coord_tp.batch.features, F.ModelShardedSparse)

    np.testing.assert_allclose(
        np.asarray(m_tp.model.coefficients.means),
        np.asarray(m_dp.model.coefficients.means), rtol=1e-7, atol=1e-9)

    # scoring parity through the coordinate's own (model-sharded) batch
    np.testing.assert_allclose(np.asarray(coord_tp.score(m_tp)),
                               np.asarray(coord_dp.score(m_dp)),
                               rtol=1e-7, atol=1e-9)

    # communication proof: the jitted solve all-reduces
    l2 = jnp.asarray(1.0, jnp.float64)
    th0 = M.shard_coef_model_parallel(
        jnp.zeros((d,), jnp.float64), coord_tp.mesh,
        padded_dim=coord_tp._dim_padded)
    hlo = coord_tp.problem._solve_fn.lower(
        th0, coord_tp.batch, l2, jnp.asarray(0.0, jnp.float64)
    ).compile().as_text()
    assert "all-reduce" in hlo

    # memory proof: each device holds HALF of theta on the (4, 2) mesh
    # (sharded over model, replicated over data), vs a FULL copy per
    # device when data-parallel — the property that lets theta exceed a
    # single chip's replicable size at model-axis width d/P_model
    per_dev_tp = {s.data.nbytes for s in th0.addressable_shards}
    assert per_dev_tp == {th0.nbytes // 2}
    th_rep = M.replicate(jnp.zeros((d,), jnp.float64), coord_dp.mesh)
    per_dev_rep = {s.data.nbytes for s in th_rep.addressable_shards}
    assert per_dev_rep == {th_rep.nbytes}


def test_estimator_sparse_model_axis_through_public_api(rng, devices8):
    """Sparse fixed effect + random effect trained through GameEstimator
    on the (4, 2) mesh == (8, 1) data-parallel (the public-API version of
    the coordinate-level sparse tp test)."""
    from photon_tpu.estimators.game_estimator import (
        CoordinateConfiguration,
        FixedEffectDataConfiguration,
        GameEstimator,
    )
    from photon_tpu.function.objective import L2Regularization
    from photon_tpu.game.dataset import FeatureShard, GameDataFrame
    from photon_tpu.game.random_effect import RandomEffectDataConfiguration

    n, d, k, users, d_u = 512, 300, 6, 10, 3
    idx = np.stack([rng.choice(d, size=k, replace=False) for _ in range(n)])
    val = rng.normal(size=(n, k))
    uid = rng.integers(0, users, size=n)
    Xu = rng.normal(size=(n, d_u))
    w = rng.normal(size=d) * 0.5
    margins = np.asarray(
        F.matvec(F.SparseFeatures(jnp.asarray(idx, jnp.int32),
                                  jnp.asarray(val)), jnp.asarray(w)))
    y = (rng.random(n) < 1 / (1 + np.exp(-margins))).astype(np.float64)
    iu = np.arange(d_u, dtype=np.int32)
    df = GameDataFrame(
        num_samples=n, response=y,
        feature_shards={
            "g": FeatureShard([(idx[i], val[i]) for i in range(n)], d),
            "u": FeatureShard([(iu, Xu[i]) for i in range(n)], d_u)},
        id_tags={"userId": [str(v) for v in uid]})

    opt = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=50, tolerance=1e-10),
        regularization=L2Regularization, regularization_weight=1.0)

    def fit(shape):
        mesh = M.create_mesh(8, (M.DATA_AXIS, M.MODEL_AXIS), shape)
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            {"fixed": CoordinateConfiguration(
                FixedEffectDataConfiguration("g"), opt),
             "per_user": CoordinateConfiguration(
                 RandomEffectDataConfiguration("userId", "u"), opt)},
            update_sequence=["fixed", "per_user"], num_iterations=2,
            dtype=jnp.float64, mesh=mesh)
        return est, est.fit(df)[-1].model

    est_dp, m_dp = fit((8, 1))
    est_tp, m_tp = fit((4, 2))
    assert est_tp._coordinates["fixed"]._model_sharded
    assert isinstance(est_tp._coordinates["fixed"].batch.features,
                      F.ModelShardedSparse)
    np.testing.assert_allclose(
        np.asarray(m_tp["fixed"].model.coefficients.means),
        np.asarray(m_dp["fixed"].model.coefficients.means),
        rtol=1e-7, atol=1e-9)
    np.testing.assert_allclose(
        np.asarray(m_tp["per_user"].coefficients),
        np.asarray(m_dp["per_user"].coefficients), rtol=1e-7, atol=1e-9)


def test_create_pod_mesh_layout(devices8):
    """Pod mesh: data outermost / model innermost; initialize_distributed
    is a no-op single-process (SURVEY §5.8 DCN staging as mesh layout)."""
    assert M.initialize_distributed() == 1
    mesh = M.create_pod_mesh(model_axis_size=2)
    assert mesh.shape == {"data": 4, "model": 2}
    # a fit through the pod mesh matches the plain mesh
    rng = np.random.default_rng(3)
    batch, _, _ = make_logistic(rng, n=256)
    prob = GlmOptimizationProblem(
        TaskType.LOGISTIC_REGRESSION,
        GLMOptimizationConfiguration(
            optimizer=OptimizerConfig(max_iterations=60, tolerance=1e-12)))
    m_pod, _ = prob.run(batch, dim=16, dtype=jnp.float64,
                        regularization_weight=1.0, mesh=mesh)
    prob2 = GlmOptimizationProblem(
        TaskType.LOGISTIC_REGRESSION,
        GLMOptimizationConfiguration(
            optimizer=OptimizerConfig(max_iterations=60, tolerance=1e-12)))
    m_flat, _ = prob2.run(batch, dim=16, dtype=jnp.float64,
                          regularization_weight=1.0)
    np.testing.assert_allclose(np.asarray(m_pod.coefficients.means),
                               np.asarray(m_flat.coefficients.means),
                               rtol=1e-8, atol=1e-10)


def test_model_axis_explicit_hessian_tron_parity():
    """TRON with the EXPLICIT [d, d] Gauss-Newton Hessian (the TPU-default
    gate) under a model-sharded theta: GSPMD must partition the Gram
    build/CG identically to the data-parallel solve. This is the
    combination the round-4 TRON switch makes the on-chip default for
    dense fixed effects."""
    from photon_tpu.function.objective import L2Regularization
    from photon_tpu.types import OptimizerType

    rng = np.random.default_rng(9)
    n, d = 512, 16
    X = rng.normal(size=(n, d))
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ rng.normal(size=d))))
         ).astype(np.float64)

    cfg = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(optimizer_type=OptimizerType.TRON,
                                  max_iterations=60, tolerance=1e-11,
                                  explicit_hessian=True),
        regularization=L2Regularization, regularization_weight=0.7)

    def solve(mesh, model_par):
        prob = GlmOptimizationProblem(TaskType.LOGISTIC_REGRESSION, cfg)
        batch = DataBatch(jnp.asarray(X), jnp.asarray(y))
        if model_par:
            batch = M.shard_features_model_parallel(batch, mesh)
            init = M.shard_coef_model_parallel(
                jnp.zeros((d,), jnp.float64), mesh)
        else:
            batch = M.shard_batch(batch, mesh)
            init = M.replicate(jnp.zeros((d,), jnp.float64), mesh)
        model, _ = prob.run(batch, initial=init, dim=d, dtype=jnp.float64)
        return np.asarray(model.coefficients.means)

    mesh_dp = M.create_mesh(8, (M.DATA_AXIS, M.MODEL_AXIS), (8, 1))
    mesh_tp = M.create_mesh(8, (M.DATA_AXIS, M.MODEL_AXIS), (4, 2))
    c_dp = solve(mesh_dp, model_par=False)
    c_tp = solve(mesh_tp, model_par=True)
    np.testing.assert_allclose(c_tp, c_dp, rtol=1e-8, atol=1e-10)


def test_dcn_staged_psum_two_collectives(rng, devices8):
    """treeAggregateDepth>1 analog (GameEstimator.scala:100): on a
    (dcn, data, model) two-level mesh, staged_psum reduces the gradient
    with TWO collectives — replica groups within the slice first, then
    across slices — and equals the flat joint-axis reduction."""
    from jax.sharding import NamedSharding

    mesh = M.create_two_level_mesh(8, dcn_factor=2, model_axis_size=2)
    assert mesh.shape == {"dcn": 2, "data": 2, "model": 2}
    n, d = 48, 10
    X = rng.normal(size=(n, d)).astype(np.float32)
    r = rng.normal(size=n).astype(np.float32)
    spec_x = P((M.DCN_AXIS, M.DATA_AXIS), None)
    spec_r = P((M.DCN_AXIS, M.DATA_AXIS))
    Xs = jax.device_put(jnp.asarray(X), NamedSharding(mesh, spec_x))
    rs = jax.device_put(jnp.asarray(r), NamedSharding(mesh, spec_r))

    staged = jax.jit(M.shard_map(
        lambda xb, rb: M.staged_psum(xb.T @ rb),
        mesh=mesh, in_specs=(spec_x, spec_r), out_specs=P()))
    flat = jax.jit(M.shard_map(
        lambda xb, rb: jax.lax.psum(xb.T @ rb, (M.DCN_AXIS, M.DATA_AXIS)),
        mesh=mesh, in_specs=(spec_x, spec_r), out_specs=P()))

    np.testing.assert_allclose(np.asarray(staged(Xs, rs)), X.T @ r,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(staged(Xs, rs)),
                               np.asarray(flat(Xs, rs)), rtol=1e-6)

    # structure: two distinct all-reduce ops, replica groups of size 2
    # each (stage 1: the data pairs, stage 2: the dcn pairs) — vs the
    # flat reduction's single size-4 groups
    hlo = staged.lower(Xs, rs).compile().as_text()
    ars = [l for l in hlo.splitlines() if "all-reduce(" in l]
    assert len(ars) >= 2, hlo
    hlo_flat = flat.lower(Xs, rs).compile().as_text()
    ars_flat = [l for l in hlo_flat.splitlines() if "all-reduce(" in l]
    assert len(ars_flat) == 1


def test_newton_solve_data_parallel_parity(rng, devices8):
    """NEWTON (the flagship bench solver) under a data-parallel mesh: the
    sharded solve equals the single-device solve and its compiled HLO
    all-reduces — the explicit-Hessian Gram contraction reduces over the
    data axis exactly like the gradient treeAggregate."""
    from photon_tpu.function.objective import L2Regularization
    from photon_tpu.types import OptimizerType

    batch, _, _ = make_logistic(rng, n=512, d=12)
    cfg = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(optimizer_type=OptimizerType.NEWTON,
                                  max_iterations=30, tolerance=1e-10),
        regularization=L2Regularization, regularization_weight=1.0)

    prob_single = GlmOptimizationProblem(TaskType.LOGISTIC_REGRESSION, cfg)
    m_single, _ = prob_single.run(batch, dim=12, dtype=jnp.float64)

    mesh = M.create_mesh()
    prob_mesh = GlmOptimizationProblem(TaskType.LOGISTIC_REGRESSION, cfg)
    m_mesh, res = prob_mesh.run(batch, dim=12, dtype=jnp.float64, mesh=mesh)
    np.testing.assert_allclose(np.asarray(m_mesh.coefficients.means),
                               np.asarray(m_single.coefficients.means),
                               rtol=1e-7, atol=1e-9)

    sharded = M.shard_batch(batch, mesh)
    th0 = M.replicate(jnp.zeros((12,), jnp.float64), mesh)
    one = jnp.asarray(1.0, jnp.float64)
    hlo = prob_mesh._solve_fn.lower(
        th0, sharded, one, jnp.asarray(0.0, jnp.float64)).compile().as_text()
    assert "all-reduce" in hlo


def test_segment_reduce_rmatvec_matches_scatter_path(rng, devices8):
    """Parity pin for the sharded-sparse gradient kernels: the
    column-sorted contiguous-segment reduction (csc_* plan present — the
    fast path shard_sparse_features_model_parallel now builds at ingest)
    must match the serialized per-slot at[].add scatter fallback (plan
    stripped) on the SAME partitioned nonzeros, in f64 to 1e-12."""
    import dataclasses

    n, d, k = 96, 53, 7
    sf = _ell(rng, n, d, k)
    w = rng.normal(size=n)

    mesh = M.create_mesh(8, (M.DATA_AXIS, M.MODEL_AXIS), (4, 2))
    batch = M.shard_sparse_features_model_parallel(
        DataBatch(sf, jnp.zeros(n)), mesh, dim=d)
    ms = batch.features
    assert ms.csc_ptr is not None, "ingest must build the CSC plan"
    scatter = dataclasses.replace(
        ms, csc_rows=None, csc_vals=None, csc_ptr=None)
    d_pad = ms.padded_dim
    wj = jax.device_put(jnp.asarray(w), NamedSharding(mesh, P(M.DATA_AXIS)))

    for kern in (F.rmatvec, F.sq_rmatvec):
        g_seg = jax.jit(lambda x, v, f=kern: f(x, v, d_pad))(ms, wj)
        g_sc = jax.jit(lambda x, v, f=kern: f(x, v, d_pad))(scatter, wj)
        np.testing.assert_allclose(np.asarray(g_seg), np.asarray(g_sc),
                                   rtol=1e-12, atol=1e-12,
                                   err_msg=kern.__name__)
    # and against the unsharded oracle, which neither path shares code with
    np.testing.assert_allclose(
        np.asarray(jax.jit(lambda x, v: F.rmatvec(x, v, d_pad))(ms, wj))[:d],
        np.asarray(F.rmatvec(sf, jnp.asarray(w), d)),
        rtol=1e-12, atol=1e-12)


def test_sparse_tp_two_level_mesh_staged_reduction(rng):
    """Sparse TP composed with the two-level (dcn, data, model) mesh: the
    CSC plan chunks samples over dcn*data, the gradient psum stages
    ICI-then-DCN (>= 2 all-reduce ops in HLO), and the kernels still match
    the unsharded oracle."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    n, d, k = 64, 41, 5
    sf = _ell(rng, n, d, k)
    theta = rng.normal(size=d)
    w = rng.normal(size=n)

    mesh = M.create_two_level_mesh(8, dcn_factor=2, model_axis_size=2)
    batch = M.shard_sparse_features_model_parallel(
        DataBatch(sf, jnp.zeros(n)), mesh, dim=d)
    ms = batch.features
    assert ms.dcn_axis == M.DCN_AXIS
    d_pad = ms.padded_dim
    th = M.shard_coef_model_parallel(jnp.asarray(theta), mesh,
                                     padded_dim=d_pad)
    mv = jax.jit(lambda x, t: F.matvec(x, t))
    np.testing.assert_allclose(np.asarray(mv(ms, th)),
                               np.asarray(F.matvec(sf, jnp.asarray(theta))),
                               rtol=1e-12)

    wj = jax.device_put(
        jnp.asarray(w), NamedSharding(mesh, P((M.DCN_AXIS, M.DATA_AXIS))))
    rv = jax.jit(lambda x, v: F.rmatvec(x, v, d_pad))
    np.testing.assert_allclose(np.asarray(rv(ms, wj))[:d],
                               np.asarray(F.rmatvec(sf, jnp.asarray(w), d)),
                               rtol=1e-12, atol=1e-12)
    hlo = rv.lower(ms, wj).compile().as_text()
    n_ar = sum(1 for line in hlo.splitlines() if "all-reduce(" in line)
    assert n_ar >= 2, \
        f"expected staged ICI-then-DCN all-reduces in rmatvec, found {n_ar}"
