"""Serving resilience: deadlines, circuit breaker, drain, live swap.

ISSUE 6. Complements tests/test_serving.py (parity + SLO ladder): here
the engine is exercised under fault and change — expiring deadlines,
a slow/failing scorer stage tripping the breaker, SIGTERM drain, and
validated live model swap with automatic rollback. Chaos injection
(photon_tpu/resilience/chaos.py) provides the faults deterministically.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys

import numpy as np
import pytest

from photon_tpu.game.dataset import EntityVocabulary
from photon_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    GeneralizedLinearModel,
    RandomEffectModel,
)
from photon_tpu.io.index_map import IndexMap, feature_key
from photon_tpu.io.model_io import (
    ServingFixedEffect,
    ServingGameModel,
    ServingRandomEffect,
    save_game_model,
)
from photon_tpu.obs.metrics import registry as metrics_registry
from photon_tpu.resilience import chaos, shutdown
from photon_tpu.serving import (
    BreakerConfig,
    BucketLadder,
    DeadlineConfig,
    DeviceResidentModel,
    FallbackReason,
    MicroBatcher,
    QueueClosedError,
    ScoreRequest,
    ServingConfig,
    ServingEngine,
    SwapConfig,
    swap_from_dir,
    verify_swap_manifest,
    write_swap_manifest,
)
from photon_tpu.types import TaskType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

D_GLOBAL, D_USER, N_USERS = 8, 6, 4


def _reasons(resp):
    return {f.reason for f in resp.fallbacks}


# -- fixtures ----------------------------------------------------------------


def _build_model_dir(tmp_path, name, coef_shift=0.0):
    """Reference-layout GAME model dir; ``coef_shift`` offsets every
    coefficient, so two dirs form a swap pair with a known score diff."""
    import jax.numpy as jnp

    rng = np.random.default_rng(42)    # same draw for v1 and v2
    im_g = IndexMap.from_keys([feature_key("g", str(j))
                               for j in range(D_GLOBAL)])
    im_u = IndexMap.from_keys([feature_key("u", str(j))
                               for j in range(D_USER)])
    theta = rng.normal(size=D_GLOBAL) + coef_shift
    K = 3
    proj = np.full((N_USERS, K), -1, np.int32)
    coef = np.zeros((N_USERS, K))
    for e in range(N_USERS):
        proj[e] = np.sort(rng.choice(D_USER, size=K, replace=False))
        coef[e] = rng.normal(size=K) + coef_shift
    users = [f"user{e}" for e in range(N_USERS)]
    vocab = EntityVocabulary()
    vocab.build("userId", users)
    model = GameModel({
        "fixed": FixedEffectModel(
            GeneralizedLinearModel(Coefficients(jnp.asarray(theta)),
                                   TaskType.LOGISTIC_REGRESSION), "g"),
        "per_user": RandomEffectModel(jnp.asarray(coef), "userId", "u",
                                      TaskType.LOGISTIC_REGRESSION),
    })
    d = str(tmp_path / name)
    save_game_model(d, model, {"g": im_g, "u": im_u}, vocab=vocab,
                    projections={"per_user": proj}, sparsity_threshold=0.0)
    return d, users


def _traffic(users, n=12, seed=3):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        gf = [("g", str(j), float(rng.normal())) for j in range(D_GLOBAL)]
        uf = [("u", str(j), float(rng.normal())) for j in range(D_USER)]
        reqs.append(ScoreRequest(
            f"r{i}", {"g": gf, "u": uf},
            {"userId": users[i % len(users)]}, float(rng.normal() * 0.1)))
    return reqs


@pytest.fixture(scope="module")
def model_dirs(tmp_path_factory):
    """(v1 dir, v2 dir, users): same shapes, shifted coefficients, both
    manifest-stamped."""
    tmp_path = tmp_path_factory.mktemp("swap_models")
    v1, users = _build_model_dir(tmp_path, "v1", coef_shift=0.0)
    v2, _ = _build_model_dir(tmp_path, "v2", coef_shift=0.5)
    write_swap_manifest(v1)
    write_swap_manifest(v2)
    return v1, v2, users


def _synth_model(seed=7, nan_fixed=False):
    """Small in-memory ServingGameModel (one shard, one random effect)."""
    rng = np.random.default_rng(seed)
    imap = IndexMap.from_keys([feature_key(f"f{j}", "") for j in range(5)])
    theta = rng.normal(size=5).astype(np.float32)
    if nan_fixed:
        theta[0] = np.nan
    E, K = 3, 2
    proj = np.stack([np.sort(rng.choice(5, size=K, replace=False))
                     for _ in range(E)]).astype(np.int32)
    coef = rng.normal(size=(E, K)).astype(np.float32)
    return ServingGameModel(
        TaskType.LOGISTIC_REGRESSION,
        [ServingFixedEffect("global", "s", theta)],
        [ServingRandomEffect("per-u", "uid", "s", coef, proj,
                             {f"u{e}": e for e in range(E)})],
        {"s": imap}, {})


def _synth_req(uid, user="u0", timeout_s=None):
    return ScoreRequest(uid, {"s": [(f"f{j}", "", 1.0) for j in range(5)]},
                        {"uid": user}, timeout_s=timeout_s)


def _mk_engine(config=None, clock=None, model=None, warm=True):
    engine = ServingEngine(
        DeviceResidentModel(model if model is not None else _synth_model()),
        config or ServingConfig(max_batch=2, max_wait_s=0.0),
        clock=clock)
    if warm:
        engine.warmup()
    return engine


# -- deadline semantics (batching) -------------------------------------------


def test_batcher_deadline_release_with_injectable_clock():
    """A queued request's absolute deadline releases the batch as soon as
    only the score headroom is left — even though the oldest-waiter
    coalescing window is far from over."""
    now = [0.0]
    b = MicroBatcher(BucketLadder(max_batch=4), max_wait_s=10.0,
                     clock=lambda: now[0], deadline_headroom_s=0.010)
    b.submit(_synth_req("a"), deadline=0.100)
    assert not b.ready()
    now[0] = 0.089
    assert not b.ready()                 # headroom not yet reached
    now[0] = 0.091                       # inside the headroom: release now
    items, bucket = b.next_batch()
    assert [p.request.uid for p in items] == ["a"] and bucket == 1
    # a deadline-free request alone still waits for the full window
    b.submit(_synth_req("b"))
    now[0] = 5.0
    assert not b.ready()
    now[0] = 10.1
    assert b.ready()


def test_batcher_tighter_deadline_beats_oldest_waiter():
    """The release check scans every queued request: a NEWER request with
    a tighter deadline must not be starved by the oldest's long budget."""
    now = [0.0]
    b = MicroBatcher(BucketLadder(max_batch=4), max_wait_s=1.0,
                     clock=lambda: now[0])
    b.submit(_synth_req("slow"), deadline=100.0)
    now[0] = 0.010
    b.submit(_synth_req("tight"), deadline=0.050)
    now[0] = 0.050                       # tight's deadline, oldest is 40ms old
    items, _ = b.next_batch()
    assert {p.request.uid for p in items} == {"slow", "tight"}


def test_batcher_close_refuses_submit_lock_free():
    b = MicroBatcher(BucketLadder(max_batch=2))
    b.submit(_synth_req("a"))
    b.close()
    assert b.closed
    with pytest.raises(QueueClosedError):
        b.submit(_synth_req("b"))
    assert [p.request.uid for p in b.pop_all()] == ["a"]
    assert b.depth() == 0
    assert b.wait_for_work(timeout=0.001) is False


# -- deadline semantics (engine) ---------------------------------------------


def test_deadline_admission_refusal_below_service_floor():
    engine = _mk_engine(ServingConfig(
        max_batch=2, max_wait_s=0.0,
        deadline=DeadlineConfig(min_service_s=0.010)))
    resp = engine.submit(_synth_req("x", timeout_s=0.005))
    assert resp is not None and resp.score is None and resp.degraded
    assert _reasons(resp) == {FallbackReason.DEADLINE_EXCEEDED}
    # a feasible budget is admitted normally
    assert engine.submit(_synth_req("y", timeout_s=0.5)) is None
    [ok] = engine.drain()
    assert ok.uid == "y" and ok.score is not None


def test_deadline_queue_expiry_while_bucket_mates_score():
    """A request that expires in the queue gets DEADLINE_EXCEEDED; the
    rest of its batch still scores, in the smallest covering bucket."""
    now = [0.0]
    engine = _mk_engine(ServingConfig(max_batch=4, max_wait_s=10.0),
                        clock=lambda: now[0])
    engine.submit(_synth_req("doomed", timeout_s=0.050))
    engine.submit(_synth_req("fine1"))
    engine.submit(_synth_req("fine2"))
    assert engine.pump() == []           # nothing released yet
    now[0] = 0.060                       # past doomed's deadline
    resps = {r.uid: r for r in engine.pump()}
    assert set(resps) == {"doomed", "fine1", "fine2"}
    assert resps["doomed"].score is None
    assert _reasons(resps["doomed"]) == {FallbackReason.DEADLINE_EXCEEDED}
    for uid in ("fine1", "fine2"):
        assert resps[uid].score is not None and not resps[uid].degraded


def test_deadline_release_scores_in_time():
    """Released at deadline-minus-headroom, a request still scores: the
    deadline path refuses only requests that genuinely cannot make it."""
    now = [0.0]
    engine = _mk_engine(ServingConfig(max_batch=4, max_wait_s=10.0),
                        clock=lambda: now[0])
    engine.submit(_synth_req("t", timeout_s=0.050))
    now[0] = 0.050                       # release boundary, not yet expired
    [resp] = engine.pump()
    assert resp.uid == "t" and resp.score is not None


def test_default_timeout_applies_to_bare_requests():
    now = [0.0]
    engine = _mk_engine(ServingConfig(
        max_batch=4, max_wait_s=10.0,
        deadline=DeadlineConfig(default_timeout_s=0.030)),
        clock=lambda: now[0])
    engine.submit(_synth_req("bare"))    # no per-request timeout
    now[0] = 0.031
    [resp] = engine.pump()
    assert _reasons(resp) == {FallbackReason.DEADLINE_EXCEEDED}


# -- circuit breaker ----------------------------------------------------------


def test_breaker_latency_trip_shed_open_recover():
    """Slow scorer (chaos) trips closed->shed->open; admission refuses
    while open; after cooldown a healthy probe closes the breaker."""
    now = [0.0]
    cfg = ServingConfig(
        max_batch=1, max_wait_s=0.0,
        breaker=BreakerConfig(window=8, min_samples=2, latency_p99_s=0.02,
                              failure_rate=0.99, cooldown_s=5.0,
                              probe_batches=1),
        swap=SwapConfig(probation_s=0.0))
    engine = _mk_engine(cfg, clock=lambda: now[0])
    with chaos.active(chaos.ChaosConfig(scorer_delay_s=0.2,
                                        scorer_delay_batches=4)):
        shed_seen = False
        for i in range(4):
            engine.submit(_synth_req(f"s{i}"))
            [resp] = engine.pump(flush=True)
            if FallbackReason.BREAKER_SHED_RANDOM_EFFECTS in _reasons(resp):
                shed_seen = True
        assert shed_seen
        assert engine.breaker.state() == "open"
        # open: admission refuses outright
        resp = engine.submit(_synth_req("refused"))
        assert resp is not None
        assert _reasons(resp) == {FallbackReason.BREAKER_REJECTED}
        # cooldown elapses on the injected clock -> half-open probe
        now[0] += 5.1
        assert engine.breaker.state() == "half_open"
        assert engine.submit(_synth_req("probe")) is None   # delay budget spent
        [resp] = engine.pump(flush=True)
        assert resp.score is not None
    assert engine.breaker.state() == "closed"
    snap = engine.breaker.snapshot()
    assert snap["trips"] >= 2
    assert engine.stats()["breaker"]["state"] == "closed"


def test_breaker_failure_trip_on_nonfinite_scores():
    """A model that yields NaN scores produces typed SCORER_FAILURE
    responses (never an exception) and trips the failure-rate breach."""
    engine = _mk_engine(
        ServingConfig(max_batch=1, max_wait_s=0.0,
                      breaker=BreakerConfig(window=8, min_samples=2,
                                            failure_rate=0.4),
                      swap=SwapConfig(probation_s=0.0)),
        model=_synth_model(nan_fixed=True))
    resps = []
    for i in range(2):
        engine.submit(_synth_req(f"n{i}"))
        resps.extend(engine.pump(flush=True))
    assert all(r.score is None for r in resps)
    assert all(_reasons(r) == {FallbackReason.SCORER_FAILURE} for r in resps)
    assert engine.breaker.state() == "shed"


# -- graceful drain -----------------------------------------------------------


def test_drain_refuses_with_typed_shutting_down():
    engine = _mk_engine()
    engine.begin_drain("test drain")
    resp = engine.submit(_synth_req("late"))
    assert resp is not None and resp.score is None
    assert _reasons(resp) == {FallbackReason.SHUTTING_DOWN}
    assert engine.stats()["draining"] is True


def test_shutdown_flushes_within_budget():
    engine = _mk_engine(ServingConfig(max_batch=2, max_wait_s=10.0))
    for i in range(3):
        engine.submit(_synth_req(f"q{i}"))
    out = engine.shutdown(drain_budget_s=30.0)
    assert {r.uid for r in out} == {"q0", "q1", "q2"}
    assert all(r.score is not None for r in out)
    drain = engine.stats()["drain"]
    assert drain["flushed"] == 3 and drain["refused"] == 0


def test_shutdown_budget_exhaustion_refuses_remainder():
    engine = _mk_engine(ServingConfig(max_batch=2, max_wait_s=10.0))
    for i in range(3):
        engine.submit(_synth_req(f"q{i}"))
    out = engine.shutdown(drain_budget_s=0.0)    # no flush time at all
    assert {r.uid for r in out} == {"q0", "q1", "q2"}
    assert all(_reasons(r) == {FallbackReason.SHUTTING_DOWN} for r in out)
    assert engine.stats()["drain"]["refused"] == 3


def test_shutdown_callback_flips_engine_to_draining():
    """resilience/shutdown.py request() drives begin_drain through the
    callback registry — the SIGTERM -> drain wiring, minus the signal."""
    engine = _mk_engine(warm=False)

    def cb(reason):
        engine.begin_drain(reason)

    shutdown.reset()
    shutdown.add_callback(cb)
    try:
        shutdown.request("test sigterm")
        assert engine.draining and engine.batcher.closed
    finally:
        shutdown.remove_callback(cb)
        shutdown.reset()


# -- live model swap ----------------------------------------------------------


def _fresh_engine_from_dir(model_dir, config=None):
    engine = ServingEngine.from_model_dir(
        model_dir, config=config or ServingConfig(max_batch=4, max_wait_s=0.0))
    engine.warmup()
    return engine


def test_swap_e2e_v1_to_v2_parity(model_dirs):
    """The acceptance path: serve v1, swap to v2 under captured traffic,
    post-swap scores match a from-scratch v2 engine to 1e-6, zero
    steady-state compiles across the swap."""
    from photon_tpu.utils import compile_cache

    v1, v2, users = model_dirs
    engine = _fresh_engine_from_dir(v1)
    reqs = _traffic(users)
    before = [r.score for r in engine.serve(reqs)]

    steady0 = compile_cache.compile_counts()["steady_state"]
    result = swap_from_dir(engine, v2, label="v2")
    assert result.accepted, result.reason
    assert result.gates["integrity"] == "pass"
    assert result.gates["shadow"] == "pass"
    assert result.shadow_requests == len(reqs)
    assert result.shadow_max_deviation > 0.0     # the models really differ
    assert engine.model_version == 2 and engine.model_label == "v2"
    assert compile_cache.compile_counts()["steady_state"] == steady0

    after = [r.score for r in engine.serve(reqs)]
    oracle = [r.score for r in _fresh_engine_from_dir(v2).serve(reqs)]
    np.testing.assert_allclose(after, oracle, atol=1e-6)
    # and the swap genuinely changed the scores
    assert max(abs(a - b) for a, b in zip(before, after)) > 1e-3
    assert engine.swap_stats()["published"] == 1


def test_swap_nan_poisoned_candidate_rejected_live_intact(model_dirs):
    """Chaos NaN-poisons the candidate: the finite gate refuses it and
    the live model keeps serving bitwise-identical scores."""
    v1, v2, users = model_dirs
    engine = _fresh_engine_from_dir(v1)
    reqs = _traffic(users)
    before = [r.score for r in engine.serve(reqs)]

    with chaos.active(chaos.ChaosConfig(swap_poison_nan=True)):
        result = swap_from_dir(engine, v2, label="poisoned")
    assert not result.accepted
    assert result.gates["finite"] == "fail"
    assert engine.model_version == 1

    after = [r.score for r in engine.serve(reqs)]
    assert before == after               # bitwise: same model, same programs
    hist = engine.swap_stats()
    assert hist["rejected"] == 1 and hist["published"] == 0
    assert engine.swap_history[-1]["gate"] == "finite"


def test_swap_corrupt_candidate_dir_rejected(model_dirs, tmp_path):
    """A torn candidate directory (chaos truncation) fails the crc32
    manifest gate before any load is attempted."""
    v1, v2, users = model_dirs
    torn = str(tmp_path / "torn")
    shutil.copytree(v2, torn)
    victim = chaos.corrupt_model_dir(torn, seed=1)
    assert os.path.exists(victim)
    verdict = verify_swap_manifest(torn)
    assert verdict["present"] and not verdict["ok"]

    engine = _fresh_engine_from_dir(v1)
    engine.serve(_traffic(users, n=4))
    result = swap_from_dir(engine, torn, label="torn")
    assert not result.accepted and result.gates["integrity"] == "fail"
    assert engine.model_version == 1


def test_swap_requires_manifest_when_configured(model_dirs, tmp_path):
    v1, v2, users = model_dirs
    bare = str(tmp_path / "bare")
    shutil.copytree(v2, bare)
    os.remove(os.path.join(bare, "swap-manifest.json"))
    engine = _fresh_engine_from_dir(
        v1, ServingConfig(max_batch=4, max_wait_s=0.0,
                          swap=SwapConfig(require_manifest=True)))
    result = swap_from_dir(engine, bare)
    assert not result.accepted and result.gates["integrity"] == "fail"
    assert "manifest required" in result.reason


def test_swap_shadow_deviation_gate(model_dirs):
    """A candidate whose scores move more than the configured bound is
    rejected by the shadow gate."""
    v1, v2, users = model_dirs
    engine = _fresh_engine_from_dir(
        v1, ServingConfig(max_batch=4, max_wait_s=0.0,
                          swap=SwapConfig(max_shadow_deviation=1e-9)))
    engine.serve(_traffic(users))
    result = swap_from_dir(engine, v2, label="too-different")
    assert not result.accepted and result.gates["shadow"] == "fail"
    assert result.shadow_max_deviation > 1e-9
    assert engine.model_version == 1


def test_post_swap_breaker_trip_rolls_back(model_dirs):
    """A breaker trip inside the probation window restores the prior
    model object — rollback is a pointer swap, bitwise by construction."""
    v1, v2, users = model_dirs
    engine = _fresh_engine_from_dir(
        v1, ServingConfig(
            max_batch=4, max_wait_s=0.0,
            breaker=BreakerConfig(window=8, min_samples=1,
                                  latency_p99_s=0.02),
            swap=SwapConfig(probation_s=3600.0)))
    engine.serve(_traffic(users))
    v1_model = engine.model
    result = swap_from_dir(engine, v2, label="v2")
    assert result.accepted and engine.model_version == 2

    with chaos.active(chaos.ChaosConfig(scorer_delay_s=0.2,
                                        scorer_delay_batches=1)):
        engine.submit(_traffic(users, n=1)[0])
        engine.pump(flush=True)
    assert engine.model_version == 1
    assert engine.model is v1_model      # the very same object/tables
    stats = engine.swap_stats()
    assert stats["rollbacks"] == 1
    assert engine.swap_history[-1]["outcome"] == "rolled_back"
    rollbacks = metrics_registry.counter("serving.swap_rollbacks").value
    assert rollbacks >= 1


# -- RunReport ----------------------------------------------------------------


def test_runreport_swap_section_roundtrip(model_dirs):
    import photon_tpu.serving as serving_pkg
    from photon_tpu.obs.report import build_run_report, validate_run_report

    v1, v2, users = model_dirs
    engine = _fresh_engine_from_dir(v1)
    engine.serve(_traffic(users))
    swap_from_dir(engine, v2, label="v2")
    serving_pkg.set_active_engine(engine)
    try:
        report = build_run_report("swap-test")
        assert validate_run_report(report) == []
        swap = report["serving"]["swap"]
        assert swap["version"] == 2 and swap["label"] == "v2"
        assert swap["history"][-1]["outcome"] == "published"
        # round-trip through JSON, still valid
        back = json.loads(json.dumps(report))
        assert validate_run_report(back) == []
        # a swap section missing its keys is flagged
        del back["serving"]["swap"]["version"]
        assert any("serving.swap" in e for e in validate_run_report(back))
    finally:
        serving_pkg.set_active_engine(None)


# -- CLI ----------------------------------------------------------------------


def _cli_env():
    return {**os.environ, "JAX_PLATFORMS": "cpu"}


def test_cli_sigterm_drains_and_exits_zero(model_dirs, tmp_path):
    """SIGTERM under load: pre-signal uids all answered, process drains
    within the budget and exits 0."""
    v1, _, users = model_dirs
    stats_path = str(tmp_path / "stats.json")
    p = subprocess.Popen(
        [sys.executable, "-m", "photon_tpu.cli.serve",
         "--model-input-directory", v1,
         "--max-batch", "4", "--max-wait-ms", "0",
         "--drain-budget-s", "5", "--stats-output", stats_path,
         "--log-level", "ERROR"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=_cli_env(), cwd=REPO)
    reqs = _traffic(users, n=6)
    for r in reqs:
        p.stdin.write(json.dumps({
            "uid": r.uid,
            "features": {k: [list(f) for f in v]
                         for k, v in r.features.items()},
            "ids": r.entity_ids, "offset": r.offset}) + "\n")
    p.stdin.flush()
    answered = [json.loads(p.stdout.readline()) for _ in reqs]
    p.send_signal(signal.SIGTERM)        # stdin stays open: drain must win
    try:
        out, err = p.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        p.kill()
        pytest.fail("serve did not exit within the drain budget")
    assert p.returncode == 0, err
    assert {a["uid"] for a in answered} == {r.uid for r in reqs}
    assert all(a["score"] is not None for a in answered)
    stats = json.load(open(stats_path))
    assert stats["draining"] is True and "drain" in stats


def test_cli_control_line_swap_under_traffic(model_dirs):
    """The stdin control plane: a swap control line mid-stream publishes
    v2; subsequent requests score with the new model."""
    v1, v2, users = model_dirs
    reqs = _traffic(users, n=4)

    def req_line(r, uid):
        return json.dumps({
            "uid": uid,
            "features": {k: [list(f) for f in v] for k, v in r.features.items()},
            "ids": r.entity_ids, "offset": r.offset})

    lines = [req_line(r, f"pre-{r.uid}") for r in reqs]
    lines.append(json.dumps({"control": "swap", "model_dir": v2,
                             "label": "v2"}))
    lines += [req_line(r, f"post-{r.uid}") for r in reqs]
    r = subprocess.run(
        [sys.executable, "-m", "photon_tpu.cli.serve",
         "--model-input-directory", v1,
         "--max-batch", "4", "--max-wait-ms", "0", "--log-level", "ERROR"],
        input="\n".join(lines) + "\n", text=True, timeout=300,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=_cli_env(), cwd=REPO)
    assert r.returncode == 0, r.stderr
    out = [json.loads(l) for l in r.stdout.splitlines() if l.strip()]
    controls = [o for o in out if "control" in o]
    assert len(controls) == 1 and controls[0]["ok"] is True
    assert controls[0]["version"] == 2

    scores = {o["uid"]: o["score"] for o in out if "uid" in o}
    oracle_v1 = {x.uid: x.score
                 for x in _fresh_engine_from_dir(v1).serve(reqs)}
    oracle_v2 = {x.uid: x.score
                 for x in _fresh_engine_from_dir(v2).serve(reqs)}
    for q in reqs:
        assert scores[f"pre-{q.uid}"] == pytest.approx(oracle_v1[q.uid],
                                                       abs=1e-6)
        assert scores[f"post-{q.uid}"] == pytest.approx(oracle_v2[q.uid],
                                                        abs=1e-6)
