"""Unit tests for pointwise losses: derivatives vs autodiff, known values."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.ops import losses as L

ALL_LOSSES = [L.LogisticLoss, L.SquaredLoss, L.PoissonLoss, L.SmoothedHingeLoss]


def _labels_for(loss, rng, n):
    if loss.name in ("logistic", "smoothed_hinge"):
        return rng.integers(0, 2, size=n).astype(np.float64)
    if loss.name == "poisson":
        return rng.poisson(3.0, size=n).astype(np.float64)
    return rng.normal(size=n)


@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_dz_matches_autodiff(loss, rng):
    z = jnp.asarray(rng.normal(size=64) * 2.0)
    y = jnp.asarray(_labels_for(loss, rng, 64))
    _, dz = loss.loss_and_dz(z, y)
    dz_auto = jax.vmap(jax.grad(lambda zi, yi: loss.loss_and_dz(zi, yi)[0]))(z, y)
    np.testing.assert_allclose(dz, dz_auto, rtol=1e-9, atol=1e-9)


@pytest.mark.parametrize("loss", [l for l in ALL_LOSSES if l.name != "smoothed_hinge"],
                         ids=lambda l: l.name)
def test_d2z_matches_autodiff(loss, rng):
    z = jnp.asarray(rng.normal(size=64) * 2.0)
    y = jnp.asarray(_labels_for(loss, rng, 64))
    d2 = loss.d2z(z, y)
    d2_auto = jax.vmap(jax.grad(jax.grad(lambda zi, yi: loss.loss_and_dz(zi, yi)[0])))(z, y)
    np.testing.assert_allclose(d2, d2_auto, rtol=1e-9, atol=1e-9)


def test_logistic_known_values():
    l, dz = L.LogisticLoss.loss_and_dz(jnp.asarray(0.0), jnp.asarray(1.0))
    np.testing.assert_allclose(l, np.log(2.0), rtol=1e-12)
    np.testing.assert_allclose(dz, -0.5, rtol=1e-12)
    # extreme margins stay finite
    l, _ = L.LogisticLoss.loss_and_dz(jnp.asarray(1000.0), jnp.asarray(0.0))
    assert np.isfinite(float(l)) and float(l) == pytest.approx(1000.0)
    l, _ = L.LogisticLoss.loss_and_dz(jnp.asarray(-1000.0), jnp.asarray(1.0))
    np.testing.assert_allclose(l, 1000.0, rtol=1e-9)


def test_smoothed_hinge_piecewise():
    # y=1 -> t=z. Three pieces (SmoothedHingeLossFunction.scala:26-60).
    y = jnp.asarray(1.0)
    assert float(L.SmoothedHingeLoss.value(jnp.asarray(2.0), y)) == 0.0
    np.testing.assert_allclose(L.SmoothedHingeLoss.value(jnp.asarray(0.5), y), 0.125)
    np.testing.assert_allclose(L.SmoothedHingeLoss.value(jnp.asarray(-1.0), y), 1.5)
    # y=0 flips the sign of the margin
    np.testing.assert_allclose(L.SmoothedHingeLoss.value(jnp.asarray(1.0), jnp.asarray(0.0)), 1.5)


def test_means():
    np.testing.assert_allclose(L.LogisticLoss.mean(jnp.asarray(0.0)), 0.5)
    np.testing.assert_allclose(L.PoissonLoss.mean(jnp.asarray(1.0)), np.e, rtol=1e-6)
    np.testing.assert_allclose(L.SquaredLoss.mean(jnp.asarray(3.7)), 3.7)
