"""Grouped-evaluator + evaluation-suite tests.

Oracle: explicit per-group Python loops over sklearn/our single-metric
implementations (the reference computes each group locally after a
groupByKey — AreaUnderROCCurveMultiEvaluator etc.).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from photon_tpu.evaluation.evaluators import EvaluatorType, auc, rmse
from photon_tpu.evaluation.multi import (
    EvaluationSuite,
    EvaluatorSpec,
    build_group_index,
    evaluate_multi,
    parse_evaluator,
)


def test_parse_evaluator_names():
    s = parse_evaluator("AUC")
    assert s.base == EvaluatorType.AUC and not s.is_multi
    s = parse_evaluator("AUC:userId")
    assert s.id_tag == "userId" and s.is_multi and s.name == "AUC:userId"
    s = parse_evaluator("precision@5:queryId")
    assert s.k == 5 and s.id_tag == "queryId"
    assert s.name == "PRECISION@5:queryId"
    assert s.bigger_is_better
    s = parse_evaluator("rmse")
    assert s.base == EvaluatorType.RMSE and not s.bigger_is_better


def test_build_group_index():
    gi, names = build_group_index(["b", "a", "b", "c"])
    assert names == ["b", "a", "c"]
    np.testing.assert_array_equal(gi, [0, 1, 0, 2])


def _grouped_oracle(metric, scores, labels, weights, groups):
    vals = []
    for g in np.unique(groups):
        m = groups == g
        v = float(metric(jnp.asarray(scores[m]), jnp.asarray(labels[m]),
                         jnp.asarray(weights[m])))
        if np.isfinite(v):
            # AUC invalid groups (single class) return garbage from the
            # tiny-denominator guard; oracle drops them explicitly
            if metric is auc:
                pos_w = weights[m][labels[m] > 0.5].sum()
                neg_w = weights[m][labels[m] <= 0.5].sum()
                if pos_w == 0 or neg_w == 0:
                    continue
            vals.append(v)
    return float(np.mean(vals))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_grouped_auc_matches_per_group_oracle(seed):
    rng = np.random.default_rng(seed)
    n, G = 500, 12
    scores = np.round(rng.normal(size=n), 1)  # coarse -> plenty of ties
    labels = (rng.random(n) < 0.4).astype(float)
    weights = rng.uniform(0.5, 2.0, size=n)
    groups = rng.integers(0, G, size=n)

    got = float(evaluate_multi(
        EvaluatorSpec(EvaluatorType.AUC, id_tag="g"),
        jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights),
        jnp.asarray(groups), G))
    want = _grouped_oracle(auc, scores, labels, weights, groups)
    assert got == pytest.approx(want, abs=1e-6)


def test_grouped_auc_vs_sklearn_unweighted():
    from sklearn.metrics import roc_auc_score

    rng = np.random.default_rng(3)
    n, G = 400, 8
    scores = rng.normal(size=n)
    labels = (rng.random(n) < 0.5).astype(float)
    groups = rng.integers(0, G, size=n)
    vals = []
    for g in range(G):
        m = groups == g
        if len(set(labels[m])) == 2:
            vals.append(roc_auc_score(labels[m], scores[m]))
    want = float(np.mean(vals))
    got = float(evaluate_multi(
        EvaluatorSpec(EvaluatorType.AUC, id_tag="g"),
        jnp.asarray(scores), jnp.asarray(labels), None,
        jnp.asarray(groups), G))
    assert got == pytest.approx(want, abs=1e-6)


def test_grouped_precision_at_k():
    # group 0: top-2 scores are labels (1, 0) -> p@2 = 0.5
    # group 1: top-2 are (1, 1) -> 1.0 ; mean = 0.75
    scores = np.asarray([5.0, 4.0, 1.0, 9.0, 8.0, 7.0])
    labels = np.asarray([1.0, 0.0, 1.0, 1.0, 1.0, 0.0])
    groups = np.asarray([0, 0, 0, 1, 1, 1])
    got = float(evaluate_multi(
        parse_evaluator("PRECISION@2:g"),
        jnp.asarray(scores), jnp.asarray(labels), None,
        jnp.asarray(groups), 2))
    assert got == pytest.approx(0.75)


def test_grouped_precision_at_k_ignores_zero_weight_pads():
    scores = np.asarray([5.0, 4.0, 99.0, 98.0])
    labels = np.asarray([1.0, 1.0, 1.0, 1.0])
    weights = np.asarray([1.0, 1.0, 0.0, 0.0])  # pads with huge scores
    groups = np.zeros(4, np.int32)
    got = float(evaluate_multi(
        parse_evaluator("PRECISION@2:g"),
        jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights),
        jnp.asarray(groups), 1))
    assert got == pytest.approx(1.0)


def test_grouped_rmse_matches_oracle():
    rng = np.random.default_rng(4)
    n, G = 300, 5
    scores = rng.normal(size=n)
    labels = rng.normal(size=n)
    weights = rng.uniform(0.1, 1.0, size=n)
    groups = rng.integers(0, G, size=n)
    got = float(evaluate_multi(
        EvaluatorSpec(EvaluatorType.RMSE, id_tag="g"),
        jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights),
        jnp.asarray(groups), G))
    want = _grouped_oracle(rmse, scores, labels, weights, groups)
    assert got == pytest.approx(want, abs=1e-6)


def test_evaluation_suite_end_to_end():
    rng = np.random.default_rng(5)
    n = 200
    labels = (rng.random(n) < 0.5).astype(float)
    scores = labels + rng.normal(size=n)
    users = [f"u{int(i)}" for i in rng.integers(0, 10, size=n)]
    suite = EvaluationSuite(
        ["AUC", "AUC:userId", "PRECISION@3:userId", "RMSE"],
        labels, id_tags={"userId": users}, dtype=jnp.float64)
    res = suite.evaluate(jnp.asarray(scores))
    assert res.primary == "AUC"
    assert set(res.evaluations) == {"AUC", "AUC:userId",
                                    "PRECISION@3:userId", "RMSE"}
    assert 0.5 < res.evaluations["AUC"] <= 1.0
    assert 0.0 <= res.evaluations["PRECISION@3:userId"] <= 1.0
    # offsets shift scores before evaluation
    suite2 = EvaluationSuite(["RMSE"], labels, offsets=np.ones(n),
                             dtype=jnp.float64)
    r0 = suite2.evaluate(jnp.asarray(scores - 1.0))
    r1 = EvaluationSuite(["RMSE"], labels, dtype=jnp.float64).evaluate(
        jnp.asarray(scores))
    assert r0.evaluations["RMSE"] == pytest.approx(r1.evaluations["RMSE"], abs=1e-9)


def test_evaluation_suite_missing_id_tag_raises():
    with pytest.raises(KeyError):
        EvaluationSuite(["AUC:userId"], np.zeros(3))
