"""Entity-sharded serving fleet tests (photon_tpu/serving/fleet.py,
photon_tpu/io/fleet_store.py, photon_tpu/parallel/partition.py).

Covers the fleet contract end to end on CPU:

  * the shared partitioner: scalar / vectorized / crc-reference
    agreement, adversarial id sets (negative ids, dense ranges, one
    entity, one shard), pinned hash values (the hash may NEVER change —
    it is burned into every split cold-store file layout on disk), and
    train-placement == serve-routing via ``entity_axis_assignment``,
  * the split store: every row lands in its crc-owner's shard file,
    union of shards == source, manifest crc round-trip, torn-manifest
    refusal (chaos injector),
  * routing parity: fleet scores bitwise-equal the single-host engine
    for hot rows, cold-then-promoted rows, and no-entity requests,
  * degradation: a killed shard (chaos or admin API) yields typed
    SHARD_UNAVAILABLE fixed-only responses — never an exception, other
    shards' scores bitwise-unchanged, full parity after revival,
  * hedging: a chaos-slowed shard is overtaken by the hedged second
    attempt,
  * obs: per-shard snapshots merge through ``merge_snapshots``,
  * the shard-mode CLI entrypoint and the tier-1 ``--mode fleet
    --quick`` bench smoke.
"""

import json
import os
import subprocess
import sys
import tempfile
import zlib

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from photon_tpu.io.cold_store import ColdStore, cold_store_path
from photon_tpu.io.fleet_store import (
    FleetManifestError,
    build_fleet_dir,
    read_fleet_manifest,
    shard_dir,
    shard_store_path,
)
from photon_tpu.parallel.partition import (
    entity_shard,
    entity_shards,
    partition_ids,
)
from photon_tpu.resilience import chaos
from photon_tpu.serving import (
    CoeffStoreConfig,
    FallbackReason,
    FleetConfig,
    ScoreRequest,
    ServingConfig,
    ServingEngine,
    ShardedServingFleet,
    SLOConfig,
)


# -- fixtures: a saved GAME model dir + a split fleet dir --------------------


def _build_model_dir(seed: int, out_dir: str):
    """Synthetic GAME model saved to disk with a per-coordinate cold
    store and feature-index sidecars. Returns the feature names."""
    import jax.numpy as jnp

    from photon_tpu.game.dataset import EntityVocabulary
    from photon_tpu.game.model import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.io.index_map import IndexMap, feature_key
    from photon_tpu.io.model_io import save_game_model
    from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_tpu.types import TaskType

    rng = np.random.default_rng(seed)
    names = [f"f{j}" for j in range(17)]
    imap = IndexMap({feature_key(n, ""): i for i, n in enumerate(names)})
    D = imap.feature_dimension
    E, K = 5, 3
    coef = rng.normal(size=(E, K)).astype(np.float32)
    proj = np.zeros((E, K), np.int32)
    for e in range(E):
        proj[e] = np.sort(rng.choice(D, size=K, replace=False))
    fixed = FixedEffectModel(
        GeneralizedLinearModel(
            Coefficients(jnp.asarray(rng.normal(size=D).astype(np.float32))),
            TaskType.LINEAR_REGRESSION), "shardA")
    rem = RandomEffectModel(
        coefficients=jnp.asarray(coef), random_effect_type="userId",
        feature_shard_id="shardA", task=TaskType.LINEAR_REGRESSION)
    vocab = EntityVocabulary()
    vocab.build("userId", [f"u{e}" for e in range(E)])
    save_game_model(out_dir, GameModel({"global": fixed, "per-user": rem}),
                    {"shardA": imap}, vocab=vocab,
                    projections={"per-user": proj}, sparsity_threshold=0.0)
    return names


@pytest.fixture(scope="module")
def fleet_dirs():
    """(model_dir, fleet_dir(2 shards), names) shared by the module —
    building + splitting the model once keeps the suite fast."""
    with tempfile.TemporaryDirectory(prefix="fleet_t_") as td:
        mdir = os.path.join(td, "model")
        fdir = os.path.join(td, "fleet")
        names = _build_model_dir(7, mdir)
        build_fleet_dir(mdir, fdir, 2)
        yield mdir, fdir, names


def _mkreq(rng, uid, names, user):
    feats = [(names[j], "", float(rng.normal()))
             for j in rng.choice(len(names), size=5, replace=False)]
    return ScoreRequest(uid, {"shardA": feats},
                        {"userId": user} if user else {})


def _serving_config(hot_capacity=8):
    return ServingConfig(
        max_batch=4, max_wait_s=0.0,
        slo=SLOConfig(shed_queue_depth=60, reject_queue_depth=100),
        coeff_store=CoeffStoreConfig(hot_capacity=hot_capacity,
                                     transfer_batch=2))


def _mk_fleet(fdir, **cfg_kw):
    cfg_kw.setdefault("serving", _serving_config())
    fleet = ShardedServingFleet.from_fleet_dir(fdir, FleetConfig(**cfg_kw))
    fleet.warmup()
    return fleet


def _mk_single(mdir, two_tier=True):
    cfg = _serving_config() if two_tier else ServingConfig(
        max_batch=4, max_wait_s=0.0,
        slo=SLOConfig(shed_queue_depth=60, reject_queue_depth=100))
    engine = ServingEngine.from_model_dir(mdir, config=cfg)
    engine.warmup()
    return engine


def _bits(score):
    return np.float32(score).tobytes()


def _promote(fleet_or_engine, rng, names, users):
    """One pass of traffic + prefetch drain so ``users`` are hot."""
    reqs = [_mkreq(rng, f"pp-{i}", names, u) for i, u in enumerate(users)]
    if isinstance(fleet_or_engine, ShardedServingFleet):
        fleet_or_engine.serve(reqs)
        for c in fleet_or_engine.clients:
            c.engine.model.drain_prefetch()
    else:
        fleet_or_engine.serve(reqs)
        fleet_or_engine.model.drain_prefetch()


# -- the shared partitioner --------------------------------------------------


class TestPartitioner:
    def test_scalar_vector_and_reference_agree(self):
        rng = np.random.default_rng(3)
        ids = ([f"m{i}" for i in range(200)]
               + [f"e{int(v):09d}" for v in rng.integers(0, 10**9, 100)])
        for n in (1, 2, 3, 7, 16):
            ref = np.array([zlib.crc32(s.encode("utf-8")) % n
                            for s in ids])
            vec = entity_shards(ids, n)
            assert vec.dtype == np.int64 or np.issubdtype(
                vec.dtype, np.integer)
            np.testing.assert_array_equal(vec, ref)
            assert [entity_shard(s, n) for s in ids] == list(ref)

    def test_adversarial_id_sets(self):
        # negative numeric ids, a dense id range, one entity, one shard
        negative = [str(v) for v in range(-50, 0)]
        dense = [str(v) for v in range(1000)]
        for ids in (negative, dense, ["solo"]):
            for n in (1, 2, 16):
                ref = [zlib.crc32(s.encode("utf-8")) % n for s in ids]
                assert list(entity_shards(ids, n)) == ref
        assert list(entity_shards(dense, 1)) == [0] * len(dense)
        assert entity_shard("anything", 1) == 0
        with pytest.raises(ValueError):
            entity_shard("x", 0)

    def test_pinned_hash_values(self):
        # the partitioner is burned into on-disk shard layouts: these
        # exact values may NEVER change across refactors
        pins = {
            "u0": {2: 0, 4: 0, 16: 0},
            "u1": {2: 0, 4: 2, 16: 6},
            "u2": {2: 0, 4: 0, 16: 12},
            "u3": {2: 0, 4: 2, 16: 10},
            "u4": {2: 1, 4: 1, 16: 9},
            "e000000042": {2: 0, 4: 2, 16: 2},
            "-17": {2: 0, 4: 0, 16: 12},
        }
        for eid, by_n in pins.items():
            for n, want in by_n.items():
                assert entity_shard(eid, n) == want, (eid, n)

    def test_bytes_and_str_ids_hash_identically(self):
        ids = ["u0", "e000000042", "-17", "solo"]
        as_bytes = np.array([s.encode() for s in ids])
        np.testing.assert_array_equal(entity_shards(ids, 16),
                                      entity_shards(as_bytes, 16))

    def test_partition_ids_covers_all_rows(self):
        ids = [f"u{i}" for i in range(40)]
        parts = partition_ids(ids, 4)
        assert len(parts) == 4
        got = sorted(i for rows in parts for i in rows)
        assert got == list(range(40))
        for s, rows in enumerate(parts):
            assert all(entity_shard(ids[i], 4) == s for i in rows)

    def test_train_placement_agrees_with_serve_routing(self):
        # entity_axis_assignment (train-time placement) must be the SAME
        # function application as the fleet router's shard ownership
        import jax
        from jax.sharding import Mesh

        from photon_tpu.parallel.mesh import entity_axis_assignment

        ids = [f"u{i}" for i in range(20)] + ["-17", "e000000042"]
        mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
        np.testing.assert_array_equal(
            entity_axis_assignment(ids, mesh),
            entity_shards(ids, 1))


# -- the split store + manifest ----------------------------------------------


class TestFleetStore:
    def test_split_layout_matches_partitioner(self, fleet_dirs):
        mdir, fdir, _ = fleet_dirs
        src = ColdStore(cold_store_path(mdir, "per-user"))
        src_ids = [i.decode() for i in src.entity_ids_array()]
        seen = {}
        for s in range(2):
            store = ColdStore(shard_store_path(fdir, s, "per-user"))
            for eid in store.entity_ids_array():
                eid = eid.decode()
                assert entity_shard(eid, 2) == s, (eid, s)
                seen[eid] = s
        assert sorted(seen) == sorted(src_ids)

    def test_manifest_round_trip(self, fleet_dirs):
        _, fdir, _ = fleet_dirs
        man = read_fleet_manifest(fdir)
        assert man["num_shards"] == 2
        assert man["partitioner"] == "crc32-utf8-mod"
        assert "per-user" in man["coordinates"]
        for s in range(2):
            assert os.path.isdir(shard_dir(fdir, s))
            assert os.path.isfile(shard_store_path(fdir, s, "per-user"))

    def test_torn_manifest_refused(self):
        with tempfile.TemporaryDirectory(prefix="fleet_torn_") as td:
            mdir, fdir = os.path.join(td, "m"), os.path.join(td, "f")
            _build_model_dir(7, mdir)
            build_fleet_dir(mdir, fdir, 2)
            removed = chaos.manifest_torn_write(fdir)
            assert removed > 0
            with pytest.raises(FleetManifestError):
                read_fleet_manifest(fdir)
            # a router must never boot on guessed shard ownership
            with pytest.raises(FleetManifestError):
                ShardedServingFleet.from_fleet_dir(fdir)


# -- routing parity vs the single-host engine --------------------------------


class TestFleetParity:
    def test_hot_rows_bitwise_equal_single_host(self, fleet_dirs):
        mdir, fdir, names = fleet_dirs
        fleet = _mk_fleet(fdir)
        single = _mk_single(mdir)
        users = [f"u{e}" for e in range(5)]
        _promote(fleet, np.random.default_rng(5), names, users * 2)
        _promote(single, np.random.default_rng(5), names, users * 2)

        rng_a, rng_b = (np.random.default_rng(11) for _ in range(2))
        for lo in range(0, 20, 4):
            batch_a = [_mkreq(rng_a, f"q{lo + i}", names,
                              users[(lo + i) % 5]) for i in range(4)]
            batch_b = [_mkreq(rng_b, f"q{lo + i}", names,
                              users[(lo + i) % 5]) for i in range(4)]
            fa = fleet.serve(batch_a)
            sb = single.serve(batch_b)
            for f, s in zip(fa, sb):
                assert not f.degraded and not s.degraded, (f, s)
                assert _bits(f.score) == _bits(s.score), f.uid
        fleet.shutdown()
        single.shutdown()

    def test_cold_then_promoted_parity(self, fleet_dirs):
        mdir, fdir, names = fleet_dirs
        fleet = _mk_fleet(fdir)
        single = _mk_single(mdir)
        rng_a, rng_b = (np.random.default_rng(13) for _ in range(2))
        # first touch: both placements cold-miss the same way (typed
        # fixed-only fallback), bitwise-equal degraded scores
        ra = fleet.serve([_mkreq(rng_a, "c0", names, "u3")])[0]
        rb = single.serve([_mkreq(rng_b, "c0", names, "u3")])[0]
        assert {f.reason for f in ra.fallbacks} \
            == {f.reason for f in rb.fallbacks}
        assert _bits(ra.score) == _bits(rb.score)
        # after promotion: full-model scores, bitwise-equal
        for c in fleet.clients:
            c.engine.model.drain_prefetch()
        single.model.drain_prefetch()
        ra = fleet.serve([_mkreq(rng_a, "c1", names, "u3")])[0]
        rb = single.serve([_mkreq(rng_b, "c1", names, "u3")])[0]
        assert not ra.degraded and not rb.degraded
        assert _bits(ra.score) == _bits(rb.score)
        fleet.shutdown()
        single.shutdown()

    def test_requests_without_entities_score_at_the_front(self, fleet_dirs):
        mdir, fdir, names = fleet_dirs
        fleet = _mk_fleet(fdir)
        single = _mk_single(mdir)
        rng_a, rng_b = (np.random.default_rng(17) for _ in range(2))
        ra = fleet.serve([_mkreq(rng_a, "n0", names, None)])[0]
        rb = single.serve([_mkreq(rng_b, "n0", names, None)])[0]
        assert _bits(ra.score) == _bits(rb.score)
        assert sum(st.requests for st in fleet._stats.values()) == 0
        fleet.shutdown()
        single.shutdown()


# -- degradation: killed shards ----------------------------------------------


class TestFleetDegradation:
    def _routed_users(self):
        # u4 is the only shard-1 user under 2 shards (pinned above)
        return ["u0", "u1", "u2", "u3"], ["u4"]

    def test_chaos_killed_shard_degrades_typed(self, fleet_dirs):
        mdir, fdir, names = fleet_dirs
        fleet = _mk_fleet(fdir)
        s0_users, s1_users = self._routed_users()
        users = [u for pair in zip(s0_users, s1_users * 4)
                 for u in pair]
        _promote(fleet, np.random.default_rng(5), names, users)

        def scores(tag):
            rng = np.random.default_rng(23)
            out = []
            for i, u in enumerate(users):
                out.append(fleet.serve(
                    [_mkreq(rng, f"{tag}{i}", names, u)])[0])
            return out

        healthy = scores("h")
        assert all(not r.degraded for r in healthy)
        with chaos.active(chaos.ChaosConfig(shard_kill_id=1)):
            killed = scores("k")
        for h, k, u in zip(healthy, killed, users):
            assert k.score is not None
            if u in s1_users:     # owner down -> typed fixed-only
                assert k.degraded
                assert any(f.reason == FallbackReason.SHARD_UNAVAILABLE
                           for f in k.fallbacks), k
            else:                 # other shards bitwise-unaffected
                assert not k.degraded
                assert _bits(k.score) == _bits(h.score)
        st = fleet.stats()
        assert st["merged"]["counters"]["fleet.shard.unavailable"] > 0
        # chaos uninstalled: full parity returns, no residual state
        recovered = scores("r")
        for h, r in zip(healthy, recovered):
            assert not r.degraded and _bits(r.score) == _bits(h.score)
        fleet.shutdown()

    def test_admin_kill_and_revive(self, fleet_dirs):
        mdir, fdir, names = fleet_dirs
        fleet = _mk_fleet(fdir)
        _promote(fleet, np.random.default_rng(5), names,
                 ["u0", "u4", "u0", "u4"])
        rng = np.random.default_rng(29)
        fleet.kill_shard(1)
        r = fleet.serve([_mkreq(rng, "a0", names, "u4")])[0]
        assert r.degraded and any(
            f.reason == FallbackReason.SHARD_UNAVAILABLE
            for f in r.fallbacks)
        assert fleet.stats()["per_shard"][1]["alive"] is False
        fleet.revive_shard(1)
        r = fleet.serve([_mkreq(rng, "a1", names, "u4")])[0]
        assert not r.degraded
        fleet.shutdown()


# -- hedging -----------------------------------------------------------------


class TestFleetHedging:
    def test_slow_shard_is_hedged(self, fleet_dirs):
        mdir, fdir, names = fleet_dirs
        fleet = _mk_fleet(fdir, hedge_timeout_s=0.02)
        _promote(fleet, np.random.default_rng(5), names,
                 ["u4", "u4", "u4", "u4"])
        rng = np.random.default_rng(31)
        with chaos.active(chaos.ChaosConfig(
                shard_slow_id=1, shard_slow_s=0.4,
                shard_slow_requests=1)):
            r = fleet.serve([_mkreq(rng, "s0", names, "u4")])[0]
        assert r.score is not None and not r.degraded
        assert fleet._stats[1].hedges >= 1
        fleet.shutdown()


# -- obs ---------------------------------------------------------------------


class TestFleetObs:
    def test_per_shard_snapshots_merge(self, fleet_dirs):
        mdir, fdir, names = fleet_dirs
        fleet = _mk_fleet(fdir)
        rng = np.random.default_rng(37)
        for i in range(8):
            fleet.serve([_mkreq(rng, f"o{i}", names, f"u{i % 5}")])
        st = fleet.stats()
        merged = st["merged"]["counters"]["fleet.shard.requests"]
        per_shard = sum(v["requests"] for v in st["per_shard"].values())
        assert merged == per_shard > 0
        hist = st["merged"]["histograms"]["fleet.shard.latency_seconds"]
        assert hist["count"] == merged
        for v in st["per_shard"].values():
            assert v["breaker_state"] == "closed"
            assert v["alive"] is True
        fleet.shutdown()


# -- CLI + bench smoke -------------------------------------------------------


class TestFleetCli:
    def test_shard_mode_serves_and_reports_stats(self, fleet_dirs):
        mdir, fdir, names = fleet_dirs
        rng = np.random.default_rng(41)
        lines = []
        for i in range(6):
            feats = [[names[j], "", float(rng.normal())]
                     for j in rng.choice(len(names), size=5,
                                         replace=False)]
            lines.append(json.dumps(
                {"uid": f"r{i}", "features": {"shardA": feats},
                 "ids": {"userId": f"u{i % 5}"}}))
        lines.append(json.dumps({"control": "stats"}))
        proc = subprocess.run(
            [sys.executable, "-m", "photon_tpu.cli.serve",
             "--fleet-manifest", fdir, "--shard-id", "0",
             "--max-wait-ms", "0"],
            input="\n".join(lines) + "\n", capture_output=True,
            text=True, cwd=REPO, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        outs = [json.loads(l) for l in proc.stdout.splitlines()
                if l.strip()]
        scored = [o for o in outs if "uid" in o]
        ctrl = [o for o in outs if o.get("control") == "stats"]
        assert len(scored) == 6
        assert ctrl and ctrl[0]["ok"]
        # shard 0 owns u0..u3; u4 is an unknown entity HERE (typed
        # fallback, not an error) — routing is the fleet router's job
        assert all(o["score"] is not None for o in scored)

    def test_shard_mode_requires_shard_id(self, fleet_dirs):
        _, fdir, _ = fleet_dirs
        proc = subprocess.run(
            [sys.executable, "-m", "photon_tpu.cli.serve",
             "--fleet-manifest", fdir],
            input="", capture_output=True, text=True, cwd=REPO,
            timeout=120, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode != 0


def test_fleet_quick_bench_smoke():
    """Tier-1 smoke: the fleet bench's quick shape end to end — split,
    scaling curve, router kill segment — no artifact write."""
    bench = os.path.join(REPO, "bench.py")
    proc = subprocess.run(
        [sys.executable, bench, "--mode", "fleet", "--quick"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.splitlines()[-1])
    assert rec["metric"] == "fleet_aggregate_qps_speedup"
    assert rec["quick"] is True
    assert rec["scaling_curve"]["2"]["aggregate_qps"] > 0
    assert rec["scaling_curve"]["2"][
        "zero_steady_state_compiles_all_shards"] is True
    assert rec["kill_one_shard"]["typed_shard_unavailable"] > 0
    assert rec["kill_one_shard"]["survivors_within_10pct"] is True
