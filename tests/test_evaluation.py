"""Evaluator correctness vs sklearn oracles, incl. weights, ties, padding."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import average_precision_score, mean_squared_error, roc_auc_score

from photon_tpu.evaluation import evaluators as E


def test_auc_matches_sklearn(rng):
    scores = rng.normal(size=500)
    labels = (rng.random(500) < 0.4).astype(np.float64)
    got = float(E.auc(jnp.asarray(scores), jnp.asarray(labels)))
    np.testing.assert_allclose(got, roc_auc_score(labels, scores), rtol=1e-10)


def test_auc_with_ties_matches_sklearn(rng):
    scores = np.round(rng.normal(size=400), 1)  # heavy ties
    labels = (rng.random(400) < 0.5).astype(np.float64)
    got = float(E.auc(jnp.asarray(scores), jnp.asarray(labels)))
    np.testing.assert_allclose(got, roc_auc_score(labels, scores), rtol=1e-10)


def test_auc_weighted_matches_sklearn(rng):
    scores = np.round(rng.normal(size=300), 1)
    labels = (rng.random(300) < 0.5).astype(np.float64)
    w = rng.random(300) + 0.1
    got = float(E.auc(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(w)))
    np.testing.assert_allclose(got, roc_auc_score(labels, scores, sample_weight=w),
                               rtol=1e-10)


def test_auc_padding_invariant(rng):
    """Weight-0 pad samples must not change the metric."""
    scores = rng.normal(size=100)
    labels = (rng.random(100) < 0.5).astype(np.float64)
    w = np.ones(100)
    base = float(E.auc(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(w)))
    ps = np.concatenate([scores, rng.normal(size=40)])
    pl = np.concatenate([labels, (rng.random(40) < 0.5).astype(np.float64)])
    pw = np.concatenate([w, np.zeros(40)])
    padded = float(E.auc(jnp.asarray(ps), jnp.asarray(pl), jnp.asarray(pw)))
    np.testing.assert_allclose(padded, base, rtol=1e-10)


def test_aupr_matches_sklearn(rng):
    scores = rng.normal(size=500)  # distinct scores
    labels = (rng.random(500) < 0.3).astype(np.float64)
    got = float(E.aupr(jnp.asarray(scores), jnp.asarray(labels)))
    np.testing.assert_allclose(got, average_precision_score(labels, scores), rtol=1e-9)


def test_rmse_weighted(rng):
    scores = rng.normal(size=200)
    labels = rng.normal(size=200)
    w = rng.random(200) + 0.1
    got = float(E.rmse(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(w)))
    want = np.sqrt(mean_squared_error(labels, scores, sample_weight=w))
    np.testing.assert_allclose(got, want, rtol=1e-10)


def test_precision_at_k(rng):
    scores = np.asarray([5.0, 4.0, 3.0, 2.0, 1.0])
    labels = np.asarray([1.0, 0.0, 1.0, 1.0, 0.0])
    got = float(E.precision_at_k(3, jnp.asarray(scores), jnp.asarray(labels)))
    np.testing.assert_allclose(got, 2.0 / 3.0)


def test_better_than_direction():
    assert E.EvaluatorType.AUC.better_than(0.9, 0.8)
    assert E.EvaluatorType.RMSE.better_than(0.1, 0.2)
    assert not E.EvaluatorType.LOGISTIC_LOSS.better_than(0.5, 0.4)


def test_mean_loss_evaluators(rng):
    scores = rng.normal(size=100)
    labels = (rng.random(100) < 0.5).astype(np.float64)
    ll = float(E.logistic_loss_eval(jnp.asarray(scores), jnp.asarray(labels)))
    want = np.mean(np.log1p(np.exp(scores)) - labels * scores)
    np.testing.assert_allclose(ll, want, rtol=1e-9)


def test_metric_metadata_registry():
    """Reference: photon-diagnostics metric/MetricMetadata.scala — every
    evaluator carries (name, description, ordering, optional range)."""
    from photon_tpu.evaluation.evaluators import (
        METRIC_METADATA,
        EvaluatorType,
        MetricMetadata,
    )

    assert set(METRIC_METADATA) == set(EvaluatorType)
    md = EvaluatorType.AUC.metadata
    assert isinstance(md, MetricMetadata)
    assert md.value_range == (0.0, 1.0) and md.bigger_is_better
    # worst-to-best: ascending for AUC, descending for RMSE
    assert md.sort_worst_to_best([0.9, 0.1, 0.5]) == [0.1, 0.5, 0.9]
    assert EvaluatorType.RMSE.metadata.sort_worst_to_best(
        [0.9, 0.1, 0.5]) == [0.9, 0.5, 0.1]
