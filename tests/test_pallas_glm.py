"""Pallas fused dense GLM kernel vs the XLA aggregator path.

Interpret mode makes these exact-semantics checks run on every backend
(the TPU lowering shares the same kernel body); parity pins the kernel
to ValueAndGradientAggregator semantics the same way the aggregator
tests pin the XLA path to jax.grad.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import aggregators
from photon_tpu.ops.losses import LogisticLoss, PoissonLoss, SquaredLoss
from photon_tpu.ops.normalization import no_normalization
from photon_tpu.ops.pallas_glm import fused_dense_value_grad

_IDN = no_normalization()


@pytest.fixture
def problem():
    rng = np.random.default_rng(7)
    n, d = 997, 37          # deliberately not tile-aligned
    X = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    y = jnp.asarray((rng.random(n) > 0.4), jnp.float32)
    off = jnp.asarray(rng.normal(size=n) * 0.2, jnp.float32)
    w = jnp.asarray(rng.random(n) + 0.1, jnp.float32)
    coef = jnp.asarray(rng.normal(size=d) * 0.4, jnp.float32)
    return X, y, off, w, coef


@pytest.mark.parametrize("loss", [LogisticLoss, SquaredLoss, PoissonLoss],
                         ids=lambda l: l.name)
def test_fused_matches_aggregator(problem, loss):
    X, y, off, w, coef = problem
    v0, g0 = aggregators.value_and_gradient(
        loss, X, y, off, w, coef, no_normalization())
    v1, g1 = fused_dense_value_grad(loss, X, y, off, w, coef, tile_n=256)
    np.testing.assert_allclose(float(v1), float(v0), rtol=5e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=5e-5, atol=5e-5)


def test_fused_none_offsets_weights(problem):
    X, y, _, _, coef = problem
    v0, g0 = aggregators.value_and_gradient(
        LogisticLoss, X, y, None, None, coef, no_normalization())
    v1, g1 = fused_dense_value_grad(LogisticLoss, X, y, None, None, coef)
    np.testing.assert_allclose(float(v1), float(v0), rtol=5e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=5e-5, atol=5e-5)


def test_env_flag_routes_objective(problem, monkeypatch):
    """PHOTON_TPU_PALLAS_GLM=1 routes the dense f32 objective through the
    fused kernel with unchanged results at the solver boundary."""
    from photon_tpu.function.objective import GLMObjective, Hyper

    X, y, off, w, coef = problem
    batch = DataBatch(X, y, off, w)
    obj = GLMObjective(LogisticLoss)
    hyper = Hyper(l2_weight=jnp.float32(0.3))
    v0, g0 = obj.value_and_gradient(coef, batch, hyper)
    monkeypatch.setenv("PHOTON_TPU_PALLAS_GLM", "1")
    v1, g1 = obj.value_and_gradient(coef, batch, hyper)
    np.testing.assert_allclose(float(v1), float(v0), rtol=5e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=5e-5, atol=5e-5)
    # sparse features fall back to the XLA path untouched (flag still set)
    from photon_tpu.ops import features as F
    idx = jnp.tile(jnp.arange(8, dtype=jnp.int32), (X.shape[0], 1))
    sb = DataBatch(F.SparseFeatures(idx, X[:, :8]), y, off, w)
    vs, gs = obj.value_and_gradient(coef[:8], sb, hyper)
    vr, gr = aggregators.value_and_gradient(
        LogisticLoss, sb.features, y, off, w, coef[:8], no_normalization())
    np.testing.assert_allclose(
        float(vs), float(vr) + 0.15 * float(coef[:8] @ coef[:8]), rtol=1e-6)
    assert np.isfinite(float(vs)) and bool(jnp.all(jnp.isfinite(gs)))


def test_fused_empty_batch():
    """n=0 must return zeros, not uninitialized buffers (grid would be
    empty) — the XLA path's empty-sum contract."""
    X = jnp.zeros((0, 5), jnp.float32)
    y = jnp.zeros((0,), jnp.float32)
    v, g = fused_dense_value_grad(LogisticLoss, X, y, None, None,
                                  jnp.ones((5,), jnp.float32))
    assert float(v) == 0.0
    np.testing.assert_array_equal(np.asarray(g), np.zeros(5))


def test_flag_solve_parity(problem, monkeypatch):
    """A full L-BFGS solve with the kernel enabled lands on the same
    coefficients as the XLA path (f32 tolerance)."""
    from photon_tpu.function.objective import L2Regularization
    from photon_tpu.optim.problem import (
        GLMOptimizationConfiguration,
        GlmOptimizationProblem,
        OptimizerConfig,
    )
    from photon_tpu.types import TaskType
    from photon_tpu.utils import jitcache

    X, y, off, w, coef = problem
    batch = DataBatch(X, y, off, w)
    cfg = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=80, tolerance=1e-8),
        regularization=L2Regularization, regularization_weight=1.0)

    def solve():
        # fresh compilation per run: the env flag is a trace-time constant
        # the jitcache key knows nothing about
        jitcache.clear()
        prob = GlmOptimizationProblem(TaskType.LOGISTIC_REGRESSION, cfg)
        m, _ = prob.run(batch, dim=X.shape[1], dtype=jnp.float32)
        return np.asarray(m.coefficients.means)

    c0 = solve()
    monkeypatch.setenv("PHOTON_TPU_PALLAS_GLM", "1")
    c1 = solve()
    jitcache.clear()
    np.testing.assert_allclose(c1, c0, rtol=5e-4, atol=5e-5)


def test_flag_does_not_break_vmapped_re_solves(monkeypatch):
    """PHOTON_TPU_PALLAS_GLM=1 must NOT route vmapped per-entity
    objectives (dense-local random-effect blocks) through the kernel —
    its sequential-grid accumulation is not vmap-safe. The solve must
    produce identical results with the flag on and off."""
    from photon_tpu.estimators.game_estimator import (
        CoordinateConfiguration,
        GameEstimator,
    )
    from photon_tpu.function.objective import L2Regularization
    from photon_tpu.game.dataset import CsrRows, FeatureShard, GameDataFrame
    from photon_tpu.game.random_effect import RandomEffectDataConfiguration
    from photon_tpu.optim.problem import (
        GLMOptimizationConfiguration,
        OptimizerConfig,
    )
    from photon_tpu.types import TaskType
    from photon_tpu.utils import jitcache

    rng = np.random.default_rng(2)
    n, d_u, users = 300, 4, 6
    Xu = rng.normal(size=(n, d_u)).astype(np.float32)
    uid = rng.integers(0, users, size=n)
    y = (rng.random(n) < 0.5).astype(np.float32)
    df = GameDataFrame(
        num_samples=n, response=y,
        feature_shards={"u": FeatureShard(CsrRows.from_dense(Xu), d_u)},
        id_tags={"userId": [f"u{v}" for v in uid]})

    def fit():
        jitcache.clear()
        opt = GLMOptimizationConfiguration(
            optimizer=OptimizerConfig(max_iterations=40, tolerance=1e-8),
            regularization=L2Regularization, regularization_weight=0.5)
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            {"per_user": CoordinateConfiguration(
                RandomEffectDataConfiguration("userId", "u"), opt)},
            update_sequence=["per_user"], num_iterations=1,
            dtype=jnp.float32)
        res = est.fit(df)
        # the dense-local fast path must actually be active
        assert all(est._coordinates["per_user"]._dense_local_blocks)
        return np.asarray(res[-1].model["per_user"].coefficients)

    c_off = fit()
    monkeypatch.setenv("PHOTON_TPU_PALLAS_GLM", "1")
    c_on = fit()
    jitcache.clear()
    np.testing.assert_allclose(c_on, c_off, rtol=1e-6, atol=1e-7)
    assert np.all(np.isfinite(c_on))


def test_flag_mesh_solve_gated_off(monkeypatch, devices8):
    """ADVICE r4: with PHOTON_TPU_PALLAS_GLM=1, a mesh-sharded SPMD solve
    must NOT trace the kernel (pallas_call has no sharding annotations) —
    the solve runs the XLA path, matches the flag-off result, and the
    single-device solve with the flag still uses its own (separate) cache
    entry."""
    import jax

    from photon_tpu.function.objective import L2Regularization
    from photon_tpu.optim.problem import (
        GLMOptimizationConfiguration,
        GlmOptimizationProblem,
        OptimizerConfig,
    )
    from photon_tpu.parallel import mesh as M
    from photon_tpu.types import TaskType
    from photon_tpu.utils import jitcache

    rng = np.random.default_rng(5)
    n, d = 256, 16
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    batch = DataBatch(jnp.asarray(X), jnp.asarray(y))
    cfg = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=40, tolerance=1e-8),
        regularization=L2Regularization, regularization_weight=1.0)
    mesh = M.create_mesh(8, (M.DATA_AXIS,), (8,))

    def run_mesh():
        prob = GlmOptimizationProblem(TaskType.LOGISTIC_REGRESSION, cfg)
        m, _ = prob.run(batch, dim=d, dtype=jnp.float32, mesh=mesh)
        return np.asarray(m.coefficients.means)

    jitcache.clear()
    c_off = run_mesh()
    monkeypatch.setenv("PHOTON_TPU_PALLAS_GLM", "1")
    jitcache.clear()
    c_on = run_mesh()
    # bitwise: the same (XLA) trace must have been used
    np.testing.assert_array_equal(c_on, c_off)
    # and the kernel is hard-disabled at trace time inside disabled()
    from photon_tpu.ops import pallas_glm
    with pallas_glm.disabled():
        assert not pallas_glm._supported(
            jnp.zeros((8, 4), jnp.float32), _IDN, jnp.zeros(4, jnp.float32))
    jitcache.clear()


def test_supported_rejects_f64_coef():
    """ADVICE r4: an f64 solve over f32 features must not take the fused
    path (it would silently return f32 and break the while_loop carry
    dtype); the XLA path promotes instead."""
    from photon_tpu.ops import pallas_glm

    x = jnp.zeros((8, 4), jnp.float32)
    assert pallas_glm._supported(x, _IDN, jnp.zeros(4, jnp.float32))
    assert not pallas_glm._supported(x, _IDN, jnp.zeros(4, jnp.float64))


def test_fused_bf16_feature_storage():
    """bf16 feature storage through the fused kernel: the two HBM levers
    (single pass + half-width storage) compose; parity vs the XLA path on
    the SAME bf16 inputs at bf16-appropriate tolerance."""
    rng = np.random.default_rng(9)
    n, d = 96, 12
    X16 = jnp.asarray(rng.normal(size=(n, d)), jnp.bfloat16)
    y = jnp.asarray((rng.random(n) > 0.4), jnp.float32)
    coef = jnp.asarray(rng.normal(size=d) * 0.3, jnp.float32)

    from photon_tpu.ops import pallas_glm
    assert pallas_glm._supported(X16, _IDN, coef)

    v_f, g_f = fused_dense_value_grad(LogisticLoss, X16, y, None, None, coef)
    v_x, g_x = aggregators.value_and_gradient(
        LogisticLoss, X16, y, None, None, coef, no_normalization())
    np.testing.assert_allclose(float(v_f), float(v_x), rtol=2e-2)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_x, np.float32),
                               rtol=5e-2, atol=5e-2)
    assert g_f.dtype == jnp.float32


# ---------------------------------------------------------------------------
# sparse ELL kernel edges: tile remainders, zero weights, empty segments
# ---------------------------------------------------------------------------


def _sparse_problem(n, k, d, seed=13):
    from photon_tpu.ops import features as F

    rng = np.random.default_rng(seed)
    idx = jnp.asarray(rng.integers(0, d, size=(n, k)), jnp.int32)
    val = jnp.asarray(rng.normal(size=(n, k)) / np.sqrt(max(k, 1)),
                      jnp.float32)
    y = jnp.asarray((rng.random(n) > 0.4), jnp.float32)
    off = jnp.asarray(rng.normal(size=n) * 0.2, jnp.float32)
    w = jnp.asarray(rng.random(n) + 0.1, jnp.float32)
    coef = jnp.asarray(rng.normal(size=d) * 0.4, jnp.float32)
    return F.SparseFeatures(idx, val), y, off, w, coef


def _sparse_xla(x, y, off, w, coef):
    from photon_tpu.ops import pallas_glm

    with pallas_glm.disabled():
        return aggregators.value_and_gradient(
            LogisticLoss, x, y, off, w, coef, no_normalization())


@pytest.mark.parametrize("n", [1, 7, 127, 128, 129, 333])
def test_sparse_tile_remainders(n):
    """N not divisible by the tile: pad rows are zero-weight all-pad rows
    and must contribute exactly nothing."""
    from photon_tpu.ops.pallas_glm import fused_sparse_value_grad

    x, y, off, w, coef = _sparse_problem(n, 4, 64)
    v0, g0 = _sparse_xla(x, y, off, w, coef)
    v1, g1 = fused_sparse_value_grad(LogisticLoss, x, y, off, w, coef,
                                     tile_n=128)
    np.testing.assert_allclose(float(v1), float(v0), rtol=5e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=5e-5, atol=5e-5)


def test_sparse_zero_weight_rows():
    from photon_tpu.ops.pallas_glm import fused_sparse_value_grad

    x, y, off, w, coef = _sparse_problem(100, 4, 64)
    w = w.at[::3].set(0.0)
    v0, g0 = _sparse_xla(x, y, off, w, coef)
    v1, g1 = fused_sparse_value_grad(LogisticLoss, x, y, off, w, coef)
    np.testing.assert_allclose(float(v1), float(v0), rtol=5e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=5e-5, atol=5e-5)


def test_sparse_empty_segments_and_zero_width():
    """Rows whose slots are ALL pads contribute only their offset's
    loss; a width-zero ELL block (k=0) is every row empty."""
    from photon_tpu.ops import features as F
    from photon_tpu.ops.pallas_glm import fused_sparse_value_grad

    x, y, off, w, coef = _sparse_problem(60, 3, 32)
    idx = x.indices.at[::4].set(0)
    val = x.values.at[::4].set(0.0)          # (0, 0.0) = pad slots
    x2 = F.SparseFeatures(idx, val)
    v0, g0 = _sparse_xla(x2, y, off, w, coef)
    v1, g1 = fused_sparse_value_grad(LogisticLoss, x2, y, off, w, coef)
    np.testing.assert_allclose(float(v1), float(v0), rtol=5e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=5e-5, atol=5e-5)

    k0 = F.SparseFeatures(jnp.zeros((16, 0), jnp.int32),
                          jnp.zeros((16, 0), jnp.float32))
    y0, off0, w0 = y[:16], off[:16], w[:16]
    v0, g0 = _sparse_xla(k0, y0, off0, w0, coef)
    v1, g1 = fused_sparse_value_grad(LogisticLoss, k0, y0, off0, w0, coef)
    np.testing.assert_allclose(float(v1), float(v0), rtol=5e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=5e-6)


def test_sparse_empty_batch():
    from photon_tpu.ops import features as F
    from photon_tpu.ops.pallas_glm import fused_sparse_value_grad

    x = F.SparseFeatures(jnp.zeros((0, 4), jnp.int32),
                         jnp.zeros((0, 4), jnp.float32))
    v, g = fused_sparse_value_grad(
        LogisticLoss, x, jnp.zeros((0,), jnp.float32), None, None,
        jnp.zeros(8, jnp.float32))
    assert float(v) == 0.0
    np.testing.assert_array_equal(np.asarray(g), np.zeros(8, np.float32))


# ---------------------------------------------------------------------------
# serving gather+margin kernel edges
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [1, 5, 64, 127, 128, 129])
def test_serving_margin_tile_remainders(n):
    from photon_tpu.ops.pallas_glm import fused_gather_margin

    rng = np.random.default_rng(21)
    d, k = 96, 6
    idx = jnp.asarray(rng.integers(0, d, size=(n, k)), jnp.int32)
    val = jnp.asarray(rng.normal(size=(n, k)), jnp.float32)
    off = jnp.asarray(rng.normal(size=n), jnp.float32)
    theta = jnp.asarray(rng.normal(size=d) * 0.3, jnp.float32)
    got = fused_gather_margin(idx, val, off, theta)
    want = off + jnp.sum(val * theta[idx], axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_serving_margin_degenerate_shapes():
    from photon_tpu.ops.pallas_glm import fused_gather_margin

    theta = jnp.arange(8, dtype=jnp.float32)
    # empty batch
    out = fused_gather_margin(jnp.zeros((0, 3), jnp.int32),
                              jnp.zeros((0, 3), jnp.float32), None, theta)
    assert out.shape == (0,)
    # zero slot width: margins are just the offsets
    off = jnp.asarray([1.5, -2.0], jnp.float32)
    out = fused_gather_margin(jnp.zeros((2, 0), jnp.int32),
                              jnp.zeros((2, 0), jnp.float32), off, theta)
    np.testing.assert_allclose(np.asarray(out), np.asarray(off))
    # None offsets
    idx = jnp.asarray([[2], [5]], jnp.int32)
    val = jnp.asarray([[2.0], [1.0]], jnp.float32)
    out = fused_gather_margin(idx, val, None, theta)
    np.testing.assert_allclose(np.asarray(out), [4.0, 5.0])


def test_serving_supported_gate():
    from photon_tpu.ops import pallas_glm

    theta = jnp.zeros(64, jnp.float32)
    assert pallas_glm._supported_serving(theta, 4)
    assert not pallas_glm._supported_serving(theta, 0)
    assert not pallas_glm._supported_serving(
        jnp.zeros(64, jnp.float64), 4)
    assert not pallas_glm._supported_serving(
        jnp.zeros(pallas_glm._MAX_SPARSE_DIM + 1, jnp.float32), 4)
    with pallas_glm.disabled():
        assert not pallas_glm._supported_serving(theta, 4)
