"""Windowed time-series telemetry: behavior over time, not just run totals.

The cumulative registry (obs/metrics.py) answers "what happened this
run"; it cannot answer "WHEN did p99 degrade during the shard kill".
This module adds fixed-interval windowed series — counters, gauges, and
streaming quantile sketches — keyed by (name, labels) exactly like the
cumulative registry, ring-buffered so memory stays bounded no matter how
long a serving process lives.

Design points:

  * **explicit timestamps** — every observation carries its own ``t``
    (seconds, any monotone clock). Window index is ``floor(t /
    interval_s)``, so a replay driven on a virtual clock produces
    bitwise-identical timelines run to run; nothing here ever reads the
    wall clock.
  * **per-label quantiles** — each (name, labels) series owns its own
    per-window sketch, so two tenants' (or two shards') latencies can no
    longer pollute each other's p99 the way the process-global
    histograms of PR 12 did. The cumulative histograms stay untouched as
    the run-total shim.
  * **geometric-bucket sketches** — a value ``v`` lands in bucket
    ``ceil(log_gamma(v))`` and is estimated as ``2·γ^i/(γ+1)``, so every
    quantile estimate is within relative error ``α = (γ-1)/(γ+1)`` of a
    true sample value of that rank, and two sketches with the same γ
    merge EXACTLY (bucket-count sums) — the property the multi-process
    ``merge_snapshots`` path and its pinned-error-bound test rely on.
  * **ring-buffered** — at most ``capacity`` windows per series; older
    windows are evicted (counted), and observations older than the ring
    are dropped (counted), never resurrected.

``snapshot()`` emits the cross-process unit: a dict shaped like
``MetricsRegistry.snapshot()`` plus a ``"timeseries"`` section, which
``obs.metrics.merge_snapshots`` aligns window-by-window across
processes. ``report_section()`` is the RunReport ``timeline`` section.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

from photon_tpu.obs.metrics import LabelItems, _label_items, _label_suffix

#: default window width (seconds on whatever clock the caller stamps with)
DEFAULT_INTERVAL_S = 1.0
#: default ring size: windows retained per (name, labels) series
DEFAULT_CAPACITY = 256
#: default sketch resolution: relative error (γ-1)/(γ+1) ≈ 4.8%
DEFAULT_GAMMA = 1.1
#: hard per-sketch bucket ceiling (γ=1.1 spans 1e-9..1e9 in ~435 buckets;
#: past the cap the smallest buckets collapse together, which can only
#: bias the extreme LOW quantiles, never the p95/p99 the SLO gates read)
MAX_SKETCH_BUCKETS = 512

QUANTILES = (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))


class QuantileSketch:
    """Mergeable geometric-bucket quantile sketch (DDSketch-style).

    Positive values map to bucket ``i = ceil(ln(v)/ln(γ))`` and are
    estimated by the bucket midpoint-in-ratio ``2·γ^i/(γ+1)``; values
    ``<= 0`` (a virtual-clock latency can be exactly 0.0) count in a
    dedicated zero bucket estimated as 0.0. The rank-q estimate is
    within relative error ``alpha()`` of the true sample of that rank.
    """

    __slots__ = ("gamma", "_log_gamma", "zeros", "counts", "count", "sum")

    def __init__(self, gamma: float = DEFAULT_GAMMA):
        if gamma <= 1.0:
            raise ValueError(f"sketch gamma must be > 1, got {gamma}")
        self.gamma = float(gamma)
        self._log_gamma = math.log(self.gamma)
        self.zeros = 0
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0

    def alpha(self) -> float:
        """Guaranteed relative-error bound of ``quantile`` estimates."""
        return (self.gamma - 1.0) / (self.gamma + 1.0)

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        if value <= 0.0:
            self.zeros += 1
            return
        i = math.ceil(math.log(value) / self._log_gamma)
        # v == γ^i exactly can round to i or i+1 across libm versions;
        # normalize so the bucket invariant γ^(i-1) < v <= γ^i holds
        if self.gamma ** (i - 1) >= value:
            i -= 1
        self.counts[i] = self.counts.get(i, 0) + 1
        if len(self.counts) > MAX_SKETCH_BUCKETS:
            lo = sorted(self.counts)[:2]
            self.counts[lo[1]] = self.counts.pop(lo[0]) + self.counts[lo[1]]

    def _estimate(self, i: int) -> float:
        return 2.0 * self.gamma ** i / (self.gamma + 1.0)

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile estimate: the bucket holding the
        ``floor(q·(n-1))``-th (0-based) smallest sample."""
        if self.count == 0:
            return None
        rank = math.floor(q * (self.count - 1))
        if rank < self.zeros:
            return 0.0
        cum = self.zeros
        for i in sorted(self.counts):
            cum += self.counts[i]
            if cum > rank:
                return self._estimate(i)
        return self._estimate(max(self.counts)) if self.counts else 0.0

    def merge(self, other: "QuantileSketch") -> None:
        if abs(other.gamma - self.gamma) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with gamma {self.gamma} vs "
                f"{other.gamma}")
        self.zeros += other.zeros
        self.count += other.count
        self.sum += other.sum
        for i, c in other.counts.items():
            self.counts[i] = self.counts.get(i, 0) + c

    def to_json(self) -> dict:
        return {"gamma": self.gamma, "zeros": self.zeros,
                "counts": {str(i): c for i, c in sorted(self.counts.items())}}

    @staticmethod
    def from_json(obj: dict) -> "QuantileSketch":
        s = QuantileSketch(float(obj["gamma"]))
        s.zeros = int(obj.get("zeros", 0))
        s.counts = {int(i): int(c)
                    for i, c in dict(obj.get("counts", {})).items()}
        s.count = s.zeros + sum(s.counts.values())
        return s


class _Window:
    __slots__ = ("value", "max", "sketch")

    def __init__(self):
        self.value = 0.0         # counter sum / gauge last-write
        self.max = float("-inf")  # gauge watermark
        self.sketch: Optional[QuantileSketch] = None


class _Series:
    __slots__ = ("kind", "windows", "evicted", "late_dropped")

    def __init__(self, kind: str):
        self.kind = kind
        self.windows: Dict[int, _Window] = {}
        self.evicted = 0
        self.late_dropped = 0


class _Handle:
    """One (name, labels) series bound to its registry; the object call
    sites hold (``series.counter("replay.requests", shard="3")``)."""

    __slots__ = ("_reg", "_series")

    def __init__(self, reg: "WindowedRegistry", series: _Series):
        self._reg = reg
        self._series = series

    def inc(self, t: float, delta: float = 1.0) -> None:
        if delta < 0:
            raise ValueError(f"windowed counter delta must be >= 0, "
                             f"got {delta}")
        w = self._reg._window(self._series, t)
        if w is not None:
            w.value += delta

    def set(self, t: float, value: float) -> None:
        w = self._reg._window(self._series, t)
        if w is not None:
            w.value = float(value)
            w.max = max(w.max, float(value))

    def observe(self, t: float, value: float) -> None:
        w = self._reg._window(self._series, t)
        if w is not None:
            if w.sketch is None:
                w.sketch = QuantileSketch(self._reg.gamma)
            w.sketch.observe(value)

    @property
    def num_windows(self) -> int:
        with self._reg._lock:
            return len(self._series.windows)


class WindowedRegistry:
    """Thread-safe (name, labels) -> windowed series registry."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 capacity: int = DEFAULT_CAPACITY,
                 gamma: float = DEFAULT_GAMMA):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.gamma = float(gamma)
        self._lock = threading.RLock()
        self._series: Dict[Tuple[str, LabelItems], _Series] = {}
        self._kinds: Dict[str, str] = {}

    # -- registration ----------------------------------------------------

    def _get(self, kind: str, name: str, labels: Dict[str, str]) -> _Handle:
        key = (name, _label_items(labels))
        with self._lock:
            existing = self._kinds.get(name)
            if existing is not None and existing != kind:
                raise ValueError(
                    f"windowed series {name!r} already registered as "
                    f"{existing}, requested {kind}")
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _Series(kind)
                self._kinds[name] = kind
            return _Handle(self, s)

    def counter(self, name: str, **labels: str) -> _Handle:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels: str) -> _Handle:
        return self._get("gauge", name, labels)

    def quantile(self, name: str, **labels: str) -> _Handle:
        return self._get("quantile", name, labels)

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._kinds.clear()

    # -- windowing -------------------------------------------------------

    def window_index(self, t: float) -> int:
        return int(math.floor(float(t) / self.interval_s))

    def _window(self, s: _Series, t: float) -> Optional[_Window]:
        idx = self.window_index(t)
        with self._lock:
            w = s.windows.get(idx)
            if w is not None:
                return w
            if s.windows and idx < max(s.windows) - self.capacity + 1:
                s.late_dropped += 1  # older than the ring can ever hold
                return None
            w = s.windows[idx] = _Window()
            while len(s.windows) > self.capacity:
                del s.windows[min(s.windows)]
                s.evicted += 1
            return w

    # -- export ----------------------------------------------------------

    def _series_json(self, s: _Series) -> dict:
        windows: List[dict] = []
        for idx in sorted(s.windows):
            w = s.windows[idx]
            if s.kind == "counter":
                windows.append({"idx": idx, "value": w.value})
            elif s.kind == "gauge":
                windows.append({"idx": idx, "value": w.value, "max": w.max})
            else:
                sk = w.sketch or QuantileSketch(self.gamma)
                rec = {"idx": idx, "count": sk.count, "sum": sk.sum,
                       "sketch": sk.to_json()}
                for qn, q in QUANTILES:
                    rec[qn] = sk.quantile(q)
                windows.append(rec)
        out = {"kind": s.kind, "interval_s": self.interval_s,
               "windows": windows}
        if s.evicted:
            out["evicted"] = s.evicted
        if s.late_dropped:
            out["late_dropped"] = s.late_dropped
        return out

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Mergeable snapshot: the ``MetricsRegistry.snapshot()`` shape
        plus a ``timeseries`` section, so one dict per process feeds
        straight into ``obs.metrics.merge_snapshots``."""
        out: Dict[str, Dict[str, object]] = {
            "counters": {}, "gauges": {}, "histograms": {},
            "timeseries": {}}
        with self._lock:
            items = list(self._series.items())
        for (name, labels), s in sorted(items, key=lambda kv: kv[0]):
            sdict = self._series_json(s)
            if labels:
                sdict["labels"] = dict(labels)
            out["timeseries"][name + _label_suffix(labels)] = sdict
        return out

    def cumulative(self, name: str, **labels: str) -> Optional[dict]:
        """All-windows run total for one series — the shim that keeps the
        old cumulative view answerable from windowed data. Counters sum,
        gauges report last/max, quantile series merge every window's
        sketch into run-level p50/p95/p99."""
        key = (name, _label_items(labels))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return None
            if s.kind == "counter":
                return {"kind": "counter",
                        "value": sum(w.value for w in s.windows.values())}
            if s.kind == "gauge":
                if not s.windows:
                    return {"kind": "gauge", "value": 0.0}
                last = s.windows[max(s.windows)]
                return {"kind": "gauge", "value": last.value,
                        "max": max(w.max for w in s.windows.values())}
            merged = QuantileSketch(self.gamma)
            for w in s.windows.values():
                if w.sketch is not None:
                    merged.merge(w.sketch)
            out = {"kind": "quantile", "count": merged.count,
                   "sum": merged.sum}
            for qn, q in QUANTILES:
                out[qn] = merged.quantile(q)
            return out


def merge_series(series_list) -> dict:
    """Merge same-key series dicts (``snapshot()['timeseries']`` values)
    window-by-window: counters sum, gauges keep the watermark, quantile
    sketches merge exactly; per-window quantiles are recomputed on the
    merged sketch. First interval wins on a layout mismatch, mirroring
    the histogram rule in ``merge_snapshots``."""
    series_list = [s for s in series_list if s is not None]
    if not series_list:
        return {}
    first = series_list[0]
    out = {"kind": first["kind"], "interval_s": first["interval_s"],
           "windows": []}
    if "labels" in first:
        out["labels"] = dict(first["labels"])
    evicted = late = 0
    by_idx: Dict[int, dict] = {}
    for s in series_list:
        if (s["kind"] != first["kind"]
                or abs(s["interval_s"] - first["interval_s"]) > 1e-12):
            continue
        evicted += int(s.get("evicted", 0))
        late += int(s.get("late_dropped", 0))
        for w in s["windows"]:
            idx = int(w["idx"])
            cur = by_idx.get(idx)
            if cur is None:
                by_idx[idx] = dict(w)
            elif first["kind"] == "counter":
                cur["value"] += w["value"]
            elif first["kind"] == "gauge":
                cur["value"] = max(cur["value"], w["value"])
                cur["max"] = max(cur.get("max", cur["value"]),
                                 w.get("max", w["value"]))
            else:
                merged = QuantileSketch.from_json(cur["sketch"])
                merged.merge(QuantileSketch.from_json(w["sketch"]))
                merged.sum = cur["sum"] + w["sum"]
                cur["sketch"] = merged.to_json()
                cur["count"] = merged.count
                cur["sum"] = merged.sum
                for qn, q in QUANTILES:
                    cur[qn] = merged.quantile(q)
    out["windows"] = [by_idx[i] for i in sorted(by_idx)]
    if evicted:
        out["evicted"] = evicted
    if late:
        out["late_dropped"] = late
    return out


#: process-wide default windowed registry — the serving engine and the
#: replay harness both record here
series = WindowedRegistry()


def clear() -> None:
    series.clear()


def report_section() -> Optional[dict]:
    """The RunReport ``timeline`` section; None while nothing windowed
    has been recorded (offline drivers' reports stay unchanged)."""
    snap = series.snapshot()["timeseries"]
    if not snap:
        return None
    return {"interval_s": series.interval_s,
            "capacity": series.capacity,
            "series": snap}
