"""RunReport: one machine-readable JSON manifest per driver run.

Written at the end of ``cli/train.py`` / ``cli/score.py`` and emitted by
``bench.py`` in the same schema: phase spans, the metrics-registry
snapshot, drained solver trajectories (per-iteration loss/||g||/step
series and per-entity RE outcomes), mesh/device topology, and host/
device memory watermarks sampled per phase. The schema is versioned so
later perf/robustness PRs can extend it without breaking parsers.

Multi-process: :func:`write_run_report` with ``aggregate=True`` gathers
every process's metrics/memory/solver sections to process 0 (two
collectives at report time — obs/aggregate.py) and only process 0
writes; other processes return ``None``.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

SCHEMA = "photon_tpu.runreport.v1"


def _topology(mesh=None) -> Dict[str, Any]:
    """Device/mesh topology; degrades to {} when jax isn't loaded."""
    if sys.modules.get("jax") is None:
        return {}
    try:
        from photon_tpu.parallel.mesh import mesh_topology
        return mesh_topology(mesh)
    except Exception:  # backend not initialized — report stays valid
        return {}


def _phases() -> List[Dict[str, Any]]:
    from photon_tpu.obs import spans
    out = []
    for r in spans.records():
        p = {
            "name": r["name"],
            "start_unix": r["start_unix"],
            "end_unix": r["end_unix"],
            "duration_s": r["dur_us"] / 1e6,
            "parent": r.get("parent"),
            "depth": r.get("depth", 0),
            "tid": r.get("tid"),
        }
        if "args" in r:
            p["args"] = r["args"]
        if r.get("error"):
            p["error"] = True
        out.append(p)
    return out


def build_run_report(driver: str,
                     mesh=None,
                     extra: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Assemble this process's report dict. Draining solver telemetry and
    sampling memory happen here — this IS the phase boundary."""
    from photon_tpu.obs import aggregate, memory, solver
    from photon_tpu.obs.metrics import registry
    from photon_tpu.resilience import failures
    from photon_tpu.utils import timing

    memory.record_phase("run_report")  # final watermark sample
    report: Dict[str, Any] = {
        "schema": SCHEMA,
        "driver": driver,
        "created_unix": time.time(),
        "argv": list(sys.argv),
        "process": aggregate.process_info(),
        "topology": _topology(mesh),
        "phases": _phases(),
        "timings": [[label, secs] for label, secs in timing.timing_records()],
        "metrics": registry.snapshot(),
        "solver": solver.drain(),
        "memory": memory.watermarks(),
        "failures": failures.snapshot(),
    }
    serving = _serving_section()
    if serving is not None:
        report["serving"] = serving
    cd = _cd_section()
    if cd is not None:
        report["cd"] = cd
    nearline = _nearline_section()
    if nearline is not None:
        report["nearline"] = nearline
    sweep = _sweep_section()
    if sweep is not None:
        report["sweep"] = sweep
    sdca = _sdca_section()
    if sdca is not None:
        report["sdca"] = sdca
    re_plan = _re_plan_section()
    if re_plan is not None:
        report["re_plan"] = re_plan
    timeline = _timeline_section()
    if timeline is not None:
        report["timeline"] = timeline
    slo = _slo_section()
    if slo is not None:
        report["slo"] = slo
    if extra:
        report["extra"] = extra
    return report


def _serving_section() -> Optional[Dict[str, Any]]:
    """The active serving engine's ``stats()``, when this process is a
    serving process. Deliberately read via ``sys.modules`` — an offline
    driver that never imported photon_tpu.serving pays nothing and its
    report is unchanged."""
    mod = sys.modules.get("photon_tpu.serving")
    if mod is None:
        return None
    try:
        return mod.serving_report_section()
    except Exception:  # noqa: BLE001 — reporting must not kill a run
        return None


def _cd_section() -> Optional[Dict[str, Any]]:
    """Parallel coordinate-descent statistics (group/staleness/fallback
    accounting), when this process ran a parallel sweep. Same
    ``sys.modules`` pattern as :func:`_serving_section` — sequential-only
    and non-training processes pay nothing."""
    mod = sys.modules.get("photon_tpu.game.parallel_cd")
    if mod is None:
        return None
    try:
        return mod.report_section()
    except Exception:  # noqa: BLE001 — reporting must not kill a run
        return None


def _nearline_section() -> Optional[Dict[str, Any]]:
    """The active nearline pipeline's summary (rounds, watermark,
    publish/rollback totals, reader stats), when this process ran one.
    Same ``sys.modules`` pattern as :func:`_serving_section`."""
    mod = sys.modules.get("photon_tpu.nearline.pipeline")
    if mod is None:
        return None
    try:
        return mod.report_section()
    except Exception:  # noqa: BLE001 — reporting must not kill a run
        return None


def _sweep_section() -> Optional[Dict[str, Any]]:
    """Lane-batched sweep/tuner accounting (batched solves, per-lane
    outcomes, tuner round summary), when this process ran one. Same
    ``sys.modules`` pattern as :func:`_serving_section` — runs that never
    sweep pay nothing."""
    mod = sys.modules.get("photon_tpu.optim.batched")
    if mod is None:
        return None
    try:
        section = mod.report_section()
        # an imported-but-idle batched module stays out of the report
        return section if section.get("runs") else None
    except Exception:  # noqa: BLE001 — reporting must not kill a run
        return None


def _re_plan_section() -> Optional[Dict[str, Any]]:
    """Random-effect sweep HBM planning (plans emitted, degraded /
    over-budget bucket counts, the last plan) — a refused or degraded
    sweep shape is DATA in the report, not a crash. Same ``sys.modules``
    pattern as :func:`_serving_section`; the section itself returns None
    while no sweep has been planned."""
    mod = sys.modules.get("photon_tpu.parallel.memory")
    if mod is None:
        return None
    try:
        return mod.report_section()
    except Exception:  # noqa: BLE001 — reporting must not kill a run
        return None


def _timeline_section() -> Optional[Dict[str, Any]]:
    """Windowed time-series telemetry (obs/timeseries.py), when this
    process recorded any. Same ``sys.modules`` pattern as
    :func:`_serving_section` — offline drivers that never touch the
    windowed registry pay nothing; the section itself returns None
    while it is empty."""
    mod = sys.modules.get("photon_tpu.obs.timeseries")
    if mod is None:
        return None
    try:
        return mod.report_section()
    except Exception:  # noqa: BLE001 — reporting must not kill a run
        return None


def _slo_section() -> Optional[Dict[str, Any]]:
    """SLO verdicts (obs/slo.py) recorded by any evaluation this run.
    Same ``sys.modules`` pattern as :func:`_serving_section`; the
    section itself returns None while nothing was evaluated."""
    mod = sys.modules.get("photon_tpu.obs.slo")
    if mod is None:
        return None
    try:
        return mod.report_section()
    except Exception:  # noqa: BLE001 — reporting must not kill a run
        return None


def _sdca_section() -> Optional[Dict[str, Any]]:
    """Stochastic dual (SDCA) solve accounting — runs/epochs/staleness
    fallbacks and the last run's gap outcome — when this process ran one.
    Same ``sys.modules`` pattern as :func:`_serving_section`; the section
    itself returns None while no solve has run."""
    mod = sys.modules.get("photon_tpu.optim.sdca")
    if mod is None:
        return None
    try:
        return mod.report_section()
    except Exception:  # noqa: BLE001 — reporting must not kill a run
        return None


def write_run_report(path: str,
                     driver: str,
                     mesh=None,
                     extra: Optional[Dict[str, Any]] = None,
                     aggregate: bool = False) -> Optional[Dict[str, Any]]:
    """Build + write the report; returns the written dict.

    With ``aggregate=True`` on a multi-process run, every process must
    call this (the gather is collective); only process 0 writes and
    returns the report — it gains a ``processes`` section with each
    process's metrics/memory/solver and cluster-merged ``metrics``
    under ``metrics_aggregated``.
    """
    from photon_tpu.obs import aggregate as agg
    from photon_tpu.obs.metrics import merge_snapshots

    report = build_run_report(driver, mesh=mesh, extra=extra)
    if aggregate and report["process"]["count"] > 1:
        local = {
            "process": report["process"],
            "metrics": report["metrics"],
            "memory": report["memory"],
            "solver": report["solver"],
            "num_phases": len(report["phases"]),
        }
        gathered = agg.gather_payloads(local)
        if gathered is None:  # non-zero process: report written by proc 0
            return None
        report["processes"] = gathered
        report["metrics_aggregated"] = merge_snapshots(
            p["metrics"] for p in gathered)

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(report, f, indent=1, default=_json_fallback)
        f.write("\n")
    return report


def _json_fallback(obj):
    """Numpy scalars/arrays sneak into extras; make them JSON-safe rather
    than killing the report at the end of a long run."""
    try:
        import numpy as np
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, np.generic):
            return obj.item()
    except ImportError:  # pragma: no cover
        pass
    return str(obj)


def validate_run_report(report: Dict[str, Any]) -> List[str]:
    """Structural schema check; returns a list of problems ([] = valid).
    Used by tests and by bench.py's self-check before emitting."""
    errors: List[str] = []
    if report.get("schema") != SCHEMA:
        errors.append(f"schema is {report.get('schema')!r}, want {SCHEMA!r}")
    if not isinstance(report.get("driver"), str) or not report.get("driver"):
        errors.append("driver must be a non-empty string")
    if not isinstance(report.get("created_unix"), (int, float)):
        errors.append("created_unix must be a number")
    phases = report.get("phases")
    if not isinstance(phases, list):
        errors.append("phases must be a list")
    else:
        for i, p in enumerate(phases):
            for k in ("name", "start_unix", "end_unix", "duration_s"):
                if k not in p:
                    errors.append(f"phases[{i}] missing {k!r}")
            if ("start_unix" in p and "end_unix" in p
                    and p["start_unix"] > p["end_unix"] + 1e-9):
                errors.append(f"phases[{i}] ({p.get('name')}): start > end")
            if p.get("duration_s", 0) < 0:
                errors.append(f"phases[{i}]: negative duration")
    metrics = report.get("metrics")
    if not isinstance(metrics, dict):
        errors.append("metrics must be a dict")
    else:
        for section in ("counters", "gauges", "histograms"):
            if not isinstance(metrics.get(section), dict):
                errors.append(f"metrics.{section} must be a dict")
    solver = report.get("solver")
    if not isinstance(solver, dict):
        errors.append("solver must be a dict")
    else:
        for section in ("trajectories", "random_effects"):
            if not isinstance(solver.get(section), list):
                errors.append(f"solver.{section} must be a list")
    if not isinstance(report.get("memory"), dict):
        errors.append("memory must be a dict")
    if not isinstance(report.get("failures"), list):
        errors.append("failures must be a list")
    proc = report.get("process")
    if (not isinstance(proc, dict) or "index" not in proc
            or "count" not in proc):
        errors.append("process must be {'index', 'count'}")
    if "serving" in report:  # optional: only serving processes emit it
        serving = report["serving"]
        if not isinstance(serving, dict):
            errors.append("serving must be a dict")
        else:
            for k in ("buckets", "compile_counts", "counters",
                      "latency_seconds"):
                if k not in serving:
                    errors.append(f"serving missing {k!r}")
            if "swap" in serving:  # optional: engines with swap support
                swap = serving["swap"]
                if not isinstance(swap, dict):
                    errors.append("serving.swap must be a dict")
                else:
                    for k in ("version", "history"):
                        if k not in swap:
                            errors.append(f"serving.swap missing {k!r}")
                    if not isinstance(swap.get("history", []), list):
                        errors.append("serving.swap history must be a list")
    if "sweep" in report:  # optional: only lane-batched sweep processes
        sweep = report["sweep"]
        if not isinstance(sweep, dict):
            errors.append("sweep must be a dict")
        else:
            for k in ("runs", "lanes_total", "lane_records", "tuner"):
                if k not in sweep:
                    errors.append(f"sweep missing {k!r}")
            if not isinstance(sweep.get("lane_records", []), list):
                errors.append("sweep.lane_records must be a list")
    if "sdca" in report:  # optional: only stochastic-dual training runs
        sdca = report["sdca"]
        if not isinstance(sdca, dict):
            errors.append("sdca must be a dict")
        else:
            for k in ("runs", "epochs", "fallbacks", "converged"):
                if k not in sdca:
                    errors.append(f"sdca missing {k!r}")
    if "re_plan" in report:  # optional: only RE-sweep planning processes
        re_plan = report["re_plan"]
        if not isinstance(re_plan, dict):
            errors.append("re_plan must be a dict")
        else:
            for k in ("plans", "buckets_degraded", "buckets_over_budget",
                      "last_plan"):
                if k not in re_plan:
                    errors.append(f"re_plan missing {k!r}")
    if "timeline" in report:  # optional: only windowed-telemetry runs
        timeline = report["timeline"]
        if not isinstance(timeline, dict):
            errors.append("timeline must be a dict")
        else:
            if not isinstance(timeline.get("interval_s"), (int, float)) \
                    or timeline.get("interval_s", 0) <= 0:
                errors.append("timeline.interval_s must be positive")
            series_map = timeline.get("series")
            if not isinstance(series_map, dict):
                errors.append("timeline.series must be a dict")
            else:
                for key, s in series_map.items():
                    if not isinstance(s, dict):
                        errors.append(f"timeline.series[{key!r}] not a dict")
                        continue
                    if s.get("kind") not in ("counter", "gauge", "quantile"):
                        errors.append(
                            f"timeline.series[{key!r}] bad kind "
                            f"{s.get('kind')!r}")
                    windows = s.get("windows")
                    if not isinstance(windows, list):
                        errors.append(
                            f"timeline.series[{key!r}].windows not a list")
                        continue
                    idxs = [w.get("idx") for w in windows
                            if isinstance(w, dict)]
                    if len(idxs) != len(windows) or idxs != sorted(idxs):
                        errors.append(
                            f"timeline.series[{key!r}] windows must carry "
                            f"sorted idx fields")
    if "slo" in report:  # optional: only runs that evaluated SLO specs
        slo = report["slo"]
        if not isinstance(slo, dict):
            errors.append("slo must be a dict")
        else:
            if slo.get("status") not in ("PASS", "WARN", "BREACH"):
                errors.append(f"slo.status invalid: {slo.get('status')!r}")
            verdicts = slo.get("verdicts")
            if not isinstance(verdicts, list):
                errors.append("slo.verdicts must be a list")
            else:
                for i, v in enumerate(verdicts):
                    if not isinstance(v, dict):
                        errors.append(f"slo.verdicts[{i}] not a dict")
                        continue
                    for k in ("rule_id", "kind", "status",
                              "offending_windows"):
                        if k not in v:
                            errors.append(f"slo.verdicts[{i}] missing {k!r}")
                    if v.get("status") not in ("PASS", "WARN", "BREACH"):
                        errors.append(
                            f"slo.verdicts[{i}] bad status "
                            f"{v.get('status')!r}")
                    if not isinstance(v.get("offending_windows", []), list):
                        errors.append(
                            f"slo.verdicts[{i}].offending_windows "
                            f"must be a list")
    if "cd" in report:  # optional: only parallel-CD training processes
        cd = report["cd"]
        if not isinstance(cd, dict) or not isinstance(
                cd.get("parallel"), dict):
            errors.append("cd must be {'parallel': {...}}")
        else:
            par = cd["parallel"]
            for k in ("runs", "groups", "groups_run", "members_solved",
                      "stale_regressions", "fallbacks", "group_records"):
                if k not in par:
                    errors.append(f"cd.parallel missing {k!r}")
            if not isinstance(par.get("group_records", []), list):
                errors.append("cd.parallel group_records must be a list")
    return errors
