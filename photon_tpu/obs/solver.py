"""Device-resident solver telemetry, drained only at phase boundaries.

Extends the lazy-transfer pattern of ``optim/tracking.py``: coordinate
descent pushes each update's tracker here as a bare reference — the
per-iteration loss/||g||/step ring buffers and per-entity RE outcome
arrays stay DEVICE arrays, so recording costs one list append and zero
syncs. :func:`drain` (called at RunReport build time, i.e. a phase
boundary) pays the host transfers in one batch, converts every tracker
to a JSON-safe dict, and empties the buffer.

Multi-process runs keep this per-process; the RunReport aggregation
(obs/aggregate.py) ships the drained host dicts to process 0 — no
collectives ride in the recording path.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List

from photon_tpu.obs import _config

_LOCK = threading.Lock()
# entries: {"kind", "coordinate", "tracker", "unix", **meta} — tracker is a
# live OptimizationStatesTracker / RandomEffectOptimizationTracker whose
# arrays may still be device-resident
_BUFFER: List[Dict[str, Any]] = []


def record(coordinate: str, tracker, **meta: Any) -> None:
    """Push one update's tracker (no-op when telemetry is off, no host
    sync ever — the tracker's arrays are adopted as-is)."""
    if tracker is None or not _config.enabled():
        return
    kind = ("random_effect" if hasattr(tracker, "reason_counts")
            else "states")
    with _LOCK:
        _BUFFER.append({"kind": kind, "coordinate": coordinate,
                        "tracker": tracker, "unix": time.time(), **meta})


def pending() -> int:
    with _LOCK:
        return len(_BUFFER)


def clear() -> None:
    with _LOCK:
        _BUFFER.clear()


def drain() -> Dict[str, List[Dict[str, Any]]]:
    """Convert + clear: {"trajectories": [...], "random_effects": [...]}.

    This is where device->host transfers happen — call it at phase
    boundaries only (RunReport build, end of fit), never inside a sweep.
    """
    with _LOCK:
        entries = list(_BUFFER)
        _BUFFER.clear()
    out: Dict[str, List[Dict[str, Any]]] = {
        "trajectories": [], "random_effects": []}
    for e in entries:
        base = {k: v for k, v in e.items() if k not in ("tracker", "kind")}
        try:
            base.update(e["tracker"].to_dict())
        except Exception as exc:  # a broken tracker must not kill a report
            base["error"] = repr(exc)
        if e["kind"] == "random_effect":
            out["random_effects"].append(base)
        else:
            out["trajectories"].append(base)
    return out
