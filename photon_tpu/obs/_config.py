"""Telemetry on/off switch — the zero-overhead-when-disabled gate.

Every obs recording path (spans, device-trace annotations, memory
sampling, RunReport emission) checks :func:`enabled` first and turns
into a no-op when telemetry is off. The metrics registry itself stays
live regardless (host-side counter bumps at cache-lookup/driver-phase
granularity, nowhere near a hot loop), but nothing is ever staged into
jitted code: device-side telemetry is carried as ordinary solver outputs
(``track_states`` ring buffers), never as ``io_callback``/``debug``
callbacks — ``scripts/check_no_host_sync.py`` enforces that statically.

Enable with ``PHOTON_TPU_TELEMETRY=1`` (any non-empty value other than
``0``/``false``/``off``), the drivers' ``--telemetry`` flag, or
``obs.configure(enabled=True)``.
"""

from __future__ import annotations

import os
from typing import Optional

ENV_FLAG = "PHOTON_TPU_TELEMETRY"

# tri-state: None = read the env var lazily; True/False = explicit override
_enabled: Optional[bool] = None


def _env_enabled() -> bool:
    raw = os.environ.get(ENV_FLAG, "").strip().lower()
    return bool(raw) and raw not in ("0", "false", "off", "no")


def enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return _env_enabled()


def configure(enabled: Optional[bool]) -> None:
    """Explicitly enable/disable telemetry; ``None`` reverts to the env."""
    global _enabled
    _enabled = enabled


def reset() -> None:
    """Forget the explicit override (tests)."""
    configure(None)
