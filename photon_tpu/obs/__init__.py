"""Unified telemetry: metrics registry, trace spans, memory watermarks,
device-resident solver telemetry, and the RunReport manifest.

The Spark-UI + ``Timed``/``OptimizationStatesTracker`` replacement
(reference: Photon ML debugs hundred-billion-coefficient GAME fits
through Spark's stage view; Snap ML's per-level pipeline accounting,
arXiv:1803.06333, is the design north star). One import surface::

    from photon_tpu import obs

    obs.configure(enabled=True)           # or PHOTON_TPU_TELEMETRY=1
    with obs.span("fit", configs=3):      # nested; Perfetto-exportable
        obs.metrics.counter("fits").inc()
    obs.write_trace("out/trace.json")     # chrome://tracing / Perfetto
    obs.write_run_report("out/runreport.json", driver="game-train")

Contracts:

  * **zero-overhead-when-disabled** — with telemetry off, ``span`` is two
    attribute writes, ``annotate`` returns a shared null context, memory
    sampling and solver recording return immediately; nothing is ever
    staged into jitted code either way (device series ride as ordinary
    solver outputs; ``scripts/check_no_host_sync.py`` enforces this).
  * **no collectives in hot paths** — multi-process aggregation happens
    once, at report time (obs/aggregate.py).
"""

from photon_tpu.obs._config import ENV_FLAG, configure, enabled
from photon_tpu.obs import memory
from photon_tpu.obs import solver as _solver_mod
from photon_tpu.obs.metrics import (
    MetricsRegistry,
    merge_snapshots,
    registry as metrics,
)
from photon_tpu.obs.spans import (
    annotate,
    chrome_trace_events,
    span,
    write_trace,
)

record_solver = _solver_mod.record
drain_solver_telemetry = _solver_mod.drain


def build_run_report(driver, mesh=None, extra=None):
    from photon_tpu.obs import report
    return report.build_run_report(driver, mesh=mesh, extra=extra)


def write_run_report(path, driver, mesh=None, extra=None, aggregate=False):
    from photon_tpu.obs import report
    return report.write_run_report(path, driver, mesh=mesh, extra=extra,
                                   aggregate=aggregate)


def validate_run_report(rep):
    from photon_tpu.obs import report
    return report.validate_run_report(rep)


def reset() -> None:
    """Clear every telemetry buffer and the enabled-override (tests)."""
    import sys as _sys

    from photon_tpu.obs import _config, spans
    _config.reset()
    metrics.clear()
    spans.clear()
    memory.clear()
    _solver_mod.clear()
    # windowed series + SLO verdicts: lazy (sys.modules) so offline
    # drivers that never touched them pay nothing here either
    for name in ("photon_tpu.obs.timeseries", "photon_tpu.obs.slo"):
        mod = _sys.modules.get(name)
        if mod is not None:
            mod.clear()


__all__ = [
    "ENV_FLAG", "configure", "enabled", "reset",
    "MetricsRegistry", "metrics", "merge_snapshots",
    "span", "annotate", "write_trace", "chrome_trace_events",
    "record_solver", "drain_solver_telemetry",
    "build_run_report", "write_run_report", "validate_run_report",
    "memory",
]
