"""Host + device memory watermark sampling, one sample per phase.

Host numbers come from ``/proc/self/status`` (VmRSS current, VmHWM
lifetime peak) with a ``resource.getrusage`` fallback; device numbers
from ``Device.memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use``
where the backend reports them — TPU does, CPU usually returns None).

Sampling is pulled, never pushed: :func:`record_phase` runs at top-level
span exit (phase boundaries) and at RunReport build time — a few /proc
reads per driver run, nothing per iteration, nothing inside jit.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, List, Optional

_LOCK = threading.Lock()
_PHASE_SAMPLES: Dict[str, Dict[str, Any]] = {}  # phase -> last sample


def host_memory() -> Dict[str, int]:
    """{"rss_bytes", "peak_rss_bytes"} for this process."""
    rss = peak = None
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                elif line.startswith("VmHWM:"):
                    peak = int(line.split()[1]) * 1024
    except OSError:
        pass
    if rss is None or peak is None:  # non-Linux fallback
        try:
            import resource
            ru = resource.getrusage(resource.RUSAGE_SELF)
            # ru_maxrss is KiB on Linux, bytes on macOS; Linux handled above
            peak = peak if peak is not None else ru.ru_maxrss * 1024
            rss = rss if rss is not None else peak
        except Exception:  # pragma: no cover - last resort
            rss = rss or 0
            peak = peak or 0
    return {"rss_bytes": int(rss), "peak_rss_bytes": int(peak)}


def device_memory() -> List[Dict[str, Any]]:
    """Per-local-device allocator stats; [] when jax isn't loaded or the
    backend doesn't report them. Never initializes a backend on its own
    (only reads stats if jax is already imported AND a backend exists)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    out: List[Dict[str, Any]] = []
    try:
        devices = jax.local_devices()
    except Exception:  # backend not initialized / unavailable
        return []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        out.append({
            "device": str(d),
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
        })
    return out


def sample() -> Dict[str, Any]:
    return {"unix": time.time(), "host": host_memory(),
            "devices": device_memory()}


def record_phase(phase: str) -> Optional[Dict[str, Any]]:
    """Store the watermark sample for a named phase (last sample wins:
    VmHWM / peak_bytes_in_use are lifetime-cumulative, so the sample at
    phase END is the watermark as of that phase)."""
    from photon_tpu.obs import _config
    if not _config.enabled():
        return None
    s = sample()
    with _LOCK:
        _PHASE_SAMPLES[phase] = s
    return s


def watermarks() -> Dict[str, Dict[str, Any]]:
    with _LOCK:
        return {k: dict(v) for k, v in _PHASE_SAMPLES.items()}


def clear() -> None:
    with _LOCK:
        _PHASE_SAMPLES.clear()
