"""Nested trace spans with Chrome-trace-event / Perfetto JSON export.

Subsumes ``utils/timing.Timed`` (which is now a shim over this module):
every span records wall-clock start/end, monotonic duration, thread and
nesting parent, and — when JAX is already loaded — wraps the body in a
``jax.profiler.TraceAnnotation`` so host spans line up with device
activity in a captured device trace (``--profile-dir``).

Zero-overhead-when-disabled: :class:`span` checks ``_config.enabled()``
once on ``__enter__`` and becomes two attribute writes when telemetry is
off — no clock reads, no list append, no profiler import.

Export: :func:`write_trace` emits ``{"traceEvents": [...]}`` with ``ph:
"X"`` complete events (ts/dur in microseconds), which chrome://tracing
and https://ui.perfetto.dev load directly.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from photon_tpu.obs import _config

_LOCK = threading.Lock()
_RECORDS: List[Dict[str, Any]] = []
_TLS = threading.local()  # per-thread span stack for nesting

# one trace epoch per process so ts values are comparable across threads
_EPOCH_PERF = time.perf_counter()
_EPOCH_UNIX = time.time()


def _stack() -> List["span"]:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _jax_annotation(name: str):
    """A jax.profiler.TraceAnnotation when jax is ALREADY imported (a
    telemetry span must never be the thing that pulls in the backend)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler unavailable
        return None


class span:
    """``with span("phase", key=value): ...`` — records one trace event.

    Nested use is encouraged: the enclosing span (same thread) becomes
    ``parent`` in the record, and Perfetto renders containment from the
    ts/dur intervals. Exceptions mark the record ``"error": true`` and
    propagate.
    """

    __slots__ = ("name", "attrs", "_on", "_t0", "_wall0", "_parent",
                 "_depth", "_ann", "seconds")

    def __init__(self, name: str, **attrs: Any):
        self.name = name
        self.attrs = attrs
        self._on = False
        self.seconds: Optional[float] = None

    def __enter__(self) -> "span":
        if not _config.enabled():
            return self
        self._on = True
        st = _stack()
        self._parent = st[-1].name if st else None
        self._depth = len(st)
        st.append(self)
        self._ann = _jax_annotation(self.name)
        if self._ann is not None:
            self._ann.__enter__()
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if not self._on:
            return
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        self.seconds = t1 - self._t0
        rec = {
            "name": self.name,
            "ts_us": (self._t0 - _EPOCH_PERF) * 1e6,
            "dur_us": self.seconds * 1e6,
            "start_unix": self._wall0,
            "end_unix": self._wall0 + self.seconds,
            "tid": threading.get_ident(),
            "parent": self._parent,
            "depth": self._depth,
        }
        if self.attrs:
            rec["args"] = dict(self.attrs)
        if exc_type is not None:
            rec["error"] = True
        with _LOCK:
            _RECORDS.append(rec)
        if self._depth == 0:
            # top-level phase boundary: sample memory watermarks here so
            # the RunReport gets per-phase host/device numbers without any
            # sampling inside nested (possibly hot) scopes
            from photon_tpu.obs import memory
            memory.record_phase(self.name)


def annotate(name: str):
    """Device-trace-only annotation for hot call sites: aligns a named
    region with device activity under ``jax.profiler`` without recording
    a host span (no lock, no list growth when called per CD update).
    Returns a no-op context when telemetry is off."""
    if not _config.enabled():
        return _NULL_CONTEXT
    ann = _jax_annotation(name)
    return ann if ann is not None else _NULL_CONTEXT


class _NullContext:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CONTEXT = _NullContext()


def current_span() -> Optional[str]:
    st = getattr(_TLS, "stack", None)
    return st[-1].name if st else None


def records() -> List[Dict[str, Any]]:
    """Snapshot of raw span records (report form: unix start/end + parent)."""
    with _LOCK:
        return [dict(r) for r in _RECORDS]


def clear() -> None:
    with _LOCK:
        _RECORDS.clear()


def chrome_trace_events() -> List[Dict[str, Any]]:
    """Chrome-trace ``ph: "X"`` complete events, Perfetto-loadable."""
    pid = os.getpid()
    events = []
    for r in records():
        ev = {
            "name": r["name"],
            "ph": "X",
            "ts": r["ts_us"],
            "dur": r["dur_us"],
            "pid": pid,
            "tid": r["tid"],
            "cat": "photon_tpu",
        }
        args = dict(r.get("args", {}))
        if r.get("parent"):
            args["parent"] = r["parent"]
        if args:
            ev["args"] = args
        events.append(ev)
    return events


def write_trace(path: str) -> str:
    """Write the span buffer as a Chrome-trace JSON file; returns path."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = {
        "displayTimeUnit": "ms",
        "metadata": {"trace_epoch_unix": _EPOCH_UNIX},
        "traceEvents": chrome_trace_events(),
    }
    with open(path, "w") as f:
        json.dump(payload, f)
    return path
