"""Multi-process telemetry aggregation to process 0.

A multi-host run produces one telemetry state per process; the RunReport
wants one manifest. This module gathers each process's JSON-safe payload
to process 0 with exactly TWO collectives (length allgather + padded
byte allgather), both issued at report-build time — hot paths stay
collective-free by construction, because nothing here is ever called
from inside a sweep or a jitted program.

Single-process runs short-circuit without touching the distributed
runtime at all.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional


def process_info() -> Dict[str, int]:
    """{"index", "count"} — (0, 1) when jax isn't initialized."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {"index": 0, "count": 1}
    try:
        return {"index": jax.process_index(), "count": jax.process_count()}
    except Exception:  # backend not initialized
        return {"index": 0, "count": 1}


def gather_payloads(payload: Dict[str, Any]) -> Optional[List[Dict[str, Any]]]:
    """Collective gather of one JSON-safe dict per process.

    Every process must call this (it is a collective). Returns the list of
    per-process payloads (index order) on process 0, ``None`` elsewhere.
    On a single process it returns ``[payload]`` without any collective.
    """
    info = process_info()
    if info["count"] == 1:
        return [payload]

    import jax
    import numpy as np
    from jax.experimental import multihost_utils

    data = np.frombuffer(json.dumps(payload).encode("utf-8"), np.uint8)
    lengths = multihost_utils.process_allgather(
        np.asarray([data.size], np.int64))
    lengths = np.asarray(lengths).ravel()
    width = int(lengths.max())
    padded = np.zeros((width,), np.uint8)
    padded[: data.size] = data
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    if jax.process_index() != 0:
        return None
    return [json.loads(bytes(gathered[p, : int(lengths[p])]).decode("utf-8"))
            for p in range(info["count"])]
