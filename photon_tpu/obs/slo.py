"""Declarative SLO specs evaluated per window over the timeseries.

An SLO here is a frozen rule object evaluated against a windowed
snapshot (``obs.timeseries.WindowedRegistry.snapshot()`` or the merged
multi-process dict from ``merge_snapshots``). Evaluation emits TYPED
verdict records — PASS / WARN / BREACH with the exact offending windows
— rather than a boolean, so a bench gate can assert not just "p99 was
fine" but "the breach was localized to the shard-kill windows and every
survivor window stayed PASS".

Rules:

  * :class:`P99Ceiling` — per-window p99 of a quantile series must stay
    under a ceiling, evaluated only in windows whose qps (a counter
    series over the same interval) meets a floor — idle windows with two
    stragglers don't count against the SLO.
  * :class:`MaxDegradationRate` — typed-degradation counter divided by a
    request counter per window must stay under a rate.
  * :class:`ZeroSteadyStateCompiles` — the post-warmup compile delta
    (from the existing three compile monitors) must be exactly zero;
    window-free, the whole run is one observation.

Verdict status: 0 offending windows → PASS; at most ``warn_windows``
offending → WARN (transients tolerated, e.g. the probation window right
after a live swap); more → BREACH.

``evaluate()`` also records every verdict in a module-level sink so the
RunReport's ``slo`` section picks them up; ``write_verdicts`` emits the
machine-readable verdict file bench gates and CI read.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

SCHEMA = "photon_tpu.slo.v1"

PASS = "PASS"
WARN = "WARN"
BREACH = "BREACH"


def _series_key(name: str, labels: Optional[Dict[str, str]]) -> str:
    from photon_tpu.obs.metrics import _label_items, _label_suffix
    return name + _label_suffix(_label_items(dict(labels or {})))


def _lookup(snapshot: dict, name: str,
            labels: Optional[Dict[str, str]]) -> Optional[dict]:
    return snapshot.get("timeseries", {}).get(_series_key(name, labels))


@dataclasses.dataclass(frozen=True)
class P99Ceiling:
    """Per-window p99 of ``series`` must stay <= ``ceiling_s`` in every
    window where ``qps_series`` (a windowed counter of requests) divided
    by the interval reaches ``qps_floor``."""

    rule_id: str
    series: str
    ceiling_s: float
    labels: Optional[Dict[str, str]] = None
    qps_series: Optional[str] = None
    qps_labels: Optional[Dict[str, str]] = None
    qps_floor: float = 0.0
    warn_windows: int = 0

    kind = "p99_ceiling"

    def evaluate(self, snapshot: dict, compile_delta=None) -> "Verdict":
        s = _lookup(snapshot, self.series, self.labels)
        qs = (_lookup(snapshot, self.qps_series, self.qps_labels or
                      self.labels) if self.qps_series else None)
        qps_by_idx: Dict[int, float] = {}
        if qs is not None:
            dt = float(qs.get("interval_s", 1.0)) or 1.0
            for w in qs.get("windows", []):
                qps_by_idx[int(w["idx"])] = float(w["value"]) / dt
        offending: List[dict] = []
        evaluated = 0
        for w in (s or {}).get("windows", []):
            idx = int(w["idx"])
            if self.qps_series is not None:
                if qps_by_idx.get(idx, 0.0) < self.qps_floor:
                    continue  # under the qps floor: window not judged
            p99 = w.get("p99")
            if p99 is None:
                continue
            evaluated += 1
            if float(p99) > self.ceiling_s:
                offending.append({"idx": idx, "value": float(p99),
                                  "limit": self.ceiling_s})
        return _verdict(self, evaluated, offending,
                        detail=f"p99 <= {self.ceiling_s:g}s"
                               + (f" @ qps >= {self.qps_floor:g}"
                                  if self.qps_series else ""))


@dataclasses.dataclass(frozen=True)
class MaxDegradationRate:
    """Per-window ``degraded_series / total_series`` must stay <=
    ``max_rate`` (windows with no traffic are skipped)."""

    rule_id: str
    degraded_series: str
    total_series: str
    max_rate: float
    degraded_labels: Optional[Dict[str, str]] = None
    total_labels: Optional[Dict[str, str]] = None
    warn_windows: int = 0

    kind = "max_degradation_rate"

    def evaluate(self, snapshot: dict, compile_delta=None) -> "Verdict":
        deg = _lookup(snapshot, self.degraded_series, self.degraded_labels)
        tot = _lookup(snapshot, self.total_series, self.total_labels)
        deg_by_idx = {int(w["idx"]): float(w["value"])
                      for w in (deg or {}).get("windows", [])}
        offending: List[dict] = []
        evaluated = 0
        for w in (tot or {}).get("windows", []):
            idx, total = int(w["idx"]), float(w["value"])
            if total <= 0:
                continue
            evaluated += 1
            rate = deg_by_idx.get(idx, 0.0) / total
            if rate > self.max_rate:
                offending.append({"idx": idx, "value": rate,
                                  "limit": self.max_rate})
        return _verdict(self, evaluated, offending,
                        detail=f"degradation rate <= {self.max_rate:g}")


@dataclasses.dataclass(frozen=True)
class ZeroSteadyStateCompiles:
    """The post-warmup compile delta must be exactly zero. Window-free:
    the caller passes ``compile_delta`` — the summed delta from the three
    existing compile monitors (steady-state compile events, jitcache
    misses, per-program ``_cache_size`` growth)."""

    rule_id: str
    warn_windows: int = 0  # always 0-tolerance; kept for shape uniformity

    kind = "zero_steady_state_compiles"

    def evaluate(self, snapshot: dict,
                 compile_delta: Optional[float] = None) -> "Verdict":
        if compile_delta is None:
            return Verdict(rule_id=self.rule_id, kind=self.kind,
                           status=WARN, windows_evaluated=0,
                           offending_windows=[],
                           detail="compile_delta not provided")
        offending = ([] if compile_delta == 0 else
                     [{"idx": -1, "value": float(compile_delta),
                       "limit": 0.0}])
        return _verdict(self, 1, offending,
                        detail="steady-state compile delta == 0")


SLORule = (P99Ceiling, MaxDegradationRate, ZeroSteadyStateCompiles)


@dataclasses.dataclass(frozen=True)
class Verdict:
    rule_id: str
    kind: str
    status: str
    windows_evaluated: int
    offending_windows: List[dict]
    detail: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _verdict(rule, evaluated: int, offending: List[dict],
             detail: str) -> Verdict:
    if not offending:
        status = PASS
    elif len(offending) <= rule.warn_windows:
        status = WARN
    else:
        status = BREACH
    return Verdict(rule_id=rule.rule_id, kind=rule.kind, status=status,
                   windows_evaluated=evaluated,
                   offending_windows=offending, detail=detail)


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    rules: Tuple[object, ...]

    def __init__(self, rules: Sequence[object]):
        object.__setattr__(self, "rules", tuple(rules))


_lock = threading.Lock()
_verdicts: List[Verdict] = []


def evaluate(spec: SLOSpec, snapshot: dict,
             compile_delta: Optional[float] = None,
             record: bool = True) -> List[Verdict]:
    """Evaluate every rule against a windowed snapshot. ``record=True``
    (default) also appends the verdicts to the module sink the RunReport
    ``slo`` section reads."""
    out = [rule.evaluate(snapshot, compile_delta=compile_delta)
           for rule in spec.rules]
    if record:
        with _lock:
            _verdicts.extend(out)
    return out


def recorded_verdicts() -> List[Verdict]:
    with _lock:
        return list(_verdicts)


def clear() -> None:
    with _lock:
        _verdicts.clear()


def worst_status(verdicts: Sequence[Verdict]) -> str:
    order = {PASS: 0, WARN: 1, BREACH: 2}
    worst = PASS
    for v in verdicts:
        if order.get(v.status, 2) > order[worst]:
            worst = v.status
    return worst


def write_verdicts(path, verdicts: Sequence[Verdict]) -> dict:
    """Machine-readable verdict file: schema id, worst status, one typed
    record per rule. Written atomically when resilience.io is available."""
    doc = {"schema": SCHEMA,
           "status": worst_status(verdicts),
           "verdicts": [v.to_json() for v in verdicts]}
    blob = json.dumps(doc, indent=1, sort_keys=True).encode() + b"\n"
    try:
        from photon_tpu.resilience import io as rio
        rio.atomic_write_bytes(str(path), blob)
    except Exception:
        with open(path, "wb") as f:
            f.write(blob)
    return doc


def report_section() -> Optional[dict]:
    """The RunReport ``slo`` section; None while nothing was evaluated."""
    with _lock:
        if not _verdicts:
            return None
        return {"status": worst_status(_verdicts),
                "verdicts": [v.to_json() for v in _verdicts]}
