"""Process-wide metrics registry: counters, gauges, histograms, labels.

The Spark-UI-counters replacement (reference: Photon ML leans on Spark's
stage/task metrics for pipeline accounting). One process-wide
:data:`registry` instance backs every subsystem — jit/compile caches,
coordinate descent, the drivers — and exports two ways:

  * ``to_json()``   — nested snapshot for the RunReport manifest;
  * ``to_prometheus_text()`` — the Prometheus text exposition format, so
    a sidecar can scrape a dumped file without any client library.

All operations take one lock; increments are host-side and happen at
cache-lookup/phase granularity, never inside jitted code.
"""

from __future__ import annotations

import bisect
import json
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Prometheus-style default buckets, extended upward for compile times
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

LabelItems = Tuple[Tuple[str, str], ...]


def _label_items(labels: Dict[str, str]) -> LabelItems:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_suffix(items: LabelItems) -> str:
    if not items:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in items)
    return "{" + inner + "}"


class Counter:
    """Monotone sum. ``inc`` only (negative deltas rejected)."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self.value = 0.0

    def inc(self, delta: float = 1.0) -> None:
        if delta < 0:
            raise ValueError(f"counter increments must be >= 0, got {delta}")
        with self._lock:
            self.value += delta


class Gauge:
    """Last-write-wins scalar, with a convenience ``max`` for watermarks."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def max(self, value: float) -> None:
        with self._lock:
            self.value = max(self.value, float(value))


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: le upper bounds
    plus an implicit +Inf bucket; ``sum``/``count`` ride along)."""

    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, lock: threading.RLock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self._lock = lock
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))
        self.counts: List[int] = [0] * (len(self.buckets) + 1)  # +Inf last
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self.counts[bisect.bisect_left(self.buckets, value)] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            return bucket_quantile(self.buckets, self.counts, q)


def bucket_quantile(buckets: Sequence[float], counts: Sequence[int],
                    q: float) -> Optional[float]:
    """Prometheus-style estimated quantile: find the bucket holding rank
    q*count, interpolate linearly inside it (lower bound 0 for the first
    bucket; the +Inf bucket clamps to the last finite bound). None when
    empty. Estimation error is bounded by bucket width — pick latency
    buckets accordingly (serving uses ~1.3x geometric steps)."""
    total = sum(counts)
    if total == 0:
        return None
    target = q * total
    cumulative = 0
    for i, c in enumerate(counts):
        prev = cumulative
        cumulative += c
        if cumulative >= target and c > 0:
            if i >= len(buckets):            # +Inf bucket
                return float(buckets[-1]) if buckets else None
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            frac = (target - prev) / c
            return float(lo + (hi - lo) * frac)
    return float(buckets[-1]) if buckets else None


class MetricsRegistry:
    """Thread-safe name+labels -> metric registry.

    The first registration of a name fixes its kind; re-registering the
    same (name, labels) returns the same instance, so call sites can do
    ``registry.counter("jitcache.hits").inc()`` on every event.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[Tuple[str, LabelItems], object] = {}
        self._kinds: Dict[str, str] = {}

    def _get(self, kind: str, name: str, labels: Dict[str, str], factory):
        key = (name, _label_items(labels))
        with self._lock:
            existing_kind = self._kinds.get(name)
            if existing_kind is not None and existing_kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {existing_kind}, "
                    f"requested {kind}")
            metric = self._metrics.get(key)
            if metric is None:
                metric = self._metrics[key] = factory()
                self._kinds[name] = kind
            return metric

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get("counter", name, labels,
                         lambda: Counter(self._lock))

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get("gauge", name, labels, lambda: Gauge(self._lock))

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels: str) -> Histogram:
        return self._get("histogram", name, labels,
                         lambda: Histogram(self._lock, buckets))

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._kinds.clear()

    # -- export ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} with
        ``name{label="v"}`` keys — the RunReport's ``metrics`` section."""
        out: Dict[str, Dict[str, object]] = {
            "counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            items = list(self._metrics.items())
        for (name, labels), metric in sorted(items, key=lambda kv: kv[0]):
            key = name + _label_suffix(labels)
            if isinstance(metric, Counter):
                out["counters"][key] = metric.value
            elif isinstance(metric, Gauge):
                out["gauges"][key] = metric.value
            else:
                assert isinstance(metric, Histogram)
                h = {
                    "buckets": list(metric.buckets),
                    "counts": list(metric.counts),
                    "sum": metric.sum,
                    "count": metric.count,
                }
                if metric.count:
                    for name_q, q in (("p50", 0.5), ("p95", 0.95),
                                      ("p99", 0.99)):
                        h[name_q] = bucket_quantile(h["buckets"],
                                                    h["counts"], q)
                out["histograms"][key] = h
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format (one ``# TYPE`` per family)."""
        with self._lock:
            items = sorted(self._metrics.items(), key=lambda kv: kv[0])
            kinds = dict(self._kinds)
        lines: List[str] = []
        seen_type: set = set()

        def prom_name(name: str) -> str:
            return name.replace(".", "_").replace("-", "_").replace("/", "_")

        for (name, labels), metric in items:
            pname = prom_name(name)
            if name not in seen_type:
                seen_type.add(name)
                lines.append(f"# TYPE {pname} {kinds[name]}")
            suffix = _label_suffix(labels)
            if isinstance(metric, (Counter, Gauge)):
                lines.append(f"{pname}{suffix} {metric.value}")
            else:
                assert isinstance(metric, Histogram)
                cumulative = 0
                for le, c in zip(metric.buckets, metric.counts):
                    cumulative += c
                    le_items = labels + (("le", repr(float(le))),)
                    lines.append(
                        f"{pname}_bucket{_label_suffix(le_items)} {cumulative}")
                cumulative += metric.counts[-1]
                inf_items = labels + (("le", "+Inf"),)
                lines.append(
                    f"{pname}_bucket{_label_suffix(inf_items)} {cumulative}")
                lines.append(f"{pname}_sum{suffix} {metric.sum}")
                lines.append(f"{pname}_count{suffix} {metric.count}")
        return "\n".join(lines) + ("\n" if lines else "")


# process-wide default registry: every subsystem records here
registry = MetricsRegistry()


def merge_snapshots(snapshots: Iterable[Dict[str, Dict[str, object]]]
                    ) -> Dict[str, Dict[str, object]]:
    """Merge per-process ``snapshot()`` dicts into one cluster view:
    counters sum, gauges take the max (they are used as watermarks/flags),
    histograms sum bucket-wise when bucket layouts agree (first layout
    wins otherwise). Snapshots carrying a ``timeseries`` section
    (obs/timeseries.py WindowedRegistry.snapshot()) merge those series
    window-by-window too, and the output gains a ``timeseries`` section
    only in that case — plain MetricsRegistry merges keep the old shape.
    Used by the RunReport's process-0 aggregation — runs once at report
    time, never in a hot path."""
    out: Dict[str, Dict[str, object]] = {
        "counters": {}, "gauges": {}, "histograms": {}}
    ts_groups: Dict[str, list] = {}
    for snap in snapshots:
        for k, s in snap.get("timeseries", {}).items():
            ts_groups.setdefault(k, []).append(s)
        for k, v in snap.get("counters", {}).items():
            out["counters"][k] = out["counters"].get(k, 0.0) + v
        for k, v in snap.get("gauges", {}).items():
            out["gauges"][k] = max(out["gauges"].get(k, float("-inf")), v)
        for k, h in snap.get("histograms", {}).items():
            cur = out["histograms"].get(k)
            if cur is None:
                out["histograms"][k] = {
                    "buckets": list(h["buckets"]), "counts": list(h["counts"]),
                    "sum": h["sum"], "count": h["count"]}
            elif list(cur["buckets"]) == list(h["buckets"]):
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], h["counts"])]
                cur["sum"] += h["sum"]
                cur["count"] += h["count"]
    for h in out["histograms"].values():
        if h["count"]:  # cluster-level quantiles over the merged buckets
            for name_q, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
                h[name_q] = bucket_quantile(h["buckets"], h["counts"], q)
    if ts_groups:
        from photon_tpu.obs import timeseries as _ts  # lazy: avoid cycle
        out["timeseries"] = {k: _ts.merge_series(v)
                             for k, v in sorted(ts_groups.items())}
    return out
