"""Per-feature summary statistics.

Reference: photon-lib stat/FeatureDataStatistics.scala:44,59 (mean,
variance, count, min, max, numNonzeros via the spark.ml summarizer) —
feeds NormalizationContext building and the persisted feature summaries.

Computed in one jitted pass over the (possibly sharded) feature matrix;
implicit zeros of sparse rows are accounted for exactly.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from photon_tpu.ops import features as F

Array = jax.Array


class FeatureDataStatistics(NamedTuple):
    count: int
    mean: Array          # [d]
    variance: Array      # [d] (sample variance, ddof=1, as spark.ml)
    min: Array           # [d]
    max: Array           # [d]
    num_nonzeros: Array  # [d]
    abs_max: Array       # [d]

    @property
    def dim(self) -> int:
        return self.mean.shape[0]


def _sparse_stats(x: F.SparseFeatures, dim: int, weights=None):
    n = x.values.shape[0]
    idx = x.indices.ravel()
    val = x.values.ravel()
    # pad slots are (0, 0.0): they contribute 0 to sums and counts
    sums = jnp.zeros((dim,), val.dtype).at[idx].add(val)
    sq_sums = jnp.zeros((dim,), val.dtype).at[idx].add(val * val)
    nnz = jnp.zeros((dim,), jnp.int32).at[idx].add((val != 0).astype(jnp.int32))
    maxs = jnp.full((dim,), -jnp.inf, val.dtype).at[idx].max(
        jnp.where(val != 0, val, -jnp.inf))
    mins = jnp.full((dim,), jnp.inf, val.dtype).at[idx].min(
        jnp.where(val != 0, val, jnp.inf))
    # features with implicit zeros include 0 in their min/max
    has_zero = nnz < n
    maxs = jnp.where(has_zero, jnp.maximum(maxs, 0.0), maxs)
    mins = jnp.where(has_zero, jnp.minimum(mins, 0.0), mins)
    return n, sums, sq_sums, nnz, mins, maxs


def _dense_stats(x: Array):
    n = x.shape[0]
    sums = jnp.sum(x, axis=0)
    sq_sums = jnp.sum(x * x, axis=0)
    nnz = jnp.sum(x != 0, axis=0).astype(jnp.int32)
    mins = jnp.min(x, axis=0)
    maxs = jnp.max(x, axis=0)
    return n, sums, sq_sums, nnz, mins, maxs


def compute_feature_stats(x: F.FeatureMatrix, dim: int) -> FeatureDataStatistics:
    if isinstance(x, F.SparseFeatures):
        n, sums, sq_sums, nnz, mins, maxs = _sparse_stats(x, dim)
    else:
        n, sums, sq_sums, nnz, mins, maxs = _dense_stats(x)
    nf = jnp.asarray(float(n), sums.dtype)
    mean = sums / nf
    # sample variance with ddof=1 (spark.ml summarizer semantics)
    var = jnp.maximum(sq_sums - nf * mean * mean, 0.0) / jnp.maximum(nf - 1.0, 1.0)
    return FeatureDataStatistics(
        count=n, mean=mean, variance=var, min=mins, max=maxs,
        num_nonzeros=nnz, abs_max=jnp.maximum(jnp.abs(mins), jnp.abs(maxs)),
    )
