"""Row-level input validation per training task.

Reference: photon-client data/DataValidators.scala:32 — per-task rule
sets (finite features/offsets/weights, non-negative weights, binary
labels for classifiers, non-negative labels for Poisson), with
DataValidationType modes VALIDATE_FULL (report every violation),
VALIDATE_SAMPLE (check a sample), VALIDATE_DISABLED
(data/DataValidationType.scala).

Vectorized over the columnar GameDataFrame — each rule is one numpy
reduction instead of a per-row closure.
"""

from __future__ import annotations

import enum
import logging
from typing import Dict, List

import numpy as np

from photon_tpu.game.dataset import GameDataFrame
from photon_tpu.types import TaskType

logger = logging.getLogger(__name__)


class DataValidationType(enum.Enum):
    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"


SAMPLE_FRACTION = 0.1  # VALIDATE_SAMPLE checks this fraction


class DataValidationError(ValueError):
    def __init__(self, violations: Dict[str, int]):
        self.violations = violations
        super().__init__(f"input data failed validation: {violations}")


def _row_mask(df: GameDataFrame, validation: DataValidationType) -> np.ndarray:
    n = df.num_samples
    if validation == DataValidationType.VALIDATE_SAMPLE:
        # deterministic sample (validation must not flake across retries)
        step = max(int(1 / SAMPLE_FRACTION), 1)
        mask = np.zeros(n, bool)
        mask[::step] = True
        return mask
    return np.ones(n, bool)


def validate_dataframe(
    df: GameDataFrame,
    task: TaskType,
    validation: DataValidationType = DataValidationType.VALIDATE_FULL,
) -> None:
    """Raise DataValidationError on any violated rule (reference:
    DataValidators.sanityCheckDataFrameForTraining)."""
    if validation == DataValidationType.VALIDATE_DISABLED:
        return
    mask = _row_mask(df, validation)
    violations: Dict[str, int] = {}

    def check(name: str, ok: np.ndarray):
        bad = int(np.sum(~ok & mask))
        if bad:
            violations[name] = bad

    y = np.asarray(df.response, float)
    check("finite labels", np.isfinite(y))
    if task == TaskType.POISSON_REGRESSION:
        check("non-negative labels (Poisson)", y >= 0)
    if task.is_classification:
        check("binary labels", (y == 0.0) | (y == 1.0))
    if df.offsets is not None:
        check("finite offsets", np.isfinite(np.asarray(df.offsets, float)))
    if df.weights is not None:
        w = np.asarray(df.weights, float)
        check("finite weights", np.isfinite(w))
        check("positive weights", w > 0)

    checked_rows = np.flatnonzero(mask)
    for sid, shard in df.feature_shards.items():
        ok = np.ones(df.num_samples, bool)
        if shard.is_dense:
            ok[checked_rows] = np.isfinite(
                np.asarray(shard.rows, float)[checked_rows]).all(axis=1)
        else:
            # only visit sampled rows — VALIDATE_SAMPLE must cost a sample
            for i in checked_rows:
                ok[i] = bool(np.isfinite(
                    np.asarray(shard.rows[i][1], float)).all())
        check(f"finite features [{sid}]", ok)

    if violations:
        raise DataValidationError(violations)
    logger.info("data validation passed (%s rows, mode %s)",
                int(mask.sum()), validation.value)
