"""Row-level input validation per training task.

Reference: photon-client data/DataValidators.scala:32 — per-task rule
sets (finite features/offsets/weights, non-negative weights, binary
labels for classifiers, non-negative labels for Poisson), with
DataValidationType modes VALIDATE_FULL (report every violation),
VALIDATE_SAMPLE (check a sample), VALIDATE_DISABLED
(data/DataValidationType.scala).

Vectorized over the columnar GameDataFrame — each rule is one numpy
reduction instead of a per-row closure.
"""

from __future__ import annotations

import enum
import logging
from typing import Dict, List

import numpy as np

from photon_tpu.game.dataset import GameDataFrame
from photon_tpu.types import TaskType

logger = logging.getLogger(__name__)


class DataValidationType(enum.Enum):
    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"


SAMPLE_FRACTION = 0.1  # VALIDATE_SAMPLE checks this fraction


class DataValidationError(ValueError):
    def __init__(self, violations: Dict[str, int]):
        self.violations = violations
        super().__init__(f"input data failed validation: {violations}")


def _scalar_rules(y: np.ndarray, task: TaskType, offsets, weights):
    """(name, ok-mask) pairs for the row-local scalar rules. Shared by the
    resident frame validator and the per-chunk streaming mask so the two
    paths cannot drift apart (surviving-row chunk assignment depends on
    both applying byte-identical rules)."""
    yield "finite labels", np.isfinite(y)
    if task == TaskType.POISSON_REGRESSION:
        yield "non-negative labels (Poisson)", y >= 0
    if task.is_classification:
        yield "binary labels", (y == 0.0) | (y == 1.0)
    if offsets is not None:
        yield "finite offsets", np.isfinite(np.asarray(offsets, float))
    if weights is not None:
        w = np.asarray(weights, float)
        yield "finite weights", np.isfinite(w)
        yield "positive weights", w > 0


def invalid_chunk_mask(labels, task: TaskType, offsets=None, weights=None,
                       feature_values=None) -> np.ndarray:
    """Row-local drop mask for ONE streaming chunk (True = invalid).

    Applies exactly the rules ``validate_dataframe(...,
    drop_invalid_rows=True)`` applies in VALIDATE_FULL mode — every rule
    here is row-local, so filtering chunk-by-chunk keeps the surviving
    rows (and therefore their chunk assignment after survivor packing)
    identical to filtering the fully-resident dataset up front.

    ``feature_values`` is whatever per-row value slab is finite-checkable:
    a dense ``[rows, dim]`` block or a padded-ELL ``[rows, max_nnz]``
    values array (pad slots are zero, hence finite)."""
    y = np.asarray(labels, float)
    bad = np.zeros(y.shape[0], bool)
    for _name, ok in _scalar_rules(y, task, offsets, weights):
        bad |= ~ok
    if feature_values is not None:
        vals = np.asarray(feature_values, float)
        bad |= ~np.isfinite(vals).all(axis=tuple(range(1, vals.ndim)))
    return bad


def _row_mask(df: GameDataFrame, validation: DataValidationType) -> np.ndarray:
    n = df.num_samples
    if validation == DataValidationType.VALIDATE_SAMPLE:
        # deterministic sample (validation must not flake across retries)
        step = max(int(1 / SAMPLE_FRACTION), 1)
        mask = np.zeros(n, bool)
        mask[::step] = True
        return mask
    return np.ones(n, bool)


def validate_dataframe(
    df: GameDataFrame,
    task: TaskType,
    validation: DataValidationType = DataValidationType.VALIDATE_FULL,
    drop_invalid_rows: bool = False,
) -> GameDataFrame:
    """Validate and return the frame (reference:
    DataValidators.sanityCheckDataFrameForTraining).

    Default: raise DataValidationError listing per-rule violation counts.
    With ``drop_invalid_rows``, rows failing ANY rule are filtered out
    instead (a new frame is returned; the drop count is logged and
    reported through obs metrics + the resilience failure trail). Only
    rows the mode actually checked are dropped — VALIDATE_SAMPLE cannot
    vouch for the rows it skipped."""
    if validation == DataValidationType.VALIDATE_DISABLED:
        return df
    mask = _row_mask(df, validation)
    violations: Dict[str, int] = {}
    bad_rows = np.zeros(df.num_samples, bool)

    def check(name: str, ok: np.ndarray):
        bad = ~ok & mask
        n_bad = int(np.sum(bad))
        if n_bad:
            violations[name] = n_bad
            np.logical_or(bad_rows, bad, out=bad_rows)

    y = np.asarray(df.response, float)
    for name, ok in _scalar_rules(y, task, df.offsets, df.weights):
        check(name, ok)

    checked_rows = np.flatnonzero(mask)
    for sid, shard in df.feature_shards.items():
        ok = np.ones(df.num_samples, bool)
        if shard.is_dense:
            ok[checked_rows] = np.isfinite(
                np.asarray(shard.rows, float)[checked_rows]).all(axis=1)
        else:
            # only visit sampled rows — VALIDATE_SAMPLE must cost a sample
            for i in checked_rows:
                ok[i] = bool(np.isfinite(
                    np.asarray(shard.rows[i][1], float)).all())
        check(f"finite features [{sid}]", ok)

    if violations:
        if not drop_invalid_rows:
            raise DataValidationError(violations)
        df = _drop_rows(df, bad_rows)
        n_dropped = int(bad_rows.sum())
        logger.warning("data validation dropped %d invalid row(s): %s",
                       n_dropped, violations)
        try:
            from photon_tpu.obs.metrics import registry
            registry.counter("data.invalid_rows_dropped").inc(n_dropped)
        except Exception:  # pragma: no cover - telemetry must not fail
            logger.debug("metrics emission failed", exc_info=True)
        from photon_tpu.resilience import failures
        failures.record_failure("invalid_rows_dropped", rows=n_dropped,
                                violations=dict(violations))
        return df
    logger.info("data validation passed (%s rows, mode %s)",
                int(mask.sum()), validation.value)
    return df


def _drop_rows(df: GameDataFrame, bad_rows: np.ndarray) -> GameDataFrame:
    """New GameDataFrame with the flagged rows filtered from every
    columnar container (response/offsets/weights/id_tags/feature shards,
    in all three shard storage forms)."""
    from photon_tpu.game.dataset import CsrRows, FeatureShard

    keep = ~bad_rows
    keep_idx = np.flatnonzero(keep)

    shards: Dict[str, FeatureShard] = {}
    for sid, shard in df.feature_shards.items():
        if shard.is_dense:
            rows = np.asarray(shard.rows)[keep]
        elif isinstance(shard.rows, CsrRows):
            nnz = shard.rows.row_nnz()
            el = np.repeat(keep, nnz)
            indptr = np.zeros(len(keep_idx) + 1, np.int64)
            np.cumsum(nnz[keep], out=indptr[1:])
            rows = CsrRows(indptr, shard.rows.cols[el], shard.rows.vals[el])
        else:
            rows = [shard.rows[i] for i in keep_idx]
        shards[sid] = FeatureShard(rows=rows, dim=shard.dim)

    def take(col):
        return None if col is None else np.asarray(col)[keep]

    return GameDataFrame(
        num_samples=int(keep.sum()),
        response=np.asarray(df.response)[keep],
        feature_shards=shards,
        offsets=take(df.offsets),
        weights=take(df.weights),
        id_tags={tag: list(np.asarray(vals, dtype=object)[keep])
                 for tag, vals in df.id_tags.items()},
    )
