"""Down-sampling as static-shape masked reweighting.

Reference: sampling/DownSampler.scala:50, BinaryClassificationDownSampler
.scala:28-50 (keep positives, sample negatives at rate r, reweight kept
negatives by 1/r), DefaultDownSampler (uniform sample + reweight),
DownSamplerHelper.buildFactory.

On TPU we never filter (dynamic shapes): dropped samples get weight 0, kept
down-sampled ones get weight/rate — expectation-preserving, identical to
the reference's semantics. Determinism under recompute is free: the mask is
a pure function of the PRNG key (the reference needs byteswap64 seeding
tricks for this — RandomEffectDataset.scala:212-215).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import DataBatch
from photon_tpu.types import TaskType

Array = jax.Array


def _weights_of(batch: DataBatch) -> Array:
    if batch.weights is not None:
        return batch.weights
    return jnp.ones_like(batch.labels)


def downsample_default(batch: DataBatch, rate: float, key: jax.Array) -> DataBatch:
    """Uniform down-sample at ``rate``, reweighting kept samples by 1/rate."""
    keep = jax.random.uniform(key, batch.labels.shape) < rate
    w = _weights_of(batch) * jnp.where(keep, 1.0 / rate, 0.0)
    return batch._replace(weights=w)


def downsample_binary(batch: DataBatch, rate: float, key: jax.Array) -> DataBatch:
    """Keep all positives; sample negatives at ``rate`` and reweight them by
    1/rate (reference: BinaryClassificationDownSampler.scala:28-50)."""
    pos = batch.labels > 0.5
    keep_neg = jax.random.uniform(key, batch.labels.shape) < rate
    w = _weights_of(batch) * jnp.where(pos, 1.0, jnp.where(keep_neg, 1.0 / rate, 0.0))
    return batch._replace(weights=w)


def downsampler_for_task(task: TaskType):
    """Reference: DownSamplerHelper.buildFactory — binary tasks get the
    class-aware sampler."""
    return downsample_binary if task.is_classification else downsample_default


def maybe_downsample(batch: DataBatch, task: TaskType, rate: float,
                     key: jax.Array) -> DataBatch:
    if rate >= 1.0 or rate <= 0.0:
        return batch
    return downsampler_for_task(task)(batch, rate, key)
