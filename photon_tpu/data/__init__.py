"""Host-side data containers, ingestion and the out-of-core streaming
loader."""

from photon_tpu.data.dataset import DataBatch  # noqa: F401
from photon_tpu.data.streaming import (  # noqa: F401
    ChunkLoader,
    ensure_aligned,
    CsrSource,
    DenseSource,
    StreamConfig,
    StreamStats,
)
