"""Core device-resident dataset containers.

Reference: photon-lib data/LabeledPoint.scala:62 (label, features, offset,
weight; margin = x.theta + offset) and data/DataPoint.scala. On TPU a
"dataset" is a struct-of-arrays batch with static shapes; a whole Spark
RDD[LabeledPoint] becomes one (possibly batch-sharded) DataBatch.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from photon_tpu.ops import features as F

Array = jax.Array


class DataBatch(NamedTuple):
    """Struct-of-arrays equivalent of RDD[LabeledPoint].

    ``offsets`` also carries coordinate-descent residual scores: the
    reference's ``Dataset.addScoresToOffsets`` becomes plain addition here.
    """

    features: F.FeatureMatrix
    labels: Array                      # [n]
    offsets: Optional[Array] = None    # [n]
    weights: Optional[Array] = None    # [n]

    @property
    def num_samples(self) -> int:
        return F.num_samples(self.features)

    def with_offsets(self, offsets: Optional[Array]) -> "DataBatch":
        return self._replace(offsets=offsets)

    def add_scores_to_offsets(self, scores: Array) -> "DataBatch":
        """Reference: Dataset.addScoresToOffsets — residual injection for
        coordinate descent (FixedEffectDataset.scala:40)."""
        base = self.offsets if self.offsets is not None else jnp.zeros_like(scores)
        return self._replace(offsets=base + scores)

    def total_weight(self) -> Array:
        if self.weights is None:
            return jnp.asarray(float(self.num_samples), dtype=self.labels.dtype)
        return jnp.sum(self.weights)

    def row_slice(self, start: int, stop: int) -> "DataBatch":
        """Static row window [start, stop) of every per-sample leaf
        (dense or padded-ELL features) — the resident-side chunking
        primitive the streaming parity tests and bench compare against."""
        def cut(a):
            return None if a is None else a[start:stop]
        if isinstance(self.features, F.SparseFeatures):
            feats = F.SparseFeatures(
                indices=self.features.indices[start:stop],
                values=self.features.values[start:stop])
        else:
            feats = self.features[start:stop]
        return DataBatch(features=feats, labels=self.labels[start:stop],
                         offsets=cut(self.offsets), weights=cut(self.weights))
