"""Out-of-core streaming ingest: double-buffered host->device chunk pipeline.

Photon ML's Spark runtime streams training data from disk through
executors, so dataset size never bounds a fit; the TPU rebuild held every
shard in device memory. This module removes that assumption with the
pipeline shape of Snap ML (PAPERS.md): a fixed pool of pow2-shaped host
staging buffers filled by a reader thread, with the device transfer of
chunk k+1 dispatched while the consumer computes on chunk k.

Invariants the rest of the system builds on:

- **Static chunk shape.** Every chunk is exactly ``chunk_rows`` rows
  (rounded up to a power of two); the tail is zero-padded with weight-0
  rows. One jitted per-chunk program therefore serves the entire stream.
- **Deterministic chunk order.** Chunks are emitted in ascending raw-row
  order, always — there is no shuffling and no reader-side reordering, so
  two runs over the same source produce bitwise-identical chunk
  sequences (the foundation of the streamed solver's run-to-run and
  kill/resume bitwise guarantees).
- **Filter-stable chunk assignment.** With ``drop_invalid``, rows are
  filtered per raw block by ``validators.invalid_chunk_mask`` (the same
  row-local rules the resident validator applies) and survivors are
  packed densely across chunk boundaries — surviving row i lands in
  chunk i // chunk_rows exactly as it would after filtering the resident
  dataset up front.
- **Bounded staging memory.** Host-side memory is ``num_buffers`` staging
  buffers plus one raw block; device-side memory is at most the chunks
  in flight through the bounded queue. Neither scales with dataset size.
- **Safe buffer recycling.** A staging buffer is reused only after the
  reader has fenced the consumer out of it — on the reader thread,
  never the consumer's per-chunk path. In copy mode (any accelerator,
  or any meshed run) the fence is ``block_until_ready`` on the prior
  device arrays: once the DMA copy lands, the staging memory is free.
  On unmeshed CPU backends the loader instead *aliases* the staging
  buffers into device arrays via dlpack (zero-copy — ``device_put`` on
  CPU is a slow single-threaded memcpy that would triple host traffic),
  and the fence becomes a **consumption token**: an async consumer
  calls ``loader.release(chunk, token)`` with an output of the
  computation that read the chunk (the streamed solver passes the new
  carry), and the reader blocks on that token before refilling the
  buffer. Consumers that read chunks synchronously need nothing — the
  generator auto-releases a chunk when the next one is requested.

Chaos hooks: ``chaos.chunk_read_delay`` (slow disk) and
``chaos.chunk_read_error`` (transient read failure, retried under the
``resilience/retry`` env knobs) fire inside the reader thread, so fault
injection exercises the real overlap path.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Iterator, List, NamedTuple, Optional

import numpy as np

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import features as F
from photon_tpu.resilience import chaos
from photon_tpu.resilience.retry import RetryPolicy, with_retries
from photon_tpu.types import TaskType


class RawBlock(NamedTuple):
    """One raw block read from a ChunkSource (host numpy, row-major).

    Dense sources fill ``x`` [rows, dim]; sparse sources fill the
    padded-ELL pair ``idx``/``val`` [rows, ell_width]. ``weights`` and
    ``offsets`` are optional per-row columns.
    """

    labels: np.ndarray
    x: Optional[np.ndarray] = None
    idx: Optional[np.ndarray] = None
    val: Optional[np.ndarray] = None
    offsets: Optional[np.ndarray] = None
    weights: Optional[np.ndarray] = None

    @property
    def rows(self) -> int:
        return int(self.labels.shape[0])


class DenseSource:
    """Dense [n, dim] design matrix (ndarray or np.memmap) as a chunk
    source. ``read_block`` returns views; the loader either copies them
    into its staging buffers or (zero-copy mode, full aligned chunks)
    publishes the views directly, so a memmapped X streams from disk
    without ever materializing in RAM beyond one block. The source
    arrays are assumed immutable for the lifetime of the stream."""

    def __init__(self, X, labels, offsets=None, weights=None):
        if X.ndim != 2 or X.shape[0] != np.shape(labels)[0]:
            raise ValueError(f"X {X.shape} does not match labels "
                             f"{np.shape(labels)}")
        self.X = X
        self.labels = labels
        self.offsets = offsets
        self.weights = weights
        self.num_rows, self.dim = X.shape
        self.ell_width: Optional[int] = None   # dense

    def read_block(self, start: int, stop: int) -> RawBlock:
        sl = slice(start, stop)
        return RawBlock(
            labels=np.asarray(self.labels[sl]),
            x=np.asarray(self.X[sl]),
            offsets=None if self.offsets is None
            else np.asarray(self.offsets[sl]),
            weights=None if self.weights is None
            else np.asarray(self.weights[sl]),
        )


class CsrSource:
    """CSR rows streamed as fixed-width padded-ELL blocks. ``max_nnz`` is
    a global static so every chunk lowers to the same compiled program;
    rows wider than it are rejected up front (silent truncation would
    corrupt margins, same contract as ops/features.from_csr_arrays)."""

    def __init__(self, indptr, cols, vals, labels, dim: int,
                 max_nnz: Optional[int] = None, offsets=None, weights=None,
                 dtype=np.float32):
        self.indptr = np.asarray(indptr, np.int64)
        self.cols = np.asarray(cols)
        self.vals = np.asarray(vals)
        self.labels = labels
        self.offsets = offsets
        self.weights = weights
        self.num_rows = len(self.indptr) - 1
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        row_nnz = np.diff(self.indptr)
        widest = int(row_nnz.max()) if self.num_rows else 0
        k = int(max_nnz) if max_nnz is not None else widest
        if widest > k:
            raise ValueError(f"row has {widest} nonzeros > max_nnz={k}; "
                             "refusing to silently truncate features")
        self.ell_width = k

    def read_block(self, start: int, stop: int) -> RawBlock:
        indptr = self.indptr[start:stop + 1]
        r = stop - start
        k = self.ell_width
        row_nnz = np.diff(indptr)
        idx = np.zeros((r, k), np.int32)
        val = np.zeros((r, k), self.dtype)
        if r and k:
            slot = np.arange(k)[None, :]
            mask = slot < row_nnz[:, None]
            src = indptr[:-1, None] + slot
            idx[mask] = self.cols[src[mask]]
            val[mask] = self.vals[src[mask]]
        sl = slice(start, stop)
        return RawBlock(
            labels=np.asarray(self.labels[sl]), idx=idx, val=val,
            offsets=None if self.offsets is None
            else np.asarray(self.offsets[sl]),
            weights=None if self.weights is None
            else np.asarray(self.weights[sl]),
        )


class EllSource:
    """In-RAM padded-ELL rows as a chunk source — the layout the mmap
    store carries on disk and ``ops/features.SparseFeatures`` holds on
    device. ``read_block`` returns plain row slices (zero-copy views),
    so a resident sparse batch can be re-streamed through the chunk
    pipeline (the SDCA passthrough wraps a coordinate's ELL batch this
    way) without a CSR round-trip."""

    def __init__(self, idx, val, labels, dim: int, offsets=None,
                 weights=None):
        idx = np.asarray(idx)
        val = np.asarray(val)
        if idx.ndim != 2 or idx.shape != val.shape:
            raise ValueError(f"idx {idx.shape} / val {val.shape} must be "
                             "matching [rows, ell_width] ELL arrays")
        if idx.shape[0] != np.shape(labels)[0]:
            raise ValueError(f"ELL rows {idx.shape[0]} do not match labels "
                             f"{np.shape(labels)}")
        self.idx = idx
        self.val = val
        self.labels = labels
        self.offsets = offsets
        self.weights = weights
        self.num_rows = int(idx.shape[0])
        self.dim = int(dim)
        self.ell_width = int(idx.shape[1])

    def read_block(self, start: int, stop: int) -> RawBlock:
        sl = slice(start, stop)
        return RawBlock(
            labels=np.asarray(self.labels[sl]),
            idx=np.asarray(self.idx[sl]),
            val=np.asarray(self.val[sl]),
            offsets=None if self.offsets is None
            else np.asarray(self.offsets[sl]),
            weights=None if self.weights is None
            else np.asarray(self.weights[sl]),
        )


class MmapChunkSource:
    """Disk-native chunk source over an ``io/data_store.py`` columnar
    store: ``read_block`` is a zero-copy mmap slice per section — no
    parse, no row assembly — so a fit streams straight off storage while
    host RAM holds only the OS page-cache window.

    The store carries sparse rows PRE-ASSEMBLED as padded ELL, bitwise
    identical to what ``CsrSource.read_block`` materializes, and every
    section file is page-aligned, so interior full chunks satisfy the
    loader's 64-byte alias contract (any chunk boundary at a multiple of
    16 rows is aligned for every section dtype) and flow through the
    same zero-copy dlpack path as the in-RAM sources — a streamed
    L-BFGS/OWL-QN fit off this source is bitwise identical to one off
    ``CsrSource``/``DenseSource`` on the same rows.

    ``shard_id`` restricts the source to the chunks the store's manifest
    assigns to that mesh shard (crc32 partitioner, see
    ``parallel/partition.entity_shard``); the shard's chunk spans are
    remapped to a dense [0, num_rows) row space so the loader needs no
    shard awareness. ``advise_behind`` (default on) drops clean resident
    pages behind the consumption cursor via madvise(DONTNEED) — purely
    an RSS bound; the pages re-fault identically if re-read, so repeated
    passes stay correct and a full pass's resident high-water is a small
    window instead of the dataset. Two release paths cover the loader's
    two modes: ``read_block`` advises behind the *read* cursor (safe in
    copy mode, where the reader's staging memcpy has already consumed
    the pages synchronously), and ``consumed`` advises behind realized
    *consumption tokens* (the loader hands over each source-aliased
    chunk's token) — in alias mode the async dispatch queue lets XLA
    executions lag the read cursor, so a reader-side advise alone gets
    quietly re-faulted by the lagging reads and a full pass ends with
    most of the store resident.
    """

    #: consumption-token lag (chunks) before a fenced page release:
    #: small enough to bound the resident window, large enough to keep
    #: chunk dispatch running ahead of execution
    _CONSUME_LAG = 4

    def __init__(self, path: str, *, shard_id: Optional[int] = None,
                 verify: bool = True, advise_behind: bool = True):
        # deferred: io.data_store imports resilience/io; keep streaming's
        # import graph free of the io package until a store is opened
        from photon_tpu.io.data_store import DataStore
        self.store = DataStore(path, verify=verify)
        man = self.store.manifest
        self.dtype = np.dtype(man["dtype"])
        self.dim = int(man["dim"])
        self.ell_width: Optional[int] = (
            None if man["ell_width"] is None else int(man["ell_width"]))
        n = int(man["n_rows"])
        cr = int(man["chunk_rows"])
        if shard_id is None:
            spans = [(0, n)] if n else []
        else:
            if not 0 <= int(shard_id) < int(man["num_shards"]):
                raise ValueError(f"shard_id={shard_id} outside the "
                                 f"store's {man['num_shards']} shards")
            spans = []
            for c, s in enumerate(man["chunk_shards"]):
                if int(s) != int(shard_id):
                    continue
                lo, hi = c * cr, min(n, (c + 1) * cr)
                if spans and spans[-1][1] == lo:
                    spans[-1] = (spans[-1][0], hi)
                else:
                    spans.append((lo, hi))
        self._spans = spans
        self.num_rows = int(sum(hi - lo for lo, hi in spans))
        self._cum = np.cumsum([0] + [hi - lo for lo, hi in spans])
        self.labels = self.store.section("labels")
        self.offsets = (self.store.section("offsets")
                        if man["has_offsets"] else None)
        self.weights = (self.store.section("weights")
                        if man["has_weights"] else None)
        if self.ell_width is None:
            self._x = self.store.section("x")
        else:
            self._idx = self.store.section("idx")
            self._val = self.store.section("val")
        self._advise = bool(advise_behind)
        self._advised_to = 0   # logical row watermark already released
        self._pending: List[tuple] = []   # (row_stop, token) FIFO
        self._consumed_to = 0  # logical row watermark token-fence-released

    def _pieces(self, start: int, stop: int) -> List[tuple]:
        """Logical row range -> physical (lo, hi) spans in the store."""
        out = []
        i = int(np.searchsorted(self._cum, start, side="right")) - 1
        while start < stop and i < len(self._spans):
            lo, hi = self._spans[i]
            p_lo = lo + (start - int(self._cum[i]))
            take = min(stop - start, hi - p_lo)
            out.append((p_lo, p_lo + take))
            start += take
            i += 1
        return out

    def _gather(self, arr: np.ndarray, pieces: List[tuple]) -> np.ndarray:
        if len(pieces) == 1:
            lo, hi = pieces[0]
            return arr[lo:hi]           # zero-copy mmap slice
        return np.concatenate([arr[lo:hi] for lo, hi in pieces])

    def _release_behind(self, start: int, stop: int) -> None:
        """madvise(DONTNEED) rows more than ~4 blocks behind the cursor
        (new pass detected by a backwards cursor => watermark reset)."""
        if start < self._advised_to:
            self._advised_to = 0
        behind = start - 4 * (stop - start)
        if behind - self._advised_to < (stop - start):
            return
        for lo, hi in self._pieces(self._advised_to, behind):
            self.store.advise_dontneed(lo, hi)
        self._advised_to = behind

    def consumed(self, row_stop: int, token) -> None:
        """Token-fenced page release for the zero-copy alias path. The
        loader calls this with every source-aliased chunk's consumption
        token (the streamed solver's new carry); the carry chain means
        token k's readiness fences every chunk <= k's reads, so pages
        advised after the wait can never be re-faulted by a lagging
        async execution. The wait itself trails ``_CONSUME_LAG`` chunks
        behind dispatch and lands on an almost-always-realized token —
        compute, not this fence, stays the critical path."""
        if not self._advise:
            return
        if self._pending and row_stop <= self._pending[-1][0]:
            # backwards cursor = new pass; its tokens were realized at
            # the pass-end (f, g) host read, nothing left to fence
            self._pending.clear()
            self._consumed_to = 0
        self._pending.append((row_stop, token))
        if len(self._pending) <= self._CONSUME_LAG:
            return
        stop, tok = self._pending.pop(0)
        import jax
        jax.block_until_ready(tok)   # host-sync-ok — trailing RSS fence,
        # _CONSUME_LAG chunks behind dispatch, NOT the per-chunk path
        for lo, hi in self._pieces(self._consumed_to, stop):
            self.store.advise_dontneed(lo, hi)
        self._consumed_to = stop

    def read_block(self, start: int, stop: int) -> RawBlock:
        pieces = self._pieces(start, stop)
        g = lambda a: self._gather(a, pieces)   # noqa: E731
        block = RawBlock(
            labels=g(self.labels),
            x=g(self._x) if self.ell_width is None else None,
            idx=g(self._idx) if self.ell_width is not None else None,
            val=g(self._val) if self.ell_width is not None else None,
            offsets=None if self.offsets is None else g(self.offsets),
            weights=None if self.weights is None else g(self.weights),
        )
        if self._advise:
            self._release_behind(start, stop)
        return block


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Knobs for the streaming chunk loader.

    ``chunk_rows`` is rounded UP to a power of two (static shapes; one
    compiled per-chunk program). ``num_buffers=2`` is classic double
    buffering: one buffer in flight to the device while the reader fills
    the other; raise it to deepen prefetch when reads are bursty.
    ``drop_invalid`` applies the resident validator's row-local rules
    per chunk (``task`` required). ``retry`` defaults to the env-tunable
    ``RetryPolicy.from_env()`` (PHOTON_TPU_IO_RETRIES / _RETRY_BASE_S /
    _RETRY_MAX_S), the same knobs the checkpoint/cold-store I/O uses.
    """

    chunk_rows: int = 8192
    num_buffers: int = 2
    dtype: object = np.float32
    drop_invalid: bool = False
    task: Optional[TaskType] = None
    retry: Optional[RetryPolicy] = None
    # None = auto: alias staging buffers into device arrays (dlpack,
    # zero-copy) on unmeshed CPU backends, DMA-copy everywhere else.
    # False forces copy mode (e.g. a consumer that dispatches async
    # compute on chunks but cannot provide release tokens).
    zero_copy: Optional[bool] = None


class DeviceChunk(NamedTuple):
    index: int          # position in the deterministic chunk order
    rows: int           # real rows (tail chunks: < chunk_rows; rest pad)
    batch: DataBatch    # device-resident, chunk_rows rows, weight-0 pads
    # True when the chunk occupies a recycled staging buffer and so needs
    # a consumption token before reuse; False for chunks aliased straight
    # off the (immutable, never-recycled) source arrays
    fenced: bool = True
    # stable chunk identity: which chunk of the CANONICAL ascending order
    # this is. Equal to ``index`` on ascending streams; under
    # ``stream(order=...)`` the visit position (``index``) permutes while
    # ``chunk_id`` names the same rows every epoch — the key consumers
    # with per-chunk state (SDCA's dual slots) key on. -1 = unset
    # (legacy constructions), meaning "same as index".
    chunk_id: int = -1


@dataclasses.dataclass
class StreamStats:
    """Wall-clock accounting of one pass, read by the overlap gauges
    (utils/flops.stream_overlap_utilization). ``reader_busy_s`` is the
    hideable work (read + validate + stage + transfer dispatch);
    ``consumer_stall_s`` is how much of it was NOT hidden (consumer sat
    in q.get); ``transfer_wait_s`` is reader-side backpressure waiting to
    recycle a buffer still in flight."""

    chunks: int = 0
    rows: int = 0
    rows_dropped: int = 0
    bytes_h2d: int = 0
    reader_busy_s: float = 0.0
    transfer_wait_s: float = 0.0
    consumer_stall_s: float = 0.0
    wall_s: float = 0.0


class _EndOfPass(NamedTuple):
    num_chunks: int


class _ReaderError(NamedTuple):
    error: BaseException


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1


_ALIGN = 64   # XLA:CPU requires 64-byte alignment to alias a host buffer


def _aligned_zeros(shape, dtype) -> np.ndarray:
    """Zeroed ndarray whose data pointer is ``_ALIGN``-byte aligned, so
    dlpack import of the staging buffer is a true alias (an unaligned
    buffer silently degrades to a copy and the whole zero-copy path
    loses its point)."""
    dt = np.dtype(dtype)
    n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    raw = np.zeros(n + _ALIGN, np.uint8)
    off = (-raw.ctypes.data) % _ALIGN
    return raw[off:off + n].view(dt).reshape(shape)


def ensure_aligned(a: np.ndarray) -> np.ndarray:
    """Return ``a`` if its buffer is 64-byte aligned and C-contiguous,
    else a one-time aligned copy. XLA:CPU only aliases aligned host
    buffers, and numpy's default allocator gives 16 — so an in-RAM dense
    source built straight from ``rng.normal``/``np.load`` silently loses
    the source-alias fast path on every chunk of every pass. Memmapped
    and freshly materialized large arrays are page-aligned already; this
    is for the in-RAM case, where one copy is affordable and amortizes
    over the whole fit."""
    a = np.ascontiguousarray(a)
    if a.ctypes.data % _ALIGN == 0:
        return a
    out = _aligned_zeros(a.shape, a.dtype)
    np.copyto(out, a)
    return out


_U64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """One splitmix64 step (pure-int, platform/numpy-version independent —
    the permutation below must be bitwise stable forever, so it cannot
    ride numpy's Generator, whose stream is only stable per release
    line)."""
    x = (x + 0x9E3779B97F4A7C15) & _U64
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _U64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _U64
    return (z ^ (z >> 31)) & _U64


def epoch_chunk_order(seed: int, epoch: int, num_chunks: int) -> np.ndarray:
    """Deterministic chunk visit order for outer epoch ``epoch``.

    Counter-derived (splitmix64-keyed Fisher-Yates on ``(seed, epoch)``)
    so two runs — and a kill/resume replay — produce bitwise-identical
    orders with no wall-clock or global-RNG entropy. Epoch 0 is the
    IDENTITY by contract: the first pass must ascend because chunk
    geometry is only learned on a completed ascending pass (with
    ``drop_invalid`` the survivor-packed chunk count and composition are
    unknown before it). Later epochs shuffle.

    Stable under drop-invalid filtering: the permutation is a function of
    ``num_chunks`` alone and chunk *composition* never changes with visit
    order (survivors pack ascending into chunk ``i // chunk_rows`` slots
    regardless of the order those chunks are later visited in), so
    enabling the filter permutes exactly the same chunk ids it packs.
    """
    n = int(num_chunks)
    if n < 0:
        raise ValueError(f"num_chunks must be >= 0, got {num_chunks}")
    order = np.arange(n, dtype=np.int64)
    if int(epoch) == 0 or n <= 1:
        return order
    # key the stream on (seed, epoch) via two absorb steps
    state = _splitmix64((int(seed) & _U64) ^ 0xD6E8FEB86659FD93)
    state = _splitmix64(state ^ (int(epoch) & _U64))
    for i in range(n - 1, 0, -1):
        state = _splitmix64(state)
        j = state % (i + 1)
        order[i], order[j] = order[j], order[i]
    return order


class ChunkLoader:
    """Async prefetching chunk loader over a ChunkSource.

    ``stream(start_chunk=k)`` yields DeviceChunks in deterministic
    ascending order; one stream may be active per loader at a time. The
    reader thread owns the staging pool and all raw I/O; the consumer
    only ever touches device arrays, so its per-chunk path stays free of
    host syncs.
    """

    def __init__(self, source, config: StreamConfig = StreamConfig(),
                 mesh=None):
        if config.drop_invalid and config.task is None:
            raise ValueError("drop_invalid requires StreamConfig.task")
        if config.num_buffers < 2:
            raise ValueError("need >= 2 staging buffers to double-buffer")
        self.source = source
        self.config = config
        self.mesh = mesh
        self.dtype = np.dtype(config.dtype)
        self.chunk_rows = _pow2_ceil(config.chunk_rows)
        if mesh is not None:
            from photon_tpu.parallel import mesh as M
            self._axes = ((M.DCN_AXIS, M.DATA_AXIS)
                          if M.DCN_AXIS in mesh.axis_names else M.DATA_AXIS)
            names = (self._axes if isinstance(self._axes, tuple)
                     else (self._axes,))
            shards = int(np.prod([mesh.shape[a] for a in names]))
            if self.chunk_rows % shards:
                raise ValueError(f"chunk_rows={self.chunk_rows} not "
                                 f"divisible by {shards} sample shards")
        import jax
        cpu = jax.devices()[0].platform not in ("tpu", "axon")
        # Zero-copy alias mode: on an unmeshed CPU backend the "device"
        # is the host, so publishing a chunk is a dlpack import of the
        # staging buffer (~0 cost) instead of device_put's slow
        # single-threaded memcpy. Recycling then fences on consumption
        # tokens (see release()). Anywhere a real transfer happens
        # (accelerators, meshed runs) we copy, and fence on the copy.
        self._alias = (cpu and mesh is None) if config.zero_copy is None \
            else bool(config.zero_copy)
        # Copy mode on CPU: device_put may itself alias host memory, so
        # leaves are defensively copied at put time.
        self._copy_on_put = cpu and not self._alias
        self._policy = config.retry or RetryPolicy.from_env()
        self._buffers = [self._alloc_buffer()
                         for _ in range(config.num_buffers)]
        # shared all-ones weights column for source-aliased full chunks
        # (immutable once built, so it needs no fence either)
        self._ones = _aligned_zeros(self.chunk_rows, self.dtype)
        self._ones[:] = 1
        self._inflight: List[Optional[DataBatch]] = \
            [None] * config.num_buffers
        self._release_q: queue.Queue = queue.Queue()
        self._released_idx = -1
        self._streaming = False
        self._num_chunks: Optional[int] = None
        # cumulative survivor counts per raw block, cached by the first
        # COMPLETE ascending pass with drop_invalid; permuted streams use
        # it to find which raw blocks feed chunk k without a full rescan
        self._block_cum: Optional[np.ndarray] = None
        self._ordered = False
        self.last_stats = StreamStats()

    # -- geometry -----------------------------------------------------------

    @property
    def num_chunks(self) -> Optional[int]:
        """Chunks per pass. Known a priori without filtering; with
        ``drop_invalid`` it depends on the survivor count and is cached
        after the first complete pass (None before that)."""
        if not self.config.drop_invalid:
            n = self.source.num_rows
            return max(1, -(-n // self.chunk_rows))
        return self._num_chunks

    def chunk_bytes(self) -> int:
        """Host bytes of one staged chunk (= device bytes per chunk)."""
        return sum(a.nbytes for a in self._buffers[0].values())

    def geometry(self) -> Optional[dict]:
        """Snapshot of the learned pass geometry (chunk count and, with
        ``drop_invalid``, the per-raw-block survivor cumsum), for
        checkpoint consumers: a killed permuted-epoch run resumes in a
        fresh process whose loader never streamed ascending, so the
        geometry must travel with the checkpoint. None until a first
        complete pass has learned it."""
        if self.num_chunks is None:
            return None
        g: dict = {"num_chunks": int(self.num_chunks)}
        if self._block_cum is not None:
            g["block_cum"] = np.array(self._block_cum)
        return g

    def restore_geometry(self, g: Optional[dict]) -> None:
        """Install a :meth:`geometry` snapshot taken from the SAME
        (immutable) source + config — permuted streams become available
        without re-paying the ascending discovery pass."""
        if g is None:
            return
        self._num_chunks = int(g["num_chunks"])
        if g.get("block_cum") is not None:
            self._block_cum = np.asarray(g["block_cum"], np.int64)

    # -- staging pool -------------------------------------------------------

    def _alloc_buffer(self) -> dict:
        c, dt = self.chunk_rows, self.dtype
        buf = {"labels": _aligned_zeros(c, dt),
               "weights": _aligned_zeros(c, dt)}
        if getattr(self.source, "offsets", None) is not None:
            buf["offsets"] = _aligned_zeros(c, dt)
        if self.source.ell_width is None:
            buf["x"] = _aligned_zeros((c, self.source.dim), dt)
        else:
            buf["idx"] = _aligned_zeros((c, self.source.ell_width), np.int32)
            buf["val"] = _aligned_zeros((c, self.source.ell_width), dt)
        return buf

    def _acquire(self, b: int, stop: threading.Event,
                 stats: StreamStats) -> dict:
        """Fence the consumer out of buffer ``b`` before the reader
        refills it. Runs on the reader thread only — the consumer's
        per-chunk path never blocks on device state. Copy mode fences on
        the chunk's own device arrays (transfer landed => staging free);
        alias mode pops the next consumption token (chunk order equals
        recycle order, so one token frees exactly one buffer)."""
        import jax
        prev = self._inflight[b]
        self._inflight[b] = None
        if prev is None:
            return self._buffers[b]
        t0 = time.perf_counter()
        fence = prev
        if self._alias:
            fence = None
            while not stop.is_set():
                try:
                    fence = self._release_q.get(timeout=0.1)
                    break
                except queue.Empty:
                    continue
        if fence is not None:
            for leaf in jax.tree_util.tree_leaves(fence):
                leaf.block_until_ready()  # host-sync-ok: reader-side buffer-recycle fence
        stats.transfer_wait_s += time.perf_counter() - t0
        return self._buffers[b]

    def _pack(self, buf: dict, fill: int, block: RawBlock,
              pos: int, take: int) -> None:
        end, bsl = fill + take, slice(pos, pos + take)
        buf["labels"][fill:end] = block.labels[bsl]
        if block.weights is not None:
            buf["weights"][fill:end] = block.weights[bsl]
        else:
            buf["weights"][fill:end] = 1.0
        if "offsets" in buf:
            buf["offsets"][fill:end] = block.offsets[bsl]
        if "x" in buf:
            buf["x"][fill:end] = block.x[bsl]
        else:
            buf["idx"][fill:end] = block.idx[bsl]
            buf["val"][fill:end] = block.val[bsl]

    def _zero_tail(self, buf: dict, fill: int) -> None:
        for a in buf.values():
            a[fill:] = 0

    def _alias_put(self, buf: dict) -> Optional[dict]:
        """Publish staging arrays as zero-copy device aliases. Returns
        None (and permanently downgrades to copy mode) if this backend
        will not alias — the pointer check catches a silent dlpack copy,
        which would reintroduce the triple host traffic AND break the
        token fence's assumption that the device reads staging memory."""
        import jax.numpy as jnp
        try:
            out = {}
            for k, a in buf.items():
                d = jnp.from_dlpack(a)
                if d.unsafe_buffer_pointer() != a.ctypes.data:
                    return None
                out[k] = d
            return out
        except Exception:   # noqa: BLE001 — alias is an optimization only
            return None

    @staticmethod
    def _to_batch(buf: dict, sparse: bool) -> DataBatch:
        if sparse:
            feats = F.SparseFeatures(indices=buf["idx"], values=buf["val"])
        else:
            feats = buf["x"]
        return DataBatch(features=feats, labels=buf["labels"],
                         offsets=buf.get("offsets"),
                         weights=buf["weights"])

    def _put(self, buf: dict) -> DataBatch:
        import jax
        if self._alias:
            aliased = self._alias_put(buf)
            if aliased is None:
                self._alias = False
                self._copy_on_put = True
            else:
                return self._to_batch(aliased,
                                      self.source.ell_width is not None)
        batch = self._to_batch(buf, self.source.ell_width is not None)
        if self._copy_on_put:
            batch = jax.tree_util.tree_map(np.copy, batch)
        if self.mesh is not None:
            from photon_tpu.parallel import mesh as M
            return M.shard_batch(batch, self.mesh, axis=self._axes)
        return jax.device_put(batch)

    def _alias_block(self, block: RawBlock) -> Optional[DataBatch]:
        """Source-alias fast path: a full chunk whose block arrays
        already have the exact staged layout (shape, dtype, row-major,
        64-byte aligned) is published without touching the staging pool
        at all — for a dense source these are views of the (immutable)
        design matrix, for CSR the block's freshly materialized ELL
        arrays, so no buffer is ever recycled and no fence is needed.
        This halves host memory traffic, which is the whole cost of
        streaming a memory-bound objective on CPU. Returns None when any
        array misses the layout contract (the staging path handles it)."""
        arrs = {"labels": block.labels,
                "weights": self._ones if block.weights is None
                else block.weights}
        if "offsets" in self._buffers[0]:
            arrs["offsets"] = block.offsets
        if self.source.ell_width is None:
            arrs["x"] = block.x
        else:
            arrs["idx"] = block.idx
            arrs["val"] = block.val
        proto = self._buffers[0]
        for k, a in arrs.items():
            if (a is None or a.shape != proto[k].shape
                    or a.dtype != proto[k].dtype
                    or not a.flags["C_CONTIGUOUS"]
                    or a.ctypes.data % _ALIGN):
                return None
        aliased = self._alias_put(arrs)
        if aliased is None:
            return None
        return self._to_batch(aliased, self.source.ell_width is not None)

    # -- reader thread ------------------------------------------------------

    def _read_raw(self, start: int, stop: int) -> RawBlock:
        chaos.chunk_read_error()
        d = chaos.chunk_read_delay()
        if d > 0:
            time.sleep(d)
        return self.source.read_block(start, stop)

    def _filter(self, block: RawBlock, stats: StreamStats) -> RawBlock:
        # deferred: validators reaches game.dataset, which itself imports
        # this package — a module-level import would be circular
        from photon_tpu.data import validators

        fv = block.x if block.x is not None else block.val
        bad = validators.invalid_chunk_mask(
            block.labels, self.config.task, offsets=block.offsets,
            weights=block.weights, feature_values=fv)
        n_bad = int(bad.sum())
        if not n_bad:
            return block
        stats.rows_dropped += n_bad
        keep = ~bad
        return RawBlock(*(None if a is None else a[keep] for a in block))

    def _produce(self, q: queue.Queue, stop: threading.Event,
                 start_chunk: int, stats: StreamStats) -> None:
        try:
            c, n = self.chunk_rows, self.source.num_rows
            # staged_i rotates the staging pool independently of the
            # global chunk index: source-aliased chunks consume no buffer
            emitted, staged_i, fill = 0, 0, 0
            survivors: List[int] = []
            buf = self._acquire(0, stop, stats)
            for s in range(0, n, c):
                if stop.is_set():
                    return
                t0 = time.perf_counter()
                block = with_retries(self._read_raw, s, min(s + c, n),
                                     op="stream.chunk_read",
                                     policy=self._policy)
                if self.config.drop_invalid:
                    block = self._filter(block, stats)
                    survivors.append(block.rows)
                if (self._alias and fill == 0 and block.rows == c
                        and not self.config.drop_invalid):
                    dev = (None if emitted < start_chunk
                           else self._alias_block(block))
                    if dev is not None or emitted < start_chunk:
                        self._emit_aliased(q, stop, emitted, c, dev, stats,
                                           t0)
                        emitted += 1
                        if stop.is_set():
                            return
                        continue
                pos, remaining = 0, block.rows
                while remaining:
                    take = min(c - fill, remaining)
                    self._pack(buf, fill, block, pos, take)
                    fill += take
                    pos += take
                    remaining -= take
                    if fill == c:
                        self._emit(q, stop, emitted,
                                   staged_i % self.config.num_buffers, c,
                                   start_chunk, stats, t0)
                        emitted += 1
                        staged_i += 1
                        fill = 0
                        if stop.is_set():
                            return
                        buf = self._acquire(
                            staged_i % self.config.num_buffers, stop, stats)
                        t0 = time.perf_counter()  # recycle wait != work
                stats.reader_busy_s += time.perf_counter() - t0
            if fill > 0 or emitted == 0:
                t0 = time.perf_counter()
                self._zero_tail(buf, fill)
                self._emit(q, stop, emitted,
                           staged_i % self.config.num_buffers, fill,
                           start_chunk, stats, t0)
                emitted += 1
            if self.config.drop_invalid:
                # complete ascending pass: cache the survivor geometry
                # permuted epochs need to locate chunk k's raw blocks
                self._block_cum = np.cumsum([0] + survivors,
                                            dtype=np.int64)
            self._q_put(q, stop, _EndOfPass(emitted))
        except BaseException as e:  # noqa: BLE001 — surfaced to consumer
            self._q_put(q, stop, _ReaderError(e))

    def _emit_aliased(self, q: queue.Queue, stop: threading.Event,
                      index: int, rows: int, dev: Optional[DataBatch],
                      stats: StreamStats, t0: float,
                      chunk_id: Optional[int] = None) -> None:
        stats.reader_busy_s += time.perf_counter() - t0
        if dev is None:   # resume fast-forward: nothing to publish
            return
        stats.chunks += 1
        stats.rows += rows
        stats.bytes_h2d += self.chunk_bytes()
        self._q_put(q, stop, DeviceChunk(
            index=index, rows=rows, batch=dev, fenced=False,
            chunk_id=index if chunk_id is None else chunk_id))

    def _emit(self, q: queue.Queue, stop: threading.Event, index: int,
              b: int, rows: int, start_chunk: int, stats: StreamStats,
              t0: float, chunk_id: Optional[int] = None) -> None:
        if index < start_chunk:
            # resume fast-forward: the raw read/pack had to happen (chunk
            # packing state carries across chunks) but the transfer is
            # skipped — the consumer restarts at its checkpointed cursor
            stats.reader_busy_s += time.perf_counter() - t0
            return
        dev = self._put(self._buffers[b])
        self._inflight[b] = dev
        stats.chunks += 1
        stats.rows += rows
        stats.bytes_h2d += self.chunk_bytes()
        stats.reader_busy_s += time.perf_counter() - t0
        self._q_put(q, stop, DeviceChunk(
            index=index, rows=rows, batch=dev,
            chunk_id=index if chunk_id is None else chunk_id))

    def _produce_ordered(self, q: queue.Queue, stop: threading.Event,
                         order: np.ndarray, start_pos: int,
                         stats: StreamStats) -> None:
        """Reader loop for ``stream(order=...)``: visit chunks of the
        canonical ascending composition in an arbitrary order. Without
        filtering, chunk k IS raw block k, so a visit is one direct
        block read (resume positions are skipped without any I/O —
        unlike the ascending path there is no cross-chunk packing
        state). With ``drop_invalid``, the cached survivor geometry maps
        chunk k's survivor-index span to the raw blocks that feed it;
        each visit reads and re-filters just those blocks, reproducing
        the ascending pass's packing bitwise."""
        try:
            c, n = self.chunk_rows, self.source.num_rows
            cum = self._block_cum
            emitted, staged_i = 0, 0
            buf = self._acquire(0, stop, stats)
            for pos in range(int(start_pos), len(order)):
                if stop.is_set():
                    return
                cid = int(order[pos])
                t0 = time.perf_counter()
                if cum is None:
                    lo, hi = cid * c, min(n, (cid + 1) * c)
                    block = with_retries(self._read_raw, lo, hi,
                                         op="stream.chunk_read",
                                         policy=self._policy)
                    rows = block.rows
                    if self._alias and rows == c:
                        dev = self._alias_block(block)
                        if dev is not None:
                            self._emit_aliased(q, stop, pos, rows, dev,
                                               stats, t0, chunk_id=cid)
                            emitted += 1
                            continue
                    self._pack(buf, 0, block, 0, rows)
                else:
                    # survivor-index span of chunk cid -> raw blocks
                    total = int(cum[-1])
                    lo, hi = cid * c, min(total, (cid + 1) * c)
                    b0 = int(np.searchsorted(cum, lo, side="right")) - 1
                    fill = 0
                    for b in range(b0, len(cum) - 1):
                        if int(cum[b]) >= hi:
                            break
                        block = with_retries(
                            self._read_raw, b * c, min(n, (b + 1) * c),
                            op="stream.chunk_read", policy=self._policy)
                        block = self._filter(block, stats)
                        if block.rows != int(cum[b + 1]) - int(cum[b]):
                            raise RuntimeError(
                                "survivor geometry changed between "
                                "passes: cached block survivor count "
                                f"{int(cum[b + 1]) - int(cum[b])} != "
                                f"refiltered {block.rows} (block {b}) — "
                                "the source must be immutable for the "
                                "lifetime of the stream")
                        p_lo = max(lo - int(cum[b]), 0)
                        p_hi = min(hi - int(cum[b]), block.rows)
                        take = p_hi - p_lo
                        self._pack(buf, fill, block, p_lo, take)
                        fill += take
                    rows = fill
                if rows < c:
                    self._zero_tail(buf, rows)
                self._emit(q, stop, pos,
                           staged_i % self.config.num_buffers, rows,
                           0, stats, t0, chunk_id=cid)
                emitted += 1
                staged_i += 1
                if stop.is_set():
                    return
                buf = self._acquire(staged_i % self.config.num_buffers,
                                    stop, stats)
            # the pass covers len(order) chunk positions even when a
            # resume skipped the leading ones (ascending-path parity)
            self._q_put(q, stop, _EndOfPass(len(order)))
        except BaseException as e:  # noqa: BLE001 — surfaced to consumer
            self._q_put(q, stop, _ReaderError(e))

    @staticmethod
    def _q_put(q: queue.Queue, stop: threading.Event, item) -> None:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    # -- consumer -----------------------------------------------------------

    def release(self, chunk: DeviceChunk, token) -> None:
        """Hand buffer ``chunk`` back to the reader. ``token`` is any
        device pytree whose readiness implies every read of the chunk
        has completed — the streamed solver passes the carry its chunk
        partial produced. Required (per chunk, in order) by consumers
        that dispatch async compute on zero-copy chunks; a no-op in copy
        mode. Consumers that read chunks synchronously may skip it: the
        generator auto-releases when the next chunk is requested."""
        if (self._alias and self._streaming
                and chunk.index > self._released_idx):
            self._released_idx = chunk.index
            if chunk.fenced:
                self._release_q.put(token)
            elif not self._ordered:
                # source-aliased chunk: no buffer to recycle, but a
                # disk-backed source can use the token to fence page
                # release behind the consumption cursor. Skipped on
                # permuted streams — the source's release watermark
                # assumes a monotone row cursor, which only the
                # ascending order provides (permuted epochs trade the
                # RSS bound for random visit order).
                consumed = getattr(self.source, "consumed", None)
                if consumed is not None:
                    consumed(chunk.index * self.chunk_rows + chunk.rows,
                             token)

    def stream(self, start_chunk: int = 0,
               order=None) -> Iterator[DeviceChunk]:
        """Yield DeviceChunks in deterministic ascending order, chunk
        k+1's staging overlapping chunk k's compute. ``start_chunk``
        resumes mid-pass (chunks before it are read but not transferred).
        Stats for the pass land in ``self.last_stats`` on close.

        ``order`` (a permutation of ``range(num_chunks)``, e.g. from
        :func:`epoch_chunk_order`) visits the SAME ascending-composition
        chunks in that order: ``DeviceChunk.index`` is the visit
        position, ``DeviceChunk.chunk_id`` the stable chunk identity,
        and ``start_chunk`` counts positions in ``order``. With
        ``drop_invalid`` a permuted pass needs the survivor geometry a
        completed ascending pass caches — stream ascending once first.

        A new pass reuses the staging pool unfenced, so in zero-copy
        mode all chunks of the previous pass must be fully consumed
        before the next ``stream()`` begins — the streamed solver's
        per-pass host read of (f, g) guarantees exactly that."""
        if self._streaming:
            raise RuntimeError("one active stream per ChunkLoader")
        if order is not None:
            order = np.asarray(order, np.int64)
            if self.config.drop_invalid:
                if self._block_cum is None or self._num_chunks is None:
                    raise ValueError(
                        "stream(order=...) with drop_invalid needs the "
                        "survivor geometry of a completed ascending "
                        "pass — stream() once without order first")
                expect = self._num_chunks
            else:
                expect = self.num_chunks
            if (order.ndim != 1 or len(order) != expect
                    or not np.array_equal(np.sort(order),
                                          np.arange(expect))):
                raise ValueError(
                    f"order must be a permutation of range({expect}), "
                    f"got shape {order.shape}")
        self._streaming = True
        self._ordered = order is not None
        q: queue.Queue = queue.Queue(maxsize=self.config.num_buffers)
        stop = threading.Event()
        stats = StreamStats()
        self._inflight = [None] * self.config.num_buffers
        self._release_q = queue.Queue()
        self._released_idx = -1
        if order is not None:
            reader = threading.Thread(
                target=self._produce_ordered,
                args=(q, stop, order, start_chunk, stats),
                daemon=True, name="photon-stream-reader")
        else:
            reader = threading.Thread(
                target=self._produce, args=(q, stop, start_chunk, stats),
                daemon=True, name="photon-stream-reader")
        wall0 = time.perf_counter()
        reader.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                stats.consumer_stall_s += time.perf_counter() - t0
                if isinstance(item, _ReaderError):
                    raise item.error
                if isinstance(item, _EndOfPass):
                    self._num_chunks = item.num_chunks
                    break
                yield item
                # consumer came back without releasing: it consumed the
                # chunk synchronously, so its own arrays are the fence
                self.release(item, item.batch)
        finally:
            stop.set()
            while reader.is_alive():
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                reader.join(timeout=0.05)
            stats.wall_s = time.perf_counter() - wall0
            self.last_stats = stats
            self._streaming = False
            self._ordered = False
            try:
                from photon_tpu.obs.metrics import registry
                registry.counter("stream.chunks").inc(stats.chunks)
                if stats.rows_dropped:
                    registry.counter("stream.rows_dropped").inc(
                        stats.rows_dropped)
            except Exception:   # hygiene-ok — telemetry is best-effort
                pass
