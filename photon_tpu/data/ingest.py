"""Host-side data ingestion: LibSVM text and synthetic generators.

Reference: the demo workflow trains on a1a LibSVM data converted to Avro
(README.md:229-268); the legacy IO layer reads LibSVM directly
(io/deprecated/LibSVMInputDataFormat.scala). Avro container IO lives in
photon_tpu/io (pure-Python codec — no Spark, no HDFS).

Everything here produces numpy, then pads to static shapes for the device.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import features as F

import jax.numpy as jnp


@dataclasses.dataclass
class LibSVMData:
    labels: np.ndarray          # [n] float, mapped to {0, 1} from {-1, +1}
    rows: list                  # list of (indices, values)
    dim: int
    max_nnz: int


def read_libsvm(path: str, dim: Optional[int] = None,
                add_intercept: bool = True,
                zero_based: bool = False) -> LibSVMData:
    """Parse LibSVM text. Labels in {-1,1} or {0,1} are mapped to {0,1}.
    If ``add_intercept``, a constant-1 feature is appended at index dim-1."""
    import os
    if os.path.isdir(path):
        files = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if not f.startswith("."))
    else:
        files = [path]
    labels = []
    rows = []
    max_idx = -1
    max_nnz = 0
    for fp in files:
        with open(fp) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                idx = []
                val = []
                for tok in parts[1:]:
                    if tok.startswith("#"):
                        break
                    i, v = tok.split(":")
                    j = int(i) - (0 if zero_based else 1)
                    idx.append(j)
                    val.append(float(v))
                if idx:
                    max_idx = max(max_idx, max(idx))
                rows.append((np.asarray(idx, np.int32),
                             np.asarray(val, np.float64)))
                max_nnz = max(max_nnz, len(idx))

    y = np.asarray(labels)
    if set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y + 1.0) / 2.0

    d = dim if dim is not None else max_idx + 1
    if add_intercept:
        rows = [(np.append(r[0], d), np.append(r[1], 1.0)) for r in rows]
        d += 1
        max_nnz += 1
    return LibSVMData(labels=y, rows=rows, dim=d, max_nnz=max_nnz)


def to_batch(data: LibSVMData, dtype=np.float32,
             pad_to: Optional[int] = None) -> DataBatch:
    """LibSVM rows -> padded-ELL DataBatch; optionally pad the sample count
    to a multiple (pad rows get weight 0)."""
    n = len(data.rows)
    n_pad = pad_to if pad_to is not None else n
    rows = list(data.rows) + [(np.zeros(0, np.int32), np.zeros(0))] * (n_pad - n)
    feats = F.from_rows(rows, data.dim, dtype=dtype, max_nnz=data.max_nnz)
    labels = np.zeros(n_pad, dtype=dtype)
    labels[:n] = data.labels
    weights = np.zeros(n_pad, dtype=dtype)
    weights[:n] = 1.0
    return DataBatch(
        features=feats,
        labels=jnp.asarray(labels),
        offsets=None,
        weights=jnp.asarray(weights),
    )


# -- synthetic generators (reference: SparkTestUtils.scala:66+) -------------

def generate_binary_classification(
    rng: np.random.Generator, n: int, dim: int,
    sparsity: float = 0.0, intercept: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(X, y, w_true): well-separated logistic data with optional sparsity."""
    X = rng.normal(size=(n, dim))
    if sparsity > 0:
        X = X * (rng.random((n, dim)) >= sparsity)
    if intercept:
        X[:, -1] = 1.0
    w = rng.normal(size=dim)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-X @ w))).astype(np.float64)
    return X, y, w


def generate_poisson(rng: np.random.Generator, n: int, dim: int,
                     scale: float = 0.3) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    X = rng.normal(size=(n, dim)) * scale
    w = rng.normal(size=dim) * 0.5
    y = rng.poisson(np.exp(X @ w)).astype(np.float64)
    return X, y, w


def generate_linear(rng: np.random.Generator, n: int, dim: int,
                    noise: float = 0.1) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    X = rng.normal(size=(n, dim))
    w = rng.normal(size=dim)
    y = X @ w + noise * rng.normal(size=n)
    return X, y, w
