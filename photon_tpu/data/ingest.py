"""Host-side data ingestion: LibSVM text and synthetic generators.

Reference: the demo workflow trains on a1a LibSVM data converted to Avro
(README.md:229-268); the legacy IO layer reads LibSVM directly
(io/deprecated/LibSVMInputDataFormat.scala). Avro container IO lives in
photon_tpu/io (pure-Python codec — no Spark, no HDFS).

Everything here produces numpy, then pads to static shapes for the device.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import features as F

import jax.numpy as jnp


@dataclasses.dataclass
class LibSVMData:
    labels: np.ndarray          # [n] float, mapped to {0, 1} from {-1, +1}
    rows: list                  # list of (indices, values) OR CsrRows
    dim: int
    max_nnz: int


def _parse_libsvm_native(files, zero_based):
    """Columnar parse via the C tokenizer (native/libsvmdec.c): zero
    Python objects per nonzero. (labels, indptr, cols, vals) raw arrays,
    or None when the native path is unavailable."""
    from photon_tpu.native import libsvm_parser

    parse = libsvm_parser()
    if parse is None or not files:
        return None    # empty dir: one empty-data contract (Python path)
    parts = []
    for fp in files:
        with open(fp, "rb") as f:
            out = parse(f.read(), int(zero_based))
        parts.append(tuple(np.frombuffer(b, dt) for b, dt in
                           zip(out, (np.float64, np.int64, np.int32,
                                     np.float64))))
    labels = np.concatenate([p[0] for p in parts])
    # splice per-file CSRs: offsets shift each file's indptr
    nnz_off = np.cumsum([0] + [len(p[2]) for p in parts])
    indptr = np.concatenate(
        [p[1][:-1] + o for p, o in zip(parts, nnz_off)]
        + [np.asarray([nnz_off[-1]], np.int64)])
    cols = np.concatenate([p[2] for p in parts])
    vals = np.concatenate([p[3] for p in parts])
    return labels, indptr, cols, vals


def _parse_libsvm_python(files, zero_based):
    """Pure-Python fallback with the same grammar as libsvmdec.c ('#'
    truncates a line anywhere, blank lines are skipped) and the same
    columnar (labels, indptr, cols, vals) output."""
    labels: list = []
    indptr: list = [0]
    cols: list = []
    vals: list = []
    for fp in files:
        with open(fp) as f:
            for line in f:
                parts = line.split("#", 1)[0].split()
                if not parts:
                    continue          # blank or comment line
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    cols.append(int(i) - (0 if zero_based else 1))
                    vals.append(float(v))
                indptr.append(len(cols))
    return (np.asarray(labels, np.float64),
            np.asarray(indptr, np.int64),
            np.asarray(cols, np.int32),
            np.asarray(vals, np.float64))


def read_libsvm(path: str, dim: Optional[int] = None,
                add_intercept: bool = True,
                zero_based: bool = False) -> LibSVMData:
    """Parse LibSVM text. Labels in {-1,1} or {0,1} are mapped to {0,1}.
    If ``add_intercept``, a constant-1 feature is appended at index dim-1.
    Uses the native columnar tokenizer when available; both parsers emit
    the same raw columnar arrays and share ONE finalize step, so the
    output is identical either way (``rows`` is a CsrRows view that
    duck-types the row-list protocol)."""
    import os
    if os.path.isdir(path):
        files = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if not f.startswith("."))
    else:
        files = [path]

    try:
        parsed = _parse_libsvm_native(files, zero_based)
    except (MemoryError, ValueError):
        raise  # malformed input / OOM: same contract as the Python parser
    except Exception:  # noqa: BLE001 — optional fast path, never fatal
        parsed = None
    if parsed is None:
        parsed = _parse_libsvm_python(files, zero_based)

    from photon_tpu.game.dataset import CsrRows

    labels, indptr, cols, vals = parsed
    if len(cols) and int(cols.min()) < 0:
        raise ValueError("negative feature index (1-based data parsed "
                         "with zero_based=True?)")
    y = labels   # both parsers hand over fresh arrays; remap reallocates
    if set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y + 1.0) / 2.0
    n = len(y)
    d = dim if dim is not None else (int(cols.max()) + 1 if len(cols) else 0)
    if add_intercept:
        # vectorized append of a constant-1 slot at index d to every row
        cols = np.insert(cols, indptr[1:], d).astype(np.int32)
        vals = np.insert(vals, indptr[1:], 1.0)
        indptr = indptr + np.arange(n + 1, dtype=np.int64)
        d += 1
    max_nnz = int(np.diff(indptr).max()) if n else (1 if add_intercept else 0)
    return LibSVMData(labels=y, rows=CsrRows(indptr, cols, vals),
                      dim=d, max_nnz=max_nnz)


def to_batch(data: LibSVMData, dtype=np.float32,
             pad_to: Optional[int] = None) -> DataBatch:
    """LibSVM rows -> padded-ELL DataBatch; optionally pad the sample count
    to a multiple (pad rows get weight 0)."""
    from photon_tpu.game.dataset import CsrRows

    n = len(data.rows)
    n_pad = pad_to if pad_to is not None else n
    if isinstance(data.rows, CsrRows):
        r = data.rows
        indptr = r.indptr
        if n_pad > n:   # pad rows are empty: repeat the final offset
            indptr = np.concatenate(
                [indptr, np.full(n_pad - n, indptr[-1], indptr.dtype)])
        feats = F.from_csr_arrays(indptr, r.cols, r.vals, dtype=dtype,
                                  max_nnz=data.max_nnz)
    else:
        rows = (list(data.rows)
                + [(np.zeros(0, np.int32), np.zeros(0))] * (n_pad - n))
        feats = F.from_rows(rows, data.dim, dtype=dtype, max_nnz=data.max_nnz)
    labels = np.zeros(n_pad, dtype=dtype)
    labels[:n] = data.labels
    weights = np.zeros(n_pad, dtype=dtype)
    weights[:n] = 1.0
    return DataBatch(
        features=feats,
        labels=jnp.asarray(labels),
        offsets=None,
        weights=jnp.asarray(weights),
    )


# -- synthetic generators (reference: SparkTestUtils.scala:66+) -------------

def generate_binary_classification(
    rng: np.random.Generator, n: int, dim: int,
    sparsity: float = 0.0, intercept: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(X, y, w_true): well-separated logistic data with optional sparsity."""
    X = rng.normal(size=(n, dim))
    if sparsity > 0:
        X = X * (rng.random((n, dim)) >= sparsity)
    if intercept:
        X[:, -1] = 1.0
    w = rng.normal(size=dim)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-X @ w))).astype(np.float64)
    return X, y, w


def generate_poisson(rng: np.random.Generator, n: int, dim: int,
                     scale: float = 0.3) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    X = rng.normal(size=(n, dim)) * scale
    w = rng.normal(size=dim) * 0.5
    y = rng.poisson(np.exp(X @ w)).astype(np.float64)
    return X, y, w


def generate_linear(rng: np.random.Generator, n: int, dim: int,
                    noise: float = 0.1) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    X = rng.normal(size=(n, dim))
    w = rng.normal(size=dim)
    y = X @ w + noise * rng.normal(size=n)
    return X, y, w
