"""Host-side data ingestion: LibSVM text and synthetic generators.

Reference: the demo workflow trains on a1a LibSVM data converted to Avro
(README.md:229-268); the legacy IO layer reads LibSVM directly
(io/deprecated/LibSVMInputDataFormat.scala). Avro container IO lives in
photon_tpu/io (pure-Python codec — no Spark, no HDFS).

Everything here produces numpy, then pads to static shapes for the device.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import features as F

import jax.numpy as jnp


@dataclasses.dataclass
class LibSVMData:
    labels: np.ndarray          # [n] float, mapped to {0, 1} from {-1, +1}
    rows: list                  # list of (indices, values) OR CsrRows
    dim: int
    max_nnz: int


_PARALLEL_CHUNK_BYTES = 1 << 20   # fan out files bigger than 2x this


def _split_at_newlines(data: bytes, n_chunks: int) -> list:
    """Split ``data`` into up to ``n_chunks`` buffer pieces, cutting only
    just after a newline so every piece is a whole number of lines (the
    LibSVM grammar is line-based, so chunked parses splice exactly).
    Every returned piece is newline-TERMINATED: a buffer whose final line
    lacks its ``\\n`` gets one appended on a small owned copy of the tail
    piece (all other pieces stay zero-copy memoryviews), so parsers may
    rely on n-lines == n-newlines instead of the caller's buffer
    happening to end in ``\\n``. Files below 2x _PARALLEL_CHUNK_BYTES
    stay whole — thread-pool overhead beats the parse at small sizes."""
    mv = memoryview(data)
    if n_chunks <= 1 or len(data) < 2 * _PARALLEL_CHUNK_BYTES:
        out, start = [], 0
    else:
        approx = len(data) // n_chunks
        out, start = [], 0
        for _ in range(n_chunks - 1):
            cut = data.find(b"\n", start + approx)
            if cut < 0:
                break
            out.append(mv[start:cut + 1])
            start = cut + 1
    if start < len(data):
        tail = mv[start:]
        if data[-1:] != b"\n":
            tail = memoryview(bytes(tail) + b"\n")
        out.append(tail)
    if not out:
        out.append(mv)   # empty input: one empty piece, same as before
    return out


def _parse_libsvm_native(files, zero_based):
    """Columnar parse via the C tokenizer (native/libsvmdec.c): zero
    Python objects per nonzero. Large files are split at line boundaries
    and parsed on a thread pool — the tokenizer releases the GIL, so the
    ingest critical path (SURVEY §7 risk (e)) scales with host cores.
    Files are read, chunked (memoryviews, no copies), parsed, and their
    raw bytes dropped ONE AT A TIME, so peak memory stays one file plus
    the columnar outputs. (labels, indptr, cols, vals) raw arrays, or
    None when the native path is unavailable."""
    import os as _os
    from concurrent.futures import ThreadPoolExecutor

    from photon_tpu.native import libsvm_parser

    parse = libsvm_parser()
    if parse is None or not files:
        return None    # empty dir: one empty-data contract (Python path)
    workers = min(8, _os.cpu_count() or 1)
    dtypes = (np.float64, np.int64, np.int32, np.float64)
    parts = []
    with ThreadPoolExecutor(max_workers=workers) as ex:
        for fp in files:
            with open(fp, "rb") as f:
                data = f.read()
            pieces = _split_at_newlines(data, workers)
            if len(pieces) > 1:
                outs = list(ex.map(lambda b: parse(b, int(zero_based)),
                                   pieces))
            else:
                outs = [parse(pieces[0], int(zero_based))]
            parts.extend(
                tuple(np.frombuffer(b, dt) for b, dt in zip(out, dtypes))
                for out in outs)
            del pieces, data    # drop raw bytes before the next file
    labels = np.concatenate([p[0] for p in parts])
    # splice per-chunk CSRs: offsets shift each chunk's indptr
    nnz_off = np.cumsum([0] + [len(p[2]) for p in parts])
    indptr = np.concatenate(
        [p[1][:-1] + o for p, o in zip(parts, nnz_off)]
        + [np.asarray([nnz_off[-1]], np.int64)])
    cols = np.concatenate([p[2] for p in parts])
    vals = np.concatenate([p[3] for p in parts])
    return labels, indptr, cols, vals


def _parse_libsvm_python(files, zero_based):
    """Pure-Python fallback with the same grammar as libsvmdec.c ('#'
    truncates a line anywhere, blank lines are skipped) and the same
    columnar (labels, indptr, cols, vals) output."""
    labels: list = []
    indptr: list = [0]
    cols: list = []
    vals: list = []
    for fp in files:
        with open(fp) as f:
            for line in f:
                parts = line.split("#", 1)[0].split()
                if not parts:
                    continue          # blank or comment line
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    cols.append(int(i) - (0 if zero_based else 1))
                    vals.append(float(v))
                indptr.append(len(cols))
    return (np.asarray(labels, np.float64),
            np.asarray(indptr, np.int64),
            np.asarray(cols, np.int32),
            np.asarray(vals, np.float64))


def read_libsvm(path: str, dim: Optional[int] = None,
                add_intercept: bool = True,
                zero_based: bool = False) -> LibSVMData:
    """Parse LibSVM text. Labels in {-1,1} or {0,1} are mapped to {0,1}.
    If ``add_intercept``, a constant-1 feature is appended at index dim-1.
    Uses the native columnar tokenizer when available; both parsers emit
    the same raw columnar arrays and share ONE finalize step, so the
    output is identical either way (``rows`` is a CsrRows view that
    duck-types the row-list protocol)."""
    import os
    if os.path.isdir(path):
        files = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if not f.startswith("."))
    else:
        files = [path]

    try:
        parsed = _parse_libsvm_native(files, zero_based)
    except (MemoryError, ValueError):
        raise  # malformed input / OOM: same contract as the Python parser
    except Exception:  # noqa: BLE001 — optional fast path, never fatal
        parsed = None
    if parsed is None:
        parsed = _parse_libsvm_python(files, zero_based)

    from photon_tpu.game.dataset import CsrRows

    labels, indptr, cols, vals = parsed
    if len(cols) and int(cols.min()) < 0:
        raise ValueError("negative feature index (1-based data parsed "
                         "with zero_based=True?)")
    y = labels   # both parsers hand over fresh arrays; remap reallocates
    if set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y + 1.0) / 2.0
    n = len(y)
    d = dim if dim is not None else (int(cols.max()) + 1 if len(cols) else 0)
    if add_intercept:
        # vectorized append of a constant-1 slot at index d to every row
        cols = np.insert(cols, indptr[1:], d).astype(np.int32)
        vals = np.insert(vals, indptr[1:], 1.0)
        indptr = indptr + np.arange(n + 1, dtype=np.int64)
        d += 1
    max_nnz = int(np.diff(indptr).max()) if n else (1 if add_intercept else 0)
    return LibSVMData(labels=y, rows=CsrRows(indptr, cols, vals),
                      dim=d, max_nnz=max_nnz)


def to_batch(data: LibSVMData, dtype=np.float32,
             pad_to: Optional[int] = None) -> DataBatch:
    """LibSVM rows -> padded-ELL DataBatch; optionally pad the sample count
    to a multiple (pad rows get weight 0)."""
    from photon_tpu.game.dataset import CsrRows

    n = len(data.rows)
    n_pad = pad_to if pad_to is not None else n
    if isinstance(data.rows, CsrRows):
        r = data.rows
        indptr = r.indptr
        if n_pad > n:   # pad rows are empty: repeat the final offset
            indptr = np.concatenate(
                [indptr, np.full(n_pad - n, indptr[-1], indptr.dtype)])
        feats = F.from_csr_arrays(indptr, r.cols, r.vals, dtype=dtype,
                                  max_nnz=data.max_nnz)
    else:
        rows = (list(data.rows)
                + [(np.zeros(0, np.int32), np.zeros(0))] * (n_pad - n))
        feats = F.from_rows(rows, data.dim, dtype=dtype, max_nnz=data.max_nnz)
    labels = np.zeros(n_pad, dtype=dtype)
    labels[:n] = data.labels
    weights = np.zeros(n_pad, dtype=dtype)
    weights[:n] = 1.0
    return DataBatch(
        features=feats,
        labels=jnp.asarray(labels),
        offsets=None,
        weights=jnp.asarray(weights),
    )


def chunk_source(data: LibSVMData, dtype=np.float32):
    """LibSVM rows -> a ``data.streaming.CsrSource`` for out-of-core
    training: the same padded-ELL rows ``to_batch`` would build, but
    materialized one chunk at a time by the streaming loader instead of
    as one resident batch. The row-list storage form is flattened to the
    CSR arrays once, on the host."""
    from photon_tpu.data.streaming import CsrSource
    from photon_tpu.game.dataset import CsrRows

    if isinstance(data.rows, CsrRows):
        indptr, cols, vals = data.rows.indptr, data.rows.cols, data.rows.vals
    else:
        nnz = np.asarray([len(r[0]) for r in data.rows], np.int64)
        indptr = np.zeros(len(data.rows) + 1, np.int64)
        np.cumsum(nnz, out=indptr[1:])
        cols = (np.concatenate([np.asarray(r[0]) for r in data.rows])
                if len(data.rows) else np.zeros(0, np.int32))
        vals = (np.concatenate([np.asarray(r[1]) for r in data.rows])
                if len(data.rows) else np.zeros(0, np.float64))
    return CsrSource(indptr, cols, vals, data.labels, dim=data.dim,
                     max_nnz=data.max_nnz, dtype=dtype)


# -- synthetic generators (reference: SparkTestUtils.scala:66+) -------------

def generate_binary_classification(
    rng: np.random.Generator, n: int, dim: int,
    sparsity: float = 0.0, intercept: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(X, y, w_true): well-separated logistic data with optional sparsity."""
    X = rng.normal(size=(n, dim))
    if sparsity > 0:
        X = X * (rng.random((n, dim)) >= sparsity)
    if intercept:
        X[:, -1] = 1.0
    w = rng.normal(size=dim)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-X @ w))).astype(np.float64)
    return X, y, w


def generate_poisson(rng: np.random.Generator, n: int, dim: int,
                     scale: float = 0.3) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    X = rng.normal(size=(n, dim)) * scale
    w = rng.normal(size=dim) * 0.5
    y = rng.poisson(np.exp(X @ w)).astype(np.float64)
    return X, y, w


def generate_linear(rng: np.random.Generator, n: int, dim: int,
                    noise: float = 0.1) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    X = rng.normal(size=(n, dim))
    w = rng.normal(size=dim)
    y = X @ w + noise * rng.normal(size=n)
    return X, y, w
