"""HBM footprint planning for λ-lane random-effect sweeps.

Lane-batching the random-effect sweep axis K-folds the per-bucket device
footprint: every λ lane carries its own ``[E_b, d]`` theta stack, solver
history, and working vectors on top of the (shared) entity-block data.
Discovering that multiplication as a runtime OOM mid-sweep would waste
the whole run, so the planner sizes every bucket of the ladder AGAINST
AN EXPLICIT BYTE BUDGET *before* anything is staged, and degrades
per bucket in typed steps:

  * ``full_k``        — all K lanes fit alongside a double-buffered
                        block: one data pass for the whole grid;
  * ``chunked``       — K splits into ⌈K/c⌉ passes of c lanes each (the
                        staged block is reused across passes, so the
                        storage→device traffic stays one pass);
  * ``single_lambda`` — lanes degrade all the way to one λ per pass —
                        the sequential sweep's footprint, still planned
                        and still recorded.

A bucket that cannot fit even one lane inside the budget is marked
``over_budget`` (the plan is still emitted — a refused shape is data,
not a crash; callers decide whether to proceed on a host with slack).

The budget defaults from the backend (``Device.memory_stats()``'s
``bytes_limit`` with a safety margin) exactly like the serving two-tier
store's ``hbm_budget_bytes``, is overridable per call, and can be pinned
fleet-wide via ``PHOTON_TPU_RE_HBM_BUDGET``. Every plan is recorded for
the RunReport ``re_plan`` section (obs/report.py reads this module via
``sys.modules`` so runs that never sweep pay nothing).

Byte model (pinned by tests/test_re_sweep.py — change them together):

  data_bytes(E, S, W)  = E*S*W*(4 + itemsize)        ELL indices + values
                       + E*S*(3*itemsize + 4)        labels/offsets/weights
                                                     + sample_rows
                       + E*4                         entity_rows
  lane_bytes(E, d)     = E*d*itemsize*(2 + 2*m + 6)  x0 + result
                                                     + L-BFGS (S,Y) pairs
                                                     + working vectors
  peak(c)              = copies*data + c*(data + lane_bytes)

where ``m`` is the solver history (``SolverConfig.num_corrections``) and
the 6 working vectors bound the gradient/direction/line-search temps.
Each lane is charged ``data + lane_bytes``: the swept program flattens
its c lanes into the entity axis by tiling the staged block c× on
device (game/coordinate._make_block_solver_swept — the price of bitwise
lane-vs-scalar parity), so the tiled batch scales with the chunk, while
the staging (``copies`` = 2 when double-buffered) does not. All terms
are deliberate over-estimates of steady state (at c=1 the block is
consumed in place, untiled) — the acceptance contract is
planned >= measured on every bucket, never the reverse.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

ENV_BUDGET = "PHOTON_TPU_RE_HBM_BUDGET"

# host/CPU fallback when the backend reports no bytes_limit: big enough
# that tests and CPU benches only degrade when they *force* a budget
_FALLBACK_BUDGET_BYTES = 1 << 30            # 1 GiB
# fraction of the backend's bytes_limit the sweep may claim — the rest
# stays for the programs themselves, XLA temps, and the residual vector
_BACKEND_BUDGET_FRACTION = 0.8

# solver working set per lane, in units of [E, d] vectors: gradient,
# direction, trial coef, trial gradient + two history-matvec temps
_WORK_VECTORS = 6

STRATEGY_FULL = "full_k"
STRATEGY_CHUNKED = "chunked"
STRATEGY_SINGLE = "single_lambda"


def default_hbm_budget_bytes(device=None) -> Tuple[int, str]:
    """(budget bytes, source) — source is ``env`` | ``backend`` |
    ``fallback``. Reads ``PHOTON_TPU_RE_HBM_BUDGET`` first, then the
    backend's ``memory_stats()['bytes_limit']`` (scaled by the safety
    fraction), else a nominal host figure (CPU backends usually report
    no limit)."""
    env = os.environ.get(ENV_BUDGET)
    if env:
        return max(1, int(env)), "env"
    try:
        if device is None:
            import jax
            device = jax.local_devices()[0]
        stats = device.memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return int(limit * _BACKEND_BUDGET_FRACTION), "backend"
    except Exception:  # hygiene-ok: any backend-probe failure (not yet
        # initialized, no memory_stats on this platform) means "budget
        # unknown" — the typed answer is the nominal fallback source
        pass
    return _FALLBACK_BUDGET_BYTES, "fallback"


def block_data_bytes(entity_rows: int, max_samples: int, ell_width: int,
                     itemsize: int) -> int:
    """Device bytes of one staged EntityBlock (ELL indices int32 + values,
    labels/offsets/weights, sample_rows int32, entity_rows int32)."""
    e, s, w = int(entity_rows), int(max_samples), int(ell_width)
    return (e * s * w * (4 + itemsize)
            + e * s * (3 * itemsize + 4)
            + e * 4)


def lane_state_bytes(entity_rows: int, dim: int, itemsize: int,
                     history: int) -> int:
    """Device bytes ONE λ lane adds on top of the shared block data:
    theta stack + result + L-BFGS (S, Y) history + working vectors."""
    e, d = int(entity_rows), int(dim)
    return e * d * itemsize * (2 + 2 * int(history) + _WORK_VECTORS)


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """One size bucket's lane decision."""

    bucket: int
    entity_rows: int
    max_samples: int
    ell_width: int
    data_bytes: int          # one staged copy of the block
    lane_bytes: int          # per-λ solver state
    lane_chunk: int          # c lanes solved per pass
    passes: int              # ceil(K / c) compute passes over the block
    strategy: str            # full_k | chunked | single_lambda
    peak_bytes: int          # planned peak: double-buffered data + c lanes
    over_budget: bool        # even c=1 exceeds the budget

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class BlockPlan:
    """The whole ladder's plan for a K-lane sweep."""

    coordinate: str
    lanes: int
    dim: int
    dtype: str
    history: int
    budget_bytes: int
    budget_source: str       # env | backend | fallback | override
    buckets: Tuple[BucketPlan, ...]

    @property
    def lane_chunk(self) -> int:
        """The ladder-wide chunk: the tightest bucket's c. The
        all-at-once swept program solves every bucket in one trace, so
        it must run at the chunk the worst bucket tolerates."""
        return min((b.lane_chunk for b in self.buckets), default=self.lanes)

    @property
    def passes(self) -> int:
        return max((b.passes for b in self.buckets), default=1)

    @property
    def peak_bytes(self) -> int:
        return max((b.peak_bytes for b in self.buckets), default=0)

    @property
    def degraded(self) -> bool:
        return any(b.strategy != STRATEGY_FULL for b in self.buckets)

    @property
    def over_budget(self) -> bool:
        return any(b.over_budget for b in self.buckets)

    def to_dict(self) -> dict:
        return {
            "coordinate": self.coordinate,
            "lanes": self.lanes,
            "dim": self.dim,
            "dtype": self.dtype,
            "history": self.history,
            "budget_bytes": self.budget_bytes,
            "budget_source": self.budget_source,
            "lane_chunk": self.lane_chunk,
            "passes": self.passes,
            "peak_bytes": self.peak_bytes,
            "degraded": self.degraded,
            "over_budget": self.over_budget,
            "buckets": [b.to_dict() for b in self.buckets],
        }


def plan_block_ladder(
    bucket_shapes: Sequence[Tuple[int, int, int]],
    *,
    lanes: int,
    dim: int,
    itemsize: int,
    history: int = 10,
    hbm_budget_bytes: Optional[int] = None,
    coordinate: str = "re",
    dtype: str = "",
    double_buffer: bool = True,
) -> BlockPlan:
    """Plan a K-lane sweep over a bucket ladder of ``(E_b, S_b, K_b)``
    shapes. Pure byte arithmetic — nothing is staged, nothing traced."""
    if lanes < 1:
        raise ValueError(f"lanes must be >= 1, got {lanes}")
    if hbm_budget_bytes is None:
        budget, source = default_hbm_budget_bytes()
    else:
        budget, source = int(hbm_budget_bytes), "override"
    if budget < 1:
        raise ValueError(f"hbm budget must be positive, got {budget}")
    data_copies = 2 if double_buffer else 1
    buckets = []
    for bi, (e, s, w) in enumerate(bucket_shapes):
        data = block_data_bytes(e, s, w, itemsize)
        lane = lane_state_bytes(e, dim, itemsize, history)
        base = data_copies * data
        headroom = budget - base
        # each lane costs a tiled copy of the block plus its solver
        # state (the flattened-lane program; module docstring)
        per_lane = data + lane
        c = max(1, min(lanes, headroom // per_lane if per_lane > 0
                       else lanes))
        over = base + c * per_lane > budget
        passes = -(-lanes // c)
        strategy = (STRATEGY_FULL if c >= lanes
                    else STRATEGY_CHUNKED if c > 1
                    else STRATEGY_SINGLE)
        buckets.append(BucketPlan(
            bucket=bi, entity_rows=int(e), max_samples=int(s),
            ell_width=int(w), data_bytes=data, lane_bytes=lane,
            lane_chunk=int(c), passes=int(passes), strategy=strategy,
            peak_bytes=base + c * per_lane, over_budget=bool(over)))
    return BlockPlan(coordinate=coordinate, lanes=int(lanes), dim=int(dim),
                     dtype=str(dtype), history=int(history),
                     budget_bytes=int(budget), budget_source=source,
                     buckets=tuple(buckets))


def plan_for_dataset(dataset, *, lanes: int, history: int = 10,
                     hbm_budget_bytes: Optional[int] = None,
                     coordinate: str = "re",
                     double_buffer: bool = True) -> BlockPlan:
    """Plan from a ``RandomEffectDataset``'s actual bucket ladder."""
    import numpy as np

    shapes = [(b.num_rows, b.max_samples, b.features.values.shape[-1])
              for b in dataset.blocks]
    dt = (np.dtype(dataset.blocks[0].labels.dtype) if dataset.blocks
          else np.dtype(np.float32))
    return plan_block_ladder(
        shapes, lanes=lanes, dim=dataset.projected_dim,
        itemsize=dt.itemsize, history=history,
        hbm_budget_bytes=hbm_budget_bytes, coordinate=coordinate,
        dtype=str(dt), double_buffer=double_buffer)


# -- plan accounting for the RunReport `re_plan` section ---------------------

_PLAN_STATS = {
    "plans": 0,                 # plans recorded this process
    "buckets_degraded": 0,      # buckets planned below full-K lanes
    "buckets_over_budget": 0,   # buckets that exceed the budget even at c=1
    "last_plan": None,          # most recent plan, as a dict
}


def record_plan(plan: BlockPlan) -> None:
    """Account one emitted plan (host-side bookkeeping only)."""
    _PLAN_STATS["plans"] += 1
    _PLAN_STATS["buckets_degraded"] += sum(
        1 for b in plan.buckets if b.strategy != STRATEGY_FULL)
    _PLAN_STATS["buckets_over_budget"] += sum(
        1 for b in plan.buckets if b.over_budget)
    _PLAN_STATS["last_plan"] = plan.to_dict()


def reset_plan_stats() -> None:
    _PLAN_STATS.update(plans=0, buckets_degraded=0, buckets_over_budget=0,
                       last_plan=None)


def report_section() -> Optional[dict]:
    """The RunReport ``re_plan`` section; ``None`` while no sweep has
    been planned (obs/report.py reads this via ``sys.modules`` so
    non-sweeping runs pay nothing)."""
    if not _PLAN_STATS["plans"]:
        return None
    return {
        "plans": _PLAN_STATS["plans"],
        "buckets_degraded": _PLAN_STATS["buckets_degraded"],
        "buckets_over_budget": _PLAN_STATS["buckets_over_budget"],
        "last_plan": _PLAN_STATS["last_plan"],
    }
