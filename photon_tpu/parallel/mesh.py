"""Device mesh + sharding: the Spark-cluster replacement, wired into training.

Reference §5.8: Spark broadcasts / treeAggregate / shuffle joins become one
SPMD program on a `jax.sharding.Mesh`. Conventions:

  * axis "data"   — batch (sample) sharding; the `jnp.sum` reductions inside
                    the aggregator kernels (ops/aggregators.py) lower to
                    `all-reduce` over this axis — the treeAggregate
                    replacement (ValueAndGradientAggregator.scala:240-255).
  * axis "entity" — random-effect entity-block sharding (the co-partitioned
                    RandomEffectDataset replacement,
                    RandomEffectDatasetPartitioner.scala:44). Entity solves
                    are independent, so this axis needs no collectives.
  * axis "model"  — feature-dimension sharding of theta for billion-feature
                    fixed effects (SURVEY §5.7): partial dots per shard,
                    psum to form margins.

Parameters are replicated (`PartitionSpec()`) — the broadcast-variable
replacement (DistributedObjectiveFunction.scala:34).

The reference's `treeAggregateDepth` knob (GameEstimator.scala:100) has no
equivalent degree of freedom here: ICI all-reduce topology is chosen by the
XLA compiler/hardware, so the knob is intentionally absent.

Divisibility: NamedSharding needs leading dims divisible by the mesh axis
size, so `pad_batch` / `pad_entities` append zero-weight rows / empty
entity blocks. Zero-weight pads contribute exactly nothing to any
aggregator (every per-sample term is multiplied by its weight) or metric
(all evaluators are weighted).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import features as F

DATA_AXIS = "data"
ENTITY_AXIS = "entity"
MODEL_AXIS = "model"


def create_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    devs = np.asarray(devices[:n])
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    return Mesh(devs.reshape(tuple(shape)), tuple(axis_names))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated (the broadcast-variable equivalent)."""
    return NamedSharding(mesh, P())


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


# -- batch padding + placement (fixed-effect path) --------------------------

def pad_batch(batch: DataBatch, multiple: int) -> DataBatch:
    """Append zero-weight samples until num_samples % multiple == 0.

    Weights are materialized (implicit all-ones otherwise) so pads carry
    weight 0 and vanish from every aggregator sum.
    """
    n = batch.num_samples
    n_pad = pad_to_multiple(n, multiple)
    if n_pad == n and batch.weights is not None:
        return batch
    extra = n_pad - n

    def pad0(a, rows):
        if a is None:
            return None
        widths = [(0, rows)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    feats = batch.features
    if isinstance(feats, F.SparseFeatures):
        feats = F.SparseFeatures(pad0(feats.indices, extra), pad0(feats.values, extra))
    else:
        feats = pad0(feats, extra)
    weights = batch.weights if batch.weights is not None \
        else jnp.ones_like(batch.labels)
    return DataBatch(
        features=feats,
        labels=pad0(batch.labels, extra),
        offsets=pad0(batch.offsets, extra),
        weights=pad0(weights, extra),
    )


def shard_batch(batch: DataBatch, mesh: Mesh, axis: str = DATA_AXIS) -> DataBatch:
    """Pad + place a DataBatch with its sample dim sharded over ``axis``.

    The treeAggregate replacement: once inputs are placed this way, the
    jitted aggregator kernels' reductions compile to all-reduce over ICI.
    """
    batch = pad_batch(batch, axis_size(mesh, axis))

    def put(a):
        if a is None:
            return None
        spec = P(axis, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree.map(put, batch)


def replicate(params, mesh: Mesh):
    sharding = replicated(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), params)


# -- entity-block padding + placement (random-effect path) -------------------

def pad_entities(ds, multiple: int, num_flat_samples: Optional[int] = None):
    """Pad a RandomEffectDataset's entity dim (and passive rows) so both
    shard evenly; pad entities have zero-weight samples and scatter rows at
    the drop sentinel ``num_flat_samples`` (the documented 'n on pads'
    invariant of RandomEffectDataset.sample_rows)."""
    from photon_tpu.game.random_effect import RandomEffectDataset

    E = ds.num_entities
    E_pad = pad_to_multiple(E, multiple)
    Ppas = ds.passive_entity.shape[0]
    P_pad = pad_to_multiple(Ppas, multiple)
    if E_pad == E and P_pad == Ppas:
        return ds

    def pad0(a, rows, fill=0):
        widths = [(0, rows)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=fill)

    eE, eP = E_pad - E, P_pad - Ppas
    # sample_rows is n on build-time pads, so max is a safe drop sentinel
    # only when pads exist; max+1 keeps pads inert when every block is full
    n_sentinel = (num_flat_samples if num_flat_samples is not None
                  else int(jnp.max(ds.sample_rows)) + 1 if ds.sample_rows.size else 0)
    return RandomEffectDataset(
        features=F.SparseFeatures(pad0(ds.features.indices, eE),
                                  pad0(ds.features.values, eE)),
        labels=pad0(ds.labels, eE),
        offsets=pad0(ds.offsets, eE),
        weights=pad0(ds.weights, eE),
        sample_rows=pad0(ds.sample_rows, eE, fill=n_sentinel),
        passive_features=F.SparseFeatures(pad0(ds.passive_features.indices, eP),
                                          pad0(ds.passive_features.values, eP)),
        passive_entity=pad0(ds.passive_entity, eP, fill=E_pad),
        passive_rows=pad0(ds.passive_rows, eP, fill=n_sentinel),
        projection=pad0(ds.projection, eE, fill=-1),
    )


def shard_entity_blocks(ds, mesh: Mesh, axis: Optional[str] = None,
                        num_flat_samples: Optional[int] = None):
    """Pad + place a RandomEffectDataset with entities (and passive rows)
    sharded over ``axis`` — the static replacement for the reference's
    entity co-partitioning (RandomEffectDatasetPartitioner.scala:44).

    Default axis: the mesh's "entity" axis when it has one, else "data"
    (entity solves are independent, so reusing the data-axis devices is
    valid and the common single-axis-mesh case)."""
    if axis is None:
        axis = ENTITY_AXIS if ENTITY_AXIS in mesh.axis_names else DATA_AXIS
    ds = pad_entities(ds, axis_size(mesh, axis), num_flat_samples)

    def put(a):
        spec = P(axis, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree.map(put, ds)


# -- feature-dimension (model-parallel) sharding -----------------------------

def shard_features_model_parallel(batch: DataBatch, mesh: Mesh,
                                  data_axis: str = DATA_AXIS,
                                  model_axis: str = MODEL_AXIS) -> DataBatch:
    """Dense-feature model sharding: X is [n, d] sharded (data, model),
    per-sample vectors sharded (data,). Used with a theta placed P(model)
    so margins are psum-ed partial dots (SURVEY §5.7 — the moral
    equivalent of sequence parallelism for billion-feature fixed effects)."""
    assert not isinstance(batch.features, F.SparseFeatures), \
        "model-parallel sharding needs dense features"
    d_mult = axis_size(mesh, model_axis)
    batch = pad_batch(batch, axis_size(mesh, data_axis))
    x = batch.features
    d = x.shape[1]
    d_pad = pad_to_multiple(d, d_mult)
    if d_pad != d:
        x = jnp.pad(x, [(0, 0), (0, d_pad - d)])
    x = jax.device_put(x, NamedSharding(mesh, P(data_axis, model_axis)))

    def put_vec(a):
        return None if a is None else jax.device_put(
            a, NamedSharding(mesh, P(data_axis)))

    return DataBatch(features=x, labels=put_vec(batch.labels),
                     offsets=put_vec(batch.offsets),
                     weights=put_vec(batch.weights))


def shard_coef_model_parallel(coef: jax.Array, mesh: Mesh,
                              model_axis: str = MODEL_AXIS) -> jax.Array:
    d_mult = axis_size(mesh, model_axis)
    d = coef.shape[0]
    d_pad = pad_to_multiple(d, d_mult)
    if d_pad != d:
        coef = jnp.pad(coef, [(0, d_pad - d)])
    return jax.device_put(coef, NamedSharding(mesh, P(model_axis)))
