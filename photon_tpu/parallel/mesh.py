"""Device mesh + sharding helpers: the Spark-cluster replacement.

Reference §5.8: Spark broadcasts / treeAggregate / shuffle joins become one
SPMD program on a `jax.sharding.Mesh`. Conventions:

  * axis "data"   — batch (sample) sharding; gradient reductions ride ICI
                    as psum (the treeAggregate replacement).
  * axis "entity" — random-effect entity-block sharding (the co-partitioned
                    RandomEffectDataset replacement).

Parameters are replicated (`PartitionSpec()`) — the broadcast-variable
replacement; feature-sharded theta for billion-feature fixed effects is the
model-parallel extension (SURVEY §5.7).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
ENTITY_AXIS = "entity"


def create_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    devs = np.asarray(devices[:n])
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    return Mesh(devs.reshape(tuple(shape)), tuple(axis_names))


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Leading-dim sharding for sample-major arrays."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated (the broadcast-variable equivalent)."""
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh, axis: str = DATA_AXIS):
    """Place every array of a DataBatch pytree with its leading dim sharded
    over ``axis``. Pads are the caller's job (static shapes)."""
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def replicate(params, mesh: Mesh):
    sharding = replicated(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), params)


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k
