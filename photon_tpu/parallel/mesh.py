"""Device mesh + sharding: the Spark-cluster replacement, wired into training.

Reference §5.8: Spark broadcasts / treeAggregate / shuffle joins become one
SPMD program on a `jax.sharding.Mesh`. Conventions:

  * axis "data"   — batch (sample) sharding; the `jnp.sum` reductions inside
                    the aggregator kernels (ops/aggregators.py) lower to
                    `all-reduce` over this axis — the treeAggregate
                    replacement (ValueAndGradientAggregator.scala:240-255).
  * axis "entity" — random-effect entity-block sharding (the co-partitioned
                    RandomEffectDataset replacement,
                    RandomEffectDatasetPartitioner.scala:44). Entity solves
                    are independent, so this axis needs no collectives.
  * axis "model"  — feature-dimension sharding of theta for billion-feature
                    fixed effects (SURVEY §5.7): partial dots per shard,
                    psum to form margins.

Parameters are replicated (`PartitionSpec()`) — the broadcast-variable
replacement (DistributedObjectiveFunction.scala:34).

The reference's `treeAggregateDepth` knob (GameEstimator.scala:100) has no
equivalent degree of freedom here: ICI all-reduce topology is chosen by the
XLA compiler/hardware, so the knob is intentionally absent.

Divisibility: NamedSharding needs leading dims divisible by the mesh axis
size, so `pad_batch` / `pad_entities` append zero-weight rows / empty
entity blocks. Zero-weight pads contribute exactly nothing to any
aggregator (every per-sample term is multiplied by its weight) or metric
(all evaluators are weighted).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import features as F

# jax.shard_map only exists from 0.5; this tree pins 0.4.x where the
# implementation lives under jax.experimental. Re-exported so shard_map
# callers (tests, bench bodies) have one version-stable spelling.
try:
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:
    from jax.experimental.shard_map import shard_map

DATA_AXIS = "data"
# cross-slice (DCN) factor of a two-level data axis; see staged_psum
DCN_AXIS = "dcn"
ENTITY_AXIS = "entity"
MODEL_AXIS = "model"


def create_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    devs = np.asarray(devices[:n])
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    return Mesh(devs.reshape(tuple(shape)), tuple(axis_names))


def initialize_distributed(**kwargs) -> int:
    """Multi-host bring-up: call once per process BEFORE any jax use on a
    multi-host pod (the Spark-cluster-join replacement, SURVEY §5.8).
    Returns the process count.

    The multi-host decision is made from the caller's kwargs or the
    coordinator env vars ONLY — touching jax.process_count() first would
    initialize the local backend and doom the real initialize() call,
    silently degrading an 8-host job to 8 independent single-host runs.
    """
    import os as _os

    import jax

    multihost = bool(kwargs) or any(
        v in _os.environ for v in
        ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS"))
    if multihost:
        jax.distributed.initialize(**kwargs)  # raises if jax already used
    return jax.process_count()


def create_pod_mesh(
    model_axis_size: int = 1,
    num_slices: int = 1,
    axis_names: Sequence[str] = (DATA_AXIS, MODEL_AXIS),
) -> Mesh:
    """Global (all-hosts) mesh with DCN-aware axis layout.

    The data axis is OUTERMOST and absorbs the cross-slice (DCN) factor;
    the model axis is innermost so its per-iteration psums of partial
    margins ride ICI only. This is the reference's treeAggregateDepth>1
    staging re-expressed as mesh layout (SURVEY §5.8): one gradient
    all-reduce per step crosses DCN, everything else stays on-chip
    interconnect. With ``num_slices > 1`` the device order comes from
    ``mesh_utils.create_hybrid_device_mesh`` so slice boundaries align
    with the data-axis split.
    """
    from jax.experimental import mesh_utils

    n = len(jax.devices())
    assert n % model_axis_size == 0, (n, model_axis_size)
    data = n // model_axis_size
    if num_slices > 1:
        assert data % num_slices == 0, (data, num_slices)
        devices = mesh_utils.create_hybrid_device_mesh(
            (data // num_slices, model_axis_size), (num_slices, 1))
    else:
        devices = mesh_utils.create_device_mesh((data, model_axis_size))
    return Mesh(devices, tuple(axis_names))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated (the broadcast-variable equivalent)."""
    return NamedSharding(mesh, P())


def create_two_level_mesh(
    n_devices: int,
    dcn_factor: int,
    model_axis_size: int = 1,
    axis_names: Sequence[str] = (DCN_AXIS, DATA_AXIS, MODEL_AXIS),
) -> Mesh:
    """(dcn, data, model) mesh: the data dimension split into a cross-
    slice (DCN) factor and a within-slice (ICI) factor. Gradient
    reductions staged with ``staged_psum`` then ride ICI first and cross
    DCN once — the reference's treeAggregateDepth>1 two-stage aggregation
    (GameEstimator.scala:100) as mesh layout. On real pods, pass device
    order from ``mesh_utils.create_hybrid_device_mesh`` so the dcn axis
    aligns with actual slice boundaries; virtually (CPU) any order
    demonstrates the staged collective structure."""
    if n_devices % (dcn_factor * model_axis_size) != 0:
        raise ValueError(
            f"two-level mesh needs n_devices divisible by dcn_factor * "
            f"model_axis_size, got (n_devices, dcn_factor, model_axis_size)"
            f" = {(n_devices, dcn_factor, model_axis_size)}")
    data = n_devices // (dcn_factor * model_axis_size)
    devices = np.array(jax.devices()[:n_devices]).reshape(
        dcn_factor, data, model_axis_size)
    return Mesh(devices, tuple(axis_names))


def staged_psum(x, ici_axis: str = DATA_AXIS, dcn_axis: str = DCN_AXIS):
    """Two-stage all-reduce for shard_map bodies on a two-level mesh:
    reduce within the slice (ICI) first, then across slices (DCN) — one
    collective per stage with replica groups aligned to each axis (the
    treeAggregateDepth>1 analog; reference: GameEstimator.scala:100,
    treeAggregate depth on the gradient RDD). Equal to a single psum
    over both axes; the staging is the communication-topology win."""
    return jax.lax.psum(jax.lax.psum(x, ici_axis), dcn_axis)


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


# -- batch padding + placement (fixed-effect path) --------------------------

def pad_batch(batch: DataBatch, multiple: int) -> DataBatch:
    """Append zero-weight samples until num_samples % multiple == 0.

    Weights are materialized (implicit all-ones otherwise) so pads carry
    weight 0 and vanish from every aggregator sum.
    """
    n = batch.num_samples
    n_pad = pad_to_multiple(n, multiple)
    if n_pad == n and batch.weights is not None:
        return batch
    extra = n_pad - n

    def pad0(a, rows):
        if a is None:
            return None
        widths = [(0, rows)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    feats = batch.features
    if isinstance(feats, F.SparseFeatures):
        feats = F.SparseFeatures(pad0(feats.indices, extra), pad0(feats.values, extra))
    else:
        feats = pad0(feats, extra)
    weights = batch.weights if batch.weights is not None \
        else jnp.ones_like(batch.labels)
    return DataBatch(
        features=feats,
        labels=pad0(batch.labels, extra),
        offsets=pad0(batch.offsets, extra),
        weights=pad0(weights, extra),
    )


def shard_batch(batch: DataBatch, mesh: Mesh, axis=DATA_AXIS) -> DataBatch:
    """Pad + place a DataBatch with its sample dim sharded over ``axis``.

    ``axis`` may be a tuple of mesh axis names (e.g. ``(DCN_AXIS,
    DATA_AXIS)`` on a two-level mesh) — the sample dim then shards over
    their product, slice-major, matching ``staged_psum``'s reduction
    order.

    The treeAggregate replacement: once inputs are placed this way, the
    jitted aggregator kernels' reductions compile to all-reduce over ICI.
    """
    axes = axis if isinstance(axis, tuple) else (axis,)
    mult = 1
    for a in axes:
        mult *= axis_size(mesh, a)
    batch = pad_batch(batch, mult)
    spec_axis = axes if len(axes) > 1 else axes[0]

    def put(a):
        if a is None:
            return None
        spec = P(spec_axis, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree.map(put, batch)


def count_axis_psums(fn, axis: str, *example_args) -> int:
    """Count ``psum`` equations over mesh axis ``axis`` in the jaxpr of
    ``fn(*example_args)``, recursing into every sub-jaxpr (jit, while,
    cond, scan, shard_map bodies).

    This is the static communication-structure oracle behind the
    hierarchical solver's claim: its round function must contain exactly
    ONE DCN-stage reduction regardless of how many inner iterations run
    (tests/bench assert ``count_axis_psums(round_fn, DCN_AXIS, ...) == 1``
    vs per-iteration for the reference solver)."""
    closed = jax.make_jaxpr(fn)(*example_args)

    def walk(jaxpr) -> int:
        n = 0
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            # shard_map's replication checker rewrites psum into
            # psum-family primitives (psum2 / psum_invariant); all carry
            # the same ``axes`` param and the same wire traffic
            if prim.startswith("psum") and axis in tuple(
                    eqn.params.get("axes", ()) or ()):
                n += 1
            for v in eqn.params.values():
                n += sum(walk(j) for j in _sub_jaxprs(v))
        return n

    def _sub_jaxprs(v):
        core = jax.core
        if isinstance(v, core.ClosedJaxpr):
            return [v.jaxpr]
        if isinstance(v, core.Jaxpr):
            return [v]
        if isinstance(v, (list, tuple)):
            out = []
            for item in v:
                out.extend(_sub_jaxprs(item))
            return out
        return []

    return walk(closed.jaxpr)


def replicate(params, mesh: Mesh):
    sharding = replicated(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), params)


def shard_process_local_batch(
    batch_local: DataBatch,
    mesh: Mesh,
    n_global: int,
    axis: str = DATA_AXIS,
) -> DataBatch:
    """Assemble a GLOBAL sample-sharded DataBatch from each process's own
    row slice — the multi-host ingest boundary (SURVEY §5.8: host-side
    streaming feeds device shards; each host reads only its shard of the
    data, the global array spans every process).

    Call after ``initialize_distributed`` with a mesh over
    ``jax.devices()`` (all processes' devices). ``batch_local`` holds
    THIS process's contiguous rows, in process order: process p
    contributes rows [p*n_global/P, (p+1)*n_global/P). The jitted solve
    over the result runs one SPMD program whose gradient reductions
    cross process boundaries over DCN (Gloo on CPU clusters, ICI/DCN
    collectives on TPU pods) — verified end-to-end by
    tests/test_multihost.py with two real OS processes.
    """
    n_procs = jax.process_count()
    n_local = len(batch_local.labels)
    n_dev = axis_size(mesh, axis)
    if n_local * n_procs != n_global or n_global % n_dev:
        raise ValueError(
            f"global sample count {n_global} must equal local rows "
            f"({n_local}) x processes ({n_procs}) and divide the mesh's "
            f"{axis!r} axis ({n_dev}); pad the LOCAL batch with "
            f"zero-weight rows first (pad_batch semantics)")

    def put(a, extra_dims):
        if a is None:
            return None
        spec = P(axis, *([None] * extra_dims))
        shape = (n_global,) + tuple(a.shape[1:])
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), np.asarray(a), shape)

    feats = batch_local.features
    if isinstance(feats, F.SparseFeatures):
        feats = F.SparseFeatures(put(feats.indices, feats.indices.ndim - 1),
                                 put(feats.values, feats.values.ndim - 1))
    else:
        feats = put(feats, feats.ndim - 1)
    return DataBatch(
        features=feats,
        labels=put(batch_local.labels, 0),
        offsets=put(batch_local.offsets, 0),
        weights=put(batch_local.weights, 0),
    )


def replicate_from_process_local(x, mesh: Mesh):
    """Replicated global array from identical per-process host values
    (multi-host analog of ``replicate``; e.g. the initial coefficients)."""
    a = np.asarray(x)
    return jax.make_array_from_process_local_data(
        replicated(mesh), a, a.shape)


# -- entity-block padding + placement (random-effect path) -------------------

def pad_entities(ds, multiple: int, num_flat_samples: Optional[int] = None):
    """Pad each entity block's row dim (and the passive rows) of a
    RandomEffectDataset so all shard evenly; pad rows carry zero weights,
    out-of-range entity rows, and scatter rows at the drop sentinel
    ``num_flat_samples`` (the 'n on pads' invariant of sample_rows)."""
    from photon_tpu.game.random_effect import EntityBlock, RandomEffectDataset

    E = ds.num_entities
    Ppas = ds.passive_entity.shape[0]
    P_pad = pad_to_multiple(Ppas, multiple)

    def pad0(a, rows, fill=0):
        widths = [(0, rows)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=fill)

    def sentinel(rows_arr):
        if num_flat_samples is not None:
            return num_flat_samples
        # max is safe only when build-time pads (== n) exist; max+1 always is
        return int(jnp.max(rows_arr)) + 1 if rows_arr.size else 0

    blocks = []
    changed = P_pad != Ppas
    for blk in ds.blocks:
        E_b = blk.num_rows
        E_b_pad = pad_to_multiple(E_b, multiple)
        if E_b_pad == E_b:
            blocks.append(blk)
            continue
        changed = True
        e = E_b_pad - E_b
        blocks.append(EntityBlock(
            features=F.SparseFeatures(pad0(blk.features.indices, e),
                                      pad0(blk.features.values, e)),
            labels=pad0(blk.labels, e),
            offsets=pad0(blk.offsets, e),
            weights=pad0(blk.weights, e),
            sample_rows=pad0(blk.sample_rows, e, fill=sentinel(blk.sample_rows)),
            entity_rows=pad0(blk.entity_rows, e, fill=E),  # out of range -> drop
        ))
    if not changed:
        return ds

    eP = P_pad - Ppas
    return RandomEffectDataset(
        blocks=tuple(blocks),
        passive_features=F.SparseFeatures(pad0(ds.passive_features.indices, eP),
                                          pad0(ds.passive_features.values, eP)),
        passive_entity=pad0(ds.passive_entity, eP, fill=E),
        passive_rows=pad0(ds.passive_rows, eP,
                          fill=sentinel(ds.passive_rows)),
        projection=ds.projection,
    )


def entity_axis_assignment(entity_ids: Sequence, mesh: Mesh,
                           axis: Optional[str] = None) -> np.ndarray:
    """Device-slot assignment for named entities along the entity axis,
    via the canonical partitioner (`parallel/partition.entity_shard`) —
    the SAME hash the cold-store splitter and serving-fleet router use,
    so train-time placement and serve-time routing provably agree.

    `shard_entity_blocks` itself places whatever block order the caller
    built; callers that want fleet-aligned placement order their entity
    rows by this assignment first (the serving fleet depends only on the
    hash, not on any one training layout)."""
    from photon_tpu.parallel.partition import entity_shards
    if axis is None:
        axis = ENTITY_AXIS if ENTITY_AXIS in mesh.axis_names else DATA_AXIS
    return entity_shards(entity_ids, axis_size(mesh, axis))


def shard_entity_blocks(ds, mesh: Mesh, axis: Optional[str] = None,
                        num_flat_samples: Optional[int] = None):
    """Pad + place a RandomEffectDataset with entities (and passive rows)
    sharded over ``axis`` — the static replacement for the reference's
    entity co-partitioning (RandomEffectDatasetPartitioner.scala:44).

    Default axis: the mesh's "entity" axis when it has one, else "data"
    (entity solves are independent, so reusing the data-axis devices is
    valid and the common single-axis-mesh case). For placement that lines
    up with the serving fleet's shard ownership, order entity rows by
    `entity_axis_assignment` (the canonical `parallel/partition` hash)
    before calling this."""
    if axis is None:
        axis = ENTITY_AXIS if ENTITY_AXIS in mesh.axis_names else DATA_AXIS
    ds = pad_entities(ds, axis_size(mesh, axis), num_flat_samples)

    def put(a):
        spec = P(axis, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))

    blocks = tuple(jax.tree.map(put, b) for b in ds.blocks)
    return type(ds)(
        blocks=blocks,
        passive_features=jax.tree.map(put, ds.passive_features),
        passive_entity=put(ds.passive_entity),
        passive_rows=put(ds.passive_rows),
        # the projection's entity dim is not padded — replicate it (it is
        # only consulted on the host and for scoring-frame projection)
        projection=jax.device_put(ds.projection, replicated(mesh)),
    )


# -- feature-dimension (model-parallel) sharding -----------------------------

def shard_features_model_parallel(batch: DataBatch, mesh: Mesh,
                                  data_axis: str = DATA_AXIS,
                                  model_axis: str = MODEL_AXIS) -> DataBatch:
    """Dense-feature model sharding: X is [n, d] sharded (data, model),
    per-sample vectors sharded (data,). Used with a theta placed P(model)
    so margins are psum-ed partial dots (SURVEY §5.7 — the moral
    equivalent of sequence parallelism for billion-feature fixed effects)."""
    assert not isinstance(batch.features, F.SparseFeatures), \
        "model-parallel sharding needs dense features"
    d_mult = axis_size(mesh, model_axis)
    batch = pad_batch(batch, axis_size(mesh, data_axis))
    x = batch.features
    d = x.shape[1]
    d_pad = pad_to_multiple(d, d_mult)
    if d_pad != d:
        x = jnp.pad(x, [(0, 0), (0, d_pad - d)])
    x = jax.device_put(x, NamedSharding(mesh, P(data_axis, model_axis)))

    def put_vec(a):
        return None if a is None else jax.device_put(
            a, NamedSharding(mesh, P(data_axis)))

    return DataBatch(features=x, labels=put_vec(batch.labels),
                     offsets=put_vec(batch.offsets),
                     weights=put_vec(batch.weights))


def shard_coef_model_parallel(coef: jax.Array, mesh: Mesh,
                              model_axis: str = MODEL_AXIS,
                              padded_dim: Optional[int] = None) -> jax.Array:
    d_mult = axis_size(mesh, model_axis)
    d = coef.shape[0]
    d_pad = padded_dim if padded_dim is not None else pad_to_multiple(d, d_mult)
    if d_pad != d:
        coef = jnp.pad(coef, [(0, d_pad - d)])
    sharding = NamedSharding(mesh, P(model_axis))
    if jax.process_count() > 1:
        # multi-host: every process holds the identical global coef, so
        # each addressable shard materializes from its global index slice
        host = np.asarray(coef)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda i: host[i])
    return jax.device_put(coef, sharding)


def shard_sparse_features_model_parallel(
    batch: DataBatch, mesh: Mesh, dim: int,
    data_axis: str = DATA_AXIS, model_axis: str = MODEL_AXIS) -> DataBatch:
    """Sparse (ELL) feature-range sharding for model-parallel theta
    (SURVEY §5.7, reference scale claim README.md:56): nonzeros are
    re-partitioned ON THE HOST into per-range ELL blocks with local ids
    (ops/features.partition_by_feature_range), placed ``P(model, data)``.
    Margins then psum partial gather-dots over the model axis; gradients
    run as contiguous segment reductions over a column-sorted view of the
    same nonzeros (ops/features.build_csc_plan), psum-ed over the data
    axis — the billion-feature fixed effect trains without theta ever
    being replicated.

    On a two-level mesh carrying a ``dcn`` axis (create_two_level_mesh)
    the sample dim shards over ``(dcn, data)`` and gradient reductions
    stage ICI-then-DCN (staged_psum as layout). Multi-process meshes are
    supported when every process holds the identical global batch: shards
    are then materialized per process from the globally-computed plan."""
    assert isinstance(batch.features, F.SparseFeatures), \
        "model-parallel sparse sharding needs ELL features"
    dcn_axis = DCN_AXIS if DCN_AXIS in mesh.axis_names else None
    n_shards = axis_size(mesh, model_axis)
    n_chunks = axis_size(mesh, data_axis) * (
        axis_size(mesh, dcn_axis) if dcn_axis else 1)
    batch = pad_batch(batch, n_chunks)
    idx, val, shard_size = F.partition_by_feature_range(
        batch.features, dim, n_shards)
    rows, vals, ptr = F.build_csc_plan(
        batch.features, dim, n_shards, n_chunks)
    sample = (dcn_axis, data_axis) if dcn_axis else data_axis
    block = NamedSharding(mesh, P(model_axis, sample, None))

    def put(a, sharding):
        # multi-host: every process computed the identical global arrays,
        # so each shard is materialized from its global index slice
        if jax.process_count() > 1:
            a = np.asarray(a)
            return jax.make_array_from_callback(
                a.shape, sharding, lambda i: a[i])
        return jax.device_put(jnp.asarray(a), sharding)

    feats = F.ModelShardedSparse(
        indices=put(idx, block), values=put(val, block),
        shard_size=shard_size, mesh=mesh,
        data_axis=data_axis, model_axis=model_axis,
        csc_rows=put(rows, block), csc_vals=put(vals, block),
        csc_ptr=put(ptr, block), dcn_axis=dcn_axis)

    vec = NamedSharding(mesh, P(sample))

    def put_vec(a):
        return None if a is None else put(a, vec)

    return DataBatch(features=feats, labels=put_vec(batch.labels),
                     offsets=put_vec(batch.offsets),
                     weights=put_vec(batch.weights))


def plan_group_placement(members: Sequence[str],
                         mesh: Mesh) -> Dict[str, List[int]]:
    """Disjoint device subsets for one parallel-CD concurrency group:
    the mesh's devices are split into ``len(members)`` contiguous
    near-equal chunks (update-sequence order), so concurrent member
    solves target non-overlapping hardware. Returns coordinate id ->
    device ids; a member's list is empty when there are more members
    than devices (it shares by time-slicing instead).

    This is the host-side PLAN recorded in the RunReport ``cd.parallel``
    section. Actually re-placing each coordinate's construction-time
    sharded arrays onto its subset needs a live multi-chip topology to
    validate against and stays open (ROADMAP: mesh placement on real TPU
    topology); on a single host the overlap comes from async dispatch.
    """
    devs = [int(getattr(d, "id", i))
            for i, d in enumerate(mesh.devices.flat)]
    n, m = len(devs), len(members)
    plan: Dict[str, List[int]] = {}
    for i, cid in enumerate(members):
        lo = (i * n) // m
        hi = ((i + 1) * n) // m
        plan[cid] = devs[lo:hi]
    return plan


def mesh_topology(mesh: Optional[Mesh] = None) -> dict:
    """JSON-ready description of the run's process/device topology (and a
    mesh's axis layout, when one is active) for the telemetry RunReport.

    Safe to call before/without distributed init and with no accelerator:
    everything is guarded, and nothing here forces backend initialization
    beyond what the caller already did (a driver calls this after data is
    placed, so devices are long since live).
    """
    out: dict = {}
    try:
        out["process_index"] = jax.process_index()
        out["process_count"] = jax.process_count()
        out["local_device_count"] = jax.local_device_count()
        out["global_device_count"] = jax.device_count()
        devs = jax.local_devices()
        if devs:
            out["platform"] = devs[0].platform
            out["device_kind"] = getattr(devs[0], "device_kind", None)
    except Exception:  # hygiene-ok — topology is best-effort telemetry
        pass
    if mesh is not None:
        try:
            out["mesh"] = {
                "axis_names": list(mesh.axis_names),
                "axis_sizes": {name: int(size) for name, size in
                               zip(mesh.axis_names, mesh.devices.shape)},
                "num_devices": int(mesh.devices.size),
            }
        except Exception:  # hygiene-ok — mesh shape is best-effort telemetry
            pass
    return out
