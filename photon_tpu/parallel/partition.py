"""Canonical entity -> shard partitioner shared by training placement,
cold-store file layout, and serving-fleet request routing.

One function is the whole contract: ``entity_shard(entity_id, num_shards)``.
Training-time entity placement (`parallel/mesh.shard_entity_blocks`), the
per-shard cold-store split (`io/fleet_store.split_cold_store`), the fleet
request router (`serving/fleet.ShardedServingFleet`), and the nearline
publish fan-out (`nearline/publisher.publish_fleet`) all import it from
here, so a row written by the trainer, laid out by the splitter, and
published by the nearline pipeline provably lands on the shard the router
queries.

The hash is ``zlib.crc32`` over the entity id's utf-8 bytes — the same
checksum primitive the cold-store format and every manifest in the repo
already use, stable across processes/platforms/Python versions (unlike
``hash()``), and cheap to vectorize. Entity ids are strings everywhere at
the serving boundary (`ScoreRequest.entity_ids`, cold-store id tables);
non-string ids (e.g. negative ints from raw training frames) partition by
their ``str()`` form so both sides agree without a schema change.
"""

from __future__ import annotations

import zlib
from typing import Iterable, List, Sequence

import numpy as np

__all__ = [
    "crc32_ids",
    "entity_shard",
    "entity_shards",
    "partition_ids",
    "validate_num_shards",
]


def validate_num_shards(num_shards: int) -> int:
    if not isinstance(num_shards, (int, np.integer)) or num_shards < 1:
        raise ValueError(f"num_shards must be a positive int, got {num_shards!r}")
    return int(num_shards)


def _id_bytes(entity_id) -> bytes:
    if isinstance(entity_id, bytes):
        return entity_id
    if not isinstance(entity_id, str):
        entity_id = str(entity_id)
    return entity_id.encode("utf-8")


def entity_shard(entity_id, num_shards: int) -> int:
    """The canonical entity->shard map: crc32(utf-8 id) mod num_shards.

    Accepts str (the serving/cold-store form), bytes (already-encoded id
    tables), or anything else via ``str()`` (e.g. int ids in training
    frames). With ``num_shards == 1`` every id maps to shard 0.
    """
    n = validate_num_shards(num_shards)
    return (zlib.crc32(_id_bytes(entity_id)) & 0xFFFFFFFF) % n


_CRC_TABLE: np.ndarray = None


def _crc_table() -> np.ndarray:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        t = np.arange(256, dtype=np.uint32)
        for _ in range(8):
            t = np.where(t & 1, (t >> 1) ^ np.uint32(0xEDB88320),
                         t >> 1).astype(np.uint32)
        _CRC_TABLE = t
    return _CRC_TABLE


def crc32_ids(ids: np.ndarray) -> np.ndarray:
    """Vectorized ``zlib.crc32`` over a 1-D numpy byte/str id array ->
    uint32 array, bit-identical to per-element ``zlib.crc32`` (the
    pinning test asserts this). Byte-column-at-a-time table CRC, so a
    100M-entity id table partitions in seconds instead of the minutes a
    Python loop takes — the path the cold-store splitter and bulk
    placement use."""
    arr = np.asarray(ids)
    if arr.dtype.kind == "U":
        arr = np.char.encode(arr, "utf-8")
    if arr.dtype.kind != "S" or arr.ndim != 1:
        raise TypeError(f"crc32_ids needs a 1-D S/U array, got "
                        f"{arr.dtype} ndim={arr.ndim}")
    width = arr.dtype.itemsize
    n = arr.shape[0]
    if n == 0 or width == 0:
        return np.zeros(n, dtype=np.uint32)
    mat = np.ascontiguousarray(arr).view(np.uint8).reshape(n, width)
    # numpy S items drop trailing NULs on access, so per-element
    # zlib.crc32 sees np.char.str_len bytes — mirror that exactly
    lengths = np.char.str_len(arr)
    table = _crc_table()
    crc = np.full(n, 0xFFFFFFFF, dtype=np.uint32)
    for j in range(width):
        active = lengths > j
        nxt = table[(crc ^ mat[:, j]) & np.uint32(0xFF)] ^ (crc >> np.uint32(8))
        crc = np.where(active, nxt, crc)
    return crc ^ np.uint32(0xFFFFFFFF)


def entity_shards(entity_ids: Iterable, num_shards: int) -> np.ndarray:
    """Vectorized ``entity_shard`` over a sequence of ids -> int32 array.

    Numpy byte/str arrays take the column-parallel CRC path; anything
    else (lists of ints, object arrays) falls back to the per-element
    hash — both are bit-identical to ``entity_shard``."""
    n = validate_num_shards(num_shards)
    if isinstance(entity_ids, np.ndarray):
        arr = entity_ids
    else:
        entity_ids = list(entity_ids)
        arr = np.asarray(entity_ids) if entity_ids else \
            np.zeros(0, dtype="S1")
    if arr.ndim == 1 and arr.dtype.kind in ("S", "U"):
        return (crc32_ids(arr) % np.uint32(n)).astype(np.int32)
    return np.fromiter(
        ((zlib.crc32(_id_bytes(e)) & 0xFFFFFFFF) % n for e in entity_ids),
        dtype=np.int32)


def partition_ids(entity_ids: Sequence, num_shards: int) -> List[List[int]]:
    """Group ``entity_ids`` by owning shard -> per-shard index lists.

    Returns ``num_shards`` lists; list ``s`` holds the positions (into the
    input sequence) of every id owned by shard ``s``, in input order —
    the shape the cold-store splitter and publish fan-out both need.
    """
    n = validate_num_shards(num_shards)
    out: List[List[int]] = [[] for _ in range(n)]
    if n == 1:
        out[0] = list(range(len(entity_ids)))
        return out
    for i, s in enumerate(entity_shards(entity_ids, n)):
        out[int(s)].append(i)
    return out
