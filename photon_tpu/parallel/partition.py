"""Canonical entity -> shard partitioner shared by training placement,
cold-store file layout, and serving-fleet request routing.

One function is the whole contract: ``entity_shard(entity_id, num_shards)``.
Training-time entity placement (`parallel/mesh.shard_entity_blocks`), the
per-shard cold-store split (`io/fleet_store.split_cold_store`), the fleet
request router (`serving/fleet.ShardedServingFleet`), and the nearline
publish fan-out (`nearline/publisher.publish_fleet`) all import it from
here, so a row written by the trainer, laid out by the splitter, and
published by the nearline pipeline provably lands on the shard the router
queries.

The hash is ``zlib.crc32`` over the entity id's utf-8 bytes — the same
checksum primitive the cold-store format and every manifest in the repo
already use, stable across processes/platforms/Python versions (unlike
``hash()``), and cheap to vectorize. Entity ids are strings everywhere at
the serving boundary (`ScoreRequest.entity_ids`, cold-store id tables);
non-string ids (e.g. negative ints from raw training frames) partition by
their ``str()`` form so both sides agree without a schema change.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Iterable, List, Sequence, Tuple

import numpy as np

__all__ = [
    "BucketMap",
    "DEFAULT_NUM_BUCKETS",
    "crc32_ids",
    "entity_bucket",
    "entity_buckets",
    "entity_shard",
    "entity_shards",
    "partition_ids",
    "validate_num_buckets",
    "validate_num_shards",
]

#: default virtual-bucket count for new (v2) fleet layouts. Power of two
#: and far above any realistic shard count, so bucket->shard rebalancing
#: moves fine-grained slices of the keyspace (Dynamo virtual nodes /
#: Redis Cluster slots, adapted to the crc32 partitioner).
DEFAULT_NUM_BUCKETS = 1024


def validate_num_shards(num_shards: int) -> int:
    if not isinstance(num_shards, (int, np.integer)) or num_shards < 1:
        raise ValueError(f"num_shards must be a positive int, got {num_shards!r}")
    return int(num_shards)


def validate_num_buckets(num_buckets: int) -> int:
    """Virtual-bucket counts are pinned to powers of two: the bucket id
    is a stable function of the entity hash alone, so the count can never
    be 'rebalanced' — refusing non-powers keeps anyone from treating it
    as a tunable and silently stranding every row."""
    if (not isinstance(num_buckets, (int, np.integer)) or num_buckets < 1
            or (int(num_buckets) & (int(num_buckets) - 1)) != 0):
        raise ValueError(
            f"num_buckets must be a positive power of two, got "
            f"{num_buckets!r}")
    return int(num_buckets)


def _id_bytes(entity_id) -> bytes:
    if isinstance(entity_id, bytes):
        return entity_id
    if not isinstance(entity_id, str):
        entity_id = str(entity_id)
    return entity_id.encode("utf-8")


def entity_shard(entity_id, num_shards: int) -> int:
    """The canonical entity->shard map: crc32(utf-8 id) mod num_shards.

    Accepts str (the serving/cold-store form), bytes (already-encoded id
    tables), or anything else via ``str()`` (e.g. int ids in training
    frames). With ``num_shards == 1`` every id maps to shard 0.
    """
    n = validate_num_shards(num_shards)
    return (zlib.crc32(_id_bytes(entity_id)) & 0xFFFFFFFF) % n


_CRC_TABLE: np.ndarray = None


def _crc_table() -> np.ndarray:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        t = np.arange(256, dtype=np.uint32)
        for _ in range(8):
            t = np.where(t & 1, (t >> 1) ^ np.uint32(0xEDB88320),
                         t >> 1).astype(np.uint32)
        _CRC_TABLE = t
    return _CRC_TABLE


def crc32_ids(ids: np.ndarray) -> np.ndarray:
    """Vectorized ``zlib.crc32`` over a 1-D numpy byte/str id array ->
    uint32 array, bit-identical to per-element ``zlib.crc32`` (the
    pinning test asserts this). Byte-column-at-a-time table CRC, so a
    100M-entity id table partitions in seconds instead of the minutes a
    Python loop takes — the path the cold-store splitter and bulk
    placement use."""
    arr = np.asarray(ids)
    if arr.dtype.kind == "U":
        arr = np.char.encode(arr, "utf-8")
    if arr.dtype.kind != "S" or arr.ndim != 1:
        raise TypeError(f"crc32_ids needs a 1-D S/U array, got "
                        f"{arr.dtype} ndim={arr.ndim}")
    width = arr.dtype.itemsize
    n = arr.shape[0]
    if n == 0 or width == 0:
        return np.zeros(n, dtype=np.uint32)
    mat = np.ascontiguousarray(arr).view(np.uint8).reshape(n, width)
    # numpy S items drop trailing NULs on access, so per-element
    # zlib.crc32 sees np.char.str_len bytes — mirror that exactly
    lengths = np.char.str_len(arr)
    table = _crc_table()
    crc = np.full(n, 0xFFFFFFFF, dtype=np.uint32)
    for j in range(width):
        active = lengths > j
        nxt = table[(crc ^ mat[:, j]) & np.uint32(0xFF)] ^ (crc >> np.uint32(8))
        crc = np.where(active, nxt, crc)
    return crc ^ np.uint32(0xFFFFFFFF)


def entity_shards(entity_ids: Iterable, num_shards: int) -> np.ndarray:
    """Vectorized ``entity_shard`` over a sequence of ids -> int32 array.

    Numpy byte/str arrays take the column-parallel CRC path; anything
    else (lists of ints, object arrays) falls back to the per-element
    hash — both are bit-identical to ``entity_shard``."""
    n = validate_num_shards(num_shards)
    if isinstance(entity_ids, np.ndarray):
        arr = entity_ids
    else:
        entity_ids = list(entity_ids)
        arr = np.asarray(entity_ids) if entity_ids else \
            np.zeros(0, dtype="S1")
    if arr.ndim == 1 and arr.dtype.kind in ("S", "U"):
        return (crc32_ids(arr) % np.uint32(n)).astype(np.int32)
    return np.fromiter(
        ((zlib.crc32(_id_bytes(e)) & 0xFFFFFFFF) % n for e in entity_ids),
        dtype=np.int32)


def entity_bucket(entity_id, num_buckets: int = DEFAULT_NUM_BUCKETS) -> int:
    """The canonical entity->virtual-bucket map: crc32(utf-8 id) mod a
    fixed power-of-two bucket count. Same hash as ``entity_shard`` —
    only the modulus differs — so the two levels of the v2 partition
    (entity -> bucket -> shard) share one pinned primitive."""
    n = validate_num_buckets(num_buckets)
    return (zlib.crc32(_id_bytes(entity_id)) & 0xFFFFFFFF) % n


def entity_buckets(entity_ids: Iterable,
                   num_buckets: int = DEFAULT_NUM_BUCKETS) -> np.ndarray:
    """Vectorized ``entity_bucket`` -> int32 array (same fast/slow path
    split as ``entity_shards``, bit-identical to the scalar form)."""
    n = validate_num_buckets(num_buckets)
    if isinstance(entity_ids, np.ndarray):
        arr = entity_ids
    else:
        entity_ids = list(entity_ids)
        arr = np.asarray(entity_ids) if entity_ids else \
            np.zeros(0, dtype="S1")
    if arr.ndim == 1 and arr.dtype.kind in ("S", "U"):
        return (crc32_ids(arr) % np.uint32(n)).astype(np.int32)
    return np.fromiter(
        ((zlib.crc32(_id_bytes(e)) & 0xFFFFFFFF) % n for e in entity_ids),
        dtype=np.int32)


@dataclass(frozen=True)
class BucketMap:
    """Versioned virtual-bucket -> shard assignment — the mutable second
    level of the v2 two-level partition.

    ``assignment[b]`` is the shard owning bucket ``b``. The map is an
    immutable value: rebalancing produces a new map via
    ``with_assignment`` and publishes it through a fleet-manifest version
    bump, so a router swaps the whole assignment atomically (one
    reference store) and two routers holding different versions disagree
    only about buckets mid-migration.

    Two constructors cover the compat matrix:

    - ``identity(n)``: ``num_buckets == num_shards``, bucket b -> shard
      b. This is exactly the v1 single-level partition (shard =
      crc32 % n for ANY n, power of two or not), so v1 manifests read
      as the degenerate identity map with bitwise-identical routing.
    - ``initial(num_buckets, num_shards)``: the canonical fresh v2
      layout, bucket b -> shard b % num_shards. With a power-of-two
      bucket count and power-of-two shard count this composes to
      crc32 % num_shards, i.e. byte-identical files to the v1 split.
    """

    num_buckets: int
    assignment: Tuple[int, ...] = field(default=())

    def __post_init__(self):
        # identity maps inherit v1's any-positive-N domain; only
        # entity_bucket/new v2 layouts pin power-of-two counts
        if (not isinstance(self.num_buckets, int)
                or self.num_buckets < 1):
            raise ValueError(
                f"num_buckets must be a positive int, got "
                f"{self.num_buckets!r}")
        a = tuple(int(s) for s in self.assignment)
        if len(a) != self.num_buckets:
            raise ValueError(
                f"assignment length {len(a)} != num_buckets "
                f"{self.num_buckets}")
        if a and min(a) < 0:
            raise ValueError("assignment has negative shard ids")
        object.__setattr__(self, "assignment", a)
        object.__setattr__(self, "_shard_arr",
                           np.asarray(a, dtype=np.int32))

    @staticmethod
    def identity(num_shards: int) -> "BucketMap":
        """The degenerate v1 map: one bucket per shard, bucket b ->
        shard b, so ``shard_for_entity == entity_shard`` exactly."""
        n = validate_num_shards(num_shards)
        return BucketMap(n, tuple(range(n)))

    @staticmethod
    def initial(num_buckets: int, num_shards: int) -> "BucketMap":
        """Fresh v2 layout: bucket b -> shard b % num_shards."""
        nb = validate_num_buckets(num_buckets)
        ns = validate_num_shards(num_shards)
        if ns > nb:
            raise ValueError(
                f"num_shards {ns} > num_buckets {nb}: some shards would "
                "own no buckets")
        return BucketMap(nb, tuple(b % ns for b in range(nb)))

    @property
    def num_shards(self) -> int:
        return (max(self.assignment) + 1) if self.assignment else 0

    @property
    def shard_ids(self) -> Tuple[int, ...]:
        return tuple(sorted(set(self.assignment)))

    def bucket_of(self, entity_id) -> int:
        # identity maps inherit v1's any-N modulus, so hash directly
        # rather than through entity_bucket's power-of-two gate
        return (zlib.crc32(_id_bytes(entity_id)) & 0xFFFFFFFF) \
            % self.num_buckets

    def shard_of(self, bucket: int) -> int:
        return self.assignment[bucket]

    def shard_for_entity(self, entity_id) -> int:
        return self.assignment[self.bucket_of(entity_id)]

    def shards_for_ids(self, entity_ids: Iterable) -> np.ndarray:
        """Vectorized ``shard_for_entity`` -> int32 array (the
        cold-store splitter's bulk path)."""
        buckets = entity_shards(entity_ids, self.num_buckets)
        return self._shard_arr[buckets]

    def buckets_on(self, shard_id: int) -> Tuple[int, ...]:
        return tuple(b for b, s in enumerate(self.assignment)
                     if s == int(shard_id))

    def with_assignment(self, bucket: int, shard_id: int) -> "BucketMap":
        """New map with one bucket reassigned — the cutover primitive."""
        b = int(bucket)
        if not (0 <= b < self.num_buckets):
            raise ValueError(f"bucket {bucket!r} out of range "
                             f"[0, {self.num_buckets})")
        a = list(self.assignment)
        a[b] = int(shard_id)
        return BucketMap(self.num_buckets, tuple(a))

    def to_json(self) -> dict:
        return {"num_buckets": self.num_buckets,
                "assignment": list(self.assignment)}

    @staticmethod
    def from_json(doc: dict) -> "BucketMap":
        if (not isinstance(doc, dict)
                or not isinstance(doc.get("num_buckets"), int)
                or not isinstance(doc.get("assignment"), list)):
            raise ValueError(f"bad bucket map document: {doc!r}")
        return BucketMap(doc["num_buckets"], tuple(doc["assignment"]))


def partition_ids(entity_ids: Sequence, num_shards: int) -> List[List[int]]:
    """Group ``entity_ids`` by owning shard -> per-shard index lists.

    Returns ``num_shards`` lists; list ``s`` holds the positions (into the
    input sequence) of every id owned by shard ``s``, in input order —
    the shape the cold-store splitter and publish fan-out both need.
    """
    n = validate_num_shards(num_shards)
    out: List[List[int]] = [[] for _ in range(n)]
    if n == 1:
        out[0] = list(range(len(entity_ids)))
        return out
    for i, s in enumerate(entity_shards(entity_ids, n)):
        out[int(s)].append(i)
    return out
