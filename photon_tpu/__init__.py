"""photon-tpu: a TPU-native framework for GLM and GAME/GLMix training.

A ground-up JAX/XLA re-design of the capabilities of LinkedIn Photon ML
(reference mounted at /root/reference): generalized linear models (linear,
logistic, Poisson regression, smoothed-hinge SVM) and GAME mixed-effect
models (one fixed-effect GLM plus per-entity random-effect GLMs trained by
coordinate descent) — executed as SPMD programs on a TPU device mesh instead
of Spark RDD jobs.
"""

__version__ = "0.1.0"

from photon_tpu.types import TaskType, OptimizerType, VarianceComputationType  # noqa: F401
