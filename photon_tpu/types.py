"""Core type aliases and task enums.

TPU-native re-design of the reference's type vocabulary
(reference: photon-lib .../Types.scala:21-44, TaskType.scala:24).
"""

from __future__ import annotations

import enum

# Reference: UniqueSampleId = Long, CoordinateId = String, REId = String.
# In the TPU build, sample / entity identity is positional: every sample has a
# dense row index in the device-resident arrays, and entities have dense block
# indices assigned at ingest. The string identities survive only on the host
# side (ingest tables, model IO).
UniqueSampleId = int
CoordinateId = str
REId = str
REType = str
FeatureShardId = str


class TaskType(enum.Enum):
    """Supported GLM training tasks (reference: TaskType.scala:24)."""

    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"

    @property
    def is_classification(self) -> bool:
        return self in (
            TaskType.LOGISTIC_REGRESSION,
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )


class VarianceComputationType(enum.Enum):
    """Coefficient-variance computation mode
    (reference: optimization/VarianceComputationType.scala:20)."""

    NONE = "NONE"
    SIMPLE = "SIMPLE"  # 1 / diag(H)
    FULL = "FULL"      # diag(H^-1) via Cholesky


class OptimizerType(enum.Enum):
    """Available convex solvers (reference: optimization/OptimizerType.scala)."""

    LBFGS = "LBFGS"
    OWLQN = "OWLQN"
    LBFGSB = "LBFGSB"
    TRON = "TRON"
    # TPU-native extension (no reference analog): exact normal-equations
    # solve for squared loss — one weighted-Gram contraction (MXU) plus a
    # Cholesky factorization, batched over entities under vmap. The same
    # minimizer the iterative solvers converge to, computed directly
    # (sklearn Ridge's own cholesky solver is the CPU-world equivalent).
    DIRECT = "DIRECT"
    # TPU-native extension: chunk-local stochastic dual coordinate ascent
    # over the streaming chunk store (optim/sdca.py) — one storage pass
    # per outer epoch with a duality-gap stopping certificate, for fits
    # whose data lives on disk (Snap ML / TPA-SCD, see PAPERS.md).
    SDCA = "SDCA"
    # TPU-native extension (no reference analog): damped Newton / IRLS
    # with an explicit Hessian Cholesky per outer iteration — DIRECT's
    # batched [E, K, K] machinery extended to logistic/Poisson, replacing
    # TRON's nested outer x CG sequential loop with ~5 batched
    # factorizations (optim/newton.py).
    NEWTON = "NEWTON"
