"""Lane-batched solvers: K hyperparameter configurations in ONE program.

Photon ML's tuner treats every regularization setting as a separate full
training run, so a K-point sweep costs K data passes. Stacking the K
coefficient vectors into a ``[K, d]`` array turns the per-example margin
into an ``[n, K]`` matmul the MXU executes at near-constant cost for
small K — the shared-data-pass economics of hierarchical GLM training
(Snap ML, arXiv:1803.06333).

The mechanism is ``jax.vmap`` over the existing lax-level L-BFGS /
OWL-QN solvers, which the batching rules turn into exactly the program
we want:

- the dense data term ``x @ theta`` vmapped over ``theta`` becomes one
  ``X Θᵀ`` dot_general; the sparse-ELL gather ``theta[x.indices]``
  becomes one stacked gather over the shared plan — the batch itself is
  closed over inside the trace, never copied per lane;
- each lane gets an *independent* line search (the inner while_loop is
  vmapped like the outer one);
- the outer ``lax.while_loop`` cond becomes "any lane still active" and
  every carry update is ``where``-selected per lane, so converged lanes
  freeze bitwise (their ``it``/``reason`` stop advancing) while the
  rest continue — the loop exits when all lanes converge, with no
  recompiles as lanes finish and no host syncs;
- a lane that hits a typed ``FailureMode`` (e.g. NaN-poisoned data)
  freezes the same way without sinking its siblings;
- with K=1 the "any over one lane" cond is the scalar cond, so the
  singleton-lane program takes exactly the scalar solver's iteration
  count.

On a mesh the whole vmapped solve runs inside ONE outer shard_map over
the sample axes; the per-evaluation reduction is a single staged
ICI→DCN psum of the packed ``[K, d+1]`` value/gradient block (the
collective batching rule keeps it one psum eqn regardless of K).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from photon_tpu.function.objective import GLMObjective, Hyper
from photon_tpu.optim import lbfgs, owlqn
from photon_tpu.optim.base import SolverConfig, SolverResult

Array = jax.Array

# value_and_gradient(coef [d], hyper) -> (value, grad [d]) for ONE lane;
# the data batch is closed over so every lane shares it.
LaneValueAndGradient = Callable[[Array, Hyper], Tuple[Array, Array]]


class SweepWeightError(ValueError):
    """A sweep/tuning regularization weight is refused at config time.

    Raised for empty grids and negative / non-finite weights — before
    anything is traced, so a bad grid can never poison a compiled solve.
    """


def validate_lane_weights(weights: Sequence[float],
                          name: str = "regularization weight") -> np.ndarray:
    """Validate a sweep grid; returns the weights as a float64 1-D array.

    The single chokepoint for every path that accepts sweep weights
    (``solve_swept``, ``CoordinateConfiguration.with_regularization_weight``,
    ``cli/train --sweep-l2``): negative and non-finite values raise a
    typed :class:`SweepWeightError` here, at config time, never inside
    the compiled program.
    """
    arr = np.atleast_1d(np.asarray(weights, dtype=np.float64))
    if arr.ndim != 1 or arr.size == 0:
        raise SweepWeightError(
            f"{name} grid must be a non-empty 1-D sequence, got shape "
            f"{arr.shape}")
    if not np.all(np.isfinite(arr)):
        bad = arr[~np.isfinite(arr)]
        raise SweepWeightError(
            f"{name} grid contains non-finite values {bad.tolist()}")
    if np.any(arr < 0):
        bad = arr[arr < 0]
        raise SweepWeightError(
            f"{name} grid contains negative values {bad.tolist()}")
    return arr


def pad_lane_grid(weights: Sequence[float],
                  chunk: int) -> List[Tuple[np.ndarray, int]]:
    """Split a K-point λ grid into ⌈K/c⌉ fixed-shape lane chunks for the
    planner's chunked-lanes degradation (parallel/memory.BlockPlan).

    Returns ``[(lane_indices [c], n_real), ...]`` where ``lane_indices``
    index into the validated grid. Every chunk has EXACTLY ``c`` lanes —
    the tail is padded by repeating its last index, so one compiled
    program per (bucket, c) shape serves the whole grid; callers write
    back only the first ``n_real`` lanes of each chunk's results (the
    padded duplicates are dropped, never published).
    """
    arr = validate_lane_weights(weights)
    k = int(arr.size)
    c = max(1, min(int(chunk), k))
    out: List[Tuple[np.ndarray, int]] = []
    for lo in range(0, k, c):
        idx = np.arange(lo, min(lo + c, k), dtype=np.int64)
        n_real = int(idx.size)
        if n_real < c:
            idx = np.concatenate(
                [idx, np.full((c - n_real,), idx[-1], np.int64)])
        out.append((idx, n_real))
    return out


def minimize_lanes(value_and_gradient: LaneValueAndGradient,
                   x0_lanes: Array,
                   *,
                   l2: Array,
                   l1: Optional[Array] = None,
                   config: SolverConfig = SolverConfig(),
                   use_owlqn: bool = False) -> SolverResult:
    """Fit K lanes — stacked ``x0_lanes [K, d]``, per-lane ``l2``/``l1``
    ``[K]`` — in one vmapped L-BFGS / OWL-QN solve.

    Returns a stacked :class:`SolverResult` whose every array field has
    a leading lane axis (``coef [K, d]``, ``iterations [K]``, ...).
    Must be called under an enclosing ``jit`` with the data batch bound
    as an argument of that jit (the repo's data-as-arguments rule).
    """
    if use_owlqn:
        l1_lanes = l1 if l1 is not None else jnp.zeros_like(l2)

        def one_lane(x0, l2k, l1k):
            vg = lambda c: value_and_gradient(c, Hyper(l2_weight=l2k))
            return owlqn.minimize(vg, x0, l1_weight=l1k, config=config)

        return jax.vmap(one_lane)(x0_lanes, l2, l1_lanes)

    def one_lane(x0, l2k):
        vg = lambda c: value_and_gradient(c, Hyper(l2_weight=l2k))
        return lbfgs.minimize(vg, x0, config=config)

    return jax.vmap(one_lane)(x0_lanes, l2)


def minimize_lanes_meshed(objective: GLMObjective,
                          sharded_batch,
                          x0_lanes: Array,
                          *,
                          l2: Array,
                          l1: Optional[Array] = None,
                          mesh,
                          config: SolverConfig = SolverConfig(),
                          use_owlqn: bool = False) -> SolverResult:
    """Data-parallel lane batch: the entire vmapped solve runs inside
    ONE shard_map over the mesh's sample axes.

    Each lane's objective evaluates the data term over this shard's
    rows (with ``1/num_shards`` of the L2 quadratic, so shard-sums
    recover the global objective exactly — the hier invariant), then
    reduces the packed ``[grad | value]`` block with a single staged
    ICI→DCN psum. Under vmap the collective batches to one psum of the
    ``[K, d+1]`` stack, so the per-iteration DCN reduction count is
    independent of K — ``parallel/mesh.count_axis_psums`` sees the same
    count as the scalar solver.
    """
    from photon_tpu.optim import hier
    from photon_tpu.parallel import mesh as M

    sample_axes = hier._sample_axes(mesh)
    p_shards, replicas = hier._mesh_factors(mesh, sample_axes)

    def lanes_body(x0_l, l2_l, l1_l, batch):
        def lane_vg(c, hyper):
            f, g = objective.local_value_and_gradient(c, batch, hyper,
                                                      p_shards)
            packed = hier._staged_all_psum(
                jnp.concatenate([g, f[None]]), mesh)
            return packed[-1] / replicas, packed[:-1] / replicas

        if use_owlqn:
            def one_lane(x0, l2k, l1k):
                vg = lambda c: lane_vg(c, Hyper(l2_weight=l2k))
                return owlqn.minimize(vg, x0, l1_weight=l1k, config=config)
            return jax.vmap(one_lane)(x0_l, l2_l, l1_l)

        def one_lane(x0, l2k):
            vg = lambda c: lane_vg(c, Hyper(l2_weight=l2k))
            return lbfgs.minimize(vg, x0, config=config)
        return jax.vmap(one_lane)(x0_l, l2_l)

    specs = hier._batch_specs(sharded_batch, sample_axes)
    l1_lanes = l1 if l1 is not None else jnp.zeros_like(l2)
    # check_rep=False: the rep checker has no rule for the vmapped
    # solver while_loop; the staged all-axis psum establishes the P()
    # output replication it would otherwise verify (hier precedent).
    return M.shard_map(lanes_body, mesh=mesh,
                       in_specs=(P(), P(), P(), specs),
                       out_specs=P(),
                       check_rep=False)(x0_lanes, l2, l1_lanes,
                                        sharded_batch)


def split_lanes(stacked: SolverResult) -> List[SolverResult]:
    """Split a stacked lane result into per-lane :class:`SolverResult`s.

    A host-boundary helper: the per-lane views are lazy indexes into the
    stacked device arrays (optional fields stay ``None``).
    """
    k = int(stacked.iterations.shape[0])
    return [
        SolverResult(*(None if f is None else f[i] for f in stacked))
        for i in range(k)
    ]


# -- sweep accounting for the RunReport `sweep` section ---------------------

_SWEEP_STATS = {
    "runs": 0,            # batched solves executed
    "lanes_total": 0,     # sum of K over runs
    "lane_records": [],   # per-run: lanes' weight/loss/iterations/reason
    "tuner": None,        # filled in by GameEstimator.tune()
}
_MAX_LANE_RECORDS = 64


def record_sweep_run(lane_records: List[dict]) -> None:
    """Account one batched solve (called at the host boundary where the
    caller already materialized per-lane scalars — no device syncs of
    its own)."""
    _SWEEP_STATS["runs"] += 1
    _SWEEP_STATS["lanes_total"] += len(lane_records)
    if len(_SWEEP_STATS["lane_records"]) < _MAX_LANE_RECORDS:
        _SWEEP_STATS["lane_records"].append(lane_records)


def record_tuner_summary(summary: dict) -> None:
    """Attach the tuner's round/selection summary to the sweep section."""
    _SWEEP_STATS["tuner"] = dict(summary)


def reset_sweep_stats() -> None:
    _SWEEP_STATS.update(runs=0, lanes_total=0, lane_records=[], tuner=None)


def report_section() -> dict:
    """The RunReport ``sweep`` section (obs/report.py reads this via
    ``sys.modules`` so runs that never sweep pay nothing)."""
    return {
        "runs": _SWEEP_STATS["runs"],
        "lanes_total": _SWEEP_STATS["lanes_total"],
        "lane_records": list(_SWEEP_STATS["lane_records"]),
        "tuner": _SWEEP_STATS["tuner"],
    }
