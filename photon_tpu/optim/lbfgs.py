"""L-BFGS as one jittable lax.while_loop (replaces breeze.optimize.LBFGS
behind the reference's LBFGS adapter, optimization/LBFGS.scala:39).

Two-loop recursion over a fixed-size circular (S, Y) history, strong-Wolfe
line search (optim/linesearch.py), optional box projection after each step
(the reference projects into the constraint box after each Breeze step —
LBFGS.scala; LBFGSB.scala:40 gets the same treatment here).

Defaults mirror the reference: maxIter=100, numCorrections=10, tol=1e-7
(LBFGS.scala:152-157).

Because every branch is lax-level, this function serves both roles the
reference splits into DistributedOptimizationProblem (one big solve over a
sharded batch) and SingleNodeOptimizationProblem (vmap-ed over entity
blocks with per-entity convergence masking).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    ConvergenceReason,
    FailureMode,
    SolverConfig,
    SolverResult,
    StateTracking,
    absolute_tolerances,
    convergence_reason,
    nonfinite_code,
    project_box,
)
from photon_tpu.optim.linesearch import (
    wolfe_linesearch,
    wolfe_linesearch_directional,
)

Array = jax.Array


class _Carry(NamedTuple):
    x: Array
    f: Array
    g: Array
    f_prev: Array
    s_hist: Array      # [m, d]
    y_hist: Array      # [m, d]
    rho: Array         # [m]
    n_pairs: Array     # int32: number of valid pairs (<= m)
    head: Array        # int32: next write slot
    it: Array
    reason: Array
    n_evals: Array
    ls_failed: Array   # bool: last line search failed to decrease
    nf_count: Array    # int32: consecutive non-finite evaluations
    failure: Array     # int32 FailureMode (non-zero terminates the loop)
    trk: Optional[StateTracking]  # per-iteration ring buffer (None = off)


def two_loop_direction(g, s_hist, y_hist, rho, n_pairs, head, m):
    """Standard two-loop recursion with circular-buffer masking."""
    dtype = g.dtype

    def bwd(j, carry):
        q, alphas = carry
        idx = (head - 1 - j) % m
        valid = j < n_pairs
        a = rho[idx] * jnp.dot(s_hist[idx], q)
        a = jnp.where(valid, a, 0.0)
        q = q - a * y_hist[idx]
        return q, alphas.at[idx].set(a)

    q, alphas = lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), dtype)))

    # initial Hessian scaling from the most recent pair
    last = (head - 1) % m
    sy = jnp.dot(s_hist[last], y_hist[last])
    yy = jnp.dot(y_hist[last], y_hist[last])
    gamma = jnp.where((n_pairs > 0) & (yy > 0), sy / jnp.where(yy > 0, yy, 1.0), 1.0)
    r = gamma * q

    def fwd(j, r):
        idx = (head - n_pairs + j) % m
        valid = j < n_pairs
        beta = rho[idx] * jnp.dot(y_hist[idx], r)
        upd = s_hist[idx] * (alphas[idx] - beta)
        return r + jnp.where(valid, upd, 0.0)

    r = lax.fori_loop(0, m, fwd, r)
    return -r


def minimize(
    value_and_grad,
    x0: Array,
    *args,
    config: SolverConfig = SolverConfig(),
    init_fg=None,
) -> SolverResult:
    """Minimize ``value_and_grad(x, *args) -> (f, g)`` from ``x0``.

    ``init_fg``, when given, is ``(f0, g0)`` already evaluated at the
    PROJECTED start point — the caller saves the solver's first full
    evaluation (the hierarchical round body computes F_k(c) anyway for
    the safeguard; optim/hier.py). Only valid when the caller guarantees
    the pair really is ``value_and_grad(project_box(x0), *args)``; with
    box constraints the projection may move x0, so callers without
    box bounds are the intended users.
    """
    m = config.num_corrections
    d = x0.shape[0]
    dtype = x0.dtype
    has_box = config.lower_bounds is not None or config.upper_bounds is not None

    x0 = project_box(x0, config)
    if init_fg is None:
        f0, g0 = value_and_grad(x0, *args)
    else:
        f0, g0 = init_fg
    tols = absolute_tolerances(f0, g0, config.tolerance)

    def cond(c: _Carry):
        return ((c.reason == ConvergenceReason.NOT_CONVERGED)
                & (c.failure == FailureMode.NONE))

    def body(c: _Carry) -> _Carry:
        direction = two_loop_direction(c.g, c.s_hist, c.y_hist, c.rho,
                                       c.n_pairs, c.head, m)
        # safeguard: fall back to steepest descent on non-descent directions
        descent = jnp.dot(direction, c.g) < 0
        direction = jnp.where(descent, direction, -c.g)

        gnorm = jnp.linalg.norm(c.g)
        first = c.n_pairs == 0
        init_step = jnp.where(first, jnp.minimum(1.0, 1.0 / jnp.maximum(gnorm, 1e-12)), 1.0)

        ls = wolfe_linesearch(
            value_and_grad, c.x, direction, c.f, c.g, *args,
            initial_step=init_step.astype(dtype),
            max_evals=config.linesearch_max_iterations,
        )

        x_new = c.x + ls.step * direction
        f_new, g_new = ls.f, ls.g
        if has_box:
            # Project and re-evaluate at the projected point (reference
            # projects coefficients into the box after each step).
            x_proj = project_box(x_new, config)
            changed = jnp.any(x_proj != x_new)
            f_proj, g_proj = value_and_grad(x_proj, *args)
            x_new = x_proj
            f_new = jnp.where(changed, f_proj, f_new)
            g_new = jnp.where(changed, g_proj[...], g_new)

        # Non-finite guard: a NaN f fails `<` on its own, but a -inf loss
        # would sail through, and a finite f with a NaN gradient would
        # poison the curvature history — gate acceptance on full
        # finiteness. Rejection leaves the carry at the last finite
        # iterate; the failure code below terminates after the retry
        # (same direction, ls shrinks) also comes back non-finite.
        g_finite = jnp.all(jnp.isfinite(g_new))
        finite = jnp.isfinite(f_new) & g_finite
        decreased = finite & (f_new < c.f)
        # reject non-decreasing steps entirely
        x_new = jnp.where(decreased, x_new, c.x)
        f_kept = jnp.where(decreased, f_new, c.f)
        g_kept = jnp.where(decreased, g_new, c.g)

        # curvature update
        s = x_new - c.x
        yv = g_kept - c.g
        sy = jnp.dot(s, yv)
        store = decreased & (sy > 1e-10 * jnp.maximum(jnp.dot(yv, yv), 1e-30))
        write = c.head % m
        s_hist = jnp.where(store, c.s_hist.at[write].set(s), c.s_hist)
        y_hist = jnp.where(store, c.y_hist.at[write].set(yv), c.y_hist)
        rho = jnp.where(store, c.rho.at[write].set(1.0 / jnp.where(sy != 0, sy, 1.0)), c.rho)
        head = jnp.where(store, (c.head + 1) % m, c.head)
        n_pairs = jnp.where(store, jnp.minimum(c.n_pairs + 1, m), c.n_pairs)

        it = c.it + 1
        reason = convergence_reason(it, c.f, f_kept, g_kept, tols,
                                    config.max_iterations, improved=decreased)
        # two consecutive failed line searches -> objective not improving
        both_failed = (~decreased) & c.ls_failed
        reason = jnp.where(
            (reason == ConvergenceReason.NOT_CONVERGED) & both_failed,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason,
        )
        # two consecutive non-finite evaluations: the NaN-aware line
        # search already shrank away once and the region is still bad —
        # terminate with a typed failure at the last finite iterate
        nf_count = jnp.where(finite, 0, c.nf_count + 1).astype(jnp.int32)
        failure = jnp.where(nf_count >= 2, nonfinite_code(f_new, g_finite),
                            jnp.asarray(FailureMode.NONE, jnp.int32))
        reason = jnp.where(
            failure != FailureMode.NONE,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason,
        )

        return _Carry(
            x=x_new, f=f_kept, g=g_kept, f_prev=c.f,
            s_hist=s_hist, y_hist=y_hist, rho=rho,
            n_pairs=n_pairs, head=head.astype(jnp.int32),
            it=it, reason=reason,
            n_evals=c.n_evals + ls.num_evals + (1 if has_box else 0),
            ls_failed=~decreased,
            nf_count=nf_count, failure=failure,
            trk=None if c.trk is None else c.trk.record(
                c.it, f_kept, g_kept,
                step=jnp.where(decreased, ls.step, 0.0)),
        )

    init = _Carry(
        x=x0, f=f0, g=g0, f_prev=f0 + jnp.asarray(jnp.inf, dtype),
        s_hist=jnp.zeros((m, d), dtype), y_hist=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        n_pairs=jnp.asarray(0, jnp.int32), head=jnp.asarray(0, jnp.int32),
        it=jnp.asarray(0, jnp.int32),
        # handle an already-converged start (zero gradient)
        reason=jnp.where(
            jnp.linalg.norm(g0) <= tols.gradient_tol,
            jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
            jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
        ),
        n_evals=jnp.asarray(1, jnp.int32),
        ls_failed=jnp.asarray(False),
        nf_count=jnp.asarray(0, jnp.int32),
        # a non-finite start (poisoned data) exits before the first step
        failure=nonfinite_code(f0, jnp.all(jnp.isfinite(g0))),
        trk=StateTracking.init(config.track_states, dtype),
    )

    out = lax.while_loop(cond, body, init)
    return SolverResult(
        coef=out.x, value=out.f, gradient=out.g,
        iterations=out.it, reason=out.reason, num_fun_evals=out.n_evals,
        loss_history=None if out.trk is None else out.trk.loss,
        gnorm_history=None if out.trk is None else out.trk.gnorm,
        step_history=None if out.trk is None else out.trk.step,
        failure=out.failure,
    )


class _DirCarry(NamedTuple):
    x: Array
    f: Array
    g: Array
    f_prev: Array
    margins: Array     # [n] resident margins at x (affinely updated)
    xx: Array          # x . x (L2 term's quadratic, refreshed each accept)
    s_hist: Array      # [m, d]
    y_hist: Array      # [m, d]
    rho: Array         # [m]
    sy_gram: Array     # [m, m]: sy_gram[i, j] = s_i . y_j
    yy_gram: Array     # [m, m]: yy_gram[i, j] = y_i . y_j
    sg: Array          # [m]: s_i . g
    yg: Array          # [m]: y_i . g
    gg: Array          # g . g
    n_pairs: Array
    head: Array
    it: Array
    reason: Array
    n_evals: Array
    ls_failed: Array
    failure: Array     # int32 FailureMode (non-zero terminates the loop)
    trk: Optional[StateTracking]


def _compact_direction(sg, yg, gg, sy_gram, yy_gram, rho, n_pairs, head, m):
    """Two-loop recursion in the span of {g} ∪ S ∪ Y by Gram algebra alone
    (the VL-BFGS observation, arXiv:1409.2442): because the backward loop
    only ever subtracts Y components from q, every inner product it needs
    is an entry of S·Yᵀ, Y·Yᵀ, S·g or Y·g — O(m²) scalar work instead of
    4m passes over d-vectors. Returns coefficients ``(c_g, c_s, c_y)`` with

        direction = -(c_g * g + c_s @ S + c_y @ Y)

    so the caller materializes the direction with ONE [m, d] combination.
    Invalid circular-buffer slots are masked exactly as in
    ``two_loop_direction``: their alphas/r_s entries stay zero, so garbage
    Gram entries at dead slots never contribute."""
    dtype = sg.dtype

    def bwd(j, alphas):
        idx = (head - 1 - j) % m
        valid = j < n_pairs
        # s_idx . q where q = g - alphas @ Y
        a = rho[idx] * (sg[idx] - jnp.dot(sy_gram[idx], alphas))
        return alphas.at[idx].set(jnp.where(valid, a, 0.0))

    alphas = lax.fori_loop(0, m, bwd, jnp.zeros((m,), dtype))

    last = (head - 1) % m
    sy = sy_gram[last, last]
    yy = yy_gram[last, last]
    gamma = jnp.where((n_pairs > 0) & (yy > 0),
                      sy / jnp.where(yy > 0, yy, 1.0), 1.0)
    # r = gamma * q = gamma * g - gamma * alphas @ Y
    r_y = -gamma * alphas

    def fwd(j, r_s):
        idx = (head - n_pairs + j) % m
        valid = j < n_pairs
        yr = (gamma * yg[idx] + jnp.dot(r_s, sy_gram[:, idx])
              + jnp.dot(r_y, yy_gram[:, idx]))
        beta = rho[idx] * yr
        return r_s.at[idx].add(jnp.where(valid, alphas[idx] - beta, 0.0))

    r_s = lax.fori_loop(0, m, fwd, jnp.zeros((m,), dtype))
    return gamma, r_s, r_y


def minimize_directional(
    problem,
    x0: Array,
    *,
    config: SolverConfig = SolverConfig(),
) -> SolverResult:
    """L-BFGS over a margin-resident ``DirectionalProblem``
    (function/objective.directional_problem).

    Built for the model-sharded sparse path, where every pass over the
    feature nnz is the wallclock. Per iteration exactly TWO such passes
    happen: one matvec for the direction's margin increment and one
    rmatvec for the gradient at the accepted point — every line-search
    trial is O(n_samples) on resident margins, and the search direction
    itself comes from ``_compact_direction``'s O(m²) Gram algebra plus a
    single [m, d] combination (the classic two-loop re-reads the whole
    history twice per iteration).

    Semantics mirror ``minimize``: same init-step rule, non-decreasing
    steps rejected, same curvature-pair store condition, same convergence
    classification. ``num_fun_evals`` counts FULL-data evaluations only
    (1 at init + 1 per iteration at the accepted point); the O(n) trial
    probes are excluded, keeping the count comparable to the classic
    path's value_and_grad calls.

    Box constraints are unsupported — projection would break margin
    residency; use ``minimize``.
    """
    if config.lower_bounds is not None or config.upper_bounds is not None:
        raise ValueError("minimize_directional does not support box "
                         "constraints; use minimize")
    m = config.num_corrections
    d = x0.shape[0]
    dtype = x0.dtype

    f0, g0, margins0, xx0 = problem.init(x0)
    tols = absolute_tolerances(f0, g0, config.tolerance)

    def cond(c: _DirCarry):
        return ((c.reason == ConvergenceReason.NOT_CONVERGED)
                & (c.failure == FailureMode.NONE))

    def body(c: _DirCarry) -> _DirCarry:
        c_g, c_s, c_y = _compact_direction(
            c.sg, c.yg, c.gg, c.sy_gram, c.yy_gram, c.rho,
            c.n_pairs, c.head, m)
        d0 = -(c_g * c.gg + jnp.dot(c_s, c.sg) + jnp.dot(c_y, c.yg))
        # safeguard: fall back to steepest descent on non-descent directions
        descent = d0 < 0
        c_g = jnp.where(descent, c_g, 1.0)
        c_s = jnp.where(descent, c_s, jnp.zeros_like(c_s))
        c_y = jnp.where(descent, c_y, jnp.zeros_like(c_y))
        d0 = jnp.where(descent, d0, -c.gg)

        direction = -(c_g * c.g + c_s @ c.s_hist + c_y @ c.y_hist)
        m_dir = problem.dir_margins(direction)
        xd = jnp.dot(c.x, direction)
        dd = jnp.dot(direction, direction)

        first = c.n_pairs == 0
        gnorm = jnp.sqrt(c.gg)
        init_step = jnp.where(
            first, jnp.minimum(1.0, 1.0 / jnp.maximum(gnorm, 1e-12)), 1.0)

        ls = wolfe_linesearch_directional(
            lambda a: problem.trial(c.margins, m_dir, c.xx, xd, dd, a),
            c.f, d0,
            initial_step=init_step.astype(dtype),
            max_evals=config.linesearch_max_iterations,
        )

        decreased = ls.f < c.f
        t = jnp.where(decreased, ls.step, 0.0).astype(dtype)
        x_new = c.x + t * direction
        margins_new = c.margins + t * m_dir
        # xx advanced by the L2 quadratic that is EXACT along the ray; the
        # drift of this scalar recurrence vs a fresh dot is O(iters * eps),
        # orders below the f32 progress floor the solve stalls at — and it
        # saves one full d-pass per iteration.
        xx_kept = c.xx + t * (2.0 * xd + t * dd)

        # ONE full-data evaluation at the accepted point. When the line
        # search fails t is exactly 0, x_new/margins_new/xx are bitwise
        # c.x/c.margins/c.xx, and this recomputation reproduces f/g
        # bit-for-bit — so no where(decreased) selects are needed on them
        # (each select over [d] is a full extra pass on a 10^7-dim solve).
        f_kept, g_kept = problem.at_point(x_new, margins_new, xx_kept)

        gng = jnp.dot(c.g, g_kept)
        gg_new = jnp.dot(g_kept, g_kept)

        # Non-finite guard priced for the sharded path: isfinite on two
        # scalars already in hand (f and g.g — any NaN/Inf component of g
        # makes g.g non-finite), NO extra d-pass. A bad full-data eval
        # withdraws the step — the carry reverts to the previous finite
        # point — and the failure code terminates the loop, so the
        # where-selects below are only ever live on the final iteration.
        ok = jnp.isfinite(f_kept) & jnp.isfinite(gg_new)
        failure = jnp.where(ok, jnp.asarray(FailureMode.NONE, jnp.int32),
                            nonfinite_code(f_kept, jnp.isfinite(gg_new)))
        x_new = jnp.where(ok, x_new, c.x)
        margins_new = jnp.where(ok, margins_new, c.margins)
        xx_kept = jnp.where(ok, xx_kept, c.xx)
        f_kept = jnp.where(ok, f_kept, c.f)
        g_kept = jnp.where(ok, g_kept, c.g)
        gng = jnp.where(ok, gng, c.gg)
        gg_new = jnp.where(ok, gg_new, c.gg)
        decreased = decreased & ok

        # direction . y_j via coefficients against the old grams;
        # direction . g_new comes straight from the line search: the trial
        # restriction's dphi at the accepted step IS direction . g(x_new)
        # by the adjoint identity (dphi = m_dir . dloss + l2*(xd + a*dd)),
        # so the store decision needs NO history matvec. On a failed
        # search t = 0 zeroes sy below, so a stale dphi is harmless.
        d_dot_y = -(c_g * c.yg + c_s @ c.sy_gram + c_y @ c.yy_gram)
        d_dot_gn = ls.dphi

        # curvature pair (s, y) = (t*direction, g_new - g) without touching
        # d-space: s.y = t*(d.g_new - d.g) and y.y = |g_new|^2 - 2 g.g_new
        # + |g|^2, all scalars already in hand. The cancellation noise this
        # admits (~eps*|g|^2) only matters when the true curvature is at
        # rounding level — exactly the pairs the threshold must reject
        # anyway — and it keeps sy consistent with the sy_gram row below,
        # which is built from the same coefficient form.
        sy = t * (d_dot_gn - d0)
        yy = jnp.maximum(gg_new - 2.0 * gng + c.gg, 0.0)
        store = decreased & (sy > 1e-10 * jnp.maximum(yy, 1e-30))
        write = c.head % m

        # conditional stores at ROW granularity: a where(store) over the
        # full [m, d] history materializes two extra history-sized buffers
        # per iteration (measured ~0.9 s/iter at d = 10^7, m = 10 — more
        # than the sparse kernels themselves); selecting the one written
        # row keeps the dynamic-update-slice in place. The y subtraction
        # fuses into the row write instead of materializing a [d] vector.
        # Writes come BEFORE the history matvecs: the old buffers' last
        # use is the update itself, so XLA aliases the carry in place.
        s_hist = c.s_hist.at[write].set(jnp.where(store, t * direction,
                                                  c.s_hist[write]))
        y_hist = c.y_hist.at[write].set(jnp.where(store, g_kept - c.g,
                                                  c.y_hist[write]))
        rho = jnp.where(
            store, c.rho.at[write].set(1.0 / jnp.where(sy != 0, sy, 1.0)),
            c.rho)

        # The ONLY O(m d) Gram work: two matvecs against the NEW history.
        # At the written slot the products are s_new . g_new and
        # y_new . g_new — exactly the values the next direction needs;
        # without a store the history is unchanged and these are plain
        # recomputations. Uniform either way — no conditional fixups.
        sg = s_hist @ g_kept
        yg = y_hist @ g_kept

        # off-diagonal column s_i . y_new = s_i . g_new - s_i . g (valid
        # for i != write; the evicted slot's entries are overwritten by the
        # row set and the diagonal set, applied last)
        sy_upd = (c.sy_gram
                  .at[write, :].set(t * d_dot_y)          # s_new . y_j
                  .at[:, write].set(sg - c.sg)            # s_i . y_new
                  .at[write, write].set(sy))
        yy_col = yg - c.yg                                # y_i . y_new
        yy_upd = (c.yy_gram
                  .at[write, :].set(yy_col)
                  .at[:, write].set(yy_col)
                  .at[write, write].set(yy))
        sy_gram = jnp.where(store, sy_upd, c.sy_gram)
        yy_gram = jnp.where(store, yy_upd, c.yy_gram)

        head = jnp.where(store, (c.head + 1) % m, c.head)
        n_pairs = jnp.where(store, jnp.minimum(c.n_pairs + 1, m), c.n_pairs)

        it = c.it + 1
        reason = convergence_reason(it, c.f, f_kept, g_kept, tols,
                                    config.max_iterations, improved=decreased,
                                    gnorm=jnp.sqrt(gg_new))
        both_failed = (~decreased) & c.ls_failed
        reason = jnp.where(
            (reason == ConvergenceReason.NOT_CONVERGED) & both_failed,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason,
        )
        reason = jnp.where(
            failure != FailureMode.NONE,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason,
        )

        return _DirCarry(
            x=x_new, f=f_kept, g=g_kept, f_prev=c.f,
            margins=margins_new, xx=xx_kept,
            s_hist=s_hist, y_hist=y_hist, rho=rho,
            sy_gram=sy_gram, yy_gram=yy_gram, sg=sg, yg=yg, gg=gg_new,
            n_pairs=n_pairs, head=head.astype(jnp.int32),
            it=it, reason=reason,
            n_evals=c.n_evals + 1,
            ls_failed=~decreased,
            failure=failure,
            trk=None if c.trk is None else c.trk.record(
                c.it, f_kept, g_kept, step=t),
        )

    gg0 = jnp.dot(g0, g0)
    init = _DirCarry(
        x=x0, f=f0, g=g0, f_prev=f0 + jnp.asarray(jnp.inf, dtype),
        margins=margins0, xx=xx0,
        s_hist=jnp.zeros((m, d), dtype), y_hist=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        sy_gram=jnp.zeros((m, m), dtype), yy_gram=jnp.zeros((m, m), dtype),
        sg=jnp.zeros((m,), dtype), yg=jnp.zeros((m,), dtype),
        gg=gg0,
        n_pairs=jnp.asarray(0, jnp.int32), head=jnp.asarray(0, jnp.int32),
        it=jnp.asarray(0, jnp.int32),
        reason=jnp.where(
            jnp.sqrt(gg0) <= tols.gradient_tol,
            jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
            jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
        ),
        n_evals=jnp.asarray(1, jnp.int32),
        ls_failed=jnp.asarray(False),
        # same scalar-witness trick as the loop guard: g.g covers g
        failure=nonfinite_code(f0, jnp.isfinite(gg0)),
        trk=StateTracking.init(config.track_states, dtype),
    )

    out = lax.while_loop(cond, body, init)
    return SolverResult(
        coef=out.x, value=out.f, gradient=out.g,
        iterations=out.it, reason=out.reason, num_fun_evals=out.n_evals,
        loss_history=None if out.trk is None else out.trk.loss,
        gnorm_history=None if out.trk is None else out.trk.gnorm,
        step_history=None if out.trk is None else out.trk.step,
        failure=out.failure,
    )
