"""L-BFGS as one jittable lax.while_loop (replaces breeze.optimize.LBFGS
behind the reference's LBFGS adapter, optimization/LBFGS.scala:39).

Two-loop recursion over a fixed-size circular (S, Y) history, strong-Wolfe
line search (optim/linesearch.py), optional box projection after each step
(the reference projects into the constraint box after each Breeze step —
LBFGS.scala; LBFGSB.scala:40 gets the same treatment here).

Defaults mirror the reference: maxIter=100, numCorrections=10, tol=1e-7
(LBFGS.scala:152-157).

Because every branch is lax-level, this function serves both roles the
reference splits into DistributedOptimizationProblem (one big solve over a
sharded batch) and SingleNodeOptimizationProblem (vmap-ed over entity
blocks with per-entity convergence masking).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    ConvergenceReason,
    SolverConfig,
    SolverResult,
    StateTracking,
    absolute_tolerances,
    convergence_reason,
    project_box,
)
from photon_tpu.optim.linesearch import wolfe_linesearch

Array = jax.Array


class _Carry(NamedTuple):
    x: Array
    f: Array
    g: Array
    f_prev: Array
    s_hist: Array      # [m, d]
    y_hist: Array      # [m, d]
    rho: Array         # [m]
    n_pairs: Array     # int32: number of valid pairs (<= m)
    head: Array        # int32: next write slot
    it: Array
    reason: Array
    n_evals: Array
    ls_failed: Array   # bool: last line search failed to decrease
    trk: Optional[StateTracking]  # per-iteration ring buffer (None = off)


def two_loop_direction(g, s_hist, y_hist, rho, n_pairs, head, m):
    """Standard two-loop recursion with circular-buffer masking."""
    dtype = g.dtype

    def bwd(j, carry):
        q, alphas = carry
        idx = (head - 1 - j) % m
        valid = j < n_pairs
        a = rho[idx] * jnp.dot(s_hist[idx], q)
        a = jnp.where(valid, a, 0.0)
        q = q - a * y_hist[idx]
        return q, alphas.at[idx].set(a)

    q, alphas = lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), dtype)))

    # initial Hessian scaling from the most recent pair
    last = (head - 1) % m
    sy = jnp.dot(s_hist[last], y_hist[last])
    yy = jnp.dot(y_hist[last], y_hist[last])
    gamma = jnp.where((n_pairs > 0) & (yy > 0), sy / jnp.where(yy > 0, yy, 1.0), 1.0)
    r = gamma * q

    def fwd(j, r):
        idx = (head - n_pairs + j) % m
        valid = j < n_pairs
        beta = rho[idx] * jnp.dot(y_hist[idx], r)
        upd = s_hist[idx] * (alphas[idx] - beta)
        return r + jnp.where(valid, upd, 0.0)

    r = lax.fori_loop(0, m, fwd, r)
    return -r


def minimize(
    value_and_grad,
    x0: Array,
    *args,
    config: SolverConfig = SolverConfig(),
) -> SolverResult:
    """Minimize ``value_and_grad(x, *args) -> (f, g)`` from ``x0``."""
    m = config.num_corrections
    d = x0.shape[0]
    dtype = x0.dtype
    has_box = config.lower_bounds is not None or config.upper_bounds is not None

    x0 = project_box(x0, config)
    f0, g0 = value_and_grad(x0, *args)
    tols = absolute_tolerances(f0, g0, config.tolerance)

    def cond(c: _Carry):
        return c.reason == ConvergenceReason.NOT_CONVERGED

    def body(c: _Carry) -> _Carry:
        direction = two_loop_direction(c.g, c.s_hist, c.y_hist, c.rho,
                                       c.n_pairs, c.head, m)
        # safeguard: fall back to steepest descent on non-descent directions
        descent = jnp.dot(direction, c.g) < 0
        direction = jnp.where(descent, direction, -c.g)

        gnorm = jnp.linalg.norm(c.g)
        first = c.n_pairs == 0
        init_step = jnp.where(first, jnp.minimum(1.0, 1.0 / jnp.maximum(gnorm, 1e-12)), 1.0)

        ls = wolfe_linesearch(
            value_and_grad, c.x, direction, c.f, c.g, *args,
            initial_step=init_step.astype(dtype),
            max_evals=config.linesearch_max_iterations,
        )

        x_new = c.x + ls.step * direction
        f_new, g_new = ls.f, ls.g
        if has_box:
            # Project and re-evaluate at the projected point (reference
            # projects coefficients into the box after each step).
            x_proj = project_box(x_new, config)
            changed = jnp.any(x_proj != x_new)
            f_proj, g_proj = value_and_grad(x_proj, *args)
            x_new = x_proj
            f_new = jnp.where(changed, f_proj, f_new)
            g_new = jnp.where(changed, g_proj[...], g_new)

        decreased = f_new < c.f
        # reject non-decreasing steps entirely
        x_new = jnp.where(decreased, x_new, c.x)
        f_kept = jnp.where(decreased, f_new, c.f)
        g_kept = jnp.where(decreased, g_new, c.g)

        # curvature update
        s = x_new - c.x
        yv = g_kept - c.g
        sy = jnp.dot(s, yv)
        store = decreased & (sy > 1e-10 * jnp.maximum(jnp.dot(yv, yv), 1e-30))
        write = c.head % m
        s_hist = jnp.where(store, c.s_hist.at[write].set(s), c.s_hist)
        y_hist = jnp.where(store, c.y_hist.at[write].set(yv), c.y_hist)
        rho = jnp.where(store, c.rho.at[write].set(1.0 / jnp.where(sy != 0, sy, 1.0)), c.rho)
        head = jnp.where(store, (c.head + 1) % m, c.head)
        n_pairs = jnp.where(store, jnp.minimum(c.n_pairs + 1, m), c.n_pairs)

        it = c.it + 1
        reason = convergence_reason(it, c.f, f_kept, g_kept, tols,
                                    config.max_iterations, improved=decreased)
        # two consecutive failed line searches -> objective not improving
        both_failed = (~decreased) & c.ls_failed
        reason = jnp.where(
            (reason == ConvergenceReason.NOT_CONVERGED) & both_failed,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason,
        )

        return _Carry(
            x=x_new, f=f_kept, g=g_kept, f_prev=c.f,
            s_hist=s_hist, y_hist=y_hist, rho=rho,
            n_pairs=n_pairs, head=head.astype(jnp.int32),
            it=it, reason=reason,
            n_evals=c.n_evals + ls.num_evals + (1 if has_box else 0),
            ls_failed=~decreased,
            trk=None if c.trk is None else c.trk.record(c.it, f_kept, g_kept),
        )

    init = _Carry(
        x=x0, f=f0, g=g0, f_prev=f0 + jnp.asarray(jnp.inf, dtype),
        s_hist=jnp.zeros((m, d), dtype), y_hist=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        n_pairs=jnp.asarray(0, jnp.int32), head=jnp.asarray(0, jnp.int32),
        it=jnp.asarray(0, jnp.int32),
        # handle an already-converged start (zero gradient)
        reason=jnp.where(
            jnp.linalg.norm(g0) <= tols.gradient_tol,
            jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
            jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
        ),
        n_evals=jnp.asarray(1, jnp.int32),
        ls_failed=jnp.asarray(False),
        trk=StateTracking.init(config.track_states, dtype),
    )

    out = lax.while_loop(cond, body, init)
    return SolverResult(
        coef=out.x, value=out.f, gradient=out.g,
        iterations=out.it, reason=out.reason, num_fun_evals=out.n_evals,
        loss_history=None if out.trk is None else out.trk.loss,
        gnorm_history=None if out.trk is None else out.trk.gnorm,
    )
