"""Convex solvers for GLM training, all as single jittable XLA programs.

Reference: photon-lib optimization/ (Optimizer.scala, LBFGS.scala,
OWLQN.scala, LBFGSB.scala, TRON.scala, OptimizerFactory.scala:26).
"""

from photon_tpu.optim.base import (  # noqa: F401
    ConvergenceReason,
    SolverConfig,
    SolverResult,
)
from photon_tpu.optim import lbfgs, newton, owlqn, streaming, tron  # noqa: F401
from photon_tpu.optim.streaming import (  # noqa: F401
    StreamedProblem,
    minimize_streamed,
)
from photon_tpu.types import OptimizerType


def minimize(
    optimizer_type: OptimizerType,
    value_and_grad,
    x0,
    *args,
    hess_vec=None,
    hess_matrix=None,
    l1_weight=0.0,
    config: SolverConfig = SolverConfig(),
) -> SolverResult:
    """Dispatch on optimizer type (reference: OptimizerFactory.scala:26).

    LBFGSB is LBFGS with box projection — set bounds in ``config``
    (reference projects into the constraint box after each step).
    """
    if optimizer_type in (OptimizerType.LBFGS, OptimizerType.LBFGSB):
        return lbfgs.minimize(value_and_grad, x0, *args, config=config)
    if optimizer_type == OptimizerType.OWLQN:
        return owlqn.minimize(value_and_grad, x0, *args,
                              l1_weight=l1_weight, config=config)
    if optimizer_type == OptimizerType.TRON:
        if hess_vec is None:
            raise ValueError("TRON requires hess_vec")
        return tron.minimize(value_and_grad, hess_vec, x0, *args, config=config)
    if optimizer_type == OptimizerType.NEWTON:
        if hess_matrix is None:
            raise ValueError("NEWTON requires hess_matrix")
        # newton.minimize takes arg-free closures; bind the extra
        # objective args here to honor the facade's *args contract
        return newton.minimize(
            (lambda x: value_and_grad(x, *args)) if args else value_and_grad,
            (lambda x: hess_matrix(x, *args)) if args else hess_matrix,
            x0, config=config)
    raise ValueError(f"unknown optimizer type {optimizer_type}")
