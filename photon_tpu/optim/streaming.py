"""Streamed GLM solves: L-BFGS / OWL-QN over data that never fully
resides in HBM.

``StreamedProblem`` evaluates the objective by folding chunk after chunk
from a ``data.streaming.ChunkLoader`` into a device-resident carry
``(value_acc, grad_acc)``. Every chunk runs the SAME jitted partial (the
loader guarantees static chunk shapes), so a full pass is one compiled
program applied N times with zero recompiles and — critically — zero
host syncs inside the chunk loop: the single host crossing of a pass is
the ``np.asarray`` pull of ``(f, g)`` at the pass boundary.

On a mesh, the carry is kept SHARD-LOCAL ([n_shards] / [n_shards, dim])
through the whole pass and the per-chunk partial contains NO collectives;
the pass-end finalize issues exactly one staged ICI-then-DCN all-psum
(optim/hier._staged_all_psum) — the same reduction structure a resident
evaluation uses, issued once per pass instead of never needing it per
chunk.

The driving solvers (``minimize_streamed``) are host-loop ports of
optim/lbfgs.minimize and optim/owlqn.minimize with the same update rules,
line searches, tolerance semantics, convergence priorities and typed
non-finite failure handling — they must run on the host because each
objective evaluation is itself a host-driven loop over streamed chunks,
which cannot live inside a ``lax.while_loop``. Determinism is total: the
loader's chunk order is fixed, device arithmetic per chunk is one fixed
program, and all host arithmetic is straight-line numpy — two runs are
bitwise identical.

Mid-epoch preemption: with a ``checkpoint_path``, the solver persists a
chunk-cursor checkpoint (crc-framed npz via resilience/io atomic publish)
containing the iteration-start solver state, the ``(f, g)`` results of
evaluations already completed in the current iteration, and the in-flight
evaluation's device carry + next-chunk cursor. Resume replays the
iteration: completed evaluations are served from the checkpoint cache and
the in-flight pass continues from its cursor, so the resumed run is
bitwise identical to an uninterrupted one.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.function.objective import GLMObjective, Hyper
from photon_tpu.optim.base import (
    ConvergenceReason,
    FailureMode,
    SolverConfig,
    SolverResult,
    jit_donating,
)
from photon_tpu.resilience import chaos
from photon_tpu.resilience import io as rio


# =========================================================================
# Streamed objective evaluation
# =========================================================================

class StreamedProblem:
    """Full-pass ``(f, g)`` evaluation of a GLMObjective over a
    ChunkLoader's stream, with a device-resident accumulation carry.

    ``value_and_gradient`` is the solver-facing entry point; its
    ``resume=(carry, next_chunk)`` hook continues a partially-accumulated
    pass from a checkpoint cursor, and ``on_chunk`` fires after each
    chunk's accumulation (the checkpoint writer) — both off by default,
    leaving the hot path a bare dispatch loop.
    """

    def __init__(self, objective: GLMObjective, loader, l2_weight: float = 0.0,
                 dim: Optional[int] = None, dtype=None):
        self.objective = objective
        self.loader = loader
        self.mesh = loader.mesh
        self.dim = int(dim if dim is not None else loader.source.dim)
        self.dtype = np.dtype(dtype if dtype is not None else loader.dtype)
        self.l2_weight = float(l2_weight)
        self.passes = 0          # completed full evaluations (chaos cursor)
        self._l2_dev = jnp.asarray(self.l2_weight, self.dtype)
        if self.mesh is None:
            self._partial = jit_donating(
                objective.chunk_value_and_gradient, donate_argnums=(0,))
            self._finalize = jax.jit(
                lambda carry, coef, l2: objective.finalize_streamed(
                    carry, coef, Hyper(l2_weight=l2)))
            self._carry_shardings = None
        else:
            self._build_meshed()

    # -- meshed build: shard-local carry, no per-chunk collectives ----------

    def _build_meshed(self):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from photon_tpu.optim.hier import (
            _mesh_factors,
            _sample_axes,
            _staged_all_psum,
        )
        from photon_tpu.parallel import mesh as M

        mesh, obj = self.mesh, self.objective
        sample_axes = _sample_axes(mesh)
        self._n_shards, self._replicas = _mesh_factors(mesh, sample_axes)
        spec_axis = sample_axes if len(sample_axes) > 1 else sample_axes[0]
        cv_spec, cg_spec = P(spec_axis), P(spec_axis, None)
        self._carry_shardings = (NamedSharding(mesh, cv_spec),
                                 NamedSharding(mesh, cg_spec))
        replicas = self._replicas

        def partial_body(cv, cg, coef, batch):
            # shard-local accumulate: cv [1], cg [1, d] — NO collectives
            v, g = obj.chunk_value_and_gradient((cv[0], cg[0]), coef, batch)
            return v[None], g[None]

        def finalize_body(cv, cg, coef, l2):
            # the pass's single reduction: one staged ICI-then-DCN psum
            packed = _staged_all_psum(jnp.concatenate([cg[0], cv]), mesh)
            carry = (packed[-1] / replicas, packed[:-1] / replicas)
            return obj.finalize_streamed(carry, coef, Hyper(l2_weight=l2))

        def partial(carry, coef, batch):
            specs = jax.tree.map(
                lambda a: P(spec_axis, *([None] * (a.ndim - 1))), batch)
            return M.shard_map(partial_body, mesh=mesh,
                               in_specs=(cv_spec, cg_spec, P(), specs),
                               out_specs=(cv_spec, cg_spec),
                               check_rep=False)(carry[0], carry[1], coef,
                                                batch)

        def finalize(carry, coef, l2):
            return M.shard_map(finalize_body, mesh=mesh,
                               in_specs=(cv_spec, cg_spec, P(), P()),
                               out_specs=(P(), P()),
                               check_rep=False)(carry[0], carry[1], coef, l2)

        self._partial = jit_donating(partial, donate_argnums=(0,))
        self._finalize = jax.jit(finalize)

    # -- carry plumbing -----------------------------------------------------

    def init_carry(self):
        if self.mesh is None:
            return self.objective.init_stream_carry(self.dim, self.dtype)
        cv = np.zeros((self._n_shards,), self.dtype)
        cg = np.zeros((self._n_shards, self.dim), self.dtype)
        return (jax.device_put(cv, self._carry_shardings[0]),
                jax.device_put(cg, self._carry_shardings[1]))

    def carry_to_host(self, carry) -> Tuple[np.ndarray, ...]:
        """Bitwise host snapshot of the carry (checkpoint boundary — the
        ONE deliberate device read outside the pass finalize)."""
        return tuple(np.asarray(leaf) for leaf in carry)

    def restore_carry(self, host_carry):
        if self.mesh is None:
            return tuple(jnp.asarray(leaf, self.dtype)
                         for leaf in host_carry)
        return tuple(jax.device_put(leaf, sh)
                     for leaf, sh in zip(host_carry, self._carry_shardings))

    def _put_coef(self, coef):
        if self.mesh is None:
            return jnp.asarray(coef, self.dtype)
        from photon_tpu.parallel import mesh as M
        return M.replicate(jnp.asarray(coef, self.dtype), self.mesh)

    # -- the streamed evaluation --------------------------------------------

    def value_and_gradient(
        self, coef, *, resume=None,
        on_chunk: Optional[Callable[[int, int, tuple], None]] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One full streamed pass -> host ``(f, g)``.

        The per-chunk loop is pure async dispatch (no host syncs, no
        collectives on the mesh path); the pass's single host crossing is
        the np.asarray pull of the finalized pair. ``resume=(host_carry,
        next_chunk)`` continues a checkpointed pass mid-stream.
        """
        coef_dev = self._put_coef(coef)
        if resume is not None:
            carry = self.restore_carry(resume.carry)
            start = int(resume.next_chunk)
        else:
            carry = self.init_carry()
            start = 0
        pass_idx = self.passes
        for chunk in self.loader.stream(start_chunk=start):
            carry = self._partial(carry, coef_dev, chunk.batch)
            # zero-copy consumption token: the new carry's readiness
            # implies this chunk's reads are done, freeing its buffer
            self.loader.release(chunk, carry)
            if on_chunk is not None:
                on_chunk(pass_idx, chunk.index, carry)
        f_dev, g_dev = self._finalize(carry, coef_dev, self._l2_dev)
        self.passes = pass_idx + 1
        # pass boundary: the solver's host loop needs scalars — np.asarray
        # here is the single sync of the whole pass, by design
        return np.asarray(f_dev), np.asarray(g_dev)


# =========================================================================
# Chunk-cursor checkpoint (crc-framed npz, atomic publish)
# =========================================================================

_MAGIC = b"PTSTRMC1"
_SCHEMA = 1


def _encode_checkpoint(meta: dict, arrays: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    body = buf.getvalue()
    meta_b = json.dumps(meta, sort_keys=True).encode()
    return (_MAGIC + struct.pack("<II", zlib.crc32(body), len(meta_b))
            + meta_b + body)


def _decode_checkpoint(blob: bytes) -> Tuple[dict, dict]:
    if blob[:8] != _MAGIC:
        raise ValueError("not a stream checkpoint (bad magic)")
    crc, mlen = struct.unpack("<II", blob[8:16])
    meta = json.loads(blob[16:16 + mlen].decode())
    body = blob[16 + mlen:]
    if zlib.crc32(body) != crc:
        raise ValueError("stream checkpoint payload crc mismatch")
    if meta.get("schema") != _SCHEMA:
        raise ValueError(f"stream checkpoint schema {meta.get('schema')} "
                         f"!= {_SCHEMA}")
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files}
    return meta, arrays


def load_stream_checkpoint(path: str) -> Tuple[dict, dict]:
    """(meta, arrays) of a chunk-cursor checkpoint; raises ValueError on
    torn/corrupt files (crc framed)."""
    return _decode_checkpoint(rio.read_bytes(path, op="stream.checkpoint"))


class _Resume(NamedTuple):
    carry: Tuple[np.ndarray, ...]
    next_chunk: int
    eval_x: np.ndarray


class _EvalDriver:
    """Evaluation boundary between the host solver and the streamed
    problem.

    Tracks the current iteration's completed ``(f, g)`` evaluations;
    after a resume it serves them back from the checkpoint cache (bitwise)
    and continues the in-flight evaluation from its chunk cursor. The
    per-chunk checkpoint hook persists: iteration-start solver state +
    completed evals + in-flight carry/cursor — everything iteration
    replay needs to be bitwise identical to the uninterrupted run.
    """

    def __init__(self, problem: StreamedProblem, path: Optional[str],
                 every: int):
        self.problem = problem
        self.path = path
        self.every = int(every or 0)
        self.completed: list = []
        self.serve_idx = 0
        self.iter_arrays: dict = {}
        self.iter_meta: dict = {}
        self.inflight: Optional[_Resume] = None
        self._restored: Optional[Tuple[dict, dict]] = None
        if path and os.path.exists(path):
            self._load()

    def _load(self) -> None:
        meta, arrays = load_stream_checkpoint(self.path)
        self._restored = (meta, arrays)
        self.iter_arrays = {k[3:]: arrays[k] for k in arrays
                            if k.startswith("st_")}
        self.iter_meta = {"mode": meta["mode"], "phase": meta["phase"]}
        self.completed = [(arrays["comp_f"][i], arrays["comp_g"][i])
                          for i in range(int(meta["n_completed"]))]
        self.serve_idx = 0
        carry = tuple(arrays[f"carry_{i}"]
                      for i in range(int(meta["n_carry"])))
        self.inflight = _Resume(carry=carry,
                                next_chunk=int(meta["next_chunk"]),
                                eval_x=arrays["eval_x"])
        self.problem.passes = int(meta["pass_idx"])

    def take_restored(self) -> Optional[Tuple[dict, dict]]:
        r, self._restored = self._restored, None
        return r

    def begin_iteration(self, arrays: dict, meta: dict) -> None:
        """Snapshot the solver state at the top of an iteration. While a
        resumed iteration still has cached evals to serve (or an
        in-flight pass), the restored snapshot stays canonical — the
        caller's freshly re-captured state is bitwise the same anyway."""
        if self.serve_idx < len(self.completed) or self.inflight is not None:
            return
        self.iter_arrays = {k: np.array(v) for k, v in arrays.items()}
        self.iter_meta = dict(meta)
        self.completed = []
        self.serve_idx = 0

    def evaluate(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        if self.serve_idx < len(self.completed):
            f, g = self.completed[self.serve_idx]
            self.serve_idx += 1
            return f, g
        resume = self.inflight
        self.inflight = None
        if resume is not None and not np.array_equal(resume.eval_x, x):
            raise RuntimeError(
                "stream checkpoint resume mismatch: the replayed "
                "iteration requested an evaluation point different from "
                "the checkpointed in-flight one — checkpoint and run "
                "state have diverged")
        hook = None
        if self.path and (self.every > 0 or chaos.is_active()):
            hook = lambda p, c, carry: self._on_chunk(x, p, c, carry)  # noqa: E731
        f, g = self.problem.value_and_gradient(x, resume=resume,
                                               on_chunk=hook)
        self.completed.append((f, g))
        self.serve_idx += 1
        return f, g

    def _on_chunk(self, x, pass_idx: int, chunk_idx: int, carry) -> None:
        kill = chaos.should_kill_stream(pass_idx, chunk_idx)
        cadence = self.every > 0 and (chunk_idx + 1) % self.every == 0
        if not (kill or cadence):
            return
        self._save(x, pass_idx, chunk_idx + 1, carry)
        if kill:
            raise chaos.SimulatedKill(
                f"chaos: killed streamed solve at pass {pass_idx}, "
                f"chunk {chunk_idx} (checkpoint written)")

    def _save(self, eval_x, pass_idx: int, next_chunk: int, carry) -> None:
        arrays = {f"st_{k}": np.asarray(v)
                  for k, v in self.iter_arrays.items()}
        k = len(self.completed)
        if k:
            arrays["comp_f"] = np.stack(
                [np.asarray(f) for f, _ in self.completed])
            arrays["comp_g"] = np.stack(
                [np.asarray(g) for _, g in self.completed])
        else:
            d = int(np.shape(eval_x)[0])
            arrays["comp_f"] = np.zeros((0,), np.float64)
            arrays["comp_g"] = np.zeros((0, d), np.float64)
        host_carry = self.problem.carry_to_host(carry)
        for i, leaf in enumerate(host_carry):
            arrays[f"carry_{i}"] = leaf
        arrays["eval_x"] = np.asarray(eval_x)
        meta = {
            "schema": _SCHEMA,
            "mode": self.iter_meta.get("mode", "lbfgs"),
            "phase": self.iter_meta.get("phase", "loop"),
            "pass_idx": int(pass_idx),
            "next_chunk": int(next_chunk),
            "n_completed": int(k),
            "n_carry": len(host_carry),
        }
        rio.atomic_write_bytes(self.path, _encode_checkpoint(meta, arrays),
                               op="stream.checkpoint")
        try:
            from photon_tpu.obs.metrics import registry
            registry.counter("stream.checkpoints").inc()
        except Exception:   # hygiene-ok — telemetry is best-effort
            pass

    def finish(self) -> None:
        """Solve completed: the cursor checkpoint is obsolete (a leftover
        file would resume a FINISHED solve's final iteration)."""
        if self.path and os.path.exists(self.path):
            try:
                os.remove(self.path)
            except OSError:  # pragma: no cover — best-effort cleanup
                pass


# =========================================================================
# Host-loop solvers (ports of optim/lbfgs.minimize / optim/owlqn.minimize)
# =========================================================================

def _two_loop_host(g, s_hist, y_hist, rho, n_pairs, head, m):
    """Numpy port of lbfgs.two_loop_direction (same visit order)."""
    q = np.array(g)
    alphas = np.zeros(m, q.dtype)
    for j in range(n_pairs):
        idx = (head - 1 - j) % m
        a = rho[idx] * float(np.dot(s_hist[idx], q))
        alphas[idx] = a
        q = q - a * y_hist[idx]
    gamma = 1.0
    if n_pairs > 0:
        last = (head - 1) % m
        yy = float(np.dot(y_hist[last], y_hist[last]))
        if yy > 0:
            gamma = float(np.dot(s_hist[last], y_hist[last])) / yy
    r = gamma * q
    for j in range(n_pairs):
        idx = (head - n_pairs + j) % m
        beta = rho[idx] * float(np.dot(y_hist[idx], r))
        r = r + s_hist[idx] * (alphas[idx] - beta)
    return -r


def _zoom_candidate_host(a_lo, f_lo, d_lo, a_hi, f_hi):
    h = a_hi - a_lo
    denom = 2.0 * (f_hi - f_lo - d_lo * h)
    a_q = a_lo - d_lo * h * h / denom if denom != 0.0 else float("inf")
    mid = a_lo + 0.5 * h
    lo, hi = min(a_lo, a_hi), max(a_lo, a_hi)
    pad = 0.1 * (hi - lo)
    if not np.isfinite(a_q) or a_q <= lo + pad or a_q >= hi - pad:
        return mid
    return a_q


def _wolfe_host(evaluate, x, direction, f0, g0, *, initial_step=1.0,
                c1=1e-4, c2=0.9, max_evals=25, max_step=1e10):
    """Host port of linesearch.wolfe_linesearch: same bracket/zoom state
    machine, same approximate-Wolfe (Hager-Zhang flatness) acceptance,
    same never-uphill accepted-point contract. Returns
    (step, f, g, num_evals, success)."""
    f0 = float(f0)
    d0 = float(np.dot(g0, direction))
    slack = 8.0 * float(np.finfo(x.dtype).eps) * abs(f0)
    stage_bracket = True
    i = 0
    a_next = float(initial_step)
    a_lo, f_lo, d_lo, g_lo = 0.0, f0, d0, g0
    a_hi, f_hi, d_hi = 0.0, f0, d0
    a_prev, f_prev, d_prev, g_prev = 0.0, f0, d0, g0
    a_best, f_best, g_best = 0.0, f0, g0
    success = False
    while True:
        f_arr, g_a = evaluate(x + a_next * direction)
        f_a = float(f_arr)
        d_a = float(np.dot(g_a, direction))
        i += 1
        a = a_next

        if f_a < f_best and np.isfinite(f_a):
            a_best, f_best, g_best = a, f_a, g_a

        armijo_fail = (f_a > f0 + c1 * a * d0) or not np.isfinite(f_a)
        wolfe_ok = abs(d_a) <= -c2 * d0
        approx_conv = ((f_a <= f0 + slack) and (d_a >= c2 * d0)
                       and (d_a <= (2.0 * c1 - 1.0) * d0)
                       and np.isfinite(f_a))
        approx_take = approx_conv and f_a <= f0
        approx_stop = approx_conv and not approx_take

        grow = False
        entering_zoom = False
        if stage_bracket:
            to_zoom1 = armijo_fail or (i > 1 and f_a >= f_prev)
            accept = (not to_zoom1) and wolfe_ok
            to_zoom2 = (not to_zoom1) and (not wolfe_ok) and d_a >= 0
            grow = not (to_zoom1 or accept or to_zoom2)
            entering_zoom = to_zoom1 or to_zoom2
            if to_zoom1:
                n_lo = (a_prev, f_prev, d_prev, g_prev)
                n_hi = (a, f_a, d_a)
            else:
                n_lo = (a, f_a, d_a, g_a)
                n_hi = (a_prev, f_prev, d_prev)
        else:
            shrink_hi = armijo_fail or f_a >= f_lo
            accept = (not shrink_hi) and wolfe_ok
            flip = ((not shrink_hi) and (not wolfe_ok)
                    and d_a * (a_hi - a_lo) >= 0)
            if shrink_hi:
                n_lo = (a_lo, f_lo, d_lo, g_lo)
                n_hi = (a, f_a, d_a)
            else:
                n_lo = (a, f_a, d_a, g_a)
                n_hi = (a_lo, f_lo, d_lo) if flip else (a_hi, f_hi, d_hi)
        accept = accept or approx_take

        a_lo, f_lo, d_lo, g_lo = n_lo
        a_hi, f_hi, d_hi = n_hi

        interval_dead = (entering_zoom or not stage_bracket) and (
            abs(a_hi - a_lo) <= 1e-10 * max(abs(a_hi), 1.0))
        collapse_accept = interval_dead and not accept

        if accept:
            a_best, f_best, g_best = a, f_a, g_a
        elif collapse_accept:
            a_best, f_best, g_best = a_lo, f_lo, g_lo
        success = success or accept or approx_stop

        if accept or collapse_accept or approx_stop or i >= max_evals:
            return a_best, f_best, g_best, i, success

        if stage_bracket and grow:
            a_next = min(2.0 * a, max_step)
        else:
            a_next = _zoom_candidate_host(a_lo, f_lo, d_lo, a_hi, f_hi)
            stage_bracket = False
        a_prev, f_prev, d_prev, g_prev = a, f_a, d_a, g_a


def _nonfinite_code_host(f, g_finite: bool) -> int:
    if np.isfinite(f):
        return int(FailureMode.NONE if g_finite
                   else FailureMode.NON_FINITE_GRADIENT)
    return int(FailureMode.NON_FINITE_LOSS)


def _reason_host(it, f_old, f_new, gnorm, value_tol, gradient_tol,
                 max_iterations, improved) -> int:
    """Host port of base.convergence_reason's priority order."""
    if it >= max_iterations:
        return int(ConvergenceReason.MAX_ITERATIONS)
    if abs(f_old - f_new) <= value_tol and improved:
        return int(ConvergenceReason.FUNCTION_VALUES_CONVERGED)
    if gnorm <= gradient_tol:
        return int(ConvergenceReason.GRADIENT_CONVERGED)
    return int(ConvergenceReason.NOT_CONVERGED)


def _fresh_state(x0: np.ndarray, m: int) -> dict:
    d = x0.shape[0]
    dtype = x0.dtype
    return {
        "x": np.array(x0), "f": np.zeros((), np.float64),
        "g": np.zeros(d, dtype), "pg": np.zeros(d, dtype),
        "s_hist": np.zeros((m, d), dtype), "y_hist": np.zeros((m, d), dtype),
        "rho": np.zeros(m, dtype),
        "n_pairs": np.int32(0), "head": np.int32(0), "it": np.int32(0),
        "n_evals": np.int32(0), "ls_failed": np.bool_(False),
        "nf_count": np.int32(0),
        "reason": np.int32(ConvergenceReason.NOT_CONVERGED),
        "failure": np.int32(FailureMode.NONE),
        "value_tol": np.zeros((), np.float64),
        "gradient_tol": np.zeros((), np.float64),
    }


def _snapshot(S: dict) -> dict:
    return {k: np.array(v) for k, v in S.items()}


def _tolerances_host(f0, g0_norm, rel_tol, dtype) -> Tuple[float, float]:
    tiny = float(np.finfo(dtype).tiny)
    return (rel_tol * max(abs(float(f0)), tiny),
            rel_tol * max(float(g0_norm), tiny))


def _result_from_state(S: dict, dtype, gradient=None) -> SolverResult:
    g = S["g"] if gradient is None else gradient
    return SolverResult(
        coef=jnp.asarray(S["x"], dtype),
        value=jnp.asarray(float(S["f"]), dtype),
        gradient=jnp.asarray(g, dtype),
        iterations=jnp.asarray(int(S["it"]), jnp.int32),
        reason=jnp.asarray(int(S["reason"]), jnp.int32),
        num_fun_evals=jnp.asarray(int(S["n_evals"]), jnp.int32),
        failure=jnp.asarray(int(S["failure"]), jnp.int32),
    )


def minimize_streamed(
    problem: StreamedProblem,
    x0,
    *,
    config: SolverConfig = SolverConfig(),
    l1_weight=0.0,
    checkpoint_path: Optional[str] = None,
    checkpoint_every_chunks: int = 0,
) -> SolverResult:
    """L-BFGS (or OWL-QN when any l1 weight is positive) against a
    ``StreamedProblem``, mirroring optim/lbfgs.minimize /
    optim/owlqn.minimize semantics on a host loop.

    ``checkpoint_path`` + ``checkpoint_every_chunks`` enable the
    chunk-cursor checkpoint: every N accumulated chunks the solver
    persists enough state to resume bitwise after a kill; an existing
    file at the path is resumed from automatically (and removed once the
    solve completes).
    """
    if config.lower_bounds is not None or config.upper_bounds is not None:
        raise ValueError("box constraints are not supported on the "
                         "streamed path (use the resident solver)")
    x0 = np.asarray(x0)
    d = x0.shape[0]
    dtype = x0.dtype
    l1 = np.broadcast_to(np.asarray(l1_weight, dtype), (d,)).copy()
    if config.l1_mask is not None:
        l1 = l1 * np.asarray(config.l1_mask, dtype)
    driver = _EvalDriver(problem, checkpoint_path, checkpoint_every_chunks)
    if bool(np.any(l1 > 0)):
        result = _owlqn_streamed(driver, x0, l1, config)
    else:
        result = _lbfgs_streamed(driver, x0, config)
    driver.finish()
    return result


def _init_or_restore(driver: _EvalDriver, x0: np.ndarray, m: int,
                     mode: str) -> dict:
    restored = driver.take_restored()
    if restored is None:
        S = _fresh_state(x0, m)
        driver.begin_iteration(S, {"mode": mode, "phase": "init"})
        S["_phase"] = "init"
        return S
    meta, _ = restored
    if meta["mode"] != mode:
        raise ValueError(f"checkpoint solver mode {meta['mode']!r} != "
                         f"requested {mode!r}")
    S = {k: np.array(v) for k, v in driver.iter_arrays.items()}
    if S["x"].shape != x0.shape:
        raise ValueError("checkpoint dimension mismatch")
    S["_phase"] = meta["phase"]
    return S


def _lbfgs_streamed(driver: _EvalDriver, x0: np.ndarray,
                    config: SolverConfig) -> SolverResult:
    m = config.num_corrections
    dtype = x0.dtype
    S = _init_or_restore(driver, x0, m, "lbfgs")

    if S.pop("_phase") == "init":
        f0, g0 = driver.evaluate(S["x"])
        vt, gt = _tolerances_host(f0, np.linalg.norm(g0),
                                  config.tolerance, dtype)
        S["f"] = np.float64(float(f0))
        S["g"] = np.asarray(g0)
        S["value_tol"], S["gradient_tol"] = np.float64(vt), np.float64(gt)
        S["n_evals"] = np.int32(1)
        S["reason"] = np.int32(
            ConvergenceReason.GRADIENT_CONVERGED
            if float(np.linalg.norm(g0)) <= gt
            else ConvergenceReason.NOT_CONVERGED)
        S["failure"] = np.int32(_nonfinite_code_host(
            float(f0), bool(np.all(np.isfinite(g0)))))

    while (int(S["reason"]) == ConvergenceReason.NOT_CONVERGED
           and int(S["failure"]) == FailureMode.NONE):
        driver.begin_iteration(S, {"mode": "lbfgs", "phase": "loop"})
        x, f, g = S["x"], float(S["f"]), S["g"]
        n_pairs, head = int(S["n_pairs"]), int(S["head"])

        direction = _two_loop_host(g, S["s_hist"], S["y_hist"], S["rho"],
                                   n_pairs, head, m)
        if not float(np.dot(direction, g)) < 0:
            direction = -g
        gnorm = float(np.linalg.norm(g))
        init_step = (min(1.0, 1.0 / max(gnorm, 1e-12))
                     if n_pairs == 0 else 1.0)

        step, f_new, g_new, ls_evals, _ok = _wolfe_host(
            driver.evaluate, x, direction, f, g, initial_step=init_step,
            max_evals=config.linesearch_max_iterations)
        x_new = x + step * direction

        g_finite = bool(np.all(np.isfinite(g_new)))
        finite = bool(np.isfinite(f_new)) and g_finite
        decreased = finite and (f_new < f)
        if not decreased:        # reject non-decreasing steps entirely
            x_new, f_kept, g_kept = x, f, g
        else:
            f_kept, g_kept = f_new, g_new

        s = x_new - x
        yv = g_kept - g
        sy = float(np.dot(s, yv))
        store = decreased and sy > 1e-10 * max(float(np.dot(yv, yv)), 1e-30)
        if store:
            w = head % m
            S["s_hist"][w] = s
            S["y_hist"][w] = yv
            S["rho"][w] = 1.0 / sy
            S["head"] = np.int32((head + 1) % m)
            S["n_pairs"] = np.int32(min(n_pairs + 1, m))

        it = int(S["it"]) + 1
        reason = _reason_host(it, f, f_kept, float(np.linalg.norm(g_kept)),
                              float(S["value_tol"]),
                              float(S["gradient_tol"]),
                              config.max_iterations, decreased)
        if (reason == ConvergenceReason.NOT_CONVERGED
                and not decreased and bool(S["ls_failed"])):
            reason = int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING)
        nf_count = 0 if finite else int(S["nf_count"]) + 1
        failure = (_nonfinite_code_host(f_new, g_finite)
                   if nf_count >= 2 else int(FailureMode.NONE))
        if failure != FailureMode.NONE:
            reason = int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING)

        S["x"] = x_new
        S["f"] = np.float64(f_kept)
        S["g"] = np.asarray(g_kept)
        S["it"] = np.int32(it)
        S["reason"] = np.int32(reason)
        S["n_evals"] = np.int32(int(S["n_evals"]) + ls_evals)
        S["ls_failed"] = np.bool_(not decreased)
        S["nf_count"] = np.int32(nf_count)
        S["failure"] = np.int32(failure)

    return _result_from_state(S, dtype)


def _pseudo_gradient_host(x, g, l1):
    right = g + l1
    left = g - l1
    pg_zero = np.where(right < 0, right, np.where(left > 0, left, 0.0))
    return np.where(x > 0, right, np.where(x < 0, left, pg_zero))


def _project_orthant_host(x, orthant):
    return np.where(x * orthant > 0, x, 0.0)


def _owlqn_streamed(driver: _EvalDriver, x0: np.ndarray, l1: np.ndarray,
                    config: SolverConfig, c1: float = 1e-4) -> SolverResult:
    m = config.num_corrections
    dtype = x0.dtype
    eps = float(np.finfo(dtype).eps)
    S = _init_or_restore(driver, x0, m, "owlqn")

    def full_value(x, fx):
        return float(fx) + float(np.sum(l1 * np.abs(x)))

    if S.pop("_phase") == "init":
        f0s, g0 = driver.evaluate(S["x"])
        f0 = full_value(S["x"], f0s)
        pg0 = _pseudo_gradient_host(S["x"], np.asarray(g0), l1)
        vt, gt = _tolerances_host(f0, np.linalg.norm(pg0),
                                  config.tolerance, dtype)
        S["f"] = np.float64(f0)
        S["g"] = np.asarray(g0)
        S["pg"] = pg0
        S["value_tol"], S["gradient_tol"] = np.float64(vt), np.float64(gt)
        S["n_evals"] = np.int32(1)
        S["reason"] = np.int32(
            ConvergenceReason.GRADIENT_CONVERGED
            if float(np.linalg.norm(pg0)) <= gt
            else ConvergenceReason.NOT_CONVERGED)
        S["failure"] = np.int32(_nonfinite_code_host(
            float(f0), bool(np.all(np.isfinite(g0)))))

    while (int(S["reason"]) == ConvergenceReason.NOT_CONVERGED
           and int(S["failure"]) == FailureMode.NONE):
        driver.begin_iteration(S, {"mode": "owlqn", "phase": "loop"})
        x, f, g, pg = S["x"], float(S["f"]), S["g"], S["pg"]
        n_pairs, head = int(S["n_pairs"]), int(S["head"])

        direction = _two_loop_host(pg, S["s_hist"], S["y_hist"], S["rho"],
                                   n_pairs, head, m)
        direction = np.where(direction * (-pg) > 0, direction, 0.0)
        if not float(np.dot(direction, pg)) < 0:
            direction = -pg

        orthant = np.where(x != 0, np.sign(x), np.sign(-pg))
        pgnorm = float(np.linalg.norm(pg))
        step0 = (min(1.0, 1.0 / max(pgnorm, 1e-12))
                 if n_pairs == 0 else 1.0)
        slack = 8.0 * eps * abs(f)

        # orthant-projected backtracking Armijo with the same flat-exit
        # guard as owlqn.minimize's ls_body
        alpha = step0
        f_new, x_new, g_new = f, x, g
        k, ok = 0, False
        while k < config.linesearch_max_iterations:
            if k > 0:
                alpha *= 0.5
            x_new = _project_orthant_host(x + alpha * direction, orthant)
            f_s, g_new = driver.evaluate(x_new)
            f_new = full_value(x_new, f_s)
            k += 1
            ok = f_new <= f + c1 * float(np.dot(pg, x_new - x))
            if ok or (k >= 2 and abs(f_new - f) <= slack):
                break

        g_new = np.asarray(g_new)
        g_fin = bool(np.all(np.isfinite(g_new)))
        fin = bool(np.isfinite(f_new)) and g_fin
        failure = (int(FailureMode.NONE) if fin
                   else _nonfinite_code_host(f_new, g_fin))
        decreased = ok and (f_new < f) and fin
        if decreased:
            x_kept, f_kept, g_kept = x_new, f_new, g_new
        else:
            x_kept, f_kept, g_kept = x, f, g
        pg_new = _pseudo_gradient_host(x_kept, g_kept, l1)

        s = x_kept - x
        yv = g_kept - g
        sy = float(np.dot(s, yv))
        store = decreased and sy > 1e-10 * max(float(np.dot(yv, yv)), 1e-30)
        if store:
            w = head % m
            S["s_hist"][w] = s
            S["y_hist"][w] = yv
            S["rho"][w] = 1.0 / sy
            S["head"] = np.int32((head + 1) % m)
            S["n_pairs"] = np.int32(min(n_pairs + 1, m))

        it = int(S["it"]) + 1
        reason = _reason_host(it, f, f_kept, float(np.linalg.norm(pg_new)),
                              float(S["value_tol"]),
                              float(S["gradient_tol"]),
                              config.max_iterations, decreased)
        if reason == ConvergenceReason.NOT_CONVERGED and not decreased:
            reason = int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING)
        if failure != FailureMode.NONE:
            reason = int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING)

        S["x"] = np.asarray(x_kept)
        S["f"] = np.float64(f_kept)
        S["g"] = np.asarray(g_kept)
        S["pg"] = pg_new
        S["it"] = np.int32(it)
        S["reason"] = np.int32(reason)
        S["n_evals"] = np.int32(int(S["n_evals"]) + k)
        S["failure"] = np.int32(failure)

    return _result_from_state(S, dtype, gradient=S["pg"])
