"""Strong-Wolfe line search as a single lax.while_loop state machine.

Replaces the line search inside Breeze's LBFGS (the reference delegates to
breeze.optimize.LBFGS — optimization/LBFGS.scala:39; there is no JVM code to
port, so this is a fresh implementation of bracket+zoom, Nocedal & Wright
alg. 3.5/3.6, with quadratic interpolation and bisection safeguards).

Written entirely with lax control flow so it jits once and vmaps over
entity blocks (the random-effect path) with per-entity masking handled by
the while_loop batching rule.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

_BRACKET = 0
_ZOOM = 1
_DONE = 2


class LineSearchResult(NamedTuple):
    """Accepted point of a (strong or approximate) Wolfe search.

    Residual-slack contract: near the optimum the approximate-Wolfe test
    classifies a step as converged when the decrease underflows ``f0``'s
    ulp (``|f_a - f0| <= 8 * eps * |f0|``, the Hager-Zhang flatness
    window). That slack affects CLASSIFICATION only — ``success`` may be
    True for such a step — but the returned iterate never moves uphill:
    a candidate with ``f_a > f0`` is refused as the accepted point, so
    callers may rely on ``f <= f0`` whenever ``step > 0``."""

    step: Array       # accepted step length
    f: Array          # objective at accepted point
    g: Array          # full gradient at accepted point
    num_evals: Array  # objective evaluations used
    success: Array    # bool: strong or approximate Wolfe satisfied


class _Carry(NamedTuple):
    stage: Array
    i: Array
    a_next: Array
    # zoom bracket: lo carries its full gradient (it may be accepted)
    a_lo: Array
    f_lo: Array
    d_lo: Array
    g_lo: Array
    a_hi: Array
    f_hi: Array
    d_hi: Array
    # previous bracketing point
    a_prev: Array
    f_prev: Array
    d_prev: Array
    g_prev: Array
    # accepted / best-decrease-so-far result
    a_best: Array
    f_best: Array
    g_best: Array
    success: Array


def wolfe_linesearch(
    fg: Callable[..., Tuple[Array, Array]],
    x: Array,
    direction: Array,
    f0: Array,
    g0: Array,
    *fg_args,
    initial_step: Array | float = 1.0,
    c1: float = 1e-4,
    c2: float = 0.9,
    max_evals: int = 25,
    max_step: float = 1e10,
) -> LineSearchResult:
    """Find a step satisfying the strong Wolfe conditions along ``direction``.

    Falls back to the best strict-decrease point seen (success=False) if the
    Wolfe point isn't found within ``max_evals`` — the caller decides whether
    to reset curvature history.
    """
    dtype = x.dtype
    d0 = jnp.dot(g0, direction)

    def phi(a):
        f, g = fg(x + a * direction, *fg_args)
        return f, g, jnp.dot(g, direction)

    def zoom_candidate(a_lo, f_lo, d_lo, a_hi, f_hi):
        """Quadratic interpolation with bisection safeguard."""
        h = a_hi - a_lo
        denom = 2.0 * (f_hi - f_lo - d_lo * h)
        a_q = a_lo - d_lo * h * h / denom
        mid = a_lo + 0.5 * h
        lo, hi = jnp.minimum(a_lo, a_hi), jnp.maximum(a_lo, a_hi)
        pad = 0.1 * (hi - lo)
        bad = (~jnp.isfinite(a_q)) | (a_q <= lo + pad) | (a_q >= hi - pad)
        return jnp.where(bad, mid, a_q)

    def body(c: _Carry) -> _Carry:
        f_a, g_a, d_a = phi(c.a_next)
        i = c.i + 1
        a = c.a_next

        # best strict-decrease tracker (failure fallback); a -inf "best"
        # would poison the caller's carry, so non-finite trials never win
        better = (f_a < c.f_best) & jnp.isfinite(f_a)
        a_best = jnp.where(better, a, c.a_best)
        f_best = jnp.where(better, f_a, c.f_best)
        g_best = jnp.where(better, g_a, c.g_best)

        # a non-finite trial classifies as an Armijo failure: the bracket
        # shrinks back toward the finite region instead of growing into it
        armijo_fail = (f_a > f0 + c1 * a * d0) | ~jnp.isfinite(f_a)
        wolfe_ok = jnp.abs(d_a) <= -c2 * d0
        # approximate-Wolfe acceptance (Hager-Zhang style): near the
        # optimum the true decrease underflows f0's ulp, strict Armijo
        # reads it as failure, and the zoom stage burns the whole eval
        # budget shrinking a bracket around machine noise (measured: 55
        # evals for a 6-iteration f32 Poisson solve). When f is flat to
        # within rounding AND the directional derivative satisfies the
        # two-sided slope test, the step is as converged as the dtype
        # can express — accept it.
        slack = 8.0 * jnp.finfo(dtype).eps * jnp.abs(f0)
        approx_conv = ((f_a <= f0 + slack)
                       & (d_a >= c2 * d0)
                       & (d_a <= (2.0 * c1 - 1.0) * d0)
                       & jnp.isfinite(f_a))
        # the slack is a CLASSIFICATION device only: a candidate inside the
        # flatness window but with f_a > f0 is a rounding-level ascent —
        # report converged (success) without moving the iterate off the
        # best point seen (see the LineSearchResult contract)
        approx_take = approx_conv & (f_a <= f0)
        approx_stop = approx_conv & ~approx_take

        in_bracket = c.stage == _BRACKET
        # --- bracket-stage classification ---
        br_to_zoom1 = armijo_fail | ((i > 1) & (f_a >= c.f_prev))
        br_accept = (~br_to_zoom1) & wolfe_ok
        br_to_zoom2 = (~br_to_zoom1) & (~wolfe_ok) & (d_a >= 0)
        br_grow = (~br_to_zoom1) & (~br_accept) & (~br_to_zoom2)

        # --- zoom-stage classification ---
        zm_shrink_hi = armijo_fail | (f_a >= c.f_lo)
        zm_accept = (~zm_shrink_hi) & wolfe_ok
        zm_flip = (~zm_shrink_hi) & (~wolfe_ok) & (d_a * (c.a_hi - c.a_lo) >= 0)

        accept = jnp.where(in_bracket, br_accept, zm_accept) | approx_take

        # new bracket for the zoom stage
        z1 = br_to_zoom1
        new_a_lo = jnp.where(
            in_bracket,
            jnp.where(z1, c.a_prev, a),
            jnp.where(zm_shrink_hi, c.a_lo, a),
        )
        new_f_lo = jnp.where(
            in_bracket,
            jnp.where(z1, c.f_prev, f_a),
            jnp.where(zm_shrink_hi, c.f_lo, f_a),
        )
        new_d_lo = jnp.where(
            in_bracket,
            jnp.where(z1, c.d_prev, d_a),
            jnp.where(zm_shrink_hi, c.d_lo, d_a),
        )
        new_g_lo = jnp.where(
            in_bracket,
            jnp.where(z1, c.g_prev, g_a),
            jnp.where(zm_shrink_hi, c.g_lo, g_a),
        )
        new_a_hi = jnp.where(
            in_bracket,
            jnp.where(z1, a, c.a_prev),
            jnp.where(zm_shrink_hi, a, jnp.where(zm_flip, c.a_lo, c.a_hi)),
        )
        new_f_hi = jnp.where(
            in_bracket,
            jnp.where(z1, f_a, c.f_prev),
            jnp.where(zm_shrink_hi, f_a, jnp.where(zm_flip, c.f_lo, c.f_hi)),
        )
        new_d_hi = jnp.where(
            in_bracket,
            jnp.where(z1, d_a, c.d_prev),
            jnp.where(zm_shrink_hi, d_a, jnp.where(zm_flip, c.d_lo, c.d_hi)),
        )

        # next stage
        entering_zoom = in_bracket & (br_to_zoom1 | br_to_zoom2)
        staying_zoom = (~in_bracket)
        interval = jnp.abs(new_a_hi - new_a_lo)
        interval_dead = (entering_zoom | staying_zoom) & (
            interval <= 1e-10 * jnp.maximum(jnp.abs(new_a_hi), 1.0)
        )
        # accept lo when the zoom interval collapses (best we have there)
        collapse_accept = interval_dead & ~accept

        stage = jnp.where(
            accept | collapse_accept | approx_stop | (i >= max_evals),
            _DONE,
            jnp.where(in_bracket & br_grow, _BRACKET, _ZOOM),
        ).astype(jnp.int32)

        # next candidate step
        grow_a = jnp.minimum(2.0 * a, max_step)
        zoom_a = zoom_candidate(new_a_lo, new_f_lo, new_d_lo, new_a_hi, new_f_hi)
        a_next = jnp.where(in_bracket & br_grow, grow_a, zoom_a)

        # accepted result
        acc_a = jnp.where(accept, a, new_a_lo)
        acc_f = jnp.where(accept, f_a, new_f_lo)
        acc_g = jnp.where(accept, g_a, new_g_lo)
        take = accept | collapse_accept
        a_best = jnp.where(take, acc_a, a_best)
        f_best = jnp.where(take, acc_f, f_best)
        g_best = jnp.where(take, acc_g, g_best)
        success = c.success | accept | approx_stop

        return _Carry(
            stage=stage, i=i, a_next=a_next,
            a_lo=new_a_lo, f_lo=new_f_lo, d_lo=new_d_lo, g_lo=new_g_lo,
            a_hi=new_a_hi, f_hi=new_f_hi, d_hi=new_d_hi,
            a_prev=a, f_prev=f_a, d_prev=d_a, g_prev=g_a,
            a_best=a_best, f_best=f_best, g_best=g_best, success=success,
        )

    zero = jnp.zeros((), dtype)
    init = _Carry(
        stage=jnp.asarray(_BRACKET, jnp.int32),
        i=jnp.asarray(0, jnp.int32),
        a_next=jnp.asarray(initial_step, dtype),
        a_lo=zero, f_lo=f0, d_lo=d0, g_lo=g0,
        a_hi=zero, f_hi=f0, d_hi=d0,
        a_prev=zero, f_prev=f0, d_prev=d0, g_prev=g0,
        a_best=zero, f_best=f0, g_best=g0,
        success=jnp.asarray(False),
    )

    out = lax.while_loop(lambda c: c.stage != _DONE, body, init)
    return LineSearchResult(
        step=out.a_best, f=out.f_best, g=out.g_best,
        num_evals=out.i, success=out.success,
    )


class DirectionalLineSearchResult(NamedTuple):
    """Accepted point of a 1-D (margin-resident) Wolfe search. Same
    residual-slack contract as ``LineSearchResult``: classification may use
    the flatness window, the iterate never moves uphill (``f <= f0``
    whenever ``step > 0``)."""

    step: Array       # accepted step length
    f: Array          # phi(step)
    dphi: Array       # phi'(step) — the directional derivative at the
                      # accepted point; lets the caller reuse it as
                      # direction . g_new without re-deriving it from
                      # history inner products
    num_evals: Array  # phi evaluations used
    success: Array    # bool: strong or approximate Wolfe satisfied


class _DirCarry(NamedTuple):
    stage: Array
    i: Array
    a_next: Array
    a_lo: Array
    f_lo: Array
    d_lo: Array
    a_hi: Array
    f_hi: Array
    d_hi: Array
    a_prev: Array
    f_prev: Array
    d_prev: Array
    a_best: Array
    f_best: Array
    d_best: Array
    success: Array


def wolfe_linesearch_directional(
    phi: Callable[[Array], Tuple[Array, Array]],
    f0: Array,
    d0: Array,
    *,
    initial_step: Array | float = 1.0,
    c1: float = 1e-4,
    c2: float = 0.9,
    max_evals: int = 25,
    max_step: float = 1e10,
) -> DirectionalLineSearchResult:
    """``wolfe_linesearch`` over a scalar restriction ``phi(a) -> (f, dphi)``.

    Same bracket+zoom machine as ``wolfe_linesearch`` but with no gradient
    vectors in the carry: the caller holds margins resident and evaluates
    trial points in O(n_samples) (GLM: loss at ``margins + a * dir_margins``
    plus the L2 quadratic in precomputed dot products), so a whole search
    costs less than ONE classic evaluation's pass over the feature nnz.
    The full gradient is recovered by the caller only at the accepted point.
    """
    f0 = jnp.asarray(f0)
    dtype = f0.dtype

    def zoom_candidate(a_lo, f_lo, d_lo, a_hi, f_hi):
        h = a_hi - a_lo
        denom = 2.0 * (f_hi - f_lo - d_lo * h)
        a_q = a_lo - d_lo * h * h / denom
        mid = a_lo + 0.5 * h
        lo, hi = jnp.minimum(a_lo, a_hi), jnp.maximum(a_lo, a_hi)
        pad = 0.1 * (hi - lo)
        bad = (~jnp.isfinite(a_q)) | (a_q <= lo + pad) | (a_q >= hi - pad)
        return jnp.where(bad, mid, a_q)

    def body(c: _DirCarry) -> _DirCarry:
        f_a, d_a = phi(c.a_next)
        i = c.i + 1
        a = c.a_next

        # same non-finite handling as wolfe_linesearch: bad trials never
        # become the fallback best, and they shrink the bracket
        better = (f_a < c.f_best) & jnp.isfinite(f_a)
        a_best = jnp.where(better, a, c.a_best)
        f_best = jnp.where(better, f_a, c.f_best)
        d_best = jnp.where(better, d_a, c.d_best)

        armijo_fail = (f_a > f0 + c1 * a * d0) | ~jnp.isfinite(f_a)
        wolfe_ok = jnp.abs(d_a) <= -c2 * d0
        slack = 8.0 * jnp.finfo(dtype).eps * jnp.abs(f0)
        approx_conv = ((f_a <= f0 + slack)
                       & (d_a >= c2 * d0)
                       & (d_a <= (2.0 * c1 - 1.0) * d0)
                       & jnp.isfinite(f_a))
        approx_take = approx_conv & (f_a <= f0)
        approx_stop = approx_conv & ~approx_take

        in_bracket = c.stage == _BRACKET
        br_to_zoom1 = armijo_fail | ((i > 1) & (f_a >= c.f_prev))
        br_accept = (~br_to_zoom1) & wolfe_ok
        br_to_zoom2 = (~br_to_zoom1) & (~wolfe_ok) & (d_a >= 0)
        br_grow = (~br_to_zoom1) & (~br_accept) & (~br_to_zoom2)

        zm_shrink_hi = armijo_fail | (f_a >= c.f_lo)
        zm_accept = (~zm_shrink_hi) & wolfe_ok
        zm_flip = (~zm_shrink_hi) & (~wolfe_ok) & (d_a * (c.a_hi - c.a_lo) >= 0)

        accept = jnp.where(in_bracket, br_accept, zm_accept) | approx_take

        z1 = br_to_zoom1
        new_a_lo = jnp.where(
            in_bracket,
            jnp.where(z1, c.a_prev, a),
            jnp.where(zm_shrink_hi, c.a_lo, a),
        )
        new_f_lo = jnp.where(
            in_bracket,
            jnp.where(z1, c.f_prev, f_a),
            jnp.where(zm_shrink_hi, c.f_lo, f_a),
        )
        new_d_lo = jnp.where(
            in_bracket,
            jnp.where(z1, c.d_prev, d_a),
            jnp.where(zm_shrink_hi, c.d_lo, d_a),
        )
        new_a_hi = jnp.where(
            in_bracket,
            jnp.where(z1, a, c.a_prev),
            jnp.where(zm_shrink_hi, a, jnp.where(zm_flip, c.a_lo, c.a_hi)),
        )
        new_f_hi = jnp.where(
            in_bracket,
            jnp.where(z1, f_a, c.f_prev),
            jnp.where(zm_shrink_hi, f_a, jnp.where(zm_flip, c.f_lo, c.f_hi)),
        )
        new_d_hi = jnp.where(
            in_bracket,
            jnp.where(z1, d_a, c.d_prev),
            jnp.where(zm_shrink_hi, d_a, jnp.where(zm_flip, c.d_lo, c.d_hi)),
        )

        entering_zoom = in_bracket & (br_to_zoom1 | br_to_zoom2)
        staying_zoom = (~in_bracket)
        interval = jnp.abs(new_a_hi - new_a_lo)
        interval_dead = (entering_zoom | staying_zoom) & (
            interval <= 1e-10 * jnp.maximum(jnp.abs(new_a_hi), 1.0)
        )
        collapse_accept = interval_dead & ~accept

        stage = jnp.where(
            accept | collapse_accept | approx_stop | (i >= max_evals),
            _DONE,
            jnp.where(in_bracket & br_grow, _BRACKET, _ZOOM),
        ).astype(jnp.int32)

        grow_a = jnp.minimum(2.0 * a, max_step)
        zoom_a = zoom_candidate(new_a_lo, new_f_lo, new_d_lo, new_a_hi, new_f_hi)
        a_next = jnp.where(in_bracket & br_grow, grow_a, zoom_a)

        acc_a = jnp.where(accept, a, new_a_lo)
        acc_f = jnp.where(accept, f_a, new_f_lo)
        acc_d = jnp.where(accept, d_a, new_d_lo)
        take = accept | collapse_accept
        a_best = jnp.where(take, acc_a, a_best)
        f_best = jnp.where(take, acc_f, f_best)
        d_best = jnp.where(take, acc_d, d_best)
        success = c.success | accept | approx_stop

        return _DirCarry(
            stage=stage, i=i, a_next=a_next,
            a_lo=new_a_lo, f_lo=new_f_lo, d_lo=new_d_lo,
            a_hi=new_a_hi, f_hi=new_f_hi, d_hi=new_d_hi,
            a_prev=a, f_prev=f_a, d_prev=d_a,
            a_best=a_best, f_best=f_best, d_best=d_best, success=success,
        )

    zero = jnp.zeros((), dtype)
    init = _DirCarry(
        stage=jnp.asarray(_BRACKET, jnp.int32),
        i=jnp.asarray(0, jnp.int32),
        a_next=jnp.asarray(initial_step, dtype),
        a_lo=zero, f_lo=f0, d_lo=d0,
        a_hi=zero, f_hi=f0, d_hi=d0,
        a_prev=zero, f_prev=f0, d_prev=d0,
        a_best=zero, f_best=f0, d_best=d0,
        success=jnp.asarray(False),
    )

    out = lax.while_loop(lambda c: c.stage != _DONE, body, init)
    return DirectionalLineSearchResult(
        step=out.a_best, f=out.f_best, dphi=out.d_best,
        num_evals=out.i, success=out.success,
    )
