"""Solver contracts: state, convergence reasons, tolerance semantics.

Reference: photon-lib optimization/Optimizer.scala:36-190 (template method:
absolute tolerances derived from the initial state, convergence reasons at
:135-149), OptimizerState.scala, OptimizationStatesTracker.scala:31.

TPU re-design: a solver is a pure jittable function
``minimize(obj, x0, data, hyper, config) -> SolverResult``; the optimize
loop is a ``lax.while_loop`` carry rather than a driver-side iteration, so
the whole solve (including every "treeAggregate") is ONE XLA program.
Because all control flow is lax-level, the same solver can be ``vmap``-ed
over entity blocks for the random-effect path — per-entity convergence
masking falls out of the while_loop batching rule.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


class ConvergenceReason(enum.IntEnum):
    """Reference: Optimizer.getConvergenceReason (Optimizer.scala:135-149)."""

    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    FUNCTION_VALUES_CONVERGED = 2
    GRADIENT_CONVERGED = 3
    OBJECTIVE_NOT_IMPROVING = 4
    # TPU-native extension (no reference analog): the stochastic dual
    # solver (optim/sdca.py) terminates on a duality-gap certificate
    # rather than value/gradient deltas — the gap bounds the primal
    # suboptimality directly, so this is a stronger typed stop.
    DUALITY_GAP_CONVERGED = 5


class FailureMode(enum.IntEnum):
    """Typed device-side failure detected inside a solver while_loop.

    The reference has no analog — a NaN objective poisons the Breeze
    history silently and the model that comes out is garbage. Here every
    solver guards its carry: a non-finite loss/gradient/step rejects the
    step and terminates the solve with one of these codes on
    ``SolverResult.failure``, leaving the last finite iterate as the
    result. Coordinate descent (game/descent.py) reads the code at the
    coordinate boundary and rolls back."""

    NONE = 0
    NON_FINITE_LOSS = 1
    NON_FINITE_GRADIENT = 2
    NON_FINITE_STEP = 3


@dataclasses.dataclass(frozen=True)
class SolverConfig:
    """Reference: OptimizerConfig.scala:28 + per-solver defaults
    (LBFGS.scala:152-157, TRON.scala:256-262)."""

    max_iterations: int = 100
    tolerance: float = 1e-7
    # L-BFGS
    num_corrections: int = 10
    # TRON
    max_cg_iterations: int = 20
    max_improvement_failures: int = 5
    # Line search
    linesearch_max_iterations: int = 25
    # Box constraints (reference: constraintMap / LBFGSB bounds) — arrays [d]
    lower_bounds: Optional[jax.Array] = None
    upper_bounds: Optional[jax.Array] = None
    # L1 (OWL-QN): per-index weight mask multiplying the l1 weight from hyper;
    # None means regularize every index.
    l1_mask: Optional[jax.Array] = None
    # Per-iteration (loss, ||g||) ring buffer size; 0 disables tracking
    # (reference: OptimizationStatesTracker.scala:31 keeps up to 100 states)
    track_states: int = 0


class SolverResult(NamedTuple):
    """Final state, mirroring OptimizerState + convergence bookkeeping."""

    coef: Array
    value: Array
    gradient: Array
    iterations: Array          # int32
    reason: Array              # int32 ConvergenceReason
    num_fun_evals: Array       # int32 — objective evaluations (profiling)
    # ring buffers of the last `track_states` iterations (None when off)
    loss_history: Optional[Array] = None    # [T]
    gnorm_history: Optional[Array] = None   # [T]
    step_history: Optional[Array] = None    # [T] accepted step sizes (NaN
    #                                         where the solver has no step)
    # int32 FailureMode; None only for legacy constructions that predate
    # the non-finite guards (treated as NONE by consumers)
    failure: Optional[Array] = None


class StateTracking(NamedTuple):
    """While-loop carry fragment for the per-iteration ring buffer.

    Device-resident by design: the series accumulate inside the jitted
    while-loop carry and only cross to the host when a tracker/report
    actually reads them — never via callbacks staged into the loop.
    """

    loss: Array    # [T]
    gnorm: Array   # [T]
    step: Array    # [T] accepted step size (NaN for steps the solver
    #                doesn't parameterize, e.g. TRON's trust region)

    @staticmethod
    def init(size: int, dtype) -> Optional["StateTracking"]:
        if size <= 0:
            return None
        nan = jnp.full((size,), jnp.nan, dtype)
        return StateTracking(loss=nan, gnorm=nan, step=nan)

    def record(self, it: Array, f: Array, g: Array,
               step: Optional[Array] = None) -> "StateTracking":
        slot = it % self.loss.shape[0]
        return StateTracking(
            loss=self.loss.at[slot].set(f),
            gnorm=self.gnorm.at[slot].set(jnp.linalg.norm(g)),
            step=self.step.at[slot].set(
                jnp.nan if step is None else step),
        )


class Tolerances(NamedTuple):
    """Absolute tolerances set from the initial state
    (reference: Optimizer.setAbsTolerances)."""

    value_tol: Array
    gradient_tol: Array


def absolute_tolerances(f0: Array, g0: Array, rel_tol: float) -> Tolerances:
    eps = jnp.asarray(jnp.finfo(g0.dtype).tiny, dtype=g0.dtype)
    return Tolerances(
        value_tol=rel_tol * jnp.maximum(jnp.abs(f0), eps),
        gradient_tol=rel_tol * jnp.maximum(jnp.linalg.norm(g0), eps),
    )


def convergence_reason(
    it: Array,
    f_prev: Array,
    f: Array,
    g: Array,
    tols: Tolerances,
    max_iterations: int,
    improved: Optional[Array] = None,
    gnorm: Optional[Array] = None,
) -> Array:
    """Priority-ordered convergence decision, matching the reference order
    MaxIterations -> FunctionValuesConverged -> GradientConverged
    (Optimizer.scala:135-149). OBJECTIVE_NOT_IMPROVING is emitted by
    solvers that track improvement failures (TRON), not here.

    ``improved`` (bool) says the iterate actually changed this iteration:
    a rejected step leaves f == f_prev, and |delta f| = 0 must NOT read as
    FUNCTION_VALUES_CONVERGED — the reference classifies an unchanged
    iterate as ObjectiveNotImproving before checking function values
    (Optimizer.scala:140-142); here the solver's own failure counting
    handles that, so the function-values check is simply gated off.

    ``gnorm`` lets a solver that already holds g . g (e.g. the Gram-based
    directional L-BFGS) pass ||g|| in instead of paying one more full pass
    over a sharded 10^7-dim gradient here.
    """
    if gnorm is None:
        gnorm = jnp.linalg.norm(g)
    f_conv = jnp.abs(f_prev - f) <= tols.value_tol
    if improved is not None:
        f_conv = f_conv & improved
    reason = jnp.where(
        it >= max_iterations,
        ConvergenceReason.MAX_ITERATIONS,
        jnp.where(
            f_conv,
            ConvergenceReason.FUNCTION_VALUES_CONVERGED,
            jnp.where(
                gnorm <= tols.gradient_tol,
                ConvergenceReason.GRADIENT_CONVERGED,
                ConvergenceReason.NOT_CONVERGED,
            ),
        ),
    )
    return reason.astype(jnp.int32)


def nonfinite_code(f: Array, g_finite: Array) -> Array:
    """int32 FailureMode from a scalar loss and a scalar gradient-finite
    flag (callers pick the cheapest finite witness they have — e.g. the
    directional L-BFGS uses its already-computed g.g instead of paying a
    full pass over a sharded gradient)."""
    return jnp.where(
        jnp.isfinite(f),
        jnp.where(g_finite, FailureMode.NONE, FailureMode.NON_FINITE_GRADIENT),
        FailureMode.NON_FINITE_LOSS,
    ).astype(jnp.int32)


# Objective closures the solvers consume: fg(x, data, hyper) -> (f, g) and
# (second order) hv(x, v, data, hyper) -> Hv.
ValueAndGrad = Callable[..., Tuple[Array, Array]]
HessVec = Callable[..., Array]


def jit_donating(fn, donate_argnums=(0,)):
    """``jax.jit`` with solver-state buffers donated on accelerator backends.

    Donating x0 lets XLA alias the initial coefficients straight into the
    while-loop carry instead of round-tripping a fresh HBM buffer per
    solve — at model-sharded scale that buffer is the full per-device θ
    shard. The CPU backend ignores donation (and warns about it), so the
    gate keeps host runs quiet; callers must still never hand a donated
    position a caller-owned array they intend to reuse (see
    GlmOptimizationProblem.run's defensive copy for warm starts)."""
    if jax.default_backend() == "cpu":
        return jax.jit(fn)
    return jax.jit(fn, donate_argnums=donate_argnums)


def project_box(x: Array, config: SolverConfig) -> Array:
    """Box projection after each step (reference: LBFGS.scala box-constraint
    projection; OptimizerConfig.constraintMap)."""
    if config.lower_bounds is not None:
        x = jnp.maximum(x, config.lower_bounds)
    if config.upper_bounds is not None:
        x = jnp.minimum(x, config.upper_bounds)
    return x
