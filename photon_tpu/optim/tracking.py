"""Host-side views over solver state tracking.

Reference: photon-lib optimization/OptimizationStatesTracker.scala:31
(ring buffer of up to 100 (coefficients, loss, ||g||, time) states with a
convergence reason) and photon-api optimization/
RandomEffectOptimizationTracker.scala (aggregates per-entity trackers
into count/convergence-reason summaries logged after each coordinate
update, CoordinateDescent.scala:242-249).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_tpu.optim.base import ConvergenceReason, SolverResult


@dataclasses.dataclass
class OptimizationStatesTracker:
    """Ordered per-iteration (loss, ||g||) trajectory for one solve."""

    losses: np.ndarray      # [k] in iteration order
    gnorms: np.ndarray      # [k]
    iterations: int
    reason: ConvergenceReason
    steps: Optional[np.ndarray] = None   # [k] accepted step sizes (NaN
    #                                      where the solver has no step)

    @staticmethod
    def from_result(result: SolverResult) -> Optional["OptimizationStatesTracker"]:
        if result.loss_history is None:
            return None
        loss = np.asarray(result.loss_history)
        gn = np.asarray(result.gnorm_history)
        it = int(result.iterations)
        size = loss.shape[0]
        if it <= size:
            order = np.arange(it)
        else:  # un-rotate the ring buffer
            order = np.arange(it - size, it) % size
        losses, gnorms = loss[order], gn[order]
        valid = np.isfinite(losses)
        steps = None
        if result.step_history is not None:
            steps = np.asarray(result.step_history)[order][valid]
        return OptimizationStatesTracker(
            losses=losses[valid], gnorms=gnorms[valid],
            iterations=it,
            reason=ConvergenceReason(int(result.reason)),
            steps=steps)

    def summary(self) -> str:
        if not len(self.losses):
            return f"converged at start ({self.reason.name})"
        return (f"{self.iterations} iters, loss {self.losses[0]:.6g} -> "
                f"{self.losses[-1]:.6g}, ||g|| {self.gnorms[-1]:.3g}, "
                f"{self.reason.name}")

    def to_dict(self) -> Dict[str, object]:
        """JSON-ready trajectory for the RunReport (pays the host
        transfer if the arrays are still on device)."""
        out: Dict[str, object] = {
            "kind": "states",
            "iterations": int(self.iterations),
            "reason": self.reason.name,
            "loss": [float(v) for v in np.asarray(self.losses)],
            "gnorm": [float(v) for v in np.asarray(self.gnorms)],
        }
        if self.steps is not None:
            out["step"] = [float(v) for v in np.asarray(self.steps)]
        return out


@dataclasses.dataclass
class RandomEffectOptimizationTracker:
    """Aggregate of per-entity solver outcomes for one coordinate update.

    ``iterations``/``reasons`` may be DEVICE arrays — the producing solve
    hands them over without a host sync, and the first summary accessor
    pays the (lazy) transfer. A blocking transfer at update time would
    serialize every coordinate-descent sweep on the solver's completion.
    """

    iterations: np.ndarray   # [E] int (numpy or jax.Array)
    reasons: np.ndarray      # [E] int (ConvergenceReason; numpy or jax.Array)

    @property
    def num_entities(self) -> int:
        return len(self.iterations)

    def _host(self) -> Tuple[np.ndarray, np.ndarray]:
        if not isinstance(self.iterations, np.ndarray):
            object.__setattr__(self, "iterations", np.asarray(self.iterations))
            object.__setattr__(self, "reasons", np.asarray(self.reasons))
        return self.iterations, self.reasons

    def reason_counts(self) -> Dict[str, int]:
        _, reasons = self._host()
        out: Dict[str, int] = {}
        for r in ConvergenceReason:
            c = int(np.sum(reasons == int(r)))
            if c:
                out[r.name] = c
        return out

    def iteration_stats(self) -> Tuple[float, int, int]:
        """(mean, min, max) iterations across entities."""
        iters, _ = self._host()
        if not len(iters):
            return 0.0, 0, 0
        return (float(np.mean(iters)),
                int(np.min(iters)), int(np.max(iters)))

    def summary(self) -> str:
        mean_it, lo, hi = self.iteration_stats()
        return (f"{self.num_entities} entities, iterations "
                f"mean {mean_it:.1f} [{lo}, {hi}], reasons "
                f"{self.reason_counts()}")

    def to_dict(self) -> Dict[str, object]:
        """JSON-ready per-entity outcome aggregate for the RunReport
        (this is the drain point: the lazy device->host transfer in
        ``_host`` happens here, at a phase boundary, not in the sweep)."""
        mean_it, lo, hi = self.iteration_stats()
        return {
            "kind": "random_effect",
            "num_entities": int(self.num_entities),
            "iterations": {"mean": mean_it, "min": lo, "max": hi},
            "reason_counts": self.reason_counts(),
        }
