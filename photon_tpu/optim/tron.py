"""TRON: trust-region Newton with truncated conjugate-gradient.

A fresh JAX implementation of the algorithm the reference hand-ports from
LIBLINEAR (optimization/TRON.scala:80, runOneIteration :152,
truncatedConjugateGradientMethod :278): outer trust-region loop with
(eta0, eta1, eta2) = (1e-4, 0.25, 0.75) and (sigma1, sigma2, sigma3) =
(0.25, 0.5, 4.0), inner Steihaug CG on Hessian-vector products, retry on
non-improvement capped at ``max_improvement_failures`` (5). Defaults
maxIter=15, tol=1e-5, CG cap 20 (TRON.scala:256-262).

Each Hv product is one fused aggregator pass (ops/aggregators.py) — the
reference's extra treeAggregate per CG step becomes an extra XLA matvec.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    ConvergenceReason,
    FailureMode,
    StateTracking,
    SolverConfig,
    SolverResult,
    absolute_tolerances,
    convergence_reason,
    nonfinite_code,
)

Array = jax.Array

_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


class _CGCarry(NamedTuple):
    s: Array
    r: Array
    d: Array
    rr: Array
    it: Array
    done: Array


def _trcg(hess_vec, g, delta, max_cg, cg_tol_factor, *args):
    """Steihaug truncated CG: approximately solve H s = -g within ||s||<=delta.

    Returns (s, r) with r the final residual -g - Hs (used in prered).
    """
    dtype = g.dtype
    r0 = -g
    cg_tol = cg_tol_factor * jnp.linalg.norm(g)

    def cond(c: _CGCarry):
        return (~c.done) & (c.it < max_cg) & (jnp.sqrt(c.rr) > cg_tol)

    def body(c: _CGCarry) -> _CGCarry:
        hd = hess_vec(c.d, *args)
        dhd = jnp.dot(c.d, hd)
        alpha = c.rr / jnp.where(dhd > 0, dhd, 1.0)
        # non-positive curvature: jump to the trust-region boundary
        npc = dhd <= 0

        s_try = c.s + alpha * c.d
        outside = jnp.linalg.norm(s_try) > delta

        # boundary step: find tau >= 0 with ||s + tau d|| = delta
        sd = jnp.dot(c.s, c.d)
        dd = jnp.dot(c.d, c.d)
        ss = jnp.dot(c.s, c.s)
        rad = jnp.sqrt(jnp.maximum(sd * sd + dd * (delta * delta - ss), 0.0))
        tau = (rad - sd) / jnp.where(dd > 0, dd, 1.0)

        hit_boundary = npc | outside
        step = jnp.where(hit_boundary, tau, alpha)
        s_new = c.s + step * c.d
        r_new = c.r - step * hd
        rr_new = jnp.dot(r_new, r_new)
        beta = rr_new / jnp.where(c.rr > 0, c.rr, 1.0)
        d_new = r_new + beta * c.d

        return _CGCarry(
            s=s_new, r=r_new, d=d_new, rr=rr_new,
            it=c.it + 1, done=hit_boundary,
        )

    init = _CGCarry(
        s=jnp.zeros_like(g), r=r0, d=r0, rr=jnp.dot(r0, r0),
        it=jnp.asarray(0, jnp.int32), done=jnp.asarray(False),
    )
    out = lax.while_loop(cond, body, init)
    return out.s, out.r


class _Carry(NamedTuple):
    x: Array
    f: Array
    g: Array
    f_prev: Array
    delta: Array
    it: Array
    failures: Array
    reason: Array
    n_evals: Array
    nf_count: Array   # consecutive non-finite trial steps
    failure: Array    # int32 FailureMode (non-zero terminates the loop)
    trk: "Optional[StateTracking]"  # per-iteration ring buffer (None = off)


def minimize(
    value_and_grad,
    hess_vec,
    x0: Array,
    *args,
    config: SolverConfig = SolverConfig(max_iterations=15, tolerance=1e-5),
    cg_tol_factor: float = 0.1,
    hess_setup=None,
    hess_apply=None,
) -> SolverResult:
    """Minimize with ``value_and_grad(x, *args)`` and
    ``hess_vec(x, v, *args)`` (Hessian at x applied to v).

    When ``hess_setup``/``hess_apply`` are given, the Hessian operator is
    split into a once-per-outer-iteration ``hstate = hess_setup(x, *args)``
    (e.g. Gauss-Newton curvature weights, or the explicit d x d matrix for
    small dims) and a cheap per-CG-step ``hess_apply(hstate, v, *args)``.
    The GLM Hessian at fixed x is fully determined by per-sample curvature
    weights, so this removes one full data pass from every CG step
    (reference pays it: HessianVectorAggregator.scala:37)."""
    f0, g0 = value_and_grad(x0, *args)
    tols = absolute_tolerances(f0, g0, config.tolerance)
    dtype = x0.dtype

    def cond(c: _Carry):
        return ((c.reason == ConvergenceReason.NOT_CONVERGED)
                & (c.failure == FailureMode.NONE))

    def body(c: _Carry) -> _Carry:
        if hess_setup is not None:
            hstate = hess_setup(c.x, *args)
            hv = lambda v: hess_apply(hstate, v, *args)
        else:
            hv = lambda v: hess_vec(c.x, v, *args)
        s, r = _trcg(lambda v, *_: hv(v), c.g, c.delta,
                     config.max_cg_iterations, cg_tol_factor)

        gs = jnp.dot(c.g, s)
        prered = -0.5 * (gs - jnp.dot(s, r))
        x_try = c.x + s
        f_try, g_try = value_and_grad(x_try, *args)
        actred = c.f - f_try
        snorm = jnp.linalg.norm(s)

        # trust-radius update (LIBLINEAR/TRON.scala constants)
        denom = f_try - c.f - gs
        alpha = jnp.where(denom <= 0, _SIGMA3,
                          jnp.maximum(_SIGMA1, -0.5 * (gs / jnp.where(denom != 0, denom, 1.0))))
        asn = alpha * snorm
        delta = jnp.where(
            actred < _ETA0 * prered,
            jnp.minimum(jnp.maximum(asn, _SIGMA1 * snorm), _SIGMA2 * c.delta),
            jnp.where(
                actred < _ETA1 * prered,
                jnp.maximum(_SIGMA1 * c.delta, jnp.minimum(asn, _SIGMA2 * c.delta)),
                jnp.where(
                    actred < _ETA2 * prered,
                    jnp.maximum(_SIGMA1 * c.delta, jnp.minimum(asn, _SIGMA3 * c.delta)),
                    jnp.maximum(c.delta, jnp.minimum(asn, _SIGMA3 * c.delta)),
                ),
            ),
        )

        # Non-finite guard: a NaN actred fails `>` on its own, but a -Inf
        # f_try makes actred = +Inf and would be accepted — gate acceptance
        # on full finiteness of the trial, and keep the trust radius finite
        # (a NaN prered/asn poisons delta even on a rejected step) so the
        # shrunken region can recover from transient overflow.
        g_fin = jnp.all(jnp.isfinite(g_try))
        fin = jnp.isfinite(f_try) & g_fin
        accept = fin & (actred > _ETA0 * prered)
        delta = jnp.where(jnp.isfinite(delta), delta, 0.5 * c.delta)
        x_new = jnp.where(accept, x_try, c.x)
        f_new = jnp.where(accept, f_try, c.f)
        g_new = jnp.where(accept, g_try, c.g)
        failures = jnp.where(accept, 0, c.failures + 1).astype(jnp.int32)
        nf_count = jnp.where(fin, 0, c.nf_count + 1).astype(jnp.int32)
        failure = jnp.where(
            nf_count >= 2,
            nonfinite_code(f_try, g_fin),
            jnp.asarray(FailureMode.NONE, jnp.int32),
        )

        it = c.it + 1
        reason = convergence_reason(it, c.f, f_new, g_new, tols,
                                    config.max_iterations, improved=accept)
        reason = jnp.where(
            (reason == ConvergenceReason.NOT_CONVERGED)
            & (failures >= config.max_improvement_failures),
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason,
        )
        reason = jnp.where(
            failure != FailureMode.NONE,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason,
        )

        return _Carry(x=x_new, f=f_new, g=g_new, f_prev=c.f, delta=delta,
                      it=it, failures=failures, reason=reason,
                      n_evals=c.n_evals + 1, nf_count=nf_count,
                      failure=failure,
                      trk=None if c.trk is None
                      else c.trk.record(c.it, f_new, g_new))

    init = _Carry(
        x=x0, f=f0, g=g0, f_prev=f0,
        delta=jnp.linalg.norm(g0).astype(dtype),
        it=jnp.asarray(0, jnp.int32),
        failures=jnp.asarray(0, jnp.int32),
        reason=jnp.where(
            jnp.linalg.norm(g0) <= tols.gradient_tol,
            jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
            jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
        ),
        n_evals=jnp.asarray(1, jnp.int32),
        nf_count=jnp.asarray(0, jnp.int32),
        failure=nonfinite_code(f0, jnp.all(jnp.isfinite(g0))),
        trk=StateTracking.init(config.track_states, dtype),
    )

    out = lax.while_loop(cond, body, init)
    return SolverResult(
        coef=out.x, value=out.f, gradient=out.g,
        iterations=out.it, reason=out.reason, num_fun_evals=out.n_evals,
        loss_history=None if out.trk is None else out.trk.loss,
        gnorm_history=None if out.trk is None else out.trk.gnorm,
        step_history=None if out.trk is None else out.trk.step,
        failure=out.failure,
    )
