"""Damped Newton (IRLS) with explicit Hessian factorization.

TPU-native extension of DIRECT (optim/direct.py) past quadratic losses:
for twice-differentiable GLM losses (logistic, Poisson, squared) the
minimizer is reached by a handful of Newton steps, each one

    H(x) s = -g(x);   x <- x + t s      (t from Armijo backtracking)

where H is the explicit [d, d] GLM Hessian — one curvature-weighted Gram
contraction (MXU) — and the solve is a Cholesky factorization. Under vmap
over entity blocks this is a batched [E, K, K] potrf/trsm pipeline per
OUTER iteration: a logistic GLMix per-entity solve costs ~5 batched
factorizations total, versus TRON's nested outer x CG sequential
while_loop steps (the reference runs full iterative TRON/L-BFGS per
entity: SingleNodeOptimizationProblem.scala:40, TRON.scala:278-338).

This is classic IRLS re-shaped for the hardware: all sequential depth
that XLA cannot batch is collapsed into the one place it is algorithmically
irreducible (the outer Newton iteration), and everything inside an
iteration is a dense contraction or factorization the MXU executes
natively.

Safeguards:
  * non-PD / singular curvature (lambda = 0 with rank-deficient data)
    produces a non-finite Cholesky step -> fall back to steepest descent
    for that iteration (never silently stop at the start point);
  * Armijo backtracking rejects divergent steps (Poisson's exp margins
    can overflow on an overconfident Newton step: a non-finite trial
    value fails the acceptance test and the step halves);
  * tolerance semantics match the other solvers (absolute-from-relative
    at the initial state, Optimizer.scala:36-190 convention), so NEWTON
    drops into any config where LBFGS/TRON run today.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from photon_tpu.optim.base import (
    ConvergenceReason,
    FailureMode,
    SolverConfig,
    SolverResult,
    StateTracking,
    absolute_tolerances,
    convergence_reason,
    nonfinite_code,
)

Array = jax.Array

_ARMIJO_C1 = 1e-4


class _Carry(NamedTuple):
    x: Array
    f: Array
    g: Array
    it: Array
    n_evals: Array
    reason: Array
    failure: Array    # int32 FailureMode (non-zero terminates the loop)
    tracking: Optional[StateTracking]


def minimize(
    value_and_grad,
    hess_matrix,
    x0: Array,
    config: SolverConfig = SolverConfig(max_iterations=25, tolerance=1e-7),
) -> SolverResult:
    """``value_and_grad(x) -> (f, g)``; ``hess_matrix(x) -> [d, d]`` full
    (regularized) Hessian at x. Both are re-evaluated every outer
    iteration — unlike DIRECT, no quadratic assumption is made."""
    f0, g0 = value_and_grad(x0)
    tols = absolute_tolerances(f0, g0, config.tolerance)

    def linesearch(x, f, g, direction):
        """Armijo backtracking from t=1 (the Newton-natural step). The
        acceptance test carries a machine-epsilon slack (approximate-Wolfe
        style): near the optimum the true decrease underflows f's ulp, and
        a strict test would burn linesearch_max_iterations full data
        passes rejecting a perfectly converged step."""
        gdot = jnp.dot(g, direction)
        slack = 4.0 * jnp.finfo(x.dtype).eps * jnp.abs(f)

        def cond(c):
            t, f_new, _, k, done = c
            return (~done) & (k < config.linesearch_max_iterations)

        def body(c):
            t, _, _, k, _ = c
            f_t, g_t = value_and_grad(x + t * direction)
            ok = jnp.isfinite(f_t) & (f_t <= f + _ARMIJO_C1 * t * gdot + slack)
            return (jnp.where(ok, t, 0.5 * t), f_t, g_t, k + 1, ok)

        t0 = jnp.asarray(1.0, x.dtype)
        t, f_new, g_new, k, ok = jax.lax.while_loop(
            cond, body, (t0, f, g, jnp.asarray(0, jnp.int32),
                         jnp.asarray(False)))
        return t, f_new, g_new, k, ok

    def cond(c: _Carry):
        return ((c.reason == ConvergenceReason.NOT_CONVERGED)
                & (c.failure == FailureMode.NONE))

    def body(c: _Carry):
        h = hess_matrix(c.x)
        chol = jax.scipy.linalg.cho_factor(h)
        step = -jax.scipy.linalg.cho_solve(chol, c.g)
        # descent safeguard: a non-PD factorization yields NaN/inf or an
        # ascent direction; steepest descent keeps the iteration alive
        newton_ok = (jnp.all(jnp.isfinite(step))
                     & (jnp.dot(c.g, step) < 0.0))
        direction = jnp.where(newton_ok, step, -c.g)
        t, f_new, g_new, ls_evals, accepted = linesearch(
            c.x, c.f, c.g, direction)
        # the slack is a CLASSIFICATION device only: a step it admits with
        # f_new > f is a rounding-level ascent — keep `accepted` (the solve
        # is converged to the dtype's resolution and classifies as
        # FUNCTION_VALUES_CONVERGED below) but never move the iterate
        # uphill (same contract as linesearch.LineSearchResult)
        # non-finite guard: the Armijo test already screens f_t, but a
        # finite trial value can still carry a NaN/Inf gradient (saturated
        # margins) — never admit one into the carry, and terminate with a
        # typed failure (retrying the same step cannot help)
        g_fin = jnp.all(jnp.isfinite(g_new))
        take = accepted & (f_new <= c.f) & g_fin
        failure = jnp.where(
            accepted & ~g_fin,
            jnp.asarray(FailureMode.NON_FINITE_GRADIENT, jnp.int32),
            jnp.asarray(FailureMode.NONE, jnp.int32))
        x_new = jnp.where(take, c.x + t * direction, c.x)
        f_new = jnp.where(take, f_new, c.f)
        g_new = jnp.where(take, g_new, c.g)
        it = c.it + 1
        reason = convergence_reason(it, c.f, f_new, g_new, tols,
                                    config.max_iterations, improved=accepted)
        # an exhausted line search means no further progress is possible
        # (TRON reports the analogous state as OBJECTIVE_NOT_IMPROVING)
        reason = jnp.where(
            (reason == ConvergenceReason.NOT_CONVERGED) & ~accepted,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason)
        reason = jnp.where(
            failure != FailureMode.NONE,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason)
        tracking = (None if c.tracking is None
                    else c.tracking.record(c.it, f_new, g_new))
        return _Carry(x_new, f_new, g_new, it,
                      c.n_evals + ls_evals, reason, failure, tracking)

    # sentinel f_prev far from f0 so the initial check can only fire on
    # the gradient (an already-stationary start) or max_iterations=0
    f_far = f0 + 2.0 * tols.value_tol + 1.0
    init = _Carry(
        x=x0, f=f0, g=g0,
        it=jnp.asarray(0, jnp.int32),
        n_evals=jnp.asarray(1, jnp.int32),
        reason=jnp.asarray(
            convergence_reason(jnp.asarray(0, jnp.int32), f_far, f0, g0,
                               tols, config.max_iterations), jnp.int32),
        failure=nonfinite_code(f0, jnp.all(jnp.isfinite(g0))),
        tracking=StateTracking.init(config.track_states, x0.dtype))
    out = jax.lax.while_loop(cond, body, init)
    return SolverResult(
        coef=out.x, value=out.f, gradient=out.g,
        iterations=out.it, reason=out.reason, num_fun_evals=out.n_evals,
        loss_history=None if out.tracking is None else out.tracking.loss,
        gnorm_history=None if out.tracking is None else out.tracking.gnorm,
        step_history=None if out.tracking is None else out.tracking.step,
        failure=out.failure,
    )
