"""Optimization problems: config + objective + solver, with variances.

Reference: photon-api optimization/GeneralizedLinearOptimizationProblem
.scala, DistributedOptimizationProblem.scala:46 (run :177, runWithSampling
:159, computeVariances :82-100, updateRegularizationWeight),
SingleNodeOptimizationProblem.scala:40, OptimizerConfig.scala:28,
CoordinateOptimizationConfiguration.scala:30,48.

TPU re-design: ONE problem class serves both the reference's Distributed
(RDD) and SingleNode (Iterable) realizations — the same jitted solve runs
over a mesh-sharded batch (psum reductions) or vmapped over entity blocks.
Regularization weights are traced arguments, so a reg-path sweep reuses a
single compilation (the warm-start chain of ModelTraining.scala:134-147).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import DataBatch
from photon_tpu.function.objective import (
    GLMObjective,
    Hyper,
    NoRegularization,
    RegularizationContext,
)
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.ops.normalization import NormalizationContext, no_normalization
from photon_tpu.optim import lbfgs, owlqn, tron
from photon_tpu.optim.base import SolverConfig, SolverResult, jit_donating
from photon_tpu.types import OptimizerType, TaskType, VarianceComputationType
from photon_tpu.utils import jitcache

Array = jax.Array


class SweptSolve(NamedTuple):
    """Output of :meth:`GlmOptimizationProblem.solve_swept`: one model /
    solver result per grid lane, plus the stacked device views."""

    models: List[GeneralizedLinearModel]   # per-lane, original space
    results: List[SolverResult]            # per-lane views of ``stacked``
    stacked: SolverResult                  # every field has a [K] lane axis
    coefs: Array                           # [K, d] original-space stack


def _validate_direct(task, opt: "OptimizerConfig", regularization) -> None:
    """DIRECT's contract is the EXACT minimizer; reject every config it
    cannot solve exactly (shared by the fixed- and random-effect paths)."""
    if task != TaskType.LINEAR_REGRESSION:
        raise ValueError(
            "OptimizerType.DIRECT is exact only for the quadratic squared "
            f"loss (LINEAR_REGRESSION); use NEWTON for logistic/Poisson or "
            f"LBFGS/TRON for {task}")
    if opt.lower_bounds is not None or opt.upper_bounds is not None:
        raise ValueError("DIRECT does not support box constraints")
    if regularization.l1_weight(1.0) != 0.0:
        raise ValueError(
            "DIRECT solves the L2/unregularized normal equations exactly; "
            "L1/elastic-net needs OWLQN")


def _validate_newton(task, opt: "OptimizerConfig", regularization) -> None:
    """NEWTON needs second derivatives and a smooth objective (shared by
    the fixed- and random-effect paths)."""
    from photon_tpu.ops.losses import loss_for_task
    if not loss_for_task(task).has_hessian:
        raise ValueError(
            f"OptimizerType.NEWTON needs a twice-differentiable loss; "
            f"{task} has no Hessian — use LBFGS")
    if opt.lower_bounds is not None or opt.upper_bounds is not None:
        raise ValueError("NEWTON does not support box constraints; "
                         "use LBFGSB")
    if regularization.l1_weight(1.0) != 0.0:
        raise ValueError("NEWTON needs a smooth objective; L1/elastic-net "
                         "needs OWLQN")


def solver_cache_key(opt: "OptimizerConfig") -> tuple:
    """Everything in an OptimizerConfig that shapes a solver's trace."""
    return (opt.optimizer_type, opt.max_iterations, opt.tolerance,
            opt.num_corrections, opt.max_cg_iterations, opt.track_states,
            opt.explicit_hessian,
            jitcache.array_token(opt.lower_bounds),
            jitcache.array_token(opt.upper_bounds))


def norm_cache_key(norm) -> tuple:
    return (jitcache.array_token(norm.factors),
            jitcache.array_token(norm.shifts))


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Reference: OptimizerConfig.scala:28 (+ per-solver defaults)."""

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 100
    tolerance: float = 1e-7
    num_corrections: int = 10
    max_cg_iterations: int = 20
    lower_bounds: Optional[jax.Array] = None
    upper_bounds: Optional[jax.Array] = None
    # per-iteration (loss, ||g||) ring size; 0 = no tracking
    track_states: int = 0
    # TRON Hessian strategy: True = build the d x d Gauss-Newton matrix once
    # per outer iteration (one MXU GEMM; CG steps become O(d^2)); False =
    # matrix-free Hv with per-iteration curvature weights; None = auto
    # (explicit for dense features with dim <= 2048)
    explicit_hessian: Optional[bool] = None

    def solver_config(self) -> SolverConfig:
        return SolverConfig(
            max_iterations=self.max_iterations,
            tolerance=self.tolerance,
            num_corrections=self.num_corrections,
            max_cg_iterations=self.max_cg_iterations,
            lower_bounds=self.lower_bounds,
            upper_bounds=self.upper_bounds,
            track_states=self.track_states,
        )


@dataclasses.dataclass(frozen=True)
class GLMOptimizationConfiguration:
    """Per-coordinate optimization config (reference:
    CoordinateOptimizationConfiguration.scala:30,48)."""

    optimizer: OptimizerConfig = OptimizerConfig()
    regularization: RegularizationContext = NoRegularization
    regularization_weight: float = 0.0
    down_sampling_rate: float = 1.0


class GlmOptimizationProblem:
    """Task + config + normalization -> a reusable, jit-cached GLM solve.

    ``run`` maps to Optimizer.optimize over the whole batch; the reg weight
    is dynamic so ``update_regularization_weight`` (reference reg-path
    support) is free.

    Model space contract: the OPTIMIZER runs in transformed (normalized)
    coefficient space — that is the conditioning win — but every model this
    class accepts (warm starts) and returns lives in ORIGINAL feature
    space, converted at this boundary via the margin-invariant maps
    (reference: NormalizationContext.scala:80-126). Published models can
    therefore always be scored as theta.x against raw features.
    """

    def __init__(
        self,
        task: TaskType,
        config: GLMOptimizationConfiguration = GLMOptimizationConfiguration(),
        norm: NormalizationContext = no_normalization(),
        intercept_index: Optional[int] = None,
    ):
        if norm.shifts is not None and intercept_index is None:
            # a shift moves margins by a constant; only an intercept can
            # absorb it (reference: NormalizationContext requires an
            # intercept for shift-ful normalization types)
            raise ValueError(
                "normalization with shifts (STANDARDIZATION) requires an "
                "intercept feature; pass intercept_index")
        self.task = task
        self.config = config
        self.intercept_index = intercept_index
        self.objective = GLMObjective(loss_for_task(task), norm)
        # variances are reported for the PUBLISHED (original-space) model,
        # so curvature is evaluated with the unnormalized objective
        self._var_objective = (
            self.objective if norm.is_identity
            else GLMObjective(loss_for_task(task)))

    # -- solving ------------------------------------------------------------

    @property
    def _solve_fn(self):
        """Default solve (non-mesh callers / HLO inspection in tests)."""
        import os
        return self._solve_fn_for(
            os.environ.get("PHOTON_TPU_PALLAS_GLM") == "1")

    def _solve_fn_for(self, use_pallas: bool):
        opt = self.config.optimizer
        solver_cfg = opt.solver_config()
        obj = self.objective

        if opt.optimizer_type == OptimizerType.DIRECT:
            _validate_direct(self.task, opt, self.config.regularization)
        if opt.optimizer_type == OptimizerType.NEWTON:
            _validate_newton(self.task, opt, self.config.regularization)

        def build():
            def solve(x0: Array, batch: DataBatch, l2: Array, l1: Array) -> SolverResult:
                hyper = Hyper(l2_weight=l2)
                vg = lambda c: obj.value_and_gradient(c, batch, hyper)
                if opt.optimizer_type == OptimizerType.DIRECT:
                    from photon_tpu.optim import direct
                    return direct.minimize(
                        vg, lambda c: obj.hessian_matrix(c, batch, hyper), x0)
                if opt.optimizer_type == OptimizerType.NEWTON:
                    # explicit Hessian via the curvature-weights split: one
                    # weighted-Gram MXU contraction per outer iteration
                    # (same operator TRON's explicit gate builds)
                    from photon_tpu.ops.features import ModelShardedSparse
                    if isinstance(batch.features, ModelShardedSparse):
                        raise ValueError(
                            "NEWTON builds an explicit d x d Hessian, "
                            "which contradicts model-axis sharding of a "
                            "sparse theta; use LBFGS or TRON (matrix-"
                            "free) for this coordinate")
                    from photon_tpu.optim import newton
                    dim = x0.shape[0]
                    if opt.explicit_hessian is not True and dim > 8192:
                        # 8192^2 f32 = 256 MB per Hessian; beyond that the
                        # explicit build stops being an MXU bargain even
                        # on chip — NEWTON has no matrix-free mode, so
                        # refuse instead of OOMing (trace-time check:
                        # shapes are static under jit)
                        raise ValueError(
                            f"NEWTON builds an explicit [{dim}, {dim}] "
                            f"Hessian; use TRON (matrix-free) above "
                            f"d=8192, or set explicit_hessian=True to "
                            f"override")
                    return newton.minimize(
                        vg,
                        lambda c: obj.hessian_matrix_from_weights(
                            obj.hessian_weights(c, batch), dim, batch, hyper),
                        x0, config=solver_cfg)
                if opt.optimizer_type == OptimizerType.OWLQN:
                    return owlqn.minimize(vg, x0, l1_weight=l1, config=solver_cfg)
                if opt.optimizer_type == OptimizerType.TRON:
                    # Hessian operator split: curvature weights once per
                    # outer iteration; explicit d x d Gauss-Newton matrix
                    # (single GEMM -> MXU) when the dim is small and the
                    # features dense, matrix-free Hv otherwise.
                    from photon_tpu.ops.features import (
                        ModelShardedSparse,
                        SparseFeatures,
                    )
                    dim = x0.shape[0]
                    dense = not isinstance(
                        batch.features, (SparseFeatures, ModelShardedSparse))
                    explicit = opt.explicit_hessian
                    if explicit is None:
                        # auto: the d x d GEMM rebuild per outer iteration
                        # is an MXU bargain at any moderate dim (measured
                        # 20x faster on TPU v5e at d=512); on host CPU the
                        # crossover vs matrix-free Hv sits between d=256
                        # (1.5x faster) and d=512 (1.3x slower)
                        on_tpu = jax.default_backend() not in ("cpu",)
                        explicit = dense and (dim <= 2048 if on_tpu
                                              else dim <= 256)
                    if explicit:
                        hs = lambda c: obj.hessian_matrix_from_weights(
                            obj.hessian_weights(c, batch), dim, batch, hyper)
                        ha = lambda h, v: h @ v
                    else:
                        hs = lambda c: obj.hessian_weights(c, batch)
                        ha = lambda d2, v: obj.hessian_vector_from_weights(
                            d2, v, batch, hyper)
                    return tron.minimize(vg, None, x0, config=solver_cfg,
                                         hess_setup=hs, hess_apply=ha)
                from photon_tpu.ops.features import ModelShardedSparse
                if (isinstance(batch.features, ModelShardedSparse)
                        and batch.features.csc_ptr is not None
                        and opt.lower_bounds is None
                        and opt.upper_bounds is None):
                    # margin-resident directional L-BFGS: on the sharded
                    # path every feature pass is the wallclock, so the
                    # solve keeps margins resident and pays exactly one
                    # matvec + one rmatvec per iteration instead of one
                    # full evaluation per line-search trial. Gated on the
                    # CSC plan: a plan-less ModelShardedSparse is the
                    # legacy compatibility layout, and gets the legacy
                    # (classic line-search) solver with the scatter kernels
                    dp = obj.directional_problem(batch, hyper)
                    return lbfgs.minimize_directional(dp, x0,
                                                      config=solver_cfg)
                return lbfgs.minimize(vg, x0, config=solver_cfg)

            # donate x0 into the while-loop carry (accelerator backends
            # only — see optim/base.jit_donating)
            return jit_donating(solve, donate_argnums=(0,))

        # share the compiled solve across problem instances with identical
        # trace-shaping state (re-fits, sweep candidates, fresh
        # estimators). use_pallas is trace-shaping too: a mesh solve and
        # a single-device solve with the flag set must not share a trace
        # (the kernel carries no sharding annotations).
        key = ("glm_solve", self.task, solver_cache_key(opt),
               norm_cache_key(self.objective.norm), use_pallas)
        return jitcache.get_or_build(key, build)

    def run(
        self,
        batch: DataBatch,
        initial: Optional[Array] = None,
        dim: Optional[int] = None,
        dtype=None,
        regularization_weight: Optional[float] = None,
        mesh=None,
        pallas_ok: Optional[bool] = None,
    ) -> Tuple[GeneralizedLinearModel, SolverResult]:
        """Solve and return (model, solver stats). Variances are computed
        separately via ``compute_variances`` (reference behavior: variances
        only on the final model).

        With ``mesh``, the batch is sample-sharded over the mesh's data
        axis and the coefficients replicated before the jitted solve — the
        whole optimize loop then runs as ONE SPMD program whose gradient
        reductions are all-reduces over ICI (the treeAggregate + broadcast
        replacement, SURVEY §5.8)."""
        norm = self.objective.norm
        if self.config.optimizer.optimizer_type == OptimizerType.SDCA:
            import numpy as np
            if mesh is not None:
                raise ValueError(
                    "SDCA over a resident batch does not take a mesh — "
                    "build a meshed ChunkLoader and call run_streamed")
            if initial is not None and bool(np.any(np.asarray(initial) != 0)):
                raise ValueError(
                    "SDCA cannot warm-start from nonzero coefficients "
                    "(no dual preimage for an arbitrary w); start from "
                    "zeros or use LBFGS for warm-started re-fits")
            if dim is None and initial is not None:
                dim = int(np.shape(initial)[0])
            return self.run_sdca_resident(
                batch, dim=dim, dtype=dtype,
                regularization_weight=regularization_weight)
        if dtype is None:
            # match the batch: a float32 x0 against float64 data would
            # promote mid-solve and break the while_loop carry contract
            dtype = batch.labels.dtype
        if initial is None:
            assert dim is not None, "need dim when no initial coefficients"
            initial = jnp.zeros((dim,), dtype)
        elif not norm.is_identity:
            # warm starts arrive in original space; optimize in transformed
            initial = norm.model_to_transformed_space(
                jnp.asarray(initial), self.intercept_index)
        else:
            initial = jnp.asarray(initial)
            if mesh is None and jax.default_backend() != "cpu":
                # the jitted solve donates x0; this is the only path where
                # the caller's own array would reach the donated position
                # unwrapped (coordinate descent reuses the previous model
                # as the warm start across outer iterations)
                initial = initial.copy()
        if mesh is not None:
            from photon_tpu.parallel import mesh as M
            batch = M.shard_batch(batch, mesh)
            initial = M.replicate(initial, mesh)
        lam = (self.config.regularization_weight
               if regularization_weight is None else regularization_weight)
        l2 = jnp.asarray(self.config.regularization.l2_weight(lam), initial.dtype)
        l1 = jnp.asarray(self.config.regularization.l1_weight(lam), initial.dtype)
        import os
        flag = os.environ.get("PHOTON_TPU_PALLAS_GLM") == "1"
        # mesh here OR a caller-declared sharded batch (FixedEffect
        # Coordinate pre-shards at construction and passes pallas_ok=False)
        use_pallas = flag and mesh is None and pallas_ok is not False
        solve = self._solve_fn_for(use_pallas)
        if flag and not use_pallas:
            # the fused kernel has no sharding annotations: under a mesh
            # it would force replication of X or fail at lowering, so the
            # SPMD solve traces with the kernel hard-disabled
            from photon_tpu.ops import pallas_glm
            with pallas_glm.disabled():
                result = solve(initial, batch, l2, l1)
        else:
            result = solve(initial, batch, l2, l1)
        coef = result.coef
        if not norm.is_identity:
            coef = norm.transformed_space_to_model(coef, self.intercept_index)
        model = GeneralizedLinearModel(Coefficients(coef), self.task)
        return model, result

    # -- lane-batched sweeps (optim/batched) --------------------------------

    def _swept_solve_fn(self, mesh):
        opt = self.config.optimizer
        if opt.optimizer_type not in (OptimizerType.LBFGS,
                                      OptimizerType.OWLQN):
            raise ValueError(
                f"solve_swept supports LBFGS/OWLQN only, not "
                f"{opt.optimizer_type} (second-order solvers have no "
                f"vmappable lax-level batching rule for the lane stack)")
        from photon_tpu.optim import batched
        solver_cfg = opt.solver_config()
        obj = self.objective
        use_owlqn = opt.optimizer_type == OptimizerType.OWLQN

        def build():
            if mesh is None:
                def solve(x0_lanes: Array, batch: DataBatch,
                          l2: Array, l1: Array) -> SolverResult:
                    vg = lambda c, hyper: obj.value_and_gradient(
                        c, batch, hyper)
                    return batched.minimize_lanes(
                        vg, x0_lanes, l2=l2, l1=l1, config=solver_cfg,
                        use_owlqn=use_owlqn)
                return jit_donating(solve, donate_argnums=(0,))

            def solve(x0_lanes: Array, batch: DataBatch,
                      l2: Array, l1: Array) -> SolverResult:
                return batched.minimize_lanes_meshed(
                    obj, batch, x0_lanes, l2=l2, l1=l1, mesh=mesh,
                    config=solver_cfg, use_owlqn=use_owlqn)
            return jax.jit(solve)

        key = ("glm_solve_swept", self.task, solver_cache_key(opt),
               norm_cache_key(self.objective.norm),
               None if mesh is None else jitcache.array_token(mesh))
        return jitcache.get_or_build(key, build)

    def solve_swept(
        self,
        batch: DataBatch,
        lambdas,
        initial: Optional[Array] = None,
        initial_lanes: Optional[Array] = None,
        dim: Optional[int] = None,
        dtype=None,
        mesh=None,
    ) -> "SweptSolve":
        """Fit the whole regularization grid ``lambdas`` as ONE compiled
        lane-batched program (optim/batched.minimize_lanes).

        Same model-space contract as ``run``, per lane: warm starts
        (``initial`` shared, or ``initial_lanes [K, d]`` per lane) arrive
        in original space and the returned models live in original
        space. Weights are validated typed at entry
        (:class:`~photon_tpu.optim.batched.SweepWeightError`), never
        inside the compiled solve. A singleton grid compiles the same
        loop structure as the scalar solver ("any over one lane" is the
        scalar cond), so K=1 matches ``run``'s iteration count with
        coefficient parity at trace precision.
        """
        from photon_tpu.optim import batched
        from photon_tpu.ops.features import ModelShardedSparse
        if isinstance(batch.features, ModelShardedSparse):
            raise ValueError(
                "solve_swept does not support model-sharded features: K "
                "lanes hold K full coefficient vectors, which contradicts "
                "a theta range-sharded over the model axis")
        lams = batched.validate_lane_weights(lambdas, name="solve_swept grid")
        k = int(lams.shape[0])
        norm = self.objective.norm
        if dtype is None:
            dtype = batch.labels.dtype
        to_opt_space = (lambda c: c) if norm.is_identity else (
            lambda c: norm.model_to_transformed_space(c, self.intercept_index))
        if initial_lanes is not None:
            x0 = jnp.asarray(initial_lanes, dtype)
            if x0.ndim != 2 or x0.shape[0] != k:
                raise ValueError(
                    f"initial_lanes must be [K={k}, d], got {x0.shape}")
            x0 = jax.vmap(to_opt_space)(x0)
        elif initial is not None:
            init = to_opt_space(jnp.asarray(initial, dtype))
            x0 = jnp.broadcast_to(init, (k,) + init.shape) + 0
        else:
            assert dim is not None, "need dim when no initial coefficients"
            x0 = jnp.zeros((k, dim), dtype)
        if mesh is not None:
            from photon_tpu.optim import hier
            from photon_tpu.parallel import mesh as M
            sample_axes = hier._sample_axes(mesh)
            batch = M.shard_batch(
                batch, mesh,
                axis=sample_axes if len(sample_axes) > 1 else sample_axes[0])
            x0 = M.replicate(x0, mesh)
        reg = self.config.regularization
        l2 = jnp.asarray([reg.l2_weight(l) for l in lams], dtype)
        l1 = jnp.asarray([reg.l1_weight(l) for l in lams], dtype)
        solve = self._swept_solve_fn(mesh)
        import os
        if os.environ.get("PHOTON_TPU_PALLAS_GLM") == "1":
            # the fused kernel has no batching rule for the lane stack;
            # the swept program always traces with it hard-disabled
            from photon_tpu.ops import pallas_glm
            with pallas_glm.disabled():
                stacked = solve(x0, batch, l2, l1)
        else:
            stacked = solve(x0, batch, l2, l1)
        coefs = stacked.coef
        if not norm.is_identity:
            coefs = jax.vmap(lambda c: norm.transformed_space_to_model(
                c, self.intercept_index))(coefs)
        models = [GeneralizedLinearModel(Coefficients(coefs[i]), self.task)
                  for i in range(k)]
        return SweptSolve(models=models,
                          results=batched.split_lanes(stacked),
                          stacked=stacked, coefs=coefs)

    def run_streamed(
        self,
        loader,
        initial: Optional[Array] = None,
        dim: Optional[int] = None,
        dtype=None,
        regularization_weight: Optional[float] = None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every_chunks: int = 0,
        sdca_config=None,
    ) -> Tuple[GeneralizedLinearModel, SolverResult]:
        """Out-of-core solve: same contract as ``run`` but the data is a
        ``data.streaming.ChunkLoader`` instead of a resident batch — the
        objective is accumulated chunk-by-chunk with double-buffered
        host->device transfer, so the dataset never needs to fit in HBM.

        Only first-order solvers stream (LBFGS; OWLQN when the
        regularization has an L1 part; SDCA for one-storage-pass-per-epoch
        stochastic training — optim/sdca.py): second-order solvers would
        need a streamed pass per Hessian application. The mesh (if any)
        comes from the loader. ``checkpoint_path`` enables the
        chunk-cursor checkpoint for bitwise mid-epoch resume after
        preemption. ``sdca_config`` (an :class:`optim.sdca.SdcaConfig`)
        overrides the default OptimizerConfig mapping
        (max_iterations -> max_epochs, tolerance -> relative
        gap_tolerance) for the SDCA arm."""
        from photon_tpu.optim import streaming

        opt = self.config.optimizer
        if opt.optimizer_type == OptimizerType.SDCA:
            return self._run_sdca(
                loader, initial=initial, dim=dim, dtype=dtype,
                regularization_weight=regularization_weight,
                checkpoint_path=checkpoint_path,
                checkpoint_every_chunks=checkpoint_every_chunks,
                sdca_config=sdca_config)
        if opt.optimizer_type not in (OptimizerType.LBFGS,
                                      OptimizerType.OWLQN):
            raise ValueError(
                f"streamed training supports LBFGS/OWLQN/SDCA only, not "
                f"{opt.optimizer_type} (second-order solvers need a full "
                f"pass per Hessian application)")
        norm = self.objective.norm
        if dtype is None:
            dtype = loader.dtype
        d = int(dim if dim is not None else loader.source.dim)
        if initial is None:
            initial = jnp.zeros((d,), dtype)
        elif not norm.is_identity:
            initial = norm.model_to_transformed_space(
                jnp.asarray(initial), self.intercept_index)
        lam = (self.config.regularization_weight
               if regularization_weight is None else regularization_weight)
        problem = streaming.StreamedProblem(
            self.objective, loader,
            l2_weight=self.config.regularization.l2_weight(lam),
            dim=d, dtype=dtype)
        result = streaming.minimize_streamed(
            problem, jnp.asarray(initial, dtype),
            config=opt.solver_config(),
            l1_weight=self.config.regularization.l1_weight(lam),
            checkpoint_path=checkpoint_path,
            checkpoint_every_chunks=checkpoint_every_chunks)
        coef = result.coef
        if not norm.is_identity:
            coef = norm.transformed_space_to_model(coef, self.intercept_index)
        model = GeneralizedLinearModel(Coefficients(coef), self.task)
        return model, result

    def _run_sdca(
        self,
        loader,
        *,
        initial,
        dim,
        dtype,
        regularization_weight,
        checkpoint_path,
        checkpoint_every_chunks,
        sdca_config,
    ) -> Tuple[GeneralizedLinearModel, SolverResult]:
        """SDCA arm of ``run_streamed`` (optim/sdca.py): typed refusals at
        this boundary, then the chunk-local dual solve."""
        import numpy as np

        from photon_tpu.optim import sdca

        opt = self.config.optimizer
        lam = (self.config.regularization_weight
               if regularization_weight is None else regularization_weight)
        if self.config.regularization.l1_weight(lam) != 0.0:
            raise ValueError(
                "SDCA has no dual coordinate step for the L1 term "
                "(the conjugate of |.| is an indicator, not a smooth box); "
                "use OWLQN for L1/elastic-net")
        if initial is not None and bool(np.any(np.asarray(initial) != 0)):
            raise ValueError(
                "SDCA cannot warm-start from nonzero coefficients: the "
                "dual decomposition w = v / l2 requires v = sum alpha_i "
                "x_i, and an arbitrary w has no dual preimage; start from "
                "zeros or use the streamed L-BFGS path for warm-started "
                "sweeps")
        cfg = sdca_config if sdca_config is not None else sdca.SdcaConfig(
            max_epochs=opt.max_iterations, gap_tolerance=opt.tolerance)
        result = sdca.minimize_sdca(
            self.objective, loader,
            l2_weight=self.config.regularization.l2_weight(lam),
            config=cfg, dim=dim, dtype=dtype,
            checkpoint_path=checkpoint_path,
            checkpoint_every_chunks=checkpoint_every_chunks)
        # minimize_sdca refuses non-identity norms, so coef is model space
        model = GeneralizedLinearModel(Coefficients(result.coef), self.task)
        return model, result

    def run_sdca_resident(
        self,
        batch: DataBatch,
        dim: Optional[int] = None,
        dtype=None,
        regularization_weight: Optional[float] = None,
        chunk_rows: int = 8192,
        sdca_config=None,
    ) -> Tuple[GeneralizedLinearModel, SolverResult]:
        """SDCA over a RESIDENT batch: re-streams the device arrays
        through the chunk pipeline (EllSource/DenseSource wrap host
        views) so the one solver serves both the disk-native and the
        in-core case. The fixed-effect coordinate passthrough lands here
        when the configured optimizer is ``OptimizerType.SDCA``."""
        import numpy as np

        from photon_tpu.data import streaming as dstream
        from photon_tpu.ops.features import (
            ModelShardedSparse,
            SparseFeatures,
        )

        feats = batch.features
        if isinstance(feats, ModelShardedSparse):
            raise ValueError(
                "SDCA keeps the full primal carry v per sample shard, "
                "which contradicts model-axis sharding of theta; use the "
                "streamed L-BFGS path for model-sharded coordinates")
        np_leaf = lambda a: None if a is None else np.asarray(a)
        if isinstance(feats, SparseFeatures):
            if dim is None:
                raise ValueError(
                    "run_sdca_resident needs dim for sparse features "
                    "(ELL indices do not bound the model width)")
            src = dstream.EllSource(
                np_leaf(feats.indices), np_leaf(feats.values),
                np_leaf(batch.labels), dim=int(dim),
                offsets=np_leaf(batch.offsets),
                weights=np_leaf(batch.weights))
        else:
            src = dstream.DenseSource(
                np_leaf(feats), np_leaf(batch.labels),
                offsets=np_leaf(batch.offsets),
                weights=np_leaf(batch.weights))
        if dtype is None:
            dtype = batch.labels.dtype
        loader = dstream.ChunkLoader(
            src, dstream.StreamConfig(chunk_rows=chunk_rows,
                                      dtype=np.dtype(dtype)))
        return self._run_sdca(
            loader, initial=None, dim=int(src.dim if dim is None else dim),
            dtype=dtype, regularization_weight=regularization_weight,
            checkpoint_path=None, checkpoint_every_chunks=0,
            sdca_config=sdca_config)

    # -- variances (reference: DistributedOptimizationProblem:82-100) -------

    @functools.cached_property
    def _variance_fns(self):
        obj = self._var_objective  # original-space curvature (see __init__)

        def build():
            @jax.jit
            def simple(coef: Array, batch: DataBatch, l2: Array) -> Array:
                d = obj.hessian_diagonal(coef, batch, Hyper(l2_weight=l2))
                return 1.0 / jnp.maximum(d, jnp.finfo(d.dtype).tiny)

            @jax.jit
            def full(coef: Array, batch: DataBatch, l2: Array) -> Array:
                h = obj.hessian_matrix(coef, batch, Hyper(l2_weight=l2))
                # diag(H^-1) via Cholesky (reference: util/Linalg Cholesky solves)
                eye = jnp.eye(h.shape[0], dtype=h.dtype)
                chol = jax.scipy.linalg.cho_factor(h)
                hinv = jax.scipy.linalg.cho_solve(chol, eye)
                return jnp.diag(hinv)

            return simple, full

        key = ("glm_variance", self.task, norm_cache_key(self._var_objective.norm))
        return jitcache.get_or_build(key, build)

    def compute_variances(
        self,
        batch: DataBatch,
        coef: Array,
        variance_type: VarianceComputationType,
        regularization_weight: Optional[float] = None,
    ) -> Optional[Array]:
        if variance_type == VarianceComputationType.NONE:
            return None
        if not self.objective.loss.has_hessian:
            return None  # first-order-only losses (smoothed hinge)
        lam = (self.config.regularization_weight
               if regularization_weight is None else regularization_weight)
        l2 = jnp.asarray(self.config.regularization.l2_weight(lam), coef.dtype)
        simple, full = self._variance_fns
        if variance_type == VarianceComputationType.SIMPLE:
            return simple(coef, batch, l2)
        return full(coef, batch, l2)
