"""Chunk-local stochastic dual coordinate ascent over the streaming store.

Every other solver in this tree is a batch method: a fit on the
disk-native chunk store pays one full storage pass per line-search
evaluation, tens of passes per solve. SDCA (Snap ML, TPA-SCD — see
PAPERS.md) flips the loop: ONE storage pass per outer epoch, with each
device-resident chunk running a compiled inner program of randomized
dual-coordinate updates while the next chunk streams in behind it on the
double-buffered :class:`~photon_tpu.data.streaming.ChunkLoader`.

Duality setup (SUM + per-example-weight convention, matching
``GLMObjective``):

    P(w) = sum_i c_i phi(x_i . w + o_i) + (l2/2) |w|^2

with dual variables ``alpha_i`` (one per example, stored chunk-local in
a ``[C, R]`` device-resident table), the shared primal carry
``v = sum_i alpha_i x_i`` (so ``w = v / l2``), and

    D(alpha) = -sum_i [ c_i phi*(-alpha_i / c_i) + alpha_i o_i ]
               - |v|^2 / (2 l2)

Weak duality gives the typed stopping certificate for free: with
``z_i = x_i . w + o_i``,

    gap_i = c_i phi(z_i) + c_i phi*(-alpha_i / c_i) + alpha_i z_i >= 0

(Fenchel-Young, pointwise), and ``sum_i gap_i = P(w) - D(alpha)`` bounds
the primal suboptimality directly. The per-chunk program accumulates
these partials AT CHUNK ENTRY — the same numbers its update loop needs
anyway — so the gap costs no extra data pass. Between chunk visits a
row's ``alpha_i`` is frozen while ``v`` moves, so the per-epoch gap
estimate is one-visit lagged (Snap ML reports the same way); it is
nonnegative always and exact at convergence.

Cross-chunk consistency follows the papers' bounded-staleness recipe:
each chunk commits against the primal snapshot it entered with (on a
mesh, each sample shard additionally carries its own local ``v`` through
the whole epoch — the chunk program contains ZERO collectives, and the
epoch-end merge is exactly one staged ICI->DCN psum). The analytic dual
increase every update predicts,

    dD = cps(alpha) - cps(alpha + d) - d (o + m) - d^2 q / 2,
         cps(a) = c phi*(-a / c),  q = |x_i|^2 / l2,

is accumulated alongside, and the realized increase (the dual estimate
is exactly one epoch lagged, so realized lands one epoch later) is
checked against it — a shortfall is the staleness signature, answered by
halving the CoCoA-style step damping (applied to BOTH ``alpha`` and
``v`` inside the update, preserving ``v = sum alpha_i x_i`` exactly)
and a typed ``sdca_staleness_fallback`` record. Never an exception —
mirroring game/parallel_cd.py's predicted-vs-realized degradation.

Determinism is total: coordinate permutations are counter-derived
(``fold_in(key, epoch, chunk, inner[, shard])``), the chunk visit order
is :func:`~photon_tpu.data.streaming.epoch_chunk_order`, and the host
loop is straight-line numpy — two runs are bitwise identical, and the
crc-framed kill/resume checkpoint (dual table + primal carry + chunk
cursor) replays to the same bits.

Losses: logistic, squared, smoothed hinge have closed-form or safe
guarded-Newton conjugate steps; Poisson's dual step has neither (the
conjugate ``u log u - u`` step lands outside any box the weights
bound) and is refused typed (:class:`SdcaUnsupportedLossError`).
"""

from __future__ import annotations

import dataclasses
import io
import json
import math
import os
import struct
import threading
import zlib
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_tpu.data.streaming import epoch_chunk_order
from photon_tpu.function.objective import GLMObjective
from photon_tpu.ops import features as F
from photon_tpu.optim.base import (
    ConvergenceReason,
    FailureMode,
    SolverResult,
    jit_donating,
)
from photon_tpu.resilience import chaos
from photon_tpu.resilience import failures
from photon_tpu.resilience import io as rio

Array = jax.Array


# =========================================================================
# Typed refusal surface
# =========================================================================

class SdcaUnsupportedLossError(ValueError):
    """The task's loss has no implemented conjugate (dual) step."""


class SdcaWeightError(ValueError):
    """Example weights are non-finite or negative — the dual step divides
    by ``c_i`` and boxes ``alpha_i`` by it, so a bad weight corrupts the
    solve silently. Validated on the host BEFORE anything compiles."""


def validate_example_weights(source, block_rows: int = 1 << 16) -> None:
    """Host block-scan of a chunk source's example weights. Sources
    without a weights column (implicit weight 1) pass trivially."""
    w = getattr(source, "weights", None)
    if w is None:
        return
    n = int(w.shape[0])
    for s in range(0, n, block_rows):
        blk = np.asarray(w[s:s + block_rows])
        if not bool(np.all(np.isfinite(blk))):
            raise SdcaWeightError(
                f"non-finite example weight in rows [{s}, "
                f"{min(n, s + block_rows)}) — SDCA's dual step divides by "
                f"the weight; clean the data or drop the rows")
        if bool(np.any(blk < 0)):
            raise SdcaWeightError(
                f"negative example weight in rows [{s}, "
                f"{min(n, s + block_rows)}) — a negative weight makes the "
                f"per-example dual problem unbounded")


# =========================================================================
# Config
# =========================================================================

@dataclasses.dataclass(frozen=True)
class SdcaConfig:
    """Knobs for :func:`minimize_sdca`.

    ``gap_tolerance`` is RELATIVE to the first epoch's gap estimate
    (with ``alpha = 0`` every conjugate term vanishes, so the initial
    gap is the initial primal data loss — the natural scale).
    ``inner_epochs`` repeats the randomized coordinate sweep within each
    resident chunk before the stream moves on (TPA-SCD's
    epochs-within-chunk; more local work per byte streamed).
    ``staleness_guard``: fallback triggers when the realized dual
    increase of an epoch falls below ``guard x predicted`` — on a single
    device realized == predicted to FP, so the default never fires
    there; meshed shard staleness is what it watches.
    """

    max_epochs: int = 20
    gap_tolerance: float = 1e-3
    inner_epochs: int = 1
    seed: int = 0
    newton_steps: int = 8
    staleness_guard: float = 0.5
    min_damping: float = 1.0 / 16.0


# =========================================================================
# Per-loss conjugate steps
# =========================================================================
#
# Each loss contributes two shape-polymorphic pure functions:
#   step(alpha, z, q, c, c_safe, y) -> d       the UNgated, UNdamped
#       coordinate-optimal dual increment solving
#       phi*'(-(alpha+d)/c) = z + d q (box-projected where the conjugate
#       has a box)
#   cps(alpha, c, c_safe, y) -> c phi*(-alpha/c)
# ``c_safe`` is ``where(c > 0, c, 1)`` — pad rows (weight 0) divide by 1
# and are gated to a zero update/partial by the caller.

def _dual_functions(loss_name: str, newton_steps: int
                    ) -> Tuple[Callable, Callable]:
    if loss_name == "squared":
        # phi(z) = (z-y)^2 / 2;  phi*(u) = u y + u^2 / 2
        def step(alpha, z, q, c, c_safe, y):
            return (c * (y - z) - alpha) / (1.0 + c * q)

        def cps(alpha, c, c_safe, y):
            return -alpha * y + alpha * alpha / (2.0 * c_safe)

        return step, cps

    if loss_name == "logistic":
        # phi(z) = log(1+e^z) - y z, y in {0,1};
        # phi*(u) = t log t + (1-t) log(1-t) with t = u + y in [0,1].
        # Coordinate optimum: t = y - (alpha+d)/c solves the monotone
        # g(t) = logit(t) - z - q (c (y - t) - alpha) = 0; g' =
        # 1/(t(1-t)) + q c > 0, so clipped Newton from t0 = sigmoid(z)
        # converges fast (8 steps lands at FP resolution in practice).
        def step(alpha, z, q, c, c_safe, y):
            lo = jnp.asarray(np.finfo(np.dtype(jnp.result_type(z))).eps,
                             jnp.result_type(z))
            t0 = jnp.clip(jax.nn.sigmoid(z), lo, 1.0 - lo)

            def newton(_, t):
                g = (jnp.log(t) - jnp.log1p(-t) - z
                     - q * (c * (y - t) - alpha))
                gp = 1.0 / (t * (1.0 - t)) + q * c
                return jnp.clip(t - g / gp, lo, 1.0 - lo)

            t = lax.fori_loop(0, newton_steps, newton, t0)
            return c * (y - t) - alpha

        def cps(alpha, c, c_safe, y):
            t = jnp.clip(y - alpha / c_safe, 0.0, 1.0)

            def xlogx(x):
                tiny = jnp.asarray(
                    np.finfo(np.dtype(jnp.result_type(x))).tiny,
                    jnp.result_type(x))
                return jnp.where(x > 0, x * jnp.log(jnp.maximum(x, tiny)),
                                 jnp.zeros_like(x))

            return c * (xlogx(t) + xlogx(1.0 - t))

        return step, cps

    if loss_name == "smoothed_hinge":
        # phi(z) = psi(s z), s = 2y-1; psi*(r) = r + r^2/2 on [-1, 0].
        # With a = s alpha / c in [0, 1]: unconstrained optimum
        # a* = a + (1 - s z - a)/(1 + q c), box-projected; d = c s (a*-a).
        def step(alpha, z, q, c, c_safe, y):
            s = 2.0 * y - 1.0
            a = s * alpha / c_safe
            a_new = jnp.clip(a + (1.0 - s * z - a) / (1.0 + q * c),
                             0.0, 1.0)
            return c * s * (a_new - a)

        def cps(alpha, c, c_safe, y):
            s = 2.0 * y - 1.0
            a = jnp.clip(s * alpha / c_safe, 0.0, 1.0)
            return c * (0.5 * a * a - a)

        return step, cps

    raise SdcaUnsupportedLossError(
        f"SDCA has no conjugate step for loss {loss_name!r} (supported: "
        f"logistic, squared, smoothed_hinge; Poisson's dual step has no "
        f"closed form or safely boxed Newton) — use the streamed "
        f"L-BFGS/OWL-QN path for this task")


def validate_loss(loss_name: str) -> None:
    """Config-time typed check that SDCA has a conjugate step for this
    loss (raises :class:`SdcaUnsupportedLossError` otherwise) — lets a
    coordinate refuse a Poisson+SDCA config at construction instead of
    mid-fit."""
    _dual_functions(loss_name, 1)


# =========================================================================
# Feature access (dense / padded-ELL; pads are (0, 0.0) => contribute 0)
# =========================================================================

def _check_features(feats) -> None:
    if isinstance(feats, F.ModelShardedSparse):
        raise ValueError(
            "SDCA keeps the full primal carry v per sample shard, which "
            "contradicts model-axis sharding of theta; use the streamed "
            "L-BFGS path for model-sharded coordinates")


def _margins(feats, v: Array) -> Array:
    if isinstance(feats, F.SparseFeatures):
        return jnp.sum(feats.values * v[feats.indices], axis=1)
    return feats @ v


def _row_sqnorms(feats) -> Array:
    if isinstance(feats, F.SparseFeatures):
        return jnp.sum(feats.values * feats.values, axis=1)
    return jnp.sum(feats * feats, axis=1)


def _row_dot(feats, i: Array, v: Array) -> Array:
    if isinstance(feats, F.SparseFeatures):
        return jnp.sum(feats.values[i] * v[feats.indices[i]])
    return jnp.dot(feats[i], v)


def _row_axpy(v: Array, feats, i: Array, scale: Array) -> Array:
    if isinstance(feats, F.SparseFeatures):
        return v.at[feats.indices[i]].add(scale * feats.values[i])
    return v + scale * feats[i]


# =========================================================================
# Module stats (RunReport `sdca` section — mirrors optim/batched's sweep)
# =========================================================================

_STATS_LOCK = threading.Lock()
_STATS = {"runs": 0, "epochs": 0, "fallbacks": 0, "converged": 0,
          "last": None}


def reset_sdca_stats() -> None:
    with _STATS_LOCK:
        _STATS.update(runs=0, epochs=0, fallbacks=0, converged=0, last=None)


def report_section() -> Optional[dict]:
    with _STATS_LOCK:
        if not _STATS["runs"]:
            return None
        return {"runs": _STATS["runs"], "epochs": _STATS["epochs"],
                "fallbacks": _STATS["fallbacks"],
                "converged": _STATS["converged"],
                "last": None if _STATS["last"] is None
                else dict(_STATS["last"])}


def _record_run(last: dict, converged: bool) -> None:
    # fallbacks are counted per-event in _record_fallback (survives a
    # mid-run kill); counting them again here would double the total
    with _STATS_LOCK:
        _STATS["runs"] += 1
        _STATS["converged"] += int(converged)
        _STATS["last"] = last


# =========================================================================
# Compiled programs (one per (mesh, batch structure) — shared across all
# chunks, epochs and damping values: everything varying is traced)
# =========================================================================

class _SdcaPrograms:
    """Compiled chunk/finalize programs + state plumbing for one solve.

    State dict (device-resident):
      unmeshed: {"alpha": [C, R], "v": [d]}
      meshed:   {"alpha": [C, R] sharded on R, "vloc": [p, d] shard-local,
                 "vg": [d] replicated epoch-start primal carry}
    ``acc`` is the per-epoch partials accumulator
    [primal_entry, gap_entry, dual_ps_entry, predicted_increase]
    ([4] unmeshed, [p, 4] shard-local meshed).
    """

    def __init__(self, objective: GLMObjective, loader, cfg: SdcaConfig,
                 l2_weight: float, dim: int, dtype, c_max: int):
        self.objective = objective
        self.loader = loader
        self.mesh = loader.mesh
        self.cfg = cfg
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.c_max = int(c_max)
        self.chunk_rows = int(loader.chunk_rows)
        self._l2 = jnp.asarray(l2_weight, self.dtype)
        self._key = jax.random.PRNGKey(cfg.seed)
        self._step, self._cps = _dual_functions(objective.loss.name,
                                                cfg.newton_steps)
        if self.mesh is None:
            self._build_unmeshed()
        else:
            self._build_meshed()

    # -- shared chunk body (runs per device; shard-local on a mesh) ---------

    def _chunk_body(self, alpha_all, v, acc, batch, rows, epoch, chunk_id,
                    damping, row_base, sigma=1.0):
        """alpha_all [C, r], v [d], acc [4] -> updated triple. ``r`` is
        the (possibly shard-local) row count; ``row_base`` offsets local
        row positions into the global chunk so the pad mask and the
        permutation key stay correct per shard.

        ``sigma`` is the CoCoA+ safety factor (= number of sample shards
        on a mesh, 1.0 unmeshed): K shards taking full local steps and
        merging additively overshoot by up to K, so each local step
        solves the sigma-conservative subproblem instead — effective
        curvature ``sigma * q`` and a sigma-boosted carry ``u = v_global
        + sigma * dv_local`` (the caller converts vloc <-> u at the
        chunk boundary). With gamma=1, sigma=K the additive epoch-end
        merge is provably safe (Ma et al., CoCoA+), and the accumulated
        predicted gain is a certified LOWER bound on the realized global
        dual increase — which is exactly what the staleness guard
        watches. At sigma=1 every formula reduces to plain sequential
        SDCA."""
        cfg, loss = self.cfg, self.objective.loss
        step_fn, cps_fn = self._step, self._cps
        l2 = self._l2
        feats, y = batch.features, batch.labels
        r = y.shape[0]
        o = (batch.offsets if batch.offsets is not None
             else jnp.zeros_like(y))
        w = batch.weights if batch.weights is not None else jnp.ones_like(y)
        # weight-0 pad rows (and any stale staging tail): gate everything
        mask = (row_base + jnp.arange(r, dtype=jnp.int32)) < rows
        c = jnp.where(mask, w, jnp.zeros_like(w))
        c_safe = jnp.where(c > 0, c, jnp.ones_like(c))
        live = c > 0
        q = jnp.asarray(sigma, self.dtype) * _row_sqnorms(feats) / l2

        zero_i = jnp.zeros((), chunk_id.dtype)  # match index width (x64)
        alpha = lax.dynamic_slice(alpha_all, (chunk_id, zero_i), (1, r))[0]

        # entry partials: the SAME numbers the update loop consumes,
        # doubling as the (one-visit-lagged) gap/dual/primal estimators
        z_entry = _margins(feats, v) / l2 + o
        phi = loss.loss_and_dz(z_entry, y)[0]
        cps_entry = cps_fn(alpha, c, c_safe, y)
        zero = jnp.zeros_like(y)
        primal_entry = jnp.sum(jnp.where(live, c * phi, zero))
        gap_entry = jnp.sum(jnp.where(
            live, c * phi + cps_entry + alpha * z_entry, zero))
        dual_ps_entry = jnp.sum(jnp.where(live, cps_entry + alpha * o,
                                          zero))

        key_c = jax.random.fold_in(
            jax.random.fold_in(self._key, epoch), chunk_id)
        if self.mesh is not None:
            key_c = jax.random.fold_in(key_c, row_base)

        def inner(inner_idx, carry):
            v, alpha, pred = carry
            perm = jax.random.permutation(
                jax.random.fold_in(key_c, inner_idx), r)

            def body(t, st):
                v, alpha, pred = st
                i = perm[t]
                ci, csi, yi = c[i], c_safe[i], y[i]
                oi, qi, ai = o[i], q[i], alpha[i]
                m_loc = _row_dot(feats, i, v) / l2
                zi = m_loc + oi
                d_raw = step_fn(ai, zi, qi, ci, csi, yi)
                d = jnp.where(ci > 0, damping * d_raw,
                              jnp.zeros_like(d_raw))
                inc = jnp.where(
                    ci > 0,
                    cps_fn(ai, ci, csi, yi) - cps_fn(ai + d, ci, csi, yi)
                    - d * zi - 0.5 * d * d * qi,
                    jnp.zeros_like(d))
                # u-carry: alpha_i += d moves the boosted vector by
                # sigma * d * x_i (= d * x_i when unmeshed)
                v = _row_axpy(v, feats, i,
                              jnp.asarray(sigma, d.dtype) * d)
                alpha = alpha.at[i].set(ai + d)
                return v, alpha, pred + inc

            v, alpha, pred = lax.fori_loop(0, r, body, (v, alpha, pred))
            return v, alpha, pred

        v, alpha, pred = lax.fori_loop(
            0, cfg.inner_epochs, inner,
            (v, alpha, jnp.zeros((), v.dtype)))

        alpha_all = lax.dynamic_update_slice(alpha_all, alpha[None],
                                             (chunk_id, zero_i))
        acc = acc + jnp.stack([primal_entry, gap_entry, dual_ps_entry,
                               pred])
        return alpha_all, v, acc

    # -- unmeshed -----------------------------------------------------------

    def _build_unmeshed(self):
        def chunk(alpha_all, v, acc, batch, rows, epoch, chunk_id,
                  damping):
            return self._chunk_body(alpha_all, v, acc, batch, rows, epoch,
                                    chunk_id, damping,
                                    jnp.zeros((), jnp.int32))

        self._chunk = jit_donating(chunk, donate_argnums=(0, 1, 2))

        def finalize(v, v_start, acc, l2):
            primal = acc[0] + jnp.dot(v, v) / (2.0 * l2)
            dual = -acc[2] - jnp.dot(v_start, v_start) / (2.0 * l2)
            return jnp.stack([primal, dual, acc[1], acc[3]])

        self._finalize = jax.jit(finalize)

    # -- meshed: shard-local v, one staged psum per epoch -------------------

    def _build_meshed(self):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from photon_tpu.optim.hier import (
            _mesh_factors,
            _sample_axes,
            _staged_all_psum,
        )
        from photon_tpu.parallel import mesh as M

        mesh = self.mesh
        sample_axes = _sample_axes(mesh)
        self._p_shards, self._replicas = _mesh_factors(mesh, sample_axes)
        spec_axis = (sample_axes if len(sample_axes) > 1
                     else sample_axes[0])
        if self.chunk_rows % self._p_shards:
            raise ValueError(
                f"chunk_rows={self.chunk_rows} not divisible by "
                f"{self._p_shards} sample shards")
        r_loc = self.chunk_rows // self._p_shards
        self._shardings = {
            "alpha": NamedSharding(mesh, P(None, spec_axis)),
            "vloc": NamedSharding(mesh, P(spec_axis, None)),
            "acc": NamedSharding(mesh, P(spec_axis, None)),
        }
        alpha_spec, vloc_spec, acc_spec = (P(None, spec_axis),
                                           P(spec_axis, None),
                                           P(spec_axis, None))
        replicas = self._replicas

        def shard_pos():
            i = jnp.zeros((), jnp.int32)
            for a in sample_axes:
                i = i * M.axis_size(mesh, a) + lax.axis_index(a)
            return i

        sigma = float(self._p_shards)

        def chunk_body(alpha_all, vloc, vg, acc, batch, rows, epoch,
                       chunk_id, damping):
            row_base = shard_pos() * r_loc
            # sigma-boosted local carry (see _chunk_body): margins and
            # steps see this shard's own updates amplified K-fold, which
            # is what makes the additive epoch-end merge safe
            u = vg + sigma * (vloc[0] - vg)
            a, u2, ac = self._chunk_body(alpha_all, u, acc[0], batch,
                                         rows, epoch, chunk_id, damping,
                                         row_base, sigma=sigma)
            vloc_out = vg + (u2 - vg) / sigma
            return a, vloc_out[None], ac[None]

        def chunk(alpha_all, vloc, vg, acc, batch, rows, epoch, chunk_id,
                  damping):
            specs = jax.tree.map(
                lambda x: P(spec_axis, *([None] * (x.ndim - 1))), batch)
            return M.shard_map(
                chunk_body, mesh=mesh,
                in_specs=(alpha_spec, vloc_spec, P(), acc_spec, specs,
                          P(), P(), P(), P()),
                out_specs=(alpha_spec, vloc_spec, acc_spec),
                check_rep=False,
            )(alpha_all, vloc, vg, acc, batch, rows, epoch, chunk_id,
              damping)

        self._chunk_meshed = jit_donating(chunk, donate_argnums=(0, 1, 3))

        def merge_body(vloc, vg, acc):
            # the epoch's single reduction: [dv | partials] in one staged
            # ICI-then-DCN psum. Shards own DISJOINT rows, so the add
            # merge preserves v = sum alpha_i x_i exactly.
            packed = _staged_all_psum(
                jnp.concatenate([vloc[0] - vg, acc[0]]), mesh) / replicas
            return vg + packed[:-4], packed[-4:]

        def merge(vloc, vg, acc):
            return M.shard_map(
                merge_body, mesh=mesh,
                in_specs=(vloc_spec, P(), acc_spec),
                out_specs=(P(), P()),
                check_rep=False,
            )(vloc, vg, acc)

        self._merge = jax.jit(merge)

        def finalize(vg_new, v_start, acc_tot, l2):
            primal = acc_tot[0] + jnp.dot(vg_new, vg_new) / (2.0 * l2)
            dual = -acc_tot[2] - jnp.dot(v_start, v_start) / (2.0 * l2)
            return jnp.stack([primal, dual, acc_tot[1], acc_tot[3]])

        self._finalize = jax.jit(finalize)

    # -- state plumbing -----------------------------------------------------

    def init_state(self) -> dict:
        c, r, d, dt = self.c_max, self.chunk_rows, self.dim, self.dtype
        if self.mesh is None:
            return {"alpha": jnp.zeros((c, r), dt), "v": jnp.zeros((d,), dt)}
        from photon_tpu.parallel import mesh as M
        p = self._p_shards
        return {
            "alpha": jax.device_put(np.zeros((c, r), dt),
                                    self._shardings["alpha"]),
            "vloc": jax.device_put(np.zeros((p, d), dt),
                                   self._shardings["vloc"]),
            "vg": M.replicate(jnp.zeros((d,), dt), self.mesh),
        }

    def init_acc(self):
        if self.mesh is None:
            return jnp.zeros((4,), self.dtype)
        return jax.device_put(np.zeros((self._p_shards, 4), self.dtype),
                              self._shardings["acc"])

    def epoch_carry(self, state: dict) -> Array:
        """The epoch-start primal carry the dual estimate is anchored to
        (functional arrays: holding the reference keeps it valid)."""
        return state["v"] if self.mesh is None else state["vg"]

    def run_chunk(self, state: dict, acc, batch, rows: int, epoch: int,
                  chunk_id: int, damping: float):
        args = (acc, batch, jnp.int32(rows), jnp.int32(epoch),
                jnp.int32(chunk_id), jnp.asarray(damping, self.dtype))
        if self.mesh is None:
            a, v, acc = self._chunk(state["alpha"], state["v"], *args)
            return {"alpha": a, "v": v}, acc
        a, vloc, acc = self._chunk_meshed(state["alpha"], state["vloc"],
                                          state["vg"], *args)
        return {"alpha": a, "vloc": vloc, "vg": state["vg"]}, acc

    def finish_epoch(self, state: dict, acc, v_start):
        """Epoch-end merge + scalars. Returns (state', scalars[4]) where
        scalars = [primal, dual, gap, predicted]."""
        if self.mesh is None:
            return state, self._finalize(state["v"], v_start, acc,
                                         self._l2)
        vg_new, acc_tot = self._merge(state["vloc"], state["vg"], acc)
        scal = self._finalize(vg_new, v_start, acc_tot, self._l2)
        vloc = jax.device_put(
            jnp.broadcast_to(vg_new, (self._p_shards, self.dim)),
            self._shardings["vloc"])
        return {"alpha": state["alpha"], "vloc": vloc, "vg": vg_new}, scal

    def coef_host(self, state: dict) -> np.ndarray:
        v = state["v"] if self.mesh is None else state["vg"]
        return np.asarray(v) / float(np.asarray(self._l2))

    def state_to_host(self, state: dict, acc, v_start) -> dict:
        out = {f"st_{k}": np.asarray(a) for k, a in state.items()}
        out["acc"] = np.asarray(acc)
        out["v_start"] = np.asarray(v_start)
        return out

    def state_from_host(self, arrays: dict):
        if self.mesh is None:
            state = {"alpha": jnp.asarray(arrays["st_alpha"], self.dtype),
                     "v": jnp.asarray(arrays["st_v"], self.dtype)}
            acc = jnp.asarray(arrays["acc"], self.dtype)
            v_start = jnp.asarray(arrays["v_start"], self.dtype)
            return state, acc, v_start
        from photon_tpu.parallel import mesh as M
        state = {
            "alpha": jax.device_put(np.asarray(arrays["st_alpha"]),
                                    self._shardings["alpha"]),
            "vloc": jax.device_put(np.asarray(arrays["st_vloc"]),
                                   self._shardings["vloc"]),
            "vg": M.replicate(jnp.asarray(arrays["st_vg"], self.dtype),
                              self.mesh),
        }
        acc = jax.device_put(np.asarray(arrays["acc"]),
                             self._shardings["acc"])
        v_start = M.replicate(jnp.asarray(arrays["v_start"], self.dtype),
                              self.mesh)
        return state, acc, v_start


# =========================================================================
# Checkpoint (crc-framed npz, atomic publish — own magic, same framing
# discipline as optim/streaming's PTSTRMC1)
# =========================================================================

_MAGIC = b"PTSDCAC1"
_SCHEMA = 1


def _encode_checkpoint(meta: dict, arrays: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    body = buf.getvalue()
    meta_b = json.dumps(meta, sort_keys=True).encode()
    return (_MAGIC + struct.pack("<II", zlib.crc32(body), len(meta_b))
            + meta_b + body)


def _decode_checkpoint(blob: bytes) -> Tuple[dict, dict]:
    if blob[:8] != _MAGIC:
        raise ValueError("not an SDCA checkpoint (bad magic)")
    crc, mlen = struct.unpack("<II", blob[8:16])
    meta = json.loads(blob[16:16 + mlen].decode())
    body = blob[16 + mlen:]
    if zlib.crc32(body) != crc:
        raise ValueError("SDCA checkpoint payload crc mismatch")
    if meta.get("schema") != _SCHEMA:
        raise ValueError(
            f"SDCA checkpoint schema {meta.get('schema')} != {_SCHEMA}")
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files}
    return meta, arrays


def load_sdca_checkpoint(path: str) -> Tuple[dict, dict]:
    """(meta, arrays) of an SDCA cursor checkpoint; raises ValueError on
    torn/corrupt files (crc framed)."""
    return _decode_checkpoint(rio.read_bytes(path, op="sdca.checkpoint"))


# =========================================================================
# Host epoch loop
# =========================================================================

def _record_fallback(epoch: int, predicted: float, realized: float,
                     damping: float) -> None:
    with _STATS_LOCK:
        _STATS["fallbacks"] += 1
    try:
        from photon_tpu.obs.metrics import registry
        registry.counter("sdca.fallbacks").inc()
    except Exception:   # hygiene-ok — telemetry is best-effort
        pass
    failures.record_failure("sdca_staleness_fallback", epoch=epoch,
                            predicted=predicted, realized=realized,
                            damping=damping)


def minimize_sdca(
    objective: GLMObjective,
    loader,
    *,
    l2_weight: float,
    config: SdcaConfig = SdcaConfig(),
    dim: Optional[int] = None,
    dtype=None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every_chunks: int = 0,
    on_epoch: Optional[Callable[[int, dict], None]] = None,
) -> SolverResult:
    """Fit ``objective`` over a ChunkLoader's stream by chunk-local SDCA.

    One storage pass per outer epoch; duality-gap-typed stopping
    (``ConvergenceReason.DUALITY_GAP_CONVERGED``); bitwise run-to-run
    reproducible; crc-framed kill/resume via ``checkpoint_path``.
    ``on_epoch(epoch, info)`` fires after each epoch with the gap /
    primal / dual estimates and a host copy of the current coefficients
    (bench instrumentation; adds one host pull per epoch when set).

    Result mapping: ``coef = v / l2`` (the dual's primal iterate),
    ``gradient`` is all-zeros (SDCA never forms a primal gradient — the
    duality gap is the optimality certificate), ``iterations`` and
    ``num_fun_evals`` both count storage passes.
    """
    from photon_tpu.obs import spans as _obs_spans
    from photon_tpu.obs.metrics import registry

    if config.max_epochs < 1:
        raise ValueError("SdcaConfig.max_epochs must be >= 1")
    if not objective.norm.is_identity:
        raise ValueError(
            "SDCA runs in raw feature space (the dual step needs literal "
            "rows x_i); fold normalization into the store before "
            "streaming, or use the streamed L-BFGS path")
    if not l2_weight > 0.0:
        raise ValueError(
            "SDCA requires l2_weight > 0: the dual decomposition "
            "w = v / l2 does not exist for the unregularized problem")
    # typed refusal BEFORE any compile: unsupported conjugate, bad weights
    _dual_functions(objective.loss.name, config.newton_steps)
    validate_example_weights(loader.source)

    d = int(dim if dim is not None else loader.source.dim)
    dt = np.dtype(dtype if dtype is not None else loader.dtype)
    r = int(loader.chunk_rows)
    # unfiltered ceiling: with drop_invalid the true chunk count is only
    # known after pass 0, but it can never exceed this
    c_max = max(1, -(-int(loader.source.num_rows) // r))
    progs = _SdcaPrograms(objective, loader, config, float(l2_weight),
                          d, dt, c_max)

    state = progs.init_state()
    acc = progs.init_acc()
    v_start = progs.epoch_carry(state)
    damping = 1.0
    gap0: Optional[float] = None
    prev_dual: Optional[float] = None
    prev_pred: Optional[float] = None
    gap_history: list = []
    start_epoch, start_pos = 0, 0
    resumed_mid_epoch = False
    run_fallbacks = 0

    if checkpoint_path and os.path.exists(checkpoint_path):
        meta, arrays = load_sdca_checkpoint(checkpoint_path)
        if int(meta["dim"]) != d or int(meta["chunk_rows"]) != r:
            raise ValueError(
                f"SDCA checkpoint geometry (dim={meta['dim']}, "
                f"chunk_rows={meta['chunk_rows']}) does not match this "
                f"solve (dim={d}, chunk_rows={r})")
        state, acc, v_start = progs.state_from_host(arrays)
        damping = float(meta["damping"])
        gap0 = meta["gap0"]
        prev_dual = meta["prev_dual"]
        prev_pred = meta["prev_pred"]
        gap_history = list(arrays["gap_history"]) \
            if "gap_history" in arrays else []
        start_epoch = int(meta["epoch"])
        start_pos = int(meta["next_pos"])
        resumed_mid_epoch = True
        geom = None
        if meta.get("num_chunks") is not None:
            geom = {"num_chunks": int(meta["num_chunks"])}
            if "block_cum" in arrays:
                geom["block_cum"] = arrays["block_cum"]
        loader.restore_geometry(geom)

    def save_checkpoint(epoch: int, next_pos: int, state, acc,
                        v_start) -> None:
        arrays = progs.state_to_host(state, acc, v_start)
        arrays["gap_history"] = np.asarray(gap_history, np.float64)
        geom = loader.geometry()
        if geom is not None and geom.get("block_cum") is not None:
            arrays["block_cum"] = geom["block_cum"]
        meta = {
            "schema": _SCHEMA, "dim": d, "chunk_rows": r,
            "epoch": int(epoch), "next_pos": int(next_pos),
            "damping": float(damping), "gap0": gap0,
            "prev_dual": prev_dual, "prev_pred": prev_pred,
            "num_chunks": None if geom is None else geom["num_chunks"],
        }
        rio.atomic_write_bytes(checkpoint_path,
                               _encode_checkpoint(meta, arrays),
                               op="sdca.checkpoint")
        try:
            registry.counter("sdca.checkpoints").inc()
        except Exception:   # hygiene-ok — telemetry is best-effort
            pass

    ckpt_on = bool(checkpoint_path) and (checkpoint_every_chunks > 0
                                         or chaos.is_active())
    tiny = float(np.finfo(np.float64).tiny)
    reason = int(ConvergenceReason.MAX_ITERATIONS)
    failure = int(FailureMode.NONE)
    primal = float("nan")
    gap = float("nan")
    epochs_done = 0

    for e in range(start_epoch, config.max_epochs):
        if e == start_epoch and resumed_mid_epoch:
            pos0 = start_pos    # acc / v_start restored mid-epoch
        else:
            pos0 = 0
            acc = progs.init_acc()
            v_start = progs.epoch_carry(state)
        order = None
        if e > 0:
            n_chunks = loader.num_chunks
            if n_chunks is None:
                raise RuntimeError(
                    "chunk count unknown after a completed pass 0 — "
                    "loader geometry was not learned")
            order = epoch_chunk_order(config.seed, e, n_chunks)
        with _obs_spans.span("sdca/epoch", epoch=e):
            for chunk in loader.stream(start_chunk=pos0, order=order):
                cid = (chunk.chunk_id if chunk.chunk_id >= 0
                       else chunk.index)
                state, acc = progs.run_chunk(state, acc, chunk.batch,
                                             chunk.rows, e, cid, damping)
                # consumption token: acc's readiness implies the chunk's
                # reads are done, freeing its staging buffer
                loader.release(chunk, acc)
                if ckpt_on:
                    kill = chaos.should_kill_stream(e, chunk.index)
                    cadence = (checkpoint_every_chunks > 0
                               and (chunk.index + 1)
                               % checkpoint_every_chunks == 0)
                    if kill or cadence:
                        save_checkpoint(e, chunk.index + 1, state, acc,
                                        v_start)
                        if kill:
                            raise chaos.SimulatedKill(
                                f"chaos: killed SDCA at epoch {e}, chunk "
                                f"{chunk.index} (checkpoint written)")
            state, scal_dev = progs.finish_epoch(state, acc, v_start)
            # the ONE deliberate host crossing per epoch
            scal = np.asarray(scal_dev)
        primal, dual, gap, pred = (float(scal[0]), float(scal[1]),
                                   float(scal[2]), float(scal[3]))
        epochs_done = e + 1
        gap_history.append(gap)
        with _STATS_LOCK:
            _STATS["epochs"] += 1
        try:
            registry.gauge("sdca.duality_gap").set(gap)
            registry.counter("sdca.epochs").inc()
        except Exception:   # hygiene-ok — telemetry is best-effort
            pass
        if not (math.isfinite(primal) and math.isfinite(dual)
                and math.isfinite(gap)):
            failure = int(FailureMode.NON_FINITE_LOSS)
            reason = int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING)
            break
        # bounded-staleness guard: the dual estimate is exactly one epoch
        # lagged, so epoch e's scalars realize epoch e-1's prediction
        if (prev_dual is not None and prev_pred is not None
                and math.isfinite(prev_pred) and prev_pred > tiny):
            realized = dual - prev_dual
            if realized < config.staleness_guard * prev_pred:
                damping = max(damping * 0.5, config.min_damping)
                run_fallbacks += 1
                _record_fallback(e, prev_pred, realized, damping)
        prev_dual, prev_pred = dual, pred
        if gap0 is None:
            gap0 = gap
        if on_epoch is not None:
            on_epoch(e, {"gap": gap, "primal": primal, "dual": dual,
                         "predicted": pred,
                         "coef": progs.coef_host(state)})
        if gap <= config.gap_tolerance * max(gap0, tiny):
            reason = int(ConvergenceReason.DUALITY_GAP_CONVERGED)
            break

    if checkpoint_path and os.path.exists(checkpoint_path):
        try:
            os.remove(checkpoint_path)
        except OSError:  # pragma: no cover — best-effort cleanup
            pass

    converged = reason == int(ConvergenceReason.DUALITY_GAP_CONVERGED)
    _record_run({"epochs": epochs_done, "gap": gap, "gap0": gap0,
                 "damping": damping, "reason": reason,
                 "converged": converged,
                 "fallbacks": run_fallbacks,
                 "loss": objective.loss.name}, converged)

    coef = progs.coef_host(state)
    return SolverResult(
        coef=jnp.asarray(coef, dt),
        value=jnp.asarray(primal, dt),
        gradient=jnp.zeros((d,), dt),
        iterations=jnp.asarray(epochs_done, jnp.int32),
        reason=jnp.asarray(reason, jnp.int32),
        num_fun_evals=jnp.asarray(epochs_done, jnp.int32),
        failure=jnp.asarray(failure, jnp.int32),
    )
