"""Exact normal-equations solve for quadratic (squared-loss) objectives.

TPU-native extension with no reference analog: the reference runs Breeze
L-BFGS / TRON to convergence on per-entity ridge problems
(SingleNodeOptimizationProblem.scala:40); for squared loss the objective
is exactly quadratic, so the minimizer is one linear solve:

    x* = x0 - H^{-1} g(x0)      (exact from ANY starting point)

H is the weighted Gram matrix + lambda*I (one MXU contraction via
aggregators.hessian_matrix) and the solve is a Cholesky factorization —
batched over entities under vmap this is one [E, K, K] potrf/trsm
pipeline instead of thousands of sequential while_loop iterations.
sklearn Ridge's own `cholesky` solver is the CPU-world equivalent, which
makes bench comparisons apples-to-apples.

Requires positive-definite H: lambda > 0, or full-rank (weighted)
features. Entities with no data keep their starting coefficients (the
iterative solvers' behavior at a zero gradient).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_tpu.optim.base import (
    ConvergenceReason,
    FailureMode,
    SolverResult,
    nonfinite_code,
)

Array = jax.Array


def _newton_step(x0: Array, f0: Array, g: Array, h: Array) -> SolverResult:
    """One exact Newton step on a quadratic with value f0 / gradient g /
    Hessian h at x0. The solution-point value and gradient follow from
    already-materialized quantities — no second data pass:
    g(x) = g + H step;  f(x) = f0 + g.step + 0.5 step.H.step.

    Singular/degenerate curvature (rank-deficient features at lambda=0,
    or an empty vmap lane) keeps the start point and SAYS SO — a failed
    entity must not read as converged in the per-entity trackers. The
    ``failure`` code distinguishes a bad input (non-finite f0/g, e.g. a
    poisoned residual) from a non-finite Cholesky step."""
    chol = jax.scipy.linalg.cho_factor(h)
    step = -jax.scipy.linalg.cho_solve(chol, g)
    ok = jnp.all(jnp.isfinite(step))
    step = jnp.where(ok, step, 0.0)
    hs = h @ step
    init_fail = nonfinite_code(f0, jnp.all(jnp.isfinite(g)))
    failure = jnp.where(
        init_fail != FailureMode.NONE,
        init_fail,
        jnp.where(ok,
                  jnp.asarray(FailureMode.NONE, jnp.int32),
                  jnp.asarray(FailureMode.NON_FINITE_STEP, jnp.int32)))
    return SolverResult(
        coef=x0 + step,
        value=f0 + jnp.dot(g, step) + 0.5 * jnp.dot(step, hs),
        gradient=g + hs,
        iterations=jnp.asarray(1, jnp.int32),
        reason=jnp.where(
            ok,
            jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
            jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32)),
        num_fun_evals=jnp.asarray(1, jnp.int32),
        loss_history=None, gnorm_history=None,
        failure=failure,
    )


def minimize_path(value_and_grad_noreg, hessian_matrix_noreg, x0: Array,
                  lambdas: Array) -> SolverResult:
    """Solve the ENTIRE L2 regularization path in one data pass.

    ``value_and_grad_noreg`` / ``hessian_matrix_noreg`` evaluate the
    UN-regularized data objective; the Gram matrix G and the data
    gradient are computed once, then each lambda is one Cholesky of
    (G + lambda I) — vmapped, so an L-point ridge path costs one pass
    over the samples plus L batched [d, d] factorizations. (The
    iterative reference pays a full warm-started solve per lambda:
    ModelTraining.scala:134-147.) Returns a SolverResult whose leaves
    are stacked on a leading [L] axis.
    """
    f0, g0 = value_and_grad_noreg(x0)
    gram = hessian_matrix_noreg(x0)
    eye = jnp.eye(x0.shape[0], dtype=x0.dtype)

    def one(lam):
        # full-objective value/gradient at x0 for this lambda
        return _newton_step(x0, f0 + 0.5 * lam * jnp.dot(x0, x0),
                            g0 + lam * x0, gram + lam * eye)

    return jax.vmap(one)(lambdas)


def minimize(value_and_grad, hessian_matrix, x0: Array) -> SolverResult:
    """``value_and_grad(x) -> (f, g)``; ``hessian_matrix(x) -> [d, d]``
    constant in ``x`` for a quadratic objective (evaluated at ``x0``)."""
    f0, g0 = value_and_grad(x0)
    return _newton_step(x0, f0, g0, hessian_matrix(x0))
