"""OWL-QN: orthant-wise limited-memory quasi-Newton for L1/elastic-net.

The reference delegates to breeze.optimize.OWLQN with a per-index L1 weight
function (optimization/OWLQN.scala:40,80); this is a fresh JAX
implementation of the Andrew & Gao (2007) algorithm: pseudo-gradient,
two-loop direction on smooth-gradient history, sign-aligned direction,
orthant-projected backtracking line search. The L1 weight is a traced
argument so regularization-path sweeps reuse one compiled solve, and a
static ``config.l1_mask`` exempts indices (e.g. the intercept) from the
penalty.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    ConvergenceReason,
    FailureMode,
    StateTracking,
    SolverConfig,
    SolverResult,
    absolute_tolerances,
    convergence_reason,
    nonfinite_code,
)
from photon_tpu.optim.lbfgs import two_loop_direction

Array = jax.Array


def _pseudo_gradient(x: Array, g: Array, l1: Array) -> Array:
    right = g + l1   # derivative moving positive
    left = g - l1    # derivative moving negative
    pg_zero = jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0))
    return jnp.where(x > 0, right, jnp.where(x < 0, left, pg_zero))


def _project_orthant(x: Array, orthant: Array) -> Array:
    return jnp.where(x * orthant > 0, x, 0.0)


class _Carry(NamedTuple):
    x: Array
    f: Array          # full objective: smooth + l1
    g: Array          # smooth gradient
    pg: Array         # pseudo-gradient
    f_prev: Array
    s_hist: Array
    y_hist: Array
    rho: Array
    n_pairs: Array
    head: Array
    it: Array
    reason: Array
    n_evals: Array
    failure: Array    # int32 FailureMode (non-zero terminates the loop)
    trk: Optional[StateTracking]  # per-iteration ring buffer (None = off)


def minimize(
    value_and_grad,
    x0: Array,
    *args,
    l1_weight,
    config: SolverConfig = SolverConfig(),
    c1: float = 1e-4,
) -> SolverResult:
    """Minimize ``f(x) + sum(l1 * |x|)`` where ``value_and_grad`` computes
    the smooth part. ``l1_weight`` is a scalar or [d] array (traced)."""
    m = config.num_corrections
    d = x0.shape[0]
    dtype = x0.dtype

    l1 = jnp.broadcast_to(jnp.asarray(l1_weight, dtype), (d,))
    if config.l1_mask is not None:
        l1 = l1 * config.l1_mask

    def full_value(x, fx):
        return fx + jnp.sum(l1 * jnp.abs(x))

    f0s, g0 = value_and_grad(x0, *args)
    f0 = full_value(x0, f0s)
    pg0 = _pseudo_gradient(x0, g0, l1)
    tols = absolute_tolerances(f0, pg0, config.tolerance)

    def cond(c: _Carry):
        return ((c.reason == ConvergenceReason.NOT_CONVERGED)
                & (c.failure == FailureMode.NONE))

    def body(c: _Carry) -> _Carry:
        direction = two_loop_direction(c.pg, c.s_hist, c.y_hist, c.rho,
                                       c.n_pairs, c.head, m)
        # sign alignment: d must agree with -pg componentwise
        direction = jnp.where(direction * (-c.pg) > 0, direction, 0.0)
        descent = jnp.dot(direction, c.pg) < 0
        direction = jnp.where(descent, direction, -c.pg)

        orthant = jnp.where(c.x != 0, jnp.sign(c.x), jnp.sign(-c.pg))

        first = c.n_pairs == 0
        pgnorm = jnp.linalg.norm(c.pg)
        step0 = jnp.where(first, jnp.minimum(1.0, 1.0 / jnp.maximum(pgnorm, 1e-12)), 1.0)

        # orthant-projected backtracking Armijo line search. Flat-exit
        # guard (same floor problem linesearch.wolfe solves with
        # approximate-Wolfe acceptance): when a trial lands within
        # machine rounding of f after at least one halving, further
        # halvings can only get flatter — stop probing instead of
        # burning linesearch_max_iterations full data passes. The exit
        # keeps ok=False, so the improvement gate below still classifies
        # the iterate as not-improving (the honest terminal state).
        slack = 8.0 * jnp.finfo(dtype).eps * jnp.abs(c.f)

        def ls_cond(s):
            alpha, f_new, _x, _g, k, ok, stop = s
            return (~stop) & (k < config.linesearch_max_iterations)

        def ls_body(s):
            alpha, _f, _x, _g, k, _ok, _stop = s
            alpha = jnp.where(k == 0, alpha, alpha * 0.5)
            x_new = _project_orthant(c.x + alpha * direction, orthant)
            f_s, g_new = value_and_grad(x_new, *args)
            f_new = full_value(x_new, f_s)
            ok = f_new <= c.f + c1 * jnp.dot(c.pg, x_new - c.x)
            flat = (~ok) & (k >= 1) & (jnp.abs(f_new - c.f) <= slack)
            return alpha, f_new, x_new, g_new, k + 1, ok, ok | flat

        init_ls = (step0.astype(dtype), c.f, c.x, c.g,
                   jnp.asarray(0, jnp.int32), jnp.asarray(False),
                   jnp.asarray(False))
        _alpha, f_new, x_new, g_new, k, ok, _ = lax.while_loop(
            ls_cond, ls_body, init_ls)

        # Non-finite guard: a NaN/Inf trial must never be kept, and unlike
        # a merely flat trial it cannot be retried (the next probe would be
        # identical), so it terminates with a typed failure code. NaN fails
        # `<` on its own but -Inf passes it — gate on full finiteness.
        g_fin = jnp.all(jnp.isfinite(g_new))
        fin = jnp.isfinite(f_new) & g_fin
        failure = jnp.where(fin, jnp.asarray(FailureMode.NONE, jnp.int32),
                            nonfinite_code(f_new, g_fin))
        decreased = ok & (f_new < c.f) & fin
        x_kept = jnp.where(decreased, x_new, c.x)
        f_kept = jnp.where(decreased, f_new, c.f)
        g_kept = jnp.where(decreased, g_new, c.g)
        pg_new = _pseudo_gradient(x_kept, g_kept, l1)

        # curvature pairs from the smooth gradient (Andrew & Gao)
        s = x_kept - c.x
        yv = g_kept - c.g
        sy = jnp.dot(s, yv)
        store = decreased & (sy > 1e-10 * jnp.maximum(jnp.dot(yv, yv), 1e-30))
        write = c.head % m
        s_hist = jnp.where(store, c.s_hist.at[write].set(s), c.s_hist)
        y_hist = jnp.where(store, c.y_hist.at[write].set(yv), c.y_hist)
        rho = jnp.where(store, c.rho.at[write].set(1.0 / jnp.where(sy != 0, sy, 1.0)), c.rho)
        head = jnp.where(store, (c.head + 1) % m, c.head).astype(jnp.int32)
        n_pairs = jnp.where(store, jnp.minimum(c.n_pairs + 1, m), c.n_pairs)

        it = c.it + 1
        reason = convergence_reason(it, c.f, f_kept, pg_new, tols,
                                    config.max_iterations, improved=decreased)
        reason = jnp.where(
            (reason == ConvergenceReason.NOT_CONVERGED) & ~decreased,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason,
        )
        reason = jnp.where(
            failure != FailureMode.NONE,
            jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
            reason,
        )

        return _Carry(x=x_kept, f=f_kept, g=g_kept, pg=pg_new, f_prev=c.f,
                      s_hist=s_hist, y_hist=y_hist, rho=rho,
                      n_pairs=n_pairs, head=head, it=it, reason=reason,
                      n_evals=c.n_evals + k, failure=failure,
                      trk=None if c.trk is None
                      else c.trk.record(c.it, f_kept, pg_new))

    init = _Carry(
        x=x0, f=f0, g=g0, pg=pg0, f_prev=f0,
        s_hist=jnp.zeros((m, d), dtype), y_hist=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        n_pairs=jnp.asarray(0, jnp.int32), head=jnp.asarray(0, jnp.int32),
        it=jnp.asarray(0, jnp.int32),
        reason=jnp.where(
            jnp.linalg.norm(pg0) <= tols.gradient_tol,
            jnp.asarray(ConvergenceReason.GRADIENT_CONVERGED, jnp.int32),
            jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
        ),
        n_evals=jnp.asarray(1, jnp.int32),
        failure=nonfinite_code(f0, jnp.all(jnp.isfinite(g0))),
        trk=StateTracking.init(config.track_states, dtype),
    )

    out = lax.while_loop(cond, body, init)
    return SolverResult(
        coef=out.x, value=out.f, gradient=out.pg,
        iterations=out.it, reason=out.reason, num_fun_evals=out.n_evals,
        loss_history=None if out.trk is None else out.trk.loss,
        gnorm_history=None if out.trk is None else out.trk.gnorm,
        step_history=None if out.trk is None else out.trk.step,
        failure=out.failure,
    )
