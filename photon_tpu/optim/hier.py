"""Hierarchical local-subproblem solver (Snap ML, arXiv 1803.06333).

Communication-avoiding distributed GLM training (arXiv 1811.01564) on
the existing two-level mesh: each device runs H inner second-order
L-BFGS steps against its LOCAL data shard with the global model frozen,
then ONE staged ICI-then-DCN ``psum`` per round aggregates the local
deltas into a globally-consistent averaged update. DCN reductions drop
from per-L-BFGS-evaluation (the reference data-parallel solve) to
per-round — the round's single collective is the entire cross-slice
traffic, regardless of how many inner iterations ran.

Local subproblem (gradient-corrected, DANE-style — Shamir et al.'s
communication-efficient distributed optimization, the same family as
arXiv 1811.01564): shard k minimizes

    F~_k(theta) = F_k(theta) + v_k . theta
                  + (mu/2) * ||theta - c||^2
    F_k(theta)  = sum_{i in shard k} w_i * loss_i(theta)
                  + (lambda / P) * 0.5 * ||theta||^2
    v_k         = grad F(c_prev) / P  -  grad F_k(c_prev)

(``GLMObjective.local_value_and_gradient`` supplies F_k; ``sum_k F_k ==
F`` exactly). The linear correction ``v_k`` cancels each shard's
gradient heterogeneity at the anchor: every local problem then has the
SAME (1/P-scaled) global gradient there, so the fixed points of the
round iteration are exactly the stationary points of F — naive
parameter averaging instead stalls at the one-shot-averaging bias
floor. The global gradient the correction needs is one round stale and
rides the SAME packed psum (``concat([delta_k, g_k, f_k])``), so each
round still issues exactly one DCN-stage reduction, and the global
objective value at every candidate comes along for free.

Safeguard (host-side, between rounds — the round boundary is therefore
a bitwise-reproducible checkpoint exactly like parallel CD's group
boundaries): a candidate is accepted only if the global loss decreased;
otherwise the round's deltas are discarded and ONE reference global
L-BFGS step is taken from the best-known iterate — a typed
``hier_fallback`` event plus counters, never an exception.

Scope: data-parallel (replicated theta) dense or ELL-sparse batches
sharded over ``(dcn?, data)``. ``ModelShardedSparse`` is refused by
construction — its margins need model-axis psums before the pointwise
dz, so a round's inner iterations could never be collective-free.

This module is scanned by ``scripts/check_no_host_sync.py``: host reads
of round scalars spell ``np.asarray`` and only happen at the round
boundary.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from photon_tpu.data.dataset import DataBatch
from photon_tpu.function.objective import GLMObjective, Hyper
from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.ops import features as F
from photon_tpu.ops import pallas_glm
from photon_tpu.optim import lbfgs
from photon_tpu.optim.base import SolverConfig
from photon_tpu.parallel import mesh as M
from photon_tpu.resilience.failures import record_failure

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class HierConfig:
    """Round structure of the hierarchical solve.

    ``local_iterations`` is H — the inner L-BFGS budget each shard
    spends per round against its frozen corrected local subproblem.
    ``prox`` seeds the damping weight mu of the proximity term anchoring
    the local solve to the incoming candidate (0 = undamped); the host
    loop adapts mu trust-region style — grown on safeguard trips, decayed
    on accepted rounds — as a TRACED round input, so adaptation never
    recompiles. ``tolerance`` stops the outer loop on the relative
    global-loss change between accepted rounds (and on a matching
    gradient norm).
    """

    rounds: int = 30
    local_iterations: int = 8
    prox: float = 0.0
    tolerance: float = 1e-8
    num_corrections: int = 10
    linesearch_max_iterations: int = 25
    # >1: each round's LOCAL solve reads only a 1/inner_chunks slice of
    # the shard's rows (round-robin over rounds; data term scaled by
    # inner_chunks to stay an unbiased estimate of the shard objective),
    # so one round streams a fraction of the local data through compute —
    # the mini-batch inner-step mode for out-of-core shards. The
    # correction anchor v, the packed psum (f, g) and the safeguard all
    # still use the FULL shard, so acceptance decisions are exact and the
    # communication structure (one staged DCN psum per round) is
    # unchanged.
    inner_chunks: int = 1


class HierResult(NamedTuple):
    coef: Array                  # best iterate (replicated)
    value: float                 # global objective at coef
    rounds: int                  # rounds executed
    accepted: int                # rounds whose candidate improved F
    fallbacks: int               # safeguard trips (reference steps taken)
    dcn_reductions: int          # DCN-stage reductions this solve issued
    history: Tuple[float, ...]   # global F at each evaluated candidate
    converged: bool


def _sample_axes(mesh) -> Tuple[str, ...]:
    if M.DCN_AXIS in mesh.axis_names:
        return (M.DCN_AXIS, M.DATA_AXIS)
    return (M.DATA_AXIS,)


def _check_features(batch: DataBatch) -> None:
    if isinstance(batch.features, F.ModelShardedSparse):
        raise ValueError(
            "hierarchical solver needs data-parallel (replicated-theta) "
            "batches; ModelShardedSparse margins require model-axis psums "
            "inside every evaluation, so collective-free local rounds are "
            "impossible by construction — use minimize_directional on the "
            "model-sharded path instead")


def _batch_specs(batch: DataBatch, sample_axes: Tuple[str, ...]):
    spec_axis = sample_axes if len(sample_axes) > 1 else sample_axes[0]
    return jax.tree.map(
        lambda a: P(spec_axis, *([None] * (a.ndim - 1))), batch)


def _staged_all_psum(x, mesh):
    """Replicate ``x``'s shard-sum over EVERY mesh axis, staging the DCN
    hop last so it is exactly one countable psum over ``DCN_AXIS``."""
    names = tuple(mesh.axis_names)
    if M.DCN_AXIS in names:
        ici = tuple(a for a in names if a != M.DCN_AXIS)
        return jax.lax.psum(jax.lax.psum(x, ici), M.DCN_AXIS)
    return jax.lax.psum(x, names)


def _mesh_factors(mesh, sample_axes) -> Tuple[int, int]:
    """(p_shards, replicas): number of data shards, and the product of
    the mesh-axis sizes the data is NOT sharded over — those replicas
    compute identical local quantities, and the all-axis psum multiplies
    every shard-sum by this factor."""
    p_shards = 1
    for a in sample_axes:
        p_shards *= M.axis_size(mesh, a)
    replicas = 1
    for name in mesh.axis_names:
        if name not in sample_axes:
            replicas *= M.axis_size(mesh, name)
    return p_shards, replicas


def build_round_fn(objective: GLMObjective, mesh,
                   config: HierConfig = HierConfig()):
    """The per-round SPMD program: ``round_fn(c, c_prev, g_prev, mu,
    hyper, batch) -> (avg_delta, g_global, f_global)`` where ``f_global
    = F(c)``, ``g_global = grad F(c)`` (the NEXT round's stale
    correction anchor), and ``avg_delta`` is the shard-averaged
    corrected local L-BFGS displacement. ``(c_prev, g_prev)`` anchor
    this round's gradient correction — the previous candidate and the
    global gradient there, both delivered by the previous round's psum.
    ``mu`` is the traced proximal damping weight.

    Exposed separately so tests and the bench can pin the communication
    structure statically: ``mesh.count_axis_psums(round_fn, DCN_AXIS,
    ...) == 1`` no matter how large ``local_iterations`` is.

    With ``config.inner_chunks > 1`` the returned function takes a
    LEADING traced ``chunk_idx`` argument selecting which local slice the
    round's inner solve reads (``round_fn(chunk_idx, c, c_prev, g_prev,
    mu, hyper, batch)``); the default keeps the classic arity.
    """
    sample_axes = _sample_axes(mesh)
    p_shards, replicas = _mesh_factors(mesh, sample_axes)
    inner = int(config.inner_chunks)
    if inner < 1:
        raise ValueError(f"inner_chunks must be >= 1, got {inner}")
    local_cfg = SolverConfig(
        max_iterations=config.local_iterations,
        tolerance=config.tolerance,
        num_corrections=config.num_corrections,
        linesearch_max_iterations=config.linesearch_max_iterations)

    def round_body(chunk_idx, c, c_prev, g_prev, mu, hyper, batch):
        d = c.shape[0]
        f0_raw, g0_raw = objective.local_value_and_gradient(
            c, batch, hyper, p_shards)
        # stale DANE correction anchored at the previous candidate:
        # v cancels this shard's gradient heterogeneity at c_prev
        _, gk_prev = objective.local_value_and_gradient(
            c_prev, batch, hyper, p_shards)
        v = g_prev / p_shards - gk_prev

        if inner > 1:
            n_local = batch.labels.shape[0]
            if n_local % inner != 0:
                raise ValueError(
                    f"inner_chunks={inner} must divide the per-shard row "
                    f"count {n_local} (shard_batch pads to the shard "
                    f"grid, not the chunk grid)")
            cl = n_local // inner
            sub = jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(
                    a, chunk_idx * cl, cl, axis=0), batch)

            def local_vg(ci):
                # 1/inner of the rows at inner x weight: same expectation
                # as the full-shard term, with L2 still at l2/p_shards
                f, g = objective.local_value_and_gradient(
                    ci, sub, hyper, p_shards * inner)
                dc = ci - c
                f = inner * f + jnp.dot(v, ci) + 0.5 * mu * jnp.dot(dc, dc)
                g = inner * g + v + mu * dc
                return f, g

            # no init_fg: the chunk objective at the anchor is NOT the
            # full-shard f0_raw — let the solver evaluate its own start
            res = lbfgs.minimize(local_vg, c, config=local_cfg)
        else:
            def local_vg(ci):
                f, g = objective.local_value_and_gradient(
                    ci, batch, hyper, p_shards)
                dc = ci - c
                f = f + jnp.dot(v, ci) + 0.5 * mu * jnp.dot(dc, dc)
                g = g + v + mu * dc
                return f, g

            # F~_k(c) / grad F~_k(c) from the raw pair — the prox term
            # and its gradient vanish at the anchor
            res = lbfgs.minimize(
                local_vg, c, config=local_cfg,
                init_fg=(f0_raw + jnp.dot(v, c), g0_raw + v))
        delta = res.coef - c
        packed = _staged_all_psum(
            jnp.concatenate([delta, g0_raw, f0_raw[None]]), mesh)
        return (packed[:d] / (p_shards * replicas),
                packed[d:2 * d] / replicas,
                packed[2 * d] / replicas)

    def make(chunk_idx, c, c_prev, g_prev, mu, hyper, batch):
        specs = _batch_specs(batch, sample_axes)
        # check_rep=False: the rep checker has no rule for the inner
        # L-BFGS while_loop; the all-axis psum above establishes the
        # P() output replication it would otherwise verify
        return M.shard_map(round_body, mesh=mesh,
                           in_specs=(P(), P(), P(), P(), P(),
                                     jax.tree.map(lambda _: P(), hyper),
                                     specs),
                           out_specs=(P(), P(), P()),
                           check_rep=False)(chunk_idx, c, c_prev, g_prev,
                                            mu, hyper, batch)

    jitted = jax.jit(make)
    if inner > 1:
        return jitted
    # classic arity: chunk_idx is meaningless at inner_chunks=1
    return jax.jit(lambda c, c_prev, g_prev, mu, hyper, batch: jitted(
        jnp.asarray(0, jnp.int32), c, c_prev, g_prev, mu, hyper, batch))


def build_global_vg(objective: GLMObjective, mesh):
    """Shard-map-explicit global ``(f, g)`` over the same layout, with
    the identical staged all-axis psum — the reference arm and the
    bootstrap/closing evaluation. Its jaxpr carries exactly ONE
    DCN-stage psum, so a reference L-BFGS solve issues one DCN
    reduction PER FUNCTION EVALUATION (vs per round for the
    hierarchical program)."""
    sample_axes = _sample_axes(mesh)
    p_shards, replicas = _mesh_factors(mesh, sample_axes)

    def vg_body(c, hyper, batch):
        f, g = objective.local_value_and_gradient(c, batch, hyper, p_shards)
        packed = _staged_all_psum(jnp.concatenate([g, f[None]]), mesh)
        return packed[-1] / replicas, packed[:-1] / replicas

    def make(c, hyper, batch):
        specs = _batch_specs(batch, sample_axes)
        return M.shard_map(vg_body, mesh=mesh,
                           in_specs=(P(), jax.tree.map(lambda _: P(), hyper),
                                     specs),
                           out_specs=(P(), P()))(c, hyper, batch)

    return jax.jit(make)


def minimize_hier(objective: GLMObjective, batch: DataBatch, hyper: Hyper,
                  x0: Array, mesh, *,
                  config: HierConfig = HierConfig()) -> HierResult:
    """Run the hierarchical solve: shard ``batch`` over the mesh's
    ``(dcn?, data)`` axes, bootstrap the correction anchor with one
    global evaluation, then iterate rounds of corrected device-local
    L-BFGS + one staged psum each, safeguarded by the host-side
    accept/fallback loop.

    The Pallas fused kernel is disabled while tracing these programs:
    inside a shard_map body the operands are per-shard tracers and the
    kernel's dispatch gate cannot see the enclosing mesh, so routing
    stays on the (shard-safe) XLA aggregators.
    """
    _check_features(batch)
    sample_axes = _sample_axes(mesh)
    sharded = M.shard_batch(
        batch, mesh,
        axis=sample_axes if len(sample_axes) > 1 else sample_axes[0])
    c = M.replicate(jnp.asarray(x0), mesh)

    round_fn = build_round_fn(objective, mesh, config)
    global_vg = build_global_vg(objective, mesh)

    fb_cfg = SolverConfig(max_iterations=1,
                          tolerance=config.tolerance,
                          num_corrections=config.num_corrections,
                          linesearch_max_iterations=(
                              config.linesearch_max_iterations))

    def _fallback_step(c_best, hyper_, batch_):
        return lbfgs.minimize(
            lambda ci: global_vg(ci, hyper_, batch_), c_best, config=fb_cfg)

    fallback_fn = jax.jit(_fallback_step)
    hits = _metrics.counter("parallel.dcn_stage_reductions", path="hier")

    # bootstrap: one global evaluation seeds f_best AND the correction
    # anchor (c_prev, g_prev), so round 1 is already gradient-corrected
    with pallas_glm.disabled():
        f0, g0 = global_vg(c, hyper, sharded)
    dcn = 1
    hits.inc()
    f_best = float(np.asarray(f0))
    g0_norm = float(np.linalg.norm(np.asarray(g0)))
    gtol = config.tolerance * max(1.0, g0_norm)
    eps = float(jnp.finfo(jnp.asarray(x0).dtype).eps)
    x_best, c_prev, g_prev = c, c, g0
    rounds = accepted = fallbacks = stall = 0
    pending = False    # does c hold a not-yet-evaluated candidate?
    at_anchor = True   # is c a point whose loss IS f_best by construction?
    mu = float(config.prox)
    dtype = jnp.asarray(x0).dtype
    history = [f_best]
    converged = g0_norm <= gtol

    inner = int(config.inner_chunks)
    while rounds < config.rounds and not converged:
        with pallas_glm.disabled():
            if inner > 1:
                # round-robin chunk cursor: traced, so every round reuses
                # the one compiled program
                avg_delta, g_c, f_c = round_fn(
                    jnp.asarray(rounds % inner, jnp.int32), c, c_prev,
                    g_prev, jnp.asarray(mu, dtype), hyper, sharded)
            else:
                avg_delta, g_c, f_c = round_fn(
                    c, c_prev, g_prev, jnp.asarray(mu, dtype), hyper,
                    sharded)
        rounds += 1
        dcn += 1
        hits.inc()
        f_c_h = float(np.asarray(f_c))
        history.append(f_c_h)
        pending = False
        # ftol: material-progress threshold; slack: the dtype's own
        # round-off at this loss magnitude — a "regression" smaller than
        # float noise is a tie, not a safeguard trip
        ftol = max(config.tolerance, 4.0 * eps) * (abs(f_best) + 1.0)
        slack = 16.0 * eps * (abs(f_best) + 1.0)
        if np.isfinite(f_c_h) and (at_anchor or f_c_h <= f_best + slack):
            # accept: the delta that produced c held or improved the
            # global loss (or c IS the anchor — f_c equals f_best by
            # construction, nothing to judge yet); advance along this
            # round's averaged local displacement
            if f_c_h < f_best:
                improvement = f_best - f_c_h
                x_best, f_best = c, f_c_h
                accepted += 1
            else:
                improvement = 0.0
            if not at_anchor:
                stall = stall + 1 if improvement <= ftol else 0
                if improvement > ftol:
                    mu *= 0.25  # damping pays rent only while needed
                    if mu < 1e-12:
                        mu = 0.0
            gnorm = float(np.linalg.norm(np.asarray(g_c)))
            if gnorm <= gtol or stall >= 3:
                # stationary, or three straight advanced rounds below
                # material progress — converged to the dtype's
                # resolution of the optimum
                converged = True
                break
            c_prev, g_prev = c, g_c
            c = c + avg_delta
            pending = True
            at_anchor = False
        else:
            # safeguard: the previous round's delta regressed the GLOBAL
            # loss. Typed event, delta discarded, one reference global
            # step from the best-known iterate re-anchors the rounds,
            # and the proximal damping tightens so the next round's
            # local solves stay nearer the anchor (trust-region shrink).
            fallbacks += 1
            _metrics.counter("hier.fallbacks").inc()
            record_failure("hier_fallback", round=rounds,
                           f_candidate=f_c_h, f_best=f_best)
            delta_norm = float(np.linalg.norm(
                np.asarray(c) - np.asarray(x_best)))
            g_anchor_norm = float(np.linalg.norm(np.asarray(g_prev)))
            mu_floor = g_anchor_norm / max(delta_norm, 1e-30)
            mu = max(4.0 * mu, mu_floor)
            with pallas_glm.disabled():
                res = fallback_fn(x_best, hyper, sharded)
            n_evals = int(np.asarray(res.num_fun_evals))
            dcn += n_evals
            hits.inc(n_evals)
            prev_best = f_best
            x_best = res.coef
            f_best = float(np.asarray(res.value))
            history.append(f_best)
            # the fallback result carries the exact global gradient at
            # the new anchor — the next round's correction is fresh
            c, c_prev, g_prev = res.coef, res.coef, res.gradient
            at_anchor = True
            stall = 0
            if (float(np.linalg.norm(np.asarray(res.gradient))) <= gtol
                    or prev_best - f_best <= ftol):
                # even the reference step cannot make material progress
                converged = True
                break

    # closing global evaluation of the final (unevaluated) candidate —
    # the monotone best-of guarantee costs one more staged reduction
    if pending:
        with pallas_glm.disabled():
            f_final, _ = global_vg(c, hyper, sharded)
        dcn += 1
        hits.inc()
        f_final_h = float(np.asarray(f_final))
        history.append(f_final_h)
        if np.isfinite(f_final_h) and f_final_h < f_best:
            x_best, f_best = c, f_final_h

    _metrics.gauge("hier.rounds").set(rounds)
    _metrics.gauge("hier.dcn_reductions").set(dcn)
    return HierResult(coef=x_best, value=f_best, rounds=rounds,
                      accepted=accepted, fallbacks=fallbacks,
                      dcn_reductions=dcn, history=tuple(history),
                      converged=converged)


def minimize_reference(objective: GLMObjective, batch: DataBatch,
                       hyper: Hyper, x0: Array, mesh, *,
                       config: SolverConfig = SolverConfig()
                       ) -> Tuple[lbfgs.SolverResult, int]:
    """Reference data-parallel solve over the SAME shard-map-explicit
    global value-and-grad (one staged DCN psum per evaluation). Returns
    ``(result, dcn_reductions)`` where the reduction count is
    ``num_fun_evals`` — every evaluation crossed DCN once. This is the
    comparison arm for the >=5x fewer-DCN-reductions acceptance bar."""
    _check_features(batch)
    sample_axes = _sample_axes(mesh)
    sharded = M.shard_batch(
        batch, mesh,
        axis=sample_axes if len(sample_axes) > 1 else sample_axes[0])
    c = M.replicate(jnp.asarray(x0), mesh)
    global_vg = build_global_vg(objective, mesh)

    def _solve(ci, hyper_, batch_):
        return lbfgs.minimize(
            lambda cc: global_vg(cc, hyper_, batch_), ci, config=config)

    with pallas_glm.disabled():
        res = jax.jit(_solve)(c, hyper, sharded)
    n = int(np.asarray(res.num_fun_evals))
    _metrics.counter("parallel.dcn_stage_reductions", path="reference").inc(n)
    return res, n
