"""Objective functions: the optimizer <-> model contract.

Reference hierarchy: function/ObjectiveFunction.scala:25, DiffFunction
.scala:25, TwiceDiffFunction.scala:25, the L2Regularization mixins
(function/L2Regularization.scala:26,77,140), and DistributedGLMLossFunction
/ SingleNodeGLMLossFunction (function/glm/*.scala), which delegate to the
four aggregators.

TPU re-design: an objective is a bundle of *pure functions* over
``(coef, batch, hyper)``. ``hyper`` carries dynamic hyperparameters —
currently the L2 weight — as traced values, so a regularization-path sweep
(reference: ModelTraining.scala:134-147) reuses ONE compiled optimizer
instead of recompiling per lambda. The same objective object drives the
distributed (batch-sharded pjit) and local (vmap-ed per-entity) paths, the
moral of the reference's abstract ``type Data`` trick.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import aggregators
from photon_tpu.ops.losses import PointwiseLoss
from photon_tpu.ops.normalization import NormalizationContext, no_normalization

Array = jax.Array


class RegularizationType(enum.Enum):
    """Reference: optimization/RegularizationContext.scala:38."""

    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """Splits a total regularization weight into L1/L2 parts
    (reference: RegularizationContext.scala:115-130; alpha is the elastic-net
    mixing weight: l1 = alpha * lambda, l2 = (1 - alpha) * lambda)."""

    reg_type: RegularizationType = RegularizationType.NONE
    elastic_net_alpha: Optional[float] = None

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L1:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return (self.elastic_net_alpha or 0.0) * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L2:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return (1.0 - (self.elastic_net_alpha or 0.0)) * reg_weight
        return 0.0


NoRegularization = RegularizationContext(RegularizationType.NONE)
L1Regularization = RegularizationContext(RegularizationType.L1)
L2Regularization = RegularizationContext(RegularizationType.L2)


class Hyper(NamedTuple):
    """Dynamic (traced) objective hyperparameters."""

    l2_weight: Array  # scalar

    @staticmethod
    def of(l2_weight: float = 0.0, dtype=jnp.float32) -> "Hyper":
        return Hyper(l2_weight=jnp.asarray(l2_weight, dtype=dtype))


@dataclasses.dataclass(frozen=True)
class GLMObjective:
    """GLM loss objective with L2 folded in (L1 is the solver's job — OWL-QN,
    as in the reference where OWLQN owns the L1 term).

    All methods are pure and jit/vmap-safe. ``coef`` lives in
    transformed (normalized) space; ``norm`` folds the affine feature map
    into the kernels algebraically.
    """

    loss: PointwiseLoss
    norm: NormalizationContext = no_normalization()

    # -- first order --------------------------------------------------------

    def value(self, coef: Array, batch: DataBatch, hyper: Hyper) -> Array:
        v, _ = self.value_and_gradient(coef, batch, hyper)
        return v

    def gradient(self, coef: Array, batch: DataBatch, hyper: Hyper) -> Array:
        _, g = self.value_and_gradient(coef, batch, hyper)
        return g

    def value_and_gradient(
        self, coef: Array, batch: DataBatch, hyper: Hyper
    ) -> Tuple[Array, Array]:
        v, g = aggregators.value_and_gradient(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, self.norm,
        )
        # L2 mixin (reference: L2Regularization.scala:26,77) — the reference
        # regularizes the full vector, intercept included.
        v = v + 0.5 * hyper.l2_weight * jnp.dot(coef, coef)
        g = g + hyper.l2_weight * coef
        return v, g

    # -- second order -------------------------------------------------------

    def hessian_vector(
        self, coef: Array, vector: Array, batch: DataBatch, hyper: Hyper
    ) -> Array:
        hv = aggregators.hessian_vector(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, vector, self.norm,
        )
        return hv + hyper.l2_weight * vector

    def hessian_weights(self, coef: Array, batch: DataBatch) -> Array:
        """Per-sample curvature weights, constant over one TRON CG solve."""
        return aggregators.hessian_weights(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, self.norm,
        )

    def hessian_vector_from_weights(
        self, d2: Array, vector: Array, batch: DataBatch, hyper: Hyper
    ) -> Array:
        hv = aggregators.hessian_vector_from_weights(
            batch.features, d2, vector, self.norm, vector.shape[0],
        )
        return hv + hyper.l2_weight * vector

    def hessian_matrix_from_weights(
        self, d2: Array, dim: int, batch: DataBatch, hyper: Hyper
    ) -> Array:
        h = aggregators.hessian_matrix_from_weights(
            batch.features, d2, self.norm, dim,
        )
        return h + hyper.l2_weight * jnp.eye(dim, dtype=h.dtype)

    def hessian_diagonal(self, coef: Array, batch: DataBatch, hyper: Hyper) -> Array:
        d = aggregators.hessian_diagonal(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, self.norm,
        )
        return d + hyper.l2_weight

    def hessian_matrix(self, coef: Array, batch: DataBatch, hyper: Hyper) -> Array:
        h = aggregators.hessian_matrix(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, self.norm,
        )
        return h + hyper.l2_weight * jnp.eye(coef.shape[0], dtype=h.dtype)
