"""Objective functions: the optimizer <-> model contract.

Reference hierarchy: function/ObjectiveFunction.scala:25, DiffFunction
.scala:25, TwiceDiffFunction.scala:25, the L2Regularization mixins
(function/L2Regularization.scala:26,77,140), and DistributedGLMLossFunction
/ SingleNodeGLMLossFunction (function/glm/*.scala), which delegate to the
four aggregators.

TPU re-design: an objective is a bundle of *pure functions* over
``(coef, batch, hyper)``. ``hyper`` carries dynamic hyperparameters —
currently the L2 weight — as traced values, so a regularization-path sweep
(reference: ModelTraining.scala:134-147) reuses ONE compiled optimizer
instead of recompiling per lambda. The same objective object drives the
distributed (batch-sharded pjit) and local (vmap-ed per-entity) paths, the
moral of the reference's abstract ``type Data`` trick.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import aggregators
from photon_tpu.ops.losses import PointwiseLoss
from photon_tpu.ops.normalization import NormalizationContext, no_normalization

Array = jax.Array


class RegularizationType(enum.Enum):
    """Reference: optimization/RegularizationContext.scala:38."""

    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """Splits a total regularization weight into L1/L2 parts
    (reference: RegularizationContext.scala:115-130; alpha is the elastic-net
    mixing weight: l1 = alpha * lambda, l2 = (1 - alpha) * lambda)."""

    reg_type: RegularizationType = RegularizationType.NONE
    elastic_net_alpha: Optional[float] = None

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L1:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return (self.elastic_net_alpha or 0.0) * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L2:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return (1.0 - (self.elastic_net_alpha or 0.0)) * reg_weight
        return 0.0


NoRegularization = RegularizationContext(RegularizationType.NONE)
L1Regularization = RegularizationContext(RegularizationType.L1)
L2Regularization = RegularizationContext(RegularizationType.L2)


class Hyper(NamedTuple):
    """Dynamic (traced) objective hyperparameters."""

    l2_weight: Array  # scalar

    @staticmethod
    def of(l2_weight: float = 0.0, dtype=jnp.float32) -> "Hyper":
        return Hyper(l2_weight=jnp.asarray(l2_weight, dtype=dtype))


class DirectionalProblem(NamedTuple):
    """Margin-resident view of an objective for directional solvers.

    A GLM objective is pointwise loss over margins plus an L2 quadratic,
    and margins are LINEAR in the coefficients. A solver that keeps the
    current margins resident can therefore evaluate any line-search trial
    ``f(x + a*d)`` in O(n_samples) pointwise work — no pass over the
    feature nnz — once the direction's margin increment is known. On the
    model-sharded sparse path, where every feature pass is the wallclock,
    this collapses a whole Wolfe search to less than one classic
    evaluation (see optim/lbfgs.minimize_directional).

    Closures (all pure, jit-safe):
      init(coef) -> (f, g, margins, xx)      one matvec + one rmatvec
      dir_margins(d) -> margin increment     one matvec
      trial(margins, m_d, xx, xd, dd, a) -> (f_a, dphi_a)   O(n_samples)
      at_point(coef, margins, xx) -> (f, g)  one rmatvec
    where ``xx = coef . coef``, ``xd = coef . d``, ``dd = d . d`` feed the
    L2 term's exact 1-D quadratic. ``at_point`` takes xx from the caller
    (the solver advances it by the same exact quadratic,
    xx + a*(2*xd + a*dd)) so the evaluation never re-pays a full
    d-dimensional dot for a scalar it already knows.
    """

    init: Callable[[Array], Tuple[Array, Array, Array, Array]]
    dir_margins: Callable[[Array], Array]
    trial: Callable[..., Tuple[Array, Array]]
    at_point: Callable[[Array, Array, Array], Tuple[Array, Array]]


@dataclasses.dataclass(frozen=True)
class GLMObjective:
    """GLM loss objective with L2 folded in (L1 is the solver's job — OWL-QN,
    as in the reference where OWLQN owns the L1 term).

    All methods are pure and jit/vmap-safe. ``coef`` lives in
    transformed (normalized) space; ``norm`` folds the affine feature map
    into the kernels algebraically.
    """

    loss: PointwiseLoss
    norm: NormalizationContext = no_normalization()

    # -- first order --------------------------------------------------------

    def value(self, coef: Array, batch: DataBatch, hyper: Hyper) -> Array:
        v, _ = self.value_and_gradient(coef, batch, hyper)
        return v

    def gradient(self, coef: Array, batch: DataBatch, hyper: Hyper) -> Array:
        _, g = self.value_and_gradient(coef, batch, hyper)
        return g

    def value_and_gradient(
        self, coef: Array, batch: DataBatch, hyper: Hyper
    ) -> Tuple[Array, Array]:
        v, g = aggregators.value_and_gradient(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, self.norm,
        )
        # L2 mixin (reference: L2Regularization.scala:26,77) — the reference
        # regularizes the full vector, intercept included.
        v = v + 0.5 * hyper.l2_weight * jnp.dot(coef, coef)
        g = g + hyper.l2_weight * coef
        return v, g

    def local_value_and_gradient(
        self, coef: Array, batch: DataBatch, hyper: Hyper, num_shards: int
    ) -> Tuple[Array, Array]:
        """Local-subproblem view for the hierarchical solver (optim/hier):
        the data term over THIS shard's rows plus ``1/num_shards`` of the
        L2 quadratic, so summing F_k over all shards recovers the global
        objective exactly — the invariant the round safeguard's global-
        loss comparison rests on."""
        scaled = Hyper(l2_weight=hyper.l2_weight / num_shards)
        return self.value_and_gradient(coef, batch, scaled)

    # -- streamed (chunk-accumulated) evaluation ----------------------------

    @staticmethod
    def init_stream_carry(dim: int, dtype) -> Tuple[Array, Array]:
        """Device-resident accumulator for a chunked objective pass:
        (value_acc scalar, grad_acc [dim]), both zero."""
        return (jnp.zeros((), dtype=dtype), jnp.zeros((dim,), dtype=dtype))

    def chunk_value_and_gradient(
        self, carry: Tuple[Array, Array], coef: Array, batch: DataBatch
    ) -> Tuple[Array, Array]:
        """One streamed chunk's contribution to the DATA term, folded into
        the carry. Pad rows carry weight 0 and contribute exactly nothing,
        so the padded tail chunk needs no separate mask. The L2 term is
        deliberately absent — it is per-pass, not per-chunk — and is added
        once by ``finalize_streamed``. Summing this over a pass's chunks
        reproduces the resident data term up to FP summation order."""
        v, g = aggregators.value_and_gradient(
            self.loss, batch.features, batch.labels, batch.offsets,
            batch.weights, coef, self.norm,
        )
        return carry[0] + v, carry[1] + g

    def finalize_streamed(
        self, carry: Tuple[Array, Array], coef: Array, hyper: Hyper
    ) -> Tuple[Array, Array]:
        """Close a chunked pass: accumulated data term + the L2 mixin,
        applied exactly once (same mixin as ``value_and_gradient``)."""
        v, g = carry
        return (v + 0.5 * hyper.l2_weight * jnp.dot(coef, coef),
                g + hyper.l2_weight * coef)

    def directional_problem(
        self, batch: DataBatch, hyper: Hyper
    ) -> DirectionalProblem:
        """Margin-resident 1-D view of this objective (see
        ``DirectionalProblem``). The L2 mixin is folded in exactly:
        0.5*l2*|x + a*d|^2 = 0.5*l2*(xx + 2a*xd + a^2*dd)."""
        loss, norm = self.loss, self.norm
        x, y = batch.features, batch.labels
        off, w = batch.offsets, batch.weights

        def at_point(coef, margins, xx):
            f_data, g_data = aggregators.margin_value_and_gradient(
                loss, x, y, w, margins, norm, coef.shape[0])
            return (f_data + 0.5 * hyper.l2_weight * xx,
                    g_data + hyper.l2_weight * coef)

        def init(coef):
            margins = aggregators.compute_margins(x, coef, off, norm)
            xx = jnp.dot(coef, coef)
            f, g = at_point(coef, margins, xx)
            return f, g, margins, xx

        def dir_margins(direction):
            # offsets=None keeps only the part that scales with the
            # coefficients, so m(coef + a*d) = m(coef) + a*dir_margins(d)
            # holds exactly (normalization included — it is affine too)
            return aggregators.compute_margins(x, direction, None, norm)

        def trial(margins, m_d, xx, xd, dd, a):
            f_data, dphi_data = aggregators.margin_trial(
                loss, y, w, margins, m_d, a)
            f = f_data + 0.5 * hyper.l2_weight * (xx + a * (2.0 * xd + a * dd))
            dphi = dphi_data + hyper.l2_weight * (xd + a * dd)
            return f, dphi

        return DirectionalProblem(init=init, dir_margins=dir_margins,
                                  trial=trial, at_point=at_point)

    # -- second order -------------------------------------------------------

    def hessian_vector(
        self, coef: Array, vector: Array, batch: DataBatch, hyper: Hyper
    ) -> Array:
        hv = aggregators.hessian_vector(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, vector, self.norm,
        )
        return hv + hyper.l2_weight * vector

    def hessian_weights(self, coef: Array, batch: DataBatch) -> Array:
        """Per-sample curvature weights, constant over one TRON CG solve."""
        return aggregators.hessian_weights(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, self.norm,
        )

    def hessian_vector_from_weights(
        self, d2: Array, vector: Array, batch: DataBatch, hyper: Hyper
    ) -> Array:
        hv = aggregators.hessian_vector_from_weights(
            batch.features, d2, vector, self.norm, vector.shape[0],
        )
        return hv + hyper.l2_weight * vector

    def hessian_matrix_from_weights(
        self, d2: Array, dim: int, batch: DataBatch, hyper: Hyper
    ) -> Array:
        h = aggregators.hessian_matrix_from_weights(
            batch.features, d2, self.norm, dim,
        )
        return h + hyper.l2_weight * jnp.eye(dim, dtype=h.dtype)

    def hessian_diagonal(self, coef: Array, batch: DataBatch, hyper: Hyper) -> Array:
        d = aggregators.hessian_diagonal(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, self.norm,
        )
        return d + hyper.l2_weight

    def hessian_matrix(self, coef: Array, batch: DataBatch, hyper: Hyper) -> Array:
        h = aggregators.hessian_matrix(
            self.loss, batch.features, batch.labels, batch.offsets, batch.weights,
            coef, self.norm,
        )
        return h + hyper.l2_weight * jnp.eye(coef.shape[0], dtype=h.dtype)
