"""Device-resident feature matrices: dense and padded-sparse (ELL) layouts.

The reference streams per-sample Breeze sparse vectors through Spark
closures. On TPU every batch is one static-shape array; sparse rows use a
padded ELL layout (``indices [n, k]``, ``values [n, k]``) with pad slots
pointing at column 0 with value 0, so no masking is ever needed:
pads contribute ``0 * theta[0]`` to margins and scatter ``+0`` into
gradients.

All four aggregator kernels (see ops/aggregators.py) reduce to three
primitives on this layout:

  * ``matvec(X, theta)        -> margins [n]``   (MXU-friendly when dense)
  * ``rmatvec(X, w, dim)      -> X^T w    [d]``  (segment-sum scatter when sparse)
  * ``sq_rmatvec(X, w, dim)   -> (X*X)^T w [d]`` (for Hessian diagonals)

plus ``weighted_gram`` for small-dimension full Hessians.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


class SparseFeatures(NamedTuple):
    """Padded ELL rows: ``indices[i, j]`` / ``values[i, j]`` is the j-th
    nonzero of sample i; pad slots are ``(0, 0.0)``."""

    indices: Array  # [n, k] int32
    values: Array   # [n, k] float


FeatureMatrix = Union[Array, SparseFeatures]


def num_samples(x: FeatureMatrix) -> int:
    return (x.values if isinstance(x, SparseFeatures) else x).shape[0]


def matvec(x: FeatureMatrix, theta: Array) -> Array:
    """Per-sample margins ``X @ theta`` -> [n]."""
    if isinstance(x, SparseFeatures):
        return jnp.sum(x.values * theta[x.indices], axis=-1)
    return x @ theta


def rmatvec(x: FeatureMatrix, w: Array, dim: int) -> Array:
    """``X^T w`` -> [d]; ``w`` is a per-sample weight vector [n]."""
    if isinstance(x, SparseFeatures):
        contrib = (x.values * w[:, None]).ravel()
        return jnp.zeros((dim,), dtype=contrib.dtype).at[x.indices.ravel()].add(contrib)
    return x.T @ w


def sq_rmatvec(x: FeatureMatrix, w: Array, dim: int) -> Array:
    """``(X * X)^T w`` -> [d] (elementwise square), for Hessian diagonals."""
    if isinstance(x, SparseFeatures):
        contrib = (x.values * x.values * w[:, None]).ravel()
        return jnp.zeros((dim,), dtype=contrib.dtype).at[x.indices.ravel()].add(contrib)
    return (x * x).T @ w


def weighted_gram(x: FeatureMatrix, w: Array, dim: int) -> Array:
    """``X^T diag(w) X`` -> [d, d], for small-dim full Hessians
    (reference: HessianMatrixAggregator.scala:31)."""
    if isinstance(x, SparseFeatures):
        dense = to_dense(x, dim)
        return dense.T @ (dense * w[:, None])
    return x.T @ (x * w[:, None])


def to_dense(x: FeatureMatrix, dim: int) -> Array:
    if isinstance(x, SparseFeatures):
        n, k = x.indices.shape
        out = jnp.zeros((n, dim), dtype=x.values.dtype)
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, k))
        return out.at[rows.ravel(), x.indices.ravel()].add(x.values.ravel())
    return x


def from_scipy_csr(csr, max_nnz: int | None = None, dtype=np.float32) -> SparseFeatures:
    """Host-side: scipy CSR -> padded ELL arrays (vectorized).

    ``max_nnz`` pads/clips the row width; rows with more nonzeros than
    ``max_nnz`` are rejected — silent feature truncation would corrupt
    margins. Callers that want capping must subsample explicitly.
    """
    csr = csr.tocsr()
    n = csr.shape[0]
    row_nnz = np.diff(csr.indptr)
    widest = int(row_nnz.max()) if n else 0
    k = int(max_nnz) if max_nnz is not None else widest
    if widest > k:
        raise ValueError(f"row has {widest} nonzeros > max_nnz={k}; "
                         "refusing to silently truncate features")
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=dtype)
    if n and k:
        cols = np.arange(k)[None, :]
        mask = cols < row_nnz[:, None]
        src = csr.indptr[:-1, None] + cols
        indices[mask] = csr.indices[src[mask]]
        values[mask] = csr.data[src[mask]]
    return SparseFeatures(indices=jnp.asarray(indices), values=jnp.asarray(values))


def from_rows(rows, dim: int, dtype=np.float32, max_nnz: int | None = None) -> SparseFeatures:
    """Host-side: list of (indices, values) pairs -> padded ELL arrays."""
    n = len(rows)
    widest = max((len(r[0]) for r in rows), default=0)
    k = max_nnz if max_nnz is not None else widest
    if widest > k:
        raise ValueError(f"row has {widest} nonzeros > max_nnz={k}; "
                         "refusing to silently truncate features")
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=dtype)
    for i, (idx, val) in enumerate(rows):
        m = len(idx)
        indices[i, :m] = np.asarray(idx, dtype=np.int32)
        values[i, :m] = np.asarray(val, dtype=dtype)
    del dim  # shape is carried by coefficient vectors, not the ELL arrays
    return SparseFeatures(indices=jnp.asarray(indices), values=jnp.asarray(values))
