"""Device-resident feature matrices: dense and padded-sparse (ELL) layouts.

The reference streams per-sample Breeze sparse vectors through Spark
closures. On TPU every batch is one static-shape array; sparse rows use a
padded ELL layout (``indices [n, k]``, ``values [n, k]``) with pad slots
pointing at column 0 with value 0, so no masking is ever needed:
pads contribute ``0 * theta[0]`` to margins and scatter ``+0`` into
gradients.

All four aggregator kernels (see ops/aggregators.py) reduce to three
primitives on this layout:

  * ``matvec(X, theta)        -> margins [n]``   (MXU-friendly when dense)
  * ``rmatvec(X, w, dim)      -> X^T w    [d]``  (segment-sum scatter when sparse)
  * ``sq_rmatvec(X, w, dim)   -> (X*X)^T w [d]`` (for Hessian diagonals)

plus ``weighted_gram`` for small-dimension full Hessians.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

Array = jax.Array


class SparseFeatures(NamedTuple):
    """Padded ELL rows: ``indices[i, j]`` / ``values[i, j]`` is the j-th
    nonzero of sample i; pad slots are ``(0, 0.0)``."""

    indices: Array  # [n, k] int32
    values: Array   # [n, k] float


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ModelShardedSparse:
    """Feature-range-partitioned ELL rows for model-parallel sparse theta.

    The TPU answer to the reference's partitioned PalDB feature indexes
    (PalDBIndexMap.scala:43) feeding "hundreds of billions of coefficients"
    (README.md:56): theta is range-sharded over the mesh's model axis, and
    each sample's nonzeros are pre-partitioned AT INGEST into one ELL block
    per range with LOCAL column ids. On device, margins are per-shard
    gather-dots psum-ed over the model axis, and gradients are per-shard
    local scatters psum-ed over the data axis — no nonzero ever crosses a
    chip boundary after ingest (SURVEY §5.7's "moral equivalent of sequence
    parallelism").

    ``indices``/``values`` are ``[P, n, kp]`` with ``indices[p, i, j]`` the
    LOCAL id (global id − p·shard_size) of the j-th in-range nonzero of
    sample i; pad slots are ``(0, 0.0)``. Placement: ``P(model, data)``.
    """

    indices: Array  # [P, n, kp] int32, local ids
    values: Array   # [P, n, kp]
    shard_size: int = dataclasses.field(metadata=dict(static=True))
    mesh: jax.sharding.Mesh = dataclasses.field(metadata=dict(static=True))
    data_axis: str = dataclasses.field(default="data",
                                       metadata=dict(static=True))
    model_axis: str = dataclasses.field(default="model",
                                        metadata=dict(static=True))

    @property
    def padded_dim(self) -> int:
        return self.indices.shape[0] * self.shard_size

    @property
    def shape(self):  # (n, d_padded) by analogy with a dense matrix
        return (self.values.shape[1], self.padded_dim)


FeatureMatrix = Union[Array, SparseFeatures, ModelShardedSparse]


def num_samples(x: FeatureMatrix) -> int:
    if isinstance(x, ModelShardedSparse):
        return x.values.shape[1]
    return (x.values if isinstance(x, SparseFeatures) else x).shape[0]


def _ms_specs(x: ModelShardedSparse):
    ell = PartitionSpec(x.model_axis, x.data_axis, None)
    return ell, PartitionSpec(x.model_axis), PartitionSpec(x.data_axis)


def matvec(x: FeatureMatrix, theta: Array) -> Array:
    """Per-sample margins ``X @ theta`` -> [n]."""
    if isinstance(x, ModelShardedSparse):
        ell, model_vec, data_vec = _ms_specs(x)

        def f(idx, val, th):
            # idx/val [1, n_local, kp]; th [shard_size] = this chip's range
            part = jnp.sum(val[0] * th[idx[0]], axis=-1)
            return jax.lax.psum(part, x.model_axis)

        return jax.shard_map(f, mesh=x.mesh,
                             in_specs=(ell, ell, model_vec),
                             out_specs=data_vec)(x.indices, x.values, theta)
    if isinstance(x, SparseFeatures):
        return jnp.sum(x.values * theta[x.indices], axis=-1)
    return x @ theta


def _ms_scatter(x: ModelShardedSparse, w: Array, square: bool) -> Array:
    """Shared shard_map scatter for X^T w / (X*X)^T w on the model-sharded
    layout: local scatters into this chip's theta range, psum over data."""
    ell, model_vec, data_vec = _ms_specs(x)
    shard_size = x.shard_size

    def f(idx, val, wl):
        if square:
            # promote BEFORE squaring: bf16 storage must not round the
            # squared Hessian terms at storage precision
            v0 = val[0].astype(wl.dtype)
            v = v0 * v0
        else:
            v = val[0]
        contrib = (v * wl[:, None]).ravel()
        g = jnp.zeros((shard_size,), dtype=contrib.dtype)
        g = g.at[idx[0].ravel()].add(contrib)
        return jax.lax.psum(g, x.data_axis)

    return jax.shard_map(f, mesh=x.mesh,
                         in_specs=(ell, ell, data_vec),
                         out_specs=model_vec)(x.indices, x.values, w)


def rmatvec(x: FeatureMatrix, w: Array, dim: int) -> Array:
    """``X^T w`` -> [d]; ``w`` is a per-sample weight vector [n]."""
    if isinstance(x, ModelShardedSparse):
        return _ms_scatter(x, w, square=False)
    if isinstance(x, SparseFeatures):
        contrib = (x.values * w[:, None]).ravel()
        return jnp.zeros((dim,), dtype=contrib.dtype).at[x.indices.ravel()].add(contrib)
    # w @ X, not X.T @ w: algebraically identical, but the explicit
    # transpose forces XLA-CPU through a strided 0.1 GFLOP/s path
    # (measured 33x slower at 200k x 512); on TPU both lower to the same
    # MXU contraction
    return w @ x


def sq_rmatvec(x: FeatureMatrix, w: Array, dim: int) -> Array:
    """``(X * X)^T w`` -> [d] (elementwise square), for Hessian diagonals.
    Values promote to the weight dtype BEFORE squaring so narrow feature
    storage (bf16) doesn't round the squared Hessian terms."""
    if isinstance(x, ModelShardedSparse):
        return _ms_scatter(x, w, square=True)
    if isinstance(x, SparseFeatures):
        v = x.values.astype(w.dtype)
        contrib = (v * v * w[:, None]).ravel()
        return jnp.zeros((dim,), dtype=contrib.dtype).at[x.indices.ravel()].add(contrib)
    xf = x.astype(w.dtype)
    return w @ (xf * xf)  # see rmatvec: avoid XLA-CPU's strided .T path


def weighted_gram(x: FeatureMatrix, w: Array, dim: int) -> Array:
    """``X^T diag(w) X`` -> [d, d], for small-dim full Hessians
    (reference: HessianMatrixAggregator.scala:31)."""
    if isinstance(x, ModelShardedSparse):
        raise NotImplementedError(
            "model-sharded sparse theta is matrix-free by design: a d x d "
            "Hessian would defeat the point of sharding theta")
    if isinstance(x, SparseFeatures):
        n, k = x.indices.shape
        if k <= 64:
            # per-slot scatter of the outer product: k scatters whose
            # temporaries are [n, k] — the same footprint as the data —
            # never an [n, dim] densification nor [n, k, k] blow-up
            # (the explicit-Hessian TRON path calls this per entity
            # under vmap, where big temps would dwarf the block)
            wv = w[:, None] * x.values                           # [n, k]
            h = jnp.zeros((dim, dim), wv.dtype)
            for j in range(k):  # k is a static ELL width, loop unrolls
                h = h.at[x.indices[:, j][:, None], x.indices].add(
                    wv[:, j][:, None] * x.values)
            return h
        dense = to_dense(x, dim)
        return dense.T @ (dense * w[:, None])
    return x.T @ (x * w[:, None])


def to_dense(x: FeatureMatrix, dim: int) -> Array:
    if isinstance(x, ModelShardedSparse):
        raise NotImplementedError("refusing to densify model-sharded features")
    if isinstance(x, SparseFeatures):
        n, k = x.indices.shape
        out = jnp.zeros((n, dim), dtype=x.values.dtype)
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, k))
        return out.at[rows.ravel(), x.indices.ravel()].add(x.values.ravel())
    return x


def partition_by_feature_range(
    sf: SparseFeatures, dim: int, n_shards: int, pad_multiple: int = 1
) -> tuple:
    """Host-side ingest step for model-parallel sparse training: split each
    ELL row's nonzeros into ``n_shards`` contiguous feature ranges with
    LOCAL column ids (the reference's partitioned-PalDB layout,
    PalDBIndexMapBuilder.scala:27, re-done as static arrays).

    Returns ``(indices [P, n, kp], values [P, n, kp], shard_size)`` as
    numpy arrays; kp is the worst-case per-(row, range) nonzero count,
    padded to ``pad_multiple``. Index maps that hash feature names over the
    id space keep ranges load-balanced — partitioning is by id range, the
    hashing already happened at index build.
    """
    idx = np.asarray(sf.indices)
    val = np.asarray(sf.values)
    n, k = idx.shape
    shard_size = -(-dim // n_shards)  # ceil
    if k == 0 or n == 0:
        kp = max(pad_multiple, 1)
        return (np.zeros((n_shards, n, kp), np.int32),
                np.zeros((n_shards, n, kp), val.dtype), shard_size)
    shard_of = idx // shard_size                       # [n, k]
    # ELL pad slots (value 0) must not inflate kp: route them to a virtual
    # shard n_shards, which sorts last and is truncated after scatter
    shard_of = np.where(val == 0, n_shards, shard_of)
    order = np.argsort(shard_of, axis=1, kind="stable")
    shard_sorted = np.take_along_axis(shard_of, order, 1)
    idx_sorted = np.take_along_axis(idx, order, 1)
    val_sorted = np.take_along_axis(val, order, 1)
    js = np.broadcast_to(np.arange(k), (n, k))
    new_group = np.concatenate(
        [np.ones((n, 1), bool), shard_sorted[:, 1:] != shard_sorted[:, :-1]], 1)
    group_start = np.maximum.accumulate(np.where(new_group, js, 0), axis=1)
    pos = js - group_start                             # slot within (row, range)
    real = shard_sorted < n_shards
    kp = int(pos[real].max()) + 1 if real.any() else 1
    kp = -(-kp // pad_multiple) * pad_multiple
    out_idx = np.zeros((n_shards + 1, n, max(kp, int(pos.max()) + 1)), np.int32)
    out_val = np.zeros_like(out_idx, dtype=val.dtype)
    rows = np.broadcast_to(np.arange(n)[:, None], (n, k))
    out_idx[shard_sorted, rows, pos] = idx_sorted - shard_sorted * shard_size
    out_val[shard_sorted, rows, pos] = val_sorted
    # drop the virtual pad shard and the slots only it used
    return (np.ascontiguousarray(out_idx[:n_shards, :, :kp]),
            np.ascontiguousarray(out_val[:n_shards, :, :kp]), shard_size)


def from_csr_arrays(indptr, cols, vals, max_nnz: int | None = None,
                    dtype=np.float32) -> SparseFeatures:
    """Host-side: raw CSR arrays -> padded ELL (vectorized; the zero-copy
    variant of from_scipy_csr for the native columnar ingest)."""
    indptr = np.asarray(indptr, np.int64)
    n = len(indptr) - 1
    row_nnz = np.diff(indptr)
    widest = int(row_nnz.max()) if n else 0
    k = int(max_nnz) if max_nnz is not None else widest
    if widest > k:
        raise ValueError(f"row has {widest} nonzeros > max_nnz={k}; "
                         "refusing to silently truncate features")
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=dtype)
    if n and k:
        slot = np.arange(k)[None, :]
        mask = slot < row_nnz[:, None]
        src = indptr[:-1, None] + slot
        indices[mask] = np.asarray(cols)[src[mask]]
        values[mask] = np.asarray(vals)[src[mask]]
    return SparseFeatures(indices=jnp.asarray(indices),
                          values=jnp.asarray(values))


def from_scipy_csr(csr, max_nnz: int | None = None, dtype=np.float32) -> SparseFeatures:
    """Host-side: scipy CSR -> padded ELL arrays (vectorized).

    ``max_nnz`` pads/clips the row width; rows with more nonzeros than
    ``max_nnz`` are rejected — silent feature truncation would corrupt
    margins. Callers that want capping must subsample explicitly.
    """
    csr = csr.tocsr()
    n = csr.shape[0]
    row_nnz = np.diff(csr.indptr)
    widest = int(row_nnz.max()) if n else 0
    k = int(max_nnz) if max_nnz is not None else widest
    if widest > k:
        raise ValueError(f"row has {widest} nonzeros > max_nnz={k}; "
                         "refusing to silently truncate features")
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=dtype)
    if n and k:
        cols = np.arange(k)[None, :]
        mask = cols < row_nnz[:, None]
        src = csr.indptr[:-1, None] + cols
        indices[mask] = csr.indices[src[mask]]
        values[mask] = csr.data[src[mask]]
    return SparseFeatures(indices=jnp.asarray(indices), values=jnp.asarray(values))


def from_rows(rows, dim: int, dtype=np.float32, max_nnz: int | None = None) -> SparseFeatures:
    """Host-side: list of (indices, values) pairs -> padded ELL arrays."""
    n = len(rows)
    widest = max((len(r[0]) for r in rows), default=0)
    k = max_nnz if max_nnz is not None else widest
    if widest > k:
        raise ValueError(f"row has {widest} nonzeros > max_nnz={k}; "
                         "refusing to silently truncate features")
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=dtype)
    for i, (idx, val) in enumerate(rows):
        m = len(idx)
        indices[i, :m] = np.asarray(idx, dtype=np.int32)
        values[i, :m] = np.asarray(val, dtype=dtype)
    del dim  # shape is carried by coefficient vectors, not the ELL arrays
    return SparseFeatures(indices=jnp.asarray(indices), values=jnp.asarray(values))
