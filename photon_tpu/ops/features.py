"""Device-resident feature matrices: dense and padded-sparse (ELL) layouts.

The reference streams per-sample Breeze sparse vectors through Spark
closures. On TPU every batch is one static-shape array; sparse rows use a
padded ELL layout (``indices [n, k]``, ``values [n, k]``) with pad slots
pointing at column 0 with value 0, so no masking is ever needed:
pads contribute ``0 * theta[0]`` to margins and scatter ``+0`` into
gradients.

All four aggregator kernels (see ops/aggregators.py) reduce to three
primitives on this layout:

  * ``matvec(X, theta)        -> margins [n]``   (MXU-friendly when dense)
  * ``rmatvec(X, w, dim)      -> X^T w    [d]``  (segment-sum scatter when sparse)
  * ``sq_rmatvec(X, w, dim)   -> (X*X)^T w [d]`` (for Hessian diagonals)

plus ``weighted_gram`` for small-dimension full Hessians.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

# jax.shard_map only exists from 0.5; this tree pins 0.4.x where the
# implementation lives under jax.experimental (keyword-argument API).
try:
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

Array = jax.Array


class SparseFeatures(NamedTuple):
    """Padded ELL rows: ``indices[i, j]`` / ``values[i, j]`` is the j-th
    nonzero of sample i; pad slots are ``(0, 0.0)``."""

    indices: Array  # [n, k] int32
    values: Array   # [n, k] float


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ModelShardedSparse:
    """Feature-range-partitioned ELL rows for model-parallel sparse theta.

    The TPU answer to the reference's partitioned PalDB feature indexes
    (PalDBIndexMap.scala:43) feeding "hundreds of billions of coefficients"
    (README.md:56): theta is range-sharded over the mesh's model axis, and
    each sample's nonzeros are pre-partitioned AT INGEST into one ELL block
    per range with LOCAL column ids. On device, margins are per-shard
    gather-dots psum-ed over the model axis, and gradients are per-shard
    local scatters psum-ed over the data axis — no nonzero ever crosses a
    chip boundary after ingest (SURVEY §5.7's "moral equivalent of sequence
    parallelism").

    ``indices``/``values`` are ``[P, n, kp]`` with ``indices[p, i, j]`` the
    LOCAL id (global id − p·shard_size) of the j-th in-range nonzero of
    sample i; pad slots are ``(0, 0.0)``. Placement: ``P(model, data)``.

    The ELL view serves ``matvec`` (contiguous gather-dot over rows). For
    the transposed products a second, column-sorted view of the SAME
    nonzeros is precomputed at ingest (``build_csc_plan``): per
    (model-shard, data-chunk) block, ``csc_rows``/``csc_vals`` hold the
    real nonzeros sorted by local column, and ``csc_ptr`` the column
    boundaries, so ``rmatvec``/``sq_rmatvec`` become contiguous segment
    reductions instead of serialized random scatter-adds (measured ~30x
    per-pass on the CPU backend at bench shapes). When the CSC view is
    absent (None) the kernels fall back to the original ``at[].add``
    scatter — tests pin the two paths against each other.

    ``dcn_axis`` (optional) names a cross-slice axis of a two-level
    (dcn, data, model) mesh: the sample dim is then sharded over
    ``(dcn, data)`` and gradient reductions are staged ICI-then-DCN
    (parallel/mesh.staged_psum as mesh layout).
    """

    indices: Array  # [P, n, kp] int32, local ids
    values: Array   # [P, n, kp]
    shard_size: int = dataclasses.field(metadata=dict(static=True))
    mesh: jax.sharding.Mesh = dataclasses.field(metadata=dict(static=True))
    data_axis: str = dataclasses.field(default="data",
                                       metadata=dict(static=True))
    model_axis: str = dataclasses.field(default="model",
                                        metadata=dict(static=True))
    # column-sorted view of the same nonzeros, per (shard, data-chunk)
    csc_rows: Optional[Array] = None   # [P, C, m] int32, chunk-local rows
    csc_vals: Optional[Array] = None   # [P, C, m]
    csc_ptr: Optional[Array] = None    # [P, C, shard_size + 1] int32
    dcn_axis: Optional[str] = dataclasses.field(
        default=None, metadata=dict(static=True))

    @property
    def padded_dim(self) -> int:
        return self.indices.shape[0] * self.shard_size

    @property
    def shape(self):  # (n, d_padded) by analogy with a dense matrix
        return (self.values.shape[1], self.padded_dim)


FeatureMatrix = Union[Array, SparseFeatures, ModelShardedSparse]


def num_samples(x: FeatureMatrix) -> int:
    if isinstance(x, ModelShardedSparse):
        return x.values.shape[1]
    return (x.values if isinstance(x, SparseFeatures) else x).shape[0]


def _ms_specs(x: ModelShardedSparse):
    # sample dims shard over (dcn, data) on a two-level mesh, data otherwise
    sample = ((x.dcn_axis, x.data_axis) if x.dcn_axis is not None
              else x.data_axis)
    ell = PartitionSpec(x.model_axis, sample, None)
    return ell, PartitionSpec(x.model_axis), PartitionSpec(sample)


def _ms_data_psum(x: ModelShardedSparse, g: Array) -> Array:
    """Gradient-shard reduction over the sample axes. On a two-level mesh
    this is the staged all-reduce (parallel/mesh.staged_psum, inlined to
    avoid the circular import): within-slice ICI first, one DCN crossing
    after — the reference's treeAggregateDepth>1 as collective structure."""
    g = jax.lax.psum(g, x.data_axis)
    if x.dcn_axis is not None:
        g = jax.lax.psum(g, x.dcn_axis)
    return g


def matvec(x: FeatureMatrix, theta: Array) -> Array:
    """Per-sample margins ``X @ theta`` -> [n]."""
    if isinstance(x, ModelShardedSparse):
        ell, model_vec, data_vec = _ms_specs(x)

        def f(idx, val, th):
            # idx/val [1, n_local, kp]; th [shard_size] = this chip's range.
            # Local ids are constructed in-range at ingest (pads point at
            # 0), so the gather plan is static and unchecked — no clamp or
            # fill lowering on the hot path.
            gathered = th.at[idx[0]].get(mode="promise_in_bounds")
            part = jnp.sum(val[0] * gathered, axis=-1)
            return jax.lax.psum(part, x.model_axis)

        return _shard_map(f, mesh=x.mesh,
                          in_specs=(ell, ell, model_vec),
                          out_specs=data_vec)(x.indices, x.values, theta)
    if isinstance(x, SparseFeatures):
        return jnp.sum(x.values * theta[x.indices], axis=-1)
    return x @ theta


def matvec_lanes(x: FeatureMatrix, thetas: Array) -> Array:
    """Stacked margins for K coefficient lanes: ``thetas [K, d] -> [K, n]``.

    The lane-batched data pass of the sweep path (optim/batched): dense
    rows contract as ONE ``Θ Xᵀ`` dot_general (contracting over d — no
    materialized transpose of the big matrix, same strided-path concern
    as ``rmatvec``'s ``w @ x``), and sparse ELL rows as ONE stacked
    gather over the shared ``x.indices`` plan — the batch is read once
    regardless of K. Model-sharded layouts train one model per mesh and
    are refused typed (sweep lanes would multiply the sharded theta
    footprint K-fold).
    """
    if isinstance(x, ModelShardedSparse):
        raise NotImplementedError(
            "matvec_lanes does not support ModelShardedSparse features — "
            "lane-batched sweeps hold K full coefficient vectors, which "
            "contradicts a theta range-sharded over the model axis")
    if isinstance(x, SparseFeatures):
        gathered = jnp.take(thetas, x.indices, axis=1)   # [K, n, k]
        return jnp.sum(x.values[None, :, :] * gathered, axis=-1)
    return jnp.einsum("kd,nd->kn", thetas, x)


def _ms_scatter(x: ModelShardedSparse, w: Array, square: bool) -> Array:
    """Shared shard_map scatter for X^T w / (X*X)^T w on the model-sharded
    layout: local scatters into this chip's theta range, psum over data.

    Fallback path for structs ingested without a CSC plan; the packed
    ``_ms_segment_reduce`` below replaces it on the hot path."""
    ell, model_vec, data_vec = _ms_specs(x)
    shard_size = x.shard_size

    def f(idx, val, wl):
        if square:
            # promote BEFORE squaring: bf16 storage must not round the
            # squared Hessian terms at storage precision
            v0 = val[0].astype(wl.dtype)
            v = v0 * v0
        else:
            v = val[0]
        contrib = (v * wl[:, None]).ravel()
        g = jnp.zeros((shard_size,), dtype=contrib.dtype)
        g = g.at[idx[0].ravel()].add(contrib)
        return _ms_data_psum(x, g)

    return _shard_map(f, mesh=x.mesh,
                      in_specs=(ell, ell, data_vec),
                      out_specs=model_vec)(x.indices, x.values, w)


def _ms_segment_reduce(x: ModelShardedSparse, w: Array, square: bool) -> Array:
    """X^T w / (X*X)^T w as a contiguous segment reduction over the
    column-sorted CSC view: gather w by row, prefix-sum in sorted order,
    difference at the precomputed column boundaries. Equivalent to a
    sorted ``segment_sum`` but lowering to two contiguous passes instead
    of per-segment bookkeeping (measured ~5x over segment_sum and ~30x
    over the serialized scatter-add on the CPU backend at bench shapes).
    Pad entries carry value 0 at row 0 and sit past every column's end
    pointer, so they vanish from both the gather-product and the
    boundary differences."""
    sample = ((x.dcn_axis, x.data_axis) if x.dcn_axis is not None
              else x.data_axis)
    csc = PartitionSpec(x.model_axis, sample, None)
    model_vec = PartitionSpec(x.model_axis)
    data_vec = PartitionSpec(sample)

    def f(rows, vals, ptr, wl):
        # rows/vals [1, 1, m] (this chip's block), ptr [1, 1, S+1],
        # wl [n_local] = this chip's slice of the per-sample weights
        v = vals[0, 0]
        if square:
            v0 = v.astype(wl.dtype)  # promote BEFORE squaring (see above)
            v = v0 * v0
        wg = wl.at[rows[0, 0]].get(mode="promise_in_bounds")
        cs = jnp.cumsum((v * wg).astype(wl.dtype))
        z = jnp.concatenate([jnp.zeros((1,), cs.dtype), cs])
        p = ptr[0, 0]
        g = (z.at[p[1:]].get(mode="promise_in_bounds")
             - z.at[p[:-1]].get(mode="promise_in_bounds"))
        return _ms_data_psum(x, g)

    return _shard_map(f, mesh=x.mesh,
                      in_specs=(csc, csc, csc, data_vec),
                      out_specs=model_vec)(x.csc_rows, x.csc_vals,
                                           x.csc_ptr, w)


def rmatvec(x: FeatureMatrix, w: Array, dim: int) -> Array:
    """``X^T w`` -> [d]; ``w`` is a per-sample weight vector [n]."""
    if isinstance(x, ModelShardedSparse):
        if x.csc_ptr is not None:
            return _ms_segment_reduce(x, w, square=False)
        return _ms_scatter(x, w, square=False)
    if isinstance(x, SparseFeatures):
        contrib = (x.values * w[:, None]).ravel()
        return jnp.zeros((dim,), dtype=contrib.dtype).at[x.indices.ravel()].add(contrib)
    # w @ X, not X.T @ w: algebraically identical, but the explicit
    # transpose forces XLA-CPU through a strided 0.1 GFLOP/s path
    # (measured 33x slower at 200k x 512); on TPU both lower to the same
    # MXU contraction
    return w @ x


def sq_rmatvec(x: FeatureMatrix, w: Array, dim: int) -> Array:
    """``(X * X)^T w`` -> [d] (elementwise square), for Hessian diagonals.
    Values promote to the weight dtype BEFORE squaring so narrow feature
    storage (bf16) doesn't round the squared Hessian terms."""
    if isinstance(x, ModelShardedSparse):
        if x.csc_ptr is not None:
            return _ms_segment_reduce(x, w, square=True)
        return _ms_scatter(x, w, square=True)
    if isinstance(x, SparseFeatures):
        v = x.values.astype(w.dtype)
        contrib = (v * v * w[:, None]).ravel()
        return jnp.zeros((dim,), dtype=contrib.dtype).at[x.indices.ravel()].add(contrib)
    xf = x.astype(w.dtype)
    return w @ (xf * xf)  # see rmatvec: avoid XLA-CPU's strided .T path


def weighted_gram(x: FeatureMatrix, w: Array, dim: int) -> Array:
    """``X^T diag(w) X`` -> [d, d], for small-dim full Hessians
    (reference: HessianMatrixAggregator.scala:31)."""
    if isinstance(x, ModelShardedSparse):
        raise NotImplementedError(
            "model-sharded sparse theta is matrix-free by design: a d x d "
            "Hessian would defeat the point of sharding theta")
    if isinstance(x, SparseFeatures):
        n, k = x.indices.shape
        if k <= 64:
            # per-slot scatter of the outer product: k scatters whose
            # temporaries are [n, k] — the same footprint as the data —
            # never an [n, dim] densification nor [n, k, k] blow-up
            # (the explicit-Hessian TRON path calls this per entity
            # under vmap, where big temps would dwarf the block)
            wv = w[:, None] * x.values                           # [n, k]
            h = jnp.zeros((dim, dim), wv.dtype)
            for j in range(k):  # k is a static ELL width, loop unrolls
                h = h.at[x.indices[:, j][:, None], x.indices].add(
                    wv[:, j][:, None] * x.values)
            return h
        dense = to_dense(x, dim)
        return dense.T @ (dense * w[:, None])
    return x.T @ (x * w[:, None])


def to_dense(x: FeatureMatrix, dim: int) -> Array:
    if isinstance(x, ModelShardedSparse):
        raise NotImplementedError("refusing to densify model-sharded features")
    if isinstance(x, SparseFeatures):
        n, k = x.indices.shape
        out = jnp.zeros((n, dim), dtype=x.values.dtype)
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, k))
        return out.at[rows.ravel(), x.indices.ravel()].add(x.values.ravel())
    return x


def partition_by_feature_range(
    sf: SparseFeatures, dim: int, n_shards: int, pad_multiple: int = 1
) -> tuple:
    """Host-side ingest step for model-parallel sparse training: split each
    ELL row's nonzeros into ``n_shards`` contiguous feature ranges with
    LOCAL column ids (the reference's partitioned-PalDB layout,
    PalDBIndexMapBuilder.scala:27, re-done as static arrays).

    Returns ``(indices [P, n, kp], values [P, n, kp], shard_size)`` as
    numpy arrays; kp is the worst-case per-(row, range) nonzero count,
    padded to ``pad_multiple``. Index maps that hash feature names over the
    id space keep ranges load-balanced — partitioning is by id range, the
    hashing already happened at index build.
    """
    idx = np.asarray(sf.indices)
    val = np.asarray(sf.values)
    n, k = idx.shape
    shard_size = -(-dim // n_shards)  # ceil
    if k == 0 or n == 0:
        kp = max(pad_multiple, 1)
        return (np.zeros((n_shards, n, kp), np.int32),
                np.zeros((n_shards, n, kp), val.dtype), shard_size)
    shard_of = idx // shard_size                       # [n, k]
    # ELL pad slots (value 0) must not inflate kp: route them to a virtual
    # shard n_shards, which sorts last and is truncated after scatter
    shard_of = np.where(val == 0, n_shards, shard_of)
    order = np.argsort(shard_of, axis=1, kind="stable")
    shard_sorted = np.take_along_axis(shard_of, order, 1)
    idx_sorted = np.take_along_axis(idx, order, 1)
    val_sorted = np.take_along_axis(val, order, 1)
    js = np.broadcast_to(np.arange(k), (n, k))
    new_group = np.concatenate(
        [np.ones((n, 1), bool), shard_sorted[:, 1:] != shard_sorted[:, :-1]], 1)
    group_start = np.maximum.accumulate(np.where(new_group, js, 0), axis=1)
    pos = js - group_start                             # slot within (row, range)
    real = shard_sorted < n_shards
    kp = int(pos[real].max()) + 1 if real.any() else 1
    kp = -(-kp // pad_multiple) * pad_multiple
    out_idx = np.zeros((n_shards + 1, n, max(kp, int(pos.max()) + 1)), np.int32)
    out_val = np.zeros_like(out_idx, dtype=val.dtype)
    rows = np.broadcast_to(np.arange(n)[:, None], (n, k))
    out_idx[shard_sorted, rows, pos] = idx_sorted - shard_sorted * shard_size
    out_val[shard_sorted, rows, pos] = val_sorted
    # drop the virtual pad shard and the slots only it used
    return (np.ascontiguousarray(out_idx[:n_shards, :, :kp]),
            np.ascontiguousarray(out_val[:n_shards, :, :kp]), shard_size)


def build_csc_plan(
    sf: SparseFeatures, dim: int, n_shards: int, n_chunks: int
) -> tuple:
    """Host-side companion of ``partition_by_feature_range``: the SAME
    nonzeros re-laid-out column-sorted per (model-shard, data-chunk)
    block, so the transposed products run as contiguous segment
    reductions on device (``_ms_segment_reduce``).

    Chunk c covers rows [c·n/C, (c+1)·n/C) — the contiguous row block a
    (dcn, data) device slice owns. Returns numpy arrays
    ``(rows [P, C, m], vals [P, C, m], ptr [P, C, S+1])`` where ``m`` is
    the worst-case per-block real-nonzero count, ``rows`` are chunk-LOCAL
    sample ids sorted by shard-LOCAL column within each block, and
    ``ptr[p, c, j]`` is the first sorted slot of local column j (ptr[S] =
    the block's real count). Pad slots hold (row 0, value 0) past every
    column's end — inert in both the gather-product and the boundary
    differences. ELL pad slots (value 0) are excluded entirely."""
    idx = np.asarray(sf.indices)
    val = np.asarray(sf.values)
    n, k = idx.shape
    shard_size = -(-dim // n_shards)
    if n % n_chunks:
        raise ValueError(f"sample count {n} must divide into {n_chunks} "
                         "data chunks; pad the batch first")
    n_loc = n // n_chunks
    if n == 0 or k == 0:
        return (np.zeros((n_shards, n_chunks, 1), np.int32),
                np.zeros((n_shards, n_chunks, 1), val.dtype),
                np.zeros((n_shards, n_chunks, shard_size + 1), np.int32))
    real = val.ravel() != 0
    flat_idx = idx.ravel()[real].astype(np.int64)
    rows_g = np.broadcast_to(np.arange(n)[:, None], (n, k)).ravel()[real]
    vals_f = val.ravel()[real]
    shard_of = flat_idx // shard_size
    chunk_of = rows_g // n_loc
    local_col = flat_idx - shard_of * shard_size
    # single sort key: (shard, chunk, local column) — one lexsort pass
    key = (shard_of * n_chunks + chunk_of) * shard_size + local_col
    order = np.argsort(key, kind="stable")
    key_s = key[order]
    rows_s = (rows_g[order] - chunk_of[order] * n_loc).astype(np.int32)
    vals_s = vals_f[order]
    # column boundaries per block from one bincount over the full key
    # space; block sizes from its per-block reduction
    counts = np.bincount(key_s, minlength=n_shards * n_chunks * shard_size)
    counts = counts.reshape(n_shards, n_chunks, shard_size)
    block_sizes = counts.sum(axis=-1)
    m = max(int(block_sizes.max()), 1)
    ptr = np.zeros((n_shards, n_chunks, shard_size + 1), np.int32)
    np.cumsum(counts, axis=-1, out=ptr[:, :, 1:])
    # scatter sorted entries into fixed-width blocks
    block_of = key_s // shard_size            # flat (shard, chunk) id
    starts = np.zeros(n_shards * n_chunks + 1, np.int64)
    np.cumsum(block_sizes.ravel(), out=starts[1:])
    pos = np.arange(key_s.size) - starts[block_of]
    rows_out = np.zeros((n_shards, n_chunks, m), np.int32)
    vals_out = np.zeros((n_shards, n_chunks, m), val.dtype)
    p_i, c_i = block_of // n_chunks, block_of % n_chunks
    rows_out[p_i, c_i, pos] = rows_s
    vals_out[p_i, c_i, pos] = vals_s
    return rows_out, vals_out, ptr


def from_csr_arrays(indptr, cols, vals, max_nnz: int | None = None,
                    dtype=np.float32) -> SparseFeatures:
    """Host-side: raw CSR arrays -> padded ELL (vectorized; the zero-copy
    variant of from_scipy_csr for the native columnar ingest)."""
    indptr = np.asarray(indptr, np.int64)
    n = len(indptr) - 1
    row_nnz = np.diff(indptr)
    widest = int(row_nnz.max()) if n else 0
    k = int(max_nnz) if max_nnz is not None else widest
    if widest > k:
        raise ValueError(f"row has {widest} nonzeros > max_nnz={k}; "
                         "refusing to silently truncate features")
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=dtype)
    if n and k:
        slot = np.arange(k)[None, :]
        mask = slot < row_nnz[:, None]
        src = indptr[:-1, None] + slot
        indices[mask] = np.asarray(cols)[src[mask]]
        values[mask] = np.asarray(vals)[src[mask]]
    return SparseFeatures(indices=jnp.asarray(indices),
                          values=jnp.asarray(values))


def from_scipy_csr(csr, max_nnz: int | None = None, dtype=np.float32) -> SparseFeatures:
    """Host-side: scipy CSR -> padded ELL arrays (vectorized).

    ``max_nnz`` pads/clips the row width; rows with more nonzeros than
    ``max_nnz`` are rejected — silent feature truncation would corrupt
    margins. Callers that want capping must subsample explicitly.
    """
    csr = csr.tocsr()
    n = csr.shape[0]
    row_nnz = np.diff(csr.indptr)
    widest = int(row_nnz.max()) if n else 0
    k = int(max_nnz) if max_nnz is not None else widest
    if widest > k:
        raise ValueError(f"row has {widest} nonzeros > max_nnz={k}; "
                         "refusing to silently truncate features")
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=dtype)
    if n and k:
        cols = np.arange(k)[None, :]
        mask = cols < row_nnz[:, None]
        src = csr.indptr[:-1, None] + cols
        indices[mask] = csr.indices[src[mask]]
        values[mask] = csr.data[src[mask]]
    return SparseFeatures(indices=jnp.asarray(indices), values=jnp.asarray(values))


def from_rows(rows, dim: int, dtype=np.float32, max_nnz: int | None = None) -> SparseFeatures:
    """Host-side: list of (indices, values) pairs -> padded ELL arrays."""
    n = len(rows)
    widest = max((len(r[0]) for r in rows), default=0)
    k = max_nnz if max_nnz is not None else widest
    if widest > k:
        raise ValueError(f"row has {widest} nonzeros > max_nnz={k}; "
                         "refusing to silently truncate features")
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=dtype)
    for i, (idx, val) in enumerate(rows):
        m = len(idx)
        indices[i, :m] = np.asarray(idx, dtype=np.int32)
        values[i, :m] = np.asarray(val, dtype=dtype)
    del dim  # shape is carried by coefficient vectors, not the ELL arrays
    return SparseFeatures(indices=jnp.asarray(indices), values=jnp.asarray(values))
