"""Pallas TPU kernels: fused GLM value+gradient in ONE pass over X.

The XLA path (ops/aggregators.py value_and_gradient) lowers to two
separate contractions over the feature matrix — ``margins = X @ coef``
and ``grad = X^T (w * dz)`` — so every objective evaluation streams X
from HBM twice. A GLM solve at fixed-effect shapes is HBM-bandwidth-
bound (bench fe_throughput: ~80% of v5e HBM peak), which makes the
second pass pure waste: dz depends only on each row's own margin, so
the gradient contraction can consume the SAME VMEM-resident tile of X
that just produced the margins.

``fused_dense_value_grad`` tiles X over rows; per grid step it computes
``m = X_tile @ coef`` (MXU), the pointwise loss/dz (VPU), and
accumulates ``value += sum(w*l)`` and ``grad += X_tile^T (w*dz)``
(MXU) into carried output blocks — X is read from HBM exactly once.
Theoretical ceiling vs the XLA path on a bandwidth-bound solve: 2x.

``fused_sparse_value_grad`` extends the same single-HBM-pass structure
to padded-ELL sparse rows: each grid step reads one [T, K] tile of the
nnz stream (indices + values) ONCE, expands it into a VMEM-resident
dense [T, D] tile via a static-K unrolled one-hot accumulation
(``broadcasted_iota`` compare — MXU/VPU-lowerable, never touches HBM),
then runs the identical margins/loss/grad flow on that tile. The XLA
sparse arm instead gathers theta for margins and scatter-adds the
gradient — two passes over the nnz stream plus a serialized scatter.
The VMEM tile bounds the supported coefficient dimension
(``_MAX_SPARSE_DIM``); larger models stay on the CSC segment-sum path.

Scope: identity normalization, f32 coefficients, dense f32/bf16 or
ELL-sparse features. Callers opt in via ``PHOTON_TPU_PALLAS_GLM=1``
(see ops/aggregators.py); correctness is pinned by interpret-mode
parity tests against the XLA path (tests/test_pallas_glm.py) which run
on every backend.

Reference semantics: ValueAndGradientAggregator.scala:36-80 (the same
fused margin/loss/grad algebra, minus the normalization prefactors).
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

_TILE_N = 1024
_TILE_N_SPARSE = 256
_TILE_B_SERVING = 128
# the sparse kernel's VMEM working set is the expanded [T, D] tile:
# 256 x 4096 x 4B = 4 MiB — comfortably inside a v5e core's 16 MiB
_MAX_SPARSE_DIM = 4096

# trace-time kill switch: pallas_call carries no sharding annotations, so
# a mesh-sharded SPMD solve must never pick the kernel up (it would force
# replication of X or fail at lowering). GlmOptimizationProblem wraps
# mesh solves in ``disabled()``; the flag is a ContextVar so it binds at
# TRACE time, exactly like the env flag it refines.
_TRACE_DISABLED = contextvars.ContextVar("pallas_glm_disabled",
                                         default=False)


@contextlib.contextmanager
def disabled():
    token = _TRACE_DISABLED.set(True)
    try:
        yield
    finally:
        _TRACE_DISABLED.reset(token)


def _supported(x, norm, coef) -> bool:
    """Dense 2D f32 features AND f32 coefficients, identity
    normalization, NOT under vmap, NOT inside a ``disabled()`` (mesh)
    region. The vmap exclusion: the kernel's sequential-grid accumulation
    (init on program_id 0, += into a revisited output block) assumes it
    owns the whole grid, which a batching transform breaks (the
    random-effect path vmaps the objective over dense-local entity
    blocks). The coef-dtype exclusion: an f64 solve over f32 features
    promotes in the XLA path, while the kernel would silently return f32
    and break the while_loop carry dtype at trace time."""
    if _TRACE_DISABLED.get():
        return False
    try:
        from jax.interpreters.batching import BatchTracer
        if isinstance(x, BatchTracer) or isinstance(coef, BatchTracer):
            return False
    except ImportError:  # pragma: no cover — jax internals moved
        if type(x).__name__ == "BatchTracer":
            return False
    return (isinstance(x, jax.Array) and x.ndim == 2
            and x.dtype in (jnp.float32, jnp.bfloat16)
            and coef.dtype == jnp.float32
            and norm.is_identity)


@functools.partial(jax.jit, static_argnums=(0, 5, 6))
def _fused(loss_and_dz, x, labels, offsets, weights, tile_n: int,
           interpret: bool, coef):
    from jax.experimental import pallas as pl

    n, d = x.shape

    def kernel(x_ref, y_ref, off_ref, w_ref, coef_ref, val_ref, grad_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            val_ref[0, 0] = jnp.float32(0.0)
            grad_ref[:] = jnp.zeros_like(grad_ref)

        # one MXU pass for margins; the tile of X stays in VMEM for the
        # gradient contraction below — HBM reads X exactly once. bf16
        # feature storage composes: the tile is read at half the bytes
        # and the MXU accumulates in f32 (preferred_element_type).
        m = jnp.dot(x_ref[:], coef_ref[:],
                    preferred_element_type=jnp.float32)       # [T, 1]
        z = m + off_ref[:]
        l, dz = loss_and_dz(z, y_ref[:])
        w = w_ref[:]
        val_ref[0, 0] += jnp.sum(l * w)
        # grad += X_tile^T (w * dz): contract over the row axis. The
        # VMEM-resident tile upcasts in-register for bf16 storage
        # (lax.dot_general is strict about operand dtypes).
        grad_ref[:] += jax.lax.dot_general(
            x_ref[:].astype(jnp.float32), w * dz,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [D, 1]

    grid = (n // tile_n,)
    value, grad = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_n, d), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((d, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, labels, offsets, weights, coef.reshape(d, 1))
    return value[0, 0], grad[:, 0]


def fused_dense_value_grad(
    loss,
    x: Array,
    labels: Array,
    offsets: Optional[Array],
    weights: Optional[Array],
    coef: Array,
    *,
    tile_n: int = _TILE_N,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Weighted loss value and gradient, X streamed from HBM once.

    Drop-in for the un-normalized dense case of
    ``aggregators.value_and_gradient`` (no L2 term — the objective adds
    it, as with the XLA path). Rows are padded to the tile size with
    zero-weight samples, which contribute nothing to either output.
    """
    if interpret is None:
        # the sequential-grid accumulation idiom (init on i==0, += on a
        # revisited output block) is a TPU guarantee; every other backend
        # gets exact interpret-mode semantics
        interpret = jax.default_backend() != "tpu"
    n, d = x.shape
    if n == 0:
        # grid=(0,) would skip the kernel entirely and return
        # uninitialized buffers; match the XLA path's empty-sum contract
        zero = jnp.zeros((), jnp.float32)
        return zero, jnp.zeros((d,), jnp.float32)
    tile = min(tile_n, max(8, n))
    pad = (-n) % tile
    y = jnp.asarray(labels, jnp.float32)
    off = (jnp.zeros((n,), jnp.float32) if offsets is None
           else jnp.asarray(offsets, jnp.float32))
    w = (jnp.ones((n,), jnp.float32) if weights is None
         else jnp.asarray(weights, jnp.float32))
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad))
        off = jnp.pad(off, (0, pad))
        w = jnp.pad(w, (0, pad))        # zero weight: no contribution
    npad = n + pad
    return _fused(loss.loss_and_dz, x, y.reshape(npad, 1),
                  off.reshape(npad, 1), w.reshape(npad, 1), tile,
                  bool(interpret), jnp.asarray(coef, jnp.float32))


def _supported_sparse(x, norm, coef) -> bool:
    """ELL-sparse analogue of ``_supported``: padded-ELL features with
    f32/bf16 values AND f32 coefficients, identity normalization, a
    coefficient dimension the VMEM expansion tile can hold, NOT under
    vmap, NOT inside a ``disabled()`` (mesh) region. Larger dimensions
    stay on the CSC segment-sum XLA path — expanding a [T, D] tile that
    overflows VMEM would spill to HBM and forfeit the single pass."""
    from photon_tpu.ops.features import SparseFeatures
    if _TRACE_DISABLED.get():
        return False
    if not isinstance(x, SparseFeatures):
        return False
    idx, val = x.indices, x.values
    try:
        from jax.interpreters.batching import BatchTracer
        if (isinstance(idx, BatchTracer) or isinstance(val, BatchTracer)
                or isinstance(coef, BatchTracer)):
            return False
    except ImportError:  # pragma: no cover — jax internals moved
        if type(val).__name__ == "BatchTracer":
            return False
    return (isinstance(val, jax.Array) and val.ndim == 2
            and val.dtype in (jnp.float32, jnp.bfloat16)
            and coef.dtype == jnp.float32
            and coef.shape[0] <= _MAX_SPARSE_DIM
            and norm.is_identity)


@functools.partial(jax.jit, static_argnums=(0, 6, 7))
def _fused_sparse(loss_and_dz, idx, val, labels, offsets, weights,
                  tile_n: int, interpret: bool, coef):
    from jax.experimental import pallas as pl

    n, k = idx.shape
    d = coef.shape[0]

    def kernel(idx_ref, val_ref, y_ref, off_ref, w_ref, coef_ref,
               val_out_ref, grad_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            val_out_ref[0, 0] = jnp.float32(0.0)
            grad_ref[:] = jnp.zeros_like(grad_ref)

        # expand this tile's nnz into a VMEM-resident dense [T, D] tile:
        # static-K unrolled one-hot accumulation, iota-compare per slot.
        # ELL pad slots (index 0, value 0) contribute exactly zero, and
        # duplicate column ids within a row accumulate — both match the
        # XLA gather/scatter semantics bit for bit in f32.
        cols = jax.lax.broadcasted_iota(jnp.int32, (tile_n, d), 1)
        dense = jnp.zeros((tile_n, d), jnp.float32)
        for j in range(k):  # k is a static ELL width, loop unrolls
            onehot = (cols == idx_ref[:, j:j + 1]).astype(jnp.float32)
            dense = dense + onehot * val_ref[:, j:j + 1].astype(jnp.float32)

        # from here the flow is the dense kernel's: the expanded tile
        # feeds BOTH contractions, so the nnz stream was read from HBM
        # exactly once
        m = jnp.dot(dense, coef_ref[:],
                    preferred_element_type=jnp.float32)       # [T, 1]
        z = m + off_ref[:]
        l, dz = loss_and_dz(z, y_ref[:])
        w = w_ref[:]
        val_out_ref[0, 0] += jnp.sum(l * w)
        grad_ref[:] += jax.lax.dot_general(
            dense, w * dz,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [D, 1]

    grid = (n // tile_n,)
    value, grad = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_n, k), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, k), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((d, 1), jnp.float32),
        ],
        interpret=interpret,
    )(idx, val, labels, offsets, weights, coef.reshape(d, 1))
    return value[0, 0], grad[:, 0]


def fused_sparse_value_grad(
    loss,
    x,
    labels: Array,
    offsets: Optional[Array],
    weights: Optional[Array],
    coef: Array,
    *,
    tile_n: int = _TILE_N_SPARSE,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Weighted loss value and gradient over padded-ELL sparse rows,
    the nnz stream read from HBM once.

    Drop-in for the un-normalized ELL case of
    ``aggregators.value_and_gradient`` (no L2 term — the objective adds
    it, as with the XLA path). Rows are padded to the tile size with
    zero-weight all-pad rows, which contribute nothing to either
    output; rows whose slots are ALL pads (empty segments) likewise
    contribute only their offset's loss, exactly like the XLA path.
    """
    if interpret is None:
        # sequential-grid accumulation is a TPU guarantee; every other
        # backend gets exact interpret-mode semantics (see _fused)
        interpret = jax.default_backend() != "tpu"
    idx, val = x.indices, x.values
    n, k = idx.shape
    d = coef.shape[0]
    if n == 0:
        zero = jnp.zeros((), jnp.float32)
        return zero, jnp.zeros((d,), jnp.float32)
    if k == 0:
        # width-zero ELL (every row an empty segment): pad one inert
        # slot so the tile shapes stay non-degenerate
        idx = jnp.zeros((n, 1), jnp.int32)
        val = jnp.zeros((n, 1), jnp.float32)
        k = 1
    tile = min(tile_n, max(8, n))
    pad = (-n) % tile
    y = jnp.asarray(labels, jnp.float32)
    off = (jnp.zeros((n,), jnp.float32) if offsets is None
           else jnp.asarray(offsets, jnp.float32))
    w = (jnp.ones((n,), jnp.float32) if weights is None
         else jnp.asarray(weights, jnp.float32))
    if pad:
        idx = jnp.pad(idx, ((0, pad), (0, 0)))
        val = jnp.pad(val, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad))
        off = jnp.pad(off, (0, pad))
        w = jnp.pad(w, (0, pad))        # zero weight: no contribution
    npad = n + pad
    return _fused_sparse(loss.loss_and_dz, idx, val, y.reshape(npad, 1),
                         off.reshape(npad, 1), w.reshape(npad, 1), tile,
                         bool(interpret), jnp.asarray(coef, jnp.float32))


def _supported_serving(theta: Array, slot_width: int) -> bool:
    """Serving gather+margin gate: f32 coefficient vector small enough
    for the VMEM one-hot expansion tile, at least one gather slot, NOT
    inside a ``disabled()`` region. Evaluated once per scorer program at
    build time — the serving tables/batches are concrete by contract."""
    if _TRACE_DISABLED.get():
        return False
    return (slot_width >= 1
            and theta.ndim == 1
            and theta.dtype == jnp.float32
            and theta.shape[0] <= _MAX_SPARSE_DIM)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _fused_margin(idx, val, offsets, tile_b: int, interpret: bool, theta):
    from jax.experimental import pallas as pl

    n, k = idx.shape
    d = theta.shape[0]

    def kernel(idx_ref, val_ref, off_ref, theta_ref, out_ref):
        # same one-hot expansion as the sparse training kernel: the
        # request tile's (index, value) slots are read from HBM once and
        # expanded in VMEM; the margin is one MXU contraction against
        # the pinned coefficient vector. Pad slots (0, 0.0) and pad rows
        # contribute exactly zero.
        cols = jax.lax.broadcasted_iota(jnp.int32, (tile_b, d), 1)
        dense = jnp.zeros((tile_b, d), jnp.float32)
        for j in range(k):  # k is the static padded slot width
            onehot = (cols == idx_ref[:, j:j + 1]).astype(jnp.float32)
            dense = dense + onehot * val_ref[:, j:j + 1].astype(jnp.float32)
        out_ref[:] = jnp.dot(dense, theta_ref[:],
                             preferred_element_type=jnp.float32) + off_ref[:]

    grid = (n // tile_b,)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_b, k), lambda i: (i, 0)),
            pl.BlockSpec((tile_b, k), lambda i: (i, 0)),
            pl.BlockSpec((tile_b, 1), lambda i: (i, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_b, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        interpret=interpret,
    )(idx, val, offsets, theta.reshape(d, 1))
    return out[:, 0]


def fused_gather_margin(
    idx: Array,
    val: Array,
    offsets: Optional[Array],
    theta: Array,
    *,
    tile_b: int = _TILE_B_SERVING,
    interpret: Optional[bool] = None,
) -> Array:
    """Fixed-effect serving margins ``offsets + sum_j val[:, j] *
    theta[idx[:, j]]`` with the request tile read from HBM once.

    Drop-in for the serving scorer's per-shard gathered dot
    (serving/scorer.py): the caller concatenates every fixed shard's
    padded (index, value) slots with the shard's offset into one
    coefficient vector, so the whole fixed-effect margin is ONE kernel
    per batch instead of a gather + multiply + reduce per shard."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, k = idx.shape
    if n == 0:
        return jnp.zeros((0,), jnp.float32)
    if k == 0:
        idx = jnp.zeros((n, 1), jnp.int32)
        val = jnp.zeros((n, 1), jnp.float32)
        k = 1
    off = (jnp.zeros((n,), jnp.float32) if offsets is None
           else jnp.asarray(offsets, jnp.float32))
    tile = min(tile_b, max(8, n))
    pad = (-n) % tile
    if pad:
        idx = jnp.pad(idx, ((0, pad), (0, 0)))
        val = jnp.pad(val, ((0, pad), (0, 0)))
        off = jnp.pad(off, (0, pad))
    npad = n + pad
    out = _fused_margin(idx, val.astype(jnp.float32),
                        off.reshape(npad, 1), tile, bool(interpret),
                        jnp.asarray(theta, jnp.float32))
    return out[:n]
