"""Pallas TPU kernel: fused dense GLM value+gradient in ONE pass over X.

The XLA path (ops/aggregators.py value_and_gradient) lowers to two
separate contractions over the feature matrix — ``margins = X @ coef``
and ``grad = X^T (w * dz)`` — so every objective evaluation streams X
from HBM twice. A GLM solve at fixed-effect shapes is HBM-bandwidth-
bound (bench fe_throughput: ~80% of v5e HBM peak), which makes the
second pass pure waste: dz depends only on each row's own margin, so
the gradient contraction can consume the SAME VMEM-resident tile of X
that just produced the margins.

This kernel tiles X over rows; per grid step it computes
``m = X_tile @ coef`` (MXU), the pointwise loss/dz (VPU), and
accumulates ``value += sum(w*l)`` and ``grad += X_tile^T (w*dz)``
(MXU) into carried output blocks — X is read from HBM exactly once.
Theoretical ceiling vs the XLA path on a bandwidth-bound solve: 2x.

Scope: dense [N, D] features, identity normalization, f32. The sparse
ELL path keeps the XLA gather/scatter kernels (its bottleneck is the
scatter, not a second stream of X). Callers opt in via
``PHOTON_TPU_PALLAS_GLM=1`` (see ops/aggregators.py); correctness is
pinned by interpret-mode parity tests against the XLA path
(tests/test_pallas_glm.py) which run on every backend.

Reference semantics: ValueAndGradientAggregator.scala:36-80 (the same
fused margin/loss/grad algebra, minus the normalization prefactors).
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

_TILE_N = 1024

# trace-time kill switch: pallas_call carries no sharding annotations, so
# a mesh-sharded SPMD solve must never pick the kernel up (it would force
# replication of X or fail at lowering). GlmOptimizationProblem wraps
# mesh solves in ``disabled()``; the flag is a ContextVar so it binds at
# TRACE time, exactly like the env flag it refines.
_TRACE_DISABLED = contextvars.ContextVar("pallas_glm_disabled",
                                         default=False)


@contextlib.contextmanager
def disabled():
    token = _TRACE_DISABLED.set(True)
    try:
        yield
    finally:
        _TRACE_DISABLED.reset(token)


def _supported(x, norm, coef) -> bool:
    """Dense 2D f32 features AND f32 coefficients, identity
    normalization, NOT under vmap, NOT inside a ``disabled()`` (mesh)
    region. The vmap exclusion: the kernel's sequential-grid accumulation
    (init on program_id 0, += into a revisited output block) assumes it
    owns the whole grid, which a batching transform breaks (the
    random-effect path vmaps the objective over dense-local entity
    blocks). The coef-dtype exclusion: an f64 solve over f32 features
    promotes in the XLA path, while the kernel would silently return f32
    and break the while_loop carry dtype at trace time."""
    if _TRACE_DISABLED.get():
        return False
    try:
        from jax.interpreters.batching import BatchTracer
        if isinstance(x, BatchTracer) or isinstance(coef, BatchTracer):
            return False
    except ImportError:  # pragma: no cover — jax internals moved
        if type(x).__name__ == "BatchTracer":
            return False
    return (isinstance(x, jax.Array) and x.ndim == 2
            and x.dtype in (jnp.float32, jnp.bfloat16)
            and coef.dtype == jnp.float32
            and norm.is_identity)


@functools.partial(jax.jit, static_argnums=(0, 5, 6))
def _fused(loss_and_dz, x, labels, offsets, weights, tile_n: int,
           interpret: bool, coef):
    from jax.experimental import pallas as pl

    n, d = x.shape

    def kernel(x_ref, y_ref, off_ref, w_ref, coef_ref, val_ref, grad_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            val_ref[0, 0] = jnp.float32(0.0)
            grad_ref[:] = jnp.zeros_like(grad_ref)

        # one MXU pass for margins; the tile of X stays in VMEM for the
        # gradient contraction below — HBM reads X exactly once. bf16
        # feature storage composes: the tile is read at half the bytes
        # and the MXU accumulates in f32 (preferred_element_type).
        m = jnp.dot(x_ref[:], coef_ref[:],
                    preferred_element_type=jnp.float32)       # [T, 1]
        z = m + off_ref[:]
        l, dz = loss_and_dz(z, y_ref[:])
        w = w_ref[:]
        val_ref[0, 0] += jnp.sum(l * w)
        # grad += X_tile^T (w * dz): contract over the row axis. The
        # VMEM-resident tile upcasts in-register for bf16 storage
        # (lax.dot_general is strict about operand dtypes).
        grad_ref[:] += jax.lax.dot_general(
            x_ref[:].astype(jnp.float32), w * dz,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [D, 1]

    grid = (n // tile_n,)
    value, grad = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_n, d), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((d, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, labels, offsets, weights, coef.reshape(d, 1))
    return value[0, 0], grad[:, 0]


def fused_dense_value_grad(
    loss,
    x: Array,
    labels: Array,
    offsets: Optional[Array],
    weights: Optional[Array],
    coef: Array,
    *,
    tile_n: int = _TILE_N,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Weighted loss value and gradient, X streamed from HBM once.

    Drop-in for the un-normalized dense case of
    ``aggregators.value_and_gradient`` (no L2 term — the objective adds
    it, as with the XLA path). Rows are padded to the tile size with
    zero-weight samples, which contribute nothing to either output.
    """
    if interpret is None:
        # the sequential-grid accumulation idiom (init on i==0, += on a
        # revisited output block) is a TPU guarantee; every other backend
        # gets exact interpret-mode semantics
        interpret = jax.default_backend() != "tpu"
    n, d = x.shape
    if n == 0:
        # grid=(0,) would skip the kernel entirely and return
        # uninitialized buffers; match the XLA path's empty-sum contract
        zero = jnp.zeros((), jnp.float32)
        return zero, jnp.zeros((d,), jnp.float32)
    tile = min(tile_n, max(8, n))
    pad = (-n) % tile
    y = jnp.asarray(labels, jnp.float32)
    off = (jnp.zeros((n,), jnp.float32) if offsets is None
           else jnp.asarray(offsets, jnp.float32))
    w = (jnp.ones((n,), jnp.float32) if weights is None
         else jnp.asarray(weights, jnp.float32))
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad))
        off = jnp.pad(off, (0, pad))
        w = jnp.pad(w, (0, pad))        # zero weight: no contribution
    npad = n + pad
    return _fused(loss.loss_and_dz, x, y.reshape(npad, 1),
                  off.reshape(npad, 1), w.reshape(npad, 1), tile,
                  bool(interpret), jnp.asarray(coef, jnp.float32))
