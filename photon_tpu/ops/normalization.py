"""Feature normalization as pure algebra folded into the training kernels.

Reference: photon-lib normalization/NormalizationContext.scala:37,80-126 and
NormalizationType.scala:26-41. The transformed feature is

    x' = (x - shift) * factor          (identity on the intercept column)

and optimizers run in *transformed* coefficient space while the data stays
raw: the aggregators (ops/aggregators.py) fold the affine map in
algebraically, exactly as ValueAndGradientAggregator.scala:36-80 does with
``effectiveCoefficients`` and the margin-shift prefactor. This module holds
the context plus the model <-> transformed-space conversions that keep
margins invariant (NormalizationContext.scala:80-100).
"""

from __future__ import annotations

import enum
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

Array = jax.Array


class NormalizationType(enum.Enum):
    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


class NormalizationContext(NamedTuple):
    """``factors``/``shifts`` are [d] arrays or None; intercept slots (if an
    intercept column exists) must hold factor=1, shift=0 — enforced by the
    builders below."""

    factors: Optional[Array] = None
    shifts: Optional[Array] = None

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    # -- coefficient-space conversions (margin-invariant) -------------------

    def model_to_transformed_space(self, coef: Array,
                                   intercept_index: Optional[int] = None) -> Array:
        """Original-space model -> transformed-space coefficients."""
        out = coef
        if self.factors is not None:
            out = out / self.factors
        if self.shifts is not None and intercept_index is not None:
            out = out.at[intercept_index].add(jnp.dot(coef, self.shifts))
        return out

    def transformed_space_to_model(self, coef: Array,
                                   intercept_index: Optional[int] = None) -> Array:
        """Transformed-space coefficients -> original-space model."""
        eff = coef * self.factors if self.factors is not None else coef
        out = eff
        if self.shifts is not None and intercept_index is not None:
            out = out.at[intercept_index].add(-jnp.dot(eff, self.shifts))
        return out


def no_normalization() -> NormalizationContext:
    return NormalizationContext(None, None)


def build_normalization_context(
    norm_type: NormalizationType,
    mean: Array,
    variance: Array,
    abs_max: Array,
    intercept_index: Optional[int] = None,
) -> NormalizationContext:
    """Build a context from feature statistics
    (reference: NormalizationContext factory from FeatureDataStatistics)."""
    std = jnp.sqrt(variance)
    inv_std = 1.0 / jnp.where(std > 0, std, 1.0)
    inv_mag = 1.0 / jnp.where(abs_max > 0, abs_max, 1.0)

    factors: Optional[Array]
    shifts: Optional[Array]
    if norm_type == NormalizationType.NONE:
        return no_normalization()
    if norm_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factors, shifts = inv_std, None
    elif norm_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factors, shifts = inv_mag, None
    elif norm_type == NormalizationType.STANDARDIZATION:
        factors, shifts = inv_std, mean
    else:  # pragma: no cover
        raise ValueError(f"unknown normalization type {norm_type}")

    if intercept_index is not None:
        if factors is not None:
            factors = factors.at[intercept_index].set(1.0)
        if shifts is not None:
            shifts = shifts.at[intercept_index].set(0.0)
    return NormalizationContext(factors, shifts)
