"""The four fused GLM compute kernels.

These replace the reference's treeAggregate kernels — the hot loops of the
whole system (reference: photon-lib function/glm/ValueAndGradientAggregator
.scala:34, HessianVectorAggregator.scala:37, HessianDiagonalAggregator
.scala:33, HessianMatrixAggregator.scala:31). On Spark each is a per-sample
``seqOp`` plus a tree merge; here each is one fused XLA computation over a
batch: margins via matvec (MXU), pointwise loss, and a transposed matvec.
Under jit with batch-sharded inputs and replicated coefficients, the
``jnp.sum`` reductions lower to ``psum`` over the mesh's ICI — the
treeAggregate equivalent.

Normalization is folded in algebraically, exactly mirroring the reference's
effective-coefficient + prefactor trick (ValueAndGradientAggregator
.scala:36-80): with x' = (x - shift) * factor and e = coef * factor,

    margin_i = e . x_i - e . shift + offset_i
    d value / d coef_j = factor_j [ sum_i w_i l'_i x_ij ] - (sum_i w_i l'_i) factor_j shift_j

so the raw data is never rescaled on device.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.ops.features import (
    FeatureMatrix,
    SparseFeatures,
    matvec,
    rmatvec,
    sq_rmatvec,
    weighted_gram,
)
from photon_tpu.ops.losses import PointwiseLoss
from photon_tpu.ops.normalization import NormalizationContext

Array = jax.Array

_WARNED_REFUSED: set = set()


def _kernel_counter(name: str, path: str) -> None:
    """Tick a kernel-activation counter. Runs at TRACE time (the routing
    decision is a Python branch), so the count is per compiled program,
    not per execution — exactly what "did this solve use the fused
    kernel" needs, with zero on-device cost."""
    from photon_tpu.obs.metrics import registry
    registry.counter(f"kernels.{name}", path=path).inc()


def _warn_kernel_refused(path: str) -> None:
    """Warn ONCE per path when PHOTON_TPU_PALLAS_GLM=1 asked for the
    fused kernel but ``_supported`` refused the operands — a silent
    performance downgrade the counters record and this makes audible."""
    if path in _WARNED_REFUSED:
        return
    _WARNED_REFUSED.add(path)
    import warnings
    warnings.warn(
        f"PHOTON_TPU_PALLAS_GLM=1 requested the fused Pallas kernel but "
        f"the {path} operands were refused (dtype/normalization/vmap/"
        f"mesh or dimension gate); falling back to the two-pass XLA "
        f"path. kernels.xla_fallbacks{{path={path}}} counts these.",
        RuntimeWarning, stacklevel=3)


def effective_coefficients(coef: Array, norm: NormalizationContext) -> Tuple[Array, Array]:
    """(e, margin_shift) with e = coef*factor and margin_shift = -e.shift."""
    e = coef * norm.factors if norm.factors is not None else coef
    if norm.shifts is not None:
        shift = -jnp.dot(e, norm.shifts)
    else:
        shift = jnp.zeros((), dtype=coef.dtype)
    return e, shift


def compute_margins(
    x: FeatureMatrix,
    coef: Array,
    offsets: Optional[Array],
    norm: NormalizationContext,
) -> Array:
    e, margin_shift = effective_coefficients(coef, norm)
    m = matvec(x, e) + margin_shift
    if offsets is not None:
        m = m + offsets
    return m


def _apply_factor_and_shift(
    vec: Array, prefactor: Array, norm: NormalizationContext
) -> Array:
    """factor * vec - prefactor * factor * shift (identity when unnormalized)."""
    out = vec
    if norm.factors is not None:
        out = out * norm.factors
        if norm.shifts is not None:
            out = out - prefactor * norm.factors * norm.shifts
    elif norm.shifts is not None:
        out = out - prefactor * norm.shifts
    return out


def value_and_gradient(
    loss: PointwiseLoss,
    x: FeatureMatrix,
    labels: Array,
    offsets: Optional[Array],
    weights: Optional[Array],
    coef: Array,
    norm: NormalizationContext,
) -> Tuple[Array, Array]:
    """Weighted loss value and gradient w.r.t. transformed-space coef.

    Reference: ValueAndGradientAggregator.calculateValueAndGradient
    (:240-255 RDD path, :266-279 local path) — here one fused kernel.

    With ``PHOTON_TPU_PALLAS_GLM=1`` the dense / identity-normalization /
    f32 case runs the Pallas single-HBM-pass kernel
    (ops/pallas_glm.py) instead of XLA's two contractions over X, and
    the ELL-sparse case runs its one-nnz-pass analogue. The flag is
    read at trace time: toggling it mid-process does not affect
    already-compiled solves. Routing decisions are counted into
    ``kernels.pallas_hits`` / ``kernels.xla_fallbacks`` (trace-time
    counters with a ``path`` label — one tick per compiled program, so
    a silent fallback to the unfused path shows up in every RunReport).
    """
    import os
    if os.environ.get("PHOTON_TPU_PALLAS_GLM") == "1":
        from photon_tpu.ops import pallas_glm
        if pallas_glm._supported(x, norm, coef):
            _kernel_counter("pallas_hits", "dense")
            return pallas_glm.fused_dense_value_grad(
                loss, x, labels, offsets, weights, coef)
        if pallas_glm._supported_sparse(x, norm, coef):
            _kernel_counter("pallas_hits", "sparse")
            return pallas_glm.fused_sparse_value_grad(
                loss, x, labels, offsets, weights, coef)
        path = "sparse" if isinstance(x, SparseFeatures) else "dense"
        _kernel_counter("xla_fallbacks", path)
        if not pallas_glm._TRACE_DISABLED.get():
            # a disabled() region is a deliberate routing decision (mesh
            # solves); only an unexpected refusal warrants the warning
            _warn_kernel_refused(path)
    dim = coef.shape[0]
    margins = compute_margins(x, coef, offsets, norm)
    l, dz = loss.loss_and_dz(margins, labels)
    if weights is not None:
        l = l * weights
        dz = dz * weights
    value = jnp.sum(l)
    vector_sum = rmatvec(x, dz, dim)
    grad = _apply_factor_and_shift(vector_sum, jnp.sum(dz), norm)
    return value, grad


def _weighted_loss_and_dz(
    loss: PointwiseLoss,
    labels: Array,
    weights: Optional[Array],
    margins: Array,
) -> Tuple[Array, Array]:
    l, dz = loss.loss_and_dz(margins, labels)
    if weights is not None:
        l = l * weights
        dz = dz * weights
    return jnp.sum(l), dz


def margin_value_and_gradient(
    loss: PointwiseLoss,
    x: FeatureMatrix,
    labels: Array,
    weights: Optional[Array],
    margins: Array,
    norm: NormalizationContext,
    dim: int,
) -> Tuple[Array, Array]:
    """``value_and_gradient`` at a point whose margins are already resident.

    Skips the matvec a classic evaluation would pay: the margin-resident
    L-BFGS path (optim/lbfgs.minimize_directional) keeps margins updated
    affinely across iterations, so a full evaluation at the accepted point
    is ONE rmatvec over the feature nnz instead of two passes."""
    value, dz = _weighted_loss_and_dz(loss, labels, weights, margins)
    grad = _apply_factor_and_shift(rmatvec(x, dz, dim), jnp.sum(dz), norm)
    return value, grad


def margin_trial(
    loss: PointwiseLoss,
    labels: Array,
    weights: Optional[Array],
    margins: Array,
    dir_margins: Array,
    step: Array,
) -> Tuple[Array, Array]:
    """(phi(a), phi'(a)) of the data term's 1-D restriction along a
    direction whose margins are precomputed: margins are linear in coef,
    so a trial point is O(n_samples) pointwise work — no feature pass."""
    value, dz = _weighted_loss_and_dz(
        loss, labels, weights, margins + step * dir_margins)
    return value, jnp.dot(dz, dir_margins)


def hessian_weights(
    loss: PointwiseLoss,
    x: FeatureMatrix,
    labels: Array,
    offsets: Optional[Array],
    weights: Optional[Array],
    coef: Array,
    norm: NormalizationContext,
) -> Array:
    """Per-sample Gauss-Newton curvature weights ``w_i l''(margin_i)``.

    The Hessian at a fixed coefficient point is fully determined by these
    weights; they are constant across an entire truncated-CG solve, so TRON
    computes them ONCE per outer iteration instead of re-deriving margins
    inside every Hv product (the reference pays one extra treeAggregate per
    CG step for exactly this — HessianVectorAggregator.scala:37)."""
    margins = compute_margins(x, coef, offsets, norm)
    d2 = loss.d2z(margins, labels)
    if weights is not None:
        d2 = d2 * weights
    return d2


def hessian_vector_from_weights(
    x: FeatureMatrix,
    d2: Array,
    vector: Array,
    norm: NormalizationContext,
    dim: int,
) -> Array:
    """Hv given precomputed curvature weights: two passes over X."""
    v_eff = vector * norm.factors if norm.factors is not None else vector
    t = matvec(x, v_eff)
    if norm.shifts is not None:
        t = t - jnp.dot(v_eff, norm.shifts)
    coeffs = d2 * t
    vector_sum = rmatvec(x, coeffs, dim)
    return _apply_factor_and_shift(vector_sum, jnp.sum(coeffs), norm)


def hessian_matrix_from_weights(
    x: FeatureMatrix,
    d2: Array,
    norm: NormalizationContext,
    dim: int,
) -> Array:
    """Full H from precomputed curvature weights: one GEMM (MXU).

    For small feature dims this turns a whole CG solve's data passes into a
    single ``X^T diag(d2) X`` contraction plus O(d^2) matvecs."""
    h = weighted_gram(x, d2, dim)
    if norm.shifts is not None:
        lin = rmatvec(x, d2, dim)
        outer = jnp.outer(lin, norm.shifts)
        h = h - outer - outer.T + jnp.sum(d2) * jnp.outer(norm.shifts, norm.shifts)
    if norm.factors is not None:
        h = h * jnp.outer(norm.factors, norm.factors)
    return h


def hessian_vector(
    loss: PointwiseLoss,
    x: FeatureMatrix,
    labels: Array,
    offsets: Optional[Array],
    weights: Optional[Array],
    coef: Array,
    vector: Array,
    norm: NormalizationContext,
) -> Array:
    """Gauss-Newton Hessian-vector product (reference:
    HessianVectorAggregator.calcHessianVector :130/:158), used by TRON CG."""
    dim = coef.shape[0]
    d2 = hessian_weights(loss, x, labels, offsets, weights, coef, norm)
    return hessian_vector_from_weights(x, d2, vector, norm, dim)


def hessian_diagonal(
    loss: PointwiseLoss,
    x: FeatureMatrix,
    labels: Array,
    offsets: Optional[Array],
    weights: Optional[Array],
    coef: Array,
    norm: NormalizationContext,
) -> Array:
    """diag(H) = sum_i w_i l''_i x'_ij^2 (reference:
    HessianDiagonalAggregator.calcHessianDiagonal :92/:115); SIMPLE variance."""
    dim = coef.shape[0]
    margins = compute_margins(x, coef, offsets, norm)
    d2 = loss.d2z(margins, labels)
    if weights is not None:
        d2 = d2 * weights

    sq = sq_rmatvec(x, d2, dim)
    if norm.shifts is None:
        diag = sq
    else:
        lin = rmatvec(x, d2, dim)
        diag = sq - 2.0 * norm.shifts * lin + (norm.shifts ** 2) * jnp.sum(d2)
    if norm.factors is not None:
        diag = diag * norm.factors * norm.factors
    return diag


def hessian_matrix(
    loss: PointwiseLoss,
    x: FeatureMatrix,
    labels: Array,
    offsets: Optional[Array],
    weights: Optional[Array],
    coef: Array,
    norm: NormalizationContext,
) -> Array:
    """Full H = sum_i w_i l''_i x'_i x'_i^T (reference:
    HessianMatrixAggregator.calcHessianMatrix :92/:116); FULL variance,
    small dims only."""
    dim = coef.shape[0]
    d2 = hessian_weights(loss, x, labels, offsets, weights, coef, norm)
    return hessian_matrix_from_weights(x, d2, norm, dim)
