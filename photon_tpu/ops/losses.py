"""Pointwise GLM losses.

The scalar contract every distributed kernel reduces to (reference:
photon-lib function/glm/PointwiseLossFunction.scala:36): given a per-sample
margin ``z = theta . x + offset`` and a label, produce

  * ``loss_and_dz(z, y) -> (l(z, y), dl/dz)``
  * ``d2z(z, y)        -> d2l/dz2``

Labels follow the reference conventions: ``{0, 1}`` for logistic regression,
non-negative counts for Poisson, reals for squared loss, and ``{0, 1}``
(mapped internally to ``{-1, +1}``) for the Rennie smoothed hinge
(reference: function/svm/SmoothedHingeLossFunction.scala:26-60).

Everything here is shape-polymorphic and jit/vmap-safe; margins and labels
may be any broadcastable arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def log1p_exp(x: Array) -> Array:
    """Numerically stable log(1 + exp(x)) (reference: util/MathUtils log1pExp)."""
    return jnp.logaddexp(0.0, x)


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise GLM loss: everything the aggregators need.

    ``has_hessian`` mirrors the reference's split between ``DiffFunction``
    (smoothed hinge is first-order only) and ``TwiceDiffFunction``.
    """

    name: str
    loss_and_dz: Callable[[Array, Array], Tuple[Array, Array]]
    d2z: Callable[[Array, Array], Array]
    # Inverse link: margin -> mean prediction, used by the GLM models
    # (reference: supervised/model/GeneralizedLinearModel.computeMean).
    mean: Callable[[Array], Array]
    has_hessian: bool = True

    def value(self, z: Array, y: Array) -> Array:
        return self.loss_and_dz(z, y)[0]


# ---------------------------------------------------------------------------
# Logistic loss (reference: function/glm/LogisticLossFunction.scala:45)
#   l(z, y) = log(1 + e^z) - y z       with y in {0, 1}
#   dl/dz   = sigmoid(z) - y
#   d2l/dz2 = sigmoid(z) (1 - sigmoid(z))
# ---------------------------------------------------------------------------

def _logistic_loss_and_dz(z: Array, y: Array) -> Tuple[Array, Array]:
    return log1p_exp(z) - y * z, jax.nn.sigmoid(z) - y


def _logistic_d2z(z: Array, y: Array) -> Array:
    del y
    s = jax.nn.sigmoid(z)
    return s * (1.0 - s)


LogisticLoss = PointwiseLoss(
    name="logistic",
    loss_and_dz=_logistic_loss_and_dz,
    d2z=_logistic_d2z,
    mean=jax.nn.sigmoid,
)


# ---------------------------------------------------------------------------
# Squared loss (reference: function/glm/SquaredLossFunction.scala:32)
#   l(z, y) = 1/2 (z - y)^2
# ---------------------------------------------------------------------------

def _squared_loss_and_dz(z: Array, y: Array) -> Tuple[Array, Array]:
    r = z - y
    return 0.5 * r * r, r


def _squared_d2z(z: Array, y: Array) -> Array:
    del y
    return jnp.ones_like(z)


SquaredLoss = PointwiseLoss(
    name="squared",
    loss_and_dz=_squared_loss_and_dz,
    d2z=_squared_d2z,
    mean=lambda z: z,
)


# ---------------------------------------------------------------------------
# Poisson loss (reference: function/glm/PoissonLossFunction.scala:31)
#   l(z, y) = e^z - y z
# ---------------------------------------------------------------------------

def _poisson_loss_and_dz(z: Array, y: Array) -> Tuple[Array, Array]:
    ez = jnp.exp(z)
    return ez - y * z, ez - y


def _poisson_d2z(z: Array, y: Array) -> Array:
    del y
    return jnp.exp(z)


PoissonLoss = PointwiseLoss(
    name="poisson",
    loss_and_dz=_poisson_loss_and_dz,
    d2z=_poisson_d2z,
    mean=jnp.exp,
)


# ---------------------------------------------------------------------------
# Rennie smoothed hinge (reference: function/svm/SmoothedHingeLossFunction.scala:26-60)
# With t = (2y - 1) z  (labels {0,1} -> {-1,+1}):
#   l = 1/2 - t          t <= 0
#   l = 1/2 (1 - t)^2    0 < t < 1
#   l = 0                t >= 1
# Piecewise-quadratic; second derivative exists a.e. (1 on the middle piece).
# The reference treats it as first-order only; has_hessian=False mirrors that.
# ---------------------------------------------------------------------------

def _smoothed_hinge_loss_and_dz(z: Array, y: Array) -> Tuple[Array, Array]:
    s = 2.0 * y - 1.0
    t = s * z
    loss = jnp.where(t <= 0.0, 0.5 - t, jnp.where(t < 1.0, 0.5 * (1.0 - t) ** 2, 0.0))
    dldt = jnp.where(t <= 0.0, -1.0, jnp.where(t < 1.0, t - 1.0, 0.0))
    return loss, s * dldt


def _smoothed_hinge_d2z(z: Array, y: Array) -> Array:
    s = 2.0 * y - 1.0
    t = s * z
    return jnp.where((t > 0.0) & (t < 1.0), 1.0, 0.0)


SmoothedHingeLoss = PointwiseLoss(
    name="smoothed_hinge",
    loss_and_dz=_smoothed_hinge_loss_and_dz,
    d2z=_smoothed_hinge_d2z,
    mean=lambda z: z,
    has_hessian=False,
)


def loss_for_task(task) -> PointwiseLoss:
    """TaskType -> PointwiseLoss (reference: ObjectiveFunctionHelper.scala:27)."""
    from photon_tpu.types import TaskType

    return {
        TaskType.LOGISTIC_REGRESSION: LogisticLoss,
        TaskType.LINEAR_REGRESSION: SquaredLoss,
        TaskType.POISSON_REGRESSION: PoissonLoss,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SmoothedHingeLoss,
    }[task]
