"""GAME data structures: host-side columnar frame -> device datasets.

Reference: photon-lib data/GameDatum.scala:40-68 (response/offset/weight,
per-shard feature vectors, id-tag map), photon-api data/GameConverters
.scala:28 (DataFrame row -> GameDatum), data/FixedEffectDataset.scala:31,
data/InputColumnsNames.scala:25.

TPU re-design: the RDD[(uid, GameDatum)] becomes a host-side columnar
``GameDataFrame`` (numpy struct-of-arrays + per-shard sparse rows) from
which static-shape device views are built: a flat uid-major DataBatch per
fixed-effect coordinate, entity-blocked padded arrays per random-effect
coordinate (game/random_effect.py). Sample identity is the row position —
uids never leave the host.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import DataBatch
from photon_tpu.ops import features as F

SparseRows = List[Tuple[np.ndarray, np.ndarray]]  # per-row (indices, values)


class CsrRows:
    """Columnar sparse rows (CSR): the zero-Python-object counterpart of
    ``SparseRows`` produced by the native ingest path (io/fast_ingest.py).
    Duck-types the row-list protocol (len / [i] / iteration) so generic
    consumers keep working; hot paths branch on isinstance for the
    vectorized form."""

    __slots__ = ("indptr", "cols", "vals")

    def __init__(self, indptr: np.ndarray, cols: np.ndarray,
                 vals: np.ndarray):
        self.indptr = np.asarray(indptr, np.int64)
        self.cols = np.asarray(cols)
        self.vals = np.asarray(vals)

    def __len__(self) -> int:
        return len(self.indptr) - 1

    def __getitem__(self, i) -> Tuple[np.ndarray, np.ndarray]:
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        s, e = self.indptr[i], self.indptr[i + 1]
        return self.cols[s:e], self.vals[s:e]

    @staticmethod
    def from_dense(x: np.ndarray) -> "CsrRows":
        """Dense [n, d] -> fully-populated CsrRows (every slot observed,
        explicit zeros kept): the columnar handover for dense blocks."""
        n, d = x.shape
        return CsrRows(np.arange(n + 1, dtype=np.int64) * d,
                       np.tile(np.arange(d, dtype=np.int32), n),
                       np.asarray(x, np.float64).reshape(-1))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def row_nnz(self) -> np.ndarray:
        return np.diff(self.indptr)


@dataclasses.dataclass
class FeatureShard:
    """One feature space: sparse rows (list- or CSR-form) or a dense
    matrix, plus its dim."""

    rows: Union[SparseRows, CsrRows, np.ndarray]
    dim: int

    @property
    def is_dense(self) -> bool:
        return isinstance(self.rows, np.ndarray)

    def max_nnz(self) -> int:
        if self.is_dense:
            return self.dim
        if isinstance(self.rows, CsrRows):
            nnz = self.rows.row_nnz()
            return int(nnz.max()) if len(nnz) else 0
        return max((len(r[0]) for r in self.rows), default=0)


@dataclasses.dataclass
class GameDataFrame:
    """Host-side columnar GAME dataset (the RDD[(uid, GameDatum)] stand-in).

    ``id_tags[re_type][i]`` is sample i's entity id string for that
    random-effect type (reference: GameDatum.idTagToValueMap).
    """

    num_samples: int
    response: np.ndarray                       # [n]
    feature_shards: Dict[str, FeatureShard]
    offsets: Optional[np.ndarray] = None       # [n]
    weights: Optional[np.ndarray] = None       # [n]
    id_tags: Dict[str, Sequence[str]] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        n = self.num_samples
        assert len(self.response) == n
        for tag, vals in self.id_tags.items():
            assert len(vals) == n, f"id tag {tag} length mismatch"

    def shard_features(self, shard_id: str, dtype=np.float32) -> F.FeatureMatrix:
        shard = self.feature_shards[shard_id]
        if shard.is_dense:
            return jnp.asarray(shard.rows, dtype)
        if isinstance(shard.rows, CsrRows):
            return F.from_csr_arrays(shard.rows.indptr, shard.rows.cols,
                                     shard.rows.vals, dtype=dtype)
        return F.from_rows(shard.rows, shard.dim, dtype=dtype)

    def fixed_effect_batch(self, shard_id: str, dtype=np.float32,
                           feature_dtype=None) -> DataBatch:
        """Reference: FixedEffectDataset — flat uid-major batch over one
        feature shard.

        ``feature_dtype`` stores X narrower than the solve dtype (e.g.
        bfloat16 under an f32 solve): matvec/rmatvec promote to the
        accumulation dtype in-register, so a bandwidth-bound solve reads
        half the HBM bytes while the optimizer math stays full-precision.
        """
        return DataBatch(
            features=self.shard_features(shard_id, feature_dtype or dtype),
            labels=jnp.asarray(self.response, dtype),
            offsets=None if self.offsets is None else jnp.asarray(self.offsets, dtype),
            weights=None if self.weights is None else jnp.asarray(self.weights, dtype),
        )


class EntityVocabulary:
    """String REId <-> dense entity index, per random-effect type.

    Built from training data; evaluation data maps unseen entities to -1
    (zero score contribution — matching the reference, where a missing
    per-entity model contributes nothing).
    """

    def __init__(self):
        self._maps: Dict[str, Dict[str, int]] = {}
        self._names: Dict[str, List[str]] = {}

    def build(self, re_type: str, ids: Sequence[str]) -> np.ndarray:
        m = self._maps.setdefault(re_type, {})
        names = self._names.setdefault(re_type, [])
        out = np.empty(len(ids), np.int32)
        for i, s in enumerate(ids):
            j = m.get(s)
            if j is None:
                j = len(names)
                m[s] = j
                names.append(s)
            out[i] = j
        return out

    def lookup(self, re_type: str, ids: Sequence[str]) -> np.ndarray:
        m = self._maps.get(re_type, {})
        return np.asarray([m.get(s, -1) for s in ids], np.int32)

    def names(self, re_type: str) -> List[str]:
        return list(self._names.get(re_type, []))

    def size(self, re_type: str) -> int:
        return len(self._names.get(re_type, []))
