"""Scoring a GameDataFrame under a GameModel (validation + inference).

Reference: photon-lib model/GameModel.scala:99 (score = sum of coordinate
scores), model/FixedEffectModel.scala:70 (broadcast dot),
model/RandomEffectModel.scala:166 (join on REId then dot — here a gather),
photon-api transformers/GameTransformer.scala:115.

The scorer precomputes device artifacts for a frame once (feature
matrices, per-sample entity indices, entity-local projected features), so
repeated scoring during coordinate descent costs one jitted pass.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.game.dataset import EntityVocabulary, GameDataFrame
from photon_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_tpu.game.random_effect import (
    RandomEffectDataConfiguration,
    project_for_scoring,
)
from photon_tpu.ops import features as F

Array = jax.Array


class GameScorer:
    """Precompiled scorer for one frame against one GAME model structure."""

    def __init__(self, num_samples: int, dtype=jnp.float32):
        self.n = num_samples
        self.dtype = dtype
        self._fixed: Dict[str, F.FeatureMatrix] = {}
        self._random: Dict[str, tuple] = {}

    # -- construction -------------------------------------------------------

    def add_fixed_effect(self, coordinate_id: str, df: GameDataFrame,
                         feature_shard_id: str):
        self._fixed[coordinate_id] = df.shard_features(
            feature_shard_id, dtype=np.dtype(self.dtype).type)
        return self

    def add_random_effect(self, coordinate_id: str, df: GameDataFrame,
                          config: RandomEffectDataConfiguration,
                          vocab: EntityVocabulary, projection: Array):
        feats, entity_idx = project_for_scoring(
            df, config, vocab, np.asarray(projection),
            dtype=np.dtype(self.dtype).type)
        self._random[coordinate_id] = (feats, entity_idx)
        return self

    # -- scoring ------------------------------------------------------------

    @functools.cached_property
    def _fixed_score(self):
        @jax.jit
        def fn(feats, coef):
            return F.matvec(feats, coef)
        return fn

    @functools.cached_property
    def _random_score(self):
        @jax.jit
        def fn(feats_idx, feats_val, entity_idx, coef_block):
            rows = coef_block.at[entity_idx].get(mode="fill", fill_value=0.0)
            return jnp.sum(feats_val * jnp.take_along_axis(rows, feats_idx, axis=1),
                           axis=-1)
        return fn

    def score_coordinate(self, coordinate_id: str, model) -> Array:
        if isinstance(model, FixedEffectModel):
            feats = self._fixed[coordinate_id]
            return self._fixed_score(feats, model.model.coefficients.means)
        if isinstance(model, RandomEffectModel):
            feats, entity_idx = self._random[coordinate_id]
            return self._random_score(feats.indices, feats.values, entity_idx,
                                      model.coefficients)
        raise TypeError(f"unknown model type {type(model)}")

    def score(self, game_model: GameModel,
              offsets: Optional[Array] = None) -> Array:
        """Total score = sum of coordinate scores (+ offsets)."""
        total = jnp.zeros((self.n,), self.dtype)
        for cid in game_model.coordinate_ids:
            if cid in self._fixed or cid in self._random:
                total = total + self.score_coordinate(cid, game_model[cid])
        if offsets is not None:
            total = total + offsets
        return total
