"""Coordinate descent over GAME coordinates with score algebra.

Reference: photon-lib algorithm/CoordinateDescent.scala:38 (run :93,
descend :119): outer loop over update sequence x iterations; each
coordinate trains against ``fullScore - ownScore`` (partial score,
:197-204); score container updated incrementally (:223-234); validation
after every coordinate update (:257-288); best model tracked by the primary
validation metric over FULL sweeps only (:162-171, :292-325); locked
coordinates (partial retraining) score but never train
(coordinatesToTrain :45).

TPU re-design: DataScores RDDs with +/- joins become flat [n] arrays with
elementwise arithmetic; the persist/unpersist choreography disappears
(arrays are device-resident); everything else keeps the reference's
semantics exactly.

Resilience (no reference analog — Spark lineage recovery doesn't exist
here): every coordinate update is a fault boundary. A solve that trips a
device-side non-finite guard (optim.base.FailureMode) rolls the
coordinate back to its previous model and the sweep continues; the same
coordinate failing ``max_consecutive_failures`` times aborts with a
resumable mid-sweep checkpoint. SIGTERM/SIGINT (resilience/shutdown.py)
is honored at the next coordinate boundary with an emergency partial
checkpoint whose resume is bitwise-equal to the uninterrupted run — which
is why partial checkpoints persist the score container verbatim instead
of recomputing it (incremental score arithmetic is order-sensitive in the
last ulp). Sweep boundaries run the multi-host consistency guard
(resilience/multihost.py).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.game.model import GameModel
from photon_tpu.obs import solver as _obs_solver
from photon_tpu.obs import spans as _obs_spans
from photon_tpu.resilience import chaos as _chaos
from photon_tpu.resilience import failures as _failures
from photon_tpu.resilience import multihost as _multihost
from photon_tpu.resilience import shutdown as _shutdown
from photon_tpu.resilience.failures import (
    CoordinateFailureError,
    PreemptionRequested,
)

Array = jax.Array

logger = logging.getLogger(__name__)

# validation callback: GameModel -> {metric name: value}; first metric is primary
ValidationFn = Callable[[GameModel], Dict[str, float]]


@dataclasses.dataclass(frozen=True)
class CoordinateDescentConfig:
    update_sequence: List[str]
    num_iterations: int = 1
    locked_coordinates: frozenset = frozenset()  # partial retraining
    # abort (with a resumable checkpoint) after this many CONSECUTIVE
    # failed solves of the same coordinate; isolated failures roll back
    # and the sweep continues
    max_consecutive_failures: int = 3


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    best_model: GameModel
    validation_history: List[Dict[str, float]]
    best_iteration: Optional[int] = None


def run_coordinate_descent(
    coordinates: Dict[str, object],
    config: CoordinateDescentConfig,
    num_samples: int,
    initial_model: Optional[GameModel] = None,
    validation_fn: Optional[ValidationFn] = None,
    primary_metric_bigger_is_better: bool = True,
    dtype=jnp.float32,
    checkpoint_dir: Optional[str] = None,
    resume: bool = False,
) -> CoordinateDescentResult:
    """Run GAME coordinate descent.

    ``coordinates`` maps coordinate id -> FixedEffectCoordinate /
    RandomEffectCoordinate (game/coordinate.py); locked ids must come with
    their model inside ``initial_model`` (they only score).

    With ``checkpoint_dir``, every completed sweep is atomically published
    there; ``resume=True`` restarts from the latest one — the continuation
    is bitwise-equal to an uninterrupted run (SURVEY §5.3: checkpoint +
    restart replaces Spark lineage recovery; scores are recomputed from
    the models at sweep boundaries, restored verbatim from mid-sweep
    partial checkpoints, down-sampling PRNG counters are restored).
    """
    to_train = [c for c in config.update_sequence
                if c not in config.locked_coordinates]
    if not to_train:
        raise ValueError("no coordinates to train (all locked)")
    for cid in config.update_sequence:
        if cid not in coordinates:
            raise KeyError(f"coordinate {cid!r} missing from coordinates")
    for cid in config.locked_coordinates:
        if initial_model is None or cid not in initial_model:
            raise ValueError(f"locked coordinate {cid!r} needs an initial model")

    models: Dict[str, object] = dict(initial_model.models) if initial_model else {}
    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None
    best_iter: Optional[int] = None
    history: List[Dict[str, float]] = []
    start_iter = 0
    resume_coord_idx = 0
    restored_scores: Optional[Dict[str, Array]] = None
    restored_full: Optional[Array] = None

    if checkpoint_dir and resume:
        from photon_tpu.game import checkpoint as ckpt
        state = ckpt.load_latest(checkpoint_dir)
        if state is not None:
            models = dict(state.models)
            if state.sweep_in_progress is not None:
                # mid-sweep partial checkpoint (preemption / coordinate
                # abort): re-enter the interrupted sweep at the exact
                # coordinate boundary, score container verbatim
                start_iter = state.sweep_in_progress
                resume_coord_idx = state.next_coordinate
                restored_scores = {cid: jnp.asarray(v) for cid, v
                                   in (state.scores or {}).items()}
                restored_full = (None if state.full_score is None
                                 else jnp.asarray(state.full_score))
            else:
                start_iter = state.sweep + 1
            best_model = (GameModel(dict(state.best_models))
                          if state.best_models else None)
            best_metric = state.best_metric
            best_iter = state.best_iteration
            history = list(state.history)
            for cid, count in state.counters.items():
                if cid in coordinates and hasattr(coordinates[cid],
                                                  "_update_count"):
                    coordinates[cid]._update_count = count
            logger.info(
                "resumed from %s (sweep %d complete%s)", checkpoint_dir,
                state.sweep,
                "" if state.sweep_in_progress is None
                else f", re-entering sweep {start_iter}"
                     f" at coordinate index {resume_coord_idx}")

    scores: Dict[str, Array] = {}
    full_score = jnp.zeros((num_samples,), dtype)

    if restored_scores is not None:
        scores = restored_scores
        if restored_full is not None:
            full_score = restored_full
    else:
        # initial scores for any pre-existing models (warm start / locked /
        # checkpoint-resumed — at sweep boundaries scores are pure
        # functions of the models)
        for cid in config.update_sequence:
            if cid in models:
                s = coordinates[cid].score(models[cid])
                scores[cid] = s
                full_score = full_score + s

    def _counters() -> Dict[str, int]:
        return {cid: coordinates[cid]._update_count
                for cid in config.update_sequence
                if hasattr(coordinates[cid], "_update_count")}

    def save_partial(sweep_in_progress: int, next_k: int) -> Optional[str]:
        """Emergency mid-sweep checkpoint at a coordinate boundary."""
        if not checkpoint_dir:
            return None
        from photon_tpu.game import checkpoint as ckpt
        return ckpt.save_checkpoint(
            checkpoint_dir, sweep_in_progress - 1, models, _counters(),
            best_models=None if best_model is None else best_model.models,
            best_metric=best_metric, best_iteration=best_iter,
            history=history,
            sweep_in_progress=sweep_in_progress, next_coordinate=next_k,
            scores={cid: np.asarray(s) for cid, s in scores.items()},
            full_score=np.asarray(full_score))

    consecutive: Dict[str, int] = {}

    for it in range(start_iter, config.num_iterations):
      with _obs_spans.span("cd/sweep", iteration=it):
        for k, cid in enumerate(config.update_sequence):
            if it == start_iter and k < resume_coord_idx:
                continue  # re-entered sweep: these already ran pre-restart
            _chaos.maybe_preempt(it, cid)
            if _shutdown.requested():
                path = save_partial(it, k)
                _failures.record_failure(
                    "preemption", sweep=it, coordinate=cid,
                    reason=_shutdown.reason(), checkpoint=path)
                raise PreemptionRequested(checkpoint_path=path, sweep=it,
                                          coordinate=cid)
            if cid in config.locked_coordinates:
                continue
            coord = coordinates[cid]
            if _chaos.is_active() and _chaos.should_poison_nan(cid, it):
                coord._chaos_poison_once = True
            own = scores.get(cid)
            partial = full_score - own if own is not None else full_score
            residual = partial if len(config.update_sequence) > 1 else None

            from photon_tpu.utils.timing import Timed
            with Timed(f"CD iter {it} update {cid}", logger,
                       level=logging.DEBUG):
                new_model = coord.update_model(models.get(cid), residual)
            tracker = getattr(coord, "last_tracker", None)
            if tracker is not None:
                # telemetry keeps a REFERENCE (device arrays and all);
                # the host transfer happens at drain time, not here
                _obs_solver.record(cid, tracker, sweep=it)
                if logger.isEnabledFor(logging.DEBUG):
                    # summary() forces a device->host sync; never pay it
                    # unless debug logging actually consumes it
                    logger.debug("coord %s solver: %s", cid, tracker.summary())

            n_failed_entities = getattr(coord, "last_failed_entities", 0)
            if n_failed_entities:
                # isolated per-entity failures: those entities kept their
                # warm start inside the solve; the coordinate is still good
                _failures.record_failure(
                    "entity_solve_failures", coordinate=cid, sweep=it,
                    entities=int(n_failed_entities))
            failure = getattr(coord, "last_failure", None)
            if failure is not None:
                # coordinate-level failure: discard the new model, keep the
                # previous one and its score — the sweep continues on the
                # other coordinates
                consecutive[cid] = consecutive.get(cid, 0) + 1
                _failures.record_failure(
                    "coordinate_rollback", coordinate=cid, sweep=it,
                    failure=failure.name, consecutive=consecutive[cid])
                logger.warning(
                    "coordinate %s failed (%s) at sweep %d; rolled back "
                    "(%d consecutive)", cid, failure.name, it,
                    consecutive[cid])
                if consecutive[cid] >= config.max_consecutive_failures:
                    path = save_partial(it, k + 1)
                    _failures.record_failure(
                        "coordinate_abort", coordinate=cid, sweep=it,
                        consecutive=consecutive[cid], checkpoint=path)
                    raise CoordinateFailureError(
                        cid, it, consecutive[cid], checkpoint_path=path)
                continue
            consecutive[cid] = 0
            models[cid] = new_model
            new_score = coord.score(new_model)
            full_score = (full_score - own + new_score) if own is not None \
                else (full_score + new_score)
            scores[cid] = new_score

            if validation_fn is not None:
                metrics = validation_fn(GameModel(dict(models)))
                history.append({"iteration": it, "coordinate": cid, **metrics})
                logger.info("CD iter %d coord %s: %s", it, cid, metrics)

        resume_coord_idx = 0  # only the re-entered sweep skips coordinates

        # best-model bookkeeping over FULL sweeps (reference :162-171)
        if validation_fn is not None:
            metrics = validation_fn(GameModel(dict(models)))
            primary = next(iter(metrics.values()))
            is_better = (best_metric is None
                         or (primary > best_metric if primary_metric_bigger_is_better
                             else primary < best_metric))
            if is_better:
                best_metric = primary
                best_model = GameModel(dict(models))
                best_iter = it

        # canonicalize the running sum at sweep boundaries: a resume
        # rebuilds full_score as a FRESH ordered sum over the models, and
        # bitwise-equal continuation requires the uninterrupted run to
        # hold the same value (incremental "full - own + new" arithmetic
        # drifts in the last ulp)
        full_score = jnp.zeros((num_samples,), dtype)
        for cid in config.update_sequence:
            if cid in scores:
                full_score = full_score + scores[cid]

        # sweep boundary = the one place replicated state is compared
        # across hosts (collective; every process reaches it together)
        _multihost.check_consistency(models, it)

        ckpt_path = None
        if checkpoint_dir:
            from photon_tpu.game import checkpoint as ckpt
            ckpt_path = ckpt.save_checkpoint(
                checkpoint_dir, it, models, _counters(),
                best_models=None if best_model is None else best_model.models,
                best_metric=best_metric, best_iteration=best_iter,
                history=history)
        if _shutdown.requested():
            # the sweep-boundary checkpoint just published IS the
            # emergency checkpoint — stop before starting another sweep
            _failures.record_failure("preemption", sweep=it,
                                     reason=_shutdown.reason(),
                                     checkpoint=ckpt_path)
            raise PreemptionRequested(checkpoint_path=ckpt_path, sweep=it)

    final = GameModel(dict(models))
    return CoordinateDescentResult(
        model=final,
        best_model=best_model if best_model is not None else final,
        validation_history=history,
        best_iteration=best_iter,
    )
