"""Coordinate descent over GAME coordinates with score algebra.

Reference: photon-lib algorithm/CoordinateDescent.scala:38 (run :93,
descend :119): outer loop over update sequence x iterations; each
coordinate trains against ``fullScore - ownScore`` (partial score,
:197-204); score container updated incrementally (:223-234); validation
after every coordinate update (:257-288); best model tracked by the primary
validation metric over FULL sweeps only (:162-171, :292-325); locked
coordinates (partial retraining) score but never train
(coordinatesToTrain :45).

TPU re-design: DataScores RDDs with +/- joins become flat [n] arrays with
elementwise arithmetic; the persist/unpersist choreography disappears
(arrays are device-resident); everything else keeps the reference's
semantics exactly.

Parallel sweeps (no reference analog — the Scala walks coordinates
strictly one at a time): with ``CoordinateDescentConfig.parallel`` the
update sequence is partitioned into CONTIGUOUS concurrency groups
(game/parallel_cd.py; default: fixed effect alone, consecutive random
effects together). Every member of a group solves against the SAME
partial score frozen at group entry — the solves become data-independent
and are dispatched from worker threads as overlapping async JAX
computations (host prep of one member overlaps device execution of
another; on a mesh, parallel/mesh.plan_group_placement names disjoint
device subsets per member). After the group, the score container is
reconciled in ONE canonical ordered pass, so sweep boundaries stay
bitwise-reproducible. Bounded staleness (arXiv 1811.01564, 1611.02101)
is policed by a convergence guard: the realized objective decrease
(fresh residuals) is compared against the solver-predicted decrease
(frozen residuals); regression beyond ``staleness_tol`` for
``staleness_patience`` consecutive groups degrades the rest of the run
to sequential mode — a typed obs event + counter, never an exception.
Singleton groups run the exact sequential arithmetic, so
``parallel_groups=[[c] for c in seq]`` is bitwise-identical to
sequential mode.

Resilience (no reference analog — Spark lineage recovery doesn't exist
here): every coordinate update is a fault boundary. A solve that trips a
device-side non-finite guard (optim.base.FailureMode) rolls the
coordinate back to its previous model and the sweep continues; the same
coordinate failing ``max_consecutive_failures`` times aborts with a
resumable mid-sweep checkpoint. In a parallel group the same isolation
holds per member: a failed member rolls back alone while the group's
other members commit. SIGTERM/SIGINT (resilience/shutdown.py) is honored
at the next coordinate boundary — GROUP boundary in parallel mode — with
an emergency partial checkpoint whose resume is bitwise-equal to the
uninterrupted run — which is why partial checkpoints persist the score
container verbatim instead of recomputing it (incremental score
arithmetic is order-sensitive in the last ulp). Sweep boundaries run the
multi-host consistency guard (resilience/multihost.py).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.game.model import GameModel
from photon_tpu.obs import solver as _obs_solver
from photon_tpu.obs import spans as _obs_spans
from photon_tpu.resilience import chaos as _chaos
from photon_tpu.resilience import failures as _failures
from photon_tpu.resilience import multihost as _multihost
from photon_tpu.resilience import shutdown as _shutdown
from photon_tpu.resilience.failures import (
    CoordinateFailureError,
    PreemptionRequested,
)

Array = jax.Array

logger = logging.getLogger(__name__)

# validation callback: GameModel -> {metric name: value}; first metric is primary
ValidationFn = Callable[[GameModel], Dict[str, float]]


@dataclasses.dataclass(frozen=True)
class CoordinateDescentConfig:
    update_sequence: List[str]
    num_iterations: int = 1
    locked_coordinates: frozenset = frozenset()  # partial retraining
    # abort (with a resumable checkpoint) after this many CONSECUTIVE
    # failed solves of the same coordinate; isolated failures roll back
    # and the sweep continues
    max_consecutive_failures: int = 3
    # parallel sweep mode: solve concurrency groups of coordinates
    # against bounded-stale frozen scores (module docstring; game/
    # parallel_cd.py). parallel_groups overrides the auto-grouping and
    # must partition update_sequence in order; singleton groups are
    # bitwise-identical to the sequential sweep.
    parallel: bool = False
    parallel_groups: Optional[List[List[str]]] = None
    # staleness guard: simultaneous solves legitimately realize LESS
    # than the sum of their independently-predicted decreases (Jacobi
    # vs Gauss-Seidel sub-additivity), so the guard polices the ratio: a
    # group regresses when realized decrease <
    # staleness_ratio * predicted - staleness_tol * (|predicted| + 1).
    # staleness_patience consecutive regressions degrade the rest of the
    # run to sequential (<= 0 disables the guard).
    staleness_tol: float = 1e-3
    staleness_ratio: float = 0.5
    staleness_patience: int = 2


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    best_model: GameModel
    validation_history: List[Dict[str, float]]
    best_iteration: Optional[int] = None


def run_coordinate_descent(
    coordinates: Dict[str, object],
    config: CoordinateDescentConfig,
    num_samples: int,
    initial_model: Optional[GameModel] = None,
    validation_fn: Optional[ValidationFn] = None,
    primary_metric_bigger_is_better: bool = True,
    dtype=jnp.float32,
    checkpoint_dir: Optional[str] = None,
    resume: bool = False,
) -> CoordinateDescentResult:
    """Run GAME coordinate descent.

    ``coordinates`` maps coordinate id -> FixedEffectCoordinate /
    RandomEffectCoordinate (game/coordinate.py); locked ids must come with
    their model inside ``initial_model`` (they only score).

    With ``checkpoint_dir``, every completed sweep is atomically published
    there; ``resume=True`` restarts from the latest one — the continuation
    is bitwise-equal to an uninterrupted run (SURVEY §5.3: checkpoint +
    restart replaces Spark lineage recovery; scores are recomputed from
    the models at sweep boundaries, restored verbatim from mid-sweep
    partial checkpoints, down-sampling PRNG counters are restored).
    """
    to_train = [c for c in config.update_sequence
                if c not in config.locked_coordinates]
    if not to_train:
        raise ValueError("no coordinates to train (all locked)")
    for cid in config.update_sequence:
        if cid not in coordinates:
            raise KeyError(f"coordinate {cid!r} missing from coordinates")
    for cid in config.locked_coordinates:
        if initial_model is None or cid not in initial_model:
            raise ValueError(f"locked coordinate {cid!r} needs an initial model")

    parallel_spans = None
    if config.parallel:
        from photon_tpu.game import parallel_cd as _pcd
        parallel_spans = _pcd.resolve_groups(config, coordinates)
        mesh = next((getattr(coordinates[c], "mesh", None)
                     for c in config.update_sequence
                     if getattr(coordinates[c], "mesh", None) is not None),
                    None)
        placement = {}
        if mesh is not None:
            from photon_tpu.parallel import mesh as M
            for _g_start, members in parallel_spans:
                if len(members) > 1:
                    placement.update(M.plan_group_placement(members, mesh))
        _pcd.begin_run(parallel_spans, placement or None)

    models: Dict[str, object] = dict(initial_model.models) if initial_model else {}
    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None
    best_iter: Optional[int] = None
    history: List[Dict[str, float]] = []
    start_iter = 0
    resume_coord_idx = 0
    restored_scores: Optional[Dict[str, Array]] = None
    restored_full: Optional[Array] = None

    if checkpoint_dir and resume:
        from photon_tpu.game import checkpoint as ckpt
        state = ckpt.load_latest(checkpoint_dir)
        if state is not None:
            models = dict(state.models)
            if state.sweep_in_progress is not None:
                # mid-sweep partial checkpoint (preemption / coordinate
                # abort): re-enter the interrupted sweep at the exact
                # coordinate boundary, score container verbatim
                start_iter = state.sweep_in_progress
                resume_coord_idx = state.next_coordinate
                restored_scores = {cid: jnp.asarray(v) for cid, v
                                   in (state.scores or {}).items()}
                restored_full = (None if state.full_score is None
                                 else jnp.asarray(state.full_score))
            else:
                start_iter = state.sweep + 1
            best_model = (GameModel(dict(state.best_models))
                          if state.best_models else None)
            best_metric = state.best_metric
            best_iter = state.best_iteration
            history = list(state.history)
            for cid, count in state.counters.items():
                if cid in coordinates and hasattr(coordinates[cid],
                                                  "_update_count"):
                    coordinates[cid]._update_count = count
            logger.info(
                "resumed from %s (sweep %d complete%s)", checkpoint_dir,
                state.sweep,
                "" if state.sweep_in_progress is None
                else f", re-entering sweep {start_iter}"
                     f" at coordinate index {resume_coord_idx}")

    scores: Dict[str, Array] = {}
    full_score = jnp.zeros((num_samples,), dtype)

    if restored_scores is not None:
        scores = restored_scores
        if restored_full is not None:
            full_score = restored_full
    else:
        # initial scores for any pre-existing models (warm start / locked /
        # checkpoint-resumed — at sweep boundaries scores are pure
        # functions of the models)
        for cid in config.update_sequence:
            if cid in models:
                s = coordinates[cid].score(models[cid])
                scores[cid] = s
                full_score = full_score + s

    def _counters() -> Dict[str, int]:
        return {cid: coordinates[cid]._update_count
                for cid in config.update_sequence
                if hasattr(coordinates[cid], "_update_count")}

    def save_partial(sweep_in_progress: int, next_k: int,
                     group_boundary: bool = False) -> Optional[str]:
        """Emergency mid-sweep checkpoint at a coordinate boundary
        (a GROUP boundary in parallel mode sets ``group_boundary``)."""
        if not checkpoint_dir:
            return None
        from photon_tpu.game import checkpoint as ckpt
        return ckpt.save_checkpoint(
            checkpoint_dir, sweep_in_progress - 1, models, _counters(),
            best_models=None if best_model is None else best_model.models,
            best_metric=best_metric, best_iteration=best_iter,
            history=history,
            sweep_in_progress=sweep_in_progress, next_coordinate=next_k,
            scores={cid: np.asarray(s) for cid, s in scores.items()},
            full_score=np.asarray(full_score),
            group_boundary=group_boundary)

    consecutive: Dict[str, int] = {}
    # the last validation_fn result for the CURRENT models, or None when
    # models changed since — lets the sweep boundary reuse the final
    # coordinate's post-update validation instead of scoring the
    # identical model a second time
    metrics_current: Optional[Dict[str, float]] = None
    # staleness-guard state (parallel mode): consecutive regressed
    # groups, and the sticky degraded-to-sequential flag
    stale_streak = 0
    fallback_active = False

    def _record_solver_obs(cid: str, coord, it: int) -> None:
        tracker = getattr(coord, "last_tracker", None)
        if tracker is not None:
            # telemetry keeps a REFERENCE (device arrays and all);
            # the host transfer happens at drain time, not here
            _obs_solver.record(cid, tracker, sweep=it)
            if logger.isEnabledFor(logging.DEBUG):
                # summary() forces a device->host sync; never pay it
                # unless debug logging actually consumes it
                logger.debug("coord %s solver: %s", cid, tracker.summary())
        n_failed_entities = getattr(coord, "last_failed_entities", 0)
        if n_failed_entities:
            # isolated per-entity failures: those entities kept their
            # warm start inside the solve; the coordinate is still good
            _failures.record_failure(
                "entity_solve_failures", coordinate=cid, sweep=it,
                entities=int(n_failed_entities))

    def _commit(cid: str, it: int, new_model, new_score,
                validate: bool = True) -> None:
        """``validate=False`` is the concurrent-group path: members commit
        atomically at reconciliation, so the models between member commits
        are mixtures that never existed as trajectory states — the group
        runs ONE validation at its boundary instead (sequential mode keeps
        the reference per-coordinate cadence)."""
        nonlocal full_score, metrics_current
        consecutive[cid] = 0
        models[cid] = new_model
        own = scores.get(cid)
        full_score = (full_score - own + new_score) if own is not None \
            else (full_score + new_score)
        scores[cid] = new_score
        metrics_current = None
        if validate and validation_fn is not None:
            metrics = validation_fn(GameModel(dict(models)))
            metrics_current = metrics
            history.append({"iteration": it, "coordinate": cid, **metrics})
            logger.info("CD iter %d coord %s: %s", it, cid, metrics)

    def _rollback(cid: str, it: int, failure) -> bool:
        """Discard the failed solve, keep the previous model + score;
        True when the consecutive-failure budget is exhausted (abort)."""
        consecutive[cid] = consecutive.get(cid, 0) + 1
        _failures.record_failure(
            "coordinate_rollback", coordinate=cid, sweep=it,
            failure=failure.name, consecutive=consecutive[cid])
        logger.warning(
            "coordinate %s failed (%s) at sweep %d; rolled back "
            "(%d consecutive)", cid, failure.name, it, consecutive[cid])
        return consecutive[cid] >= config.max_consecutive_failures

    def _train_one(k: int, cid: str, it: int) -> bool:
        """One sequential-semantics coordinate update against the LIVE
        score container; ``k`` is the coordinate's index in the update
        sequence (the checkpoint boundary on abort). Returns True when
        the new model committed, False on rollback."""
        coord = coordinates[cid]
        if _chaos.is_active() and _chaos.should_poison_nan(cid, it):
            coord._chaos_poison_once = True
        own = scores.get(cid)
        partial = full_score - own if own is not None else full_score
        residual = partial if len(config.update_sequence) > 1 else None
        with _obs_spans.span("cd/update", coordinate=cid):
            new_model = coord.update_model(models.get(cid), residual)
        _record_solver_obs(cid, coord, it)
        failure = getattr(coord, "last_failure", None)
        if failure is not None:
            # coordinate-level failure: discard the new model, keep the
            # previous one and its score — the sweep continues on the
            # other coordinates
            if _rollback(cid, it, failure):
                path = save_partial(it, k + 1)
                _failures.record_failure(
                    "coordinate_abort", coordinate=cid, sweep=it,
                    consecutive=consecutive[cid], checkpoint=path)
                raise CoordinateFailureError(
                    cid, it, consecutive[cid], checkpoint_path=path)
            return False
        new_score = coord.score(new_model)
        _commit(cid, it, new_model, new_score)
        return True

    def _run_group(it: int, gi: int, g_start: int, members: List[str],
                   train: List[str]) -> None:
        """One concurrent group: freeze the score container, dispatch all
        members' solves from worker threads against the same frozen
        partial scores, then reconcile in ONE canonical ordered pass and
        run the staleness guard (one host read, at the group boundary)."""
        nonlocal stale_streak, fallback_active, metrics_current
        from photon_tpu.game import parallel_cd as _pcd
        t0 = time.perf_counter()
        with _obs_spans.span("cd/group", iteration=it, group=gi,
                             size=len(train)):
            # every member sees the container AS OF group entry
            frozen = full_score
            resids = {}
            for cid in train:
                own = scores.get(cid)
                resids[cid] = frozen - own if own is not None else frozen
            old_models = {cid: models.get(cid) for cid in train}
            old_scores = {cid: scores.get(cid) for cid in train}

            def _solve_member(cid: str):
                coord = coordinates[cid]
                delay = _chaos.straggler_delay(cid, it)
                if delay:
                    time.sleep(delay)  # injected straggler inside the group
                if _chaos.is_active() and _chaos.should_poison_nan(cid, it):
                    coord._chaos_poison_once = True
                with _obs_spans.span("cd/update", coordinate=cid, group=gi):
                    new_model = coord.update_model(old_models[cid],
                                                   resids[cid])
                failure = getattr(coord, "last_failure", None)
                # scoring in-thread too: score VALUES are order-free (only
                # the container arithmetic is order-sensitive, and that
                # happens in the canonical pass below)
                new_score = coord.score(new_model) if failure is None else None
                return new_model, new_score, failure

            # run-level pool: worker threads are reused across groups and
            # sweeps (per-group executor churn would cost ~0.1 ms each)
            solved = dict(zip(train, group_pool.map(_solve_member, train)))

            aborted: Optional[str] = None
            committed: List[str] = []
            for cid in train:  # canonical ordered reconciliation pass
                new_model, new_score, failure = solved[cid]
                _record_solver_obs(cid, coordinates[cid], it)
                if failure is not None:
                    # member-level isolation: this member rolls back; the
                    # group's other members still commit below
                    _pcd.record_member_failure(cid, it)
                    if _rollback(cid, it, failure):
                        aborted = cid
                    continue
                _commit(cid, it, new_model, new_score, validate=False)
                committed.append(cid)

            if aborted is not None:
                # healthy members committed above — the group END is the
                # resumable boundary
                path = save_partial(it, g_start + len(members),
                                    group_boundary=True)
                _failures.record_failure(
                    "coordinate_abort", coordinate=aborted, sweep=it,
                    consecutive=consecutive[aborted], checkpoint=path)
                raise CoordinateFailureError(
                    aborted, it, consecutive[aborted], checkpoint_path=path)

            if committed and validation_fn is not None:
                # group-granular validation cadence (see _commit)
                metrics = validation_fn(GameModel(dict(models)))
                metrics_current = metrics
                history.append({"iteration": it,
                                "coordinate": f"group:{gi}", **metrics})
                logger.info("CD iter %d group %d: %s", it, gi, metrics)

            # convergence guard in SCORE SPACE: objective_value(m, resid)
            # == data_loss(resid + score(m)) + reg(m), and reconciliation
            # already materialized every score vector involved — so the
            # predicted loss decrease of member m against its frozen
            # residual is L(frozen) - L(frozen + new_score_m -
            # old_score_m), and the realized group decrease is L(frozen) -
            # L(reconciled container). The guard therefore costs O(n)
            # elementwise evals, never feature passes. Per-member reg
            # deltas appear identically in predicted and realized and drop
            # out of both sides. Everything stays on device until the
            # single boundary read.
            predicted = realized = None
            regressed = False
            if config.staleness_patience > 0 and len(committed) >= 2:
                lp = coordinates[committed[0]]
                L0 = lp.data_loss_at(frozen)
                pred = None
                for cid in committed:
                    own = old_scores[cid]
                    delta = (scores[cid] - own if own is not None
                             else scores[cid])
                    d = L0 - lp.data_loss_at(frozen + delta)
                    pred = d if pred is None else pred + d
                real = L0 - lp.data_loss_at(full_score)
                if pred is not None:
                    thresh = (config.staleness_ratio * pred
                              - config.staleness_tol * (jnp.abs(pred) + 1.0))
                    # ONE device->host transfer per group, at the boundary
                    h = np.asarray(jnp.stack([pred, real, thresh]))
                    predicted, realized = float(h[0]), float(h[1])
                    regressed = bool(h[1] < h[2])
                    if regressed:
                        stale_streak += 1
                        logger.warning(
                            "parallel CD group %d (sweep %d): stale "
                            "regression — realized decrease %.3e < "
                            "predicted %.3e (streak %d)", gi, it,
                            realized, predicted, stale_streak)
                        if (stale_streak >= config.staleness_patience
                                and not fallback_active):
                            fallback_active = True
                            _pcd.record_fallback(it, gi, stale_streak)
                            logger.warning(
                                "parallel CD: staleness guard tripped %d "
                                "consecutive groups — degrading to "
                                "sequential sweeps", stale_streak)
                    else:
                        stale_streak = 0
        _pcd.record_group(sweep=it, group=gi, size=len(train),
                          committed=len(committed),
                          seconds=time.perf_counter() - t0,
                          predicted=predicted, realized=realized,
                          regressed=regressed)

    # one worker pool for the whole run: concurrent-group members are
    # dispatched from threads so their host-side work and device waits
    # interleave; reusing the pool across groups and sweeps avoids
    # per-group executor churn
    group_pool: Optional[ThreadPoolExecutor] = None
    if parallel_spans is not None:
        widest = max((len(m) for _g, m in parallel_spans), default=0)
        if widest > 1:
            group_pool = ThreadPoolExecutor(max_workers=widest,
                                            thread_name_prefix="cd-group")
    try:
        for it in range(start_iter, config.num_iterations):
          with _obs_spans.span("cd/sweep", iteration=it):
            if parallel_spans is not None:
                from photon_tpu.game import parallel_cd as _pcd
                for gi, (g_start, members) in enumerate(parallel_spans):
                    if it == start_iter and g_start + len(members) <= resume_coord_idx:
                        continue  # re-entered sweep: group fully ran pre-restart
                    for cid in members:
                        _chaos.maybe_preempt(it, cid)
                    if _shutdown.requested():
                        # preemption lands on the GROUP boundary
                        path = save_partial(it, g_start, group_boundary=True)
                        _failures.record_failure(
                            "preemption", sweep=it, coordinate=members[0],
                            reason=_shutdown.reason(), checkpoint=path)
                        raise PreemptionRequested(checkpoint_path=path, sweep=it,
                                                  coordinate=members[0])
                    midgroup = it == start_iter and g_start < resume_coord_idx
                    pending = (members[resume_coord_idx - g_start:] if midgroup
                               else members)
                    train = [cid for cid in pending
                             if cid not in config.locked_coordinates]
                    if not train:
                        continue
                    if fallback_active or len(train) == 1 or midgroup:
                        # sequential semantics: staleness fallback, degenerate
                        # group, or re-entry MID-group from a coordinate-
                        # boundary checkpoint (the restored container's
                        # incremental arithmetic must continue exactly)
                        t0 = time.perf_counter()
                        n_committed = 0
                        with _obs_spans.span("cd/group", iteration=it, group=gi,
                                             size=len(train), mode="sequential"):
                            for cid in train:
                                if _train_one(g_start + members.index(cid),
                                              cid, it):
                                    n_committed += 1
                        _pcd.record_group(sweep=it, group=gi, size=len(train),
                                          committed=n_committed,
                                          seconds=time.perf_counter() - t0,
                                          sequentialized=True)
                        continue
                    _run_group(it, gi, g_start, members, train)
            else:
                for k, cid in enumerate(config.update_sequence):
                    if it == start_iter and k < resume_coord_idx:
                        continue  # re-entered sweep: these already ran pre-restart
                    _chaos.maybe_preempt(it, cid)
                    if _shutdown.requested():
                        path = save_partial(it, k)
                        _failures.record_failure(
                            "preemption", sweep=it, coordinate=cid,
                            reason=_shutdown.reason(), checkpoint=path)
                        raise PreemptionRequested(checkpoint_path=path, sweep=it,
                                                  coordinate=cid)
                    if cid in config.locked_coordinates:
                        continue
                    _train_one(k, cid, it)

            resume_coord_idx = 0  # only the re-entered sweep skips coordinates

            # best-model bookkeeping over FULL sweeps (reference :162-171).
            # The final coordinate's post-update validation already scored
            # exactly these models — reuse it instead of a second identical
            # validation pass; metrics_current is None whenever models
            # changed without a fresh validation (or none ran this sweep)
            if validation_fn is not None:
                metrics = (metrics_current if metrics_current is not None
                           else validation_fn(GameModel(dict(models))))
                metrics_current = metrics
                primary = next(iter(metrics.values()))
                is_better = (best_metric is None
                             or (primary > best_metric if primary_metric_bigger_is_better
                                 else primary < best_metric))
                if is_better:
                    best_metric = primary
                    best_model = GameModel(dict(models))
                    best_iter = it

            # canonicalize the running sum at sweep boundaries: a resume
            # rebuilds full_score as a FRESH ordered sum over the models, and
            # bitwise-equal continuation requires the uninterrupted run to
            # hold the same value (incremental "full - own + new" arithmetic
            # drifts in the last ulp)
            full_score = jnp.zeros((num_samples,), dtype)
            for cid in config.update_sequence:
                if cid in scores:
                    full_score = full_score + scores[cid]

            # sweep boundary = the one place replicated state is compared
            # across hosts (collective; every process reaches it together)
            _multihost.check_consistency(models, it)

            ckpt_path = None
            if checkpoint_dir:
                from photon_tpu.game import checkpoint as ckpt
                ckpt_path = ckpt.save_checkpoint(
                    checkpoint_dir, it, models, _counters(),
                    best_models=None if best_model is None else best_model.models,
                    best_metric=best_metric, best_iteration=best_iter,
                    history=history)
            if _shutdown.requested():
                # the sweep-boundary checkpoint just published IS the
                # emergency checkpoint — stop before starting another sweep
                _failures.record_failure("preemption", sweep=it,
                                         reason=_shutdown.reason(),
                                         checkpoint=ckpt_path)
                raise PreemptionRequested(checkpoint_path=ckpt_path, sweep=it)
    finally:
        if group_pool is not None:
            group_pool.shutdown(wait=False)

    final = GameModel(dict(models))
    return CoordinateDescentResult(
        model=final,
        best_model=best_model if best_model is not None else final,
        validation_history=history,
        best_iteration=best_iter,
    )
