"""Mid-training checkpoint/resume for coordinate descent.

SURVEY §5.3: the reference delegates failure recovery to Spark lineage
(recompute lost partitions deterministically); the TPU-native answer is a
sweep-granular checkpoint of everything the continuation depends on —
per-coordinate model arrays, the sweep index, the per-coordinate
down-sampling counters (the PRNG fold-in state), and the best-model
bookkeeping — so a killed run resumes BITWISE-equal to an uninterrupted
one. Scores/full_score are deliberately NOT persisted: they are pure
deterministic functions of the models and are recomputed on resume (the
same trick the reference plays with deterministic reservoir keys,
RandomEffectDataset.scala:212-215).

Layout (one directory per completed sweep, atomic rename on publish):

    <dir>/sweep_0007/
        meta.json              # sweep, counters, best_*, history
        model__<coord>.npz     # arrays of that coordinate's model
        best__<coord>.npz      # arrays of the best-so-far model (if any)
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_tpu.types import TaskType

Array = jax.Array

_SWEEP_PREFIX = "sweep_"


# -- model (de)serialization --------------------------------------------------

def _model_arrays(m) -> Tuple[dict, dict]:
    """(arrays, meta) for one coordinate model."""
    if isinstance(m, FixedEffectModel):
        c = m.model.coefficients
        arrays = {"means": np.asarray(c.means)}
        if c.variances is not None:
            arrays["variances"] = np.asarray(c.variances)
        return arrays, {"kind": "fixed", "task": m.model.task.value,
                        "feature_shard_id": m.feature_shard_id}
    if isinstance(m, RandomEffectModel):
        arrays = {"coefficients": np.asarray(m.coefficients)}
        if m.variances is not None:
            arrays["variances"] = np.asarray(m.variances)
        return arrays, {"kind": "random", "task": m.task.value,
                        "feature_shard_id": m.feature_shard_id,
                        "random_effect_type": m.random_effect_type}
    raise TypeError(f"unknown coordinate model type {type(m).__name__}")


def _model_from_arrays(arrays: dict, meta: dict):
    task = TaskType(meta["task"])
    if meta["kind"] == "fixed":
        coef = Coefficients(
            jnp.asarray(arrays["means"]),
            jnp.asarray(arrays["variances"]) if "variances" in arrays else None)
        return FixedEffectModel(GeneralizedLinearModel(coef, task),
                                meta["feature_shard_id"])
    return RandomEffectModel(
        coefficients=jnp.asarray(arrays["coefficients"]),
        random_effect_type=meta["random_effect_type"],
        feature_shard_id=meta["feature_shard_id"],
        task=task,
        variances=jnp.asarray(arrays["variances"]) if "variances" in arrays
        else None)


# -- checkpoint state ---------------------------------------------------------

@dataclasses.dataclass
class CheckpointState:
    sweep: int                              # last COMPLETED sweep index
    models: Dict[str, object]               # coordinate id -> model
    counters: Dict[str, int]                # coordinate id -> _update_count
    best_models: Optional[Dict[str, object]]
    best_metric: Optional[float]
    best_iteration: Optional[int]
    history: List[Dict[str, float]]


def save_checkpoint(
    directory: str,
    sweep: int,
    models: Dict[str, object],
    counters: Dict[str, int],
    best_models: Optional[Dict[str, object]] = None,
    best_metric: Optional[float] = None,
    best_iteration: Optional[int] = None,
    history: Optional[List[Dict[str, float]]] = None,
) -> str:
    """Atomically publish one sweep's checkpoint; returns its path."""
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, f"{_SWEEP_PREFIX}{sweep:04d}")
    tmp = tempfile.mkdtemp(prefix=".ckpt_tmp_", dir=directory)
    try:
        model_meta = {}
        for cid, m in models.items():
            arrays, meta = _model_arrays(m)
            np.savez(os.path.join(tmp, f"model__{cid}.npz"), **arrays)
            model_meta[cid] = meta
        best_meta = None
        if best_models is not None:
            best_meta = {}
            for cid, m in best_models.items():
                arrays, meta = _model_arrays(m)
                np.savez(os.path.join(tmp, f"best__{cid}.npz"), **arrays)
                best_meta[cid] = meta
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"sweep": sweep,
                       "counters": counters,
                       "models": model_meta,
                       "best_models": best_meta,
                       "best_metric": best_metric,
                       "best_iteration": best_iteration,
                       "history": history or []}, f, indent=2)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    sweeps = sorted(d for d in os.listdir(directory)
                    if d.startswith(_SWEEP_PREFIX)
                    and os.path.isfile(os.path.join(directory, d, "meta.json")))
    return os.path.join(directory, sweeps[-1]) if sweeps else None


def load_checkpoint(path: str) -> CheckpointState:
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)

    def load_models(prefix: str, metas) -> Optional[Dict[str, object]]:
        if metas is None:
            return None
        out = {}
        for cid, m in metas.items():
            with np.load(os.path.join(path, f"{prefix}__{cid}.npz")) as z:
                out[cid] = _model_from_arrays(dict(z), m)
        return out

    return CheckpointState(
        sweep=int(meta["sweep"]),
        models=load_models("model", meta["models"]),
        counters={k: int(v) for k, v in meta["counters"].items()},
        best_models=load_models("best", meta.get("best_models")),
        best_metric=meta.get("best_metric"),
        best_iteration=meta.get("best_iteration"),
        history=meta.get("history") or [],
    )


def load_latest(directory: str) -> Optional[CheckpointState]:
    path = latest_checkpoint(directory)
    return load_checkpoint(path) if path else None
