"""Mid-training checkpoint/resume for coordinate descent.

SURVEY §5.3: the reference delegates failure recovery to Spark lineage
(recompute lost partitions deterministically); the TPU-native answer is a
sweep-granular checkpoint of everything the continuation depends on —
per-coordinate model arrays, the sweep index, the per-coordinate
down-sampling counters (the PRNG fold-in state), and the best-model
bookkeeping — so a killed run resumes BITWISE-equal to an uninterrupted
one. At sweep boundaries scores are NOT persisted: they are pure
deterministic functions of the models and are recomputed on resume.
MID-sweep (preemption / coordinate-failure aborts) they MUST be: the
running ``full_score`` is an incremental sum whose last-ulp rounding
depends on the exact order of updates, and a recomputed sum would break
bitwise-equal continuation. Partial checkpoints therefore carry the score
container verbatim.

Layout (one directory per publish, atomic rename):

    <dir>/sweep_0007/                   # completed sweep 7
    <dir>/sweep_0007_part02/            # preempted DURING sweep 8, about
                                        # to update coordinate index 2
        meta.json              # schema, sweep, counters, best_*, history,
                               # per-file crc32 checksums, partial fields
        model__<coord>.npz     # arrays of that coordinate's model
        best__<coord>.npz      # arrays of the best-so-far model (if any)
        scores__<coord>.npz    # partial only: score container entry
        full_score.npz         # partial only: running sum, verbatim

Naming invariant: lexicographic order == resume order. A partial dir is
named by its LAST COMPLETED sweep, so ``sweep_0007_part02`` sorts after
``sweep_0007`` (strict prefix) and before ``sweep_0008``; a run that was
preempted in its very first sweep publishes ``sweep_-001_part..``, which
sorts before ``sweep_0000`` ('-' < '0').

Durability: every file is fsynced before the rename and the parent
directory after it (a rename is only atomic-durable once the directory
entry itself is on disk). meta.json carries a crc32 per sibling file;
``load_latest`` walks candidates newest-first and SKIPS (with a warning)
any directory whose checksums, JSON, or arrays fail to load — a torn
checkpoint costs one sweep of progress, never the run.
"""

from __future__ import annotations

import dataclasses
import io
import json
import logging
import os
import shutil
import tempfile
import zipfile
import zlib
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_tpu.resilience import chaos as _chaos
from photon_tpu.resilience import io as rio
from photon_tpu.resilience import retry as _retry
from photon_tpu.types import TaskType

Array = jax.Array

logger = logging.getLogger(__name__)

_SWEEP_PREFIX = "sweep_"
# v3: adds ``group_boundary`` — whether a partial checkpoint's
# ``next_coordinate`` is a parallel-mode concurrency-group boundary
# (game/parallel_cd.py) rather than an arbitrary coordinate boundary.
# Resume handles both (a mid-group index re-enters the group with
# sequential semantics); v2 checkpoints load unchanged (flag False).
# v4: adds ``re_block_cursor`` — per-coordinate next-block index for a
# random effect whose BLOCKED update (coordinate.update_model_blocked,
# cold-tier streaming) was mid-stream at preemption. The partial
# checkpoint's model arrays for that coordinate hold the host table as
# of the cursor (solved blocks fresh, later blocks still warm-start);
# resume re-enters update_model_blocked(start_block=cursor,
# warm_start=checkpointed coefficients). v2/v3 checkpoints load
# unchanged (empty cursor map).
SCHEMA_VERSION = 4


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory failed checksum/parse validation."""

    def __init__(self, path: str, detail: str):
        self.path = path
        super().__init__(f"corrupt checkpoint at {path}: {detail}")


# -- model (de)serialization --------------------------------------------------

def _model_arrays(m) -> Tuple[dict, dict]:
    """(arrays, meta) for one coordinate model."""
    if isinstance(m, FixedEffectModel):
        c = m.model.coefficients
        arrays = {"means": np.asarray(c.means)}
        if c.variances is not None:
            arrays["variances"] = np.asarray(c.variances)
        return arrays, {"kind": "fixed", "task": m.model.task.value,
                        "feature_shard_id": m.feature_shard_id}
    if isinstance(m, RandomEffectModel):
        arrays = {"coefficients": np.asarray(m.coefficients)}
        if m.variances is not None:
            arrays["variances"] = np.asarray(m.variances)
        return arrays, {"kind": "random", "task": m.task.value,
                        "feature_shard_id": m.feature_shard_id,
                        "random_effect_type": m.random_effect_type}
    raise TypeError(f"unknown coordinate model type {type(m).__name__}")


def _model_from_arrays(arrays: dict, meta: dict):
    task = TaskType(meta["task"])
    if meta["kind"] == "fixed":
        coef = Coefficients(
            jnp.asarray(arrays["means"]),
            jnp.asarray(arrays["variances"]) if "variances" in arrays else None)
        return FixedEffectModel(GeneralizedLinearModel(coef, task),
                                meta["feature_shard_id"])
    return RandomEffectModel(
        coefficients=jnp.asarray(arrays["coefficients"]),
        random_effect_type=meta["random_effect_type"],
        feature_shard_id=meta["feature_shard_id"],
        task=task,
        variances=jnp.asarray(arrays["variances"]) if "variances" in arrays
        else None)


# -- checkpoint state ---------------------------------------------------------

@dataclasses.dataclass
class CheckpointState:
    sweep: int                              # last COMPLETED sweep index
    models: Dict[str, object]               # coordinate id -> model
    counters: Dict[str, int]                # coordinate id -> _update_count
    best_models: Optional[Dict[str, object]]
    best_metric: Optional[float]
    best_iteration: Optional[int]
    history: List[Dict[str, float]]
    # mid-sweep (partial) state; None/0 for sweep-boundary checkpoints
    sweep_in_progress: Optional[int] = None
    next_coordinate: int = 0
    scores: Optional[Dict[str, np.ndarray]] = None
    full_score: Optional[np.ndarray] = None
    # v3: next_coordinate is a parallel concurrency-group boundary
    group_boundary: bool = False
    # v4: coordinate id -> next block index of a mid-stream blocked
    # random-effect update (empty when no blocked update was in flight)
    re_block_cursor: Dict[str, int] = dataclasses.field(default_factory=dict)


def _npz_bytes(arrays: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def save_checkpoint(
    directory: str,
    sweep: int,
    models: Dict[str, object],
    counters: Dict[str, int],
    best_models: Optional[Dict[str, object]] = None,
    best_metric: Optional[float] = None,
    best_iteration: Optional[int] = None,
    history: Optional[List[Dict[str, float]]] = None,
    sweep_in_progress: Optional[int] = None,
    next_coordinate: int = 0,
    scores: Optional[Dict[str, np.ndarray]] = None,
    full_score: Optional[np.ndarray] = None,
    group_boundary: bool = False,
    re_block_cursor: Optional[Dict[str, int]] = None,
) -> str:
    """Atomically publish one checkpoint; returns its path.

    ``sweep`` is the last COMPLETED sweep (-1 if none). Passing
    ``sweep_in_progress`` publishes a mid-sweep PARTIAL checkpoint (see
    module docstring for naming/resume semantics); partial checkpoints
    must also pass the score container (``scores`` + ``full_score``)
    verbatim for bitwise-equal continuation."""
    os.makedirs(directory, exist_ok=True)
    if sweep_in_progress is not None:
        name = f"{_SWEEP_PREFIX}{sweep:04d}_part{next_coordinate:02d}"
    else:
        name = f"{_SWEEP_PREFIX}{sweep:04d}"
    final = os.path.join(directory, name)

    def _publish() -> None:
        tmp = tempfile.mkdtemp(prefix=".ckpt_tmp_", dir=directory)
        try:
            checksums: Dict[str, int] = {}

            def put(fname: str, data: bytes) -> None:
                with open(os.path.join(tmp, fname), "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                checksums[fname] = zlib.crc32(data)

            model_meta = {}
            for cid, m in models.items():
                arrays, meta = _model_arrays(m)
                put(f"model__{cid}.npz", _npz_bytes(arrays))
                model_meta[cid] = meta
            best_meta = None
            if best_models is not None:
                best_meta = {}
                for cid, m in best_models.items():
                    arrays, meta = _model_arrays(m)
                    put(f"best__{cid}.npz", _npz_bytes(arrays))
                    best_meta[cid] = meta
            if scores is not None:
                for cid, s in scores.items():
                    put(f"scores__{cid}.npz",
                        _npz_bytes({"scores": np.asarray(s)}))
            if full_score is not None:
                put("full_score.npz",
                    _npz_bytes({"full_score": np.asarray(full_score)}))
            meta_doc = {"schema": SCHEMA_VERSION,
                        "sweep": sweep,
                        "counters": counters,
                        "models": model_meta,
                        "best_models": best_meta,
                        "best_metric": best_metric,
                        "best_iteration": best_iteration,
                        "history": history or [],
                        "checksums": checksums,
                        "sweep_in_progress": sweep_in_progress,
                        "next_coordinate": next_coordinate,
                        "group_boundary": group_boundary,
                        "re_block_cursor": re_block_cursor or {},
                        "score_coordinates":
                            None if scores is None else sorted(scores)}
            put("meta.json", json.dumps(meta_doc, indent=2).encode())
            rio.fsync_dir(tmp)
            _chaos.at_publish("checkpoint")
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
            rio.fsync_dir(directory)
        except _chaos.SimulatedKill:
            raise  # a real kill leaves the tmp dir behind; so does this one
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    _retry.with_retries(_publish, op="checkpoint")
    return final


def checkpoint_candidates(directory: str) -> List[str]:
    """All checkpoint directories, oldest first (lexicographic == resume
    order; see module docstring)."""
    if not os.path.isdir(directory):
        return []
    return [os.path.join(directory, d)
            for d in sorted(os.listdir(directory))
            if d.startswith(_SWEEP_PREFIX)
            and os.path.isfile(os.path.join(directory, d, "meta.json"))]


def latest_checkpoint(directory: str) -> Optional[str]:
    cands = checkpoint_candidates(directory)
    return cands[-1] if cands else None


def load_checkpoint(path: str) -> CheckpointState:
    try:
        with open(os.path.join(path, "meta.json"), "rb") as f:
            meta = json.loads(f.read().decode())
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(path, f"meta.json unreadable: {e}")

    checksums = meta.get("checksums")
    if meta.get("schema", 1) >= 2 and checksums is not None:
        for fname, want in checksums.items():
            fpath = os.path.join(path, fname)
            try:
                with open(fpath, "rb") as f:
                    got = zlib.crc32(f.read())
            except OSError as e:
                raise CheckpointCorruptError(path, f"{fname} unreadable: {e}")
            if got != int(want):
                raise CheckpointCorruptError(
                    path, f"{fname} checksum mismatch "
                          f"(want {int(want):#010x}, got {got:#010x})")

    def load_npz(fname: str) -> dict:
        try:
            with np.load(os.path.join(path, fname)) as z:
                return dict(z)
        except (OSError, ValueError, zipfile.BadZipFile) as e:
            raise CheckpointCorruptError(path, f"{fname} unreadable: {e}")

    def load_models(prefix: str, metas) -> Optional[Dict[str, object]]:
        if metas is None:
            return None
        return {cid: _model_from_arrays(load_npz(f"{prefix}__{cid}.npz"), m)
                for cid, m in metas.items()}

    scores = None
    if meta.get("score_coordinates"):
        scores = {cid: load_npz(f"scores__{cid}.npz")["scores"]
                  for cid in meta["score_coordinates"]}
    full_score = None
    if os.path.isfile(os.path.join(path, "full_score.npz")):
        full_score = load_npz("full_score.npz")["full_score"]

    return CheckpointState(
        sweep=int(meta["sweep"]),
        models=load_models("model", meta["models"]),
        counters={k: int(v) for k, v in meta["counters"].items()},
        best_models=load_models("best", meta.get("best_models")),
        best_metric=meta.get("best_metric"),
        best_iteration=meta.get("best_iteration"),
        history=meta.get("history") or [],
        sweep_in_progress=meta.get("sweep_in_progress"),
        next_coordinate=int(meta.get("next_coordinate") or 0),
        scores=scores,
        full_score=full_score,
        group_boundary=bool(meta.get("group_boundary", False)),
        re_block_cursor={k: int(v) for k, v in
                         (meta.get("re_block_cursor") or {}).items()},
    )


def load_latest(directory: str) -> Optional[CheckpointState]:
    """Newest loadable checkpoint, skipping corrupt/partial-write
    directories with a warning (a torn publish must never kill a
    resume — it costs at most one sweep of progress)."""
    for path in reversed(checkpoint_candidates(directory)):
        try:
            return load_checkpoint(path)
        except (CheckpointCorruptError, KeyError) as e:
            logger.warning("skipping unusable checkpoint %s: %s", path, e)
            try:
                from photon_tpu.resilience import failures
                failures.record_failure("checkpoint_corrupt", path=path,
                                        error=str(e))
            except Exception:  # pragma: no cover - telemetry must not fail
                logger.debug("failure-event emission failed", exc_info=True)
    return None
