"""Parallel coordinate descent: concurrency groups + staleness accounting.

The sequential GAME sweep (game/descent.py) updates one coordinate at a
time, so sweep wall-clock is the SUM of per-coordinate solves. This
module holds the host-side scheduling pieces of the parallel sweep mode
(arXiv 1811.01564 "Parallel training of linear models without
compromising convergence"; arXiv 1611.02101 distributed block CD):

- :func:`auto_groups` — the default partition of the update sequence
  into CONTIGUOUS concurrency groups: the fixed effect(s) stay alone,
  consecutive random-effect coordinates merge into one group. Random
  effects touch disjoint coefficient blocks and only couple through the
  shared score container, which the parallel sweep freezes per group —
  so they are the safely-concurrent set. Contiguity is load-bearing:
  the mid-sweep checkpoint contract indexes into the flat update
  sequence (``next_coordinate``), and contiguous groups mean every
  group boundary IS a valid coordinate boundary for resume.
- :func:`validate_groups` — checks a user-supplied
  ``CoordinateDescentConfig.parallel_groups`` override covers the
  update sequence exactly, in order.
- run statistics (:func:`begin_run` / :func:`record_group` /
  :func:`record_fallback` ...) feeding the RunReport ``cd.parallel``
  section (:func:`report_section`), mirroring how serving exposes its
  stats to obs/report.py via ``sys.modules`` — an offline sequential
  run that never imports this module pays nothing.

The actual frozen-score dispatch, reconciliation, and the staleness
guard live in game/descent.py next to the sequential sweep they must
stay in parity with.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# (start index in the flat update sequence, member coordinate ids).
# Members are contiguous: group g covers update_sequence[start:start+len].
GroupSpan = Tuple[int, List[str]]

_MAX_GROUP_RECORDS = 256  # bounded per-group detail ring for the report


def auto_groups(update_sequence: Sequence[str],
                coordinates: Dict[str, object]) -> List[List[str]]:
    """Default grouping by coordinate independence.

    Consecutive random-effect coordinates (identified by their
    ``random_effect_type`` attribute) form one concurrency group; every
    other coordinate — the fixed effect(s) — is a singleton. Singleton
    groups run with exactly the sequential arithmetic, so a sequence
    with no adjacent random effects degenerates to sequential mode.
    """
    groups: List[List[str]] = []
    run: List[str] = []
    for cid in update_sequence:
        if hasattr(coordinates[cid], "random_effect_type"):
            run.append(cid)
        else:
            if run:
                groups.append(run)
                run = []
            groups.append([cid])
    if run:
        groups.append(run)
    return groups


def validate_groups(groups: Sequence[Sequence[str]],
                    update_sequence: Sequence[str]) -> List[List[str]]:
    """A user override must be an in-order partition of the update
    sequence into non-empty contiguous groups (see module docstring for
    why contiguity is required)."""
    out = [list(g) for g in groups]
    if any(not g for g in out):
        raise ValueError("parallel_groups contains an empty group")
    flat = [cid for g in out for cid in g]
    if flat != list(update_sequence):
        raise ValueError(
            f"parallel_groups must partition the update sequence in order: "
            f"flattened groups {flat!r} != update_sequence "
            f"{list(update_sequence)!r}")
    return out


def resolve_groups(config, coordinates: Dict[str, object]) -> List[GroupSpan]:
    """Concrete (start, members) spans for this config — user override
    when given, :func:`auto_groups` otherwise."""
    if config.parallel_groups is not None:
        groups = validate_groups(config.parallel_groups,
                                 config.update_sequence)
    else:
        groups = auto_groups(config.update_sequence, coordinates)
    spans: List[GroupSpan] = []
    k = 0
    for g in groups:
        spans.append((k, g))
        k += len(g)
    return spans


# -- run statistics (RunReport cd.parallel section) ---------------------------

class _Stats:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.runs = 0
        self.groups: List[List[str]] = []
        self.placement: Optional[Dict[str, List[int]]] = None
        self.groups_run = 0
        self.concurrent_groups = 0
        self.members_solved = 0
        self.member_failures = 0
        self.stale_regressions = 0
        self.fallbacks = 0
        self.sequentialized_groups = 0
        self.group_records: List[Dict[str, Any]] = []


_stats = _Stats()


def reset() -> None:
    """Test isolation: drop all accumulated statistics."""
    global _stats
    _stats = _Stats()


def begin_run(spans: Sequence[GroupSpan],
              placement: Optional[Dict[str, List[int]]] = None) -> None:
    with _stats.lock:
        _stats.runs += 1
        _stats.groups = [list(members) for _start, members in spans]
        if placement is not None:
            _stats.placement = {cid: list(devs)
                                for cid, devs in placement.items()}


def record_group(sweep: int, group: int, size: int, committed: int,
                 seconds: float,
                 predicted: Optional[float] = None,
                 realized: Optional[float] = None,
                 regressed: bool = False,
                 sequentialized: bool = False) -> None:
    from photon_tpu.obs.metrics import registry
    registry.counter("cd.parallel.groups").inc()
    registry.counter("cd.parallel.members").inc(size)
    with _stats.lock:
        _stats.groups_run += 1
        _stats.members_solved += size
        if sequentialized:
            _stats.sequentialized_groups += 1
        else:
            _stats.concurrent_groups += 1
        if regressed:
            _stats.stale_regressions += 1
        rec: Dict[str, Any] = {"sweep": sweep, "group": group, "size": size,
                               "committed": committed,
                               "seconds": round(seconds, 6)}
        if predicted is not None:
            rec["predicted_decrease"] = predicted
            rec["realized_decrease"] = realized
            rec["stale_regression"] = regressed
        if sequentialized:
            rec["sequentialized"] = True
        _stats.group_records.append(rec)
        del _stats.group_records[:-_MAX_GROUP_RECORDS]
    if regressed:
        registry.counter("cd.parallel.stale_regressions").inc()


def record_member_failure(coordinate: str, sweep: int) -> None:
    from photon_tpu.obs.metrics import registry
    registry.counter("cd.parallel.member_failures").inc()
    with _stats.lock:
        _stats.member_failures += 1


def record_fallback(sweep: int, group: int, streak: int) -> None:
    """Staleness tripped the convergence guard ``staleness_patience``
    groups in a row: typed event + counter, never an exception — the
    run continues sequentially."""
    from photon_tpu.obs.metrics import registry
    from photon_tpu.resilience import failures
    registry.counter("cd.parallel.fallbacks").inc()
    failures.record_failure("parallel_staleness_fallback", sweep=sweep,
                            group=group, consecutive_regressions=streak)
    with _stats.lock:
        _stats.fallbacks += 1


def report_section() -> Optional[Dict[str, Any]]:
    """The RunReport ``cd`` section (obs/report.py reads it via
    ``sys.modules`` so sequential-only processes pay nothing). ``None``
    until a parallel run actually started."""
    with _stats.lock:
        if _stats.runs == 0:
            return None
        section: Dict[str, Any] = {
            "runs": _stats.runs,
            "groups": [list(g) for g in _stats.groups],
            "groups_run": _stats.groups_run,
            "concurrent_groups": _stats.concurrent_groups,
            "sequentialized_groups": _stats.sequentialized_groups,
            "members_solved": _stats.members_solved,
            "member_failures": _stats.member_failures,
            "stale_regressions": _stats.stale_regressions,
            "fallbacks": _stats.fallbacks,
            "group_records": list(_stats.group_records),
        }
        if _stats.placement is not None:
            section["placement"] = dict(_stats.placement)
    return {"parallel": section}
