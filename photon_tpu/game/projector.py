"""Per-entity feature-space projectors.

Reference: photon-api projector/Projector.scala:20-33 (projectFeatures /
projectCoefficients), ProjectorType.scala:17-28 (RANDOM = shared Gaussian
random projection, INDEX_MAP = per-entity compact reindex [default],
IDENTITY), ProjectionMatrixBroadcast.scala:15 (one broadcast projection
matrix shared by all entities), IndexMapProjectorRDD.scala:19.

TPU re-design: INDEX_MAP is the gather-table pipeline built by
build_random_effect_dataset. RANDOM is implemented here: one deterministic
Gaussian matrix P [proj_dim, D] (seeded, never materialized per entity)
projects every sample's sparse row to a dense proj_dim vector at ingest —
a [nnz] scatter-matmul — and back-projects trained coefficients to the
original space for persistence (margin invariance: w.(Px) = (P^T w).x).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Tuple

import numpy as np


class ProjectorType(enum.Enum):
    """Reference: ProjectorType.scala:17-28."""

    INDEX_MAP = "INDEX_MAP"
    RANDOM = "RANDOM"
    IDENTITY = "IDENTITY"


@dataclasses.dataclass(frozen=True)
class RandomProjection:
    """Shared Gaussian projection (ProjectionMatrixBroadcast analog)."""

    original_dim: int
    projected_dim: int
    seed: int = 0

    def matrix(self) -> np.ndarray:
        """P [proj_dim, D], entries N(0, 1/proj_dim) — deterministic."""
        rng = np.random.default_rng(self.seed)
        return rng.normal(size=(self.projected_dim, self.original_dim)) \
            / np.sqrt(self.projected_dim)

    def project_rows(self, rows) -> np.ndarray:
        """Sparse rows [(idx, val)] -> dense [n, proj_dim]."""
        P = self.matrix()
        out = np.zeros((len(rows), self.projected_dim))
        for i, (idx, val) in enumerate(rows):
            if len(idx):
                out[i] = P[:, idx] @ val
        return out

    def project_dense(self, X: np.ndarray) -> np.ndarray:
        return X @ self.matrix().T

    def back_project_coefficients(self, coef: np.ndarray) -> np.ndarray:
        """[..., proj_dim] projected-space coefficients -> [..., D]
        original-space equivalents (w.(Px) == (P^T w).x)."""
        return np.asarray(coef) @ self.matrix()
