"""Random-effect datasets: entity-blocked, size-bucketed, projected.

Reference: photon-api data/RandomEffectDataset.scala (activeData grouped
per-entity :46-55; build pipeline :207-340 — bounded groupBy via
deterministic reservoir sampling with byteswap64 ordering keys :212-215,
lower-bound filtering :319-340, Pearson feature selection :305, passive
split :264), data/LocalDataset.scala (Pearson correlation :122),
data/RandomEffectDataConfiguration (:68), projector/IndexMapProjectorRDD
.scala:19,24,156 (per-entity compact reindex of observed features),
data/MinHeapWithFixedCapacity.scala:29.

TPU re-design: the groupByKey shuffle becomes fully-vectorized numpy
grouping over a CSR view of the shard (no per-sample Python loops);
entities are bucketed by power-of-two active-sample count into a few
padded ELL blocks — a MovieLens-style power-law entity distribution no
longer pays S_max padding for every entity (SURVEY §7 risk (a)).
Per-entity index-map projection is a static [E, D_loc] gather table;
passive (score-only) samples are a flat gather-scored array. Reservoir
capping orders samples by splitmix64(uid) — deterministic under
recomputation exactly like the reference's byteswap64 trick.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from photon_tpu.game.dataset import EntityVocabulary, GameDataFrame
from photon_tpu.ops import features as F

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Reference: RandomEffectDataConfiguration (CoordinateDataConfiguration
    .scala:68) incl. projectorType."""

    random_effect_type: str
    feature_shard_id: str
    active_data_lower_bound: Optional[int] = None   # min samples per entity
    active_data_upper_bound: Optional[int] = None   # reservoir cap
    features_to_samples_ratio: Optional[float] = None  # Pearson cap
    keep_passive_data: bool = True
    # ProjectorType.INDEX_MAP (default) | RANDOM | IDENTITY; RANDOM needs
    # projected_dimension (reference: ProjectorType.scala, RandomProjection)
    projector_type: str = "INDEX_MAP"
    projected_dimension: Optional[int] = None
    projection_seed: int = 0
    # cap on the number of padded size buckets: every distinct [E_b, S_b,
    # K_b] block shape is a separate XLA compile inside the one jitted
    # solve, so a long-tailed entity distribution must trade padding for
    # compile count (VERDICT r2 weak #8; no reference analog — Spark has
    # no compilation step). None/0 = uncapped.
    max_entity_buckets: Optional[int] = 16

    def random_projection(self, original_dim: int):
        from photon_tpu.game.projector import ProjectorType, RandomProjection

        if ProjectorType(self.projector_type) != ProjectorType.RANDOM:
            return None
        assert self.projected_dimension, \
            "RANDOM projector needs projected_dimension"
        return RandomProjection(original_dim, self.projected_dimension,
                                self.projection_seed)


class EntityBlock(NamedTuple):
    """One size bucket of entities, padded to [E_b, S_b] / [E_b, S_b, K_b].
    All pads carry weight 0; ``entity_rows`` maps block rows to global
    entity rows (out-of-range = pad row)."""

    features: F.SparseFeatures        # indices/values [E_b, S_b, K_b] LOCAL slots
    labels: Array                     # [E_b, S_b]
    offsets: Array                    # [E_b, S_b]
    weights: Array                    # [E_b, S_b] (0 on pads)
    sample_rows: Array                # [E_b, S_b] int32 row in flat frame (n on pads)
    entity_rows: Array                # [E_b] int32 global entity row

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    @property
    def max_samples(self) -> int:
        return self.labels.shape[1]


class RandomEffectDataset(NamedTuple):
    """Device-resident bucketed entity blocks + passive split + projection."""

    blocks: Tuple[EntityBlock, ...]
    # passive (score-only) samples, in LOCAL slots
    passive_features: F.SparseFeatures  # [P, K]
    passive_entity: Array               # [P] int32 global entity row (E on pads)
    passive_rows: Array                 # [P] int32 flat row (n on pads)
    # projection table: local slot -> global feature index (-1 unused)
    projection: Array                 # [E, D_loc] int32

    @property
    def num_entities(self) -> int:
        return self.projection.shape[0]

    @property
    def max_samples(self) -> int:
        return max((b.max_samples for b in self.blocks), default=0)

    @property
    def projected_dim(self) -> int:
        return self.projection.shape[1]

    def padding_waste(self) -> float:
        """(padded cells) / (real cells) over sample slots — the bucketing
        quality metric (SURVEY §7 risk (a))."""
        padded = sum(b.labels.size for b in self.blocks)
        real = sum(int(jnp.sum(b.weights > 0)) for b in self.blocks)
        return padded / max(real, 1)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Deterministic sample-ordering hash (role of byteswap64(uid),
    RandomEffectDataset.scala:212-215)."""
    z = (x.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _csr_of(rows) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sparse rows -> (indptr [n+1], cols, vals); CSR-form rows from the
    native columnar ingest pass straight through; a dense [n, d] matrix
    is converted (vectorized) so dense feature shards work for random
    effects too."""
    from photon_tpu.game.dataset import CsrRows

    if isinstance(rows, CsrRows):
        return (rows.indptr, np.asarray(rows.cols, np.int64),
                np.asarray(rows.vals, np.float64))
    if isinstance(rows, np.ndarray):
        dense = np.asarray(rows, np.float64)
        r, cols = np.nonzero(dense)
        indptr = np.concatenate(
            [[0], np.cumsum(np.bincount(r, minlength=dense.shape[0]))])
        return indptr.astype(np.int64), cols.astype(np.int64), dense[r, cols]
    nnz = np.fromiter((len(r[0]) for r in rows), np.int64, len(rows))
    indptr = np.concatenate([[0], np.cumsum(nnz)])
    if len(rows):
        cols = np.concatenate([np.asarray(r[0], np.int64) for r in rows])
        vals = np.concatenate([np.asarray(r[1], np.float64) for r in rows])
    else:
        cols = np.zeros(0, np.int64)
        vals = np.zeros(0)
    return indptr, cols, vals


def _bucket_of(sizes: np.ndarray) -> np.ndarray:
    """Power-of-two size bucket id (sizes >= 1)."""
    return np.ceil(np.log2(np.maximum(sizes, 1))).astype(np.int64)


def build_random_effect_dataset(
    df: GameDataFrame,
    config: RandomEffectDataConfiguration,
    vocab: EntityVocabulary,
    dtype=np.float32,
    scores_offsets: Optional[np.ndarray] = None,
) -> RandomEffectDataset:
    """Fully-vectorized ingest: grouping, deterministic reservoir capping,
    Pearson feature selection, per-entity projection, bucketed ELL fill,
    passive split — no per-sample Python loops."""
    re_type = config.random_effect_type
    shard = df.feature_shards[config.feature_shard_id]
    # sparse row lists, columnar CsrRows, and dense [n, d] matrices all
    # funnel through _csr_of into the same columnar pipeline
    shard = _maybe_random_project(shard, config)
    n = df.num_samples
    D = shard.dim

    entity_idx = vocab.build(re_type, df.id_tags[re_type]).astype(np.int64)
    E = vocab.size(re_type)
    base_offsets = np.zeros(n) if df.offsets is None else np.asarray(df.offsets, np.float64)
    if scores_offsets is not None:
        base_offsets = base_offsets + np.asarray(scores_offsets, np.float64)
    weights = np.ones(n) if df.weights is None else np.asarray(df.weights, np.float64)
    resp = np.asarray(df.response, np.float64)

    indptr, cols, vals = _csr_of(shard.rows)
    nnz = np.diff(indptr)

    # -- deterministic ordering within entities + active/passive split -------
    counts = np.bincount(entity_idx, minlength=E)
    keys = _splitmix64(np.arange(n, dtype=np.uint64))
    order = np.lexsort((keys, entity_idx))           # by (entity, hash)
    starts = np.concatenate([[0], np.cumsum(counts)])
    pos = np.arange(n) - np.repeat(starts[:-1], counts)  # rank within entity

    e_sorted = entity_idx[order]
    active_sorted = np.ones(n, bool)
    if config.active_data_lower_bound is not None:
        active_sorted &= counts[e_sorted] >= config.active_data_lower_bound
    if config.active_data_upper_bound is not None:
        active_sorted &= pos < config.active_data_upper_bound
    passive_sorted = ~active_sorted
    if config.active_data_upper_bound is not None and not config.keep_passive_data:
        # over-cap samples are dropped entirely; below-lower-bound samples
        # stay passive (they are scored, just never trained on)
        over_cap = pos >= config.active_data_upper_bound
        if config.active_data_lower_bound is not None:
            over_cap &= counts[e_sorted] >= config.active_data_lower_bound
        passive_sorted &= ~over_cap

    active = np.zeros(n, bool)
    active[order] = active_sorted
    passive = np.zeros(n, bool)
    passive[order] = passive_sorted
    act_counts = np.bincount(entity_idx[active], minlength=E)

    # -- observed (entity, feature) pairs over ACTIVE data -------------------
    s_nz = np.repeat(np.arange(n), nnz)              # sample id per nonzero
    keep_nz = active[s_nz]
    e_nz = entity_idx[s_nz]
    pair = e_nz * D + cols                            # int64 composite key
    uniq = np.unique(pair[keep_nz]) if keep_nz.any() else np.zeros(0, np.int64)

    # -- optional Pearson feature selection (reference: LocalDataset:122) ----
    if config.features_to_samples_ratio is not None and len(uniq):
        ratio = config.features_to_samples_ratio
        k_per_entity = np.maximum((ratio * act_counts).astype(np.int64), 1)
        scores = _pearson_scores_vectorized(
            uniq, pair, keep_nz, vals, s_nz, entity_idx, resp, weights,
            active, E, D)
        u_e = uniq // D
        sel_order = np.lexsort((-scores, u_e))
        u_starts = np.searchsorted(u_e[sel_order], np.arange(E))
        sel_pos = np.arange(len(uniq)) - u_starts[u_e[sel_order]]
        need_cap = k_per_entity[u_e[sel_order]]
        keep_pair = np.zeros(len(uniq), bool)
        keep_pair[sel_order[sel_pos < need_cap]] = True
        # entities whose feature count is within bound keep everything
        feat_counts = np.bincount(u_e, minlength=E)
        within = feat_counts[u_e] <= np.maximum(
            (ratio * act_counts[u_e]).astype(np.int64), 1)
        keep_pair |= within
        uniq = uniq[keep_pair]

    # -- projection table ----------------------------------------------------
    u_e = uniq // D
    u_f = uniq % D
    d_loc_per_entity = np.bincount(u_e, minlength=E) if len(uniq) else np.zeros(E, np.int64)
    D_loc = max(int(d_loc_per_entity.max()) if E else 1, 1)
    u_starts = np.searchsorted(u_e, np.arange(E + 1))
    slot_of_pair = np.arange(len(uniq)) - u_starts[u_e]
    projection = np.full((E, D_loc), -1, np.int32)
    if len(uniq):
        projection[u_e, slot_of_pair] = u_f.astype(np.int32)

    # -- per-nonzero local slots (kept nonzeros only) ------------------------
    rank = np.searchsorted(uniq, pair) if len(uniq) else np.zeros(len(pair), np.int64)
    rank = np.minimum(rank, max(len(uniq) - 1, 0))
    kept_nz_mask = np.zeros(len(pair), bool)
    if len(uniq):
        kept_nz_mask = uniq[rank] == pair
    slot_nz = slot_of_pair[rank] if len(uniq) else np.zeros(len(pair), np.int64)

    # position of each kept nonzero within its sample
    def _slot_positions(mask: np.ndarray) -> np.ndarray:
        if not len(pair):
            return np.zeros(0, np.int64)
        kept_i = mask.astype(np.int64)
        c = np.cumsum(kept_i)
        excl = c - kept_i
        # indptr may equal total_nnz for trailing empty rows; those repeat
        # zero times, so clamp the index to keep the gather in range
        base = np.repeat(excl[np.minimum(indptr[:-1], len(excl) - 1)], nnz)
        return excl - base

    # -- bucketed active blocks ---------------------------------------------
    has_active = act_counts > 0
    bucket_id = np.where(has_active, _bucket_of(act_counts), -1)
    uniq_buckets = np.unique(bucket_id[bucket_id >= 0])
    cap = config.max_entity_buckets
    if cap and len(uniq_buckets) > cap:
        # coarsen: merge adjacent pow-2 buckets into at most `cap` groups
        # (each group pads to its largest member's S_b) — bounded compile
        # count at the cost of extra padding, both reported below
        groups = np.array_split(uniq_buckets, cap)
        lut = np.arange(int(uniq_buckets.max()) + 1)
        for g in groups:
            lut[g] = g[-1]
        bucket_id = np.where(bucket_id >= 0, lut[np.maximum(bucket_id, 0)], -1)
    blocks: List[EntityBlock] = []

    # active samples sorted by (entity, hash) and within cap
    act_idx_sorted = order[active_sorted]             # flat rows, grouped
    act_pos = pos[active_sorted]                      # rank within entity
    act_entity = entity_idx[act_idx_sorted]

    k_nz_pos_all = _slot_positions(kept_nz_mask & active[s_nz])

    for b in np.unique(bucket_id[bucket_id >= 0]):
        ents = np.flatnonzero(bucket_id == b)         # global entity rows
        E_b = len(ents)
        S_b = int(act_counts[ents].max())
        # block row per global entity
        row_of_entity = np.full(E, -1, np.int64)
        row_of_entity[ents] = np.arange(E_b)

        in_b = row_of_entity[act_entity] >= 0
        rows_flat = act_idx_sorted[in_b]              # flat sample rows
        r_idx = row_of_entity[act_entity[in_b]]
        c_idx = act_pos[in_b]

        labels_b = np.zeros((E_b, S_b), dtype)
        offsets_b = np.zeros((E_b, S_b), dtype)
        weights_b = np.zeros((E_b, S_b), dtype)
        rows_b = np.full((E_b, S_b), n, np.int32)
        labels_b[r_idx, c_idx] = resp[rows_flat]
        offsets_b[r_idx, c_idx] = base_offsets[rows_flat]
        weights_b[r_idx, c_idx] = weights[rows_flat]
        rows_b[r_idx, c_idx] = rows_flat

        # ELL features: nonzeros of this bucket's active samples
        nz_mask = kept_nz_mask & active[s_nz] & (row_of_entity[e_nz] >= 0)
        nz_sample = s_nz[nz_mask]
        nz_r = row_of_entity[e_nz[nz_mask]]
        # column of the sample within the block
        pos_of_sample = np.full(n, -1, np.int64)
        pos_of_sample[act_idx_sorted[in_b]] = c_idx
        nz_c = pos_of_sample[nz_sample]
        nz_k = k_nz_pos_all[nz_mask]
        K_b = max(int(nz_k.max()) + 1 if len(nz_k) else 1, 1)

        f_idx = np.zeros((E_b, S_b, K_b), np.int32)
        f_val = np.zeros((E_b, S_b, K_b), dtype)
        f_idx[nz_r, nz_c, nz_k] = slot_nz[nz_mask].astype(np.int32)
        f_val[nz_r, nz_c, nz_k] = vals[nz_mask]

        blocks.append(EntityBlock(
            features=F.SparseFeatures(jnp.asarray(f_idx), jnp.asarray(f_val)),
            labels=jnp.asarray(labels_b),
            offsets=jnp.asarray(offsets_b),
            weights=jnp.asarray(weights_b),
            sample_rows=jnp.asarray(rows_b),
            entity_rows=jnp.asarray(ents.astype(np.int32)),
        ))

    # -- passive block (projected through each entity's local map) -----------
    pas_rows = np.flatnonzero(passive)
    P = max(len(pas_rows), 1)
    pas_nz_mask = kept_nz_mask & passive[s_nz]
    pas_k = _slot_positions(pas_nz_mask)
    K_p = max(int(pas_k[pas_nz_mask].max()) + 1 if pas_nz_mask.any() else 1, 1)
    p_idx = np.zeros((P, K_p), np.int32)
    p_val = np.zeros((P, K_p), dtype)
    p_entity = np.full(P, E, np.int32)
    p_rows = np.full(P, n, np.int32)
    if len(pas_rows):
        row_rank = np.full(n, -1, np.int64)
        row_rank[pas_rows] = np.arange(len(pas_rows))
        p_entity[: len(pas_rows)] = entity_idx[pas_rows]
        p_rows[: len(pas_rows)] = pas_rows
        sel = pas_nz_mask
        p_idx[row_rank[s_nz[sel]], pas_k[sel]] = slot_nz[sel].astype(np.int32)
        p_val[row_rank[s_nz[sel]], pas_k[sel]] = vals[sel]

    ds = RandomEffectDataset(
        blocks=tuple(blocks),
        passive_features=F.SparseFeatures(jnp.asarray(p_idx), jnp.asarray(p_val)),
        passive_entity=jnp.asarray(p_entity),
        passive_rows=jnp.asarray(p_rows),
        projection=jnp.asarray(projection),
    )
    # ingest telemetry (VERDICT r2 weak #8): block count == distinct XLA
    # compiles for this coordinate's solve; padding_waste == padded/real
    # sample cells
    logger.info(
        "random-effect %r ingest: %d entities, %d block(s) (bucket cap %s), "
        "padding waste %.3f, shapes %s",
        re_type, E, len(ds.blocks), cap,
        ds.padding_waste(),
        [(b.num_rows, b.max_samples, b.features.values.shape[-1])
         for b in ds.blocks])
    return ds


def _maybe_random_project(shard, config: RandomEffectDataConfiguration):
    """RANDOM projector: replace the shard with dense rows in the shared
    Gaussian-projected space (the pipeline then treats every projected dim
    as observed for every entity)."""
    from photon_tpu.game.dataset import FeatureShard

    rp = config.random_projection(shard.dim)
    if rp is None:
        return shard
    dense = (rp.project_dense(np.asarray(shard.rows, np.float64))
             if shard.is_dense else rp.project_rows(shard.rows))
    from photon_tpu.game.dataset import CsrRows

    # columnar handover (every projected dim is observed for every row):
    # no per-row Python tuples — _csr_of passes CsrRows straight through
    return FeatureShard(CsrRows.from_dense(dense), rp.projected_dim)


def _pearson_scores_vectorized(uniq, pair, keep_nz, vals, s_nz, entity_idx,
                               resp, weights, active, E, D) -> np.ndarray:
    """|Pearson corr(feature, label)| per observed (entity, feature) pair
    over active samples (reference: LocalDataset.computePearsonCorrelation
    Score :122; constant nonzero columns — intercepts — score 1)."""
    act_counts = np.bincount(entity_idx[active], minlength=E).astype(np.float64)
    # per-entity label stats over active samples
    lab_sum = np.bincount(entity_idx[active], weights=resp[active], minlength=E)
    lab_sq = np.bincount(entity_idx[active], weights=resp[active] ** 2, minlength=E)
    with np.errstate(invalid="ignore", divide="ignore"):
        lab_mean = lab_sum / act_counts
        lab_var = lab_sq / act_counts - lab_mean ** 2
    lab_sd = np.sqrt(np.maximum(lab_var, 0))

    m = keep_nz
    rank = np.searchsorted(uniq, pair[m])
    v = vals[m]
    y = resp[s_nz[m]]
    nfeat = len(uniq)
    sums = np.bincount(rank, weights=v, minlength=nfeat)
    sqs = np.bincount(rank, weights=v * v, minlength=nfeat)
    u_e = uniq // D
    ly = y - lab_mean[u_e[rank]]
    xy = np.bincount(rank, weights=v * ly, minlength=nfeat)

    cnt = act_counts[u_e]
    with np.errstate(invalid="ignore", divide="ignore"):
        mean = sums / cnt
        var = sqs / cnt - mean ** 2
        corr = np.abs(xy / cnt) / np.sqrt(np.maximum(var, 0)) / np.maximum(
            lab_sd[u_e], 1e-12)
    corr[~np.isfinite(corr)] = 0.0
    is_const = (var <= 1e-12) & (np.abs(mean) > 0)
    corr[is_const] = 1.0
    return corr


def project_for_scoring(
    df: GameDataFrame,
    config: RandomEffectDataConfiguration,
    vocab: EntityVocabulary,
    projection: np.ndarray,
    dtype=np.float32,
) -> Tuple[F.SparseFeatures, Array]:
    """Project an evaluation frame into each sample's entity-local feature
    space (reference: IndexMapProjector applied to scoring data). Unseen
    entities -> entity index E (out of range => zero score); unmapped
    features are dropped. Fully vectorized."""
    shard = df.feature_shards[config.feature_shard_id]
    shard = _maybe_random_project(shard, config)
    n = df.num_samples
    D = shard.dim
    proj_np = np.asarray(projection)
    E, d_loc = proj_np.shape

    entity_idx = vocab.lookup(config.random_effect_type,
                              df.id_tags[config.random_effect_type]).astype(np.int64)
    ent_out = np.where(entity_idx < 0, E, entity_idx).astype(np.int32)

    # (entity, feature) -> slot lookup table, rebuilt from the projection
    valid = proj_np >= 0
    pe, ps = np.nonzero(valid)
    pkeys = pe.astype(np.int64) * D + proj_np[pe, ps]
    # projection rows are slot-ordered by ascending feature id, so pkeys
    # is sorted within each entity and across entities
    porder = np.argsort(pkeys, kind="stable")
    pkeys_sorted = pkeys[porder]
    pslots_sorted = ps[porder].astype(np.int64)

    indptr, cols, vals = _csr_of(shard.rows)
    nnz = np.diff(indptr)
    s_nz = np.repeat(np.arange(n), nnz)
    e_nz = entity_idx[s_nz]
    in_vocab = e_nz >= 0
    key_nz = np.where(in_vocab, e_nz, 0) * D + cols
    rank = np.searchsorted(pkeys_sorted, key_nz)
    rank = np.minimum(rank, max(len(pkeys_sorted) - 1, 0))
    kept = in_vocab & (len(pkeys_sorted) > 0)
    if len(pkeys_sorted):
        kept &= pkeys_sorted[rank] == key_nz
    slot_nz = pslots_sorted[rank] if len(pkeys_sorted) else np.zeros(len(cols), np.int64)

    if len(cols):
        kept_i = kept.astype(np.int64)
        c = np.cumsum(kept_i)
        excl = c - kept_i
        base = np.repeat(excl[np.minimum(indptr[:-1], len(excl) - 1)], nnz)
        k_pos = excl - base
    else:
        k_pos = np.zeros(0, np.int64)

    K = max(int(k_pos[kept].max()) + 1 if kept.any() else 1, 1)
    out_idx = np.zeros((n, K), np.int32)
    out_val = np.zeros((n, K), dtype)
    out_idx[s_nz[kept], k_pos[kept]] = slot_nz[kept].astype(np.int32)
    out_val[s_nz[kept], k_pos[kept]] = vals[kept]
    return (F.SparseFeatures(jnp.asarray(out_idx), jnp.asarray(out_val)),
            jnp.asarray(ent_out))


# -- cold-tier warm starts ----------------------------------------------------

def replay_cold_rows(ds_proj: np.ndarray, cold_proj: np.ndarray,
                     cold_coef: np.ndarray) -> np.ndarray:
    """Map cold-store coefficient rows into this dataset's local slot
    layout by global column id.

    Both layouts are slot-sorted ascending with -1 padding (the dataset
    by construction, the cold store normalized at write —
    io/cold_store.py), but the two column SETS can differ: the cold model
    may have been trained on a different sample of each entity's
    features. Columns present in both carry their cold value; dataset
    slots with no cold counterpart warm-start at zero."""
    if ds_proj.shape[0] != cold_proj.shape[0]:
        raise ValueError(
            f"row count mismatch: {ds_proj.shape[0]} dataset rows vs "
            f"{cold_proj.shape[0]} cold rows")
    # pairwise column match per entity; slot widths are small, so the
    # [E_b, D, K] broadcast stays cheap relative to the mmap read itself
    eq = ((ds_proj[:, :, None] == cold_proj[:, None, :])
          & (ds_proj[:, :, None] >= 0))
    hit = eq.any(axis=2)
    pos = eq.argmax(axis=2)
    vals = np.take_along_axis(
        np.asarray(cold_coef, np.float32), pos, axis=1)
    return np.where(hit, vals, np.float32(0.0))


def warm_start_from_cold_store(cold, entity_names: Sequence[str],
                               projection, *,
                               block_rows: int = 262144) -> np.ndarray:
    """Stream a ``ColdStore`` into a host-RAM warm-start block aligned to
    this dataset's entity rows and slot layout.

    ``entity_names[r]`` is the entity id of dataset row ``r`` (the ingest
    vocabulary's ordering). Entities absent from the cold store — new
    since the warm model was written — start at zero. Peak memory is the
    host [E, K] output plus one streamed block; nothing touches the
    device."""
    proj = np.asarray(projection)
    out = np.zeros(proj.shape, np.float32)
    row_of = {str(name): r for r, name in enumerate(entity_names)}
    for _lo, ids, coef_b, proj_b in cold.iter_blocks(block_rows):
        rows = np.fromiter((row_of.get(str(i), -1) for i in ids),
                           np.int64, count=len(ids))
        sel = rows >= 0
        if not sel.any():
            continue
        ds_rows = rows[sel]
        out[ds_rows] = replay_cold_rows(proj[ds_rows], proj_b[sel],
                                        np.asarray(coef_b)[sel])
    return out
