"""Random-effect datasets: entity-blocked, padded, projected.

Reference: photon-api data/RandomEffectDataset.scala (activeData grouped
per-entity :46-55; build pipeline :207-340 — bounded groupBy via
deterministic reservoir sampling with byteswap64 ordering keys :212-215,
lower-bound filtering :319-340, Pearson feature selection :305, passive
split :264), data/LocalDataset.scala (Pearson correlation :122),
data/RandomEffectDataConfiguration (:68), projector/IndexMapProjectorRDD
.scala:19,24,156 (per-entity compact reindex of observed features),
data/MinHeapWithFixedCapacity.scala:29.

TPU re-design: the groupByKey shuffle becomes ingest-time numpy grouping;
per-entity index-map projection becomes a static [E, D_loc] gather table;
active data is ONE padded block ([E, S] samples, ELL features in local
slots) sharded over the mesh's entity axis; passive (score-only) samples
are a flat gather-scored array. Reservoir capping orders samples by
splitmix64(uid) — deterministic under recomputation exactly like the
reference's byteswap64 trick, without needing it for fault tolerance
(pure functions recompute identically anyway).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.game.dataset import EntityVocabulary, GameDataFrame
from photon_tpu.ops import features as F

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Reference: RandomEffectDataConfiguration (CoordinateDataConfiguration
    .scala:68)."""

    random_effect_type: str
    feature_shard_id: str
    active_data_lower_bound: Optional[int] = None   # min samples per entity
    active_data_upper_bound: Optional[int] = None   # reservoir cap
    features_to_samples_ratio: Optional[float] = None  # Pearson cap
    keep_passive_data: bool = True


class RandomEffectDataset(NamedTuple):
    """Device-resident entity blocks (all pads carry weight 0)."""

    # active block
    features: F.SparseFeatures        # indices/values [E, S, K] in LOCAL slots
    labels: Array                     # [E, S]
    offsets: Array                    # [E, S]
    weights: Array                    # [E, S] (0 on pads)
    sample_rows: Array                # [E, S] int32 row in flat frame (n on pads)
    # passive (score-only) samples
    passive_features: F.SparseFeatures  # [P, K] local slots
    passive_entity: Array               # [P] int32 entity row (E on pads)
    passive_rows: Array                 # [P] int32 flat row (n on pads)
    # projection table: local slot -> global feature index (-1 unused)
    projection: Array                 # [E, D_loc] int32

    @property
    def num_entities(self) -> int:
        return self.labels.shape[0]

    @property
    def max_samples(self) -> int:
        return self.labels.shape[1]

    @property
    def projected_dim(self) -> int:
        return self.projection.shape[1]


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Deterministic sample-ordering hash (role of byteswap64(uid),
    RandomEffectDataset.scala:212-215)."""
    z = (x.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _pearson_scores(rows, labels, dim) -> np.ndarray:
    """|Pearson corr| per observed global feature within one entity
    (reference: LocalDataset.computePearsonCorrelationScore :122).
    Constant features get score ~0 except the intercept-like all-constant
    column, which the reference keeps (score 1)."""
    n = len(rows)
    sums = np.zeros(dim)
    sq_sums = np.zeros(dim)
    xy = np.zeros(dim)
    seen = np.zeros(dim, bool)
    ly = labels - labels.mean()
    for i, (idx, val) in enumerate(rows):
        sums[idx] += val
        sq_sums[idx] += val * val
        xy[idx] += val * ly[i]
        seen[idx] = True
    mean = sums / n
    var = sq_sums / n - mean * mean
    label_sd = labels.std()
    with np.errstate(invalid="ignore", divide="ignore"):
        corr = np.abs(xy / n) / np.sqrt(np.maximum(var, 0)) / max(label_sd, 1e-12)
    corr[~np.isfinite(corr)] = 0.0
    # constant nonzero column across all samples (intercept) -> keep
    is_const = seen & (var <= 1e-12) & (np.abs(mean) > 0)
    corr[is_const] = 1.0
    corr[~seen] = -1.0
    return corr


def build_random_effect_dataset(
    df: GameDataFrame,
    config: RandomEffectDataConfiguration,
    vocab: EntityVocabulary,
    dtype=np.float32,
    scores_offsets: Optional[np.ndarray] = None,
) -> RandomEffectDataset:
    """Ingest-time grouping/capping/projection (the reference's whole
    RandomEffectDataset build pipeline, minus the shuffles)."""
    re_type = config.random_effect_type
    shard = df.feature_shards[config.feature_shard_id]
    assert not shard.is_dense, "random-effect shards use sparse rows"
    rows = shard.rows
    n = df.num_samples

    entity_idx = vocab.build(re_type, df.id_tags[re_type])
    base_offsets = df.offsets if df.offsets is not None else np.zeros(n)
    if scores_offsets is not None:
        base_offsets = base_offsets + scores_offsets
    weights = df.weights if df.weights is not None else np.ones(n)

    # group sample row-ids per entity
    order = np.argsort(entity_idx, kind="stable")
    groups: Dict[int, np.ndarray] = {}
    sorted_e = entity_idx[order]
    bounds = np.searchsorted(sorted_e, np.arange(vocab.size(re_type) + 1))
    for e in range(vocab.size(re_type)):
        groups[e] = order[bounds[e]:bounds[e + 1]]

    E = vocab.size(re_type)
    active: Dict[int, np.ndarray] = {}
    passive: List[Tuple[int, int]] = []  # (entity, row)
    lower = config.active_data_lower_bound
    upper = config.active_data_upper_bound
    for e in range(E):
        g = groups[e]
        if lower is not None and len(g) < lower:
            # below lower bound: all samples become passive (score-only);
            # the entity keeps a zero model (reference drops the entity
            # from training, RandomEffectDataset.scala:319-340)
            passive.extend((e, int(r)) for r in g)
            active[e] = g[:0]
            continue
        if upper is not None and len(g) > upper:
            keys = _splitmix64(g.astype(np.uint64))
            keep = g[np.argsort(keys, kind="stable")[:upper]]
            kept_set = set(keep.tolist())
            active[e] = keep
            if config.keep_passive_data:
                passive.extend((e, int(r)) for r in g if int(r) not in kept_set)
        else:
            active[e] = g

    # per-entity feature selection + local projection
    projections: List[np.ndarray] = []
    local_maps: List[Dict[int, int]] = []
    d_loc_max = 1
    for e in range(E):
        g = active[e]
        observed: Dict[int, None] = {}
        for r in g:
            for j in rows[r][0]:
                observed.setdefault(int(j), None)
        obs = np.asarray(list(observed.keys()), np.int64)
        ratio = config.features_to_samples_ratio
        if ratio is not None and len(g) > 0 and len(obs) > ratio * len(g):
            k = max(int(ratio * len(g)), 1)
            scores = _pearson_scores([rows[r] for r in g],
                                     np.asarray(df.response, np.float64)[g],
                                     shard.dim)
            top = np.argsort(-scores[obs], kind="stable")[:k]
            obs = obs[np.sort(top)]
        lm = {int(j): s for s, j in enumerate(obs)}
        local_maps.append(lm)
        projections.append(obs)
        d_loc_max = max(d_loc_max, len(obs))

    S = max((len(active[e]) for e in range(E)), default=1) or 1
    K = min(shard.max_nnz(), d_loc_max) or 1

    feat_idx = np.zeros((E, S, K), np.int32)
    feat_val = np.zeros((E, S, K), dtype)
    labels_b = np.zeros((E, S), dtype)
    offsets_b = np.zeros((E, S), dtype)
    weights_b = np.zeros((E, S), dtype)
    rows_b = np.full((E, S), n, np.int32)
    resp = np.asarray(df.response, np.float64)

    for e in range(E):
        lm = local_maps[e]
        for s, r in enumerate(active[e]):
            idx, val = rows[r]
            kk = 0
            for j, v in zip(idx, val):
                slot = lm.get(int(j))
                if slot is not None:
                    feat_idx[e, s, kk] = slot
                    feat_val[e, s, kk] = v
                    kk += 1
            labels_b[e, s] = resp[r]
            offsets_b[e, s] = base_offsets[r]
            weights_b[e, s] = weights[r]
            rows_b[e, s] = r

    proj = np.full((E, d_loc_max), -1, np.int32)
    for e in range(E):
        proj[e, : len(projections[e])] = projections[e]

    # passive block
    P = max(len(passive), 1)
    p_idx = np.zeros((P, K), np.int32)
    p_val = np.zeros((P, K), dtype)
    p_entity = np.full(P, E, np.int32)
    p_rows = np.full(P, n, np.int32)
    for p, (e, r) in enumerate(passive):
        lm = local_maps[e]
        idx, val = rows[r]
        kk = 0
        for j, v in zip(idx, val):
            slot = lm.get(int(j))
            if slot is not None and kk < K:
                p_idx[p, kk] = slot
                p_val[p, kk] = v
                kk += 1
        p_entity[p] = e
        p_rows[p] = r

    return RandomEffectDataset(
        features=F.SparseFeatures(jnp.asarray(feat_idx), jnp.asarray(feat_val)),
        labels=jnp.asarray(labels_b),
        offsets=jnp.asarray(offsets_b),
        weights=jnp.asarray(weights_b),
        sample_rows=jnp.asarray(rows_b),
        passive_features=F.SparseFeatures(jnp.asarray(p_idx), jnp.asarray(p_val)),
        passive_entity=jnp.asarray(p_entity),
        passive_rows=jnp.asarray(p_rows),
        projection=jnp.asarray(proj),
    )


def project_for_scoring(
    df: GameDataFrame,
    config: RandomEffectDataConfiguration,
    vocab: EntityVocabulary,
    projection: np.ndarray,
    dtype=np.float32,
) -> Tuple[F.SparseFeatures, Array]:
    """Project an evaluation frame into each sample's entity-local feature
    space (reference: IndexMapProjector applied to scoring data). Unseen
    entities -> entity index E (out of range => zero score); unmapped
    features are dropped."""
    shard = df.feature_shards[config.feature_shard_id]
    rows = shard.rows
    n = df.num_samples
    entity_idx = vocab.lookup(config.random_effect_type, df.id_tags[config.random_effect_type])
    E, d_loc = projection.shape

    local_maps: List[Dict[int, int]] = []
    proj_np = np.asarray(projection)
    for e in range(E):
        lm = {int(j): s for s, j in enumerate(proj_np[e]) if j >= 0}
        local_maps.append(lm)

    K = min(shard.max_nnz() or 1, d_loc)
    out_idx = np.zeros((n, K), np.int32)
    out_val = np.zeros((n, K), dtype)
    ent = np.empty(n, np.int32)
    for i in range(n):
        e = int(entity_idx[i])
        ent[i] = e if e >= 0 else E
        if e < 0:
            continue
        lm = local_maps[e]
        idx, val = rows[i]
        kk = 0
        for j, v in zip(idx, val):
            slot = lm.get(int(j))
            if slot is not None and kk < K:
                out_idx[i, kk] = slot
                out_val[i, kk] = v
                kk += 1
    return (F.SparseFeatures(jnp.asarray(out_idx), jnp.asarray(out_val)),
            jnp.asarray(ent))
