"""Double-buffered entity-block staging for blocked random-effect training.

``update_model_blocked`` used to stream buckets strictly sequentially:
host→device copy of bucket b, solve, host copy-back, repeat — the
staging time of every bucket sat on the critical path. This module moves
staging onto a prefetch thread with the consumption-token fence pattern
of ``data/streaming.ChunkLoader``: while bucket b solves on device, the
reader stages bucket b+1 from host RAM (or wherever the dataset's block
pytree lives — on real hardware this is the H2D DMA the solve hides).

Fence protocol (the part that keeps a lagging async solve from ever
seeing a recycled buffer):

- the reader holds ``depth`` staging tokens; it stages a bucket only
  after acquiring one, so at most ``depth`` buckets are in flight —
  host+device staging memory is bounded by the planner's
  double-buffered footprint (parallel/memory), never by ladder length;
- the reader fences its OWN transfer (``block_until_ready`` on the
  staged pytree, reader thread only — never the consumer's solve path)
  before publishing, so the consumer dequeues fully-landed arrays;
- the consumer returns the token via :meth:`BlockPrefetcher.release`
  only after the bucket's results are back on the host, which is the
  proof the solve consumed the staged arrays.

Chaos hooks ``chaos.re_block_read_delay`` / ``chaos.re_block_read_error``
fire inside the reader (the error path retried under the
``resilience/retry`` env knobs), so fault injection exercises the real
overlap path. The reader also keeps the busy/stall clocks that
``utils/flops.re_block_overlap`` turns into the pipeline's overlap
gauges.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional, Sequence

import jax

from photon_tpu.resilience import chaos
from photon_tpu.resilience.retry import RetryPolicy, with_retries

_SENTINEL = object()


def staged_bytes(tree) -> int:
    """Total array bytes of a staged block pytree (the measured side of
    the planner's ``data_bytes``)."""
    return sum(int(getattr(leaf, "nbytes", 0))
               for leaf in jax.tree_util.tree_leaves(tree))


class BlockPrefetcher:
    """Stage entity blocks ``start_block..`` onto the device one bucket
    ahead of the solve loop.

    The consumer calls :meth:`get` (blocking) once per bucket, in
    ascending order, and :meth:`release` after copying that bucket's
    results back to the host; :meth:`close` joins the thread (idempotent
    — call it in a ``finally``)."""

    def __init__(self, blocks: Sequence, *, start_block: int = 0,
                 depth: int = 2, device=None,
                 policy: Optional[RetryPolicy] = None):
        self._blocks = blocks
        self._start = int(start_block)
        self._device = device
        self._policy = policy or RetryPolicy.from_env()
        self._out: "queue.Queue" = queue.Queue()
        self._tokens: "queue.Queue" = queue.Queue()
        for _ in range(max(1, int(depth))):
            self._tokens.put(None)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        # pipeline clocks for flops.re_block_overlap
        self.reader_busy_s = 0.0
        self.consumer_stall_s = 0.0
        self.bytes_staged = 0
        self.blocks_staged = 0
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(
            target=self._run, name="re-block-prefetch", daemon=True)
        self._thread.start()

    # -- reader side ---------------------------------------------------

    def _stage(self, bi: int):
        def read():
            chaos.re_block_read_error()
            delay = chaos.re_block_read_delay()
            if delay:
                time.sleep(delay)
            staged = jax.device_put(self._blocks[bi], self._device)
            # buffer-recycle fence on the READER thread (the streaming
            # loader's pattern): the consumer must dequeue fully-landed
            # arrays, and the solve path itself stays sync-free
            jax.block_until_ready(staged)  # host-sync-ok: reader-side staging fence
            return staged

        return with_retries(read, op="re.block_read", policy=self._policy)

    def _run(self) -> None:
        try:
            for bi in range(self._start, len(self._blocks)):
                # consumption-token fence: wait for a free staging slot
                while True:
                    if self._stop.is_set():
                        return
                    try:
                        self._tokens.get(timeout=0.1)
                        break
                    except queue.Empty:
                        continue
                t0 = time.perf_counter()
                staged = self._stage(bi)
                self.reader_busy_s += time.perf_counter() - t0
                self.bytes_staged += staged_bytes(staged)
                self.blocks_staged += 1
                self._out.put((bi, staged))
            self._out.put(_SENTINEL)
        except BaseException as e:  # surfaces on the consumer's get()
            self._error = e
            self._out.put(_SENTINEL)

    # -- consumer side -------------------------------------------------

    def get(self, bi: int):
        """Blocking dequeue of bucket ``bi``'s staged block (buckets are
        produced in order; time spent here is consumer stall — the part
        of staging the pipeline failed to hide)."""
        t0 = time.perf_counter()
        item = self._out.get()
        self.consumer_stall_s += time.perf_counter() - t0
        if item is _SENTINEL:
            if self._error is not None:
                raise self._error
            raise RuntimeError(
                f"block prefetcher exhausted before bucket {bi}")
        got, staged = item
        if got != bi:
            raise RuntimeError(
                f"block prefetcher out of order: wanted {bi}, got {got}")
        return staged

    def release(self) -> None:
        """Return one staging token — the consumer's proof that the
        bucket's results are back on the host and its staged arrays are
        consumable."""
        self._tokens.put(None)

    @property
    def wall_s(self) -> float:
        return time.perf_counter() - self._t0

    def close(self) -> None:
        """Stop and join the reader (idempotent; safe mid-stream — e.g.
        a ``SimulatedKill`` unwinding the solve loop)."""
        self._stop.set()
        # unblock a reader parked on a token or let a finished one exit
        try:
            while True:
                self._out.get_nowait()
        except queue.Empty:
            pass
        self._tokens.put(None)
        self._thread.join(timeout=5.0)
