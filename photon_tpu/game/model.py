"""GAME model containers: fixed-effect and random-effect models.

Reference: photon-api model/FixedEffectModel.scala:32 (broadcast GLM +
feature shard), model/RandomEffectModel.scala:36 (RDD[(REId, GLM)] +
random-effect type + shard; scoring = hash-join on REId), photon-lib
model/GameModel.scala:32 (Map[CoordinateId -> DatumScoringModel] with
type-consistency check), model/DatumScoringModel.scala:27-53.

TPU re-design: a random-effect model is ONE dense [E, K] coefficient block
in per-entity projected feature space (the IndexMapProjector equivalent is
a static gather table built at ingest). The RDD hash-join becomes
``coef_block[entity_index]`` — a gather. Entities are dense integer rows;
the string REIds live in a host-side vocabulary.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """GLM + feature shard id (reference: FixedEffectModel.scala:32)."""

    model: GeneralizedLinearModel
    feature_shard_id: str

    @property
    def task(self) -> TaskType:
        return self.model.task


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity coefficient block in projected space.

    ``coefficients``: [E, K] — row e is entity e's model over its projected
    (local) feature slots; ``variances`` optional [E, K].
    Entity row 0..E-1 indexes the ingest-time vocabulary (host side).
    Unseen entities at scoring time get index -1 -> zero contribution.
    """

    coefficients: Array
    random_effect_type: str
    feature_shard_id: str
    task: TaskType
    variances: Optional[Array] = None

    @property
    def num_entities(self) -> int:
        return self.coefficients.shape[0]

    @property
    def projected_dim(self) -> int:
        return self.coefficients.shape[1]


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Map coordinate-id -> model, with task consistency
    (reference: GameModel.scala:32,161)."""

    models: Dict[str, object]  # FixedEffectModel | RandomEffectModel

    def __post_init__(self):
        tasks = {m.task for m in self.models.values()}
        if len(tasks) > 1:
            raise ValueError(f"inconsistent task types in GAME model: {tasks}")

    def __getitem__(self, coordinate_id: str):
        return self.models[coordinate_id]

    def __contains__(self, coordinate_id: str) -> bool:
        return coordinate_id in self.models

    @property
    def coordinate_ids(self):
        return list(self.models.keys())

    @property
    def task(self) -> TaskType:
        return next(iter(self.models.values())).task

    def updated(self, coordinate_id: str, model) -> "GameModel":
        new = dict(self.models)
        new[coordinate_id] = model
        return GameModel(new)
