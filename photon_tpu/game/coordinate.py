"""GAME coordinates: per-coordinate training + scoring.

Reference: photon-lib algorithm/Coordinate.scala:60-63 (update against
residual-injected offsets), photon-api algorithm/FixedEffectCoordinate
.scala:136-165 (update = DistributedOptimizationProblem.runWithSampling,
score = broadcast dot), algorithm/RandomEffectCoordinate.scala:104-232
(update = co-partitioned join + per-entity local solves in mapValues;
score = join + dot + passive broadcast scoring), ModelCoordinate.scala:28
(frozen coordinates for partial retraining).

TPU re-design: the fixed effect trains one jitted solve over the sharded
flat batch; the random effect trains ALL entities at once with a vmap-ed
L-BFGS over the entity-blocked dataset (per-entity convergence masking via
the while_loop batching rule) — the reference's millions of independent
Breeze solves become one SPMD program on the entity-sharded mesh axis.
Residual injection is a gather; score emission is a scatter-add.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import DataBatch
from photon_tpu.data.sampling import maybe_downsample
from photon_tpu.function.objective import GLMObjective, Hyper
from photon_tpu.game.model import FixedEffectModel, RandomEffectModel
from photon_tpu.game.random_effect import EntityBlock, RandomEffectDataset
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_tpu.ops import features as F
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.optim import lbfgs, owlqn, tron
from photon_tpu.optim.base import FailureMode
from photon_tpu.optim.problem import (
    GLMOptimizationConfiguration,
    GlmOptimizationProblem,
    solver_cache_key,
)
from photon_tpu.types import OptimizerType, TaskType
from photon_tpu.obs.spans import annotate as _obs_annotate, span as _obs_span
from photon_tpu.utils import jitcache

Array = jax.Array


@jax.jit
def _fixed_score(feats, coef: Array) -> Array:
    # data enters as an argument, never a closure: closed-over arrays
    # would be baked into the HLO as giant literal constants
    return F.matvec(feats, coef)


@jax.jit
def _fixed_score_lanes(feats, coefs: Array) -> Array:
    # lane-batched validation/score pass for the sweep path: one shared
    # data read for all K coefficient lanes (ops/features.matvec_lanes)
    return F.matvec_lanes(feats, coefs)


class FixedEffectCoordinate:
    """Reference: FixedEffectCoordinate.scala:136-165."""

    def __init__(
        self,
        batch: DataBatch,
        dim: int,
        feature_shard_id: str,
        task: TaskType,
        config: GLMOptimizationConfiguration = GLMOptimizationConfiguration(),
        norm=None,
        sampling_key: Optional[jax.Array] = None,
        mesh=None,
        variance_type=None,
        intercept_index: Optional[int] = None,
    ):
        from photon_tpu.ops.normalization import (
            NormalizationContext,
            no_normalization,
        )
        from photon_tpu.types import VarianceComputationType

        self.variance_type = variance_type or VarianceComputationType.NONE

        self._n_orig = batch.num_samples
        self._model_sharded = False
        self._dim_padded = dim
        if mesh is not None:
            from photon_tpu.parallel import mesh as M
            model_par = (M.MODEL_AXIS in mesh.axis_names
                         and M.axis_size(mesh, M.MODEL_AXIS) > 1)
            if model_par:
                # feature-dimension (tensor-parallel) sharding for theta
                # bigger than one chip's HBM (SURVEY §5.7). Dense: X placed
                # P(data, model), theta P(model); XLA turns the partial
                # dots of matvec/rmatvec into all-reduces over the model
                # axis. Sparse: nonzeros are re-partitioned at ingest into
                # per-feature-range blocks with LOCAL ids — the billion-
                # coefficient workload the reference serves with
                # partitioned PalDB indexes (PalDBIndexMap.scala:43) — in a
                # DUAL layout: ELL rows for the margin gather (matvec) and
                # a column-sorted CSC plan for contiguous-segment gradient
                # reductions (rmatvec), built once here at construction
                # (ops/features.ModelShardedSparse; mesh.shard_sparse_
                # features_model_parallel). Margins/gradients psum over the
                # model/data axes via shard_map, staging the gradient
                # all-reduce ICI-then-DCN on a two-level mesh, and the
                # L-BFGS solve itself runs margin-resident
                # (optim/lbfgs.minimize_directional via problem.run).
                if isinstance(batch.features, F.SparseFeatures):
                    if self.variance_type == VarianceComputationType.FULL:
                        raise ValueError(
                            "FULL variance needs the dense d x d Hessian, "
                            "which contradicts model-axis sharding of a "
                            "sparse theta; use SIMPLE variance or a "
                            "data-parallel mesh for this coordinate")
                    batch = M.shard_sparse_features_model_parallel(
                        batch, mesh, dim)
                    self._dim_padded = batch.features.padded_dim
                else:
                    batch = M.shard_features_model_parallel(batch, mesh)
                    self._dim_padded = batch.features.shape[1]
                self._model_sharded = True
                if norm is not None and not norm.is_identity:
                    # pad the context to the padded feature dim
                    pad = self._dim_padded - dim
                    norm = NormalizationContext(
                        None if norm.factors is None else jnp.pad(
                            norm.factors, (0, pad), constant_values=1.0),
                        None if norm.shifts is None else jnp.pad(
                            norm.shifts, (0, pad)))
            else:
                # sample-shard once at construction; every solve and score
                # pass then runs SPMD over the data axis
                batch = M.shard_batch(batch, mesh)
        self.batch = batch
        self.dim = dim
        self.feature_shard_id = feature_shard_id
        self.task = task
        self.config = config
        self.problem = GlmOptimizationProblem(task, config,
                                              norm or no_normalization(),
                                              intercept_index=intercept_index)
        if config.optimizer.optimizer_type == OptimizerType.SDCA:
            # config-time typed refusal (SdcaUnsupportedLossError) for
            # tasks whose loss has no conjugate dual step (Poisson) —
            # don't wait for the first sweep to fail mid-fit
            from photon_tpu.optim.sdca import validate_loss
            validate_loss(loss_for_task(task).name)
        self._sampling_key = sampling_key
        self._update_count = 0
        self.mesh = mesh

    def update_model(
        self, prev: Optional[FixedEffectModel], residual_scores: Optional[Array]
    ) -> FixedEffectModel:
        """Train against residual-injected offsets
        (= dataset.addScoresToOffsets + runWithSampling).

        ``residual_scores`` is either the live partial score (sequential
        sweep) or a frozen group-entry snapshot (parallel sweep) — the
        solve is a pure function of it either way."""
        batch = self.batch
        if residual_scores is not None:
            extra = batch.num_samples - residual_scores.shape[0]
            if extra:  # mesh padding: zero residual on zero-weight pad rows
                residual_scores = jnp.pad(residual_scores, (0, extra))
            batch = batch.add_scores_to_offsets(residual_scores)
        if getattr(self, "_chaos_poison_once", False):
            # fault injection (resilience/chaos.py): a NaN offset poisons
            # the first objective evaluation exactly like a corrupt
            # upstream residual would
            self._chaos_poison_once = False
            batch = batch.add_scores_to_offsets(
                jnp.full((batch.num_samples,), jnp.nan, batch.labels.dtype))
        if self._sampling_key is not None and self.config.down_sampling_rate < 1.0:
            # fresh subsample per coordinate-descent sweep (the reference
            # draws a new down-sample on every update)
            key = jax.random.fold_in(self._sampling_key, self._update_count)
            self._update_count += 1
            batch = maybe_downsample(batch, self.task,
                                     self.config.down_sampling_rate, key)
        init = prev.model.coefficients.means if prev is not None else None
        if self._model_sharded:
            from photon_tpu.parallel import mesh as M
            # theta lives P(model): pad to the sharded feature dim and
            # place; zero-init also placed so the solve is fully SPMD
            init = jnp.zeros((self.dim,), batch.labels.dtype) \
                if init is None else jnp.asarray(init)
            init = M.shard_coef_model_parallel(init, self.mesh,
                                               padded_dim=self._dim_padded)
        with _obs_annotate("fe/solve"):
            model, result = self.problem.run(
                batch, initial=init, dim=self.dim, dtype=batch.labels.dtype,
                # read the weight from the coordinate's (possibly
                # sweep-updated) config, not the problem's
                # construction-time copy
                regularization_weight=self.config.regularization_weight,
                # this coordinate's batch was sharded at construction; the
                # pallas kernel must not trace over mesh-placed arrays
                pallas_ok=self.mesh is None)
        from photon_tpu.optim.tracking import OptimizationStatesTracker
        self.last_result = result
        self.last_tracker = OptimizationStatesTracker.from_result(result)
        # one scalar host read at the coordinate boundary (never inside the
        # solve): the descent driver must branch on failure in Python to
        # roll the coordinate back
        self.last_failure = None
        if result.failure is not None:
            code = int(np.asarray(result.failure))
            if code != FailureMode.NONE:
                self.last_failure = FailureMode(code)
        from photon_tpu.types import VarianceComputationType
        if self.variance_type != VarianceComputationType.NONE:
            # reference: DistributedOptimizationProblem.run computes
            # variances on the same (residual-injected) data as the solve
            var = self.problem.compute_variances(
                batch, model.coefficients.means, self.variance_type,
                regularization_weight=self.config.regularization_weight)
            if var is not None:
                model = GeneralizedLinearModel(
                    Coefficients(model.coefficients.means, var), model.task)
        if self._model_sharded and self._dim_padded != self.dim:
            # publish at the true feature dim; padding stays internal
            c = model.coefficients
            model = GeneralizedLinearModel(
                Coefficients(c.means[: self.dim],
                             None if c.variances is None
                             else c.variances[: self.dim]), model.task)
        return FixedEffectModel(model, self.feature_shard_id)

    def score(self, model: FixedEffectModel) -> Array:
        """Training-data scores WITHOUT offsets — coordinate-descent score
        algebra sums raw model scores (reference: scoreForCoordinateDescent).
        Mesh pad rows are sliced off so score algebra stays [n]."""
        coef = model.model.coefficients.means
        if self._model_sharded:
            from photon_tpu.parallel import mesh as M
            coef = M.shard_coef_model_parallel(jnp.asarray(coef), self.mesh,
                                               padded_dim=self._dim_padded)
        with _obs_annotate("fe/score"):
            s = _fixed_score(self.batch.features, coef)
        if s.shape[0] != self._n_orig:
            s = s[: self._n_orig]
        return s

    def update_model_swept(self, prev: Optional[FixedEffectModel],
                           residual_scores: Optional[Array],
                           weights,
                           initial_lanes: Optional[Array] = None):
        """Fit the whole regularization grid ``weights`` against the same
        residual-injected batch as ONE lane-batched program
        (optim/problem.solve_swept) — the per-coordinate sweep that used
        to cost K sequential ``update_model`` calls and K data passes.

        ``initial_lanes [K, d]`` warm-starts each lane independently
        (tuner rounds warm-start every lane from the previous round's
        best); otherwise every lane starts from ``prev``'s coefficients.
        Returns the :class:`~photon_tpu.optim.problem.SweptSolve`;
        per-lane failures stay per-lane (a poisoned lane freezes typed
        without sinking its siblings). Sweep telemetry: ``sweep.*``
        metrics + the RunReport ``sweep`` section.
        """
        if self._model_sharded:
            raise ValueError(
                "lane-batched sweeps are not supported on model-axis "
                "sharded coordinates: K lanes hold K full coefficient "
                "vectors, which contradicts a range-sharded theta — sweep "
                "this coordinate sequentially")
        from photon_tpu.obs.metrics import registry
        from photon_tpu.optim import batched
        batch = self.batch
        if residual_scores is not None:
            extra = batch.num_samples - residual_scores.shape[0]
            if extra:  # mesh padding: zero residual on zero-weight pad rows
                residual_scores = jnp.pad(residual_scores, (0, extra))
            batch = batch.add_scores_to_offsets(residual_scores)
        if getattr(self, "_chaos_poison_once", False):
            # fault injection (resilience/chaos.py): poisons every lane's
            # shared data term, like a corrupt upstream residual
            self._chaos_poison_once = False
            batch = batch.add_scores_to_offsets(
                jnp.full((batch.num_samples,), jnp.nan, batch.labels.dtype))
        if self._sampling_key is not None and self.config.down_sampling_rate < 1.0:
            key = jax.random.fold_in(self._sampling_key, self._update_count)
            self._update_count += 1
            batch = maybe_downsample(batch, self.task,
                                     self.config.down_sampling_rate, key)
        init = prev.model.coefficients.means if prev is not None else None
        with _obs_annotate("fe/solve_swept"):
            # the coordinate's batch was (possibly) sharded at
            # construction, so the solve gets mesh=None: GSPMD follows
            # the input placement exactly as in update_model
            swept = self.problem.solve_swept(
                batch, weights, initial=init, initial_lanes=initial_lanes,
                dim=self.dim, dtype=batch.labels.dtype)
        # host boundary: per-lane scalars for telemetry + failure typing
        iters = np.asarray(swept.stacked.iterations)
        reasons = np.asarray(swept.stacked.reason)
        fails = (np.zeros_like(iters) if swept.stacked.failure is None
                 else np.asarray(swept.stacked.failure))
        losses = np.asarray(swept.stacked.value)
        self.last_lane_failures = [
            None if code == FailureMode.NONE else FailureMode(int(code))
            for code in fails]
        registry.gauge("sweep.lanes_active").set(
            int(np.sum(fails == FailureMode.NONE)))
        hist = registry.histogram(
            "sweep.lane_iterations",
            buckets=(1, 2, 5, 10, 20, 50, 100, 200, 500))
        for it in iters:
            hist.observe(float(it))
        lams = batched.validate_lane_weights(weights)
        batched.record_sweep_run([
            {"weight": float(lams[i]), "loss": float(losses[i]),
             "iterations": int(iters[i]), "reason": int(reasons[i]),
             "failure": int(fails[i])}
            for i in range(len(lams))])
        return swept

    def score_lanes(self, coefs: Array) -> Array:
        """Training-data scores for K coefficient lanes ``[K, d] ->
        [K, n]`` — one shared feature pass (the sweep counterpart of
        ``score``). Mesh pad rows are sliced off per lane."""
        if self._model_sharded:
            raise ValueError(
                "score_lanes is not supported on model-axis sharded "
                "coordinates (see update_model_swept)")
        with _obs_annotate("fe/score_lanes"):
            s = _fixed_score_lanes(self.batch.features, jnp.asarray(coefs))
        if s.shape[1] != self._n_orig:
            s = s[:, : self._n_orig]
        return s

    @functools.cached_property
    def _objective_value_fn(self):
        obj = GLMObjective(loss_for_task(self.task))

        def build():
            @jax.jit
            def value(feats, labels, offsets, weights, coef, l2):
                return obj.value(coef, DataBatch(feats, labels, offsets,
                                                 weights), Hyper(l2_weight=l2))
            return value

        return jitcache.get_or_build(("fe_objval", self.task), build)

    def objective_value(self, model: Optional[FixedEffectModel],
                        residual_scores: Optional[Array]) -> Optional[Array]:
        """L2-regularized objective of ``model`` against a residual
        snapshot, as a DEVICE scalar (no host sync — the parallel-CD
        staleness guard sums these and reads one bool per group).
        ``None`` when the coordinate is model-axis sharded: the guard is
        skipped there rather than re-deriving the shard_map margin
        machinery for a diagnostic."""
        if self._model_sharded:
            return None
        batch = self.batch
        if residual_scores is not None:
            extra = batch.num_samples - residual_scores.shape[0]
            if extra:  # mesh padding: zero residual on zero-weight pad rows
                residual_scores = jnp.pad(residual_scores, (0, extra))
            batch = batch.add_scores_to_offsets(residual_scores)
        coef = (jnp.zeros((self.dim,), batch.labels.dtype) if model is None
                else jnp.asarray(model.model.coefficients.means))
        l2 = jnp.asarray(self.config.regularization.l2_weight(
            self.config.regularization_weight), batch.labels.dtype)
        return self._objective_value_fn(batch.features, batch.labels,
                                        batch.offsets, batch.weights,
                                        coef, l2)

    def predicted_decrease(self, prev: Optional[FixedEffectModel],
                           new: FixedEffectModel,
                           residual_scores: Optional[Array]
                           ) -> Optional[Array]:
        """Solver-predicted objective decrease for ``prev -> new`` against
        the FROZEN residual the solve actually saw (device scalar)."""
        a = self.objective_value(prev, residual_scores)
        b = self.objective_value(new, residual_scores)
        return None if a is None or b is None else a - b

    @functools.cached_property
    def _data_loss_fn(self):
        loss = loss_for_task(self.task)

        def build():
            @jax.jit
            def value(labels, offsets, weights, scores):
                l, _ = loss.loss_and_dz(offsets + scores, labels)
                return jnp.sum(l * weights) if weights is not None \
                    else jnp.sum(l)
            return value

        return jitcache.get_or_build(("fe_dataloss", self.task), build)

    def data_loss_at(self, total_scores: Array) -> Array:
        """Weighted GLM data loss at a TOTAL score vector (no features, no
        regularization), as a device scalar: ``sum_i w_i * l(y_i,
        base_offset_i + s_i)``. This is the score-space primitive of the
        parallel-CD staleness guard: every objective difference the guard
        needs is a difference of these at score vectors the group
        reconciliation already materialized, so the guard costs O(n)
        elementwise work instead of per-member feature passes (see
        descent._run_group). Mesh pad rows carry zero weight and
        contribute exactly 0."""
        batch = self.batch
        extra = batch.num_samples - total_scores.shape[0]
        if extra:
            total_scores = jnp.pad(total_scores, (0, extra))
        return self._data_loss_fn(batch.labels, batch.offsets,
                                  batch.weights, total_scores)


class RandomEffectCoordinate:
    """Reference: RandomEffectCoordinate.scala:104-232 — redesigned as one
    vmapped solve over the entity-blocked dataset."""

    def __init__(
        self,
        dataset: RandomEffectDataset,
        num_flat_samples: int,
        random_effect_type: str,
        feature_shard_id: str,
        task: TaskType,
        config: GLMOptimizationConfiguration = GLMOptimizationConfiguration(),
        mesh=None,
        variance_type=None,
        norm=None,
        intercept_index: Optional[int] = None,
    ):
        from photon_tpu.types import VarianceComputationType

        self.variance_type = variance_type or VarianceComputationType.NONE
        self._num_entities_orig = dataset.num_entities
        if mesh is not None:
            from photon_tpu.parallel import mesh as M
            # entity-shard once at construction (the co-partitioning
            # replacement); the vmapped solves are independent per entity,
            # so this axis runs collective-free
            dataset = M.shard_entity_blocks(dataset, mesh,
                                            num_flat_samples=num_flat_samples)
        self.dataset = dataset
        self.n = num_flat_samples
        self.random_effect_type = random_effect_type
        self.feature_shard_id = feature_shard_id
        self.task = task
        self.config = config
        self.objective = GLMObjective(loss_for_task(task))
        self.mesh = mesh
        # per-entity normalization (reference: NormalizationContextWrapper):
        # the shard-level [D] context is gathered through each entity's
        # projection into local-slot space; pad slots get factor 1, shift 0
        self._norm_local = self._build_local_norm(norm, intercept_index)

    def _build_local_norm(self, norm, intercept_index: Optional[int]):
        """Gather a shard-space NormalizationContext [D] into per-entity
        local-slot arrays aligned with this dataset's projection table:
        (factors [E, D_loc], shifts [E, D_loc] | None, islot [E]).
        ``islot`` is each entity's local slot of the intercept feature
        (-1 when unobserved — only possible for entities with no active
        data, whose zero coefficients transform to zero anyway)."""
        if norm is None or norm.is_identity:
            return None
        import numpy as np

        proj = np.asarray(self.dataset.projection)
        E, d_loc = proj.shape
        valid = proj >= 0
        f = np.ones((E, d_loc), np.float32)
        if norm.factors is not None:
            f[valid] = np.asarray(norm.factors, np.float32)[proj[valid]]
        s = None
        islot = np.full((E,), -1, np.int32)
        if norm.shifts is not None:
            if intercept_index is None:
                raise ValueError(
                    "random-effect normalization with shifts requires the "
                    "shard's intercept_index")
            s = np.zeros((E, d_loc), np.float32)
            s[valid] = np.asarray(norm.shifts, np.float32)[proj[valid]]
            ent, slot = np.nonzero(proj == intercept_index)
            islot[ent] = slot
        return (jnp.asarray(f),
                None if s is None else jnp.asarray(s),
                jnp.asarray(islot))

    @functools.cached_property
    def _dense_local_blocks(self) -> Tuple[bool, ...]:
        """Per-block static flag: the ELL slots are exactly the local
        feature space (every nonzero sits at slot == its local index and
        the ELL width equals the projected dim), so the block's per-entity
        solves can treat values as a DENSE [S, K] matrix — margins/Gram/
        gradient become plain dot_generals (MXU) instead of gather/scatter
        kernels. Common case: per-entity feature vectors observed in full
        (the MovieLens-style GLMix workload). Computed once from the host
        copy at solve-build time; trace-time static."""
        import numpy as np

        D = self.dataset.projected_dim
        flags = []
        for blk in self.dataset.blocks:
            k = blk.features.values.shape[-1]
            if k != D or not getattr(blk.features.indices,
                                     "is_fully_addressable", True):
                # multi-host entity sharding: the host copy isn't
                # reachable — skip the optimization, never crash
                flags.append(False)
                continue
            idx = np.asarray(blk.features.indices)
            slot = np.broadcast_to(np.arange(k, dtype=idx.dtype), idx.shape)
            idx_ok = idx == slot
            if idx_ok.all():
                # the common from_dense layout: indices alone prove it —
                # skip the device-to-host copy of the (much larger) values
                flags.append(True)
                continue
            val = np.asarray(blk.features.values)
            flags.append(bool(np.all((val == 0) | idx_ok)))
        return tuple(flags)

    def _validate_solver(self) -> None:
        opt = self.config.optimizer
        if opt.optimizer_type == OptimizerType.SDCA:
            raise ValueError(
                "SDCA is a streaming fixed-effect solver (per-example "
                "dual state over the chunk store); the per-entity "
                "random-effect solves have no dual-state batching rule — "
                "use LBFGS/DIRECT/NEWTON for random-effect coordinates")
        if opt.optimizer_type == OptimizerType.DIRECT:
            from photon_tpu.optim.problem import _validate_direct
            _validate_direct(self.task, opt, self.config.regularization)
        if opt.optimizer_type == OptimizerType.NEWTON:
            from photon_tpu.optim.problem import _validate_newton
            _validate_newton(self.task, opt, self.config.regularization)
            if (opt.explicit_hessian is not True
                    and self.dataset.projected_dim > 64):
                # same bound as TRON's explicit gate below: an [E, K, K]
                # Hessian block at large K (IDENTITY projectors / fat
                # entities) would dwarf the data itself — NEWTON has no
                # matrix-free mode, so refuse instead of OOMing
                raise ValueError(
                    f"NEWTON builds explicit [E, K, K] Hessians; projected "
                    f"dim {self.dataset.projected_dim} > 64 would dwarf the "
                    f"data. Use TRON (matrix-free above K=64) or set "
                    f"explicit_hessian=True to override")

    def _make_entity_solvers(self):
        """(solve_sparse, solve_dense): one entity's local solve, shared
        by the all-at-once program (``_solve_fn``) and the sequential
        blocked program (``_block_solve_fn``)."""
        obj = self.objective
        opt = self.config.optimizer
        solver_cfg = opt.solver_config()
        opt_type = opt.optimizer_type
        from photon_tpu.ops.normalization import NormalizationContext

        def solve_core(feats, labels, offsets, weights, x0,
                       l2, l1, f_row=None, s_row=None, islot=None):
            batch = DataBatch(feats, labels, offsets, weights)
            hyper = Hyper(l2_weight=l2)
            if f_row is not None:
                # per-entity transformed space (NormalizationContext
                # Wrapper analog); x0/coef cross the boundary via the
                # margin-invariant maps, islot the dynamic intercept slot
                ctx = NormalizationContext(f_row, s_row)
                obj_e = GLMObjective(obj.loss, ctx)
                x0 = ctx.model_to_transformed_space(
                    x0, islot if s_row is not None else None)
            else:
                obj_e = obj
            vg = lambda c: obj_e.value_and_gradient(c, batch, hyper)
            if opt_type == OptimizerType.DIRECT:
                # one [K, K] normal-equations solve per entity; under
                # vmap this is a single batched [E, K, K] Cholesky
                # (optim/direct.py) — no sequential iterations at all
                from photon_tpu.optim import direct
                r = direct.minimize(
                    vg, lambda c: obj_e.hessian_matrix(c, batch, hyper),
                    x0)
            elif opt_type == OptimizerType.NEWTON:
                # damped Newton/IRLS: DIRECT's [E, K, K] batched
                # Cholesky machinery for logistic/Poisson — a handful
                # of outer iterations, each one batched weighted-Gram
                # contraction + factorization, zero inner CG
                # (optim/newton.py; replaces per-entity iterative TRON,
                # SingleNodeOptimizationProblem.scala:40)
                from photon_tpu.optim import newton
                K = x0.shape[0]
                r = newton.minimize(
                    vg,
                    lambda c: obj_e.hessian_matrix_from_weights(
                        obj_e.hessian_weights(c, batch), K, batch,
                        hyper),
                    x0, config=solver_cfg)
            elif opt_type == OptimizerType.OWLQN:
                r = owlqn.minimize(vg, x0, l1_weight=l1, config=solver_cfg)
            elif opt_type == OptimizerType.TRON:
                # explicit K x K Gauss-Newton per outer iteration when
                # the per-entity dim is small (the common projected
                # case): under vmap it becomes one batched [E, K, K]
                # contraction (MXU) and CG touches no sample data.
                # IDENTITY projectors / fat entities keep the
                # matrix-free operator — an [E, K, K] block at large K
                # would dwarf the data itself. opt.explicit_hessian
                # overrides, mirroring the fixed-effect gate
                # (optim/problem.py).
                K = x0.shape[0]
                explicit = opt.explicit_hessian
                if explicit is None:
                    explicit = K <= 64
                if explicit:
                    hs = lambda c: obj_e.hessian_matrix_from_weights(
                        obj_e.hessian_weights(c, batch), K, batch, hyper)
                    ha = lambda h, v: h @ v
                else:
                    hs = lambda c: obj_e.hessian_weights(c, batch)
                    ha = lambda d2, v: obj_e.hessian_vector_from_weights(
                        d2, v, batch, hyper)
                r = tron.minimize(vg, None, x0, config=solver_cfg,
                                  hess_setup=hs, hess_apply=ha)
            else:
                r = lbfgs.minimize(vg, x0, config=solver_cfg)
            coef = r.coef
            if f_row is not None:
                coef = ctx.transformed_space_to_model(
                    coef, islot if s_row is not None else None)
            fail = (jnp.asarray(0, jnp.int32) if r.failure is None
                    else r.failure)
            return coef, r.iterations, r.reason, fail

        def solve_sparse(feat_idx, feat_val, *rest):
            return solve_core(F.SparseFeatures(feat_idx, feat_val), *rest)

        def solve_dense(feat_val, *rest):
            # dense-local block: ELL slot == local index everywhere,
            # so values ARE the entity's dense [S, K] design matrix
            return solve_core(feat_val, *rest)

        return solve_sparse, solve_dense

    def _make_ladder_solver(self):
        """The whole-ladder solve body, UNJITTED — the scalar program
        (``_solve_fn``). The λ-lane program (``_solve_swept_fn``) shares
        the per-entity solvers (``_make_entity_solvers``) and flattens
        its lanes into this body's one entity-vmap axis, which is what
        keeps every lane bitwise-equal to this scalar solve."""
        dense_flags = self._dense_local_blocks
        solve_sparse, solve_dense = self._make_entity_solvers()

        # the dataset enters as a pytree argument, never a closure (a
        # closed-over array would be baked into the HLO as a constant);
        # the Python loop over size buckets unrolls into one program
        def solve_all(ds: RandomEffectDataset, residual_flat: Optional[Array],
                      coef0: Array, l2: Array, l1: Array,
                      norm_f: Optional[Array] = None,
                      norm_s: Optional[Array] = None,
                      norm_islot: Optional[Array] = None):
                out = coef0  # entities with no active data keep warm start
                E = coef0.shape[0]
                # per-entity solver stats (-1 = entity never trained)
                iters = jnp.full((E,), -1, jnp.int32)
                reasons = jnp.full((E,), -1, jnp.int32)
                fails = jnp.zeros((E,), jnp.int32)
                for blk, dense in zip(ds.blocks, dense_flags):
                    offsets = blk.offsets
                    if residual_flat is not None:
                        # gather residuals by flat row; pad rows -> fill 0
                        res = residual_flat.at[blk.sample_rows].get(
                            mode="fill", fill_value=0.0)
                        offsets = offsets + res
                    x0 = coef0.at[blk.entity_rows].get(mode="fill", fill_value=0.0)
                    if dense:
                        fn = solve_dense
                        args = [blk.features.values,
                                blk.labels, offsets, blk.weights, x0, l2, l1]
                        axes = [0, 0, 0, 0, 0, None, None]
                    else:
                        fn = solve_sparse
                        args = [blk.features.indices, blk.features.values,
                                blk.labels, offsets, blk.weights, x0, l2, l1]
                        axes = [0, 0, 0, 0, 0, 0, None, None]
                    if norm_f is not None:
                        args.append(norm_f.at[blk.entity_rows].get(
                            mode="fill", fill_value=1.0))
                        axes.append(0)
                        if norm_s is not None:
                            args.append(norm_s.at[blk.entity_rows].get(
                                mode="fill", fill_value=0.0))
                            args.append(norm_islot.at[blk.entity_rows].get(
                                mode="fill", fill_value=-1))
                            axes.extend([0, 0])
                    solved, it_b, reason_b, fail_b = jax.vmap(
                        fn, in_axes=tuple(axes))(*args)
                    # per-entity isolation: a failed entity keeps its warm
                    # start; healthy lanes in the same block keep their
                    # fresh solves (no host branch — pure select)
                    solved = jnp.where((fail_b != 0)[:, None], x0, solved)
                    out = out.at[blk.entity_rows].set(solved, mode="drop")
                    iters = iters.at[blk.entity_rows].set(it_b, mode="drop")
                    reasons = reasons.at[blk.entity_rows].set(reason_b, mode="drop")
                    fails = fails.at[blk.entity_rows].set(fail_b, mode="drop")
                return out, iters, reasons, fails

        return solve_all

    @functools.cached_property
    def _solve_fn(self):
        self._validate_solver()
        opt = self.config.optimizer
        dense_flags = self._dense_local_blocks
        has_norm = self._norm_local is not None
        has_shifts = has_norm and self._norm_local[1] is not None

        def build():
            return jax.jit(self._make_ladder_solver())

        key = ("re_solve", self.task, solver_cache_key(opt),
               has_norm, has_shifts, dense_flags)
        return jitcache.get_or_build(key, build)

    def _make_ladder_solver_swept(self):
        """The whole-ladder λ-lane solve body, UNJITTED. Lanes are
        FLATTENED into the entity axis per bucket (see
        ``_make_block_solver_swept`` for why — it is the bitwise
        contract), so per bucket the c lanes' virtual entities solve
        under the scalar body's single entity-vmap against one shared
        staging of the ladder, and results scatter back to the
        ``[K, E_pad, ...]`` lane tables."""
        dense_flags = self._dense_local_blocks
        core_dense = self._make_block_solver_swept(True)
        core_sparse = self._make_block_solver_swept(False)

        def solve_all_lanes(ds: RandomEffectDataset,
                            residual_flat: Optional[Array],
                            coef0_lanes: Array, l2_lanes: Array,
                            l1_lanes: Array,
                            norm_f: Optional[Array] = None,
                            norm_s: Optional[Array] = None,
                            norm_islot: Optional[Array] = None):
            out = coef0_lanes  # entities with no active data keep warm start
            K, E = coef0_lanes.shape[0], coef0_lanes.shape[1]
            iters = jnp.full((K, E), -1, jnp.int32)
            reasons = jnp.full((K, E), -1, jnp.int32)
            fails = jnp.zeros((K, E), jnp.int32)
            for blk, dense in zip(ds.blocks, dense_flags):
                x0 = coef0_lanes.at[:, blk.entity_rows].get(
                    mode="fill", fill_value=0.0)
                core = core_dense if dense else core_sparse
                solved, it_b, reason_b, fail_b = core(
                    blk, residual_flat, x0, l2_lanes, l1_lanes,
                    norm_f, norm_s, norm_islot)
                out = out.at[:, blk.entity_rows].set(solved, mode="drop")
                iters = iters.at[:, blk.entity_rows].set(it_b, mode="drop")
                reasons = reasons.at[:, blk.entity_rows].set(
                    reason_b, mode="drop")
                fails = fails.at[:, blk.entity_rows].set(fail_b, mode="drop")
            return out, iters, reasons, fails

        return solve_all_lanes

    @functools.cached_property
    def _solve_swept_fn(self):
        """λ-lane variant of ``_solve_fn``: c lanes of
        ``(coef0 [c, E, d], l2 [c], l1 [c])`` solved in one program per
        lane-chunk width, reading the bucket ladder's data once for all
        lanes (the dataset stays a shared jit argument — the
        ``minimize_lanes`` data-pass economics applied to the per-entity
        vmap). Per-entity failure isolation carries over per lane, and
        EVERY lane — not just K=1 — is bitwise its scalar solve (see
        ``_make_block_solver_swept``)."""
        self._validate_solver()
        opt = self.config.optimizer
        dense_flags = self._dense_local_blocks
        has_norm = self._norm_local is not None
        has_shifts = has_norm and self._norm_local[1] is not None

        def build():
            return jax.jit(self._make_ladder_solver_swept())

        key = ("re_solve_swept", self.task, solver_cache_key(opt),
               has_norm, has_shifts, dense_flags)
        return jitcache.get_or_build(key, build)

    def update_model(
        self, prev: Optional[RandomEffectModel], residual_scores: Optional[Array]
    ) -> RandomEffectModel:
        ds = self.dataset
        dtype = (prev.coefficients.dtype if prev is not None
                 else (ds.blocks[0].labels.dtype if ds.blocks else jnp.float32))
        coef0 = (prev.coefficients if prev is not None
                 else jnp.zeros((ds.num_entities, ds.projected_dim), dtype))
        coef0 = self._pad_entity_rows(coef0)
        lam = self.config.regularization_weight
        l2 = jnp.asarray(self.config.regularization.l2_weight(lam), dtype)
        l1 = jnp.asarray(self.config.regularization.l1_weight(lam), dtype)
        norm_args = ()
        if self._norm_local is not None:
            f, s, islot = self._norm_local
            norm_args = (f,) if s is None else (f, s, islot)
        if getattr(self, "_chaos_poison_once", False):
            # fault injection (resilience/chaos.py): NaN residuals poison
            # every entity's objective, like a corrupt upstream score pass
            self._chaos_poison_once = False
            residual_scores = jnp.full((self.n,), jnp.nan,
                                       coef0.dtype)
        with _obs_annotate("re/solve"):
            coefs, iters, reasons, fails = self._solve_fn(
                self.dataset, residual_scores, coef0, l2, l1, *norm_args)
        # per-entity outcome aggregation (RandomEffectOptimizationTracker).
        # Keep the DEVICE arrays: a blocking host transfer here would
        # serialize every CD sweep on the solver's completion; the tracker
        # converts lazily when someone actually reads a summary.
        from photon_tpu.optim.tracking import RandomEffectOptimizationTracker
        e_orig = self._num_entities_orig
        self.last_tracker = RandomEffectOptimizationTracker(
            iterations=iters[:e_orig], reasons=reasons[:e_orig])
        # failure isolation already happened device-side (failed entities
        # kept their warm start inside solve_all); here only the counts
        # cross to the host — one scalar at the coordinate boundary
        fails_orig = fails[:e_orig]
        n_failed = int(np.asarray(jnp.sum(fails_orig != 0)))
        self.last_failed_entities = n_failed
        self.last_failure = None
        if n_failed and e_orig and n_failed == e_orig:
            # EVERY entity failed: the coordinate as a whole is poisoned
            # (a bad residual pass, not a few degenerate entities)
            self.last_failure = FailureMode(int(np.asarray(
                jnp.max(fails_orig))))
        variances = None
        from photon_tpu.types import VarianceComputationType
        if (self.variance_type != VarianceComputationType.NONE
                and self.objective.loss.has_hessian):
            variances = self._variance_fn(self.dataset, residual_scores,
                                          coefs, l2)
            variances = variances[: self._num_entities_orig]
        # publish the model at the vocabulary's true entity count; mesh
        # padding stays an internal detail of this coordinate
        coefs = coefs[: self._num_entities_orig]
        return RandomEffectModel(
            coefficients=coefs,
            random_effect_type=self.random_effect_type,
            feature_shard_id=self.feature_shard_id,
            task=self.task,
            variances=variances,
        )

    def update_model_swept(
        self,
        prev: Optional[RandomEffectModel],
        residual_scores: Optional[Array],
        weights,
        *,
        initial_lanes=None,
        plan=None,
        hbm_budget_bytes: Optional[int] = None,
    ):
        """Fit the whole regularization grid ``weights`` over the entity
        ladder as lane-batched programs — K λ points in ONE data pass
        over every bucket, instead of K sequential ``update_model``
        calls (the random-effect half of the PR 15 sweep machinery).

        The K per-entity theta tables stack to ``[K, E, d]`` and the
        existing entity-vmap body batches over (entity-lane × λ-lane);
        per-entity failure isolation carries over per lane, and K=1 is
        bitwise ``update_model``. Device footprint is governed by a
        ``parallel/memory.BlockPlan`` (computed here unless ``plan`` is
        passed; budget from the backend unless ``hbm_budget_bytes``
        overrides): when the full-K stack exceeds the budget the grid
        degrades to ⌈K/c⌉ chunked passes — typed in the plan, recorded
        in the RunReport ``re_plan`` section, never a runtime OOM.
        Chunking never changes results (each chunk is the same
        lane-vmapped program at width c).

        ``initial_lanes [K, E, d]`` warm-starts each lane independently;
        otherwise every lane starts from ``prev``'s coefficients.
        Returns a list of K :class:`RandomEffectModel`s (variances are
        not computed on the sweep path); per-lane telemetry lands in
        ``last_lane_trackers`` / ``last_lane_failed_entities`` /
        ``last_lane_failures`` and the ``sweep.*`` metrics."""
        from photon_tpu.obs.metrics import registry
        from photon_tpu.optim import batched
        from photon_tpu.parallel import memory as hbm

        lams = batched.validate_lane_weights(weights)
        K = int(lams.size)
        ds = self.dataset
        dtype = (prev.coefficients.dtype if prev is not None
                 else (ds.blocks[0].labels.dtype if ds.blocks
                       else jnp.float32))
        base = (prev.coefficients if prev is not None
                else jnp.zeros((ds.num_entities, ds.projected_dim), dtype))
        base = self._pad_entity_rows(jnp.asarray(base, dtype))
        if initial_lanes is not None:
            init = jnp.asarray(initial_lanes, dtype)
            if init.ndim != 3 or init.shape[0] != K:
                raise ValueError(
                    f"initial_lanes must be [K={K}, E, d], got "
                    f"{init.shape}")
            lanes0 = jnp.stack(
                [self._pad_entity_rows(init[k]) for k in range(K)])
        else:
            lanes0 = jnp.broadcast_to(base, (K,) + base.shape)
        if plan is None:
            plan = hbm.plan_for_dataset(
                ds, lanes=K,
                history=self.config.optimizer.solver_config()
                .num_corrections,
                hbm_budget_bytes=hbm_budget_bytes,
                coordinate=self.random_effect_type)
        hbm.record_plan(plan)
        self.last_block_plan = plan
        chunk = max(1, min(plan.lane_chunk, K))
        reg = self.config.regularization
        norm_args = ()
        if self._norm_local is not None:
            f, s, islot = self._norm_local
            norm_args = (f,) if s is None else (f, s, islot)
        if getattr(self, "_chaos_poison_once", False):
            # fault injection (resilience/chaos.py): poisons every lane's
            # shared residual, like a corrupt upstream score pass
            self._chaos_poison_once = False
            residual_scores = jnp.full((self.n,), jnp.nan, dtype)
        coefs: list = [None] * K
        iters: list = [None] * K
        reasons: list = [None] * K
        fails: list = [None] * K
        for idx, n_real in batched.pad_lane_grid(lams, chunk):
            l2c = jnp.asarray([reg.l2_weight(float(lams[i])) for i in idx],
                              dtype)
            l1c = jnp.asarray([reg.l1_weight(float(lams[i])) for i in idx],
                              dtype)
            x0c = jnp.take(lanes0, jnp.asarray(idx), axis=0)
            with _obs_annotate("re/solve_swept"):
                co, it_c, re_c, fa_c = self._solve_swept_fn(
                    ds, residual_scores, x0c, l2c, l1c, *norm_args)
            # padded tail lanes (repeated last λ) are dropped, never
            # published
            for j in range(n_real):
                k = int(idx[j])
                coefs[k], iters[k] = co[j], it_c[j]
                reasons[k], fails[k] = re_c[j], fa_c[j]
        # host boundary: per-lane scalars for telemetry + failure typing
        from photon_tpu.optim.tracking import RandomEffectOptimizationTracker
        e_orig = self._num_entities_orig
        self.last_lane_trackers = [
            RandomEffectOptimizationTracker(iterations=iters[k][:e_orig],
                                            reasons=reasons[k][:e_orig])
            for k in range(K)]
        fails_np = [np.asarray(fails[k][:e_orig]) for k in range(K)]
        self.last_lane_failed_entities = [
            int(np.sum(f != 0)) for f in fails_np]
        self.last_lane_failures = []
        lane_medians = []
        for k in range(K):
            n_failed = self.last_lane_failed_entities[k]
            self.last_lane_failures.append(
                FailureMode(int(fails_np[k].max()))
                if n_failed and e_orig and n_failed == e_orig else None)
            it_np = np.asarray(iters[k][:e_orig])
            trained = it_np[it_np >= 0]
            lane_medians.append(
                float(np.median(trained)) if trained.size else 0.0)
        registry.gauge("sweep.lanes_active").set(
            sum(1 for lf in self.last_lane_failures if lf is None))
        hist = registry.histogram(
            "sweep.lane_iterations",
            buckets=(1, 2, 5, 10, 20, 50, 100, 200, 500))
        for med in lane_medians:
            hist.observe(med)
        batched.record_sweep_run([
            {"weight": float(lams[k]),
             "entities_failed": self.last_lane_failed_entities[k],
             "iterations": lane_medians[k],
             "failure": 0 if self.last_lane_failures[k] is None
             else int(self.last_lane_failures[k])}
            for k in range(K)])
        return [
            RandomEffectModel(
                coefficients=coefs[k][:e_orig],
                random_effect_type=self.random_effect_type,
                feature_shard_id=self.feature_shard_id,
                task=self.task,
                variances=None,
            )
            for k in range(K)
        ]

    def _make_block_solver(self, dense: bool):
        """One size bucket's solve body, UNJITTED — the scalar blocked
        program (``_block_solve_fn``). The λ-lane blocked program
        (``_block_solve_swept_fn``) shares the per-entity solvers and
        the exact vmap structure via ``_make_block_solver_swept``."""
        solve_sparse, solve_dense = self._make_entity_solvers()

        def solve_block(blk: EntityBlock, residual_flat: Optional[Array],
                        x0: Array, l2: Array, l1: Array,
                        norm_f: Optional[Array] = None,
                        norm_s: Optional[Array] = None,
                        norm_islot: Optional[Array] = None):
            offsets = blk.offsets
            if residual_flat is not None:
                offsets = offsets + residual_flat.at[blk.sample_rows].get(
                    mode="fill", fill_value=0.0)
            if dense:
                fn = solve_dense
                args = [blk.features.values,
                        blk.labels, offsets, blk.weights, x0, l2, l1]
                axes = [0, 0, 0, 0, 0, None, None]
            else:
                fn = solve_sparse
                args = [blk.features.indices, blk.features.values,
                        blk.labels, offsets, blk.weights, x0, l2, l1]
                axes = [0, 0, 0, 0, 0, 0, None, None]
            if norm_f is not None:
                args.append(norm_f.at[blk.entity_rows].get(
                    mode="fill", fill_value=1.0))
                axes.append(0)
                if norm_s is not None:
                    args.append(norm_s.at[blk.entity_rows].get(
                        mode="fill", fill_value=0.0))
                    args.append(norm_islot.at[blk.entity_rows].get(
                        mode="fill", fill_value=-1))
                    axes.extend([0, 0])
            solved, it_b, reason_b, fail_b = jax.vmap(
                fn, in_axes=tuple(axes))(*args)
            solved = jnp.where((fail_b != 0)[:, None], x0, solved)
            return solved, it_b, reason_b, fail_b

        return solve_block

    def _block_solve_fn(self, dense: bool):
        """One size bucket's per-entity solves as a standalone program —
        the streaming unit of ``update_model_blocked``. Two cached
        programs per coordinate config (dense / sparse block), reused
        across every block of that flavor."""
        self._validate_solver()
        opt = self.config.optimizer
        has_norm = self._norm_local is not None
        has_shifts = has_norm and self._norm_local[1] is not None

        def build():
            return jax.jit(self._make_block_solver(dense))

        key = ("re_solve_block", self.task, solver_cache_key(opt),
               has_norm, has_shifts, bool(dense))
        return jitcache.get_or_build(key, build)

    def _make_block_solver_swept(self, dense: bool):
        """One size bucket's λ-lane solve body, UNJITTED — the c lanes
        are FLATTENED into the entity axis: the bucket's arrays are
        tiled c× inside the program (lane-major virtual entities) and
        the per-entity solver is vmapped over ONE ``c*E``-wide batch
        axis, exactly the scalar body's vmap structure.

        Flattening — not a nested ``vmap`` over lanes — is the bitwise
        contract. The entity-vmap is width-insensitive on every backend
        we pin (solving a tiled ``2E`` batch reproduces the ``E`` batch
        bit-for-bit), but NESTING a second vmap re-lowers the batched
        reductions with an extra batch dimension and reassociates their
        FP order: lane results then drift ~1e-9 from the scalar solve at
        f64, and a lane sitting at a convergence-threshold knife edge
        (observed at strong regularization) splits its ITERATION COUNT.
        With flattening, every lane of every chunk width — padded tails
        included — is bitwise-equal to its sequential scalar solve.

        The tile costs ``c×`` block data on device; parallel/memory's
        planner charges each lane ``data + lane_state`` bytes and chunks
        the grid when the budget can't carry full K. The block still
        STAGES once — tiling is a device-side op, so storage→device
        traffic stays one pass per bucket regardless of K."""
        solve_sparse, solve_dense = self._make_entity_solvers()

        def solve_block_lanes(blk: EntityBlock,
                              residual_flat: Optional[Array],
                              x0_lanes: Array, l2_lanes: Array,
                              l1_lanes: Array,
                              norm_f: Optional[Array] = None,
                              norm_s: Optional[Array] = None,
                              norm_islot: Optional[Array] = None):
            c, E = x0_lanes.shape[0], x0_lanes.shape[1]
            tile = ((lambda a: jnp.concatenate([a] * c, axis=0)) if c > 1
                    else (lambda a: a))
            offsets = blk.offsets
            if residual_flat is not None:
                offsets = offsets + residual_flat.at[blk.sample_rows].get(
                    mode="fill", fill_value=0.0)
            x0 = x0_lanes.reshape((c * E,) + x0_lanes.shape[2:])
            l2e = jnp.repeat(l2_lanes, E)
            l1e = jnp.repeat(l1_lanes, E)
            if dense:
                fn = solve_dense
                args = [tile(blk.features.values), tile(blk.labels),
                        tile(offsets), tile(blk.weights), x0, l2e, l1e]
            else:
                fn = solve_sparse
                args = [tile(blk.features.indices),
                        tile(blk.features.values), tile(blk.labels),
                        tile(offsets), tile(blk.weights), x0, l2e, l1e]
            if norm_f is not None:
                args.append(tile(norm_f.at[blk.entity_rows].get(
                    mode="fill", fill_value=1.0)))
                if norm_s is not None:
                    args.append(tile(norm_s.at[blk.entity_rows].get(
                        mode="fill", fill_value=0.0)))
                    args.append(tile(norm_islot.at[blk.entity_rows].get(
                        mode="fill", fill_value=-1)))
            solved, it_b, reason_b, fail_b = jax.vmap(fn)(*args)
            # per-entity isolation, per lane: a failed virtual entity
            # keeps its lane's warm start
            solved = jnp.where((fail_b != 0)[:, None], x0, solved)

            def unflatten(a):
                return a.reshape((c, E) + a.shape[1:])

            return (unflatten(solved), unflatten(it_b),
                    unflatten(reason_b), unflatten(fail_b))

        return solve_block_lanes

    def _block_solve_swept_fn(self, dense: bool):
        """λ-lane variant of ``_block_solve_fn``: one program per
        (bucket flavor, lane-chunk width) solving c λ points against ONE
        staging of the bucket (the tile to ``c*E`` virtual entities is a
        device-side op inside the program). Every lane is bitwise the
        scalar blocked program (see ``_make_block_solver_swept``)."""
        self._validate_solver()
        opt = self.config.optimizer
        has_norm = self._norm_local is not None
        has_shifts = has_norm and self._norm_local[1] is not None

        def build():
            return jax.jit(self._make_block_solver_swept(dense))

        key = ("re_solve_block_swept", self.task, solver_cache_key(opt),
               has_norm, has_shifts, bool(dense))
        return jitcache.get_or_build(key, build)

    def update_model_blocked(
        self,
        residual_scores: Optional[Array],
        *,
        warm_start=None,
        entity_names: Optional[Tuple[str, ...]] = None,
        start_block: int = 0,
        on_block=None,
        prefetch: bool = True,
    ) -> RandomEffectModel:
        """Larger-than-HBM training: sequential per-bucket solves with the
        coefficient table resident in HOST RAM, warm starts streamed from
        the cold tier.

        ``update_model`` keeps the full [E, K] table plus every solve on
        device at once; here the device only ever holds ONE size bucket's
        samples-with-warm-starts-and-results, and the [E, K] table lives
        in host memory — the training-side counterpart of serving's
        two-tier store. Semantics match ``update_model`` per entity
        (same per-entity program, same failure isolation: a failed entity
        keeps its warm start) but the blocks run sequentially with a host
        round-trip between them, so use it only when [E, K] doesn't fit.

        ``warm_start``: ``None`` (zeros), a host/device [E, K] array, or
        an ``io.cold_store.ColdStore`` (requires ``entity_names``: the
        entity id of each dataset row, i.e. the ingest vocabulary order).
        ``start_block`` is the resume cursor — buckets before it are
        skipped and keep their ``warm_start`` rows, so resuming a
        preempted run must pass the checkpointed coefficients (schema v4
        records the cursor per coordinate; game/checkpoint.py).
        ``on_block(next_block, num_blocks)`` fires after each bucket —
        the checkpoint hook — OUTSIDE the per-bucket solve span, so
        checkpoint I/O never pollutes ``re/solve_block`` phase timings.

        With ``prefetch`` (default), a reader thread
        (game/block_stream.BlockPrefetcher) stages bucket b+1 while
        bucket b solves — staging order, solve math, and the v4 cursor
        contract are unchanged (results stay bitwise with
        ``prefetch=False``); overlap telemetry lands in
        ``last_block_overlap`` / the ``perf.re_block_overlap`` gauge."""
        ds = self.dataset
        n_blocks = len(ds.blocks)
        if not 0 <= start_block <= n_blocks:
            raise ValueError(
                f"start_block {start_block} outside [0, {n_blocks}]")
        E_pad = ds.num_entities
        K = ds.projected_dim
        # solve in the dataset's dtype, matching update_model's coef0 —
        # the per-entity programs must see identical input dtypes for
        # blocked/all-at-once parity to be bitwise
        dtype = np.dtype(ds.blocks[0].labels.dtype) if ds.blocks \
            else np.dtype(np.float32)
        # host-resident coefficient table: init from the warm-start source
        if warm_start is None:
            out = np.zeros((E_pad, K), dtype)
        elif isinstance(warm_start, np.ndarray) or isinstance(
                warm_start, jax.Array):
            out = np.zeros((E_pad, K), dtype)
            w = np.asarray(warm_start, dtype)
            out[: min(E_pad, w.shape[0])] = w[:E_pad]
        else:  # ColdStore
            if entity_names is None:
                raise ValueError(
                    "ColdStore warm_start requires entity_names (entity id "
                    "per dataset row, vocabulary order)")
            from photon_tpu.game.random_effect import warm_start_from_cold_store
            out = warm_start_from_cold_store(
                warm_start, entity_names, ds.projection).astype(dtype)
            extra = E_pad - out.shape[0]
            if extra > 0:
                out = np.pad(out, [(0, extra), (0, 0)])
        lam = self.config.regularization_weight
        l2 = jnp.asarray(self.config.regularization.l2_weight(lam), dtype)
        l1 = jnp.asarray(self.config.regularization.l1_weight(lam), dtype)
        norm_args = ()
        if self._norm_local is not None:
            f, s, islot = self._norm_local
            norm_args = (f,) if s is None else (f, s, islot)
        iters = np.full((E_pad,), -1, np.int32)
        reasons = np.full((E_pad,), -1, np.int32)
        fails = np.zeros((E_pad,), np.int32)
        from photon_tpu.game.block_stream import BlockPrefetcher
        from photon_tpu.resilience import chaos
        stream = None
        if prefetch and n_blocks - start_block > 1:
            stream = BlockPrefetcher(ds.blocks, start_block=start_block)
        try:
            with _obs_span("re/solve_blocked",
                           blocks=n_blocks - start_block):
                for bi, (blk, dense) in enumerate(
                        zip(ds.blocks, self._dense_local_blocks)):
                    if bi < start_block:
                        continue
                    ents = np.asarray(blk.entity_rows)
                    valid = (ents >= 0) & (ents < E_pad)
                    x0 = np.zeros((ents.shape[0], K), dtype)
                    x0[valid] = out[ents[valid]]
                    # bucket b+1 is already staging on the reader thread
                    # while this bucket solves; values are identical to
                    # the unstaged block, so parity stays bitwise
                    staged = stream.get(bi) if stream is not None else blk
                    with _obs_span("re/solve_block", block=bi):
                        with _obs_annotate("re/solve_block"):
                            solved, it_b, reason_b, fail_b = \
                                self._block_solve_fn(dense)(
                                    staged, residual_scores,
                                    jnp.asarray(x0), l2, l1, *norm_args)
                        # the per-bucket host round-trip IS the design
                        # here: device peak memory stays one staged
                        # bucket (+ one in flight), results land in
                        # host RAM
                        out[ents[valid]] = np.asarray(solved)[valid]
                        iters[ents[valid]] = np.asarray(it_b)[valid]
                        reasons[ents[valid]] = np.asarray(reason_b)[valid]
                        fails[ents[valid]] = np.asarray(fail_b)[valid]
                    if stream is not None:
                        # results are on the host: the staged buffer is
                        # consumed — return its token to the reader
                        stream.release()
                    if on_block is not None:
                        on_block(bi + 1, n_blocks)
                    if chaos.should_kill_re_block(bi):
                        # after on_block: the cursor is durable, resume
                        # must be bitwise (the v4 contract)
                        raise chaos.SimulatedKill(
                            f"chaos: killed after re block {bi} "
                            f"checkpoint")
        finally:
            if stream is not None:
                stream.close()
        self.last_block_overlap = None
        # storage->device data passes this run (the bench's accounting
        # unit): one staging per bucket whether prefetched or inline
        self.last_blocks_staged = (stream.blocks_staged
                                   if stream is not None
                                   else n_blocks - start_block)
        if stream is not None:
            from photon_tpu.utils import flops
            self.last_block_overlap = flops.re_block_overlap(
                stream.reader_busy_s, stream.consumer_stall_s,
                stream.wall_s, stream.bytes_staged,
                coordinate=self.random_effect_type)
        from photon_tpu.optim.tracking import RandomEffectOptimizationTracker
        e_orig = self._num_entities_orig
        self.last_tracker = RandomEffectOptimizationTracker(
            iterations=iters[:e_orig], reasons=reasons[:e_orig])
        n_failed = int(np.sum(fails[:e_orig] != 0))
        self.last_failed_entities = n_failed
        self.last_failure = None
        if n_failed and e_orig and n_failed == e_orig:
            self.last_failure = FailureMode(int(fails[:e_orig].max()))
        # coefficients stay a HOST array — materializing [E, K] on device
        # would defeat the mode; downstream jnp ops accept numpy, and
        # io.model_io.save_game_model writes cold stores straight from it
        return RandomEffectModel(
            coefficients=out[:e_orig],
            random_effect_type=self.random_effect_type,
            feature_shard_id=self.feature_shard_id,
            task=self.task,
            variances=None,
        )

    def update_model_blocked_swept(
        self,
        residual_scores: Optional[Array],
        weights,
        *,
        warm_start=None,
        entity_names: Optional[Tuple[str, ...]] = None,
        start_block: int = 0,
        on_block=None,
        plan=None,
        hbm_budget_bytes: Optional[int] = None,
        prefetch: bool = True,
    ):
        """``update_model_blocked`` × λ lanes: the K coefficient tables
        live in HOST RAM as ``[K, E, d]`` while each staged bucket is
        solved for all K λ points — one storage→device staging per
        bucket for the whole grid (the sequential sweep staged every
        bucket K times). Per-bucket lane chunking follows the
        ``parallel/memory`` plan: a bucket whose full-K lane stack
        exceeds the budget re-solves the SAME staged copy in ⌈K/c⌉
        compute passes, so degradation costs FLOPs dispatches, never
        extra staging traffic, and never changes results.

        ``warm_start``: ``None`` (zeros), ``[E, d]`` (broadcast to all
        lanes), ``[K, E, d]`` (per-lane — the resume shape), or a
        ``ColdStore`` (broadcast; requires ``entity_names``). The
        ``start_block`` cursor and ``on_block(next_block, num_blocks)``
        hook keep the v4 ``re_block_cursor`` contract — kill after
        bucket b's hook, resume at ``start_block=b+1`` with the
        checkpointed ``[K, E, d]`` table, and the result is bitwise.
        Returns a list of K :class:`RandomEffectModel`s (host-resident
        coefficients, like ``update_model_blocked``); the plan and
        per-bucket planned-vs-measured footprints land in
        ``last_block_plan`` / ``last_block_measured`` and the
        ``perf.re_peak_hbm_bytes`` gauges."""
        from photon_tpu.game import block_stream
        from photon_tpu.optim import batched
        from photon_tpu.parallel import memory as hbm
        from photon_tpu.resilience import chaos
        from photon_tpu.utils import flops

        lams = batched.validate_lane_weights(weights)
        K_lanes = int(lams.size)
        ds = self.dataset
        n_blocks = len(ds.blocks)
        if not 0 <= start_block <= n_blocks:
            raise ValueError(
                f"start_block {start_block} outside [0, {n_blocks}]")
        E_pad = ds.num_entities
        D = ds.projected_dim
        dtype = np.dtype(ds.blocks[0].labels.dtype) if ds.blocks \
            else np.dtype(np.float32)
        # K host-resident coefficient tables
        if warm_start is None:
            out = np.zeros((K_lanes, E_pad, D), dtype)
        elif isinstance(warm_start, np.ndarray) or isinstance(
                warm_start, jax.Array):
            w = np.asarray(warm_start, dtype)
            out = np.zeros((K_lanes, E_pad, D), dtype)
            if w.ndim == 2:
                out[:, : min(E_pad, w.shape[0])] = w[None, :E_pad]
            elif w.ndim == 3:
                if w.shape[0] != K_lanes:
                    raise ValueError(
                        f"per-lane warm_start must be [K={K_lanes}, E, d], "
                        f"got {w.shape}")
                out[:, : min(E_pad, w.shape[1])] = w[:, :E_pad]
            else:
                raise ValueError(
                    f"warm_start must be [E, d] or [K, E, d], got "
                    f"{w.shape}")
        else:  # ColdStore, broadcast to every lane
            if entity_names is None:
                raise ValueError(
                    "ColdStore warm_start requires entity_names (entity id "
                    "per dataset row, vocabulary order)")
            from photon_tpu.game.random_effect import (
                warm_start_from_cold_store,
            )
            w = warm_start_from_cold_store(
                warm_start, entity_names, ds.projection).astype(dtype)
            extra = E_pad - w.shape[0]
            if extra > 0:
                w = np.pad(w, [(0, extra), (0, 0)])
            out = np.repeat(w[None, :E_pad], K_lanes, axis=0)
        if plan is None:
            plan = hbm.plan_for_dataset(
                ds, lanes=K_lanes,
                history=self.config.optimizer.solver_config()
                .num_corrections,
                hbm_budget_bytes=hbm_budget_bytes,
                coordinate=self.random_effect_type)
        hbm.record_plan(plan)
        self.last_block_plan = plan
        reg = self.config.regularization
        l2_all = np.asarray([reg.l2_weight(float(w)) for w in lams], dtype)
        l1_all = np.asarray([reg.l1_weight(float(w)) for w in lams], dtype)
        norm_args = ()
        if self._norm_local is not None:
            f, s, islot = self._norm_local
            norm_args = (f,) if s is None else (f, s, islot)
        iters = np.full((K_lanes, E_pad), -1, np.int32)
        reasons = np.full((K_lanes, E_pad), -1, np.int32)
        fails = np.zeros((K_lanes, E_pad), np.int32)
        measured: list = []
        stream = None
        if prefetch and n_blocks - start_block > 1:
            stream = block_stream.BlockPrefetcher(
                ds.blocks, start_block=start_block)
        try:
            with _obs_span("re/solve_blocked",
                           blocks=n_blocks - start_block, lanes=K_lanes):
                for bi, (blk, dense) in enumerate(
                        zip(ds.blocks, self._dense_local_blocks)):
                    if bi < start_block:
                        continue
                    bplan = plan.buckets[bi] if bi < len(plan.buckets) \
                        else None
                    chunk = max(1, min(
                        bplan.lane_chunk if bplan is not None else K_lanes,
                        K_lanes))
                    ents = np.asarray(blk.entity_rows)
                    valid = (ents >= 0) & (ents < E_pad)
                    staged = stream.get(bi) if stream is not None else blk
                    bucket_peak = 0
                    with _obs_span("re/solve_block", block=bi):
                        for idx, n_real in batched.pad_lane_grid(
                                lams, chunk):
                            x0 = np.zeros(
                                (idx.size, ents.shape[0], D), dtype)
                            for j, k in enumerate(idx):
                                x0[j, valid] = out[k][ents[valid]]
                            x0j = jnp.asarray(x0)
                            l2c = jnp.asarray(l2_all[idx])
                            l1c = jnp.asarray(l1_all[idx])
                            with _obs_annotate("re/solve_block_swept"):
                                solved, it_b, reason_b, fail_b = \
                                    self._block_solve_swept_fn(dense)(
                                        staged, residual_scores, x0j,
                                        l2c, l1c, *norm_args)
                            solved_np = np.asarray(solved)
                            it_np = np.asarray(it_b)
                            re_np = np.asarray(reason_b)
                            fa_np = np.asarray(fail_b)
                            # padded tail lanes (repeated last λ) are
                            # dropped, never written back
                            for j in range(n_real):
                                k = int(idx[j])
                                out[k][ents[valid]] = solved_np[j][valid]
                                iters[k][ents[valid]] = it_np[j][valid]
                                reasons[k][ents[valid]] = re_np[j][valid]
                                fails[k][ents[valid]] = fa_np[j][valid]
                            # staging copies + the c×-tiled batch the
                            # flattened-lane program materializes
                            sb = block_stream.staged_bytes(staged)
                            tiled = sb * idx.size if idx.size > 1 else 0
                            bucket_peak = max(
                                bucket_peak,
                                sb * (2 if stream is not None else 1)
                                + tiled
                                + int(x0j.nbytes) + int(solved_np.nbytes))
                    measured.append({
                        "bucket": bi,
                        "lane_chunk": chunk,
                        "strategy": bplan.strategy if bplan is not None
                        else hbm.STRATEGY_FULL,
                        "planned_peak_bytes": bplan.peak_bytes
                        if bplan is not None else 0,
                        "measured_peak_bytes": int(bucket_peak),
                    })
                    if stream is not None:
                        stream.release()
                    if on_block is not None:
                        # checkpoint hook OUTSIDE the timed solve span
                        on_block(bi + 1, n_blocks)
                    if chaos.should_kill_re_block(bi):
                        raise chaos.SimulatedKill(
                            f"chaos: killed after re block {bi} "
                            f"checkpoint")
        finally:
            if stream is not None:
                stream.close()
        self.last_block_measured = measured
        if measured:
            flops.re_peak_hbm(
                self.random_effect_type,
                max(m["planned_peak_bytes"] for m in measured),
                max(m["measured_peak_bytes"] for m in measured))
        self.last_block_overlap = None
        # one staging per bucket serves EVERY lane chunk — this is the
        # (1/K)-data-passes economics the bench records
        self.last_blocks_staged = (stream.blocks_staged
                                   if stream is not None
                                   else n_blocks - start_block)
        if stream is not None:
            self.last_block_overlap = flops.re_block_overlap(
                stream.reader_busy_s, stream.consumer_stall_s,
                stream.wall_s, stream.bytes_staged,
                coordinate=self.random_effect_type)
        # host boundary: per-lane telemetry + failure typing
        from photon_tpu.optim.tracking import RandomEffectOptimizationTracker
        e_orig = self._num_entities_orig
        self.last_lane_trackers = [
            RandomEffectOptimizationTracker(iterations=iters[k][:e_orig],
                                            reasons=reasons[k][:e_orig])
            for k in range(K_lanes)]
        self.last_lane_failed_entities = [
            int(np.sum(fails[k][:e_orig] != 0)) for k in range(K_lanes)]
        self.last_lane_failures = [
            FailureMode(int(fails[k][:e_orig].max()))
            if self.last_lane_failed_entities[k] and e_orig
            and self.last_lane_failed_entities[k] == e_orig else None
            for k in range(K_lanes)]
        batched.record_sweep_run([
            {"weight": float(lams[k]),
             "entities_failed": self.last_lane_failed_entities[k],
             "failure": 0 if self.last_lane_failures[k] is None
             else int(self.last_lane_failures[k])}
            for k in range(K_lanes)])
        return [
            RandomEffectModel(
                coefficients=out[k][:e_orig],
                random_effect_type=self.random_effect_type,
                feature_shard_id=self.feature_shard_id,
                task=self.task,
                variances=None,
            )
            for k in range(K_lanes)
        ]

    @functools.cached_property
    def _variance_fn(self):
        """vmapped per-entity coefficient variances: SIMPLE = 1/diag(H),
        FULL = diag(H^-1) via Cholesky — H is each entity's [K, K] Hessian
        (reference: DistributedOptimizationProblem.computeVariances :82-100
        applied per entity; Bayesian output of RandomEffectModel)."""
        from photon_tpu.types import VarianceComputationType

        obj = self.objective
        vtype = self.variance_type

        def build():
            def one(feat_idx, feat_val, labels, offsets, weights, coef, l2):
                batch = DataBatch(F.SparseFeatures(feat_idx, feat_val),
                                  labels, offsets, weights)
                hyper = Hyper(l2_weight=l2)
                has_data = jnp.sum(weights) > 0
                if vtype == VarianceComputationType.SIMPLE:
                    d = obj.hessian_diagonal(coef, batch, hyper)
                    var = 1.0 / jnp.maximum(d, jnp.finfo(d.dtype).tiny)
                else:
                    h = obj.hessian_matrix(coef, batch, hyper)
                    eye = jnp.eye(h.shape[0], dtype=h.dtype)
                    chol = jax.scipy.linalg.cho_factor(h)
                    var = jnp.diag(jax.scipy.linalg.cho_solve(chol, eye))
                return jnp.where(has_data, var, 0.0)

            @jax.jit
            def variance_all(ds: RandomEffectDataset, residual_flat,
                             coef_block, l2):
                out = jnp.zeros_like(coef_block)
                for blk in ds.blocks:
                    offsets = blk.offsets
                    if residual_flat is not None:
                        res = residual_flat.at[blk.sample_rows].get(
                            mode="fill", fill_value=0.0)
                        offsets = offsets + res
                    coefs_b = coef_block.at[blk.entity_rows].get(
                        mode="fill", fill_value=0.0)
                    var_b = jax.vmap(one, in_axes=(0, 0, 0, 0, 0, 0, None))(
                        blk.features.indices, blk.features.values,
                        blk.labels, offsets, blk.weights, coefs_b, l2)
                    out = out.at[blk.entity_rows].set(var_b, mode="drop")
                return out

            return variance_all

        return jitcache.get_or_build(("re_variance", self.task, vtype), build)

    def _pad_entity_rows(self, coef_block: Array) -> Array:
        """Match a model's entity rows to this coordinate's (possibly
        mesh-padded) block: pad with zero rows or slice down."""
        extra = self.dataset.num_entities - coef_block.shape[0]
        if extra > 0:
            coef_block = jnp.pad(coef_block, [(0, extra), (0, 0)])
        elif extra < 0:
            coef_block = coef_block[: self.dataset.num_entities]
        return coef_block

    @functools.cached_property
    def _score_fn(self):
        n = self.n
        dense_flags = self._dense_local_blocks

        def build():
            return jax.jit(_re_score_builder(n, dense_flags))

        return jitcache.get_or_build(("re_score", n, dense_flags), build)

    def score(self, model: RandomEffectModel) -> Array:
        with _obs_annotate("re/score"):
            return self._score_fn(self.dataset,
                                  self._pad_entity_rows(model.coefficients))

    @functools.cached_property
    def _objective_value_fn(self):
        obj = self.objective
        dense_flags = self._dense_local_blocks

        def build():
            def one_core(feats, labels, offsets, weights, coef, l2):
                return obj.value(coef, DataBatch(feats, labels, offsets,
                                                 weights), Hyper(l2_weight=l2))

            def one_sparse(feat_idx, feat_val, *rest):
                return one_core(F.SparseFeatures(feat_idx, feat_val), *rest)

            @jax.jit
            def value_all(ds: RandomEffectDataset,
                          residual_flat: Optional[Array],
                          coef_block: Array, l2: Array) -> Array:
                total = jnp.zeros((), coef_block.dtype)
                for blk, dense in zip(ds.blocks, dense_flags):
                    offsets = blk.offsets
                    if residual_flat is not None:
                        offsets = offsets + residual_flat.at[
                            blk.sample_rows].get(mode="fill", fill_value=0.0)
                    rows = coef_block.at[blk.entity_rows].get(
                        mode="fill", fill_value=0.0)
                    if dense:
                        vals = jax.vmap(one_core,
                                        in_axes=(0, 0, 0, 0, 0, None))(
                            blk.features.values, blk.labels, offsets,
                            blk.weights, rows, l2)
                    else:
                        vals = jax.vmap(one_sparse,
                                        in_axes=(0, 0, 0, 0, 0, 0, None))(
                            blk.features.indices, blk.features.values,
                            blk.labels, offsets, blk.weights, rows, l2)
                    total = total + jnp.sum(vals)
                return total

            return value_all

        return jitcache.get_or_build(("re_objval", self.task, dense_flags),
                                     build)

    def objective_value(self, model: Optional[RandomEffectModel],
                        residual_scores: Optional[Array]) -> Array:
        """Sum of per-entity L2-regularized objectives against a residual
        snapshot, as a DEVICE scalar (no host sync; see the fixed-effect
        counterpart). Pad entities carry zero weights and zero coefficient
        rows, so they contribute exactly 0."""
        ds = self.dataset
        dtype = (model.coefficients.dtype if model is not None
                 else (ds.blocks[0].labels.dtype if ds.blocks
                       else jnp.float32))
        coef = (model.coefficients if model is not None
                else jnp.zeros((ds.num_entities, ds.projected_dim), dtype))
        coef = self._pad_entity_rows(jnp.asarray(coef))
        l2 = jnp.asarray(self.config.regularization.l2_weight(
            self.config.regularization_weight), coef.dtype)
        return self._objective_value_fn(ds, residual_scores, coef, l2)

    def predicted_decrease(self, prev: Optional[RandomEffectModel],
                           new: RandomEffectModel,
                           residual_scores: Optional[Array]) -> Array:
        """Solver-predicted objective decrease for ``prev -> new`` against
        the FROZEN residual the solve actually saw (device scalar)."""
        return (self.objective_value(prev, residual_scores)
                - self.objective_value(new, residual_scores))

    @functools.cached_property
    def _data_loss_fn(self):
        loss = self.objective.loss

        def build():
            @jax.jit
            def loss_all(ds: RandomEffectDataset, scores_flat: Array) -> Array:
                total = jnp.zeros((), scores_flat.dtype)
                for blk in ds.blocks:
                    z = blk.offsets + scores_flat.at[blk.sample_rows].get(
                        mode="fill", fill_value=0.0)
                    l, _ = loss.loss_and_dz(z, blk.labels)
                    total = total + jnp.sum(l * blk.weights)
                return total
            return loss_all

        return jitcache.get_or_build(("re_dataloss", self.task), build)

    def data_loss_at(self, total_scores: Array) -> Array:
        """Weighted GLM data loss at a TOTAL score vector (no features, no
        regularization), as a device scalar — the random-effect counterpart
        of ``FixedEffectCoordinate.data_loss_at`` (the entity blocks
        partition the sample space, so the block-sum equals the flat
        weighted loss; pad rows carry zero weight)."""
        return self._data_loss_fn(self.dataset, total_scores)


def _re_score_builder(n: int, dense_flags=()):
    def score(ds: RandomEffectDataset, coef_block: Array) -> Array:
        flat = jnp.zeros((n,), coef_block.dtype)
        flags = (dense_flags if len(dense_flags) == len(ds.blocks)
                 else (False,) * len(ds.blocks))
        # active blocks: per-entity margins, scattered to flat rows
        for blk, dense in zip(ds.blocks, flags):
            rows = coef_block.at[blk.entity_rows].get(mode="fill", fill_value=0.0)
            if dense:
                # dense-local block: one batched [S, K] x [K] contraction
                margins = jnp.einsum("esk,ek->es", blk.features.values, rows)
            else:
                margins = jnp.sum(
                    blk.features.values
                    * jax.vmap(lambda c, i: c[i])(rows, blk.features.indices),
                    axis=-1,
                )
            flat = flat.at[blk.sample_rows.ravel()].add(
                margins.ravel(), mode="drop")
        # passive: gather entity coef rows (out-of-range entity -> 0)
        pcoef = coef_block.at[ds.passive_entity].get(mode="fill", fill_value=0.0)
        pmargin = jnp.sum(ds.passive_features.values
                          * jnp.take_along_axis(pcoef, ds.passive_features.indices, axis=1),
                          axis=-1)
        flat = flat.at[ds.passive_rows].add(pmargin, mode="drop")
        return flat

    return score
