"""GameEstimator / GameTransformer: the user-facing GAME training API.

Reference: photon-api estimators/GameEstimator.scala:55 (fit :299, train
:699, prepareTrainingDatasets :399, prepareValidationDatasetAndEvaluators
:505, warm-started multi-config fit :344-360, partial-retrain locked
coordinates :728-751), transformers/GameTransformer.scala:39 (transform
:115).

TPU re-design: datasets are built once per fit (ingest-time grouping
replaces shuffles); each optimization configuration trains via
coordinate descent (game/descent.py) warm-started from the previous
config's model, mirroring the reference's config-sweep semantics.
"""

from __future__ import annotations

import os
import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.evaluation.evaluators import (
    EvaluatorType,
    default_evaluator_for_task,
    evaluate,
)
from photon_tpu.evaluation.multi import (
    EvaluationSuite,
    EvaluatorSpec,
    parse_evaluator,
)
from photon_tpu.game.coordinate import FixedEffectCoordinate, RandomEffectCoordinate
from photon_tpu.game.dataset import EntityVocabulary, GameDataFrame
from photon_tpu.game.descent import (
    CoordinateDescentConfig,
    CoordinateDescentResult,
    run_coordinate_descent,
)
from photon_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_tpu.game.random_effect import (
    RandomEffectDataConfiguration,
    RandomEffectDataset,
    build_random_effect_dataset,
)
from photon_tpu.game.scoring import GameScorer
from photon_tpu.optim.problem import GLMOptimizationConfiguration
from photon_tpu.types import TaskType

Array = jax.Array
logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class FixedEffectDataConfiguration:
    """Reference: CoordinateDataConfiguration.scala:37."""

    feature_shard_id: str


@dataclasses.dataclass(frozen=True)
class CoordinateConfiguration:
    """Data + optimization config for one coordinate (reference:
    io/CoordinateConfiguration.scala:57,81)."""

    data: Union[FixedEffectDataConfiguration, RandomEffectDataConfiguration]
    optimization: GLMOptimizationConfiguration = GLMOptimizationConfiguration()

    @property
    def is_random_effect(self) -> bool:
        return isinstance(self.data, RandomEffectDataConfiguration)

    def with_regularization_weight(self, w: float) -> "CoordinateConfiguration":
        """Round-trips everything but the weight. Negative / non-finite
        weights are refused with a typed
        :class:`~photon_tpu.optim.batched.SweepWeightError` HERE, at
        config time — a bad sweep value must never reach a compiled
        solve."""
        from photon_tpu.optim.batched import validate_lane_weights
        w = float(validate_lane_weights([w])[0])
        return dataclasses.replace(
            self, optimization=dataclasses.replace(
                self.optimization, regularization_weight=w))


@dataclasses.dataclass
class GameResult:
    model: GameModel
    config: Dict[str, CoordinateConfiguration]
    evaluation: Optional[Dict[str, float]]
    descent: CoordinateDescentResult
    # per-coordinate convergence summaries captured at the END of THIS
    # configuration's descent (coordinates are reused across a sweep, so
    # their live trackers only ever show the last configuration)
    tracker_summaries: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TuneResult:
    """Outcome of :meth:`GameEstimator.tune`.

    ``best_value`` follows the search's MINIMIZE convention (the primary
    metric negated when bigger-is-better); ``best_metric`` is the same
    number in the metric's natural orientation."""

    best_config: Dict[str, float]
    best_value: float
    best_metric: float
    best_model: GameModel
    rounds: List[dict]
    total_iterations: int
    observations: List[Tuple[np.ndarray, float]]


class GameEstimator:
    """Train a GAME model by coordinate descent over configured coordinates."""

    def __init__(
        self,
        task: TaskType,
        coordinate_configs: Dict[str, CoordinateConfiguration],
        update_sequence: Optional[List[str]] = None,
        num_iterations: int = 1,
        validation_evaluators: Optional[Sequence[EvaluatorType]] = None,
        locked_coordinates: Sequence[str] = (),
        dtype=jnp.float32,
        mesh=None,
        variance_computation_type=None,
        normalization_contexts=None,
        intercept_indices=None,
        feature_dtype=None,
        parallel_cd: bool = False,
        parallel_groups: Optional[List[List[str]]] = None,
        staleness_tol: float = 1e-3,
        staleness_ratio: float = 0.5,
        staleness_patience: int = 2,
    ):
        """``mesh``: a `jax.sharding.Mesh` — fixed-effect batches are
        sample-sharded and random-effect entity blocks entity-sharded over
        its data axis, so each coordinate's solve runs SPMD (SURVEY §5.8).

        ``normalization_contexts``: {feature_shard_id: NormalizationContext}
        (reference: GameEstimator.scala:55-111 threading per-coordinate
        contexts built by the driver). Fixed effects fold the context into
        their solve; random effects gather it through each entity's
        projection (NormalizationContextWrapper analog). Published models
        are ALWAYS in original feature space. ``intercept_indices``:
        {feature_shard_id: index} — required by shift-ful types.

        ``parallel_cd``: run parallel (concurrency-grouped, bounded-stale)
        coordinate-descent sweeps; ``parallel_groups`` / ``staleness_tol``
        / ``staleness_patience`` forward to
        :class:`CoordinateDescentConfig` (game/descent.py)."""
        self.task = task
        self.coordinate_configs = coordinate_configs
        self.update_sequence = update_sequence or list(coordinate_configs.keys())
        self.num_iterations = num_iterations
        # evaluator names accept the reference's grouped syntax too:
        # "AUC", "RMSE", "PRECISION@5", "AUC:userId", "PRECISION@1:queryId"
        self.evaluators: List[EvaluatorSpec] = (
            [parse_evaluator(e) for e in validation_evaluators]
            if validation_evaluators
            else [EvaluatorSpec(default_evaluator_for_task(task))])
        self.locked = frozenset(locked_coordinates)
        self.dtype = dtype
        self.mesh = mesh
        self.normalization_contexts = dict(normalization_contexts or {})
        self.intercept_indices = dict(intercept_indices or {})
        # narrower on-device feature storage (e.g. jnp.bfloat16): the
        # bandwidth-bound fixed-effect solve reads half the HBM bytes
        # while solver math stays at `dtype` via in-register promotion
        self.feature_dtype = feature_dtype
        self.parallel_cd = parallel_cd
        self.parallel_groups = parallel_groups
        self.staleness_tol = staleness_tol
        self.staleness_ratio = staleness_ratio
        self.staleness_patience = staleness_patience
        from photon_tpu.types import VarianceComputationType
        self.variance_computation_type = (
            variance_computation_type or VarianceComputationType.NONE)

    # -- dataset / coordinate preparation ----------------------------------

    def _prepare(self, df: GameDataFrame, vocab: EntityVocabulary,
                 sampling_seed: int = 0):
        coordinates: Dict[str, object] = {}
        re_datasets: Dict[str, RandomEffectDataset] = {}
        # original (pre-RANDOM-projection) feature dims per RE coordinate —
        # persistable_artifacts needs them to back-project trained models
        self._original_dims: Dict[str, int] = {}
        for i, (cid, cfg) in enumerate(self.coordinate_configs.items()):
            shard_id = cfg.data.feature_shard_id
            norm = self.normalization_contexts.get(shard_id)
            icpt = self.intercept_indices.get(shard_id)
            if cfg.is_random_effect:
                if norm is not None and cfg.data.projector_type == "RANDOM":
                    # contexts are defined in the original feature space;
                    # a RANDOM projector replaces that space, so the
                    # coordinate trains unnormalized (the Gaussian mix
                    # already equalizes column scales)
                    logger.warning(
                        "coordinate %s: skipping normalization under a "
                        "RANDOM projector", cid)
                    norm, icpt = None, None
                self._original_dims[cid] = df.feature_shards[shard_id].dim
                ds = build_random_effect_dataset(
                    df, cfg.data, vocab, dtype=np.dtype(self.dtype).type)
                re_datasets[cid] = ds
                coordinates[cid] = RandomEffectCoordinate(
                    ds, df.num_samples, cfg.data.random_effect_type,
                    cfg.data.feature_shard_id, self.task, cfg.optimization,
                    mesh=self.mesh,
                    variance_type=self.variance_computation_type,
                    norm=norm, intercept_index=icpt)
            else:
                batch = df.fixed_effect_batch(
                    shard_id, dtype=np.dtype(self.dtype).type,
                    feature_dtype=self.feature_dtype)
                key = jax.random.PRNGKey(sampling_seed + i)
                coordinates[cid] = FixedEffectCoordinate(
                    batch, df.feature_shards[shard_id].dim, shard_id, self.task,
                    cfg.optimization, sampling_key=key, mesh=self.mesh,
                    variance_type=self.variance_computation_type,
                    norm=norm, intercept_index=icpt)
        return coordinates, re_datasets

    def _prepare_cached(self, df: GameDataFrame):
        """Dataset preparation (entity grouping, padding, device placement)
        is a pure function of (df, data configs, dtype, mesh) — cache it
        per estimator so repeated fits on the same frame (hyperparameter
        tuning candidates, warm re-fits) skip the host-side ingest
        entirely; only regularization weights change between candidates
        and those are traced arguments of the cached solves."""
        prep_key = (self.dtype, self.feature_dtype, self.mesh,
                    tuple((cid, cfg.data)
                          for cid, cfg in self.coordinate_configs.items()))
        cached = getattr(self, "_prep_cache", None)
        # identity check on the HELD frame (not id() of a possibly-freed
        # object): the cache keeps df alive, so `is` cannot false-hit
        if (cached is not None and cached[0] is df and cached[1] == prep_key):
            vocab, coordinates, re_datasets = cached[2]
            # a fresh fit must be reproducible: the down-sampling PRNG
            # fold-in counters restart at 0 exactly as _prepare would
            # have built them (checkpoint resume overwrites them later)
            for coord in coordinates.values():
                if hasattr(coord, "_update_count"):
                    coord._update_count = 0
        else:
            vocab = EntityVocabulary()
            coordinates, re_datasets = self._prepare(df, vocab)
            self._prep_cache = (df, prep_key, (vocab, coordinates, re_datasets))
        return vocab, coordinates, re_datasets

    def _build_scorer(self, df: GameDataFrame, vocab: EntityVocabulary,
                      re_datasets: Dict[str, RandomEffectDataset]) -> GameScorer:
        scorer = GameScorer(df.num_samples, dtype=self.dtype)
        for cid, cfg in self.coordinate_configs.items():
            if cfg.is_random_effect:
                scorer.add_random_effect(cid, df, cfg.data, vocab,
                                         re_datasets[cid].projection)
            else:
                scorer.add_fixed_effect(cid, df, cfg.data.feature_shard_id)
        return scorer

    def _validation_fn(self, scorer: GameScorer, df: GameDataFrame):
        suite = EvaluationSuite(self.evaluators, df.response,
                                offsets=df.offsets, weights=df.weights,
                                id_tags=df.id_tags, dtype=self.dtype)

        def fn(model: GameModel) -> Dict[str, float]:
            # offsets are applied inside the suite
            scores = scorer.score(model, offsets=None)
            return suite.evaluate(scores).evaluations

        return fn

    # -- fitting ------------------------------------------------------------

    def fit(
        self,
        df: GameDataFrame,
        validation_df: Optional[GameDataFrame] = None,
        configurations: Optional[Sequence[Dict[str, float]]] = None,
        initial_model: Optional[GameModel] = None,
        checkpoint_dir: Optional[str] = None,
        resume: bool = False,
    ) -> List[GameResult]:
        """Train one model per configuration, warm-starting each from the
        previous (reference: GameEstimator.fit :344-360). A configuration is
        {coordinate id: regularization weight} — reg weights are traced
        arguments of the compiled solves, so a sweep recompiles nothing
        (the reference's config sweep varies exactly these weights; see
        GameEstimatorEvaluationFunction.vectorToConfiguration).
        With ``configurations=None``, one fit with the coordinates' own
        weights."""
        vocab, coordinates, re_datasets = self._prepare_cached(df)
        # a model loaded from disk must be re-packed into this fit's entity
        # order / projection slots before it can warm-start or lock coords
        from photon_tpu.io.model_io import LoadedGameModel
        if isinstance(initial_model, LoadedGameModel):
            initial_model = initial_model.aligned_to(
                vocab, {cid: np.asarray(ds.projection)
                        for cid, ds in re_datasets.items()})
        cd_config = CoordinateDescentConfig(
            update_sequence=self.update_sequence,
            num_iterations=self.num_iterations,
            locked_coordinates=self.locked,
            parallel=self.parallel_cd,
            parallel_groups=self.parallel_groups,
            staleness_tol=self.staleness_tol,
            staleness_ratio=self.staleness_ratio,
            staleness_patience=self.staleness_patience,
        )

        validation_fn = None
        if validation_df is not None:
            scorer = self._build_scorer(validation_df, vocab, re_datasets)
            validation_fn = self._validation_fn(scorer, validation_df)
        primary_bigger = self.evaluators[0].bigger_is_better

        sweeps: List[Optional[Dict[str, float]]] = (
            list(configurations) if configurations else [None])

        results: List[GameResult] = []
        warm: Optional[GameModel] = initial_model
        for config_i, sweep in enumerate(sweeps):
            if sweep is not None:
                for cid, reg_weight in sweep.items():
                    # reg weight is a traced argument of the cached jitted
                    # solve — updating it recompiles nothing
                    coordinates[cid].config = dataclasses.replace(
                        coordinates[cid].config,
                        regularization_weight=float(reg_weight))
                    self.coordinate_configs = {
                        **self.coordinate_configs,
                        cid: self.coordinate_configs[cid].with_regularization_weight(
                            float(reg_weight)),
                    }
            descent = run_coordinate_descent(
                coordinates, cd_config, df.num_samples,
                initial_model=warm, validation_fn=validation_fn,
                primary_metric_bigger_is_better=primary_bigger,
                dtype=self.dtype,
                # per-configuration checkpoint namespace (SURVEY §5.3)
                checkpoint_dir=None if checkpoint_dir is None
                else os.path.join(checkpoint_dir, f"config_{config_i:03d}"),
                resume=resume,
            )
            evaluation = None
            if validation_fn is not None:
                evaluation = validation_fn(descent.model)
            results.append(GameResult(
                model=descent.model,
                config=dict(self.coordinate_configs),
                evaluation=evaluation,
                descent=descent,
                tracker_summaries=_tracker_summaries(coordinates),
            ))
            warm = descent.model
        # expose artifacts for transformer reuse / model IO / telemetry
        self._vocab = vocab
        self._re_datasets = re_datasets
        self._coordinates = coordinates
        return results

    def fit_swept(
        self,
        df: GameDataFrame,
        validation_df: Optional[GameDataFrame] = None,
        weights: Sequence[float] = (),
    ) -> List[GameResult]:
        """Fit an l2 grid over a single fixed-effect OR single
        random-effect model as ONE lane-batched solve
        (``cli/train --sweep-l2``): one compiled program, one shared
        data pass per iteration, one :class:`GameResult` per lane. The
        fixed path scores validation lanes batched; the random path
        (:meth:`RandomEffectCoordinate.update_model_swept`) reads its
        bucket ladder once for all λ points and scores per lane through
        the ordinary scorer. Multi-coordinate / entity- or model-sharded
        estimators fall back to :meth:`fit` with one configuration per
        weight — identical results, sequential solves."""
        from photon_tpu.optim import batched
        from photon_tpu.optim.base import ConvergenceReason

        lams = batched.validate_lane_weights(weights, name="sweep-l2 grid")
        cids = list(self.coordinate_configs.keys())
        vocab, coordinates, re_datasets = self._prepare_cached(df)
        only = coordinates[cids[0]] if len(cids) == 1 else None
        opt_ok = (only is not None
                  and self.coordinate_configs[cids[0]].optimization.optimizer
                      .optimizer_type.name in ("LBFGS", "OWLQN"))
        if (opt_ok and isinstance(only, RandomEffectCoordinate)
                and only.mesh is None):
            return self._fit_swept_random_effect(
                cids[0], only, lams, validation_df, vocab, coordinates,
                re_datasets)
        if not (opt_ok and isinstance(only, FixedEffectCoordinate)
                and not only._model_sharded):
            return self.fit(df, validation_df=validation_df,
                            configurations=[{cid: float(w) for cid in cids}
                                            for w in lams])
        cid = cids[0]
        shard_id = self.coordinate_configs[cid].data.feature_shard_id
        swept = only.update_model_swept(None, None, lams)
        evaluations: List[Optional[Dict[str, float]]] = [None] * len(lams)
        if validation_df is not None:
            from photon_tpu.game.coordinate import _fixed_score_lanes
            vbatch = validation_df.fixed_effect_batch(
                shard_id, dtype=np.dtype(self.dtype).type,
                feature_dtype=self.feature_dtype)
            suite = EvaluationSuite(self.evaluators, validation_df.response,
                                    offsets=validation_df.offsets,
                                    weights=validation_df.weights,
                                    id_tags=validation_df.id_tags,
                                    dtype=self.dtype)
            scores = _fixed_score_lanes(vbatch.features,
                                        jnp.asarray(swept.coefs))
            evaluations = [suite.evaluate(scores[i]).evaluations
                           for i in range(len(lams))]
        iters = np.asarray(swept.stacked.iterations)
        reasons = np.asarray(swept.stacked.reason)
        results = []
        for i, w in enumerate(lams):
            gm = GameModel({cid: FixedEffectModel(swept.models[i], shard_id)})
            results.append(GameResult(
                model=gm,
                config={cid: self.coordinate_configs[cid]
                        .with_regularization_weight(float(w))},
                evaluation=evaluations[i],
                descent=CoordinateDescentResult(
                    model=gm, best_model=gm,
                    validation_history=[evaluations[i]]
                    if evaluations[i] is not None else []),
                tracker_summaries={cid: (
                    f"{int(iters[i])} iters, "
                    f"{ConvergenceReason(int(reasons[i])).name}")},
            ))
        self._vocab = vocab
        self._re_datasets = re_datasets
        self._coordinates = coordinates
        return results

    def _fit_swept_random_effect(self, cid, coord, lams, validation_df,
                                 vocab, coordinates, re_datasets
                                 ) -> List[GameResult]:
        """The random-effect arm of :meth:`fit_swept`: all λ lanes of
        the per-entity solves ride one swept program per lane-chunk
        (bitwise-equal per lane to the sequential fits), then each
        lane's model is validated through the ordinary scorer."""
        models = coord.update_model_swept(None, None, lams)
        validation_fn = None
        if validation_df is not None:
            scorer = self._build_scorer(validation_df, vocab, re_datasets)
            validation_fn = self._validation_fn(scorer, validation_df)
        results: List[GameResult] = []
        for i, w in enumerate(lams):
            gm = GameModel({cid: models[i]})
            ev = validation_fn(gm) if validation_fn is not None else None
            tracker = coord.last_lane_trackers[i]
            results.append(GameResult(
                model=gm,
                config={cid: self.coordinate_configs[cid]
                        .with_regularization_weight(float(w))},
                evaluation=ev,
                descent=CoordinateDescentResult(
                    model=gm, best_model=gm,
                    validation_history=[ev] if ev is not None else []),
                tracker_summaries={cid: tracker.summary()},
            ))
        self._vocab = vocab
        self._re_datasets = re_datasets
        self._coordinates = coordinates
        return results

    # -- hyperparameter tuning (lane-batched ask/tell) -----------------------

    def tune(
        self,
        df: GameDataFrame,
        validation_df: GameDataFrame,
        *,
        n_rounds: int = 2,
        ask_batch: int = 4,
        mode=None,
        ranges=None,
        seed: int = 0,
        warm_start_lanes: bool = True,
    ) -> TuneResult:
        """GP / random search over regularization weights where each
        ask-batch of candidates is evaluated as ONE lane-batched solve.

        Every round asks the search for ``ask_batch`` candidates, fits
        them as K lanes of one compiled program
        (:meth:`~photon_tpu.game.coordinate.FixedEffectCoordinate
        .update_model_swept`), scores all lanes against the validation
        frame in one shared feature pass, and tells the observed values
        back. Rounds warm-start every lane from the previous round's best
        lane (``warm_start_lanes``), so later rounds converge in fewer
        solver iterations than cold starts.

        The batched path applies to a single non-model-sharded
        fixed-effect coordinate on an LBFGS/OWLQN solver (the sweepable
        family); anything else — random effects, multi-coordinate
        models — evaluates candidates sequentially through :meth:`fit`
        with the same ask/tell search loop, so tuning semantics are
        identical either way.
        """
        from photon_tpu.hyperparameter.rescaling import scale_backward
        from photon_tpu.hyperparameter.search import (
            GaussianProcessSearch,
            RandomSearch,
        )
        from photon_tpu.hyperparameter.tuner import (
            HyperparameterTuningMode,
            TuningRange,
            game_hyperparameter_defaults,
        )
        from photon_tpu.obs.metrics import registry
        from photon_tpu.optim import batched

        if mode is None:
            mode = HyperparameterTuningMode.BAYESIAN
        if mode == HyperparameterTuningMode.NONE:
            raise ValueError("tune() needs a tuning mode (BAYESIAN/RANDOM)")
        if n_rounds <= 0 or ask_batch <= 0:
            raise ValueError(
                f"tune() needs n_rounds > 0 and ask_batch > 0, got "
                f"{n_rounds}/{ask_batch}")

        cids = list(self.coordinate_configs.keys())
        if ranges is None:
            ranges = game_hyperparameter_defaults(cids)
        else:
            ranges = {cid: ranges.get(cid, TuningRange()) for cid in cids}
        log_ranges = [ranges[cid].log_range for cid in cids]

        def to_config(cand: np.ndarray) -> Dict[str, float]:
            logw = scale_backward(np.asarray(cand, float), log_ranges)
            return {cid: float(10.0 ** w) for cid, w in zip(cids, logw)}

        search_cls = (GaussianProcessSearch
                      if mode == HyperparameterTuningMode.BAYESIAN
                      else RandomSearch)
        search = search_cls(len(cids), seed=seed)
        primary = self.evaluators[0]

        vocab, coordinates, re_datasets = self._prepare_cached(df)
        only = coordinates[cids[0]] if len(cids) == 1 else None
        batched_path = (
            only is not None
            and isinstance(only, FixedEffectCoordinate)
            and not only._model_sharded
            and self.coordinate_configs[cids[0]].optimization.optimizer
                .optimizer_type.name in ("LBFGS", "OWLQN"))

        best_value = np.inf
        best_config: Dict[str, float] = {}
        best_model: Optional[GameModel] = None
        best_coef: Optional[np.ndarray] = None
        rounds: List[dict] = []
        observations: List[Tuple[np.ndarray, float]] = []
        total_iterations = 0

        if batched_path:
            cid = cids[0]
            shard_id = self.coordinate_configs[cid].data.feature_shard_id
            vbatch = validation_df.fixed_effect_batch(
                shard_id, dtype=np.dtype(self.dtype).type,
                feature_dtype=self.feature_dtype)
            suite = EvaluationSuite(self.evaluators, validation_df.response,
                                    offsets=validation_df.offsets,
                                    weights=validation_df.weights,
                                    id_tags=validation_df.id_tags,
                                    dtype=self.dtype)
            from photon_tpu.game.coordinate import _fixed_score_lanes

        for r in range(n_rounds):
            cands = search.ask(ask_batch)
            values: List[float] = []
            round_weights: List[float] = []
            round_iters: List[int] = []

            if batched_path:
                weights = [to_config(c)[cids[0]] for c in cands]
                init_lanes = None
                if warm_start_lanes and best_coef is not None:
                    # every lane starts from the previous round's best lane
                    init_lanes = np.tile(best_coef, (ask_batch, 1))
                swept = only.update_model_swept(None, None, weights,
                                                initial_lanes=init_lanes)
                scores = _fixed_score_lanes(vbatch.features,
                                            jnp.asarray(swept.coefs))
                iters = np.asarray(swept.stacked.iterations)
                for i, w in enumerate(weights):
                    metric = suite.evaluate(scores[i]).evaluations[primary.name]
                    v = -metric if primary.bigger_is_better else metric
                    lane_fail = only.last_lane_failures[i]
                    if lane_fail is not None:
                        v = np.inf  # failed lane never wins selection
                    values.append(float(v))
                    round_weights.append(float(w))
                    round_iters.append(int(iters[i]))
                    total_iterations += int(iters[i])
                    if v < best_value:
                        best_value = float(v)
                        best_config = {cids[0]: float(w)}
                        best_coef = np.asarray(swept.coefs[i])
                        best_model = GameModel({cids[0]: FixedEffectModel(
                            swept.models[i], shard_id)})
            else:
                warm = best_model if warm_start_lanes else None
                for c in cands:
                    config = to_config(c)
                    result = self.fit(df, validation_df=validation_df,
                                      configurations=[config],
                                      initial_model=warm)[-1]
                    metric = result.evaluation[primary.name]
                    v = -metric if primary.bigger_is_better else metric
                    it = sum(
                        int(np.asarray(coord.last_result.iterations))
                        for coord in self._coordinates.values()
                        if getattr(coord, "last_result", None) is not None)
                    values.append(float(v))
                    round_weights.append(
                        config[cids[0]] if len(cids) == 1 else np.nan)
                    round_iters.append(it)
                    total_iterations += it
                    if v < best_value:
                        best_value = float(v)
                        best_config = dict(config)
                        best_model = result.model

            # ±inf is a sentinel, not an observable value — feed the
            # search a finite penalty so the GP fit stays well-posed
            told = [v if np.isfinite(v)
                    else (max(x for x in values if np.isfinite(x))
                          if any(np.isfinite(x) for x in values) else 0.0)
                    for v in values]
            search.tell(cands, told)
            observations.extend(
                (np.asarray(c, float), float(v))
                for c, v in zip(cands, told))
            registry.counter("tuner.rounds").inc()
            registry.gauge("tuner.best_value").set(float(best_value))
            rounds.append({
                "round": r,
                "weights": round_weights,
                "values": values,
                "iterations": round_iters,
                "best_value": float(best_value),
                "best_config": dict(best_config),
            })
            logger.info("tune round %d: best %s -> %s", r, best_config,
                        best_value)

        batched.record_tuner_summary({
            "mode": mode.value,
            "rounds": len(rounds),
            "ask_batch": ask_batch,
            "batched": bool(batched_path),
            "warm_start_lanes": bool(warm_start_lanes),
            "best_config": dict(best_config),
            "best_value": float(best_value),
            "total_iterations": int(total_iterations),
            "round_records": rounds,
        })
        best_metric = (-best_value if primary.bigger_is_better
                       else best_value)
        return TuneResult(
            best_config=best_config,
            best_value=float(best_value),
            best_metric=float(best_metric),
            best_model=best_model,
            rounds=rounds,
            total_iterations=int(total_iterations),
            observations=observations,
        )


def _tracker_summaries(coordinates) -> Dict[str, str]:
    """Snapshot each coordinate's convergence summary (ring-buffer tracker
    when state tracking is on, basic solver stats otherwise)."""
    out: Dict[str, str] = {}
    for cid, coord in coordinates.items():
        tracker = getattr(coord, "last_tracker", None)
        if tracker is not None:
            out[cid] = tracker.summary()
            continue
        r = getattr(coord, "last_result", None)
        if r is not None:
            from photon_tpu.optim.base import ConvergenceReason
            out[cid] = (f"{int(r.iterations)} iters, "
                        f"{ConvergenceReason(int(r.reason)).name}")
    return out


def persistable_artifacts(estimator: "GameEstimator", model: GameModel,
                          base_projections=None):
    """(model, projections) ready for model IO: coordinates trained under a
    RANDOM projector are back-projected into the original feature space
    (reference: Projector.projectCoefficients) so their coefficients can be
    written as (name, term, value) records.

    ``base_projections``: optional pre-fetched {cid: np.ndarray} projection
    tables (callers saving several models hoist the device->host copy)."""
    import numpy as np

    from photon_tpu.game.model import RandomEffectModel

    projections = dict(base_projections) if base_projections is not None \
        else {cid: np.asarray(ds.projection)
              for cid, ds in estimator._re_datasets.items()}
    out_models = dict(model.models)
    for cid, cfg in estimator.coordinate_configs.items():
        if not cfg.is_random_effect or cid not in out_models:
            continue
        m = out_models[cid]
        if not isinstance(m, RandomEffectModel):
            continue
        orig_dim = estimator._original_dims.get(cid)
        rp = cfg.data.random_projection(orig_dim) if orig_dim else None
        if rp is None:
            continue
        proj = projections[cid]
        # expand projected-slot coefficients to the full projected space,
        # then back-project: w_orig = P^T w_proj
        coef_p = np.zeros((m.num_entities, rp.projected_dim))
        block = np.asarray(m.coefficients)
        for s in range(proj.shape[1]):
            cols = proj[:, s]
            ok = cols >= 0
            coef_p[ok, cols[ok]] = block[ok, s]
        coef_orig = rp.back_project_coefficients(coef_p)  # [E, D]
        E, D = coef_orig.shape
        out_models[cid] = RandomEffectModel(
            coefficients=jnp.asarray(coef_orig.astype(block.dtype)),
            random_effect_type=m.random_effect_type,
            feature_shard_id=m.feature_shard_id,
            task=m.task,
            variances=None,  # variances do not survive back-projection
        )
        projections[cid] = np.tile(np.arange(D, dtype=np.int32), (E, 1))
    return GameModel(out_models), projections


class GameTransformer:
    """Score new frames under a trained GAME model
    (reference: GameTransformer.scala:39)."""

    def __init__(self, model: GameModel, estimator: GameEstimator,
                 vocab: Optional[EntityVocabulary] = None):
        self.model = model
        self.estimator = estimator
        self.vocab = vocab if vocab is not None else getattr(estimator, "_vocab", None)
        self._re_projections = {
            cid: ds.projection
            for cid, ds in getattr(estimator, "_re_datasets", {}).items()
        }

    def transform(self, df: GameDataFrame) -> Array:
        """Total scores [n] for the frame (offsets included)."""
        est = self.estimator
        scorer = GameScorer(df.num_samples, dtype=est.dtype)
        for cid, cfg in est.coordinate_configs.items():
            if cid not in self.model:
                continue
            if cfg.is_random_effect:
                scorer.add_random_effect(cid, df, cfg.data, self.vocab,
                                         self._re_projections[cid])
            else:
                scorer.add_fixed_effect(cid, df, cfg.data.feature_shard_id)
        offsets = None if df.offsets is None else jnp.asarray(df.offsets, est.dtype)
        return scorer.score(self.model, offsets=offsets)

    def evaluate(self, df: GameDataFrame,
                 evaluators: Optional[Sequence] = None) -> Dict[str, float]:
        scores = self.transform(df)
        evs = list(evaluators) if evaluators else self.estimator.evaluators
        # transform() already adds frame offsets to the scores
        suite = EvaluationSuite(evs, df.response, weights=df.weights,
                                id_tags=df.id_tags, dtype=self.estimator.dtype)
        return suite.evaluate(scores).evaluations
