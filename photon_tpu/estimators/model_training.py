"""Plain-GLM training over a regularization path with warm starts.

Reference: photon-api ModelTraining.trainGeneralizedLinearModel
(ModelTraining.scala:34,73-108; warm-start chain :134-147) — the engine
behind the legacy Driver's lambda sweep.

Because the L2/L1 weights are traced arguments of one jit-compiled solve
(optim/problem.py), the whole path reuses a single XLA program; warm
starting is just feeding the previous lambda's coefficients as init.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import DataBatch
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.ops.normalization import NormalizationContext, no_normalization
from photon_tpu.optim.base import SolverResult
from photon_tpu.optim.problem import GLMOptimizationConfiguration, GlmOptimizationProblem
from photon_tpu.types import TaskType

Array = jax.Array


def train_generalized_linear_model(
    task: TaskType,
    batch: DataBatch,
    dim: int,
    config: GLMOptimizationConfiguration = GLMOptimizationConfiguration(),
    regularization_weights: Sequence[float] = (0.0,),
    norm: NormalizationContext = no_normalization(),
    warm_start: bool = True,
    initial: Optional[Array] = None,
    dtype=jnp.float32,
    intercept_index: Optional[int] = None,
) -> Tuple[Dict[float, GeneralizedLinearModel], Dict[float, SolverResult]]:
    """Train one GLM per regularization weight, warm-starting along the path
    (descending lambda order is the caller's choice, as in the reference).

    Returns ({lambda: model}, {lambda: solver stats}).
    """
    problem = GlmOptimizationProblem(task, config, norm,
                                     intercept_index=intercept_index)
    models: Dict[float, GeneralizedLinearModel] = {}
    stats: Dict[float, SolverResult] = {}
    coef = initial
    for lam in regularization_weights:
        model, result = problem.run(
            batch, initial=coef, dim=dim, dtype=dtype, regularization_weight=lam)
        models[lam] = model
        stats[lam] = result
        if warm_start:
            # models are published in original space; run() converts warm
            # starts back into the transformed optimization space
            coef = model.coefficients.means
    return models, stats
