"""Plain-GLM training over a regularization path with warm starts.

Reference: photon-api ModelTraining.trainGeneralizedLinearModel
(ModelTraining.scala:34,73-108; warm-start chain :134-147) — the engine
behind the legacy Driver's lambda sweep.

Because the L2/L1 weights are traced arguments of one jit-compiled solve
(optim/problem.py), the whole path reuses a single XLA program; warm
starting is just feeding the previous lambda's coefficients as init.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.data.dataset import DataBatch
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.ops.normalization import NormalizationContext, no_normalization
from photon_tpu.optim.base import SolverResult
from photon_tpu.optim.problem import (
    GLMOptimizationConfiguration,
    GlmOptimizationProblem,
    _validate_direct,
    norm_cache_key,
)
from photon_tpu.types import OptimizerType, TaskType

Array = jax.Array


def train_generalized_linear_model(
    task: TaskType,
    batch: DataBatch,
    dim: int,
    config: GLMOptimizationConfiguration = GLMOptimizationConfiguration(),
    regularization_weights: Sequence[float] = (0.0,),
    norm: NormalizationContext = no_normalization(),
    warm_start: bool = True,
    initial: Optional[Array] = None,
    dtype=jnp.float32,
    intercept_index: Optional[int] = None,
) -> Tuple[Dict[float, GeneralizedLinearModel], Dict[float, SolverResult]]:
    """Train one GLM per regularization weight, warm-starting along the path
    (descending lambda order is the caller's choice, as in the reference).

    Returns ({lambda: model}, {lambda: solver stats}).
    """
    problem = GlmOptimizationProblem(task, config, norm,
                                     intercept_index=intercept_index)
    if (config.optimizer.optimizer_type == OptimizerType.DIRECT
            and len(regularization_weights) > 1):
        # the whole ridge path shares one Gram matrix: one data pass +
        # one batched Cholesky per lambda (optim/direct.minimize_path);
        # warm starts are irrelevant for an exact solver. Same validity
        # contract as the per-lambda path (problem._solve_fn).
        _validate_direct(task, config.optimizer, config.regularization)
        return _direct_path(problem, batch, dim, regularization_weights,
                            initial, dtype, intercept_index)

    models: Dict[float, GeneralizedLinearModel] = {}
    stats: Dict[float, SolverResult] = {}
    coef = initial
    for lam in regularization_weights:
        model, result = problem.run(
            batch, initial=coef, dim=dim, dtype=dtype, regularization_weight=lam)
        models[lam] = model
        stats[lam] = result
        if warm_start:
            # models are published in original space; run() converts warm
            # starts back into the transformed optimization space
            coef = model.coefficients.means
    return models, stats


def _direct_path(problem, batch, dim, lambdas, initial, dtype,
                 intercept_index):
    """DIRECT over a lambda path: shared Gram, per-lambda Cholesky."""
    from photon_tpu.function.objective import Hyper
    from photon_tpu.models.glm import Coefficients
    from photon_tpu.optim import direct
    from photon_tpu.utils import jitcache

    # the regularization context splits each total weight into its L2
    # part exactly as the per-lambda path does (problem.run)
    reg = problem.config.regularization
    l2_weights = [reg.l2_weight(lam) for lam in lambdas]
    obj = problem.objective
    norm = obj.norm
    if initial is None:
        x0 = jnp.zeros((dim,), dtype)
    else:
        x0 = jnp.asarray(initial, dtype)
        if not norm.is_identity:
            x0 = norm.model_to_transformed_space(x0, intercept_index)

    def build():
        @jax.jit
        def path(x0, batch, lams):
            zero = jnp.zeros((), x0.dtype)
            vg = lambda c: obj.value_and_gradient(c, batch, Hyper(zero))
            hm = lambda c: obj.hessian_matrix(c, batch, Hyper(zero))
            return direct.minimize_path(vg, hm, x0, lams)

        return path

    path_fn = jitcache.get_or_build(
        ("direct_path", problem.task, norm_cache_key(norm)), build)
    res = path_fn(x0, batch, jnp.asarray(l2_weights, dtype))

    models, stats = {}, {}
    for i, lam in enumerate(lambdas):
        r = jax.tree.map(lambda a: a[i], res)
        coef = r.coef
        if not norm.is_identity:
            coef = norm.transformed_space_to_model(coef, intercept_index)
        models[lam] = GeneralizedLinearModel(Coefficients(coef), problem.task)
        stats[lam] = r
    return models, stats
