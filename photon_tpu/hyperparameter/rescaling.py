"""Hyperparameter-space transforms: [0,1] unit cube <-> natural ranges.

Reference: photon-lib hyperparameter/VectorRescaling.scala — LOG (base
10) / SQRT per-index forward and backward transforms, and linear scaling
into/out of [0,1] with a +1 range adjustment for discrete indices.
"""

from __future__ import annotations

from typing import Dict, Sequence, Set, Tuple

import numpy as np

LOG_TRANSFORM = "LOG"
SQRT_TRANSFORM = "SQRT"

DoubleRange = Tuple[float, float]


def transform_forward(vector: np.ndarray,
                      transforms: Dict[int, str]) -> np.ndarray:
    out = np.array(vector, float)
    for idx, t in transforms.items():
        if t == LOG_TRANSFORM:
            out[idx] = np.log10(out[idx])
        elif t == SQRT_TRANSFORM:
            out[idx] = np.sqrt(out[idx])
        else:
            raise ValueError(f"unknown transformation {t!r}")
    return out


def transform_backward(vector: np.ndarray,
                       transforms: Dict[int, str]) -> np.ndarray:
    out = np.array(vector, float)
    for idx, t in transforms.items():
        if t == LOG_TRANSFORM:
            out[idx] = 10.0 ** out[idx]
        elif t == SQRT_TRANSFORM:
            out[idx] = out[idx] ** 2
        else:
            raise ValueError(f"unknown transformation {t!r}")
    return out


def _range_arrays(ranges: Sequence[DoubleRange], discrete: Set[int]):
    start = np.asarray([r[0] for r in ranges], float)
    end = np.asarray([r[1] for r in ranges], float)
    adj = np.asarray([1.0 if i in discrete else 0.0
                      for i in range(len(ranges))])
    return start, end, adj


def scale_forward(vector: np.ndarray, ranges: Sequence[DoubleRange],
                  discrete: Set[int] = frozenset()) -> np.ndarray:
    start, end, adj = _range_arrays(ranges, set(discrete))
    return (np.asarray(vector, float) - start) / (end - start + adj)


def scale_backward(vector: np.ndarray, ranges: Sequence[DoubleRange],
                   discrete: Set[int] = frozenset()) -> np.ndarray:
    start, end, adj = _range_arrays(ranges, set(discrete))
    return np.asarray(vector, float) * (end - start + adj) + start
