"""Hyperparameter search: Sobol random search, GP Bayesian optimization,
slice-sampled kernel posteriors, acquisition criteria, estimator glue.

Replaces the reference's photon-lib hyperparameter/ package (+ the
photon-api tuner factory and photon-client estimator glue).
"""

from photon_tpu.hyperparameter.criteria import ConfidenceBound, ExpectedImprovement
from photon_tpu.hyperparameter.gp import (
    GaussianProcessEstimator,
    GaussianProcessModel,
)
from photon_tpu.hyperparameter.kernels import RBF, Matern52, StationaryKernel
from photon_tpu.hyperparameter.rescaling import (
    LOG_TRANSFORM,
    SQRT_TRANSFORM,
    scale_backward,
    scale_forward,
    transform_backward,
    transform_forward,
)
from photon_tpu.hyperparameter.search import GaussianProcessSearch, RandomSearch
from photon_tpu.hyperparameter.slice_sampler import SliceSampler
from photon_tpu.hyperparameter.tuner import (
    GameEstimatorEvaluationFunction,
    HyperparameterTuningMode,
    TuningRange,
    run_hyperparameter_tuning,
)

__all__ = [
    "ConfidenceBound", "ExpectedImprovement",
    "GaussianProcessEstimator", "GaussianProcessModel",
    "RBF", "Matern52", "StationaryKernel",
    "LOG_TRANSFORM", "SQRT_TRANSFORM",
    "scale_forward", "scale_backward", "transform_forward", "transform_backward",
    "GaussianProcessSearch", "RandomSearch", "SliceSampler",
    "GameEstimatorEvaluationFunction", "HyperparameterTuningMode",
    "TuningRange", "run_hyperparameter_tuning",
]
