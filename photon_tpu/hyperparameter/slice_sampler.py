"""Slice sampling for kernel-hyperparameter posteriors.

Reference: photon-lib hyperparameter/SliceSampler.scala — univariate
slice sampling along a direction (Neal 2003): draw slice level
y = log u + logp(x), step out an interval along the direction until it
brackets the slice, then shrink rejected proposals back toward x.
``draw`` samples along one random direction; ``draw_dimension_wise``
cycles axis-aligned directions in shuffled order.
"""

from __future__ import annotations

from typing import Callable, Tuple

import numpy as np

LogP = Callable[[np.ndarray], float]


class SliceSampler:

    def __init__(self, step_size: float = 1.0, max_steps_out: int = 1000,
                 rng: np.random.Generator | int | None = None):
        self.step_size = step_size
        self.max_steps_out = max_steps_out
        self.rng = (rng if isinstance(rng, np.random.Generator)
                    else np.random.default_rng(rng))

    def draw(self, x: np.ndarray, logp: LogP) -> np.ndarray:
        """One sample along a uniformly random direction."""
        d = self.rng.normal(size=len(x))
        d = d / np.linalg.norm(d)
        return self._draw_along(x, logp, d)

    def draw_dimension_wise(self, x: np.ndarray, logp: LogP) -> np.ndarray:
        """One Gibbs-style sweep: each coordinate direction in random order."""
        order = self.rng.permutation(len(x))
        for i in order:
            e = np.zeros(len(x))
            e[i] = 1.0
            x = self._draw_along(x, logp, e)
        return x

    # -- internals -----------------------------------------------------------

    def _draw_along(self, x: np.ndarray, logp: LogP, direction: np.ndarray
                    ) -> np.ndarray:
        y = np.log(self.rng.random()) + logp(x)
        lower, upper = self._step_out(x, y, logp, direction)
        # shrink until a proposal lands above the slice
        for _ in range(1000):
            new_x = lower + self.rng.random() * (upper - lower)
            if logp(new_x) > y:
                return new_x
            if new_x @ direction < x @ direction:
                lower = new_x
            elif new_x @ direction > x @ direction:
                upper = new_x
            else:
                # slice shrank to the current point — keep it
                return x
        return x

    def _step_out(self, x: np.ndarray, y: float, logp: LogP,
                  direction: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        lower = x - direction * self.rng.random() * self.step_size
        upper = lower + direction * self.step_size
        steps = 0
        while logp(lower) > y and steps < self.max_steps_out:
            lower = lower - direction * self.step_size
            steps += 1
        steps = 0
        while logp(upper) > y and steps < self.max_steps_out:
            upper = upper + direction * self.step_size
            steps += 1
        return lower, upper
