"""GAME estimator tuning glue + tuner factory.

Reference: photon-client estimators/GameEstimatorEvaluationFunction
.scala:40 (candidate vector in [0,1]^d <-> per-coordinate regularization
weights on log10 scale within ranges; apply = retrain + primary
validation metric), photon-api hyperparameter/tuner/
HyperparameterTunerFactory.scala:19 (DUMMY vs ATLAS), AtlasTuner.scala:27
(BAYESIAN -> GaussianProcessSearch, RANDOM -> RandomSearch),
photon-lib HyperparameterTuningMode.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.hyperparameter.rescaling import (
    scale_backward,
    scale_forward,
)
from photon_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    Observation,
    RandomSearch,
)

logger = logging.getLogger(__name__)


class HyperparameterTuningMode(enum.Enum):
    BAYESIAN = "BAYESIAN"
    RANDOM = "RANDOM"
    NONE = "NONE"


@dataclasses.dataclass(frozen=True)
class TuningRange:
    """log10 regularization-weight range for one coordinate (reference
    default 1e-4..1e4, GameHyperparameterDefaults)."""

    min_weight: float = 1e-4
    max_weight: float = 1e4

    @property
    def log_range(self) -> Tuple[float, float]:
        return (np.log10(self.min_weight), np.log10(self.max_weight))


class GameEstimatorEvaluationFunction:
    """Bridge between the search's [0,1]^d vectors and GAME configs.

    ``estimator.fit`` is invoked per candidate with one configuration
    {coordinate: reg weight}; the value minimized is the primary
    validation metric (negated when bigger is better).
    """

    def __init__(self, estimator, df, validation_df,
                 ranges: Optional[Dict[str, TuningRange]] = None,
                 initial_model=None):
        self.estimator = estimator
        self.df = df
        self.validation_df = validation_df
        self.coordinate_ids = list(estimator.coordinate_configs.keys())
        self.ranges = {cid: (ranges or {}).get(cid, TuningRange())
                       for cid in self.coordinate_ids}
        self.initial_model = initial_model
        self.num_params = len(self.coordinate_ids)
        self._log_ranges = [self.ranges[cid].log_range
                            for cid in self.coordinate_ids]

    # -- vector <-> configuration (reference :104-144) -----------------------

    def vector_to_configuration(self, candidate: np.ndarray) -> Dict[str, float]:
        logw = scale_backward(candidate, self._log_ranges)
        return {cid: float(10.0 ** w)
                for cid, w in zip(self.coordinate_ids, logw)}

    def configuration_to_vector(self, config: Dict[str, float]) -> np.ndarray:
        logw = np.asarray([np.log10(config[cid]) for cid in self.coordinate_ids])
        return scale_forward(logw, self._log_ranges)

    # -- evaluation ----------------------------------------------------------

    def __call__(self, candidate: np.ndarray):
        config = self.vector_to_configuration(candidate)
        results = self.estimator.fit(
            self.df, validation_df=self.validation_df,
            configurations=[config], initial_model=self.initial_model)
        result = results[-1]
        value = self._value_of(result)
        logger.info("tuning candidate %s -> %s", config, value)
        return value, result

    def _value_of(self, result) -> float:
        primary = self.estimator.evaluators[0]
        v = result.evaluation[primary.name]
        return -v if primary.bigger_is_better else v

    def convert_observations(self, results: Sequence) -> List[Observation]:
        """Past GameResults -> (vector, value) observations for warm-started
        search (reference: EvaluationFunction.convertObservations)."""
        out = []
        for r in results:
            weights = {cid: ccfg.optimization.regularization_weight
                       for cid, ccfg in r.config.items()}
            out.append((self.configuration_to_vector(weights),
                        self._value_of(r)))
        return out


# -- defaults (reference: GameHyperparameterDefaults.scala) -------------------

# per-parameter prior default used when a prior record omits a value
PRIOR_DEFAULT_WEIGHT = 1.0  # 10^0, the center of the default log range

def game_hyperparameter_defaults(coordinate_ids: Sequence[str]
                                 ) -> Dict[str, TuningRange]:
    """Default LOG-scale search ranges per coordinate: 10^-3..10^3
    (reference: GameHyperparameterDefaults.configDefault — FLOAT/LOG,
    min -3, max 3 for every regularizer)."""
    return {cid: TuningRange(1e-3, 1e3) for cid in coordinate_ids}


def priors_from_json(json_str: str, coordinate_ids: Sequence[str],
                     default_weight: float = PRIOR_DEFAULT_WEIGHT
                     ) -> List[Tuple[Dict[str, float], float]]:
    """Parse prior observations: ``{"records": [{<coord>: weight, ...,
    "evaluationValue": v}, ...]}`` — missing coordinates take the default
    (reference: HyperparameterSerialization.priorFromJson + priorDefault).
    Values follow this module's MINIMIZE convention."""
    import json as _json
    out = []
    for rec in _json.loads(json_str).get("records", []):
        config = {cid: float(rec.get(cid, default_weight))
                  for cid in coordinate_ids}
        out.append((config, float(rec["evaluationValue"])))
    return out


# -- search-range shrinking (reference: ShrinkSearchRange.scala:28-80) --------

def shrink_search_range(
    fn: GameEstimatorEvaluationFunction,
    prior_observations: Sequence[Tuple[np.ndarray, float]],
    radius: float = 0.25,
    candidate_pool_size: int = 1000,
    seed: int = 0,
) -> Dict[str, TuningRange]:
    """Narrow each coordinate's search range around the GP-predicted best
    of the prior observations.

    Reference recipe (ShrinkSearchRange.getBounds): rescale priors to
    [0,1]^d, fit a Matern-5/2 GP, score a Sobol candidate pool, take the
    best-predicted candidate, and return [best - radius, best + radius]
    clipped to the original ranges, mapped back through the log transform.
    Values are MINIMIZED here (the reference maximizes; its evaluation
    sign convention is inverted upstream).
    """
    from photon_tpu.hyperparameter.gp import GaussianProcessEstimator
    from photon_tpu.hyperparameter.kernels import Matern52
    from scipy.stats import qmc

    assert prior_observations, "need prior observations to shrink around"
    points = np.vstack([np.asarray(p, float) for p, _ in prior_observations])
    values = np.asarray([v for _, v in prior_observations], float)

    if len(points) == 1:
        best = points[0]
    else:
        model = GaussianProcessEstimator(kernel=Matern52(), seed=seed).fit(
            points, values)
        candidates = qmc.Sobol(d=fn.num_params, scramble=True,
                               seed=seed).random(candidate_pool_size)
        mean, _ = model.predict(candidates)
        best = candidates[int(np.argmin(mean))]

    out: Dict[str, TuningRange] = {}
    for i, cid in enumerate(fn.coordinate_ids):
        lo01 = max(0.0, float(best[i]) - radius)
        hi01 = min(1.0, float(best[i]) + radius)
        lmin, lmax = fn.ranges[cid].log_range
        span = lmax - lmin
        out[cid] = TuningRange(10.0 ** (lmin + lo01 * span),
                               10.0 ** (lmin + hi01 * span))
        logger.info("shrunk %s range: [%.3g, %.3g]", cid,
                    out[cid].min_weight, out[cid].max_weight)
    return out


def run_hyperparameter_tuning(
    estimator,
    df,
    validation_df,
    n_iterations: int,
    mode: HyperparameterTuningMode = HyperparameterTuningMode.BAYESIAN,
    ranges: Optional[Dict[str, TuningRange]] = None,
    prior_results: Sequence = (),
    prior_json: Optional[str] = None,
    shrink_radius: Optional[float] = None,
    seed: int = 0,
) -> List:
    """Tune per-coordinate reg weights; returns the candidate GameResults
    (reference: GameTrainingDriver.runHyperparameterTuning :559 +
    AtlasTuner routing). ``shrink_radius`` narrows the search ranges
    around the prior best before searching (ShrinkSearchRange.scala:28);
    ``prior_json`` supplies serialized prior observations in addition to
    in-memory ``prior_results``."""
    if mode == HyperparameterTuningMode.NONE or n_iterations <= 0:
        return []
    if ranges is None:
        ranges = game_hyperparameter_defaults(
            list(estimator.coordinate_configs.keys()))
    fn = GameEstimatorEvaluationFunction(estimator, df, validation_df,
                                         ranges=ranges)
    priors = fn.convert_observations(prior_results)
    if prior_json:
        for config, value in priors_from_json(prior_json, fn.coordinate_ids):
            priors.append((fn.configuration_to_vector(config), value))
    if shrink_radius is not None and priors:
        full_ranges = fn.ranges  # filled-in per-coordinate ranges
        shrunk = shrink_search_range(fn, priors, radius=shrink_radius,
                                     seed=seed)
        fn = GameEstimatorEvaluationFunction(estimator, df, validation_df,
                                             ranges=shrunk)
        # re-express the priors in the SHRUNK [0,1]^d coordinates
        old_priors = priors
        priors = []
        for p, v in old_priors:
            config = {cid: float(10.0 ** w) for cid, w in zip(
                fn.coordinate_ids,
                scale_backward(np.asarray(p),
                               [full_ranges[cid].log_range
                                for cid in fn.coordinate_ids]))}
            vec = fn.configuration_to_vector(config)
            if np.all((vec >= 0.0) & (vec <= 1.0)):
                priors.append((vec, v))
    search_cls = (GaussianProcessSearch
                  if mode == HyperparameterTuningMode.BAYESIAN else RandomSearch)
    search = search_cls(fn.num_params, fn, seed=seed)
    if priors:
        return search.find_with_prior_observations(n_iterations, priors)
    return search.find(n_iterations)
