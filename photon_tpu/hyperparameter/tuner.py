"""GAME estimator tuning glue + tuner factory.

Reference: photon-client estimators/GameEstimatorEvaluationFunction
.scala:40 (candidate vector in [0,1]^d <-> per-coordinate regularization
weights on log10 scale within ranges; apply = retrain + primary
validation metric), photon-api hyperparameter/tuner/
HyperparameterTunerFactory.scala:19 (DUMMY vs ATLAS), AtlasTuner.scala:27
(BAYESIAN -> GaussianProcessSearch, RANDOM -> RandomSearch),
photon-lib HyperparameterTuningMode.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.hyperparameter.rescaling import (
    scale_backward,
    scale_forward,
)
from photon_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    Observation,
    RandomSearch,
)

logger = logging.getLogger(__name__)


class HyperparameterTuningMode(enum.Enum):
    BAYESIAN = "BAYESIAN"
    RANDOM = "RANDOM"
    NONE = "NONE"


@dataclasses.dataclass(frozen=True)
class TuningRange:
    """log10 regularization-weight range for one coordinate (reference
    default 1e-4..1e4, GameHyperparameterDefaults)."""

    min_weight: float = 1e-4
    max_weight: float = 1e4

    @property
    def log_range(self) -> Tuple[float, float]:
        return (np.log10(self.min_weight), np.log10(self.max_weight))


class GameEstimatorEvaluationFunction:
    """Bridge between the search's [0,1]^d vectors and GAME configs.

    ``estimator.fit`` is invoked per candidate with one configuration
    {coordinate: reg weight}; the value minimized is the primary
    validation metric (negated when bigger is better).
    """

    def __init__(self, estimator, df, validation_df,
                 ranges: Optional[Dict[str, TuningRange]] = None,
                 initial_model=None):
        self.estimator = estimator
        self.df = df
        self.validation_df = validation_df
        self.coordinate_ids = list(estimator.coordinate_configs.keys())
        self.ranges = {cid: (ranges or {}).get(cid, TuningRange())
                       for cid in self.coordinate_ids}
        self.initial_model = initial_model
        self.num_params = len(self.coordinate_ids)
        self._log_ranges = [self.ranges[cid].log_range
                            for cid in self.coordinate_ids]

    # -- vector <-> configuration (reference :104-144) -----------------------

    def vector_to_configuration(self, candidate: np.ndarray) -> Dict[str, float]:
        logw = scale_backward(candidate, self._log_ranges)
        return {cid: float(10.0 ** w)
                for cid, w in zip(self.coordinate_ids, logw)}

    def configuration_to_vector(self, config: Dict[str, float]) -> np.ndarray:
        logw = np.asarray([np.log10(config[cid]) for cid in self.coordinate_ids])
        return scale_forward(logw, self._log_ranges)

    # -- evaluation ----------------------------------------------------------

    def __call__(self, candidate: np.ndarray):
        config = self.vector_to_configuration(candidate)
        results = self.estimator.fit(
            self.df, validation_df=self.validation_df,
            configurations=[config], initial_model=self.initial_model)
        result = results[-1]
        value = self._value_of(result)
        logger.info("tuning candidate %s -> %s", config, value)
        return value, result

    def _value_of(self, result) -> float:
        primary = self.estimator.evaluators[0]
        v = result.evaluation[primary.name]
        return -v if primary.bigger_is_better else v

    def convert_observations(self, results: Sequence) -> List[Observation]:
        """Past GameResults -> (vector, value) observations for warm-started
        search (reference: EvaluationFunction.convertObservations)."""
        out = []
        for r in results:
            weights = {cid: ccfg.optimization.regularization_weight
                       for cid, ccfg in r.config.items()}
            out.append((self.configuration_to_vector(weights),
                        self._value_of(r)))
        return out


def run_hyperparameter_tuning(
    estimator,
    df,
    validation_df,
    n_iterations: int,
    mode: HyperparameterTuningMode = HyperparameterTuningMode.BAYESIAN,
    ranges: Optional[Dict[str, TuningRange]] = None,
    prior_results: Sequence = (),
    seed: int = 0,
) -> List:
    """Tune per-coordinate reg weights; returns the candidate GameResults
    (reference: GameTrainingDriver.runHyperparameterTuning :559 +
    AtlasTuner routing)."""
    if mode == HyperparameterTuningMode.NONE or n_iterations <= 0:
        return []
    fn = GameEstimatorEvaluationFunction(estimator, df, validation_df,
                                         ranges=ranges)
    search_cls = (GaussianProcessSearch
                  if mode == HyperparameterTuningMode.BAYESIAN else RandomSearch)
    search = search_cls(fn.num_params, fn, seed=seed)
    priors = fn.convert_observations(prior_results)
    if priors:
        return search.find_with_prior_observations(n_iterations, priors)
    return search.find(n_iterations)
